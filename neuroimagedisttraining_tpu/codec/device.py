"""Jitted wire-codec math: the encode/decode hot path as XLA ops.

Two consumers:

- ``lossy_roundtrip`` — the PURE value transform the wire performs
  (delta -> sparsify -> quantize -> dequantize -> reconstruct) with no
  byte packing, as one jitted program. The simulated engines apply it to
  client updates before aggregation when ``--wire_codec`` is set, so an
  in-process run reproduces exactly what a cross-silo federation would
  aggregate — error-feedback accumulators included. Bitwise parity with
  the host path (wire.py encode -> decode) is pinned in
  tests/test_codec.py.
- ``encode_arrays`` — the device-side half of ``wire.encode_update``:
  residual/EF math, the global top-k threshold (ops/topk.py's histogram
  select — the Pallas kernel on TPU), per-leaf scales and quantized
  values computed on device; only the variable-length packing (boolean
  extract, packbits, zlib) stays on the host.

Top-k reuses ``ops/topk.kth_largest`` (ISSUE 3): the threshold is the
exact k-th largest |residual| to float32 resolution, identical to the
host's ``np.partition`` selection, so the two paths keep the same support
set.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from neuroimagedisttraining_tpu.codec.wire import WireSpec
from neuroimagedisttraining_tpu.ops.topk import kth_largest

PyTree = Any


def _residual_tree(spec: WireSpec, update: PyTree, reference: PyTree | None,
                   ef: PyTree | None) -> PyTree:
    x = update
    if spec.delta:
        x = jax.tree.map(
            lambda u, r: u.astype(jnp.float32) - r.astype(jnp.float32),
            update, reference)
    else:
        x = jax.tree.map(lambda u: u.astype(jnp.float32), x)
    if ef is not None:
        x = jax.tree.map(jnp.add, x, ef)
    return x


def _global_topk_keep(spec: WireSpec, x: PyTree) -> PyTree:
    """Cross-layer top-``topk_ratio`` keep masks over ALL leaves (the
    same global-threshold shape as the SNIP mask, ops/snip.py)."""
    leaves = jax.tree.leaves(x)
    flat = jnp.concatenate([jnp.abs(v).reshape(-1) for v in leaves])
    k = max(1, int(-(-spec.topk_ratio * flat.size // 1)))  # ceil, static
    thr = kth_largest(flat, k)
    return jax.tree.map(lambda v: jnp.abs(v) >= thr, x)


def _quant_dequant(spec: WireSpec, v: jax.Array) -> jax.Array:
    """Per-leaf quantize->dequantize (what the receiver sees)."""
    if spec.quant == "int8":
        amax = jnp.max(jnp.abs(v))
        scale = jnp.where(amax > 0, amax / jnp.float32(127.0),
                          jnp.float32(1.0))
        q = jnp.clip(jnp.rint(v / scale), -127, 127).astype(jnp.int8)
        return q.astype(jnp.float32) * scale
    if spec.quant == "bf16":
        return v.astype(jnp.bfloat16).astype(jnp.float32)
    return v


def lossy_roundtrip(spec: WireSpec, update: PyTree, *,
                    reference: PyTree | None = None,
                    masks: PyTree | None = None,
                    ef: PyTree | None = None
                    ) -> tuple[PyTree, PyTree | None]:
    """decode(encode(update)) as pure array math: what the aggregating
    server reconstructs, plus the sender's next error-feedback state
    (top-k mode; None otherwise). Trace-safe — call it inside jit/vmap
    (the engines vmap it over the client axis)."""
    if spec.delta and reference is None:
        raise ValueError("wire codec: delta stage needs the broadcast "
                         "reference tree")
    x = _residual_tree(spec, update, reference, ef)
    track_ef = spec.sparse and masks is None
    if spec.sparse:
        keep = (jax.tree.map(lambda m: m > 0, masks) if masks is not None
                else _global_topk_keep(spec, x))
    else:
        keep = None
    xs = (jax.tree.map(lambda v, kp: jnp.where(kp, v, 0.0), x, keep)
          if keep is not None else x)
    deq = jax.tree.map(lambda v: _quant_dequant(spec, v), xs)
    new_ef = jax.tree.map(jnp.subtract, x, deq) if track_ef else None
    # mask-zero semantics apply only when the sparse stage actually
    # DROPPED the off-mask entries (keep is not None): without the
    # sparse stage the full residual ships dense and the plain
    # reconstruction already returns exact zeros off-mask — a spec like
    # delta+quant with an engine mask supplied must not crash or mask
    masked = masks is not None and keep is not None
    if spec.delta:
        if masked:
            # mask-zero semantics (wire.py decode): off-mask entries are
            # exact zeros by the engine's training, never the reference
            decoded = jax.tree.map(
                lambda d, r, kp: jnp.where(kp, d + r.astype(jnp.float32),
                                           0.0),
                deq, reference, keep)
        else:
            decoded = jax.tree.map(
                lambda d, r: d + r.astype(jnp.float32), deq, reference)
    else:
        decoded = (jax.tree.map(
            lambda d, kp: jnp.where(kp, d, 0.0), deq, keep)
            if masked else deq)
    decoded = jax.tree.map(lambda d, u: d.astype(u.dtype), decoded, update)
    return decoded, new_ef


@functools.partial(jax.jit, static_argnames=("spec",),
                   donate_argnums=(4,))
def _encode_math_jit(spec: WireSpec, update: PyTree,
                     reference: PyTree | None, masks: PyTree | None,
                     ef: PyTree | None):
    # ``ef`` (the sender's error-feedback accumulator) is donated: its
    # float32 buffers back the returned ``new_ef`` and the caller
    # contract (wire.encode_update -> cross_silo client) rebinds the
    # accumulator from the return value every round. ``update`` and
    # ``reference`` are NOT donated — encode_update rereads the update
    # leaves for dtype/shape framing after the device math returns.
    """Device half of encode: (residuals, keep masks|None, new_ef|None).
    Quantization happens host-side on the packed values so the wire
    bytes are produced exactly once (idempotent with the host path)."""
    x = _residual_tree(spec, update, reference, ef)
    if spec.sparse:
        keep = (jax.tree.map(lambda m: m > 0, masks) if masks is not None
                else _global_topk_keep(spec, x))
    else:
        keep = None
    new_ef = None
    if spec.sparse and masks is None:
        xs = jax.tree.map(lambda v, kp: jnp.where(kp, v, 0.0), x, keep)
        deq = jax.tree.map(lambda v: _quant_dequant(spec, v), xs)
        new_ef = jax.tree.map(jnp.subtract, x, deq)
    return x, keep, new_ef


def encode_math(spec: WireSpec, update: PyTree, *,
                reference: PyTree | None = None,
                masks: PyTree | None = None, ef: PyTree | None = None):
    """Run the encode-side array math as one jitted program (the
    device-backend option of ``wire.encode_update``)."""
    if ef is not None:
        # ``ef`` rides a DONATED argument position: the cross-silo caller
        # holds it as host numpy, and the numpy->device conversion at a
        # donated jit boundary (device_put included) can borrow that
        # memory zero-copy on CPU — the donation would then let XLA
        # write into, and free, memory numpy still owns. ``jnp.array``
        # copies numpy leaves into runtime-owned buffers the donation
        # may safely consume; device-resident leaves pass through.
        import numpy as _np

        ef = jax.tree.map(
            lambda x: jnp.array(x) if isinstance(x, _np.ndarray) else x,
            ef)
    return _encode_math_jit(spec, update, reference, masks, ef)
