"""Composable model-update wire codec (delta / mask-sparse / quantized).

``wire.py`` — spec parsing, the tagged frame format, and the NumPy host
encode/decode the OS-process federation runs without a device.
``device.py`` — the same math as jitted XLA ops (top-k via the Pallas
histogram select) plus ``lossy_roundtrip``, the pure value transform the
simulated engines apply so in-process rounds aggregate exactly what a
cross-silo federation would.
"""

from neuroimagedisttraining_tpu.codec.wire import (  # noqa: F401
    FRAME_KEY,
    FRAME_VERSION,
    SECURE_QUANT_KEY,
    WireSpec,
    decode_update,
    encode_update,
    frame_nbytes,
    is_codec_frame,
    parse_wire_spec,
)
from neuroimagedisttraining_tpu.codec.device import (  # noqa: F401
    lossy_roundtrip,
)
