"""Model-update wire codec: delta + mask/top-k sparse + int8/bf16 quant.

The cross-silo plane historically shipped every model message as a dense
float32 flax-msgpack pytree (~4 bytes/param; distributed/message.py). But
the flagship algorithm's uploads are top-k sparse BY CONSTRUCTION
(SalientGrads' global SNIP mask), DisPFL/Sub-FedAvg train under explicit
masks, and every upload is a small-magnitude residual of the round's
broadcast reference — so the wire can carry far fewer bytes without
changing what the server aggregates (Bonawitz et al. 2017 shows the
aggregation contract survives an encoded transport; FedProx frames
cross-silo FL as bandwidth-bound).

Three composable stages, each optional (``parse_wire_spec``):

- **delta** — the payload becomes ``update - reference`` where the
  reference is the round's broadcast model; the receiver adds it back.
  Value-exact up to one float32 rounding of ``(u - r) + r``; it
  concentrates values near zero so the later stages bite harder (and
  zlib sees low-entropy bytes).
- **sparse** — two modes. *Mask mode* (``masks`` given): engines that
  already own a pruning/saliency mask ship only the surviving values,
  plus a packed bitmap frame — or no bitmap at all when the receiver
  provably holds the same mask (``mask_on_wire=False``: SalientGrads'
  phase-1 mask is computed server-side and broadcast, so both endpoints
  own it — the "mask handoff"). *Top-k mode* (no masks): dense engines
  opt into magnitude top-k over the whole update with a per-client
  error-feedback accumulator — the dropped mass (and quantization error)
  is carried into the next round's residual, so no gradient signal is
  permanently lost (standard EF-SGD semantics).
- **quant** — linear quantization of the surviving values with per-leaf
  scales: ``int8`` (symmetric, scale = amax/127) or ``bf16`` (bit
  truncation). Non-finite scales are impossible by construction
  (amax == 0 -> scale 1).

Frame format (the tagged body frame distributed/message.py's envelope
carries): a dict ``{FRAME_KEY: FRAME_VERSION, "spec", "delta", "z",
"body"}`` where ``body`` is the per-leaf record table serialized with
flax msgpack and (when it shrinks) zlib-deflated. A receiver decodes any
frame without prior configuration — the frame is self-describing except
for shared-mask mode, which fails loudly when the receiver lacks the
mask. Anything WITHOUT the magic key is the dense fallback and passes
through ``decode_update`` untouched, so a dense sender never breaks an
encoded receiver (or vice versa).

This module is the NumPy host path — no JAX dependency on the hot
arrays, so the OS-process federation runs without a device.
``codec/device.py`` holds the jitted encode math and the pure
``lossy_roundtrip`` the simulated engines use; the two paths produce
bitwise-identical decoded values (pinned in tests/test_codec.py).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, replace
from typing import Any

import numpy as np

PyTree = Any

#: frame magic + version: the tagged-body contract. Bump the version on
#: any incompatible layout change; decoders reject unknown versions
#: loudly instead of mis-parsing.
FRAME_KEY = "__nidt_codec__"
FRAME_VERSION = 1

#: magic of the OTHER tagged body on this wire: secure-quantized
#: field-element frames (privacy/secure_quant.py). Defined here — not in
#: privacy/ — so this module can recognize and loudly reject one that
#: reaches the PLAIN decode path (a masked GF(p) residue array decoded
#: as a dense float tree would silently poison the aggregate), without a
#: codec -> privacy import cycle.
SECURE_QUANT_KEY = "__nidt_secure_quant__"

#: magic of the DOWNLINK delta-sync frame (ISSUE 18): a changed-version
#: sync reply shipped as the LOSSLESS byte-delta against the version the
#: sender last synced (the broadcast ring, mirrored downlink). Distinct
#: from the uplink FRAME_KEY codec: uplink deltas are float arithmetic
#: (value-exact up to one f32 rounding); the downlink must reproduce the
#: broadcast tree BITWISE — the receiver trains on it and the ingest
#: delta-transport anchors on its flat image — so the delta is raw-byte
#: XOR against the base, which is exactly invertible for every dtype.
SYNC_DELTA_KEY = "__nidt_sync_delta__"
SYNC_DELTA_VERSION = 1

_QUANT_MODES = ("", "int8", "bf16")
# sparse-record modes: how the receiver learns the support
_SP_DENSE = 0      # all values shipped
_SP_BITMAP = 1     # packed bitmap frame precedes the values
_SP_SHARED = 2     # receiver holds the same mask (engine mask handoff)


@dataclass(frozen=True)
class WireSpec:
    """Parsed ``--wire_codec`` value. Hashable (jit-static) and order-
    insensitive: ``"quant+delta" == "delta+quant"``."""

    delta: bool = False
    sparse: bool = False
    quant: str = ""            # "" | "int8" | "bf16"
    topk_ratio: float = 0.25   # top-k keep fraction when sparse w/o masks

    @property
    def canonical(self) -> str:
        parts = ([p for p, on in (("delta", self.delta),
                                  ("sparse", self.sparse)) if on]
                 + ([{"int8": "quant", "bf16": "quant16"}[self.quant]]
                    if self.quant else []))
        return "+".join(parts) if parts else "none"

    @property
    def needs_ef(self) -> bool:
        """Error feedback applies only to lossy TOP-K sparsification;
        mask-mode sparsity drops entries the engine's own training
        already pins to zero, so there is no mass to feed back."""
        return self.sparse


def parse_wire_spec(text: str, topk_ratio: float = 0.25) -> WireSpec | None:
    """``none | delta | sparse | quant | quant16`` joined by ``+`` in any
    order -> WireSpec, or None for "none"/empty (dense wire)."""
    text = (text or "none").strip().lower()
    if text in ("", "none"):
        return None
    spec = WireSpec(topk_ratio=float(topk_ratio))
    for tok in text.split("+"):
        tok = tok.strip()
        if tok == "delta":
            spec = replace(spec, delta=True)
        elif tok == "sparse":
            spec = replace(spec, sparse=True)
        elif tok in ("quant", "int8", "quant8"):
            spec = replace(spec, quant="int8")
        elif tok in ("quant16", "bf16"):
            spec = replace(spec, quant="bf16")
        elif tok in ("", "none"):
            raise ValueError(
                f"--wire_codec {text!r}: 'none' cannot compose with "
                "other stages")
        else:
            raise ValueError(
                f"--wire_codec {text!r}: unknown stage {tok!r} (have "
                "delta | sparse | quant | quant16)")
    if not 0.0 < spec.topk_ratio <= 1.0:
        raise ValueError(
            f"wire_topk_ratio ({spec.topk_ratio}) must be in (0, 1]")
    return spec


def is_codec_frame(obj: Any) -> bool:
    return isinstance(obj, dict) and FRAME_KEY in obj


# ---------------------------------------------------------------------------
# pytree <-> named flat leaves (decode rebuilds against a template tree,
# so the frame never needs to carry a treedef)
# ---------------------------------------------------------------------------

def _named_leaves(tree: PyTree) -> list[tuple[str, np.ndarray]]:
    import jax

    out = []
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        name = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path)
        out.append((name, leaf))
    return out


def _rebuild_like(template: PyTree, by_name: dict[str, np.ndarray]) -> PyTree:
    import jax

    def build(path, leaf):
        name = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path)
        if name not in by_name:
            raise ValueError(
                f"codec frame is missing leaf {name!r} present in the "
                "template tree — sender/receiver model structures differ")
        return by_name[name]

    return jax.tree_util.tree_map_with_path(build, template)


# ---------------------------------------------------------------------------
# shared encode math (float32 numpy; codec/device.py mirrors it in jnp —
# the two must stay bitwise-aligned, tests/test_codec.py pins it)
# ---------------------------------------------------------------------------

def _topk_threshold_np(absflat: np.ndarray, k: int) -> np.float32:
    """Exact k-th largest of a 1-D float32 vector — same tie semantics as
    ops/topk.kth_largest: a ``|x| >= thr`` mask keeps >= k entries."""
    k = min(max(int(k), 1), absflat.size)
    return np.partition(absflat, absflat.size - k)[absflat.size - k]


def _quant_encode(vals: np.ndarray, quant: str) -> tuple[np.ndarray, float]:
    """Kept values -> wire values + per-leaf scale (int8 symmetric)."""
    if quant == "int8":
        amax = np.float32(np.max(np.abs(vals))) if vals.size else np.float32(0)
        scale = np.float32(amax / np.float32(127.0)) if amax > 0 \
            else np.float32(1.0)
        q = np.clip(np.rint(vals / scale), -127, 127).astype(np.int8)
        return q, float(scale)
    if quant == "bf16":
        import ml_dtypes

        return vals.astype(ml_dtypes.bfloat16).view(np.uint16), 0.0
    return vals, 0.0


def _quant_decode(wire_vals: np.ndarray, quant: str,
                  scale: float) -> np.ndarray:
    if quant == "int8":
        return wire_vals.astype(np.float32) * np.float32(scale)
    if quant == "bf16":
        import ml_dtypes

        return np.asarray(wire_vals, np.uint16).view(
            ml_dtypes.bfloat16).astype(np.float32)
    return np.asarray(wire_vals, np.float32)


# ---------------------------------------------------------------------------
# encode / decode
# ---------------------------------------------------------------------------

def encode_update(spec: WireSpec, update: PyTree, *,
                  reference: PyTree | None = None,
                  masks: PyTree | None = None,
                  ef: PyTree | None = None,
                  mask_on_wire: bool = True,
                  zlib_level: int = 6,
                  backend: str = "numpy") -> tuple[dict, PyTree | None]:
    """Encode one model update into a wire frame.

    Returns ``(frame, new_ef)``. ``new_ef`` is the next round's
    error-feedback accumulator (top-k mode only; None otherwise — pass it
    back in on the next call). ``reference`` is required when
    ``spec.delta`` (the round's broadcast model the receiver also holds);
    ``masks`` switches the sparse stage to mask mode; ``mask_on_wire``
    False elides the bitmap frame for masks the receiver provably owns
    (engine mask handoff — the frame then flags shared-mask mode and the
    receiver must supply the identical mask to ``decode_update``).
    ``backend="jax"`` runs the residual/EF/top-k math as one jitted
    program (codec/device.py — the Pallas histogram select on TPU) and
    keeps only the variable-length packing on the host; "numpy" is the
    device-free fallback the OS-process federation uses. Both produce
    byte-identical frames.
    """
    from flax import serialization

    if spec.delta and reference is None:
        raise ValueError("wire codec: delta stage needs the round's "
                         "broadcast reference tree")
    upd = _named_leaves(update)
    refs = dict(_named_leaves(reference)) if reference is not None else {}
    mask_by = dict(_named_leaves(masks)) if masks is not None else {}
    track_ef = spec.sparse and masks is None

    keep_by: dict[str, np.ndarray] = {}
    new_ef: dict[str, np.ndarray] = {}
    if backend == "jax":
        from neuroimagedisttraining_tpu.codec import device as D

        x_tree, keep_tree, ef_tree_dev = D.encode_math(
            spec, update, reference=reference, masks=masks, ef=ef)
        residuals = {name: np.asarray(v)
                     for name, v in _named_leaves(x_tree)}
        if keep_tree is not None:
            keep_by = {name: np.asarray(v)
                       for name, v in _named_leaves(keep_tree)}
        if ef_tree_dev is not None:
            new_ef = {name: np.asarray(v)
                      for name, v in _named_leaves(ef_tree_dev)}
    else:
        ef_by = dict(_named_leaves(ef)) if ef is not None else {}
        # residuals (+ error feedback) per leaf, then the GLOBAL top-k
        # threshold across every leaf (cross-layer, like the SNIP mask)
        residuals = {}
        for name, leaf in upd:
            x = np.asarray(leaf, np.float32)
            if spec.delta:
                x = x - np.asarray(refs[name], np.float32)
            if track_ef and name in ef_by:
                x = x + np.asarray(ef_by[name], np.float32)
            residuals[name] = x
        if spec.sparse:
            if masks is not None:
                keep_by = {name: np.asarray(m) > 0
                           for name, m in mask_by.items()}
            else:
                flat = np.concatenate([np.abs(v).reshape(-1)
                                       for v in residuals.values()])
                k = max(1, int(np.ceil(spec.topk_ratio * flat.size)))
                thr = _topk_threshold_np(flat, k)
                keep_by = {name: np.abs(v) >= thr
                           for name, v in residuals.items()}

    leaves: dict[str, dict] = {}
    for name, leaf in upd:
        x = residuals[name]
        rec: dict[str, Any] = {"sh": list(x.shape), "dt": str(
            np.asarray(leaf).dtype)}
        if spec.sparse:
            keep = keep_by[name]
            if masks is not None:
                rec["sp"] = _SP_SHARED if not mask_on_wire else _SP_BITMAP
                # mask-zero semantics: the engine's training pins
                # off-mask entries to exact zero, so the decoder must
                # reconstruct 0 there — not the delta reference (round
                # 0's dense init would otherwise survive off-mask)
                rec["mz"] = 1
            else:
                rec["sp"] = _SP_BITMAP
            if rec["sp"] == _SP_BITMAP:
                if keep.all():
                    rec["sp"] = _SP_DENSE  # bitmap would be pure overhead
                else:
                    rec["bm"] = np.packbits(keep.reshape(-1))
            kept = x.reshape(-1)[keep.reshape(-1)]
        else:
            keep = None
            kept = x.reshape(-1)
        wire_vals, scale = _quant_encode(kept, spec.quant)
        rec["q"] = spec.quant
        if spec.quant == "int8":
            rec["sc"] = scale
        rec["v"] = wire_vals
        leaves[name] = rec
        if track_ef and backend != "jax":  # jax backend computed EF on device
            deq = np.zeros(x.size, np.float32)
            pos = keep.reshape(-1) if keep is not None else slice(None)
            deq[pos] = _quant_decode(wire_vals, spec.quant, scale)
            new_ef[name] = x - deq.reshape(x.shape)

    body = serialization.msgpack_serialize({"leaves": leaves})
    packed = zlib.compress(body, zlib_level)
    z = 1 if len(packed) < len(body) else 0
    frame = {FRAME_KEY: FRAME_VERSION, "spec": spec.canonical,
             "delta": int(spec.delta), "z": z,
             "body": np.frombuffer(packed if z else body, np.uint8)}
    ef_tree = (_rebuild_like(update, new_ef) if track_ef else None)
    return frame, ef_tree


def decode_update(obj: Any, *, like: PyTree,
                  reference: PyTree | None = None,
                  masks: PyTree | None = None) -> PyTree:
    """Decode a wire frame back into a pytree shaped like ``like``.

    Dense fallback: anything without the frame magic passes through
    unchanged, so a receiver never needs to know the sender's codec
    config. ``reference`` is required for delta frames; ``masks`` for
    shared-mask frames (both fail loudly when absent).
    """
    from flax import serialization

    if isinstance(obj, dict) and SECURE_QUANT_KEY in obj:
        raise ValueError(
            "received a secure-quant field-element frame on the plain "
            "decode path: its values are masked GF(p) residues, not "
            "model floats — the receiver must run the secure-quant "
            "server (--secure_quant on every rank; see "
            "privacy/secure_quant.py and ARCHITECTURE.md 'Privacy "
            "plane')")
    if not is_codec_frame(obj):
        return obj  # dense fallback: always decodable
    ver = obj[FRAME_KEY]
    if int(ver) != FRAME_VERSION:
        raise ValueError(f"wire codec frame version {ver} != supported "
                         f"{FRAME_VERSION}")
    raw = np.asarray(obj["body"], np.uint8).tobytes()
    if int(obj.get("z", 0)):
        raw = zlib.decompress(raw)
    leaves = serialization.msgpack_restore(raw)["leaves"]
    delta = bool(int(obj.get("delta", 0)))
    if delta and reference is None:
        raise ValueError("wire codec: delta frame needs the round's "
                         "broadcast reference to decode")
    refs = dict(_named_leaves(reference)) if reference is not None else {}
    mask_by = dict(_named_leaves(masks)) if masks is not None else {}

    out: dict[str, np.ndarray] = {}
    for name, rec in leaves.items():
        shape = tuple(int(s) for s in rec["sh"])
        size = int(np.prod(shape)) if shape else 1
        vals = _quant_decode(rec["v"], rec.get("q", ""),
                             float(rec.get("sc", 0.0)))
        sp = int(rec.get("sp", _SP_DENSE))
        if sp == _SP_DENSE:
            flat = vals.astype(np.float32)
            keep = None
        else:
            if sp == _SP_SHARED:
                if name not in mask_by:
                    raise ValueError(
                        f"wire codec: frame for leaf {name!r} uses "
                        "shared-mask mode but the receiver holds no mask "
                        "— configure the same engine mask on both "
                        "endpoints (mask handoff)")
                keep = (np.asarray(mask_by[name]) > 0).reshape(-1)
            else:
                keep = np.unpackbits(np.asarray(rec["bm"], np.uint8),
                                     count=size).astype(bool)
            flat = np.zeros(size, np.float32)
            flat[keep] = vals
        x = flat.reshape(shape)
        if delta:
            ref = np.asarray(refs[name], np.float32)
            if keep is not None and int(rec.get("mz", 0)):
                x = np.where(keep.reshape(shape), x + ref, np.float32(0.0))
            else:
                x = x + ref
        out[name] = x.astype(rec.get("dt", "float32"))
    return _rebuild_like(like, out)


# ---------------------------------------------------------------------------
# downlink delta-sync (ISSUE 18): lossless byte-delta between two
# versions of the SAME model tree
# ---------------------------------------------------------------------------

def is_sync_delta_frame(obj: Any) -> bool:
    return isinstance(obj, dict) and SYNC_DELTA_KEY in obj


def _tree_bytes(tree: PyTree) -> bytes:
    """The tree's raw leaf bytes, concatenated in named-leaf order —
    the canonical byte image both delta endpoints agree on (they hold
    structurally identical trees: consecutive versions of one model)."""
    return b"".join(np.ascontiguousarray(np.asarray(x)).tobytes()
                    for _, x in _named_leaves(tree))


def _byte_shuffle(x: np.ndarray) -> np.ndarray:
    """Stride-4 byte-plane transpose (the HDF5 'shuffle' filter). The
    XOR image of two float32 versions has near-zero sign/exponent bytes
    and noisy low-mantissa bytes ELEMENT-INTERLEAVED; grouping byte
    plane k of every element into one run hands zlib long zero runs
    instead of a zero-noise-noise-noise stipple it cannot match. Pure
    permutation — losslessly inverted by :func:`_byte_unshuffle` — so
    it is safe (if pointless) on non-4-byte leaves too; the trailing
    ``len % 4`` bytes pass through untouched."""
    n4 = (x.size // 4) * 4
    if n4 == 0:
        return x
    return np.concatenate(
        [x[:n4].reshape(-1, 4).T.ravel(), x[n4:]])


def _byte_unshuffle(x: np.ndarray) -> np.ndarray:
    n4 = (x.size // 4) * 4
    if n4 == 0:
        return x
    return np.concatenate(
        [x[:n4].reshape(4, -1).T.ravel(), x[n4:]])


def encode_sync_delta(new: PyTree, base: PyTree, *, base_version: int,
                      zlib_level: int = 6) -> dict:
    """Encode ``new`` as the lossless delta against ``base``.

    The body is ``bytes(new) XOR bytes(base)``, byte-plane shuffled,
    deflated: consecutive aggregated models are means of overlapping
    cohorts, so their float bit patterns agree in the sign/exponent/
    high-mantissa bits and the shuffled XOR image is long zero runs —
    zlib's favorite input. Exactness is structural (XOR is its own
    inverse on the byte level and the shuffle is a permutation), never
    a float-rounding argument, so ``decode_sync_delta(frame, base) ==
    new`` BITWISE for every leaf dtype (pinned in tests).
    """
    nb = _tree_bytes(new)
    bb = _tree_bytes(base)
    if len(nb) != len(bb):
        raise ValueError(
            "sync delta: base and new trees have different byte sizes "
            f"({len(bb)} vs {len(nb)}) — not two versions of one model")
    x = _byte_shuffle(
        np.frombuffer(nb, np.uint8) ^ np.frombuffer(bb, np.uint8))
    packed = zlib.compress(x.tobytes(), zlib_level)
    z = 1 if len(packed) < x.size else 0
    return {SYNC_DELTA_KEY: SYNC_DELTA_VERSION,
            "base": int(base_version), "z": z,
            "body": np.frombuffer(packed, np.uint8) if z else x}


def decode_sync_delta(frame: dict, base: PyTree) -> PyTree:
    """Invert :func:`encode_sync_delta` against the receiver-held base
    tree (which MUST be the version named by ``frame["base"]`` — the
    caller checks that against its own sync bookkeeping and treats a
    mismatch as a protocol error, never a silent wrong model)."""
    ver = frame[SYNC_DELTA_KEY]
    if int(ver) != SYNC_DELTA_VERSION:
        raise ValueError(f"sync delta frame version {ver} != supported "
                         f"{SYNC_DELTA_VERSION}")
    raw = np.asarray(frame["body"], np.uint8).tobytes()
    if int(frame.get("z", 0)):
        raw = zlib.decompress(raw)
    bb = _tree_bytes(base)
    if len(raw) != len(bb):
        raise ValueError(
            f"sync delta: body is {len(raw)} bytes but the base tree "
            f"is {len(bb)} — receiver base differs from the encoder's")
    nb = (_byte_unshuffle(np.frombuffer(raw, np.uint8))
          ^ np.frombuffer(bb, np.uint8)).tobytes()
    out: dict[str, np.ndarray] = {}
    off = 0
    for name, leaf in _named_leaves(base):
        arr = np.asarray(leaf)
        n = arr.nbytes
        out[name] = np.frombuffer(
            nb[off:off + n], arr.dtype).reshape(arr.shape)
        off += n
    return _rebuild_like(base, out)


def frame_nbytes(frame: dict) -> int:
    """Exact on-the-wire size of a frame (or dense tree) once the message
    envelope serializes it — the codec A/B's numerator/denominator."""
    from flax import serialization

    import jax

    as_np = jax.tree.map(
        lambda v: np.asarray(v) if hasattr(v, "shape") else v, frame)
    return len(serialization.msgpack_serialize(as_np))
