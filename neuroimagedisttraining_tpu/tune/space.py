"""The autotuner's search space as data (ISSUE 19, tune/).

A space is the cross product of the probe cell axes (obs/probe.py
``CELL_KEYS`` — the ``batch`` axis included) filtered through validity
predicates, so the search NEVER proposes a cell the CLIs would reject
at startup:

- every axis value passes the probe domain check
  (``obs_probe.validate_cell_value`` — the same validator manifests
  load through);
- the startup-rejection knowledge extracted into
  ``analysis/compat_matrix.py`` is re-applied here: of the committed
  rejection rows, exactly those whose guard knobs fall inside the
  tuned-or-pinned knob set constrain the space
  (``relevant_compat_rows``), and the predicates satisfy each one —
  ``fused_update`` composes because the tuner PINS
  ``client_optimizer=sgd``; ``loss_scale`` is pinned 1.0 so every
  precision composes;
- device-kind-aware bounds: ``client_mesh`` cells above the visible
  device count are dropped (the driver would skip them), and on
  devices with a known HBM capacity the activation-byte estimate the
  profiler's ``memory_analysis``/``nidt_hbm_peak_bytes`` plane
  measures is approximated per cell to drop batch sizes that cannot
  fit (``est_step_bytes``).

Cells enumerate in a deterministic order (declared axis order, value
order as declared) and are identified by a sha256 fingerprint of their
canonical JSON — the journal/resume key and the tie-breaker the search
sorts by.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json

from neuroimagedisttraining_tpu.obs import probe as obs_probe

__all__ = ["Space", "build_space", "cell_fingerprint", "cell_valid",
           "est_step_bytes", "relevant_compat_rows", "PINNED",
           "DEFAULT_AXES", "HBM_BYTES_BY_KIND"]

#: knobs the tuner PINS instead of searching — part of the space's
#: identity (the compat predicates below depend on them)
PINNED = {"client_optimizer": "sgd", "loss_scale": 1.0,
          "algorithm": "fedavg"}

#: per-device HBM capacities by device kind (bytes); kinds not listed
#: (cpu included) are unbounded here — host RAM is not the contract
#: this bound models
HBM_BYTES_BY_KIND = {
    "TPU v2": 8 << 30,
    "TPU v3": 16 << 30,
    "TPU v4": 32 << 30,
    "TPU v5 lite": 16 << 30,
    "TPU v5p": 95 << 30,
}

#: the CPU-harness default axes (small on purpose: the committed
#: artifact regenerates on this box); a TPU session passes the
#: flagship axes instead (scripts/run_autotune.sh documents the
#: command). Order is the enumeration order.
DEFAULT_AXES: tuple[tuple[str, tuple], ...] = (
    ("precision", ("fp32", "bf16_mixed")),
    ("fused_update", (False, True)),
    ("remat", ("none", "stem")),
    ("client_mesh", (0, 2)),
    ("rounds_per_dispatch", (1, 4)),
    ("batch", (4, 8, 16)),
)


def cell_fingerprint(cell: dict) -> str:
    """Canonical-JSON sha256 prefix — the journal key, the recipe's
    winner id, and the deterministic tie-breaker."""
    canon = json.dumps(cell, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def relevant_compat_rows() -> tuple[dict, ...]:
    """The committed startup-rejection rows whose guard knobs all fall
    inside the tuned-or-pinned knob set — the rejection knowledge the
    validity predicates must (and do) satisfy. Rows reading knobs the
    tuner neither searches nor pins cannot constrain the space."""
    from neuroimagedisttraining_tpu.analysis.compat_matrix import MATRIX

    knobs = {name for name, _ in DEFAULT_AXES} | set(PINNED)
    # the probe cell key "batch" rides OptimConfig.batch_size
    knobs |= {"batch_size"}
    return tuple(r for r in MATRIX if set(r["knobs"]) <= knobs)


def est_step_bytes(shape: tuple[int, ...], batch: int, precision: str,
                   remat) -> int:
    """Deterministic activation-footprint estimate of one train step
    (bytes/device): batch x voxels x a stem-channel expansion factor at
    the compute dtype, plus the fp32 master/grad residency. This is
    the cheap stand-in for the ``memory_analysis`` bytes the profiler
    publishes as ``nidt_hbm_peak_bytes`` — same shape of answer, no
    compile. Remat divides the live-activation term (stem frees the
    widest early maps; full remat keeps ~one stage live)."""
    voxels = 1
    for s in shape:
        voxels *= int(s)
    act_bytes = 2 if precision == "bf16_mixed" else 4
    channels = 32  # stem feature-map expansion of the 3D-CNN family
    live = batch * voxels * channels * act_bytes
    policy = obs_probe.remat_policy(remat)
    if policy == "stem":
        live //= 2
    elif policy is True:
        live //= 4
    master = 64 << 20  # params + momentum + grads, f32 (model-scale)
    return int(live + master)


def cell_valid(cell: dict, *, n_devices: int = 1,
               hbm_bytes: int | None = None,
               shape: tuple[int, ...] = (12, 14, 12)
               ) -> tuple[bool, str]:
    """(ok, reason). Every predicate mirrors a startup rejection or
    driver skip — an invalid cell is one the CLIs/driver would refuse,
    never a taste judgment."""
    for key, value in cell.items():
        obs_probe.validate_cell_value(key, value)
    if cell.get("fused_update") and PINNED["client_optimizer"] != "sgd":
        # compat row (client_optimizer, fused_update): only the sgd
        # tail has a fused kernel
        return False, "fused_update requires the sgd optimizer"
    cm = int(cell.get("client_mesh", 0))
    if cm > n_devices:
        return False, (f"client_mesh={cm} needs {cm} devices, "
                       f"{n_devices} visible")
    if hbm_bytes:
        need = est_step_bytes(shape, int(cell.get("batch", 8)),
                              cell.get("precision", "fp32"),
                              cell.get("remat", "none"))
        if need > 0.92 * hbm_bytes:
            return False, (f"hbm-bound: ~{need >> 20} MiB estimated "
                           f"step footprint vs {hbm_bytes >> 20} MiB "
                           "device HBM")
    return True, ""


@dataclasses.dataclass(frozen=True)
class Space:
    """One declared search space: axes (ordered), the device context
    the validity predicates were evaluated against, and the harness
    shape the HBM estimate uses."""

    axes: tuple[tuple[str, tuple], ...]
    device_kind: str = "cpu"
    n_devices: int = 1
    shape: tuple[int, ...] = (12, 14, 12)
    hbm_bytes: int | None = None

    def __post_init__(self):
        known = set(obs_probe.CELL_KEYS)
        bad = [name for name, _ in self.axes if name not in known]
        if bad:
            raise ValueError(
                f"space names unknown axes {sorted(bad)}; tunable axes "
                f"are the probe cell keys: {obs_probe.CELL_KEYS}")
        for name, values in self.axes:
            if not values:
                raise ValueError(f"axis {name!r} has no values")
            for v in values:
                obs_probe.validate_cell_value(name, v)

    def fingerprint(self) -> str:
        canon = json.dumps(
            {"axes": [[n, list(vs)] for n, vs in self.axes],
             "device_kind": self.device_kind,
             "n_devices": self.n_devices,
             "shape": list(self.shape),
             "hbm_bytes": self.hbm_bytes,
             "pinned": {k: PINNED[k] for k in sorted(PINNED)}},
            sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(canon.encode()).hexdigest()[:16]

    def cells(self) -> tuple[list[dict], list[dict]]:
        """(valid, rejected) in deterministic enumeration order;
        rejected rows carry the predicate's reason (the session
        artifact records them — a bounded space must say what it
        dropped, not silently shrink)."""
        names = [n for n, _ in self.axes]
        valid: list[dict] = []
        rejected: list[dict] = []
        for combo in itertools.product(*(vs for _, vs in self.axes)):
            cell = dict(zip(names, combo))
            ok, reason = cell_valid(cell, n_devices=self.n_devices,
                                    hbm_bytes=self.hbm_bytes,
                                    shape=self.shape)
            if ok:
                valid.append(cell)
            else:
                rejected.append({"cell": cell, "reason": reason,
                                 "fingerprint": cell_fingerprint(cell)})
        return valid, rejected


def build_space(device_kind: str = "cpu", n_devices: int = 1,
                shape: tuple[int, ...] = (12, 14, 12),
                axes: tuple[tuple[str, tuple], ...] | None = None
                ) -> Space:
    """The default space for a device context: declared axes plus the
    device-kind HBM bound (None off-TPU — host RAM is not modeled)."""
    hbm = HBM_BYTES_BY_KIND.get(device_kind)
    return Space(axes=tuple(axes) if axes is not None else DEFAULT_AXES,
                 device_kind=device_kind, n_devices=int(n_devices),
                 shape=tuple(int(s) for s in shape), hbm_bytes=hbm)
