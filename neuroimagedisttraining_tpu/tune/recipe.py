"""Per-hardware recipes: the autotuner's winner as a config artifact
(ISSUE 19, tune/).

A recipe is the committed serialization of one search winner —
``bench_matrix/recipes/<device_kind>.json`` — carrying the winning
cell, its committed-window score, the full score trace of both
fidelity rungs, the space fingerprint it was searched under, and a
sha256 self-pin over the whole document (a truncated or hand-edited
recipe fails loudly at load, never silently mis-tunes a run).

``--recipe <path|auto>`` on BOTH CLIs loads one as config DEFAULTS:
every knob the operator did not spell on the command line is set from
the recipe; a knob the operator DID spell wins, and the override is
announced through the structured fallback machinery
(``engines/program.py`` REASONS key ``recipe-override``) so the
divergence is scrapeable, not silent. Loading also publishes the
recipe's score as ``nidt_recipe_score`` and arms the
``mfu-below-recipe`` drift rule (:func:`drift_rules`): when the live
score metric sits below 80% of the recipe's recorded score for 3
boundaries, ``nidt_alert`` fires and a ``retune_recommended`` event
lands in the flight recorder — the closed loop's "re-tune now"
signal.

Every key a recipe may set is declared in :data:`RECIPE_KEYS`
(cell knob -> CLI option); the ``recipe-key-closure`` project lint
rule checks the committed recipes stay inside this table and that the
table's options exist on both CLIs.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import sys

from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import names as obs_names
from neuroimagedisttraining_tpu.obs import probe as obs_probe
from neuroimagedisttraining_tpu.tune.space import cell_fingerprint

__all__ = ["RECIPE_KEYS", "apply_recipe", "drift_rules", "load_recipe",
           "resolve_and_load", "recipe_doc_from_search", "recipe_sha",
           "write_recipe", "recipes_dir", "device_slug"]

#: every knob a recipe may set, mapped to the CLI option that owns it
#: on BOTH CLIs (the ``recipe-key-closure`` lint rule pins this table
#: against the committed recipes and both argparse surfaces). A cell
#: key outside this table is a load-time error — a recipe can never
#: name a config field the CLIs do not declare.
RECIPE_KEYS = {
    "precision": "--precision",
    "fused_update": "--fused_update",
    "remat": "--remat",
    "client_mesh": "--client_mesh",
    "rounds_per_dispatch": "--rounds_per_dispatch",
    "batch": "--batch_size",
}

#: live-score-to-recipe-score ratio below which the drift rule fires
DRIFT_RATIO = 0.8
#: boundaries the ratio must hold before the drift rule fires
DRIFT_ROUNDS = 3


def device_slug(device_kind: str) -> str:
    """``"TPU v4"`` -> ``"tpu_v4"`` — the recipe file stem."""
    return device_kind.strip().lower().replace(" ", "_")


def recipes_dir() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "bench_matrix", "recipes")


def recipe_sha(doc: dict) -> str:
    """sha256 over the canonical JSON of the document MINUS its own
    ``sha256`` field — the self-pin."""
    body = {k: v for k, v in doc.items() if k != "sha256"}
    canon = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()


def recipe_doc_from_search(result: dict, device_kind: str) -> dict:
    """The committed recipe document for one ``run_search`` result:
    winner + score trace of both rungs + the space identity, sha-pinned.
    Key order is irrelevant (serialization sorts); the trace keeps only
    the ranking-relevant fields so recipe bytes stay stable."""
    def _trace(rows):
        return [{"fingerprint": m["fingerprint"], "fidelity": m["fidelity"],
                 "status": m["status"], "score": m["score"],
                 "reason": m["reason"]} for m in rows]

    w = result["winner"]
    doc = {
        "metric": "autotune_recipe",
        "device_kind": device_kind,
        "cell": dict(w["cell"]),
        "fingerprint": w["fingerprint"],
        "score": w["score"],
        "score_metric": w["score_metric"],
        "fidelity": w["fidelity"],
        "seed": result["seed"],
        "space_fingerprint": result["space_fingerprint"],
        "trace": {"screened": _trace(result["screened"]),
                  "refined": _trace(result["refined"]),
                  "rejected": result["rejected"]},
    }
    doc["sha256"] = recipe_sha(doc)
    return doc


def write_recipe(doc: dict, path: str) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


def load_recipe(path: str, expected_kind: str | None = None) -> dict:
    """Load + fully validate one recipe file. Every failure mode is a
    ``ValueError`` naming the file and the defect — the CLIs surface it
    through ``parser.error`` so a bad recipe dies loudly at startup."""
    try:
        with open(path) as f:
            raw = f.read()
    except OSError as e:
        raise ValueError(f"recipe {path}: cannot read ({e})") from e
    try:
        doc = json.loads(raw)
    except ValueError as e:
        raise ValueError(
            f"recipe {path}: invalid JSON ({e}) — truncated or "
            "corrupt; regenerate with scripts/run_autotune.sh") from e
    if not isinstance(doc, dict):
        raise ValueError(f"recipe {path}: expected a JSON object, got "
                         f"{type(doc).__name__}")
    missing = [k for k in ("device_kind", "cell", "fingerprint",
                           "score", "score_metric", "sha256")
               if k not in doc]
    if missing:
        raise ValueError(f"recipe {path}: missing keys {missing}")
    want = recipe_sha(doc)
    if doc["sha256"] != want:
        raise ValueError(
            f"recipe {path}: sha256 mismatch (recorded "
            f"{doc['sha256'][:12]}…, computed {want[:12]}…) — the file "
            "was edited or truncated after emission; re-run the tuner")
    cell = doc["cell"]
    if not isinstance(cell, dict) or not cell:
        raise ValueError(f"recipe {path}: 'cell' must be a non-empty "
                         "object of knob -> value")
    for key, value in sorted(cell.items()):
        if key not in RECIPE_KEYS:
            raise ValueError(
                f"recipe {path}: cell key {key!r} has no config-field "
                f"mapping; a recipe may only set "
                f"{sorted(RECIPE_KEYS)} (tune/recipe.py RECIPE_KEYS)")
        try:
            obs_probe.validate_cell_value(key, value)
        except ValueError as e:
            raise ValueError(f"recipe {path}: {e}") from e
    if cell_fingerprint(cell) != doc["fingerprint"]:
        raise ValueError(
            f"recipe {path}: winner fingerprint does not match the "
            "cell — the file was hand-edited; re-run the tuner")
    if expected_kind is not None and doc["device_kind"] != expected_kind:
        raise ValueError(
            f"recipe {path}: tuned for device_kind "
            f"{doc['device_kind']!r} but this process runs on "
            f"{expected_kind!r}; pass the matching recipe or re-tune "
            "(scripts/run_autotune.sh)")
    doc["_path"] = path
    return doc


def _live_device_kind() -> str:
    import jax
    return jax.devices()[0].device_kind


def resolve_and_load(arg: str) -> dict:
    """``--recipe`` resolution: a literal path loads that file (its
    device_kind must match the live backend); ``auto`` looks up the
    committed recipe for the live device kind under
    ``bench_matrix/recipes/``."""
    kind = _live_device_kind()
    if arg == "auto":
        path = os.path.join(recipes_dir(), device_slug(kind) + ".json")
        if not os.path.exists(path):
            have = sorted(os.path.basename(p) for p in
                          glob.glob(os.path.join(recipes_dir(), "*.json")))
            raise ValueError(
                f"no committed recipe for device_kind {kind!r} "
                f"(looked for {path}); committed recipes: "
                f"{have or 'none'} — run scripts/run_autotune.sh")
    else:
        path = arg
    return load_recipe(path, expected_kind=kind)


def apply_recipe(args, doc: dict, argv: list[str]) -> list[str]:
    """Apply a loaded recipe to the parsed-args namespace as config
    DEFAULTS: each recipe knob whose CLI option the operator did NOT
    spell in ``argv`` is set from the recipe; an explicitly-spelled
    option keeps its CLI value and the divergence is announced through
    the structured fallback counter (REASONS key ``recipe-override``).
    Returns the cell keys that were overridden (kept CLI values)."""
    from neuroimagedisttraining_tpu.engines.program import report_fallback

    overridden: list[str] = []
    for key in sorted(doc["cell"]):
        opt = RECIPE_KEYS[key]
        dest = "batch_size" if key == "batch" else opt.lstrip("-")
        value = doc["cell"][key]
        explicit = any(tok == opt or tok.startswith(opt + "=")
                       for tok in argv)
        if explicit:
            overridden.append(key)
            msg = report_fallback("cli", "recipe-override")
            print(f"[recipe] {opt} spelled on the command line; keeping "
                  f"the CLI value over the recipe's {value!r} — {msg}",
                  file=sys.stderr)
            continue
        if key == "fused_update":
            value = bool(value)
        elif key == "remat" and isinstance(value, bool):
            value = "all" if value else "none"
        setattr(args, dest, value)
    obs_metrics.gauge(
        obs_names.RECIPE_SCORE,
        "the loaded autotuner recipe's recorded committed-window score "
        "(tune/recipe.py) — the mfu-below-recipe drift rule compares "
        "the live score metric against 80% of this",
    ).set(float(doc["score"]))
    return overridden


def drift_rules(doc: dict) -> tuple:
    """The closed loop's re-tune trigger: one HealthRule that fires
    when the live score metric sits below ``DRIFT_RATIO`` of the
    recipe's recorded score for ``DRIFT_ROUNDS`` boundaries. Firing
    raises ``nidt_alert{rule="mfu-below-recipe"}`` and records a
    ``retune_recommended`` flight event (obs/rules.py
    ``on_fire_event``) — the operator's cue to re-run
    scripts/run_autotune.sh."""
    from neuroimagedisttraining_tpu.obs.rules import HealthRule

    score = doc.get("score")
    if score is None:
        return ()
    metric = (obs_names.MFU if doc.get("score_metric") == "mfu"
              else obs_names.SUSTAINED_TFLOPS)
    return (HealthRule(
        name="mfu-below-recipe",
        metric=metric,
        op="<",
        threshold=DRIFT_RATIO * float(score),
        severity="warn",
        for_rounds=DRIFT_ROUNDS,
        description=(
            "live {} below {:.0%} of the loaded recipe's committed "
            "score {} — hardware/config drift; re-tune "
            "(scripts/run_autotune.sh)".format(metric, DRIFT_RATIO,
                                               score)),
        on_fire_event="retune_recommended",
    ),)
