"""Autotune CLI (ISSUE 19): search the declared space on this
hardware, emit the per-device-kind recipe + the session artifact.

::

    python -m neuroimagedisttraining_tpu.tune \
        --backend virtual --seed 20 --virtual_devices 2 \
        --out /tmp/recipes/cpu.json --session_out /tmp/autotune.json \
        --journal /tmp/tune.jsonl --validate_winner

Backends: ``virtual`` scores cells through the seeded deterministic
cost model (tune/search.py ``virtual_measure`` — the CPU harness's
artifact generator, byte-reproducible); ``driver`` measures every cell
through the shipped ``engine.train()`` probe driver (the TPU-session
mode; wall-clock scores, journal still makes it resumable).

The virtual backend finishes with a determinism self-check — the whole
search re-runs twice in memory and the serialized recipes are
byte-compared — and ``--validate_winner`` additionally runs the winning
cell through the REAL driver once at screen fidelity, so the committed
recipe is proven loadable and runnable, not just well-scored. The last
stdout line is the machine-readable session summary (the CLI contract
shared with the trainer CLIs).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from neuroimagedisttraining_tpu.tune import recipe as tune_recipe
from neuroimagedisttraining_tpu.tune import search as tune_search
from neuroimagedisttraining_tpu.tune import space as tune_space


def add_args(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--backend", type=str, default="virtual",
                    choices=("virtual", "driver"),
                    help="measurement backend: the seeded deterministic "
                         "cost model | the real engine.train() probe "
                         "driver")
    ap.add_argument("--seed", type=int, default=20,
                    help="search seed (virtual scores + tie-breaks are "
                         "derived from it; same seed + space = same "
                         "recipe bytes)")
    ap.add_argument("--out", type=str, default="",
                    help="recipe output path (default: "
                         "bench_matrix/recipes/<device_kind>.json)")
    ap.add_argument("--session_out", type=str, default="",
                    help="session-artifact output path (the bench_gate-"
                         "spec'd autotune_session.json); empty = don't "
                         "write")
    ap.add_argument("--journal", type=str, default="",
                    help="JSONL measurement journal for kill/resume; "
                         "empty = in-memory only")
    ap.add_argument("--screen_rounds", type=int, default=2,
                    help="short-window screen fidelity (rounds)")
    ap.add_argument("--commit_rounds", type=int, default=5,
                    help="committed-window fidelity survivors are "
                         "re-measured at")
    ap.add_argument("--survivors", type=int, default=4,
                    help="screen survivors re-measured at the committed "
                         "window")
    ap.add_argument("--virtual_devices", type=int, default=0,
                    help="provision N virtual CPU devices before the "
                         "backend initializes (client_mesh cells need "
                         ">=2; same mechanism as the trainer CLI)")
    ap.add_argument("--device_kind", type=str, default="",
                    help="override the recipe's device kind (default: "
                         "the live backend's)")
    ap.add_argument("--n_devices", type=int, default=0,
                    help="override the visible device count the space's "
                         "validity predicates use (default: the live "
                         "backend's)")
    ap.add_argument("--validate_winner", action="store_true",
                    help="after emission, run the winning cell once "
                         "through the REAL probe driver at screen "
                         "fidelity (proves the recipe is runnable, not "
                         "just well-scored)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m neuroimagedisttraining_tpu.tune",
        description=__doc__.split("\n\n")[0])
    add_args(ap)
    args = ap.parse_args(argv)

    if args.virtual_devices:
        from neuroimagedisttraining_tpu.parallel.mesh import (
            provision_virtual_devices,
        )
        provision_virtual_devices(args.virtual_devices)
    import jax
    device_kind = args.device_kind or jax.devices()[0].device_kind
    n_devices = args.n_devices or jax.device_count()

    space = tune_space.build_space(device_kind, n_devices)
    journal = tune_search.Journal(args.journal) if args.journal else None
    if args.backend == "virtual":
        measure = tune_search.virtual_measure
    else:
        measure = tune_search.make_driver_measure()

    t0 = time.time()
    try:
        res = tune_search.run_search(
            space, args.seed, measure, journal,
            screen_fidelity=args.screen_rounds,
            commit_fidelity=args.commit_rounds,
            survivors=args.survivors)
    except ValueError as e:
        ap.error(str(e))
    doc = tune_recipe.recipe_doc_from_search(res, device_kind)

    # determinism self-check (virtual backend only): the WHOLE search
    # twice more, in memory, byte-comparing the serialized recipes.
    # The driver backend measures wall clocks — determinism is not its
    # contract, so the check reads null there, not green.
    deterministic = None
    if args.backend == "virtual":
        reruns = []
        for _ in range(2):
            r = tune_search.run_search(
                space, args.seed, measure, None,
                screen_fidelity=args.screen_rounds,
                commit_fidelity=args.commit_rounds,
                survivors=args.survivors, log=lambda *a: None)
            d = tune_recipe.recipe_doc_from_search(r, device_kind)
            reruns.append(json.dumps(d, sort_keys=True))
        want = json.dumps(doc, sort_keys=True)
        deterministic = all(r == want for r in reruns)

    out = args.out or os.path.join(
        tune_recipe.recipes_dir(),
        tune_recipe.device_slug(device_kind) + ".json")
    tune_recipe.write_recipe(doc, out)
    print(f"[tune] recipe -> {out} (sha256 {doc['sha256'][:12]}…)",
          file=sys.stderr)

    validation = {"ran": False}
    if args.validate_winner:
        # prove the committed winner survives the full load path + the
        # real driver: load (sha/domain/kind checks) then one short
        # probe window through engine.train()
        loaded = tune_recipe.load_recipe(out, expected_kind=device_kind)
        driver = tune_search.make_driver_measure()
        m = driver(loaded["cell"], args.screen_rounds, args.seed)
        validation = {"ran": True, "status": m["status"],
                      "reason": m["reason"],
                      "round_ms": m["metrics"].get("round_ms")}
        print(f"[tune] winner validation: {m['status']}"
              + (f" ({m['reason']})" if m["reason"] else ""),
              file=sys.stderr)

    session = {
        "metric": "autotune_session",
        "meta": {"device_kind": device_kind, "n_devices": n_devices,
                 "seed": args.seed, "backend": args.backend,
                 "screen_rounds": args.screen_rounds,
                 "commit_rounds": args.commit_rounds,
                 "survivors": args.survivors, "jax": jax.__version__},
        "space": {"n_cells": res["n_cells"],
                  "n_rejected": len(res["rejected"]),
                  "fingerprint": res["space_fingerprint"]},
        "search": {"screened": len(res["screened"]),
                   "refined": len(res["refined"]),
                   "fresh_measurements": res["fresh_measurements"],
                   "journal_reused": res["journal_reused"]},
        "winner": {"fingerprint": doc["fingerprint"],
                   "cell": doc["cell"], "score": doc["score"],
                   "score_metric": doc["score_metric"],
                   "fidelity": doc["fidelity"]},
        "recipe": {"path": out, "sha256": doc["sha256"]},
        "session": {"deterministic": deterministic,
                    "wall_s": round(time.time() - t0, 3)},
        "winner_validation": validation,
    }
    if args.session_out:
        d = os.path.dirname(args.session_out)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(args.session_out, "w") as f:
            json.dump(session, f, indent=1, sort_keys=True)
            f.write("\n")
    print(json.dumps(session, sort_keys=True))
    ok = (deterministic is not False
          and (not validation["ran"] or validation["status"] == "ok"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
