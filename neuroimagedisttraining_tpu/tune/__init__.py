"""Closed-loop autotuner (ISSUE 19): search the declared probe space,
emit per-hardware recipes, re-tune on MFU drift.

- :mod:`tune.space` — the search space as DATA: the probe cell axes
  (obs/probe.py CELL_KEYS, ``batch`` included) with per-axis validity
  predicates reusing the compat-matrix rejection knowledge and
  device-kind-aware HBM bounds. Never proposes a cell the CLIs would
  reject at startup.
- :mod:`tune.search` — seeded successive halving: cheap short-window
  screens, survivors re-measured at the committed window; every
  measurement keyed by cell fingerprint in a JSONL journal so a killed
  run resumes without re-measuring.
- :mod:`tune.recipe` — the winner serialized as
  ``bench_matrix/recipes/<device_kind>.json`` (sha256-pinned, full
  score trace retained), loadable via ``--recipe <path|auto>`` on both
  CLIs; loading arms the ``mfu-below-recipe`` drift rule.

Entry points::

    scripts/run_autotune.sh                          # the push-button
    python -m neuroimagedisttraining_tpu.tune --backend virtual
    python -m neuroimagedisttraining_tpu ... --recipe auto
"""

from neuroimagedisttraining_tpu.tune.space import (  # noqa: F401
    Space, build_space, cell_fingerprint,
)
from neuroimagedisttraining_tpu.tune.search import (  # noqa: F401
    Journal, run_search, virtual_measure,
)
from neuroimagedisttraining_tpu.tune.recipe import (  # noqa: F401
    RECIPE_KEYS, apply_recipe, drift_rules, load_recipe,
    resolve_and_load,
)
