"""Seeded, resumable successive-halving search (ISSUE 19, tune/).

The fidelity ladder is two rungs: every valid cell is SCREENED at a
cheap short window (``screen_fidelity`` rounds through the measurement
backend), the top ``survivors`` by score are RE-MEASURED at the
committed window (``commit_fidelity``), and the best refined cell is
the winner. Scores come from the live gauges the profiler already
publishes — ``nidt_mfu`` when a device peak is known, else
``nidt_sustained_tflops``, else the inverse round wall — and a cell
that recompile-storms or trips a critical health rule is scored
FAILED (it loses the tournament) rather than crashing the search.

Determinism and resume:

- no wall-clock or RNG feeds a decision: the virtual backend derives
  its measurements from sha256(seed, cell fingerprint, fidelity), ties
  break on the fingerprint sort, and enumeration order is the space's
  declared order — same seed + space ⇒ same winner, same artifact
  bytes (pinned in tests/test_tune.py);
- every measurement is keyed by ``(fingerprint, fidelity)`` in a JSONL
  journal flushed after each fresh measurement, so a killed run
  re-executed with the same journal path completes WITHOUT
  re-measuring finished cells.

Backends: :func:`virtual_measure` is the seeded deterministic cost
model the CPU harness commits artifacts with (it prices the same
effects the probes measure: bf16 step ratio, fused-tail saving,
dispatch amortization vs recompiles, mesh scaling, batch saturation);
:func:`make_driver_measure` runs the cell through the SHIPPED
``engine.train()`` driver via ``obs/probe.py`` — the TPU-session
backend.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Callable

from neuroimagedisttraining_tpu.tune.space import Space, cell_fingerprint

__all__ = ["Journal", "run_search", "virtual_measure",
           "make_driver_measure", "score_of"]

#: a dispatch plan rebuilding this often within one short probe window
#: is thrashing — the same tripwire the recompile-storm health rule
#: uses (obs/rules.py)
RECOMPILE_STORM_DELTA = 3

MeasureFn = Callable[[dict, int, int], dict]


def score_of(metrics: dict) -> tuple[float | None, str]:
    """(score, metric name) from a measurement's metrics block: MFU
    when the peak is known, sustained TFLOP/s otherwise, inverse
    round-wall as the last resort (still higher-better)."""
    if metrics.get("mfu") is not None:
        return float(metrics["mfu"]), "mfu"
    if metrics.get("sustained_tflops") is not None:
        return float(metrics["sustained_tflops"]), "sustained_tflops"
    rms = metrics.get("round_ms")
    if rms:
        return 1000.0 / float(rms), "inv_round_ms"
    return None, "none"


class Journal:
    """Append-only JSONL measurement journal keyed by
    ``(fingerprint, fidelity)`` — the resume store. Each record is one
    completed measurement; a record is written (and flushed) only
    AFTER its measurement finishes, so a kill mid-measurement simply
    re-measures that cell on resume."""

    def __init__(self, path: str):
        self.path = path
        self._done: dict[tuple[str, int], dict] = {}
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail line of a killed run
                    key = (rec.get("fingerprint"),
                           int(rec.get("fidelity", 0)))
                    if key[0]:
                        self._done[key] = rec

    def get(self, fingerprint: str, fidelity: int) -> dict | None:
        return self._done.get((fingerprint, int(fidelity)))

    def record(self, rec: dict) -> None:
        self._done[(rec["fingerprint"], int(rec["fidelity"]))] = rec
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def __len__(self) -> int:
        return len(self._done)


def virtual_measure(cell: dict, fidelity: int, seed: int) -> dict:
    """The seeded deterministic cost model. Derives a score from the
    cell alone plus sha256-seeded noise that SHRINKS with fidelity
    (short screens are noisier than committed windows — the property
    successive halving exists to exploit). Prices the measured
    effects: bf16's step ratio, the fused SGD tail, dispatch
    amortization, near-linear client-mesh scaling, batch saturation,
    and remat's recompute tax."""
    fp = cell_fingerprint(cell)
    h = hashlib.sha256(
        f"virtual:{int(seed)}:{fp}:{int(fidelity)}".encode()).digest()
    unit = int.from_bytes(h[:8], "big") / float(1 << 64)  # [0, 1)
    score = 1.0
    if cell.get("precision") == "bf16_mixed":
        score *= 1.55
    if cell.get("fused_update"):
        score *= 1.12
    rpd = int(cell.get("rounds_per_dispatch", 1))
    score *= 1.0 + 0.06 * (rpd - 1)
    cm = int(cell.get("client_mesh", 0))
    if cm > 1:
        score *= 1.0 + 0.45 * (cm - 1)
    batch = int(cell.get("batch", 8))
    score *= batch / (batch + 6.0)
    remat = cell.get("remat", "none")
    if remat == "stem":
        score *= 0.93
    elif remat in ("all", True):
        score *= 0.85
    score *= 1.0 + (unit - 0.5) * (0.12 / max(1, int(fidelity)))
    score = round(score, 6)
    return {
        "status": "ok", "reason": "",
        "score": score, "score_metric": "sustained_tflops",
        "metrics": {"mfu": None, "sustained_tflops": score,
                    "round_ms": round(120.0 / score, 3),
                    "dispatches": int(fidelity), "compiles": 1},
    }


def make_driver_measure(meta_overrides: dict | None = None) -> MeasureFn:
    """The live backend: one closure holding the session federation so
    the search measures N cells against ONE seeded cohort. Each call
    runs the cell through ``obs_probe.run_probe`` (the shipped
    ``engine.train()`` driver) at ``fidelity`` rounds; a recompile
    storm or a critical health-rule verdict scores the cell FAILED,
    never crashes the search."""
    from neuroimagedisttraining_tpu.obs import compute as obs_compute
    from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
    from neuroimagedisttraining_tpu.obs import probe as obs_probe
    from neuroimagedisttraining_tpu.obs import rules as obs_rules
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    base_meta = dict(obs_probe._env_meta())
    base_meta.update(meta_overrides or {})
    fed = obs_probe._make_fed(base_meta)
    log = ExperimentLogger("/tmp/nidt_autotune", "synthetic",
                           "autotune", console=False)

    def measure(cell: dict, fidelity: int, seed: int) -> dict:
        meta = dict(base_meta, rounds=int(fidelity))
        probe = obs_probe.Probe(f"tune-{cell_fingerprint(cell)}",
                                dict(cell))
        before = obs_compute.PROFILER.health().get("recompiles", 0)
        try:
            res = obs_probe.run_probe(probe, meta, fed, log)
        except Exception as e:  # noqa: BLE001 — failed cell, not a
            # crashed search: the tournament continues and the journal
            # records why this cell lost
            return {"status": "failed",
                    "reason": f"error: {type(e).__name__}: {e}",
                    "score": None, "score_metric": "none",
                    "metrics": {}}
        if not res.get("ran"):
            return {"status": "failed",
                    "reason": res.get("skip_reason") or "did not run",
                    "score": None, "score_metric": "none",
                    "metrics": {}}
        recompiles = (obs_compute.PROFILER.health().get("recompiles", 0)
                      - before)
        metrics = {"mfu": res.get("mfu"),
                   "sustained_tflops": res.get("sustained_tflops"),
                   "round_ms": res.get("round_ms"),
                   "dispatches": res.get("dispatches"),
                   "compiles": res.get("compiles"),
                   "recompiles": int(recompiles)}
        if recompiles >= RECOMPILE_STORM_DELTA:
            return {"status": "failed", "reason": "recompile-storm",
                    "score": None, "score_metric": "none",
                    "metrics": metrics}
        gate = obs_rules.RuleEngine(obs_rules.builtin_rules())
        gate.observe(10 ** 9, obs_metrics.REGISTRY.snapshot())
        if gate.health_block()["status"] == "critical":
            return {"status": "failed", "reason": "health-gate-red",
                    "score": None, "score_metric": "none",
                    "metrics": metrics}
        score, metric = score_of(metrics)
        if score is None:
            return {"status": "failed", "reason": "no score sample",
                    "score": None, "score_metric": "none",
                    "metrics": metrics}
        return {"status": "ok", "reason": "", "score": score,
                "score_metric": metric, "metrics": metrics}

    return measure


def _measure_keyed(cell: dict, fidelity: int, seed: int,
                   measure: MeasureFn, journal: Journal | None,
                   counters: dict) -> dict:
    fp = cell_fingerprint(cell)
    if journal is not None:
        prior = journal.get(fp, fidelity)
        if prior is not None:
            counters["reused"] += 1
            return prior
    m = measure(cell, int(fidelity), int(seed))
    rec = {"fingerprint": fp, "cell": dict(cell),
           "fidelity": int(fidelity), **m}
    counters["fresh"] += 1
    if journal is not None:
        journal.record(rec)
    return rec


def run_search(space: Space, seed: int, measure: MeasureFn,
               journal: Journal | None = None, *,
               screen_fidelity: int = 2, commit_fidelity: int = 5,
               survivors: int = 4, log=print) -> dict[str, Any]:
    """Screen every valid cell at ``screen_fidelity``, re-measure the
    top ``survivors`` at ``commit_fidelity``, return the full result
    document (winner + both rungs' traces + the rejected cells). A
    journal makes the whole thing resumable; without one the search is
    purely in-memory (the determinism self-check's mode)."""
    if screen_fidelity < 1 or commit_fidelity < screen_fidelity:
        raise ValueError(
            f"fidelity ladder must satisfy 1 <= screen <= commit (got "
            f"screen={screen_fidelity}, commit={commit_fidelity})")
    if survivors < 1:
        raise ValueError(f"survivors must be >= 1 (got {survivors})")
    cells, rejected = space.cells()
    if not cells:
        raise ValueError(
            "the space has no valid cells (every combination was "
            "rejected by the validity predicates)")
    counters = {"fresh": 0, "reused": 0}
    screened = [_measure_keyed(c, screen_fidelity, seed, measure,
                               journal, counters) for c in cells]
    ok = [m for m in screened if m["status"] == "ok"]
    if not ok:
        raise ValueError(
            "every screened cell failed — no survivor to refine "
            "(see the journal/session trace for per-cell reasons)")
    ok.sort(key=lambda m: (-m["score"], m["fingerprint"]))
    finalists = ok[:max(1, min(survivors, len(ok)))]
    log(f"[tune] screened {len(screened)} cells "
        f"({len(screened) - len(ok)} failed, "
        f"{counters['reused']} from journal); refining "
        f"{len(finalists)} at {commit_fidelity} rounds")
    refined = [_measure_keyed(m["cell"], commit_fidelity, seed, measure,
                              journal, counters) for m in finalists]
    ok_refined = [m for m in refined if m["status"] == "ok"]
    if not ok_refined:
        raise ValueError("every refined survivor failed at the "
                         "committed window")
    ok_refined.sort(key=lambda m: (-m["score"], m["fingerprint"]))
    winner = ok_refined[0]
    log(f"[tune] winner {winner['fingerprint']} "
        f"score={winner['score']} ({winner['score_metric']}): "
        f"{winner['cell']}")
    return {
        "winner": winner,
        "screened": screened,
        "refined": refined,
        "rejected": rejected,
        "n_cells": len(cells),
        "screen_fidelity": int(screen_fidelity),
        "commit_fidelity": int(commit_fidelity),
        "survivors": int(survivors),
        "seed": int(seed),
        "fresh_measurements": counters["fresh"],
        "journal_reused": counters["reused"],
        "space_fingerprint": space.fingerprint(),
    }
