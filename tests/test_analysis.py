"""nidtlint (neuroimagedisttraining_tpu.analysis) — rule unit tests on
positive/negative fixtures, pragma mechanics, CLI exit codes, and the
tier-1 gate: the shipped tree must lint clean forever."""

import json
import os
import subprocess
import sys
import textwrap

from neuroimagedisttraining_tpu.analysis import lint_paths, lint_source
from neuroimagedisttraining_tpu.analysis.cli import main as cli_main
from neuroimagedisttraining_tpu.analysis.core import parse_pragmas

PACKAGE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "neuroimagedisttraining_tpu")


def lint(src, path="pkg/mod.py", rules=None):
    return lint_source(textwrap.dedent(src), path=path, rules=rules)


def rules_of(findings):
    return [f.rule for f in findings]


# ---------------- trace-safety ----------------

def test_trace_flags_host_sync_in_jit_decorated():
    fs = lint("""
        import jax

        @jax.jit
        def f(x):
            return float(x) + x.item()
        """)
    assert rules_of(fs) == ["trace-host-sync", "trace-host-sync"]


def test_trace_flags_partial_jit_decorator():
    fs = lint("""
        import functools
        import jax
        import numpy as np

        @functools.partial(jax.jit, static_argnums=0)
        def f(n, x):
            return np.asarray(x)
        """)
    assert rules_of(fs) == ["trace-host-sync"]


def test_trace_resolves_local_def_passed_to_jit():
    fs = lint("""
        import jax
        import numpy as np

        def build():
            def step_fn(x):
                return np.asarray(x)
            return jax.jit(step_fn)
        """)
    assert rules_of(fs) == ["trace-host-sync"]


def test_trace_resolves_vmap_lambda_np_random():
    fs = lint("""
        import jax
        import numpy as np

        def f(xs):
            return jax.vmap(lambda i: i * np.random.rand())(xs)
        """)
    # the same call is both a trace hazard and a global-stream draw
    assert rules_of(fs) == ["determinism-global-random", "trace-np-random"]


def test_trace_resolves_self_method_and_partial_wrapper():
    fs = lint("""
        import functools
        import jax

        class Engine:
            def _step_body(self, x):
                return jax.device_get(x)

            def _consensus(self, x, plan=None):
                return x.item()

            def _step_jit(self):
                return jax.jit(self._step_body)

            def _consensus_jit(self, plan):
                return jax.jit(functools.partial(self._consensus, plan=plan),
                               donate_argnums=(0,))
        """)
    assert rules_of(fs) == ["trace-host-sync", "trace-host-sync"]


def test_trace_flags_nested_helper_inside_traced_fn():
    fs = lint("""
        import jax

        def build():
            def step_fn(xs):
                def per_client(x):
                    return x.tolist()
                return jax.vmap(per_client)(xs)
            return jax.jit(step_fn)
        """)
    # per_client is flagged once even though it is doubly traced
    # (lexically inside step_fn AND passed to vmap)
    assert rules_of(fs) == ["trace-host-sync"]


def test_trace_resolves_grad_and_lax_combinators():
    fs = lint("""
        import jax
        from jax import lax

        def step(params, xs):
            def loss_fn(p):
                return float(p)

            def body(carry, x):
                return carry.item(), x

            g = jax.value_and_grad(loss_fn)(params)
            out, _ = lax.scan(body, g, xs)
            return out
        """)
    assert rules_of(fs) == ["trace-host-sync", "trace-host-sync"]


def test_trace_resolves_cond_branches_only():
    fs = lint("""
        from jax import lax

        def pick(pred, x):
            def stay(v):
                return v

            def sync(v):
                return v.tolist()

            return lax.cond(pred, stay, sync, x)
        """)
    assert rules_of(fs) == ["trace-host-sync"]


def test_trace_resolves_modern_jax_shard_map_spelling():
    fs = lint("""
        import jax

        def build(mesh, specs, tree):
            def block_fn(blk):
                return blk.item()

            return jax.shard_map(block_fn, mesh=mesh, in_specs=(specs,),
                                 out_specs=specs)(tree)
        """)
    assert rules_of(fs) == ["trace-host-sync"]


def test_trace_ignores_host_code_and_jnp():
    fs = lint("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        def step_jit():
            def step_fn(x):
                return jnp.asarray(x) + 1  # jnp is trace-safe
            return jax.jit(step_fn)

        def host_driver(fn, x):
            out = fn(x)                    # calling a jitted fn is fine
            return float(np.asarray(jax.device_get(out)).mean())
        """)
    assert fs == []


# ---------------- engine-contract ----------------

def test_engine_missing_attrs_and_round_method():
    fs = lint("""
        from neuroimagedisttraining_tpu.engines.base import FederatedEngine

        class BadEngine(FederatedEngine):
            pass
        """, path="pkg/engines/bad.py")
    assert sorted(rules_of(fs)) == ["engine-attrs", "engine-attrs",
                                    "engine-round"]


def test_engine_signature_mismatch_against_base():
    fs = lint("""
        from neuroimagedisttraining_tpu.engines.base import FederatedEngine

        class SigEngine(FederatedEngine):
            name = "sig"
            supports_streaming = False

            def train(self, extra):
                return {}

            def client_sampling(self, idx):  # base: (self, round_idx)
                return idx
        """, path="pkg/engines/sig.py")
    assert sorted(rules_of(fs)) == ["engine-signature", "engine-signature"]


def test_engine_inherited_streaming_flag_but_own_name_required():
    fs = lint("""
        from neuroimagedisttraining_tpu.engines.base import FederatedEngine

        class MidEngine(FederatedEngine):
            name = "mid"
            supports_streaming = True

            def train(self):
                return {}

        class LeafEngine(MidEngine):
            pass  # inherits train/supports_streaming, but name collides
        """, path="pkg/engines/leaf.py")
    assert rules_of(fs) == ["engine-attrs"]
    assert "name" in fs[0].message


def test_engine_compliant_subclass_is_clean():
    fs = lint("""
        from neuroimagedisttraining_tpu.engines.base import FederatedEngine

        class GoodEngine(FederatedEngine):
            name = "good"
            supports_streaming = False

            def train(self):
                return {}

            def eval_global(self, params, bstats, split="test"):
                return {}
        """, path="pkg/engines/good.py")
    assert fs == []


def test_non_engine_classes_ignored():
    fs = lint("""
        class Helper:
            pass

        class Codec(dict):
            pass
        """, path="pkg/engines/util.py")
    assert fs == []


# ---------------- lock-discipline ----------------

def test_lock_flags_unlocked_send_only_under_distributed():
    src = """
        def relay(conn, payload):
            conn.sendall(payload)
        """
    assert rules_of(lint(src, path="pkg/distributed/t.py")) == ["lock-send"]
    # faults/ writes raw frames too (FaultyCommManager's torn-frame
    # sends) — same interleaving hazard, same rule scope
    assert rules_of(lint(src, path="pkg/faults/t.py")) == ["lock-send"]
    assert lint(src, path="pkg/engines/t.py") == []


def test_lock_and_async_rules_cover_ingest_module():
    """ISSUE 12: the sharded ingest plane rides the SAME discipline
    families — an unlocked worker-pipe send and a blocking call inside
    an asyncfl coroutine both fire against asyncfl/ingest.py paths (the
    kill-one-worker plane multiplies the threads sharing each pipe)."""
    ingest = "neuroimagedisttraining_tpu/asyncfl/ingest.py"
    fs = lint("""
        class Worker:
            def reply(self, conn, verdict):
                conn.send(("v", verdict))
        """, path=ingest)
    # the unlocked send fires lock-send; since ISSUE 13 the per-upload
    # ("v", ...) spelling ALSO fires the batching rule — both real
    assert rules_of(fs) == ["lock-send", "obs-pipe-per-upload"]
    fs = lint("""
        import time

        async def watch_worker(pipe):
            time.sleep(0.5)
        """, path=ingest, rules=["async-blocking-call"])
    assert rules_of(fs) == ["async-blocking-call"]


def test_lock_flags_unlocked_shared_map_mutations():
    fs = lint("""
        class Broker:
            def register(self, topic, conn, payload):
                self._subs.setdefault(topic, []).append(conn)
                self._retained[topic] = payload
        """, path="pkg/distributed/broker2.py")
    assert rules_of(fs) == ["lock-shared-map", "lock-shared-map"]


def test_lock_satisfied_inside_with_lock():
    fs = lint("""
        class Broker:
            def register(self, topic, conn, payload):
                with self._lock:
                    self._subs.setdefault(topic, []).append(conn)
                    self._retained[topic] = payload
                with self._wlocks[conn]:
                    conn.sendall(payload)
        """, path="pkg/distributed/broker2.py")
    assert fs == []


def test_lock_with_header_mutation_is_flagged():
    """The `with` header runs BEFORE the lock is acquired — a shared-map
    mutation there must still be flagged."""
    fs = lint("""
        import threading

        class Broker:
            def serve(self, conn, payload):
                with self._wlocks.setdefault(conn, threading.Lock()):
                    conn.sendall(payload)
        """, path="pkg/distributed/t.py")
    assert rules_of(fs) == ["lock-shared-map"]


def test_lock_nested_def_does_not_inherit_lock():
    fs = lint("""
        def serve(self, conn):
            with self._lock:
                def later():
                    conn.sendall(b"x")  # runs after the with exits
                return later
        """, path="pkg/distributed/t.py")
    assert rules_of(fs) == ["lock-send"]


# ---------------- determinism ----------------

def test_determinism_flags_global_stream_and_unseeded_rng():
    fs = lint("""
        import numpy as np

        def sample(n):
            np.random.seed(0)
            idx = np.random.choice(n, 2)
            g = np.random.default_rng()
            r = np.random.RandomState()
            return idx, g, r
        """)
    assert rules_of(fs) == ["determinism-global-random",
                            "determinism-global-random",
                            "determinism-unseeded-rng",
                            "determinism-unseeded-rng"]


def test_determinism_allows_seeded_generators():
    fs = lint("""
        import numpy as np

        def sample(seed, n):
            rs = np.random.RandomState(seed)
            rng = np.random.default_rng(seed + 1)
            return rs.permutation(n), rng.integers(0, n)
        """)
    assert fs == []


# ---------------- pragmas ----------------

def test_pragma_suppresses_with_justification():
    fs = lint("""
        import numpy as np

        np.random.seed(0)  # nidt: allow[determinism-global-random] -- reference-parity shim (fedavg_api.py:92-100)
        """)
    assert fs == []


def test_bare_pragma_is_itself_a_finding():
    fs = lint("""
        import numpy as np

        np.random.seed(0)  # nidt: allow[determinism-global-random]
        """)
    assert rules_of(fs) == ["pragma"]
    assert "justification" in fs[0].message


def test_pragma_unknown_rule_id_is_flagged():
    fs = lint("""
        x = 1  # nidt: allow[no-such-rule] -- why not
        """)
    assert rules_of(fs) == ["pragma"]
    assert "no-such-rule" in fs[0].message


def test_pragma_on_multiline_statement_end_line():
    fs = lint("""
        import numpy as np

        idx = np.sort(np.random.choice(range(10), 2,  # nidt: allow[determinism-global-random] -- parity shim
                                       replace=False))
        """)
    assert fs == []


def test_pragma_on_multiline_statement_first_line():
    fs = lint("""
        import numpy as np

        idx = np.sort(  # nidt: allow[determinism-global-random] -- parity shim
            np.random.choice(range(10), 2, replace=False))
        """)
    assert fs == []


def test_pragma_inside_class_body_cannot_excuse_class_finding():
    """A pragma buried in a method must not suppress a class-header
    finding — only a pragma on the flagged `class` line itself counts."""
    src = """
        from neuroimagedisttraining_tpu.engines.base import FederatedEngine

        class BadEngine(FederatedEngine):{pragma}
            supports_streaming = False

            def train(self):
                x = 1  # nidt: allow[engine-attrs] -- buried, must not count
                return x
        """
    buried = lint(src.format(pragma=""), path="pkg/engines/bad.py")
    assert rules_of(buried) == ["engine-attrs"]
    on_header = lint(src.format(
        pragma="  # nidt: allow[engine-attrs] -- fixture engine"),
        path="pkg/engines/bad.py")
    assert on_header == []


def test_parse_error_is_a_finding():
    fs = lint("def broken(:\n")
    assert rules_of(fs) == ["parse-error"]


# ---------------- shm-discipline (ISSUE 18) ----------------

def test_shm_owner_must_close_and_unlink():
    """A creator class missing EITHER teardown call is flagged at the
    creation site — one finding per missing call."""
    src = """
        from multiprocessing import shared_memory

        class LeakyWriter:
            def __init__(self, size):
                self.shm = shared_memory.SharedMemory(create=True,
                                                      size=size)

            def destroy(self):
                self.shm.close()  # close but never unlink: name leaks
        """
    assert rules_of(lint(src)) == ["shm-owner-teardown"]
    src_neither = """
        from multiprocessing import shared_memory

        class VeryLeakyWriter:
            def __init__(self, size):
                self.shm = shared_memory.SharedMemory(create=True,
                                                      size=size)
        """
    assert rules_of(lint(src_neither)) == ["shm-owner-teardown"] * 2


def test_shm_attacher_must_never_unlink():
    src = """
        from multiprocessing import shared_memory

        class GreedyReader:
            def __init__(self, name):
                self.shm = shared_memory.SharedMemory(name=name)

            def close(self):
                self.shm.close()
                self.shm.unlink()  # destroying a name it does not own
        """
    assert rules_of(lint(src)) == ["shm-attach-unlink"]


def test_shm_discipline_clean_lifecycles_and_aliases():
    """The correct asymmetric lifecycle is clean on both sides, and the
    rule resolves the import alias + positional create=True spelling."""
    src = """
        import multiprocessing.shared_memory as sm

        class Writer:
            def __init__(self, size):
                self.shm = sm.SharedMemory(None, True, size)

            def destroy(self):
                self.shm.close()
                self.shm.unlink()

        class Reader:
            def __init__(self, name):
                self.shm = sm.SharedMemory(name=name)

            def close(self):
                self.shm.close()
        """
    assert lint(src) == []


# ---------------- CLI + tier-1 gate ----------------

def test_cli_exits_nonzero_on_seeded_violations(tmp_path, capsys):
    bad = tmp_path / "distributed" / "t.py"
    bad.parent.mkdir()
    bad.write_text("def f(conn):\n    conn.sendall(b'x')\n")
    rc = cli_main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "lock-send" in out and str(bad) in out


def test_cli_json_mode(tmp_path, capsys):
    bad = tmp_path / "t.py"
    bad.write_text("import numpy as np\nnp.random.seed(1)\n")
    rc = cli_main(["--json", str(bad)])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert report and set(report[0]) == {"path", "line", "rule", "message"}
    assert report[0]["rule"] == "determinism-global-random"
    assert report[0]["line"] == 2


def test_cli_rule_selection_and_usage_errors(tmp_path, capsys):
    bad = tmp_path / "t.py"
    bad.write_text("import numpy as np\nnp.random.seed(1)\n")
    assert cli_main(["--rules", "lock-send", str(bad)]) == 0
    assert cli_main(["--rules", "bogus", str(bad)]) == 2
    assert cli_main([]) == 2
    capsys.readouterr()


def test_rule_selection_is_id_granular(tmp_path):
    """Selecting one id of a multi-id family must not surface the family's
    other ids: seed(1) is global-random, clean for unseeded-rng."""
    from neuroimagedisttraining_tpu.analysis import lint_source

    src = "import numpy as np\nnp.random.seed(1)\n"
    assert lint_source(src, rules=["determinism-unseeded-rng"]) == []
    assert [f.rule for f in lint_source(
        src, rules=["determinism-global-random"])] == [
        "determinism-global-random"]


def test_shipped_tree_is_clean():
    """THE tier-1 gate: every invariant holds (or carries a justified
    pragma) across the whole package, forever."""
    findings = lint_paths([PACKAGE_DIR])
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


def test_shipped_tree_clean_via_cli_subprocess():
    """Acceptance criterion verbatim: the module CLI exits 0 on the tree."""
    proc = subprocess.run(
        [sys.executable, "-m", "neuroimagedisttraining_tpu.analysis",
         PACKAGE_DIR],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_every_shipped_pragma_carries_a_justification():
    """Acceptance criterion: every `# nidt: allow[...]` in the tree has a
    one-line reason (also enforced at lint time by the pragma rule)."""
    from neuroimagedisttraining_tpu.analysis.core import iter_py_files

    seen = 0
    for fp in iter_py_files([PACKAGE_DIR]):
        with open(fp, encoding="utf-8") as fh:
            for pragma in parse_pragmas(fh.read()).values():
                seen += 1
                assert pragma.justification, (fp, pragma.line)
                assert pragma.rule_ids, (fp, pragma.line)
    assert seen >= 10  # the reference-parity shims are annotated


# ---------------- donation discipline (ISSUE 4) ----------------

def test_donation_missing_flags_undeclared_round_jit():
    fs = lint("""
        import jax

        class E:
            @property
            def _round_jit(self):
                def round_fn(params, bstats):
                    return params
                return jax.jit(round_fn)
        """, rules=["donation-missing"])
    assert rules_of(fs) == ["donation-missing"]


def test_donation_missing_accepts_gating_call_and_pragma():
    fs = lint("""
        import jax

        class E:
            @property
            def _round_jit(self):
                def round_fn(params, bstats):
                    return params
                return jax.jit(round_fn,
                               donate_argnums=self._donate_argnums(0, 1))

            @property
            def _consensus_jit(self):
                def consensus_fn(per):
                    return per
                return jax.jit(consensus_fn)  # nidt: allow[donation-missing] -- outputs alias no input shape
        """, rules=["donation-missing"])
    assert fs == []


def test_donation_missing_ignores_non_round_jits():
    fs = lint("""
        import jax

        def make():
            def eval_all(params, X):
                return params
            return jax.jit(eval_all)
        """, rules=["donation-missing"])
    assert fs == []


def test_donation_use_after_donate_flags_read():
    fs = lint("""
        import jax

        class E:
            @property
            def _round_jit(self):
                def round_fn(params, bstats):
                    return params, bstats
                return jax.jit(round_fn, donate_argnums=(0, 1))

            def train(self, params, bstats):
                out, new_b = self._round_jit(params, bstats)
                leak = params
                return out, leak
        """, rules=["donation-use-after-donate"])
    assert rules_of(fs) == ["donation-use-after-donate"]


def test_donation_use_after_donate_same_statement_rebind_is_clean():
    fs = lint("""
        import jax

        class E:
            @property
            def _round_jit(self):
                def round_fn(params, bstats, rngs):
                    return params, bstats, 0.0
                return jax.jit(round_fn,
                               donate_argnums=self._donate_argnums(0, 1))

            def train(self, params, bstats, rngs):
                for r in range(3):
                    params, bstats, loss = self._round_jit(params, bstats,
                                                           rngs)
                return params, bstats, rngs  # rngs was never donated
        """, rules=["donation-use-after-donate"])
    assert fs == []


def test_donation_use_after_donate_resolves_jit_factories():
    fs = lint("""
        import jax

        class E:
            def _round_jit_for(self, plan):
                def round_fn(per, b, M):
                    return per, b
                return jax.jit(round_fn, donate_argnums=(0, 1))

            def train(self, per, b, plan, M):
                out = self._round_jit_for(plan)(per, b, M)
                stale = per
                return out, stale
        """, rules=["donation-use-after-donate"])
    assert rules_of(fs) == ["donation-use-after-donate"]
    # ...and the factory's own argument (plan) is NOT treated as donated
    assert "'per'" in fs[0].message


def test_donation_use_after_donate_rebind_then_read_is_clean():
    fs = lint("""
        import jax

        class E:
            @property
            def _round_jit(self):
                def round_fn(params):
                    return params
                return jax.jit(round_fn, donate_argnums=(0,))

            def train(self, params):
                out = self._round_jit(params)
                params = out
                return params
        """, rules=["donation-use-after-donate"])
    assert fs == []


# ---------------- Byzantine layer coverage (ISSUE 5) ----------------

def test_byzantine_layer_modules_lint_clean_standalone():
    """faults/adversary.py and core/robust.py are inside the lexical net
    and clean on their own (not just as part of the whole-tree gate):
    the jitted attack transforms and order-statistic aggregators carry
    no host syncs, no global RNG, no unseeded streams."""
    for rel in ("faults/adversary.py", "core/robust.py"):
        fs = lint_paths([os.path.join(PACKAGE_DIR, rel)])
        assert fs == [], rel + "\n" + "\n".join(f.render() for f in fs)


def test_trace_safety_catches_adversary_shaped_violation():
    """The exact idiom faults/adversary.py uses — a per-client transform
    CALLED from a vmapped lambda — is covered by the transitive-call
    closure: host numpy RNG inside it is a trace finding (the attack
    must draw from jax.random so one seed replays in both
    federations). Before ISSUE 5 the resolver stopped at the call
    boundary and this idiom escaped the net entirely."""
    fs = lint("""
        import jax
        import numpy as np

        def apply_attack(u, ref, mult):
            noise = np.random.normal(size=u.shape)
            return ref + (u - ref) * mult + noise

        def apply_attack_stacked(us, ref, mults):
            return jax.vmap(
                lambda u, m: apply_attack(u, ref, m))(us, mults)
        """)
    # the same draw is both a global-stream read and a trace hazard
    assert rules_of(fs) == ["determinism-global-random", "trace-np-random"]


def test_trace_safety_catches_host_sync_in_weiszfeld_body():
    """An eager .item() escape inside a lax.fori_loop body (the
    geometric_median Weiszfeld shape) is a trace-safety finding."""
    fs = lint("""
        import jax

        def geometric_median(stacked, iters):
            def step(_, z):
                return z * float(jax.numpy.sum(z).item())
            return jax.lax.fori_loop(0, iters, step, stacked)
        """)
    assert "trace-host-sync" in rules_of(fs)


def test_determinism_rule_covers_schedule_shaped_rng():
    """The byz_prob transient stream must ride the seeded FaultSchedule
    draw: an unseeded default_rng in a schedule-shaped module is a
    determinism finding."""
    fs = lint("""
        import numpy as np

        def byzantine_kind(round_idx, rank, p):
            return np.random.default_rng().random() < p
        """, rules=["determinism-unseeded-rng"])
    assert rules_of(fs) == ["determinism-unseeded-rng"]


# ---------------- mesh discipline (ISSUE 6) ----------------

def test_shardmap_missing_specs_flagged():
    fs = lint("""
        from jax.experimental.shard_map import shard_map

        def f(block, mesh, x):
            return shard_map(block, mesh=mesh)(x)
        """, rules=["mesh-shardmap-specs"])
    assert rules_of(fs) == ["mesh-shardmap-specs"]
    assert "in_specs and out_specs" in fs[0].message


def test_shardmap_partial_specs_flagged_and_full_specs_pass():
    fs = lint("""
        from jax import shard_map

        def f(block, mesh, x, spec):
            return shard_map(block, mesh=mesh, in_specs=(spec,))(x)
        """, rules=["mesh-shardmap-specs"])
    assert rules_of(fs) == ["mesh-shardmap-specs"]
    assert "out_specs" in fs[0].message
    assert lint("""
        from jax.experimental.shard_map import shard_map

        def f(block, mesh, x, spec):
            return shard_map(block, mesh=mesh, in_specs=(spec,),
                             out_specs=spec)(x)
        """, rules=["mesh-shardmap-specs"]) == []


def test_pad_weights_adhoc_mask_flagged():
    fs = lint("""
        import jax.numpy as jnp

        def weights(ns, n_real):
            return jnp.where(jnp.arange(ns.shape[0]) < n_real, ns, 0)
        """, path="neuroimagedisttraining_tpu/engines/base.py",
        rules=["mesh-pad-weights"])
    assert rules_of(fs) == ["mesh-pad-weights"]
    assert "pad_row_weights" in fs[0].message


def test_pad_weights_helper_home_and_other_compares_pass():
    # the helper's own home is exempt
    assert lint("""
        import jax.numpy as jnp

        def pad_row_weights(ns, n_real):
            return jnp.where(jnp.arange(ns.shape[0]) < n_real, ns, 0)
        """, path="neuroimagedisttraining_tpu/parallel/cohort.py",
        rules=["mesh-pad-weights"]) == []
    # sample-validity masks (arange vs a per-client count) are not the
    # pad-row idiom and stay legal
    assert lint("""
        import jax.numpy as jnp

        def valid(X, nc):
            return jnp.arange(X.shape[0]) < nc
        """, rules=["mesh-pad-weights"]) == []


# ---------------- async discipline (ISSUE 7) ----------------

_ASYNCFL_PATH = "neuroimagedisttraining_tpu/asyncfl/loadgen.py"


def test_async_blocking_calls_flagged_in_asyncfl():
    fs = lint("""
        import time
        import select

        async def drive(sock):
            time.sleep(0.1)
            select.select([sock], [], [])
            sock.recv(4)
            sock.accept()
        """, path=_ASYNCFL_PATH, rules=["async-blocking-call"])
    assert rules_of(fs) == ["async-blocking-call"] * 4
    assert "freezes every coroutine" in fs[0].message


def test_async_awaited_and_nested_sync_bodies_pass():
    # awaited calls are the sanctioned non-blocking spellings; nested
    # SYNC defs/lambdas are executor-shipped bodies and may block
    assert lint("""
        import asyncio
        import time

        async def drive(loop, sock):
            await asyncio.sleep(0.1)
            data = await loop.sock_recv(sock, 4)

            def off_loop():
                time.sleep(1)
                return sock.recv(4)
            return await loop.run_in_executor(None, off_loop)
        """, path=_ASYNCFL_PATH, rules=["async-blocking-call"]) == []


def test_async_rules_scoped_to_asyncfl_and_sync_defs_exempt():
    src = """
        import time

        def sync_helper():
            time.sleep(1)

        async def drive(sock):
            time.sleep(1)
        """
    # outside asyncfl/ the family never fires
    assert lint(src, path="neuroimagedisttraining_tpu/distributed/x.py",
                rules=["async-blocking-call"]) == []
    # inside, only the async body is flagged — module-level sync code
    # (the selector loop itself) blocks legitimately
    fs = lint(src, path=_ASYNCFL_PATH, rules=["async-blocking-call"])
    assert len(fs) == 1 and fs[0].line == 8


def test_async_nested_coroutine_violation_reported_once():
    fs = lint("""
        import time

        async def outer():
            async def inner():
                time.sleep(1)
            return inner
        """, path=_ASYNCFL_PATH, rules=["async-blocking-call"])
    assert rules_of(fs) == ["async-blocking-call"]
    assert "inner" in fs[0].message


def test_async_queue_get_flagged_dict_get_passes():
    fs = lint("""
        async def drain(q, d):
            item = q.get()
            known = d.get("key")
            timed = q.get(timeout=0.1)
            nonblock = q.get(block=False)
        """, path=_ASYNCFL_PATH, rules=["async-queue-get"])
    assert rules_of(fs) == ["async-queue-get"]
    assert fs[0].line == 3


# ---------------- obs-discipline (ISSUE 9) ----------------

def test_obs_clock_in_jitted_body_flagged():
    fs = lint("""
        import time
        import jax

        @jax.jit
        def f(x):
            t0 = time.perf_counter()
            return x + time.monotonic() - t0
        """, rules=["obs-clock-in-trace"])
    assert rules_of(fs) == ["obs-clock-in-trace", "obs-clock-in-trace"]
    assert "trace-time clock value" in fs[0].message


def test_obs_clock_aliased_import_and_vmap_lambda():
    fs = lint("""
        from time import perf_counter
        import jax

        def g(xs):
            return jax.vmap(lambda x: x * perf_counter())(xs)
        """, rules=["obs-clock-in-trace"])
    assert rules_of(fs) == ["obs-clock-in-trace"]


def test_obs_clock_at_host_boundary_passes():
    fs = lint("""
        import time
        import jax

        @jax.jit
        def f(x):
            return x * 2

        def driver(x):
            t0 = time.perf_counter()
            y = f(x)
            return y, time.perf_counter() - t0
        """, rules=["obs-clock-in-trace"])
    assert fs == []


def test_obs_metrics_mutation_in_trace_flagged():
    fs = lint("""
        import jax
        from neuroimagedisttraining_tpu.obs import metrics as obs_metrics

        COUNTER = obs_metrics.counter("x_total")

        @jax.jit
        def f(x):
            COUNTER.inc()
            obs_metrics.gauge("g").set(1)
            return x
        """, rules=["obs-metrics-in-trace"])
    # .inc() via the method heuristic, the gauge() call via the obs
    # package prefix
    assert rules_of(fs) == ["obs-metrics-in-trace", "obs-metrics-in-trace"]


def test_obs_metrics_transitive_callee_flagged():
    """The trace-safety resolver's transitive closure: a helper CALLED
    from a traced body is traced too, so its histogram observe is
    caught."""
    fs = lint("""
        import jax

        def note(h, v):
            h.observe(v)

        def f(h, xs):
            return jax.vmap(lambda x: note(h, x) or x)(xs)
        """, rules=["obs-metrics-in-trace"])
    assert rules_of(fs) == ["obs-metrics-in-trace"]


def test_obs_indexed_set_and_host_mutation_pass():
    fs = lint("""
        import jax
        from neuroimagedisttraining_tpu.obs import metrics as obs_metrics

        @jax.jit
        def f(x, i):
            return x.at[i].set(0.0)  # jnp indexed update, not a gauge

        def host_boundary(c):
            c.inc()
            obs_metrics.gauge("g").set(2)
        """, rules=["obs-metrics-in-trace"])
    assert fs == []


# -- obs-sync-in-trace (ISSUE 14: the dispatch profiler's zero-sync rule)


def test_obs_sync_in_jitted_body_flagged():
    """block_until_ready inside a traced body — both the jax dotted
    call and the zero-arg array method — is the hidden-sync class the
    dispatch profiler's wiring must never introduce."""
    fs = lint("""
        import jax

        @jax.jit
        def f(x):
            jax.block_until_ready(x)
            return x.block_until_ready() + 1
        """, rules=["obs-sync-in-trace"])
    assert rules_of(fs) == ["obs-sync-in-trace", "obs-sync-in-trace"]
    assert "zero-sync" in fs[0].message


def test_obs_sync_transitive_callee_flagged():
    fs = lint("""
        import jax

        def wait(x):
            return jax.block_until_ready(x)

        def f(xs):
            return jax.vmap(lambda x: wait(x) + 1)(xs)
        """, rules=["obs-sync-in-trace"])
    assert rules_of(fs) == ["obs-sync-in-trace"]


def test_obs_sync_at_host_boundary_passes():
    """The blessed pattern: time around the ENQUEUE on the host, sync
    only at host boundaries (what obs/compute.note_dispatch and the
    bench cells do)."""
    fs = lint("""
        import time
        import jax

        @jax.jit
        def f(x):
            return x * 2

        def driver(x):
            t0 = time.perf_counter()
            y = f(x)
            jax.block_until_ready(y)
            return y, time.perf_counter() - t0
        """, rules=["obs-sync-in-trace"])
    assert fs == []


# ---------------- obs fan-in discipline (ISSUE 13) ----------------

_INGEST_PATH = "neuroimagedisttraining_tpu/asyncfl/ingest.py"
_MESSAGE_PATH = "neuroimagedisttraining_tpu/distributed/message.py"


def test_trace_ctx_literal_in_add_get_flagged():
    fs = lint("""
        def stamp(msg, ctx):
            msg.add("trace_ctx", ctx)

        def read(msg):
            return msg.get("trace_ctx")
        """, rules=["obs-trace-ctx-key"])
    assert rules_of(fs) == ["obs-trace-ctx-key", "obs-trace-ctx-key"]
    assert "ARG_TRACE_CTX" in fs[0].message


def test_trace_ctx_constant_spelling_and_definition_site_pass():
    # spelled through the constant: clean
    fs = lint("""
        from neuroimagedisttraining_tpu.distributed import message as M

        def stamp(msg, ctx):
            msg.add(M.ARG_TRACE_CTX, ctx)
            other = msg.get("round_idx")
        """, rules=["obs-trace-ctx-key"])
    assert fs == []
    # the definition site itself may spell the literal
    fs = lint("""
        ARG_TRACE_CTX = "trace_ctx"

        def demo(msg):
            return msg.get("trace_ctx")
        """, path=_MESSAGE_PATH, rules=["obs-trace-ctx-key"])
    assert fs == []


def test_unbatched_pipe_send_in_ingest_flagged():
    fs = lint("""
        class W:
            def receive_message(self, msg):
                self.conn.send(("beat", self.wid, msg.sender_id))

            def per_upload(self, verdict):
                self.conn.send(("v", self.wid, verdict))
        """, path=_INGEST_PATH, rules=["obs-pipe-per-upload"])
    assert rules_of(fs) == ["obs-pipe-per-upload",
                            "obs-pipe-per-upload"]
    assert "batch" in fs[0].message


def test_batched_pipe_sends_and_other_modules_pass():
    src = """
        class W:
            def _flush_locked(self):
                self.conn.send(("beats", self.wid, sorted(self.pend)))
                self.conn.send(("vb", self.wid, self.counts, self.taus))
                self.conn.send(("obs", self.wid, payload))
                self.conn.send(("reg", self.wid, c))
        """
    assert lint(src, path=_INGEST_PATH,
                rules=["obs-pipe-per-upload"]) == []
    # the rule is scoped to asyncfl/ingest.py — a ("beat", ...) tuple
    # elsewhere (e.g. a test fixture) is not its business
    assert lint("""
        def elsewhere(conn):
            conn.send(("beat", 0, 1))
        """, rules=["obs-pipe-per-upload"]) == []


# ---------------- precision-discipline (ISSUE 10) ----------------

def test_precision_upcast_astype_in_traced_body_flagged():
    fs = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return jnp.sum(x.astype(jnp.float32))
        """, path="pkg/core/mod.py", rules=["precision-upcast"])
    assert rules_of(fs) == ["precision-upcast"]
    assert "re-widens" in fs[0].message


def test_precision_upcast_asarray_and_constructor_flagged():
    fs = lint("""
        import jax
        import jax.numpy as jnp

        def f(xs):
            return jax.vmap(lambda x: jnp.asarray(x, jnp.float32)
                            + jnp.float32(2.0))(xs)
        """, path="pkg/ops/mod.py", rules=["precision-upcast"])
    assert sorted(rules_of(fs)) == ["precision-upcast", "precision-upcast"]


def test_precision_upcast_transitive_callee_flagged():
    """The rule rides the trace-safety resolver: an upcast in a helper
    CALLED from a traced body is caught like a decorated one."""
    fs = lint("""
        import jax
        import jax.numpy as jnp

        def widen(x):
            return x.astype(jnp.float32)

        @jax.jit
        def step(x):
            return widen(x) * 2
        """, path="pkg/models/mod.py", rules=["precision-upcast"])
    assert rules_of(fs) == ["precision-upcast"]


def test_precision_upcast_out_of_scope_and_host_pass():
    """engines/ aggregation tails (f32 master weights by contract) and
    host-side code are out of the rule's reach; model-dtype casts pass."""
    src = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def round_tail(w):
            return w.astype(jnp.float32)

        def host(x):
            return x.astype(jnp.float32)
        """
    assert lint(src, path="pkg/engines/mod.py",
                rules=["precision-upcast"]) == []
    fs = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x, dtype):
            return x.astype(dtype) + jnp.zeros((4,), jnp.float32)
        """, path="pkg/core/mod.py", rules=["precision-upcast"])
    assert fs == []  # threading a dtype / f32 zeros-construction are fine


def test_precision_upcast_pragma_suppresses_with_reason():
    fs = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return x.astype(jnp.float32)  # nidt: allow[precision-upcast] -- blessed loss site
        """, path="pkg/core/mod.py", rules=["precision-upcast"])
    assert fs == []


# ---------------- round-program discipline (ISSUE 11) ----------------

def test_round_program_flags_hand_rolled_fused_scan():
    """A lax.scan inside a *round*/*fused*-named method of an engine
    class is a hand-rolled fused round body — the builder
    (engines/program.py) owns the K-round scan."""
    fs = lint("""
        import jax
        from neuroimagedisttraining_tpu.engines.base import FederatedEngine

        class E(FederatedEngine):
            name = "e"
            supports_streaming = False

            def train(self):
                pass

            def _fused_round_jit(self, k):
                def fused_round_fn(params, xs):
                    return jax.lax.scan(lambda c, x: (c, c), params, xs)
                return jax.jit(fused_round_fn,
                               donate_argnums=self._donate_argnums(0))
        """, path="pkg/engines/mod.py",
        rules=["round-program-fused-body"])
    assert rules_of(fs) == ["round-program-fused-body"]


def test_round_program_allows_scan_outside_engines_and_in_builder():
    src = """
        import jax

        def fused_round_fn(params, xs):
            return jax.lax.scan(lambda c, x: (c, c), params, xs)
        """
    # module-level scan (no engine class): fine
    assert lint(src, path="pkg/engines/mod.py",
                rules=["round-program-fused-body"]) == []
    # the builder itself: exempt by file
    engine_src = """
        import jax
        from neuroimagedisttraining_tpu.engines.base import FederatedEngine

        class E(FederatedEngine):
            name = "e"
            supports_streaming = False

            def train(self):
                pass

            def _fused_round_jit(self, k):
                def fused_round_fn(params, xs):
                    return jax.lax.scan(lambda c, x: (c, c), params, xs)
                return jax.jit(fused_round_fn,
                               donate_argnums=self._donate_argnums(0))
        """
    assert lint(engine_src, path="pkg/engines/program.py",
                rules=["round-program-fused-body"]) == []


def test_round_program_allows_non_round_scan_in_engine():
    """Scans in non-round methods (phase-1 scoring, eval chunking) stay
    legal — only the fused-round naming convention is fenced."""
    fs = lint("""
        import jax
        from neuroimagedisttraining_tpu.engines.base import FederatedEngine

        class E(FederatedEngine):
            name = "e"
            supports_streaming = False

            def train(self):
                pass

            def _scores_body(self, xs):
                return jax.lax.scan(lambda c, x: (c, c), 0, xs)
        """, path="pkg/engines/mod.py",
        rules=["round-program-fused-body"])
    assert fs == []


def test_round_program_reason_must_be_table_key():
    base = """
        from neuroimagedisttraining_tpu.engines.base import FederatedEngine

        class E(FederatedEngine):
            name = "e"
            supports_streaming = False

            def train(self):
                pass

            def fused_fallback_key(self):
                return {key}
        """
    fs = lint(base.format(key="'my ad-hoc reason string'"),
              path="pkg/engines/mod.py", rules=["round-program-reason"])
    assert rules_of(fs) == ["round-program-reason"]
    assert lint(base.format(key="'mpc-host-stage'"),
                path="pkg/engines/mod.py",
                rules=["round-program-reason"]) == []
    assert lint(base.format(key="None"),
                path="pkg/engines/mod.py",
                rules=["round-program-reason"]) == []


def test_round_program_reason_keys_parse_from_source():
    from neuroimagedisttraining_tpu.analysis.round_program import (
        _reason_keys,
    )

    keys = _reason_keys()
    assert "no-fused-body" in keys
    assert "mpc-host-stage" in keys
    assert "gossip-mesh-collectives" in keys
