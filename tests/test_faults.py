"""faults/: deterministic chaos + the tolerance it forces (ISSUE 2).

Covers the seeded FaultSchedule (pure function of (seed, round, rank) —
identical replay), the FaultyCommManager wrapper over both transports,
the server's deadline/quorum survivor aggregation (bitwise-equal to the
jitted engine aggregation over the same survivor set), round-tagged
dedup (duplicates/stale uploads never double-count), heartbeat
suspicion, late rejoin, the engine-side survivor sampling driven by the
same schedule, and SecureFedAvgServer under dropout.
"""

import multiprocessing as mp
import socket
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.distributed.comm import SocketCommManager
from neuroimagedisttraining_tpu.distributed.cross_silo import (
    FedAvgClientProc,
    FedAvgServer,
    SecureFedAvgClientProc,
    SecureFedAvgServer,
    survivor_weighted_mean,
)
from neuroimagedisttraining_tpu.distributed.ports import free_port_block
from neuroimagedisttraining_tpu.faults import (
    FaultSchedule,
    FaultyCommManager,
    activity_mask,
    parse_fault_spec,
)
from neuroimagedisttraining_tpu.utils.pytree import tree_weighted_mean


def _base_port() -> int:
    return free_port_block(8)


# ---------------------------------------------------------------- schedule


def test_parse_fault_spec_grammar():
    spec = parse_fault_spec(
        "crash:3@1,crash_prob:0.01;straggle:0.5:0.25,drop:0.1,"
        "dup:0.05,disconnect:0.02")
    assert spec.crashes == ((3, 1),)
    assert spec.crash_prob == 0.01
    assert spec.straggle_prob == 0.5 and spec.straggle_delay == 0.25
    assert spec.drop_prob == 0.1 and spec.dup_prob == 0.05
    assert spec.disconnect_prob == 0.02
    assert spec.any_faults
    assert not parse_fault_spec("").any_faults
    with pytest.raises(ValueError):
        parse_fault_spec("explode:0.5")
    with pytest.raises(ValueError):
        parse_fault_spec("drop:1.5")


def test_fault_schedule_replays_identically():
    """The acceptance property: the ENTIRE fault trace is a pure
    function of the config seed — fresh instances, any query order."""
    text = "crash:2@1,crash_prob:0.05,straggle:0.4:0.1,drop:0.2,dup:0.1"
    a = FaultSchedule(parse_fault_spec(text), seed=1024)
    b = FaultSchedule(parse_fault_spec(text), seed=1024)
    # query b in reverse order first: per-event streams are independent
    tb = list(reversed([b.drop(r, k, s) for r in range(5)
                        for k in range(1, 5) for s in range(3)]))
    ta = list(reversed([a.drop(r, k, s) for r in range(5)
                        for k in range(1, 5) for s in range(3)]))
    assert ta == tb
    assert a.trace(6, range(6)) == b.trace(6, range(6))
    # a different seed produces a different trace
    c = FaultSchedule(parse_fault_spec(text), seed=7)
    assert c.trace(6, range(6)) != a.trace(6, range(6))


def test_schedule_crash_semantics():
    s = FaultSchedule(parse_fault_spec("crash:3@2"), seed=0)
    assert not s.crashed(0, 3) and not s.crashed(1, 3)
    assert s.crashed(2, 3) and s.crashed(7, 3)  # permanent
    assert not s.crashed(7, 1)
    assert s.crash_round(3, horizon=10) == 2
    assert s.crash_round(1, horizon=10) is None
    # survivors() maps engine client index c -> rank c + 1
    np.testing.assert_array_equal(
        s.survivors(2, np.arange(4)), np.asarray([0, 1, 3]))
    # a schedule that kills everyone keeps the cohort (0/0 guard)
    k = FaultSchedule(parse_fault_spec("crash:1@0,crash:2@0"), seed=0)
    np.testing.assert_array_equal(k.survivors(0, np.arange(2)),
                                  np.arange(2))


def test_rejoin_directive_parse_and_crash_windows():
    """``rejoin:RANK@ROUND`` (ISSUE 7) ends a deterministic crash
    window: crash/rejoin/crash directives alternate, and parse
    validation rejects a rejoin with no earlier crash to return from."""
    s = FaultSchedule(parse_fault_spec("crash:3@1,rejoin:3@4,crash:3@6"),
                      seed=0)
    assert [s.crashed(r, 3) for r in range(8)] == [
        False, True, True, True, False, False, True, True]
    # crash_round sees the FIRST window; survivors honor the rejoin
    assert s.crash_round(3, horizon=10) == 1
    np.testing.assert_array_equal(
        s.survivors(4, np.arange(4)), np.arange(4))  # rank 3 is back
    np.testing.assert_array_equal(
        s.survivors(2, np.arange(4)), np.asarray([0, 1, 3]))
    with pytest.raises(ValueError, match="rejoin:2@3 has no crash"):
        parse_fault_spec("rejoin:2@3")
    with pytest.raises(ValueError, match="no crash"):
        # a rejoin must be STRICTLY after the crash it ends
        parse_fault_spec("crash:2@5,rejoin:2@5")
    with pytest.raises(ValueError, match="share a\n?.*round|share a round"):
        # ... and a LATER crash may not tie an existing rejoin either —
        # the event walk's 'rounds never tie' invariant is validated,
        # not assumed (a tie would silently cancel the rejoin)
        parse_fault_spec("crash:2@1,rejoin:2@5,crash:2@5")
    # probabilistic crashes stay permanent: rejoin only pairs with
    # deterministic crash directives
    assert parse_fault_spec("crash:1@0,rejoin:1@2,crash_prob:0.5")


def test_rejoin_schedule_replays_identically():
    """The replay acceptance property extends to rejoin windows: the
    full trace is a pure function of (spec, seed), any query order."""
    text = "crash:2@1,rejoin:2@3,crash:4@2,rejoin:4@5,drop:0.2"
    a = FaultSchedule(parse_fault_spec(text), seed=11)
    b = FaultSchedule(parse_fault_spec(text), seed=11)
    tb = [b.crashed(r, k) for r in reversed(range(7))
          for k in reversed(range(1, 6))]
    ta = [a.crashed(r, k) for r in range(7) for k in range(1, 6)]
    assert ta == list(reversed(tb))
    assert a.trace(7, range(6)) == b.trace(7, range(6))


def test_activity_mask_matches_legacy_dispfl_formula():
    """The unified draw reproduces engines/dispfl.py's historical inline
    formula bit-for-bit, so seeds keep their meaning."""
    for seed, round_idx, n, p in [(1024, 0, 21, 0.5), (7, 3, 8, 0.3),
                                  (42, 17, 4, 0.9)]:
        rng = np.random.default_rng(seed * 100003 + round_idx)
        want = rng.random(n) < p
        np.testing.assert_array_equal(
            activity_mask(seed, round_idx, n, p), want)


def test_schedule_active_mask_forces_crashed_inactive():
    s = FaultSchedule(parse_fault_spec("crash:2@1"), seed=1024)
    # round 0: pure activity; round 1+: client index 1 (rank 2) forced off
    np.testing.assert_array_equal(s.active_mask(0, 4, 1.0),
                                  np.ones(4, bool))
    np.testing.assert_array_equal(s.active_mask(1, 4, 1.0),
                                  np.asarray([True, False, True, True]))


# ------------------------------------------------------------ free ports


def test_free_port_block_is_bindable():
    import socket

    base = free_port_block(4)
    socks = []
    try:
        for i in range(4):
            s = socket.socket()
            s.bind(("127.0.0.1", base + i))
            socks.append(s)
    finally:
        for s in socks:
            s.close()
    with pytest.raises(ValueError):
        free_port_block(0)


# ------------------------------------------- in-thread tolerant protocol


def _toy_train(rank, lr=0.5):
    """Deterministic float32 'training': pull w toward the rank value."""
    def fn(params, round_idx):
        p = {k: np.asarray(v, np.float32) for k, v in params.items()}
        p["w"] = p["w"] + np.float32(lr) * (np.float32(rank) - p["w"])
        return p, 10.0 * rank
    return fn


def _make_client(rank, num_clients, bp, *, spec=None, seed=0, hb=0.0,
                 train=None):
    comm = SocketCommManager(rank, num_clients + 1, base_port=bp)
    if spec:
        comm = FaultyCommManager(
            comm, FaultSchedule(parse_fault_spec(spec), seed), rank)
    return FedAvgClientProc(rank, num_clients,
                            train or _toy_train(rank), base_port=bp,
                            comm=comm, heartbeat_interval=hb)


def _replay_rounds(init, survivors_per_round, lr=0.5):
    """Host-side replay of the protocol: per round, survivors train from
    the current global model and the aggregate is the jitted engine
    aggregation over the survivor set."""
    params = {k: np.asarray(v, np.float32) for k, v in init.items()}
    for r, survivors in enumerate(survivors_per_round):
        outs = {c: _toy_train(c, lr)(params, r) for c in survivors}
        senders = sorted(outs)
        params = survivor_weighted_mean(
            [outs[s][0] for s in senders], [outs[s][1] for s in senders])
    return params


def test_deadline_quorum_survivor_aggregate_bitwise():
    """Client 4 crashes at round 1 (seeded schedule). The server's
    deadline+quorum round aggregates the 3 survivors with sample-count
    re-weighting, bitwise-equal to the jitted engine aggregation
    (tree_weighted_mean) over the same survivor set."""
    num_clients, rounds = 4, 3
    bp = _base_port()
    init = {"w": np.zeros(3, np.float32)}
    spec, seed = "crash:4@1", 1024
    server = FedAvgServer(init, rounds, num_clients, base_port=bp,
                          round_deadline=2.0, quorum=2)
    clients = [_make_client(c, num_clients, bp, spec=spec, seed=seed)
               for c in range(1, num_clients + 1)]
    threads = [threading.Thread(target=m.run, daemon=True)
               for m in [server] + clients]
    for t in threads:
        t.start()
    assert server._done.wait(timeout=90), "chaos protocol stalled"
    for t in threads:
        t.join(timeout=15)

    assert len(server.history) == rounds
    assert server.history[0]["survivors"] == [1, 2, 3, 4]
    for entry in server.history[1:]:
        assert entry["survivors"] == [1, 2, 3]
    assert 4 in server.suspect_clients()
    # replay the schedule from the seed: identical survivor sets
    sched = FaultSchedule(parse_fault_spec(spec), seed)
    survivors = [[c for c in range(1, num_clients + 1)
                  if not sched.crashed(r, c)] for r in range(rounds)]
    assert survivors == [e["survivors"] for e in server.history]
    want = _replay_rounds(init, survivors)
    np.testing.assert_array_equal(server.params["w"], want["w"])
    # and the aggregation primitive IS the engine one: a fresh jit of
    # tree_weighted_mean over the last survivor round agrees bitwise
    params_in = _replay_rounds(init, survivors[:-1])
    outs = {c: _toy_train(c)(params_in, rounds - 1)
            for c in survivors[-1]}
    stacked = jax.tree.map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
        *[outs[s][0] for s in sorted(outs)])
    ns = jnp.asarray([outs[s][1] for s in sorted(outs)], jnp.float32)
    engine_agg = jax.jit(tree_weighted_mean)(stacked, ns)
    np.testing.assert_array_equal(server.params["w"],
                                  np.asarray(engine_agg["w"]))


def test_duplicate_uploads_never_double_count():
    """dup:1.0 duplicates every protocol message; round-tagged dedup
    must keep the aggregate identical to the clean run."""
    num_clients, rounds = 3, 2
    bp = _base_port()
    init = {"w": np.zeros(3, np.float32)}
    server = FedAvgServer(init, rounds, num_clients, base_port=bp)
    clients = [_make_client(c, num_clients, bp, spec="dup:1.0")
               for c in range(1, num_clients + 1)]
    threads = [threading.Thread(target=m.run, daemon=True)
               for m in [server] + clients]
    for t in threads:
        t.start()
    assert server._done.wait(timeout=60), "dup protocol stalled"
    for t in threads:
        t.join(timeout=15)
    assert len(server.history) == rounds
    assert all(e["clients"] == num_clients for e in server.history)
    want = _replay_rounds(init, [[1, 2, 3]] * rounds)
    np.testing.assert_array_equal(server.params["w"], want["w"])


def test_drop_and_disconnect_survivor_rounds():
    """Client 2's uploads are all lost (drop:1.0 / torn mid-frame by
    disconnect:1.0). The deadline round completes over the survivor and
    the server listener survives the torn frames."""
    for directive in ("drop:1.0", "disconnect:1.0"):
        num_clients, rounds = 2, 2
        bp = _base_port()
        init = {"w": np.zeros(2, np.float32)}
        server = FedAvgServer(init, rounds, num_clients, base_port=bp,
                              round_deadline=1.0, quorum=1)
        # only client 2 is chaotic; client 1 is clean
        clients = [_make_client(1, num_clients, bp),
                   _make_client(2, num_clients, bp, spec=directive)]
        threads = [threading.Thread(target=m.run, daemon=True)
                   for m in [server] + clients]
        for t in threads:
            t.start()
        assert server._done.wait(timeout=60), f"{directive} stalled"
        server_thread = threads[0]
        server_thread.join(timeout=15)
        assert len(server.history) == rounds
        for e in server.history:
            assert e["survivors"] == [1], (directive, server.history)
        want = _replay_rounds(init, [[1]] * rounds)
        np.testing.assert_array_equal(server.params["w"], want["w"])
        # the chaotic client never crashed — tear its loop down
        for cl in clients:
            cl.com_manager.stop_receive_message()
        for t in threads[1:]:
            t.join(timeout=15)


class _NullComm:
    """Transport stub for handler-level unit tests (no sockets)."""

    def send_message(self, msg, **kw):
        pass

    def add_observer(self, obs):
        pass

    def remove_observer(self, obs):
        pass

    def handle_receive_message(self):
        pass

    def stop_receive_message(self):
        pass


def test_stale_round_upload_rejected_unit():
    """Direct handler-level pin: an upload tagged with a wrong round (a
    straggler finishing after its round closed, or a re-delivered frame)
    never enters the aggregate."""
    server = FedAvgServer({"w": np.zeros(2, np.float32)}, 5, 2,
                          comm=_NullComm())
    server.register_message_receive_handlers()
    for c in (1, 2):
        reg = M.Message(M.MSG_TYPE_C2S_REGISTER, c, 0)
        server._on_register(reg)
    assert server._started and server.round_idx == 0

    def upload(c, round_tag, value, n):
        msg = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, c, 0)
        msg.add(M.ARG_MODEL_PARAMS, {"w": np.full(2, value, np.float32)})
        msg.add(M.ARG_NUM_SAMPLES, float(n))
        msg.add(M.ARG_ROUND_IDX, round_tag)
        server._on_model(msg)

    upload(1, 0, 1.0, 10.0)
    upload(1, 0, 99.0, 10.0)   # duplicate: ignored
    upload(2, 3, 99.0, 10.0)   # stale/future round: ignored
    assert server.round_idx == 0 and len(server._updates) == 1
    upload(2, 0, 3.0, 30.0)    # completes the round
    assert server.round_idx == 1
    # aggregate = (10*1 + 30*3)/40 = 2.5 — the 99-valued frames never
    # double-counted
    np.testing.assert_allclose(server.params["w"],
                               np.full(2, 2.5, np.float32), rtol=1e-6)


def test_heartbeat_flags_killed_client_within_bound():
    """A client that registers, beats, then goes silent is marked
    suspect within ~heartbeat_timeout + poll; the monitor's suspicion
    lets rounds complete without it (quorum floor holds)."""
    num_clients, rounds = 2, 2
    hb_timeout = 0.5
    bp = _base_port()
    init = {"w": np.zeros(2, np.float32)}
    server = FedAvgServer(init, rounds, num_clients, base_port=bp,
                          quorum=1, heartbeat_timeout=hb_timeout)
    live = _make_client(1, num_clients, bp, hb=0.1)
    # rank 2: a zombie — real listener, registers, beats briefly, dies
    zombie_comm = SocketCommManager(2, num_clients + 1, base_port=bp)
    threads = [threading.Thread(target=m.run, daemon=True)
               for m in (server, live)]
    for t in threads:
        t.start()
    reg = M.Message(M.MSG_TYPE_C2S_REGISTER, 2, 0)
    zombie_comm.send_message(reg)
    for _ in range(3):
        zombie_comm.send_message(M.Message(M.MSG_TYPE_C2S_HEARTBEAT, 2, 0))
        time.sleep(0.05)
    t_silent = time.monotonic()
    deadline = t_silent + 10 * hb_timeout
    while time.monotonic() < deadline:
        if 2 in server.suspect_clients():
            break
        time.sleep(0.02)
    t_flag = time.monotonic()
    assert 2 in server.suspect_clients(), "killed client never flagged"
    assert t_flag - t_silent <= 6 * hb_timeout, (
        f"suspicion took {t_flag - t_silent:.2f}s for a "
        f"{hb_timeout}s timeout")
    assert server._done.wait(timeout=30), "monitor-driven rounds stalled"
    for e in server.history:
        assert e["survivors"] == [1]
    zombie_comm.stop_receive_message()
    for t in threads:
        t.join(timeout=15)


def test_late_rejoin_via_reregister():
    """A crashed client's replacement re-registers mid-federation; the
    server ships it the current round state and it contributes again
    (suspicion cleared, survivors grow back)."""
    num_clients, rounds = 2, 8
    bp = _base_port()
    init = {"w": np.zeros(2, np.float32)}
    server = FedAvgServer(init, rounds, num_clients, base_port=bp,
                          round_deadline=0.5, quorum=1)

    def slow_train(params, round_idx):  # keep rounds >= 0.3s so the
        time.sleep(0.3)                 # rejoin lands before FINISH
        return _toy_train(1)(params, round_idx)

    c1 = _make_client(1, num_clients, bp, train=slow_train)
    c2 = _make_client(2, num_clients, bp, spec="crash:2@1", seed=0)
    threads = [threading.Thread(target=m.run, daemon=True)
               for m in (server, c1, c2)]
    for t in threads:
        t.start()
    # wait until the crash bit: some round completed without client 2
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if any(e.get("survivors") == [1] for e in server.history):
            break
        time.sleep(0.05)
    assert any(e.get("survivors") == [1] for e in server.history), \
        "client 2 never dropped out"
    # the server's deadline verdict can precede the crash itself: the
    # crash fires on c2's dispatch thread when it processes the round-1
    # sync, and under load that thread may lag the 0.5s deadline — so
    # wait for the crashed listener to actually release the port before
    # the replacement binds it (EADDRINUSE otherwise)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as probe:
            probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                probe.bind(("0.0.0.0", bp + 2))
                break
            except OSError:
                time.sleep(0.05)
    # a fresh healthy process takes over rank 2 and re-registers
    c2b = _make_client(2, num_clients, bp)
    t2b = threading.Thread(target=c2b.run, daemon=True)
    t2b.start()
    assert server._done.wait(timeout=60), "rejoin federation stalled"
    assert any(e.get("survivors") == [1, 2]
               for e in server.history[1:]), (
        f"rejoined client never contributed: {server.history}")
    for t in threads + [t2b]:
        t.join(timeout=15)


# ------------------------------------------------- broker-transport chaos


def test_faulty_comm_wraps_broker_transport():
    """The wrapper is transport-agnostic: duplicates over the pub/sub
    broker are deduped by the round tag exactly as over sockets."""
    from neuroimagedisttraining_tpu.distributed.broker import (
        BrokerCommManager, MessageBroker,
    )

    num_clients, rounds = 2, 2
    broker = MessageBroker()
    init = {"w": np.zeros(2, np.float32)}
    server = FedAvgServer(
        init, rounds, num_clients,
        comm=BrokerCommManager("127.0.0.1", broker.port, client_id=0,
                               client_num=num_clients))
    sched = FaultSchedule(parse_fault_spec("dup:1.0"), seed=3)
    clients = []
    for c in (1, 2):
        inner = BrokerCommManager("127.0.0.1", broker.port, client_id=c,
                                  client_num=num_clients)
        clients.append(FedAvgClientProc(
            c, num_clients, _toy_train(c),
            comm=FaultyCommManager(inner, sched, c)))
    threads = [threading.Thread(target=m.run, daemon=True)
               for m in [server] + clients]
    for t in threads:
        t.start()
    assert server._done.wait(timeout=60), "broker chaos stalled"
    for t in threads:
        t.join(timeout=15)
    assert len(server.history) == rounds
    want = _replay_rounds(init, [[1, 2]] * rounds)
    np.testing.assert_array_equal(server.params["w"], want["w"])
    broker.stop()


# ------------------------------------------------- multiprocess chaos run


def _chaos_client(rank, num_clients, base_port, seed, spec, hb):
    # separate OS process: a simulated crash kills the whole process
    from neuroimagedisttraining_tpu.distributed.comm import (
        SocketCommManager,
    )
    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        FedAvgClientProc,
    )
    from neuroimagedisttraining_tpu.faults import (
        FaultSchedule, FaultyCommManager, parse_fault_spec,
    )

    comm = SocketCommManager(rank, num_clients + 1, base_port=base_port)
    comm = FaultyCommManager(
        comm, FaultSchedule(parse_fault_spec(spec), seed), rank)

    def train_fn(params, round_idx):
        p = {k: np.asarray(v, np.float32) * np.float32(0.5) + rank
             for k, v in params.items()}
        return p, float(rank)

    FedAvgClientProc(rank, num_clients, train_fn, base_port=base_port,
                     comm=comm, heartbeat_interval=hb).run()


def test_multiprocess_chaos_one_of_four_killed():
    """THE acceptance scenario: a 4-silo multiprocess FedAvg federation
    with client 3 killed mid-run (seeded schedule -> its process exits)
    completes all rounds; the survivor-weighted aggregate bitwise-equals
    the jitted engine aggregation replay over the same survivor sets;
    the fault schedule replays identically from the config seed."""
    num_clients, rounds, seed, spec = 4, 3, 1024, "crash:3@1"
    bp = _base_port()
    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=_chaos_client,
                         args=(r, num_clients, bp, seed, spec, 0.2),
                         daemon=True)
             for r in range(1, num_clients + 1)]
    for p in procs:
        p.start()
    init = {"w": np.zeros(3, np.float32)}
    server = FedAvgServer(init, rounds, num_clients, base_port=bp,
                          round_deadline=30.0, quorum=2,
                          heartbeat_timeout=3.0)
    t = threading.Thread(target=server.run, daemon=True)
    t.start()
    assert server._done.wait(timeout=240), "chaos federation stalled"
    t.join(timeout=15)
    for p in procs:
        p.join(timeout=30)

    assert len(server.history) == rounds
    sched = FaultSchedule(parse_fault_spec(spec), seed)
    survivors = [[c for c in range(1, num_clients + 1)
                  if not sched.crashed(r, c)] for r in range(rounds)]
    assert survivors == [e["survivors"] for e in server.history], \
        "survivor sets did not replay from the config seed"
    assert 3 in server.suspect_clients()

    # bitwise replay: survivors train (p*0.5 + rank), jitted engine
    # aggregation over the survivor set each round
    params = dict(init)
    for r, surv in enumerate(survivors):
        outs = {c: ({"w": np.asarray(params["w"], np.float32)
                     * np.float32(0.5) + c}, float(c)) for c in surv}
        senders = sorted(outs)
        params = survivor_weighted_mean(
            [outs[s][0] for s in senders], [outs[s][1] for s in senders])
    np.testing.assert_array_equal(server.params["w"], params["w"])


# ----------------------------------------------- secure server + dropout


def _secure_toy(rank, lr=0.5):
    def fn(params, round_idx):
        p = {k: np.asarray(v, np.float32) for k, v in params.items()}
        p["w"] = p["w"] + np.float32(lr) * (np.float32(rank) - p["w"])
        return p, 10.0 * rank
    return fn


def test_secure_server_requires_all_clients_without_quorum():
    """Pins the pre-tolerance contract: with no deadline/quorum the
    secure server blocks the round until EVERY client reports — a single
    missing client stalls the federation (the behavior ISSUE 2 calls
    out; the quorum test below pins the fix)."""
    num_clients = 3
    bp = _base_port()
    init = {"w": np.zeros(2, np.float32)}
    server = SecureFedAvgServer(init, 1, num_clients, base_port=bp)
    clients = [SecureFedAvgClientProc(c, num_clients, _secure_toy(c),
                                      n_shares=3, mpc_seed=c, base_port=bp)
               for c in (1, 2)]  # client 3 never starts
    threads = [threading.Thread(target=m.run, daemon=True)
               for m in [server] + clients]
    for t in threads:
        t.start()
    assert not server._done.wait(timeout=3.0), (
        "secure server completed without all clients — the strict "
        "contract this test pins has changed")
    assert len(server.history) == 0
    for m in [server] + clients:
        m.com_manager.stop_receive_message()
    for t in threads:
        t.join(timeout=15)


def test_secure_server_quorum_dropout_reweighted():
    """With deadline+quorum, a client crashing mid-run drops out of the
    secure aggregate cleanly: survivors' share sets fold, the missing
    client contributes NOTHING (atomic discard), and the dequantized
    aggregate is re-weighted to a true mean over the survivors."""
    num_clients, rounds, lr = 3, 3, 0.5
    bp = _base_port()
    init = {"w": np.zeros(2, np.float32)}
    server = SecureFedAvgServer(init, rounds, num_clients, base_port=bp,
                                round_deadline=1.5, quorum=2)
    clients = []
    for c in (1, 2, 3):
        comm = SocketCommManager(c, num_clients + 1, base_port=bp)
        if c == 3:
            comm = FaultyCommManager(
                comm, FaultSchedule(parse_fault_spec("crash:3@1"), 0), c)
        clients.append(SecureFedAvgClientProc(
            c, num_clients, _secure_toy(c, lr), n_shares=3, mpc_seed=c,
            base_port=bp, comm=comm))
    threads = [threading.Thread(target=m.run, daemon=True)
               for m in [server] + clients]
    for t in threads:
        t.start()
    assert server._done.wait(timeout=120), "secure dropout stalled"
    for t in threads:
        t.join(timeout=15)

    assert len(server.history) == rounds
    assert server.history[0]["survivors"] == [1, 2, 3]
    for e in server.history[1:]:
        assert e["survivors"] == [1, 2]
    # plaintext replay with survivor re-weighting (fixed-point tolerance)
    params = {"w": np.zeros(2, np.float64)}
    for r, surv in enumerate([[1, 2, 3], [1, 2], [1, 2]][:rounds]):
        outs = {c: _secure_toy(c, lr)(params, r) for c in surv}
        w = np.asarray([outs[c][1] for c in sorted(outs)], np.float64)
        w = w / w.sum()
        params = {"w": sum(wi * np.asarray(outs[c][0]["w"], np.float64)
                           for wi, c in zip(w, sorted(outs)))}
    np.testing.assert_allclose(server.params["w"], params["w"], atol=1e-3)


def test_secure_stale_share_upload_discarded_atomically():
    """Handler-level pin of the atomic-discard contract: a share upload
    tagged with a stale round (or from a client with no weight this
    round) never folds into the slot accumulator — not even partially."""
    server = SecureFedAvgServer({"w": np.zeros(2, np.float32)}, 5, 2,
                                comm=_NullComm())
    server.register_message_receive_handlers()
    for c in (1, 2):
        server._on_register(M.Message(M.MSG_TYPE_C2S_REGISTER, c, 0))
    # phase A: both clients report n_c -> weights go out, phase flips
    for c, n in ((1, 10.0), (2, 30.0)):
        msg = M.Message(M.MSG_TYPE_C2S_NUM_SAMPLES, c, 0)
        msg.add(M.ARG_NUM_SAMPLES, n)
        msg.add(M.ARG_ROUND_IDX, 0)
        server._on_num_samples(msg)
    assert server._phase == "B" and set(server._weights_sent) == {1, 2}

    shares = {"w": np.arange(6, dtype=np.int64).reshape(3, 2)}
    stale = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, 1, 0)
    stale.add(M.ARG_MODEL_PARAMS, shares)
    stale.add(M.ARG_ROUND_IDX, 4)       # wrong round
    server._on_model(stale)
    assert server._slot_acc is None and server._folded == set()

    ok = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, 1, 0)
    ok.add(M.ARG_MODEL_PARAMS, shares)
    ok.add(M.ARG_ROUND_IDX, 0)
    server._on_model(ok)
    assert server._folded == {1}
    dup = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, 1, 0)
    dup.add(M.ARG_MODEL_PARAMS, shares)
    dup.add(M.ARG_ROUND_IDX, 0)
    server._on_model(dup)               # duplicate: no second fold
    np.testing.assert_array_equal(server._slot_acc["w"], shares["w"])


def test_secure_phase_b_dropout_rescale_unit():
    """A client that reported n_c (so got a weight) but died before
    uploading shares: the deadline fires, the survivors' dequantized sum
    is w-deficient, and the server re-weights by 1 / (sum of survivor
    weights) — recovering a true weighted mean over the survivors."""
    from neuroimagedisttraining_tpu.ops import mpc

    server = SecureFedAvgServer({"w": np.zeros(2, np.float32)}, 5, 2,
                                comm=_NullComm(), round_deadline=60.0,
                                quorum=1)
    server.register_message_receive_handlers()
    for c in (1, 2):
        server._on_register(M.Message(M.MSG_TYPE_C2S_REGISTER, c, 0))
    for c, n in ((1, 10.0), (2, 30.0)):  # -> w_1 = 0.25, w_2 = 0.75
        msg = M.Message(M.MSG_TYPE_C2S_NUM_SAMPLES, c, 0)
        msg.add(M.ARG_NUM_SAMPLES, n)
        msg.add(M.ARG_ROUND_IDX, 0)
        server._on_num_samples(msg)
    assert server._phase == "B"
    x = np.asarray([1.5, -2.0], np.float64)  # client 1's trained params
    shares = {"w": mpc.additive_shares(
        mpc.quantize(0.25 * x), 3, rng=np.random.default_rng(0))}
    up = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, 1, 0)
    up.add(M.ARG_MODEL_PARAMS, shares)
    up.add(M.ARG_ROUND_IDX, 0)
    server._on_model(up)
    # client 2 never uploads; quorum=1 holds at the deadline
    server._on_deadline(0, server._deadline_gen)
    if server._timer is not None:
        server._timer.cancel()
    assert server.round_idx == 1
    assert server.history[0]["survivors"] == [1]
    assert 2 in server.suspect_clients()
    # dequantize(0.25 * x) / 0.25 == x to fixed-point precision
    np.testing.assert_allclose(server.params["w"], x, atol=1e-3)


# --------------------------------------------- engine-side unification


def _make_engine(tmp_path, cohort, algorithm="fedavg", **fed_kw):
    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.federate import federate_cohort
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm=algorithm,
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=OptimConfig(lr=5e-4, batch_size=8, epochs=1),
        fed=FedConfig(**{"client_num_in_total": 4, "comm_round": 3,
                         **fed_kw}),
        log_dir=str(tmp_path))
    mesh = make_mesh(shape=())
    fed, _ = federate_cohort(cohort, partition_method="site", mesh=mesh)
    model = create_model(cfg.model, num_classes=1)
    trainer = LocalTrainer(model, cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    return create_engine(algorithm, cfg, fed, trainer, mesh=mesh,
                         logger=log)


def test_engine_sampling_excludes_crashed_clients(tmp_path,
                                                  synthetic_cohort):
    """One seed drives both worlds: the simulated engine's cohort
    filtering uses the SAME schedule as the multiprocess federation
    (engine client index c == rank c + 1)."""
    eng = _make_engine(tmp_path, synthetic_cohort, fault_spec="crash:2@1")
    np.testing.assert_array_equal(eng.client_sampling(0), np.arange(4))
    np.testing.assert_array_equal(eng.client_sampling(1),
                                  np.asarray([0, 2, 3]))
    clean = _make_engine(tmp_path, synthetic_cohort)
    assert clean.fault_schedule is None
    np.testing.assert_array_equal(clean.client_sampling(1), np.arange(4))


def test_engine_survivor_round_is_frac_sampled_round(tmp_path,
                                                     synthetic_cohort):
    """Survivor-reweight parity: the faulty engine's jitted round over
    the survivor set is the SAME program a clean engine runs for a
    frac-sampled round with that cohort — bitwise-identical outputs."""
    eng_f = _make_engine(tmp_path, synthetic_cohort,
                         fault_spec="crash:2@1")
    eng_c = _make_engine(tmp_path, synthetic_cohort)
    # the same state tuple rides into BOTH round programs; donation
    # (ISSUE 4) would delete it at the first dispatch
    eng_f._donate = eng_c._donate = False
    surv = eng_f.client_sampling(1)
    gs = eng_c.init_global_state()
    rngs = eng_c.per_client_rngs(1, surv)
    args = (gs.params, gs.batch_stats)
    out_f = eng_f._round_jit(*args, eng_f.data, jnp.asarray(surv), rngs,
                             eng_f.round_lr(1))
    out_c = eng_c._round_jit(*args, eng_c.data, jnp.asarray(surv), rngs,
                             eng_c.round_lr(1))
    for a, b in zip(jax.tree.leaves(out_f), jax.tree.leaves(out_c)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dispfl_active_draw_crash_gating(tmp_path, synthetic_cohort):
    """DisPFL's activity draw now routes through the unified schedule:
    without faults it is bit-identical to the legacy stream; with a
    crash directive the dead client is forced inactive."""
    eng = _make_engine(tmp_path, synthetic_cohort, algorithm="dispfl",
                       active=0.7)
    for r in (0, 1, 5):
        want = np.zeros(eng.num_clients, bool)
        want[: eng.real_clients] = activity_mask(
            eng.cfg.seed, r, eng.real_clients, 0.7)
        np.testing.assert_array_equal(eng.active_draw(r), want)
    eng_f = _make_engine(tmp_path, synthetic_cohort, algorithm="dispfl",
                         active=1.0, fault_spec="crash:2@1")
    assert eng_f.active_draw(0)[: eng_f.real_clients].all()
    a1 = eng_f.active_draw(1)
    assert not a1[1] and a1[0] and a1[2] and a1[3]


def test_config_roundtrips_fault_fields():
    from neuroimagedisttraining_tpu.config import ExperimentConfig
    import json

    cfg = ExperimentConfig.from_dict({
        "fed": {"fault_spec": "crash:3@1,drop:0.1",
                "round_deadline": 12.5, "quorum": 2,
                "heartbeat_interval": 0.5, "heartbeat_timeout": 5.0}})
    assert cfg.fed.fault_spec == "crash:3@1,drop:0.1"
    assert cfg.fed.round_deadline == 12.5 and cfg.fed.quorum == 2
    back = ExperimentConfig.from_dict(json.loads(cfg.to_json()))
    assert back.fed.fault_spec == cfg.fed.fault_spec
    assert back.fed.heartbeat_timeout == 5.0
