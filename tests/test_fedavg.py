"""End-to-end FedAvg on synthetic ABCD volumes over an 8-device CPU mesh.

The minimum vertical slice from SURVEY.md §7 step 5: partition a synthetic
cohort by site, run a few federated rounds, check that (a) training loss
drops, (b) the model beats chance on held-out data, (c) sampling matches the
reference's seeding contract, (d) aggregation algebra is exact on tiny
pytrees.
"""

import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.config import (
    DataConfig, ExperimentConfig, FedConfig, OptimConfig,
)
from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
from neuroimagedisttraining_tpu.data.federate import federate_cohort
from neuroimagedisttraining_tpu.engines import create_engine
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger
from neuroimagedisttraining_tpu.utils.pytree import tree_weighted_mean


def _make_engine(tmp_path, cohort, algorithm="fedavg", mesh_shape=(),
                 **fed_kw):
    cfg = ExperimentConfig(
        model="3dcnn_tiny",  # tiny but real 3D conv net; fast on CPU
        num_classes=1,
        algorithm=algorithm,
        data=DataConfig(dataset="synthetic", partition_method="site"),
        # lr 2e-3 (was 5e-4): at CI scale (4 rounds x 2 epochs) the
        # smaller rate left the loss decrease inside run-to-run noise
        optim=OptimConfig(lr=2e-3, batch_size=8, epochs=2, momentum=0.9,
                          wd=1e-4),
        fed=FedConfig(**{"client_num_in_total": 4, "comm_round": 4,
                         "frequency_of_the_test": 1, **fed_kw}),
        log_dir=str(tmp_path),
    )
    mesh = make_mesh(shape=mesh_shape)
    fed, info = federate_cohort(cohort, partition_method="site", mesh=mesh)
    model = create_model(cfg.model, num_classes=1)
    trainer = LocalTrainer(model, cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    return create_engine(algorithm, cfg, fed, trainer, mesh=mesh, logger=log)


def test_fedavg_end_to_end(tmp_path, synthetic_cohort):
    engine = _make_engine(tmp_path, synthetic_cohort)
    result = engine.train()
    hist = result["history"]
    assert len(hist) == 4
    # loss decreases over training (lr 2e-3 gives a ~0.13 drop — far
    # outside numerical noise, unlike the old 5e-4 config's ~0.01)
    assert hist[-1]["train_loss"] < hist[0]["train_loss"] - 0.02
    # better than chance on the synthetic signal. AUC is the pinned
    # beats-chance metric at this scale: threshold-free, and ~0.82 here.
    # Fixed-threshold accuracy is NOT pinned above chance — with ~20
    # optimizer steps the BatchNorm running statistics used by eval lag
    # training, every held-out logit lands positive, and acc collapses
    # to the label rate (a constant independent of model quality; the
    # old `acc > 0.55` assertion was the suite's one standing failure).
    assert result["final_global"]["auc"] > 0.65
    assert 0.0 <= result["final_global"]["acc"] <= 1.0
    # personalized models exist and evaluate
    assert 0.0 <= result["final_personal"]["acc"] <= 1.0


def test_client_sampling_reference_parity(tmp_path, synthetic_cohort):
    engine = _make_engine(tmp_path, synthetic_cohort, frac=0.5)
    # reference: np.random.seed(round_idx); np.random.choice(n, k, False)
    for round_idx in (0, 1, 7):
        got = engine.client_sampling(round_idx)
        np.random.seed(round_idx)
        want = np.sort(np.random.choice(range(4), 2, replace=False))
        np.testing.assert_array_equal(got, want)
    # full participation => everyone, no RNG
    engine_full = _make_engine(tmp_path, synthetic_cohort, frac=1.0)
    np.testing.assert_array_equal(engine_full.client_sampling(3),
                                  np.arange(4))


def test_weighted_mean_matches_reference_aggregate():
    # reference _aggregate: w_global[k] = sum_i (n_i / sum n) * w_i[k]
    # (fedavg_api.py:102-117)
    rng = np.random.default_rng(0)
    stacked = {"a": jnp.asarray(rng.normal(size=(3, 4, 2))),
               "b": jnp.asarray(rng.normal(size=(3, 5)))}
    n = jnp.asarray([10.0, 30.0, 60.0])
    got = tree_weighted_mean(stacked, n)
    for k in stacked:
        want = sum(float(n[i]) / 100.0 * np.asarray(stacked[k][i])
                   for i in range(3))
        np.testing.assert_allclose(np.asarray(got[k]), want, rtol=1e-5)


def test_round_is_one_compiled_program(tmp_path, synthetic_cohort):
    engine = _make_engine(tmp_path, synthetic_cohort)
    fn = engine._round_jit
    sampled = jnp.asarray(engine.client_sampling(0))
    rngs = engine.per_client_rngs(0, np.arange(4))
    gs = engine.init_global_state()
    lowered = fn.lower(gs.params, gs.batch_stats, engine.data, sampled, rngs,
                       jnp.float32(0.01))
    compiled = lowered.compile()
    assert compiled is not None
