"""Test configuration: force an 8-virtual-device CPU platform BEFORE jax
import, so multi-client mesh sharding is exercised without TPU hardware
(SURVEY.md §4 implication: mesh-simulated backend stands in for multi-node)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# Under the axon TPU plugin the env vars above are ignored; the config API
# (wrapped in provision_virtual_devices) wins as long as it runs before any
# backend initialization.
from neuroimagedisttraining_tpu.parallel.mesh import provision_virtual_devices  # noqa: E402

provision_virtual_devices(8)

# Persistent XLA compilation cache: the suite is compile-bound (~100 jitted
# engine programs); warm-cache reruns skip nearly all of it.
import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/nidt_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def synthetic_cohort():
    from neuroimagedisttraining_tpu.data.synthetic import generate_synthetic_abcd

    return generate_synthetic_abcd(num_subjects=96, shape=(12, 14, 12),
                                   num_sites=4, seed=0)


@pytest.fixture(scope="session")
def synthetic_cohort8():
    """8-site cohort: one real client per device on the 8-device mesh
    (ring-gossip plans require no padding clients)."""
    from neuroimagedisttraining_tpu.data.synthetic import (
        generate_synthetic_abcd,
    )

    return generate_synthetic_abcd(num_subjects=96, shape=(12, 14, 12),
                                   num_sites=8, seed=1)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
