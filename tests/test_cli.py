"""CLI harness: flag mapping, experiment wiring, and a subprocess smoke."""

import json
import subprocess
import sys

import numpy as np

from neuroimagedisttraining_tpu.__main__ import add_args, config_from_args


def _parse(argv):
    import argparse

    return add_args(argparse.ArgumentParser()).parse_args(argv)


def test_flag_mapping_reference_names():
    args = _parse([
        "--algorithm", "salientgrads", "--model", "3DCNN",
        "--dataset", "ABCD", "--partition_method", "dir",
        "--partition_alpha", "0.3", "--batch_size", "16", "--lr", "0.01",
        "--lr_decay", "0.998", "--wd", "5e-4", "--epochs", "2",
        "--client_num_in_total", "21", "--frac", "0.5",
        "--comm_round", "200", "--dense_ratio", "0.2",
        "--itersnip_iteration", "20", "--stratified_sampling",
        "--each_prune_ratio", "0.2", "--lamda", "0.75", "--seed", "7",
        "--mpc_n_shares", "5", "--mpc_frac_bits", "20",
        "--stream_chunk_clients", "2",
    ])
    cfg = config_from_args(args)
    assert cfg.algorithm == "salientgrads"
    assert cfg.data.partition_method == "dir"
    assert cfg.optim.batch_size == 16 and cfg.optim.lr_decay == 0.998
    assert cfg.fed.client_num_in_total == 21 and cfg.fed.frac == 0.5
    assert cfg.fed.client_num_per_round == 10  # int(21 * 0.5)
    assert cfg.sparsity.dense_ratio == 0.2
    assert cfg.sparsity.itersnip_iterations == 20
    assert cfg.sparsity.stratified_sampling is True
    assert cfg.sparsity.each_prune_ratio == 0.2
    assert cfg.fed.lamda == 0.75
    assert cfg.fed.mpc_n_shares == 5 and cfg.fed.mpc_frac_bits == 20
    assert cfg.stream_chunk_clients == 2
    assert cfg.seed == 7
    assert "salientgrads" in cfg.identity() and "seed7" in cfg.identity()


def test_snip_mask_off_switch():
    # the reference's `--snip_mask type=bool` bug makes ANY string truthy
    # (main_sailentgrads.py:125); our explicit off switch must actually work
    assert config_from_args(_parse([])).sparsity.snip_mask is True
    assert config_from_args(
        _parse(["--no_snip_mask"])).sparsity.snip_mask is False


def test_cli_subprocess_end_to_end(tmp_path):
    """One shell command reproduces a FedAvg experiment (VERDICT r1 #6)."""
    out = subprocess.run(
        [sys.executable, "-m", "neuroimagedisttraining_tpu",
         "--algorithm", "fedavg", "--dataset", "synthetic",
         "--model", "3dcnn_tiny", "--synthetic_num_subjects", "32",
         "--synthetic_shape", "12", "14", "12",
         "--client_num_in_total", "4", "--comm_round", "1",
         "--batch_size", "4", "--epochs", "1", "--virtual_devices", "4",
         "--log_dir", str(tmp_path)],
        capture_output=True, text=True, timeout=420)
    assert out.returncode == 0, out.stderr[-2000:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert "final_global" in result and "identity" in result
    assert np.isfinite(result["final_global"]["loss"])
    # file logging under LOG/<dataset>/<identity> (main_sailentgrads.py:184)
    logs = list(tmp_path.glob("synthetic/*.log"))
    assert logs, list(tmp_path.rglob("*"))
    # stat_info persisted at end of training (reference stat pickle,
    # subavg_api.py:218-220)
    stats = list(tmp_path.glob("synthetic/*.stats.json"))
    assert stats, list(tmp_path.rglob("*"))
    blob = json.loads(stats[0].read_text())
    assert "sum_training_flops" in blob and "global_test_acc" in blob


def test_cli_unknown_dataset_errors(tmp_path):
    import pytest

    from neuroimagedisttraining_tpu.__main__ import build_experiment

    cfg = config_from_args(_parse(["--dataset", "imagenet",
                                   "--log_dir", str(tmp_path)]))
    with pytest.raises(ValueError, match="no loader"):
        build_experiment(cfg, console=False)


def test_streaming_fedfomo_requires_val_split(tmp_path):
    """All nine algorithms stream; fedfomo's remaining precondition is a
    val split (its pair-list eval keeps the val_fraction-small shards
    resident), so --streaming without --val_fraction must fail with the
    specific guard in engines/fedfomo.py, not a generic streaming error."""
    import pytest

    from neuroimagedisttraining_tpu.__main__ import build_experiment
    from neuroimagedisttraining_tpu.data.synthetic import write_synthetic_hdf5

    path = str(tmp_path / "c.h5")
    write_synthetic_hdf5(path, num_subjects=16, shape=(8, 8, 8),
                         num_sites=2, seed=0)
    cfg = config_from_args(_parse([
        "--algorithm", "fedfomo", "--dataset", "abcd_h5",
        "--data_dir", path, "--log_dir", str(tmp_path)]))
    with pytest.raises(ValueError,
                       match="streaming requires a val split"):
        build_experiment(cfg, streaming=True, console=False)


def test_two_level_mesh_composes_with_streaming(tmp_path):
    """--streaming --mesh_shape S C now COMPOSES (VERDICT r3 next-step
    #10): round buffers shard over the two-level (silos, clients) mesh
    silo-major, preserving the silo-first aggregation routing."""
    from neuroimagedisttraining_tpu.__main__ import build_experiment
    from neuroimagedisttraining_tpu.data.synthetic import (
        write_synthetic_hdf5,
    )
    from neuroimagedisttraining_tpu.parallel.hierarchical import (
        is_two_level,
    )

    path = str(tmp_path / "c.h5")
    write_synthetic_hdf5(path, num_subjects=64, shape=(12, 14, 12),
                         num_sites=8, seed=0)
    cfg = config_from_args(_parse([
        "--algorithm", "fedavg", "--dataset", "abcd_h5",
        "--data_dir", path, "--client_num_in_total", "8",
        "--mesh_shape", "2", "4", "--log_dir", str(tmp_path)]))
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(shape=(2, 4))
    engine = build_experiment(cfg, streaming=True, mesh=mesh,
                              console=False)
    try:
        assert engine.stream is not None and engine.stream.mesh is mesh
        assert is_two_level(engine.stream.mesh)
        Xs, _, _ = engine.stream.get_train(engine.client_sampling(0))
        # sharded across all 8 devices of the (2, 4) grid, one client each
        assert len(Xs.sharding.device_set) == 8
        assert {s.data.shape[0] for s in Xs.addressable_shards} == {1}
    finally:
        engine.stream.close()


def test_streaming_mesh_pads_nontiling_sample_count(tmp_path):
    """A sampled set that does not tile the mesh (the north-star shape:
    frac-sampling vs a fixed device grid) streams via stream_sampling's
    zero-weight padding instead of erroring (VERDICT r4 #2)."""
    import jax

    from neuroimagedisttraining_tpu.__main__ import build_experiment
    from neuroimagedisttraining_tpu.data.synthetic import write_synthetic_hdf5
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh

    path = str(tmp_path / "c.h5")
    write_synthetic_hdf5(path, num_subjects=32, shape=(12, 14, 12),
                         num_sites=4, seed=0)
    mesh = make_mesh(shape=(2,))
    cfg = config_from_args(_parse([
        "--algorithm", "fedavg", "--dataset", "abcd_h5",
        "--model", "3dcnn_tiny",
        "--data_dir", path, "--client_num_in_total", "4",
        "--frac", "0.75",  # 3 sampled clients, 2-device mesh: no tile
        "--comm_round", "1", "--batch_size", "4", "--epochs", "1",
        "--log_dir", str(tmp_path)]))
    engine = build_experiment(cfg, streaming=True, mesh=mesh, console=False)
    try:
        fed_ids, n_real = engine.stream_sampling(0)
        assert n_real == 3 and len(fed_ids) == 4  # padded to tile 2 devs
        Xs, ys, ns = engine.stream.get_train(fed_ids, n_real)
        assert len(Xs.sharding.device_set) == 2
        assert int(jax.device_get(ns)[-1]) == 0  # pad client weighs 0
        result = engine.train()
        assert np.isfinite(result["final_global"]["loss"])
    finally:
        engine.stream.close()
