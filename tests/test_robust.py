"""Robust aggregation: norm-diff clipping + weak-DP noise
(robust_aggregation.py:28-55 parity) and the Byzantine-client scenario from
BASELINE.json's robustness config."""

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core import robust
from neuroimagedisttraining_tpu.utils import pytree as pt
import pytest


def _tree(rng, scale=1.0):
    return {"w": jnp.asarray(rng.normal(size=(4, 3)) * scale, jnp.float32),
            "b": jnp.asarray(rng.normal(size=(5,)) * scale, jnp.float32)}


def test_clip_noop_inside_bound():
    rng = np.random.default_rng(0)
    g = _tree(rng)
    local = pt.tree_add(g, pt.tree_scale(pt.tree_ones_like(g), 1e-3))
    out = robust.norm_diff_clip(local, g, norm_bound=5.0)
    for k in g:
        np.testing.assert_allclose(np.asarray(out[k]), np.asarray(local[k]),
                                   rtol=1e-6)


def test_clip_bounds_update_norm():
    rng = np.random.default_rng(1)
    g = _tree(rng)
    local = pt.tree_add(g, _tree(rng, scale=100.0))
    out = robust.norm_diff_clip(local, g, norm_bound=2.0)
    norm = float(pt.tree_norm(pt.tree_sub(out, g)))
    assert abs(norm - 2.0) < 1e-4  # clipped exactly to the bound
    # direction preserved: clipped diff parallel to raw diff
    raw = pt.tree_vector(pt.tree_sub(local, g))
    clp = pt.tree_vector(pt.tree_sub(out, g))
    cos = float(jnp.vdot(raw, clp) / (jnp.linalg.norm(raw)
                                      * jnp.linalg.norm(clp)))
    assert cos > 0.9999


def test_byzantine_client_neutralized():
    """One client ships a 100x-norm update; with clipping the aggregate stays
    near the honest mean, without it the aggregate is dragged away."""
    rng = np.random.default_rng(2)
    g = _tree(rng)
    honest = [pt.tree_add(g, _tree(rng, scale=0.1)) for _ in range(3)]
    byz = pt.tree_add(g, _tree(rng, scale=100.0))
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *(honest + [byz]))
    w = jnp.ones((4,), jnp.float32)

    plain = pt.tree_weighted_mean(stacked, w)
    defended = pt.tree_weighted_mean(
        robust.defend_stacked(stacked, g, defense="norm_diff_clipping",
                              norm_bound=1.0, stddev=0.0), w)
    honest_mean = pt.tree_weighted_mean(
        jax.tree.map(lambda *xs: jnp.stack(xs), *honest),
        jnp.ones((3,), jnp.float32))

    err_plain = float(pt.tree_norm(pt.tree_sub(plain, honest_mean)))
    err_def = float(pt.tree_norm(pt.tree_sub(defended, honest_mean)))
    assert err_def < 1.0
    assert err_plain > 10 * err_def


def test_weak_dp_noise_statistics():
    g = {"w": jnp.zeros((200, 200), jnp.float32)}
    out = robust.add_weak_dp_noise(g, jax.random.key(0), stddev=0.05)
    got = np.asarray(out["w"])
    assert abs(got.std() - 0.05) < 0.005
    assert abs(got.mean()) < 0.005


def test_defense_unknown_raises():
    g = {"w": jnp.zeros((2,), jnp.float32)}
    stacked = jax.tree.map(lambda x: jnp.stack([x, x]), g)
    try:
        robust.defend_stacked(stacked, g, defense="madeup_defense",
                              norm_bound=1.0, stddev=0.0)
        raise AssertionError("should have raised")
    except ValueError:
        pass
    # order-statistic names are now VALID defense names — they pass
    # through defend_stacked untouched (aggregation-time dispatch)
    out = robust.defend_stacked(stacked, g, defense="krum", norm_bound=1.0,
                                stddev=0.0)
    for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(stacked)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_fedavg_with_defense_runs(tmp_path, synthetic_cohort):
    from tests.test_fedavg import _make_engine

    engine = _make_engine(tmp_path, synthetic_cohort,
                          defense_type="weak_dp", norm_bound=5.0,
                          stddev=0.01)
    result = engine.train()
    assert np.isfinite(result["history"][-1]["train_loss"])


@pytest.mark.slow  # tier-1 window (PR 7): heavy twin/artifact test, core pin covered by a lighter tier-1 sibling
def test_fedavg_round_clipping_bounds_byzantine_update(tmp_path,
                                                       synthetic_cohort):
    """Engine-level: poison one client's data so its gradients explode;
    with norm_diff_clipping the post-round global moves a bounded distance
    from the init, without it the aggregate is dragged far away."""
    import jax.numpy as jnp

    from tests.test_fedavg import _make_engine

    def poisoned_round(engine):
        engine._donate = False  # gs.params is reread after the dispatch
        gs = engine.init_global_state()
        data = engine.data
        # client 0's labels adversarially flipped + inputs scaled: huge
        # gradients (the Byzantine update), honest clients unchanged
        Xb = data.X_train.at[0].set(255)
        yb = data.y_train.at[0].set(1 - data.y_train[0])
        data = data.replace(X_train=Xb, y_train=yb)
        sampled = jnp.asarray(engine.client_sampling(0))
        rngs = engine.per_client_rngs(0, np.asarray(sampled))
        params, _, _, _ = engine._round_jit(
            gs.params, gs.batch_stats, data, sampled, rngs,
            jnp.float32(0.5))  # big lr amplifies the poison
        return float(pt.tree_norm(pt.tree_sub(params, gs.params)))

    drift_plain = poisoned_round(_make_engine(tmp_path, synthetic_cohort))
    drift_clip = poisoned_round(_make_engine(
        tmp_path, synthetic_cohort, defense_type="norm_diff_clipping",
        norm_bound=0.5))
    # every clipped client update has norm <= 0.5, so the weighted mean
    # cannot drift farther than the bound
    assert drift_clip <= 0.5 + 1e-4
    assert drift_plain > drift_clip
