"""Round-driver dispatch tests (ISSUE 4): buffer donation + fused windows.

Three contracts:

(a) Donation is value-transparent: a round program with ``donate_argnums``
    produces bitwise-identical outputs to the same program without it
    (donation changes buffer residency, never math) — fedavg, the
    salientgrads flagship, and ditto's dual-track round.
(b) The fused multi-round driver (``--rounds_per_dispatch K``) is
    bitwise-identical to the sequential loop: params, batch_stats and the
    logged metrics of a K-fused run equal the K=1 run for
    fedavg/fedprox/salientgrads at K in {1, 2, 4}, including a frac < 1
    sampled config and a checkpoint-resume that lands mid-window.
(c) Engines/modes that cross the host each round fall back to one round
    per dispatch WITH a logged reason (streaming, fedfomo, the
    distributed CLI) — and still train.
"""

import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.config import (
    DataConfig, ExperimentConfig, FedConfig, OptimConfig,
)
from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
from neuroimagedisttraining_tpu.data import partition as P
from neuroimagedisttraining_tpu.data.federate import federate_cohort
from neuroimagedisttraining_tpu.data.stream import StreamingFederation
from neuroimagedisttraining_tpu.engines import create_engine
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger


def _engine(tmp_path, cohort, algorithm="fedavg", K=1, comm_round=4,
            freq=4, donate=True, tag="d", val_fraction=0.0, stream=False,
            checkpoint_dir="", checkpoint_every=0, **fed_kw):
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm=algorithm,
        data=DataConfig(dataset="synthetic", partition_method="site",
                        val_fraction=val_fraction),
        optim=OptimConfig(lr=1e-3, batch_size=8, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=comm_round,
                      frequency_of_the_test=freq, rounds_per_dispatch=K,
                      **fed_kw),
        checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
        log_dir=str(tmp_path), tag=tag)
    mesh = make_mesh()
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1),
                           cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    if stream:
        train_map, test_map, _ = P.site_partition(cohort["site"], seed=42)
        feed = StreamingFederation(np.asarray(cohort["X"]),
                                   np.asarray(cohort["y"]),
                                   train_map, test_map, mesh=mesh)
        eng = create_engine(algorithm, cfg, None, trainer, mesh=mesh,
                            logger=log, stream=feed)
    else:
        fed, _ = federate_cohort(cohort, partition_method="site",
                                 mesh=mesh, val_fraction=val_fraction)
        eng = create_engine(algorithm, cfg, fed, trainer, mesh=mesh,
                            logger=log)
    eng._donate = donate
    return eng


def _assert_trees_bitwise(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# (a) donated == undonated, bitwise
# ---------------------------------------------------------------------------

def _one_round_outputs(eng):
    """One dispatched round of ``eng``'s program from a fresh init (each
    caller builds its own engine: donation consumes the inputs)."""
    gs = eng.init_global_state()
    sampled = eng.client_sampling(0)
    rngs = eng.per_client_rngs(0, sampled)
    lr = eng.round_lr(0)
    if eng.name in ("fedavg", "fedprox"):
        return eng._round_jit(gs.params, gs.batch_stats, eng.data,
                              jnp.asarray(sampled), rngs, lr)
    if eng.name == "salientgrads":
        masks, _ = eng.generate_global_mask(gs.params, gs.batch_stats)
        per = eng.broadcast_states(gs, eng.num_clients)
        return eng._round_jit(gs.params, gs.batch_stats, per.params,
                              per.batch_stats, eng.data, masks,
                              jnp.asarray(sampled), rngs, lr)
    if eng.name == "ditto":
        per = eng.broadcast_states(gs, eng.num_clients)
        return eng._round_jit(gs.params, gs.batch_stats, per.params,
                              per.batch_stats, eng.data,
                              jnp.asarray(sampled), rngs, lr)
    raise AssertionError(eng.name)


@pytest.mark.parametrize("algorithm", [
    "fedavg",
    # tier-1 870s window (PR 7/11 precedent): the fedavg twin keeps the
    # donation pin; the stacked-state variants ride the full suite
    pytest.param("salientgrads", marks=pytest.mark.slow),
    pytest.param("ditto", marks=pytest.mark.slow),
])
def test_donated_round_bitwise_equals_undonated(tmp_path, synthetic_cohort,
                                                algorithm):
    out_d = _one_round_outputs(
        _engine(tmp_path, synthetic_cohort, algorithm, donate=True,
                tag="don"))
    out_u = _one_round_outputs(
        _engine(tmp_path, synthetic_cohort, algorithm, donate=False,
                tag="und"))
    _assert_trees_bitwise(out_d, out_u)


def test_donated_inputs_are_consumed(tmp_path, synthetic_cohort):
    """The donation is real, not decorative: after a donated dispatch the
    input buffers are deleted (reading one raises), while the undonated
    program leaves them alive — the exact failure mode the
    donation-use-after-donate lint rule guards the drivers against."""
    eng = _engine(tmp_path, synthetic_cohort, "fedavg", donate=True,
                  tag="cons")
    gs = eng.init_global_state()
    sampled = eng.client_sampling(0)
    eng._round_jit(gs.params, gs.batch_stats, eng.data,
                   jnp.asarray(sampled), eng.per_client_rngs(0, sampled),
                   eng.round_lr(0))
    leaf = jax.tree.leaves(gs.params)[0]
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(leaf)


# ---------------------------------------------------------------------------
# (b) K-fused scan == K sequential dispatches, bitwise
# ---------------------------------------------------------------------------

@pytest.mark.slow  # tier-1 870s window (PR 11, the PR 2/7 precedent): heavy twin rides the full suite; a lighter tier-1 sibling keeps the pin
def test_fused_driver_bitwise_equal_sequential_fedavg(tmp_path,
                                                      synthetic_cohort):
    """The full driver end to end: a K=4 fedavg run — windows planned
    around the eval cadence, hooks at boundaries — equals the K=1 run in
    params, batch_stats, metrics history, and final eval, bitwise.
    frac=0.5 keeps the per-round ``np.random.seed(round_idx)`` sampling
    contract load-bearing (different cohort each round); comm_round=4
    with eval every 4 rounds exercises a 1-round hooked window, a fused
    interior window, and the final boundary."""
    base = _engine(tmp_path, synthetic_cohort, "fedavg", K=1, frac=0.5,
                   tag="k1").train()
    fused = _engine(tmp_path, synthetic_cohort, "fedavg", K=4, frac=0.5,
                    tag="k4").train()
    _assert_trees_bitwise(base["params"], fused["params"])
    _assert_trees_bitwise(base["batch_stats"], fused["batch_stats"])
    assert base["history"] == fused["history"]
    assert base["final_global"] == fused["final_global"]


@pytest.mark.parametrize("algorithm", [
    "fedavg",
    # fedprox shares FedAvg's program shape (a prox op on top) — its
    # variant rides the full suite; tier-1 keeps the two distinct shapes
    pytest.param("fedprox", marks=pytest.mark.slow),
    pytest.param("salientgrads", marks=pytest.mark.slow),  # tier-1 window (PR 7): fedavg twin stays
])
def test_fused_program_bitwise_equal_sequential(tmp_path, synthetic_cohort,
                                                algorithm):
    """Program-level K sweep, every K in {1, 2, 4}: 4 rounds dispatched
    as four K=1 singles, two K=2 windows, and one K=4 window must yield
    bitwise-identical state and per-round losses (frac=0.5: the
    host-precomputed per-round sampling is load-bearing). Cheaper than
    full trains — the driver integration is pinned end-to-end by
    test_fused_driver_bitwise_equal_sequential_fedavg and the resume
    test below."""
    def init_state(eng):
        gs = eng.init_global_state()
        if algorithm == "salientgrads":
            masks, _ = eng.generate_global_mask(gs.params, gs.batch_stats)
            per = eng.broadcast_states(gs, eng.num_clients)
            return [gs.params, gs.batch_stats, per.params,
                    per.batch_stats], masks
        return [gs.params, gs.batch_stats], None

    # sequential reference: 4 single-round dispatches
    seq = _engine(tmp_path, synthetic_cohort, algorithm, K=1, frac=0.5,
                  tag="pseq")
    state, masks = init_state(seq)
    seq_losses = []
    for r in range(4):
        sampled = seq.client_sampling(r)
        rngs = seq.per_client_rngs(r, sampled)
        if algorithm == "salientgrads":
            out = seq._round_jit(*state[:2], *state[2:], seq.data, masks,
                                 jnp.asarray(sampled), rngs, seq.round_lr(r))
            state, loss = list(out[:4]), out[4]
        else:
            out = seq._round_jit(*state, seq.data, jnp.asarray(sampled),
                                 rngs, seq.round_lr(r))
            state, loss = list(out[:2]), out[2]
        seq_losses.append(float(loss))

    # fused: two K=2 windows, then (fresh state) one K=4 window — one
    # engine for both partitions (its jit caches persist; the state is
    # re-derived per partition because donation consumes it)
    fz = _engine(tmp_path, synthetic_cohort, algorithm, K=4, frac=0.5,
                 tag="pf")
    for windows in ([(0, 2), (2, 2)], [(0, 4)]):
        fstate, fmasks = init_state(fz)
        flosses = []
        for r0, k in windows:
            if algorithm == "salientgrads":
                (*fstate, _, loss, kk) = fz._run_fused_window(
                    *fstate, fmasks, r0, k)
            else:
                (*fstate, loss, kk) = fz._run_fused_window(*fstate, r0, k)
            assert kk == k
            flosses.append(float(loss))
        assert flosses == [seq_losses[r0 + k - 1] for r0, k in windows]
        _assert_trees_bitwise(state, list(fstate))


def test_fused_window_planner_respects_hooks(tmp_path, synthetic_cohort):
    """Window lengths: hook rounds (eval cadence, checkpoints, the final
    round) always land on a window boundary, never inside one."""
    eng = _engine(tmp_path, synthetic_cohort, K=4, comm_round=10, freq=3,
                  tag="plan")
    # eval rounds: 0, 3, 6, 9 (freq=3) + last (9)
    assert eng._dispatch_window(0) == 1        # round 0 is hooked
    assert eng._dispatch_window(1) == 3        # [1, 2, 3] — 3 hooked, ends
    assert eng._dispatch_window(4) == 3        # [4, 5, 6]
    assert eng._dispatch_window(7) == 3        # [7, 8, 9]
    ck = _engine(tmp_path, synthetic_cohort, K=4, comm_round=10,
                 freq=10 ** 9, checkpoint_dir=str(tmp_path / "ck"),
                 checkpoint_every=2, tag="plan2")
    # round 0 is ALWAYS an eval round (0 % freq == 0 — same as the
    # sequential loop); checkpoints land after rounds 1, 3, 5, ...
    assert ck._dispatch_window(0) == 1
    assert ck._dispatch_window(1) == 1         # ckpt after round 1
    assert ck._dispatch_window(2) == 2         # [2, 3] — ckpt after 3
    free = _engine(tmp_path, synthetic_cohort, K=4, comm_round=10,
                   freq=10 ** 9, tag="plan3")
    assert free._dispatch_window(1) == 4       # nothing hooked: full K


@pytest.mark.slow
def test_fused_resume_mid_window_bitwise(tmp_path, synthetic_cohort):
    """A checkpoint-resume landing mid-window (start round not aligned to
    K) must reproduce the uninterrupted sequential run bitwise: windows
    re-plan from the resume round. (Full-suite tier: tier-1 covers the
    restored-state-into-donated-round path via test_checkpoint's K=1
    resume pins and the fused driver via the tests above; this is the
    composition of the two.)"""
    full = _engine(tmp_path, synthetic_cohort, "fedavg", K=1, comm_round=4,
                   freq=10 ** 9, tag="full").train()
    ck = str(tmp_path / "ck_resume")
    # partial K=4 run: rounds 0-1, checkpoint at round 1
    _engine(tmp_path, synthetic_cohort, "fedavg", K=4, comm_round=2,
            freq=10 ** 9, checkpoint_dir=ck, checkpoint_every=2,
            tag="part").train()
    # resume at round 2 — mid-window w.r.t. a K=4 alignment from round 0
    resumed = _engine(tmp_path, synthetic_cohort, "fedavg", K=4,
                      comm_round=4, freq=10 ** 9, checkpoint_dir=ck,
                      checkpoint_every=2, tag="res").train()
    _assert_trees_bitwise(full["params"], resumed["params"])
    _assert_trees_bitwise(full["batch_stats"], resumed["batch_stats"])


# ---------------------------------------------------------------------------
# (c) fallback-to-K=1 paths log and run
# ---------------------------------------------------------------------------

def _log_text(eng) -> str:
    with open(eng.log.log_path) as f:
        return f.read()


def test_streaming_falls_back_with_logged_reason(tmp_path,
                                                 synthetic_cohort):
    """Engines WITHOUT a fused streamed window body (ISSUE 10:
    ``supports_fused_streaming`` — salientgrads here) still collapse to
    K=1 under --streaming with the logged streaming reason; the fedavg
    family now fuses streamed windows instead (pinned below)."""
    eng = _engine(tmp_path, synthetic_cohort, "salientgrads", K=4,
                  comm_round=1, freq=1, stream=True, tag="stfall")
    try:
        assert "dispatching one round at a time" in _log_text(eng)
        assert "streaming" in _log_text(eng)
        result = eng.train()
        assert np.isfinite(result["history"][-1]["train_loss"])
    finally:
        eng.stream.close()


@pytest.mark.slow  # tier-1 870s window (PR 11, the PR 2/7 precedent): heavy twin rides the full suite; a lighter tier-1 sibling keeps the pin
def test_streaming_fedavg_fused_window_bitwise(tmp_path, synthetic_cohort):
    """The fused STREAMED driver (ISSUE 10): a K=4 streamed fedavg run —
    whole-window shard stacks prefetched, one lax.scan dispatch per
    window — equals the K=1 streamed loop bitwise in params,
    batch_stats, and metrics history (frac=0.5 keeps the per-round
    sampling contract load-bearing)."""
    base = _engine(tmp_path, synthetic_cohort, "fedavg", K=1, comm_round=4,
                   freq=4, frac=0.5, stream=True, tag="swk1")
    fused = _engine(tmp_path, synthetic_cohort, "fedavg", K=4, comm_round=4,
                    freq=4, frac=0.5, stream=True, tag="swk4")
    try:
        assert fused.fused_fallback_reason() is None
        r1 = base.train()
        r4 = fused.train()
    finally:
        base.stream.close()
        fused.stream.close()
    _assert_trees_bitwise(r1["params"], r4["params"])
    _assert_trees_bitwise(r1["batch_stats"], r4["batch_stats"])
    assert r1["history"] == r4["history"]


def test_fedfomo_falls_back_with_logged_reason(tmp_path, synthetic_cohort):
    eng = _engine(tmp_path, synthetic_cohort, "fedfomo", K=4, comm_round=1,
                  freq=1, val_fraction=0.25, tag="fomofall")
    assert "dispatching one round at a time" in _log_text(eng)
    result = eng.train()
    assert np.isfinite(result["history"][-1]["train_loss"])


def test_wire_codec_falls_back_with_logged_reason(tmp_path,
                                                  synthetic_cohort):
    eng = _engine(tmp_path, synthetic_cohort, "fedavg", K=4, comm_round=1,
                  freq=1, wire_codec="delta+quant", tag="codecfall")
    assert "dispatching one round at a time" in _log_text(eng)
    assert "wire_codec" in _log_text(eng)


def test_distributed_cli_logs_dispatch_collapse(capsys):
    """The cross-silo runner accepts --rounds_per_dispatch for config
    parity and announces the per-round collapse before doing anything
    else (here the run is then stopped by an unrelated usage error, so
    no sockets are opened)."""
    from neuroimagedisttraining_tpu.distributed import run as drun

    assert drun.dispatch_fallback_note(1) is None
    note = drun.dispatch_fallback_note(3)
    assert "one round at a time" in note
    with pytest.raises(SystemExit):
        drun.main(["--role", "aggregator", "--num_clients", "1",
                   "--rounds_per_dispatch", "3"])
    assert "one round at a time" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# persistent compile cache (--compile_cache / NIDT_COMPILE_CACHE)
# ---------------------------------------------------------------------------

def test_compile_cache_resolution_order(monkeypatch, tmp_path):
    from neuroimagedisttraining_tpu.utils import compile_cache as cc

    monkeypatch.delenv("NIDT_COMPILE_CACHE", raising=False)
    # nothing specified anywhere + empty default -> disabled, config
    # untouched
    assert cc.enable_compile_cache(None, default="") is None
    # env fallback only applies when the flag was not given
    monkeypatch.setenv("NIDT_COMPILE_CACHE", str(tmp_path / "env"))
    import jax

    prev = jax.config.jax_compilation_cache_dir
    try:
        assert cc.enable_compile_cache(None, default="") == \
            str(tmp_path / "env")
        assert cc.enable_compile_cache(str(tmp_path / "flag")) == \
            str(tmp_path / "flag")
        assert cc.enable_compile_cache("", default="") is None
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


@pytest.mark.slow
def test_compile_cache_writes_entries(tmp_path):
    """End-to-end smoke in a fresh process (the cache backend binds its
    directory at first use, so an in-process dir swap would test
    nothing): NIDT_COMPILE_CACHE alone routes compiles to disk."""
    cache = tmp_path / "cc"
    code = (
        "from neuroimagedisttraining_tpu.utils.compile_cache import "
        "enable_compile_cache\n"
        "import jax, jax.numpy as jnp\n"
        "assert enable_compile_cache(None, default='') is not None\n"
        "jax.config.update('jax_persistent_cache_min_compile_time_secs',"
        " 0.0)\n"
        "f = jax.jit(lambda x: jnp.tanh(x) @ x.T)\n"
        "f(jnp.ones((37, 53))).block_until_ready()\n"
    )
    subprocess.run(
        [sys.executable, "-c", code], check=True, timeout=300,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "NIDT_COMPILE_CACHE": str(cache),
             "PYTHONPATH": "."})
    assert any(p.name.endswith("-cache") for p in cache.iterdir())
