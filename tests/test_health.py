"""Training-health plane tests (ISSUE 15).

Four contracts:

(a) The in-dispatch stats leg is value-transparent: a round with
    ``--health_stats`` armed produces BITWISE-identical
    params/batch_stats to a disarmed round, at the same
    compiled-program/dispatch counts (no added device syncs) — and the
    armed leg composes with fused K-windows and cohort sharding at the
    same bitwise pins those planes carry.
(b) The anomaly-rule engine's full matrix: every comparator, window
    aggregation, severity, debounce path, label-subset selection
    (worker labels included), histogram p99 evaluation, NaN semantics,
    startup validation against the declared-name set, JSON manifests.
(c) The seeded divergence scenario: a 1-of-4 sign-flip silo fires the
    client-divergence rule (nidt_alert sample, flight ``alert`` event,
    critical health block, nonzero --health_gate exit) while the clean
    twin stays green; run_report joins both runs into artifacts that
    differ in the alert timeline.
(d) The health-rule-discipline lint family: metric-name literals
    outside obs/ are findings; constants and obs/-internal literals
    are clean.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.analysis import lint_source
from neuroimagedisttraining_tpu.analysis.run_report import (
    build_report, read_metrics_jsonl, render_markdown,
)
from neuroimagedisttraining_tpu.analysis.run_report import (
    main as run_report_main,
)
from neuroimagedisttraining_tpu.config import (
    DataConfig, ExperimentConfig, FedConfig, OptimConfig,
)
from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
from neuroimagedisttraining_tpu.data.federate import federate_cohort
from neuroimagedisttraining_tpu.data.synthetic import (
    generate_synthetic_abcd,
)
from neuroimagedisttraining_tpu.engines import create_engine
from neuroimagedisttraining_tpu.engines import program as round_program
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import health as obs_health
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import names as N
from neuroimagedisttraining_tpu.obs import rules as obs_rules
from neuroimagedisttraining_tpu.obs.rules import HealthRule, RuleEngine
from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger


@pytest.fixture(scope="module")
def cohort64():
    """64 subjects over 4 sites: enough shared signal that honest site
    updates cohere (clean leave-one-out cosines land ~ +0.2..+0.4),
    which is what separates a sign-flip silo from ordinary non-IID
    noise."""
    return generate_synthetic_abcd(num_subjects=64, shape=(12, 14, 12),
                                   num_sites=4, seed=0)


def _engine(tmp_path, cohort, algorithm="fedavg", health=True, K=1,
            comm_round=2, freq=2, client_mesh=0, tag="h", seed=1024,
            metrics_out="", **fed_kw):
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm=algorithm,
        seed=seed,
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=OptimConfig(lr=1e-3, batch_size=8, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=comm_round,
                      frequency_of_the_test=freq,
                      rounds_per_dispatch=K, client_mesh=client_mesh,
                      **fed_kw),
        log_dir=str(tmp_path), tag=tag, health_stats=health,
        metrics_out=metrics_out)
    mesh = make_mesh()
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1),
                           cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    fed, _ = federate_cohort(cohort, partition_method="site", mesh=mesh)
    return create_engine(algorithm, cfg, fed, trainer, mesh=mesh,
                         logger=log)


def _bitwise(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _gauge_value(name, **labels):
    snap = obs_metrics.REGISTRY.snapshot().get(name)
    assert snap is not None, f"{name} not in registry"
    for cell in snap["values"]:
        if all(cell["labels"].get(k) == v for k, v in labels.items()):
            return cell["value"]
    raise AssertionError(f"{name}: no cell with {labels}: {snap}")


# ---------------------------------------------------------------------------
# (a) the in-dispatch stats leg
# ---------------------------------------------------------------------------


def test_update_stats_match_numpy_reference():
    """The traced stat math vs a straight numpy reimplementation —
    norms, leave-one-out cosine, dispersion, global norms."""
    rng = np.random.default_rng(3)
    C = 4
    up = {"params": {"w": jnp.asarray(rng.normal(size=(C, 5, 3)),
                                      jnp.float32)},
          "batch_stats": {}}
    ref = {"params": {"w": jnp.asarray(rng.normal(size=(5, 3)),
                                       jnp.float32)}, "batch_stats": {}}
    new = {"w": jnp.asarray(rng.normal(size=(5, 3)), jnp.float32)}
    w = jnp.asarray([3.0, 1.0, 2.0, 2.0], jnp.float32)
    out = round_program.health_update_stats(up, ref, new, w)

    d = (np.asarray(up["params"]["w"])
         - np.asarray(ref["params"]["w"])[None]).reshape(C, -1)
    agg = (np.asarray(new["w"])
           - np.asarray(ref["params"]["w"])).reshape(-1)
    norms = np.linalg.norm(d, axis=1)
    p = np.asarray(w) / np.sum(np.asarray(w))
    cos = np.empty(C)
    for i in range(C):
        loo = agg - p[i] * d[i]
        cos[i] = d[i] @ loo / (norms[i] * np.linalg.norm(loo))
    np.testing.assert_allclose(np.asarray(out["h_up_norms"]), norms,
                               rtol=1e-5)
    np.testing.assert_allclose(float(out["h_cos_min"]), cos.min(),
                               rtol=1e-4)
    np.testing.assert_allclose(float(out["h_cos_mean"]), cos.mean(),
                               rtol=1e-4)
    np.testing.assert_allclose(float(out["h_disp"]),
                               norms.max() / np.median(norms),
                               rtol=1e-5)
    np.testing.assert_allclose(float(out["h_agg_up"]),
                               np.linalg.norm(agg), rtol=1e-5)
    np.testing.assert_allclose(
        float(out["h_gnorm"]),
        np.linalg.norm(np.asarray(new["w"]).ravel()), rtol=1e-5)


def test_mask_health_stats():
    old = {"w": jnp.asarray([[1, 1, 1, 0], [1, 1, 0, 0]], jnp.float32)}
    new = {"w": jnp.asarray([[1, 1, 0, 0], [1, 0, 0, 0]], jnp.float32)}
    out = round_program.mask_health_stats(new, old)
    assert float(out["h_mask_density"]) == pytest.approx(3 / 8)
    assert float(out["h_mask_overlap"]) == pytest.approx(3 / 5)
    assert float(out["h_mask_churn"]) == pytest.approx(2 / 5)
    static = round_program.mask_health_stats(new, None)
    assert float(static["h_mask_overlap"]) == 1.0
    assert float(static["h_mask_churn"]) == 0.0


def test_armed_vs_disarmed_bitwise_same_counts(tmp_path, cohort64):
    """The acceptance pin: armed rounds are bitwise-identical to
    disarmed rounds at the SAME compiled-program and dispatch counts
    (the health leg adds outputs, never syncs or dispatches)."""
    off = _engine(tmp_path, cohort64, health=False, tag="off")
    on = _engine(tmp_path, cohort64, health=True, tag="on")
    r_off = off.train()
    r_on = on.train()
    _bitwise(r_off["params"], r_on["params"])
    _bitwise(r_off["batch_stats"], r_on["batch_stats"])
    assert [h["train_loss"] for h in r_off["history"]] == \
        [h["train_loss"] for h in r_on["history"]]
    assert on.program.built == off.program.built
    assert on.program.dispatches == off.program.dispatches
    # and the armed run actually published the health series
    assert _gauge_value(N.HEALTH_COSINE_MIN, engine="fedavg") is not None
    assert _gauge_value(N.HEALTH_ROUND, engine="fedavg") == 1.0


def test_fused_k4_matches_k1_with_health_armed(tmp_path, cohort64):
    r1 = _engine(tmp_path, cohort64, health=True, K=1, comm_round=4,
                 freq=4, tag="k1").train()
    e4 = _engine(tmp_path, cohort64, health=True, K=4, comm_round=4,
                 freq=4, tag="k4")
    r4 = e4.train()
    _bitwise(r1["params"], r4["params"])
    _bitwise(r1["batch_stats"], r4["batch_stats"])
    # the fused window drained per-round health rows up to the boundary
    assert _gauge_value(N.HEALTH_ROUND, engine="fedavg") == 3.0


def test_sharded_with_health_armed(tmp_path, cohort64):
    """Cohort-sharding composition: arming the stats leg changes
    NOTHING on the sharded path (bitwise vs the disarmed sharded
    round), and the sharded-vs-sequential pin holds with health armed
    at the cohort plane's own tolerance (the ~1-ulp compile-context
    residue, tests/test_cohort.py — sharded is not bitwise vs
    sequential even without health)."""
    sh_off = _engine(tmp_path, cohort64, health=False, client_mesh=8,
                     tag="shoff").train()
    shr = _engine(tmp_path, cohort64, health=True, client_mesh=8,
                  tag="shr")
    sh_on = shr.train()
    _bitwise(sh_off["params"], sh_on["params"])
    _bitwise(sh_off["batch_stats"], sh_on["batch_stats"])
    seq = _engine(tmp_path, cohort64, health=True, client_mesh=8,
                  tag="seq")
    seq._cohort_sequential = True
    rs = seq.train()
    for x, y in zip(jax.tree.leaves(rs["params"]),
                    jax.tree.leaves(sh_on["params"])):
        np.testing.assert_allclose(np.asarray(x, np.float64),
                                   np.asarray(y, np.float64),
                                   rtol=1e-6, atol=1e-8)


def test_metrics_jsonl_sink_round_seq(tmp_path, cohort64):
    """ISSUE 15 satellite: one JSONL record per round with monotonic
    round/seq join keys, health gauges inside."""
    path = str(tmp_path / "m.jsonl")
    _engine(tmp_path, cohort64, health=True, comm_round=3, freq=1,
            tag="sink", metrics_out=path).train()
    recs = read_metrics_jsonl(path)
    assert [r["round"] for r in recs] == [0, 1, 2]
    assert [r["seq"] for r in recs] == [1, 2, 3]
    assert all(r["engine"] == "fedavg" for r in recs)
    snap = recs[-1]["metrics"]
    assert N.HEALTH_COSINE_MIN in snap
    assert N.STAT in snap


def test_subavg_mask_health_stats(tmp_path, cohort64):
    _engine(tmp_path, cohort64, algorithm="subavg", health=True,
            comm_round=1, freq=1, tag="sub").train()
    dens = _gauge_value(N.HEALTH_MASK_DENSITY, engine="subavg")
    churn = _gauge_value(N.HEALTH_MASK_CHURN, engine="subavg")
    assert 0.0 <= dens <= 1.0
    assert 0.0 <= churn <= 1.0


def test_mask_density_publishes_from_nnz_boundary(tmp_path, cohort64):
    """dispfl-style engines publish density from the existing
    warn_if_masks_collapsed nnz fetch (no new sync)."""
    eng = _engine(tmp_path, cohort64, health=False, tag="nnz")
    masks = {"w": jnp.ones((4, 10), jnp.float32).at[:, 5:].set(0.0)}
    nnz = eng.warn_if_masks_collapsed(masks, round_idx=7)
    assert (nnz == 5).all()
    assert _gauge_value(N.HEALTH_MASK_DENSITY,
                        engine="fedavg") == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# (b) the rule-engine matrix
# ---------------------------------------------------------------------------


def _snap(value, metric=N.HEALTH_COSINE_MIN, kind="gauge", labels=None):
    return {metric: {"kind": kind, "help": "",
                     "values": [{"labels": labels or {},
                                 "value": value}]}}


def _rule(**kw):
    base = dict(name="r", metric=N.HEALTH_COSINE_MIN, op="<",
                threshold=-0.2)
    base.update(kw)
    return HealthRule(**base)


def test_rule_validation_matrix():
    with pytest.raises(ValueError, match="unknown metric"):
        RuleEngine([_rule(metric="nidt_not_a_thing")])
    with pytest.raises(ValueError, match="comparator"):
        RuleEngine([_rule(op="~")])
    with pytest.raises(ValueError, match="window"):
        RuleEngine([_rule(window="p50")])
    with pytest.raises(ValueError, match="severity"):
        RuleEngine([_rule(severity="fatal")])
    with pytest.raises(ValueError, match="aggregation"):
        RuleEngine([_rule(agg="median")])
    with pytest.raises(ValueError, match=">= 1"):
        RuleEngine([_rule(for_rounds=0)])
    with pytest.raises(ValueError, match="delta"):
        RuleEngine([_rule(window="delta", n=1)])
    with pytest.raises(ValueError, match="declared twice"):
        RuleEngine([_rule(), _rule()])
    # the error names the known-names list
    try:
        RuleEngine([_rule(metric="nidt_zzz")])
    except ValueError as e:
        assert N.HEALTH_COSINE_MIN in str(e)


@pytest.mark.parametrize("op,value,thr,fires", [
    (">", 2.0, 1.0, True), (">", 1.0, 1.0, False),
    (">=", 1.0, 1.0, True), ("<", 0.5, 1.0, True),
    ("<", 1.5, 1.0, False), ("<=", 1.0, 1.0, True),
    ("==", 3.0, 3.0, True), ("==", 3.1, 3.0, False),
    ("!=", 3.1, 3.0, True), ("!=", 3.0, 3.0, False),
])
def test_comparator_matrix(op, value, thr, fires):
    eng = RuleEngine([_rule(op=op, threshold=thr)])
    eng.observe(0, _snap(value))
    assert eng.health_block()["firing"] == ({"r": "warn"} if fires
                                            else {})


def test_nan_never_fires():
    for op in obs_rules.OPS:
        eng = RuleEngine([_rule(op=op, threshold=0.0)])
        eng.observe(0, _snap(float("nan")))
        assert eng.health_block()["status"] == "ok", op


def test_window_aggregations():
    vals = [1.0, 5.0, 3.0]
    for window, expect in (("last", 3.0), ("mean", 3.0), ("max", 5.0),
                           ("min", 1.0), ("delta", 2.0)):
        eng = RuleEngine([_rule(op="==", threshold=expect,
                                window=window, n=3)])
        for r, v in enumerate(vals):
            eng.observe(r, _snap(v))
        assert eng.health_block()["firing"], window


def test_debounce_for_rounds_and_clear():
    eng = RuleEngine([_rule(for_rounds=2)])
    eng.observe(0, _snap(-0.5))
    assert eng.health_block()["status"] == "ok"  # 1 of 2
    eng.observe(1, _snap(-0.5))
    assert eng.health_block()["status"] == "degraded"  # debounced fire
    eng.observe(2, _snap(0.5))
    assert eng.health_block()["status"] == "ok"  # cleared
    assert eng.health_block()["worst_status"] == "degraded"  # sticky
    v = eng.verdict()
    assert v["alerts_total"] == 1
    kinds = [e["kind"] for e in v["timeline"]]
    assert kinds == ["alert", "alert_clear"]
    assert [e["round"] for e in v["timeline"]] == [1, 2]


def test_missing_metric_resets_debounce():
    eng = RuleEngine([_rule(for_rounds=2)])
    eng.observe(0, _snap(-0.5))
    eng.observe(1, {})  # no samples: not an anomaly, debounce resets
    eng.observe(2, _snap(-0.5))
    assert eng.health_block()["status"] == "ok"


def test_severity_critical_and_rounds_dedupe():
    eng = RuleEngine([_rule(severity="critical")])
    eng.observe(3, _snap(-0.5))
    assert eng.health_block()["status"] == "critical"
    # re-observing an already-evaluated round is a no-op
    assert eng.observe(3, _snap(0.5)) == []
    assert eng.health_block()["status"] == "critical"
    assert eng.health_block()["rounds_evaluated"] == 1


def test_label_subset_match_fires_on_worker_series():
    eng = RuleEngine([_rule(metric=N.SELECTOR_CONNECTIONS, op=">",
                            threshold=10.0)])
    snap = _snap(50.0, metric=N.SELECTOR_CONNECTIONS,
                 labels={"worker": "2"})
    eng.observe(0, snap)
    assert eng.health_block()["firing"] == {"r": "warn"}


def test_cell_aggregations_across_labels():
    cells = [{"labels": {"engine": "a"}, "value": 1.0},
             {"labels": {"engine": "b"}, "value": 9.0}]
    snap = {N.HEALTH_DIVERGENCE: {"kind": "gauge", "help": "",
                                  "values": cells}}
    for agg, expect in (("max", 9.0), ("min", 1.0), ("sum", 10.0)):
        eng = RuleEngine([_rule(metric=N.HEALTH_DIVERGENCE, op="==",
                                threshold=expect, agg=agg)])
        eng.observe(0, snap)
        assert eng.health_block()["firing"], agg


def test_histogram_rules_evaluate_p99():
    cell = {"count": 100, "sum": 0.0,
            "buckets": {"1": 50, "2": 40, "4": 9, "8": 1, "+Inf": 0}}
    snap = {N.ASYNC_STALENESS: {"kind": "histogram", "help": "",
                                "values": [{"labels": {},
                                            "value": cell}]}}
    eng = RuleEngine([_rule(metric=N.ASYNC_STALENESS, op=">",
                            threshold=3.0)])
    eng.observe(0, snap)
    # p99 lands in the (2, 4] bucket, interpolated to 4.0 at the 99th
    assert eng.health_block()["firing"]


def test_alert_gauge_published_even_when_green():
    obs_metrics.REGISTRY.reset()
    eng = RuleEngine([_rule(name="quiet")])
    eng.observe(0, _snap(0.9))
    assert _gauge_value(N.ALERT, rule="quiet", severity="warn") == 0.0


def test_flight_ring_carries_alert_edges():
    obs_flight.clear()
    eng = RuleEngine([_rule(name="edgy")])
    eng.observe(0, _snap(-0.9))
    eng.observe(1, _snap(0.9))
    kinds = [(e["kind"], e.get("rule")) for e in obs_flight.events()
             if e["kind"].startswith("alert")]
    assert kinds == [("alert", "edgy"), ("alert_clear", "edgy")]


def test_load_rules_manifest(tmp_path):
    p = tmp_path / "rules.json"
    p.write_text(json.dumps([
        {"name": "m1", "metric": N.HEALTH_DIVERGENCE, "op": ">",
         "threshold": 5, "labels": {"engine": "fedavg"},
         "severity": "critical", "for_rounds": 2}]))
    rules = obs_rules.load_rules(str(p))
    assert rules[0].labels == (("engine", "fedavg"),)
    assert rules[0].for_rounds == 2
    p.write_text(json.dumps([{"name": "x", "metric": "nidt_zzz",
                              "op": ">", "threshold": 1}]))
    with pytest.raises(ValueError, match="unknown metric"):
        RuleEngine(obs_rules.load_rules(str(p)))
    p.write_text(json.dumps([{"metric": N.MFU}]))
    with pytest.raises(ValueError, match="missing required"):
        obs_rules.load_rules(str(p))
    p.write_text(json.dumps([{"name": "x", "metric": N.MFU, "op": ">",
                              "threshold": 1, "frobnicate": True}]))
    with pytest.raises(ValueError, match="unknown fields"):
        obs_rules.load_rules(str(p))
    p.write_text(json.dumps({"not": "a list"}))
    with pytest.raises(ValueError, match="JSON list"):
        obs_rules.load_rules(str(p))


def test_builtin_manifest_validates_and_budget_rules():
    base = obs_rules.builtin_rules()
    RuleEngine(base)  # every built-in name is declared
    names = {r.name for r in base}
    assert "client-divergence" in names
    assert "dp-budget-exceeded" not in names
    with_budget = obs_rules.builtin_rules(dp_epsilon_budget=4.0,
                                          comm_round=100)
    names_b = {r.name for r in with_budget}
    assert {"dp-budget-exceeded", "dp-burn-rate"} <= names_b
    burn = next(r for r in with_budget if r.name == "dp-burn-rate")
    assert burn.threshold == pytest.approx(2.0 * 4.0 / 100)


def test_example_manifest_action_bindings():
    """The shipped example manifest (scripts/health_rules.example.json)
    must load, validate, and carry reflex-action bindings whose names
    resolve in obs/actions.py BUILTIN_ACTIONS (ISSUE 20): the manifest
    is both operator documentation and the action-discipline lint's
    cross-file fixture."""
    from neuroimagedisttraining_tpu.obs import actions as obs_actions

    rules = obs_rules.load_rules(
        os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                     "health_rules.example.json"))
    RuleEngine(rules)  # metrics declared, actions resolve, no dupes
    bound = {r.name: r.action for r in rules if r.action}
    assert bound == {
        "update-blowup-rollback-example": "freeze_rollback",
        "divergence-quarantine-example": "quarantine_silo"}
    assert set(bound.values()) <= set(obs_actions.BUILTIN_ACTIONS)


def test_configure_manifest_overrides_builtin(tmp_path):
    p = tmp_path / "rules.json"
    p.write_text(json.dumps([
        {"name": "client-divergence", "metric": N.HEALTH_COSINE_MIN,
         "op": "<", "threshold": -0.9}]))
    try:
        eng = obs_rules.configure(manifest_path=str(p))
        rule = next(r for r in eng.rules
                    if r.name == "client-divergence")
        assert rule.threshold == -0.9
        assert obs_rules.active() is eng
    finally:
        obs_rules.disarm()
    assert obs_rules.active() is None
    assert obs_rules.observe_boundary(0) == []
    assert obs_rules.health_block() == {"status": "unarmed"}


# ---------------------------------------------------------------------------
# (c) the seeded divergence scenario + run report
# ---------------------------------------------------------------------------

_BYZ = "byz:1@0:sign_flip,byz:1@1:sign_flip,byz:1@2:sign_flip"


def test_sign_flip_fires_divergence_clean_twin_green(tmp_path,
                                                     cohort64):
    """Engine-level acceptance: the sign-flip run fires
    client-divergence (alert gauge, flight event, critical block); the
    clean twin stays ok on the same config."""
    obs_flight.clear()
    try:
        obs_rules.configure()
        _engine(tmp_path, cohort64, health=True, comm_round=1, freq=1,
                tag="clean").train()
        assert obs_rules.health_block()["status"] == "ok"
        assert _gauge_value(N.ALERT, rule="client-divergence",
                            severity="critical") == 0.0
        clean_verdict = obs_rules.active().verdict()
        assert clean_verdict["alerts_total"] == 0
    finally:
        obs_rules.disarm()
    try:
        obs_rules.configure()
        _engine(tmp_path, cohort64, health=True, comm_round=1, freq=1,
                tag="byz", fault_spec=_BYZ).train()
        block = obs_rules.health_block()
        assert block["status"] == "critical"
        assert block["firing"].get("client-divergence") == "critical"
        assert _gauge_value(N.ALERT, rule="client-divergence",
                            severity="critical") == 1.0
        verdict = obs_rules.active().verdict()
        assert verdict["alerts_total"] >= 1
        assert any(e["rule"] == "client-divergence"
                   for e in verdict["timeline"])
    finally:
        obs_rules.disarm()
    alerts = [e for e in obs_flight.events() if e["kind"] == "alert"]
    assert any(e["rule"] == "client-divergence" for e in alerts)


def test_cli_health_gate_end_to_end(tmp_path, cohort64):
    """The CLI acceptance criterion: --health_gate exits nonzero on the
    sign-flip run and 0 on the clean twin; both write gate-passing
    run_report artifacts whose alert timelines differ."""
    from neuroimagedisttraining_tpu.__main__ import main

    argv = ["--algorithm", "fedavg", "--dataset", "synthetic",
            "--model", "3dcnn_tiny", "--synthetic_num_subjects", "64",
            "--synthetic_shape", "12", "14", "12",
            "--client_num_in_total", "4", "--comm_round", "1",
            "--batch_size", "8", "--epochs", "1", "--lr", "1e-3",
            "--seed", "0", "--log_dir", str(tmp_path),
            "--health_stats", "--health_gate"]
    rc_clean = main(argv + ["--tag", "cli_clean", "--metrics_out",
                            str(tmp_path / "clean.jsonl")])
    assert rc_clean == 0
    rc_byz = main(argv + ["--tag", "cli_byz", "--metrics_out",
                          str(tmp_path / "byz.jsonl"),
                          "--fault_spec", "byz:1@0:sign_flip"])
    assert rc_byz != 0

    def verdict_path(tag):
        (p,) = [os.path.join(tmp_path, "synthetic", f)
                for f in os.listdir(tmp_path / "synthetic")
                if tag in f and f.endswith(".health.json")]
        return p

    reports = {}
    for tag, metrics in (("cli_clean", "clean.jsonl"),
                         ("cli_byz", "byz.jsonl")):
        out = tmp_path / ("report_" + tag)
        assert run_report_main([
            "--metrics", str(tmp_path / metrics),
            "--verdict", verdict_path(tag), "--out", str(out)]) == 0
        reports[tag] = json.load(open(out / "run_report.json"))
        assert (out / "run_report.md").exists()
    clean, byz = reports["cli_clean"], reports["cli_byz"]
    assert clean["summary"]["schema_ok"] and byz["summary"]["schema_ok"]
    assert clean["summary"]["worst_status"] == "ok"
    assert byz["summary"]["worst_status"] == "critical"
    assert clean["alerts"] == []
    assert any(e["rule"] == "client-divergence" for e in byz["alerts"])


def test_run_report_build_join():
    recs = [
        {"round": 0, "seq": 1, "metrics": {
            N.EXP_METRIC: {"kind": "gauge", "help": "", "values": [
                {"labels": {"key": "train_loss"}, "value": 0.9}]},
            N.HEALTH_COSINE_MIN: {"kind": "gauge", "help": "",
                                  "values": [{"labels":
                                              {"engine": "fedavg"},
                                              "value": 0.3}]}}},
        {"round": 1, "seq": 2, "metrics": {
            N.DP_EPSILON: {"kind": "gauge", "help": "", "values": [
                {"labels": {"source": "weak_dp"}, "value": 1.5}]},
            N.DP_EPSILON_PER_ROUND: {
                "kind": "gauge", "help": "", "values": [
                    {"labels": {"source": "weak_dp"}, "value": 0.2}]},
            N.FALLBACK_TOTAL: {"kind": "counter", "help": "",
                               "values": [{"labels": {
                                   "plane": "fused",
                                   "engine": "fedavg",
                                   "reason": "no-fused-body"},
                                   "value": 1.0}]}}},
    ]
    verdict = {"status": "ok", "worst_status": "degraded",
               "alerts_total": 1,
               "timeline": [{"kind": "alert", "rule": "x",
                             "severity": "warn", "round": 1,
                             "value": 2.0}]}
    flight = {"capacity": 8, "evicted": 0, "events": [
        {"kind": "alert", "rule": "x", "severity": "warn", "round": 1},
        {"kind": "accept", "client": 2}]}
    rep = build_report(recs, flight, verdict)
    assert rep["summary"]["rounds"] == 2
    assert rep["summary"]["worst_status"] == "degraded"
    assert rep["rounds"][0]["train_loss"] == 0.9
    assert rep["rounds"][0]["cos_min"] == 0.3
    assert rep["epsilon_ledger"]["sources"]["weak_dp"] == {
        "epsilon": 1.5, "epsilon_per_round": 0.2}
    assert rep["dispatch"]["fallbacks"][0]["reason"] == "no-fused-body"
    # the flight alert deduped against the verdict's (same key)
    assert len(rep["alerts"]) == 1
    md = render_markdown(rep)
    assert "## Alert timeline" in md and "`x`" in md


# ---------------------------------------------------------------------------
# (d) /healthz blocks + the lint family
# ---------------------------------------------------------------------------


def test_fallback_block_shape():
    round_program.report_fallback("fedavg", "no-fused-body")
    block = obs_health.fallback_block()
    assert block["total"] >= 1
    assert block["by_plane"].get("fused", 0) >= 1
    rows = [r for r in block["announcements"]
            if r["reason"] == "no-fused-body"]
    assert rows and rows[0]["engine"] == "fedavg"


def test_health_metric_literal_lint_fires_outside_obs():
    findings = lint_source(
        'from neuroimagedisttraining_tpu.obs import metrics as m\n'
        'g = m.gauge("nidt_health_cosine_min", "h")\n',
        path="neuroimagedisttraining_tpu/engines/whatever.py")
    ids = [f.rule for f in findings]
    assert "health-metric-literal" in ids


def test_health_metric_literal_lint_clean_cases():
    # prose mentioning a metric is not a full-match literal
    assert not lint_source(
        'x = "the nidt_mfu gauge"\n',
        path="neuroimagedisttraining_tpu/engines/whatever.py")
    # the constant spelling is the blessed one
    assert not lint_source(
        'from neuroimagedisttraining_tpu.obs import names as n\n'
        'name = n.MFU + "_bucket"\n',
        path="neuroimagedisttraining_tpu/engines/whatever.py")
    # obs/ is the declaration side — exempt
    assert not lint_source(
        'g = ("nidt_mfu",)\n',
        path="neuroimagedisttraining_tpu/obs/compute.py")


def test_declared_set_covers_builtin_rules_and_health_names():
    for r in obs_rules.builtin_rules(dp_epsilon_budget=1.0):
        assert r.metric in N.DECLARED
    for name in (N.HEALTH_COSINE_MIN, N.ALERT, N.RECOMPILES_TOTAL,
                 N.DP_EPSILON_PER_ROUND):
        assert name in N.DECLARED
