"""North-star scale: 100 clients / frac 0.1 on the 8-device virtual mesh
(VERDICT r4 #2).

The reference's own jobs run 100 clients with frac 0.1
(fedml_experiments/standalone/sailentgrads/Jobs/sailentgradsjob.sh:39-51);
BASELINE.json's metric is "@100 clients". These tests run that SHAPE —
clients ≫ devices (13 stacked per core), frac-sampled subsets (10) that do
NOT tile the 8-device grid, resident AND streaming — end-to-end on the
virtual mesh: fedavg, the salientgrads flagship, and dispfl.

Client count is exact via the reference's cross-silo rescale partition
(load_partition_data_abcd_rescale, ABCD/data_loader.py:216-315): merge all
sites, contiguous-slice into 100 equal shards.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.config import (
    DataConfig, ExperimentConfig, FedConfig, OptimConfig,
)
from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
from neuroimagedisttraining_tpu.data import partition as P
from neuroimagedisttraining_tpu.data.federate import (
    DATA_SPLIT_SEED, federate_cohort,
)
from neuroimagedisttraining_tpu.data.stream import StreamingFederation
from neuroimagedisttraining_tpu.data.synthetic import generate_synthetic_abcd
from neuroimagedisttraining_tpu.engines import create_engine
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

C = 100


@pytest.fixture(scope="module")
def scale_cohort():
    return generate_synthetic_abcd(num_subjects=500, shape=(12, 14, 12),
                                   num_sites=20, seed=0)


def _cfg(tmp_path, algorithm, **fed_kw):
    return ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm=algorithm,
        data=DataConfig(dataset="synthetic", partition_method="rescale"),
        optim=OptimConfig(lr=1e-3, batch_size=4, epochs=1),
        fed=FedConfig(**{"client_num_in_total": C, "frac": 0.1,
                         "comm_round": 2, "frequency_of_the_test": 1,
                         **fed_kw}),
        log_dir=str(tmp_path))


def _scale_engine(tmp_path, cohort, algorithm, streaming=False, **fed_kw):
    # these tests replay ONE init state through resident and streamed
    # programs to compare outputs; buffer donation (ISSUE 4) would delete
    # the shared buffers at the first dispatch, so it is off here (the
    # donated path is pinned bitwise in tests/test_dispatch.py)
    cfg = _cfg(tmp_path, algorithm, **fed_kw)
    mesh = make_mesh()
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1),
                           cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    if streaming:
        train_map, test_map = P.rescale_partition(
            len(cohort["y"]), C, seed=DATA_SPLIT_SEED)
        stream = StreamingFederation(np.asarray(cohort["X"]),
                                     np.asarray(cohort["y"]),
                                     train_map, test_map, mesh=mesh)
        eng = create_engine(algorithm, cfg, None, trainer, mesh=mesh,
                            logger=log, stream=stream)
        eng._donate = False
        return eng
    fed, _ = federate_cohort(cohort, partition_method="rescale",
                             client_number=C, mesh=mesh)
    eng = create_engine(algorithm, cfg, fed, trainer, mesh=mesh,
                        logger=log)
    eng._donate = False
    return eng


@pytest.mark.slow  # tier-1 window (PR 7): heavy twin/artifact test, core pin covered by a lighter tier-1 sibling
def test_fedavg_100clients_resident(tmp_path, scale_cohort):
    engine = _scale_engine(tmp_path, scale_cohort, "fedavg")
    assert engine.real_clients == C
    assert engine.num_clients == 104  # padded to tile the 8-device mesh
    # reference sampling contract at the north-star shape
    sampled = engine.client_sampling(0)
    np.random.seed(0)
    want = np.sort(np.random.choice(range(C), 10, replace=False))
    np.testing.assert_array_equal(sampled, want)
    result = engine.train()
    assert len(result["history"]) == 2
    for h in result["history"]:
        assert np.isfinite(h["train_loss"])
    assert np.isfinite(result["final_global"]["loss"])


@pytest.mark.slow
def test_fedavg_100clients_streaming_matches_resident(tmp_path,
                                                      scale_cohort):
    """The streamed padded round (10 real + 6 zero-weight pads to tile the
    mesh) equals the resident 10-client round, and the full streamed run
    executes."""
    res = _scale_engine(tmp_path, scale_cohort, "fedavg")
    st = _scale_engine(tmp_path, scale_cohort, "fedavg", streaming=True)
    try:
        gs = res.init_global_state()
        sampled = res.client_sampling(0)
        p_res, b_res, l_res, _ = res._round_jit(
            gs.params, gs.batch_stats, res.data, jnp.asarray(sampled),
            res.per_client_rngs(0, sampled), res.round_lr(0))

        fed_ids, n_real = st.stream_sampling(0)
        assert n_real == 10 and len(fed_ids) == 16  # padded to tile 8
        np.testing.assert_array_equal(fed_ids[:10], sampled)
        Xs, ys, ns = st.stream.get_train(fed_ids, n_real)
        assert int(np.sum(np.asarray(jax.device_get(ns)) > 0)) == 10
        p_st, b_st, l_st, _ = st._round_stream_jit(
            gs.params, gs.batch_stats, Xs, ys, ns,
            st.per_client_rngs(0, fed_ids), st.round_lr(0))
        np.testing.assert_allclose(float(l_res), float(l_st), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(p_res), jax.tree.leaves(p_st)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)
        result = st.train()
        assert np.isfinite(result["final_global"]["loss"])
    finally:
        st.stream.close()


@pytest.mark.slow
def test_salientgrads_100clients_resident_and_streaming(tmp_path,
                                                        scale_cohort):
    """The flagship at the north-star shape: phase-1 over all 100 clients,
    masked rounds over the 10-sampled subset; personal state of unsampled
    clients (and mesh pads) must be untouched by the guarded scatter."""
    engine = _scale_engine(tmp_path, scale_cohort, "salientgrads",
                           comm_round=1)
    gs = engine.init_global_state()
    masks, _ = engine.generate_global_mask(gs.params, gs.batch_stats)
    per = engine.broadcast_states(gs, engine.num_clients)
    sampled = engine.client_sampling(0)
    out = engine._round_jit(
        gs.params, gs.batch_stats, per.params, per.batch_stats,
        engine.data, masks, jnp.asarray(sampled),
        engine.per_client_rngs(0, sampled), engine.round_lr(0))
    assert np.isfinite(float(out[4]))  # out[4] = mean loss
    new_per = out[2]
    leaf0 = jax.tree.leaves(per.params)[0]
    new_leaf0 = jax.tree.leaves(new_per)[0]
    sampled_set = set(sampled.tolist())
    changed = [c for c in range(engine.num_clients)
               if not np.allclose(np.asarray(leaf0[c]),
                                  np.asarray(new_leaf0[c]))]
    assert set(changed) <= sampled_set  # only sampled clients moved
    assert changed  # and the sampled ones actually trained

    stream_engine = _scale_engine(tmp_path, scale_cohort, "salientgrads",
                                  streaming=True, comm_round=1)
    try:
        # duplicate-pad regression (r5 review): the streaming federation
        # has no mesh-pad clients (num_clients == 100), so ALL six pad
        # entries are DUPLICATES of sampled[-1]; the dropped-pad scatter
        # must leave sampled[-1]'s trained row intact, so the streamed
        # round's personal state equals the resident round's
        fed_ids, n_real = stream_engine.stream_sampling(0)
        assert len(fed_ids) == 16 and n_real == 10
        assert (fed_ids[10:] == sampled[-1]).all()  # the duplicates
        Xs, ys, ns = stream_engine.stream.get_train(fed_ids, n_real)
        per_st = stream_engine.broadcast_states(
            gs, stream_engine.num_clients)  # 100 rows: no mesh pads here
        out_st = stream_engine._round_stream_jit(
            gs.params, gs.batch_stats, per_st.params, per_st.batch_stats,
            Xs, ys, ns, masks, jnp.asarray(fed_ids),
            stream_engine.per_client_rngs(0, fed_ids),
            stream_engine.round_lr(0))
        for a, b in zip(jax.tree.leaves(new_per),
                        jax.tree.leaves(out_st[2])):
            np.testing.assert_allclose(np.asarray(a)[:C], np.asarray(b)[:C],
                                       atol=1e-6)
        result = stream_engine.train()
        assert np.isfinite(result["history"][-1]["train_loss"])
        assert result["mask_density"] == pytest.approx(0.5, abs=0.02)
    finally:
        stream_engine.stream.close()


@pytest.mark.slow  # tier-1 870s window (PR 11, the PR 2/7 precedent): per-engine streamed==resident e2e twins ride the full suite; the fedavg/salientgrads/local siblings + the streamed machinery tests keep tier-1 coverage
def test_ditto_100clients_streamed_round_matches_resident(tmp_path,
                                                          scale_cohort):
    """Ditto's guarded personal-state scatter + n-weighted aggregation
    under the padded streamed feed (6 duplicate pads) must equal the
    resident 10-client round."""
    res = _scale_engine(tmp_path, scale_cohort, "ditto")
    st = _scale_engine(tmp_path, scale_cohort, "ditto", streaming=True)
    try:
        gs = res.init_global_state()
        per = res.broadcast_states(gs, res.num_clients)
        sampled = res.client_sampling(0)
        out_res = res._round_jit(
            gs.params, gs.batch_stats, per.params, per.batch_stats,
            res.data, jnp.asarray(sampled),
            res.per_client_rngs(0, sampled), res.round_lr(0))

        fed_ids, n_real = st.stream_sampling(0)
        assert n_real == 10 and len(fed_ids) == 16
        assert (fed_ids[10:] == sampled[-1]).all()  # duplicate pads
        Xs, ys, ns = st.stream.get_train(fed_ids, n_real)
        per_st = st.broadcast_states(gs, st.num_clients)
        out_st = st._round_stream_jit(
            gs.params, gs.batch_stats, per_st.params, per_st.batch_stats,
            Xs, ys, ns, jnp.asarray(fed_ids),
            st.per_client_rngs(0, fed_ids), st.round_lr(0))
        # global params + loss
        for a, b in zip(jax.tree.leaves(out_res[0]),
                        jax.tree.leaves(out_st[0])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)
        np.testing.assert_allclose(float(out_res[-1]), float(out_st[-1]),
                                   rtol=1e-6)
        # personal stacks (first 100 rows; resident carries 4 mesh pads)
        for a, b in zip(jax.tree.leaves(out_res[2]),
                        jax.tree.leaves(out_st[2])):
            np.testing.assert_allclose(np.asarray(a)[:C],
                                       np.asarray(b)[:C], atol=1e-6)
    finally:
        st.stream.close()


@pytest.mark.slow  # tier-1 870s window (PR 11, the PR 2/7 precedent): per-engine streamed==resident e2e twins ride the full suite; the fedavg/salientgrads/local siblings + the streamed machinery tests keep tier-1 coverage
def test_subavg_100clients_streamed_round_matches_resident(tmp_path,
                                                           scale_cohort):
    """Sub-FedAvg's count-based aggregation and mask scatter explicitly
    mask pad contributions; the padded streamed round must equal the
    resident one (aggregate, masks, loss, accept stats)."""
    res = _scale_engine(tmp_path, scale_cohort, "subavg")
    st = _scale_engine(tmp_path, scale_cohort, "subavg", streaming=True)
    try:
        from neuroimagedisttraining_tpu.ops.masks import ones_mask

        gs = res.init_global_state()
        masks_res = res.broadcast_states(ones_mask(gs.params),
                                         res.num_clients)
        masks_st = st.broadcast_states(ones_mask(gs.params),
                                       st.num_clients)
        sampled = res.client_sampling(0)
        out_res = res._round_jit(
            gs.params, gs.batch_stats, masks_res, res.data,
            jnp.asarray(sampled), res.per_client_rngs(0, sampled),
            res.round_lr(0))

        fed_ids, n_real = st.stream_sampling(0)
        assert n_real == 10 and len(fed_ids) == 16
        assert (fed_ids[10:] == sampled[-1]).all()  # duplicate pads
        Xs, ys, ns = st.stream.get_train(fed_ids, n_real)
        out_st = st._round_stream_jit(
            gs.params, gs.batch_stats, masks_st, Xs, ys, ns,
            jnp.asarray(fed_ids), st.per_client_rngs(0, fed_ids),
            st.round_lr(0))
        # aggregated params AND batch_stats (independent pad-masked
        # reductions in engines/subavg.py)
        for i in (0, 1):
            for a, b in zip(jax.tree.leaves(out_res[i]),
                            jax.tree.leaves(out_st[i])):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           atol=1e-6)
        for a, b in zip(jax.tree.leaves(out_res[2]),
                        jax.tree.leaves(out_st[2])):
            np.testing.assert_array_equal(np.asarray(a)[:C],
                                          np.asarray(b)[:C])
        # loss / mean mask dist / accepts / uplink nnz all pad-clean
        for i in (3, 4, 5, 6):
            np.testing.assert_allclose(float(out_res[i]),
                                       float(out_st[i]), rtol=1e-6)
    finally:
        st.stream.close()


@pytest.mark.slow
def test_dispfl_100clients_consensus_path_and_round(tmp_path,
                                                    scale_cohort):
    """DisPFL at 100 clients: the reference-default random adjacency at
    frac 0.1 (10 neighbors) is dense relative to 13 clients/device, so
    the plan machinery must choose the einsum; at 3 neighbors the routed
    sparse all_to_all engages. One full round executes at the
    north-star shape either way."""
    from neuroimagedisttraining_tpu.parallel.gossip import SparseSpec

    engine = _scale_engine(tmp_path, scale_cohort, "dispfl", cs="random",
                           comm_round=1)
    A = engine.adjacency(0, engine.active_draw(0))
    plan, _ = engine.gossip_plan(A)
    # 10 neighbors over 13 rows/device: per-pair padded slots reach a
    # full block, so the sparse plan must decline and the engine takes
    # the dense einsum
    assert plan is None

    sparse_engine = _scale_engine(tmp_path, scale_cohort, "dispfl",
                                  cs="random", frac=0.03, comm_round=1)
    picked = []
    for r in range(5):
        A = sparse_engine.adjacency(r, sparse_engine.active_draw(r))
        p, _ = sparse_engine.gossip_plan(A)
        picked.append(isinstance(p, SparseSpec))
    assert any(picked), (
        "3 random neighbors over 13 clients/device never took the routed "
        "sparse path across 5 rounds")

    result = sparse_engine.train()
    assert np.isfinite(result["history"][-1]["train_loss"])
