"""Model zoo shape/semantics tests.

Validates layer parity facts derived from the reference: AlexNet3D_Dropout's
flatten width is 256 on the real 121x145x121 ABCD volume
(salient_models.py:171 Linear(256, 64)), CNN_OriginalFedAvg matches the
FedAvg-paper parameter count (cnn.py:13-28), etc.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.models import (
    AlexNet3D_Dropout,
    AlexNet3D_Deeper_Dropout,
    AlexNet3D_Dropout_Regression,
    ResNet3D_l3,
    CNN_OriginalFedAvg,
    create_model,
    primary_logits,
)
from neuroimagedisttraining_tpu.utils.pytree import tree_size


def _init_and_apply(model, x, train=False):
    rngs = {"params": jax.random.key(0), "dropout": jax.random.key(1)}
    variables = model.init(rngs, x, train=False)
    out, mutated = model.apply(
        variables, x, train=train,
        rngs={"dropout": jax.random.key(2)} if train else None,
        mutable=["batch_stats"] if train else [],
    )
    return variables, out, mutated


def _shapes_only(model, x_shape):
    """Initialize abstractly (no FLOPs) — full ABCD volumes are too slow for
    real CPU conv3d in unit tests."""
    x = jax.ShapeDtypeStruct(x_shape, jnp.float32)
    rngs = {"params": jax.random.key(0), "dropout": jax.random.key(1)}
    return jax.eval_shape(lambda: model.init(rngs, jnp.zeros(x_shape),
                                             train=False))


def test_alexnet3d_flatten_width_matches_reference_on_abcd_shape():
    # Reference hard-codes Linear(256, 64) after flatten (salient_models.py:171);
    # check our pool/conv arithmetic reproduces 256 features on 121x145x121.
    variables = _shapes_only(AlexNet3D_Dropout(num_classes=1),
                             (1, 121, 145, 121, 1))
    assert variables["params"]["fc1"]["kernel"].shape[0] == 256


def test_alexnet3d_train_mode_updates_batch_stats():
    model = AlexNet3D_Dropout(num_classes=1)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 69, 69, 69, 1)),
                    jnp.float32)
    variables, out, mutated = _init_and_apply(model, x, train=True)
    assert out.shape == (2, 1)
    old = variables["batch_stats"]["f0"]["bn"]["mean"]
    new = mutated["batch_stats"]["f0"]["bn"]["mean"]
    assert not np.allclose(np.asarray(old), np.asarray(new))


def test_alexnet3d_deeper_flatten_width_512():
    # flatten width 512 parity (salient_models.py:227 Linear(512, 64))
    variables = _shapes_only(AlexNet3D_Deeper_Dropout(num_classes=2),
                             (1, 121, 145, 121, 1))
    assert variables["params"]["fc1"]["kernel"].shape[0] == 512


def test_alexnet3d_regression_returns_pred_and_features():
    model = AlexNet3D_Dropout_Regression(num_classes=1)
    x = jnp.zeros((3, 69, 69, 69, 1))
    _, out, _ = _init_and_apply(model, x)
    pred, feat = out
    assert pred.shape == (3,)
    assert feat.ndim == 5


def test_resnet3d_l3_runs():
    model = ResNet3D_l3(layers=(1, 1, 1), num_classes=2)
    x = jnp.zeros((1, 49, 57, 49, 1))
    _, out, _ = _init_and_apply(model, x)
    logits, penult = out
    assert logits.shape == (1, 2)
    assert penult.shape == (1, 512)


def test_cnn_original_fedavg_param_count():
    model = CNN_OriginalFedAvg(only_digits=True)
    x = jnp.zeros((1, 28, 28))
    variables, out, _ = _init_and_apply(model, x)
    # 1,663,370 params reported in the FedAvg paper (cnn.py:13-40).
    assert tree_size(variables["params"]) == 1_663_370
    assert out.shape == (1, 10)


@pytest.mark.parametrize("name,shape,nc", [
    ("resnet18", (1, 32, 32, 3), 10),
    ("tiny_resnet18", (1, 64, 64, 3), 200),
    ("resnet18_ip", (1, 32, 32, 3), 10),
    ("vgg11", (1, 32, 32, 3), 10),
    ("cnn_cifar10", (1, 32, 32, 3), 10),
    ("cnn_cifar10_bn", (1, 32, 32, 3), 10),
    ("cnn_cifar100", (1, 32, 32, 3), 100),
    ("lenet5", (1, 28, 28, 1), 10),
    ("lenet5_cifar", (1, 32, 32, 3), 10),
    ("cnn_dropout", (1, 28, 28, 1), 10),
])
def test_registry_models_forward(name, shape, nc):
    model = create_model(name, num_classes=nc)
    x = jnp.zeros(shape)
    _, out, _ = _init_and_apply(model, x)
    assert primary_logits(out).shape == (shape[0], nc)


def test_norm_variants_have_no_running_stats():
    """GN-3D and resnet_ip variants must carry NO batch_stats collection —
    GroupNorm is stat-free and IP-norm never tracks (resnet_ip semantics,
    track_running_stats=False). The 3D variant is shape-checked lazily at
    the real ABCD shape (the full AlexNet3D stack needs >= ~41^3 inputs)."""
    import jax

    # resnet18_ip: real forward at CIFAR shape
    model = create_model("resnet18_ip", num_classes=2)
    variables, out, _ = _init_and_apply(model, jnp.zeros((1, 32, 32, 3)))
    assert primary_logits(out).shape == (1, 2)
    assert not jax.tree.leaves(dict(variables).get("batch_stats", {}))

    # 3dcnn_gn: eval_shape at ABCD scale (no compute)
    m3 = create_model("3dcnn_gn", num_classes=2)
    variables = jax.eval_shape(
        lambda: m3.init({"params": jax.random.key(0),
                         "dropout": jax.random.key(1)},
                        jnp.zeros((1, 121, 145, 121, 1)), train=False))
    assert not jax.tree.leaves(dict(variables).get("batch_stats", {}))
    # GN params exist where BN params would have been
    assert "gn" in variables["params"]["f0"]


def test_lenet5_flatten_matches_caffe_5x5_to_4x4():
    # lenet5.py:18 hard-codes 50*4*4; verify our VALID conv/pool arithmetic.
    model = create_model("lenet5", num_classes=10)
    x = jnp.zeros((1, 28, 28, 1))
    variables, _, _ = _init_and_apply(model, x)
    assert variables["params"]["fc3"]["kernel"].shape[0] == 50 * 4 * 4
