"""Whole-program contract checker (analysis/project.py + contracts.py):
one seeded violation per contract family against synthetic fixture
trees, pragma mechanics on project findings, the finding cache, and the
tier-1 gates — `--project` exits 0 on the shipped tree forever."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from neuroimagedisttraining_tpu.analysis import lint_paths
from neuroimagedisttraining_tpu.analysis.cli import main as cli_main
from neuroimagedisttraining_tpu.analysis.project import (
    build_model,
    lint_project,
    regen_compat,
    rejection_rows,
    knob_vocabulary,
    render_matrix_py,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = "neuroimagedisttraining_tpu"


def make_tree(tmp_path, files):
    """Write a synthetic mini-package under tmp_path/pkg and return the
    (root, package) pair lint_project takes."""
    for rel, src in files.items():
        fp = tmp_path / "pkg" / rel
        fp.parent.mkdir(parents=True, exist_ok=True)
        fp.write_text(textwrap.dedent(src))
    return str(tmp_path), "pkg"


def project_rules(tmp_path, files, rules=None):
    root, pkg = make_tree(tmp_path, files)
    return [(f.rule, f.path) for f in lint_project(root, pkg, rules=rules)]


# ---------------- family 1: flag <-> config ----------------

FLAG_CONFIG_TREE = {
    "config.py": """
        import dataclasses

        @dataclasses.dataclass(frozen=True)
        class OptimConfig:
            lr: float = 0.01

        @dataclasses.dataclass(frozen=True)
        class ExperimentConfig:
            tag: str = "exp"
            hidden: int = 3
    """,
    "__main__.py": """
        import argparse

        from pkg.config import ExperimentConfig, OptimConfig

        def add_args(parser):
            parser.add_argument("--lr", type=float, default=0.01)
            parser.add_argument("--tag", type=str, default="test")
            parser.add_argument("--ghost", type=int, default=0)
            return parser

        def config_from_args(args):
            return ExperimentConfig(
                tag=args.tag,
                optim=OptimConfig(lr=args.lr))
    """,
}


def test_flag_config_catches_drifted_default_unmapped_flag_and_field(
        tmp_path):
    found = project_rules(tmp_path, FLAG_CONFIG_TREE)
    rules = [r for r, _ in found]
    assert "flag-config-default-drift" in rules     # tag: 'test' vs 'exp'
    assert "flag-config-unmapped-flag" in rules     # --ghost never consumed
    assert "flag-config-unmapped-field" in rules    # hidden not assignable


def test_flag_config_clean_when_in_lockstep(tmp_path):
    tree = dict(FLAG_CONFIG_TREE)
    tree["__main__.py"] = """
        import argparse

        from pkg.config import ExperimentConfig, OptimConfig

        def add_args(parser):
            parser.add_argument("--lr", type=float, default=0.01)
            parser.add_argument("--tag", type=str, default="exp")
            parser.add_argument("--hidden", type=int, default=3)
            return parser

        def config_from_args(args):
            return ExperimentConfig(
                tag=args.tag, hidden=args.hidden,
                optim=OptimConfig(lr=args.lr))
    """
    assert project_rules(tmp_path, tree) == []


def test_flag_config_wrapper_aware_default_comparison(tmp_path):
    """tuple()/not wrappers are applied to the argparse default before
    comparing, so list-vs-tuple and inverted store_true flags agree."""
    found = project_rules(tmp_path, {
        "config.py": """
            import dataclasses

            @dataclasses.dataclass(frozen=True)
            class ExperimentConfig:
                mesh_shape: tuple = (1, 1)
                snip: bool = True
        """,
        "__main__.py": """
            import argparse

            from pkg.config import ExperimentConfig

            def add_args(parser):
                parser.add_argument("--mesh_shape", type=int, nargs=2,
                                    default=[1, 1])
                parser.add_argument("--no_snip", action="store_true")
                return parser

            def config_from_args(args):
                return ExperimentConfig(
                    mesh_shape=tuple(args.mesh_shape),
                    snip=not args.no_snip)
        """,
    })
    assert found == []


def test_cross_cli_drift_and_pragma_suppression(tmp_path):
    run_py = """
        import argparse

        def main():
            ap = argparse.ArgumentParser()
            ap.add_argument("--lr", type=float, default=0.05)
            args = ap.parse_args()
            return args.lr
    """
    tree = dict(FLAG_CONFIG_TREE)
    tree["distributed/run.py"] = run_py
    found = project_rules(tmp_path, tree)
    assert ("flag-config-cross-cli-drift", "pkg/distributed/run.py") \
        in found
    # the standard pragma on the flagged line suppresses it
    tree["distributed/run.py"] = run_py.replace(
        'default=0.05)',
        'default=0.05)  '
        '# nidt: allow[flag-config-cross-cli-drift] -- smoke-scale')
    found2 = project_rules(tmp_path / "b", tree)
    assert ("flag-config-cross-cli-drift", "pkg/distributed/run.py") \
        not in found2


# ---------------- family 2: metric-name closure ----------------

METRIC_TREE = {
    "obs/names.py": """
        USED = "nidt_used_total"
        ORPHAN = "nidt_orphan_total"

        DECLARED = frozenset(
            v for k, v in list(globals().items()) if k.isupper())
    """,
    "train.py": """
        from pkg.obs import metrics as obs_metrics
        from pkg.obs import names as obs_names

        def arm():
            obs_metrics.counter(obs_names.USED, "ok")
            obs_metrics.counter(obs_names.MISSING, "undeclared attr")
            obs_metrics.gauge("nidt_rogue_total", "undeclared literal")
    """,
}


def test_metric_closure_catches_undeclared_and_orphan(tmp_path):
    found = project_rules(tmp_path, METRIC_TREE)
    undeclared = [(r, p) for r, p in found if r == "metric-undeclared"]
    assert ("metric-undeclared", "pkg/train.py") in undeclared
    # both the names.MISSING attr and the rogue literal are findings
    assert len(undeclared) >= 2
    assert ("metric-orphan", "pkg/obs/names.py") in found


def test_metric_closure_clean_when_closed(tmp_path):
    tree = dict(METRIC_TREE)
    tree["train.py"] = """
        from pkg.obs import metrics as obs_metrics
        from pkg.obs import names as obs_names

        def arm():
            obs_metrics.counter(obs_names.USED, "ok")
            obs_metrics.gauge(obs_names.ORPHAN, "now consumed")
    """
    assert project_rules(tmp_path, tree) == []


# ---------------- family 2b: REASONS + bench SPECS closures ----------------

def test_reason_closure_catches_unknown_and_orphan(tmp_path):
    found = project_rules(tmp_path, {
        "engines/program.py": """
            REASONS = {
                "used-key": ("host", "why"),
                "orphan-key": ("host", "why"),
            }

            def reason(key):
                return REASONS[key]
        """,
        "engines/base.py": """
            def _report(report_fallback):
                report_fallback("engine", "used-key")

            def thing_fallback_key():
                return "bogus-key"
        """,
    })
    assert ("reason-unknown", "pkg/engines/base.py") in found
    assert ("reason-orphan", "pkg/engines/program.py") in found
    assert ("reason-unknown", "pkg/engines/program.py") not in found


def test_bench_spec_closure_catches_unresolvable_cell(tmp_path):
    root, pkg = make_tree(tmp_path, {
        "analysis/bench_gate.py": """
            SPECS = {
                "art.json": (
                    Check("summary.ok", "min", 1, "resolves"),
                    Check("summary.gone", "min", 1, "does not"),
                ),
            }
        """,
    })
    bm = tmp_path / "bench_matrix"
    bm.mkdir()
    (bm / "art.json").write_text(json.dumps({"summary": {"ok": 2}}))
    found = [(f.rule, f.message) for f in lint_project(root, pkg)]
    assert len(found) == 1
    assert found[0][0] == "bench-spec-closure"
    assert "summary.gone" in found[0][1]


# ---------------- family 3: compat matrix as data ----------------

MATRIX_CLI = {
    "__main__.py": """
        import argparse

        def add_args(parser):
            parser.add_argument("--a_flag", type=int, default=0)
            parser.add_argument("--b_flag", type=int, default=0)
            return parser

        def main():
            parser = argparse.ArgumentParser()
            add_args(parser)
            args = parser.parse_args()
            if args.a_flag and args.b_flag:
                parser.error("--a_flag does not compose with --b_flag")
            return args
    """,
}


def test_compat_matrix_missing_artifact_is_drift(tmp_path):
    found = project_rules(tmp_path, MATRIX_CLI)
    assert ("compat-matrix-drift", "pkg/analysis/compat_matrix.py") \
        in found


def test_compat_matrix_regen_round_trips_clean(tmp_path):
    root, pkg = make_tree(tmp_path, MATRIX_CLI)
    regen_compat(root, pkg)
    assert lint_project(root, pkg) == []


def test_compat_matrix_stale_row_and_hand_edited_doc(tmp_path):
    root, pkg = make_tree(tmp_path, MATRIX_CLI)
    regen_compat(root, pkg)
    # a NEW rejection lands without regenerating -> drift at the site
    main_py = tmp_path / "pkg" / "__main__.py"
    main_py.write_text(main_py.read_text().replace(
        "return args",
        'if args.b_flag and not args.a_flag:\n'
        '        parser.error("--b_flag requires --a_flag")\n'
        '    return args'))
    rules = [f.rule for f in lint_project(root, pkg)]
    assert "compat-matrix-drift" in rules
    regen_compat(root, pkg)
    assert lint_project(root, pkg) == []
    # hand-editing the generated markdown twin is a finding of its own
    arch = tmp_path / "ARCHITECTURE.md"
    arch.write_text(arch.read_text().replace("`a_flag`", "`tweaked`"))
    rules = [f.rule for f in lint_project(root, pkg)]
    assert rules == ["compat-matrix-doc-stale"]
    # a REMOVED rejection makes the committed row stale in the other
    # direction
    regen_compat(root, pkg)
    row_src = main_py.read_text()
    main_py.write_text(row_src.replace(
        'parser.error("--b_flag requires --a_flag")', "pass"))
    found = [(f.rule, f.path) for f in lint_project(root, pkg)]
    assert ("compat-matrix-drift", "pkg/analysis/compat_matrix.py") \
        in found


def test_extraction_requires_two_knobs(tmp_path):
    """Single-knob range checks are validation, not compatibility."""
    root, pkg = make_tree(tmp_path, {
        "__main__.py": """
            import argparse

            def add_args(parser):
                parser.add_argument("--a_flag", type=int, default=0)
                return parser

            def main():
                parser = argparse.ArgumentParser()
                add_args(parser)
                args = parser.parse_args()
                if args.a_flag < 0:
                    parser.error("--a_flag must be >= 0")
                return args
        """,
    })
    model = build_model(root, pkg)
    assert rejection_rows(model, knob_vocabulary(model)) == []


def test_render_matrix_py_is_literal_eval_safe(tmp_path):
    root, pkg = make_tree(tmp_path, MATRIX_CLI)
    model = build_model(root, pkg)
    rows = rejection_rows(model, knob_vocabulary(model))
    assert rows, "fixture must extract at least one row"
    src = render_matrix_py(rows)
    import ast as ast_mod
    tree = ast_mod.parse(src)
    assign = next(n for n in tree.body
                  if isinstance(n, (ast_mod.Assign, ast_mod.AnnAssign)))
    parsed = ast_mod.literal_eval(assign.value)
    assert [dict(r, knobs=tuple(r["knobs"])) for r in parsed] == [
        {k: v for k, v in r.items() if not k.startswith("_")}
        for r in rows]


# ---------------- family 4: cross-module donation ----------------

DONATION_TREE = {
    "helpers.py": """
        import functools

        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _round_step(state, batch):
            return state + batch

        def apply_round(state, batch):
            return _round_step(state, batch)
    """,
    "driver.py": """
        from pkg.helpers import apply_round

        def drive(params, batch):
            new = apply_round(params, batch)
            return params + new
    """,
}


def test_xmodule_donation_catches_read_through_helper(tmp_path):
    found = project_rules(tmp_path, DONATION_TREE)
    assert ("donation-use-after-donate-xmodule", "pkg/driver.py") in found


def test_xmodule_donation_clean_when_rebound(tmp_path):
    tree = dict(DONATION_TREE)
    tree["driver.py"] = """
        from pkg.helpers import apply_round

        def drive(params, batch):
            params = apply_round(params, batch)
            return params
    """
    assert project_rules(tmp_path, tree) == []


def test_xmodule_donation_propagates_through_two_hops(tmp_path):
    """The summary fixed point follows helper -> helper -> jit."""
    tree = dict(DONATION_TREE)
    tree["middle.py"] = """
        from pkg.helpers import apply_round

        def relay(state, batch):
            return apply_round(state, batch)
    """
    tree["driver.py"] = """
        from pkg.middle import relay

        def drive(params, batch):
            new = relay(params, batch)
            return params + new
    """
    found = project_rules(tmp_path, tree)
    assert ("donation-use-after-donate-xmodule", "pkg/driver.py") in found


# ---------------- finding cache + changed-files ----------------

def test_cache_hit_equals_cold_run(tmp_path, monkeypatch):
    src = ("import numpy as np\n"
           "def f():\n"
           "    return np.random.rand()\n")
    target = tmp_path / "mod.py"
    target.write_text(src)
    cache = tmp_path / "cache"
    cold = lint_paths([str(target)], cache_dir=str(cache))
    assert [f.rule for f in cold] == ["determinism-global-random"]
    assert list(cache.glob("*.json")), "cold run must populate the cache"

    # the warm run must come from the cache: a parse now raises
    import neuroimagedisttraining_tpu.analysis.core as core

    def boom(*a, **k):
        raise AssertionError("cache miss: lint_source was called")

    monkeypatch.setattr(core, "lint_source", boom)
    warm = lint_paths([str(target)], cache_dir=str(cache))
    assert warm == cold
    monkeypatch.undo()

    # touching the content invalidates the entry
    target.write_text(src + "np.random.seed(1)\n")
    changed = lint_paths([str(target)], cache_dir=str(cache))
    assert sorted(f.rule for f in changed) == [
        "determinism-global-random", "determinism-global-random"]


def test_cache_key_covers_rule_selection(tmp_path):
    src = "import numpy as np\nnp.random.seed(1)\n"
    target = tmp_path / "mod.py"
    target.write_text(src)
    cache = tmp_path / "cache"
    full = lint_paths([str(target)], cache_dir=str(cache))
    narrowed = lint_paths([str(target)], cache_dir=str(cache),
                          rules=["determinism-unseeded-rng"])
    assert [f.rule for f in full] == ["determinism-global-random"]
    assert narrowed == []  # selection change must not replay 'full'


def test_cli_cache_and_changed_files_flags(tmp_path, capsys):
    target = tmp_path / "mod.py"
    target.write_text("import numpy as np\nnp.random.seed(1)\n")
    cache = tmp_path / "cache"
    assert cli_main([str(target), "--cache", str(cache)]) == 1
    capsys.readouterr()
    assert cli_main([str(target), "--cache", str(cache)]) == 1
    capsys.readouterr()
    # --changed-files outside any git checkout falls back to everything
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        rc = cli_main([str(target), "--changed-files"])
    finally:
        os.chdir(cwd)
    assert rc == 1


# ---------------- manifest validation (CLI) ----------------

def test_check_manifest_accepts_shipped_example(capsys):
    path = os.path.join(REPO_ROOT, "scripts", "health_rules.example.json")
    assert cli_main(["--check-manifest", path]) == 0


def test_check_manifest_rejects_undeclared_metric(tmp_path, capsys):
    bad = tmp_path / "rules.json"
    bad.write_text(json.dumps([{
        "name": "ghost", "metric": "nidt_ghost_metric",
        "op": ">", "threshold": 1}]))
    assert cli_main(["--check-manifest", str(bad)]) == 1
    err = capsys.readouterr().err
    assert "nidt_ghost_metric" in err


# ---------------- tier-1 gates on the shipped tree ----------------

def test_shipped_tree_project_pass_is_clean():
    """THE tier-1 gate: every cross-file contract holds (or carries a
    justified pragma) across the whole package, forever."""
    findings = lint_project(REPO_ROOT, PACKAGE)
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


def test_shipped_tree_project_clean_via_cli_subprocess():
    """Acceptance criterion verbatim: `--project` exits 0 on the tree."""
    proc = subprocess.run(
        [sys.executable, "-m", "neuroimagedisttraining_tpu.analysis",
         "--project"],
        capture_output=True, text=True, timeout=300, cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_committed_matrix_matches_fresh_extraction():
    """The committed artifact is diff-gated: a fresh extraction of
    today's tree must equal analysis/compat_matrix.py exactly."""
    from neuroimagedisttraining_tpu.analysis.compat_matrix import MATRIX

    model = build_model(REPO_ROOT, PACKAGE)
    rows = rejection_rows(model, knob_vocabulary(model))
    assert [
        {k: v for k, v in r.items() if not k.startswith("_")}
        for r in rows
    ] == [dict(r, knobs=tuple(r["knobs"])) for r in MATRIX]
    assert len(MATRIX) > 10, "the real tree has many rejection sites"


def test_project_rules_do_not_change_per_file_pass():
    """Registering the project families must not add per-file findings:
    a ProjectRule's check() is a no-op by contract."""
    from neuroimagedisttraining_tpu.analysis import RULE_REGISTRY
    from neuroimagedisttraining_tpu.analysis.project import ProjectRule

    project_families = [cls for cls in RULE_REGISTRY.values()
                        if issubclass(cls, ProjectRule)]
    assert len(project_families) >= 5
    mod_stub = object()
    for cls in project_families:
        assert list(cls().check(mod_stub)) == []
