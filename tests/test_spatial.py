"""Spatial (voxel) sharding: depth-sharded Conv3D with ppermute halo
exchange must equal the unsharded convolution (parallel/spatial.py — the
context-parallelism analog, SURVEY §5.7)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.parallel.spatial import (
    make_space_mesh, spatial_sharded_conv3d,
)


def _reference_conv(x, k, b):
    kd, kh, kw = k.shape[:3]
    out = jax.lax.conv_general_dilated(
        x, k, window_strides=(1, 1, 1),
        padding=[(kd // 2, kd // 2), (kh // 2, kh // 2), (kw // 2, kw // 2)],
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    return out if b is None else out + b


@pytest.mark.parametrize("kd,cin,cout", [(1, 1, 2), (3, 1, 4), (5, 2, 3)])
def test_depth_sharded_conv_matches_unsharded(kd, cin, cout):
    rng = np.random.default_rng(0)
    mesh = make_space_mesh(8)
    x = jnp.asarray(rng.normal(size=(2, 16, 6, 5, cin)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(kd, 3, 3, cin, cout)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(cout,)), jnp.float32)

    want = _reference_conv(x, k, b)
    got = spatial_sharded_conv3d(x, k, mesh, bias=b)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    # output really is depth-sharded over the 8 devices
    assert len(got.sharding.device_set) == 8
    assert not got.sharding.is_fully_replicated


def test_sharded_conv_contains_collective():
    rng = np.random.default_rng(1)
    mesh = make_space_mesh(8)
    x = jnp.asarray(rng.normal(size=(1, 16, 4, 4, 1)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(3, 3, 3, 1, 2)), jnp.float32)
    txt = jax.jit(
        lambda x, k: spatial_sharded_conv3d(x, k, mesh)
    ).lower(x, k).compile().as_text()
    assert "collective-permute" in txt, "halo exchange did not lower to ICI"


def test_rejects_bad_shapes():
    mesh = make_space_mesh(8)
    x = jnp.zeros((1, 12, 4, 4, 1))  # 12 % 8 != 0
    k = jnp.zeros((3, 3, 3, 1, 2))
    with pytest.raises(AssertionError, match="not divisible"):
        spatial_sharded_conv3d(x, k, mesh)
    x2 = jnp.zeros((1, 8, 4, 4, 1))  # 1 row/shard < halo 2
    k2 = jnp.zeros((5, 3, 3, 1, 2))
    with pytest.raises(AssertionError, match="halo"):
        spatial_sharded_conv3d(x2, k2, mesh)
