"""obs/: the unified telemetry plane (ISSUE 9).

Covers the span tracer (nesting containment, thread safety, Chrome
trace-event schema, disarmed no-op), the metrics registry (Prometheus
exposition scraped from a LIVE in-process endpoint, histogram bucket
math, idempotent registration, JSONL sink, disable switch), the flight
recorder (bounded ring, dump schema, failure_context and upload-audit
dump triggers), the ExperimentLogger handler-leak regression, and the
legacy-surface parity pins: registry values == ``byte_stats()`` /
``upload_stats`` / ``stat_info`` on live smoke federations (no double
counting — the counters increment in lockstep with the legacy dicts,
not from a second measurement).
"""

import json
import logging
import re
import threading
import urllib.request

import numpy as np
import pytest

from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import trace as obs_trace
from neuroimagedisttraining_tpu.obs.flight import FlightRecorder
from neuroimagedisttraining_tpu.obs.http import MetricsServer
from neuroimagedisttraining_tpu.obs.metrics import MetricsRegistry
from neuroimagedisttraining_tpu.obs.trace import SpanTracer


# ------------------------------------------------ span tracer


def test_span_nesting_containment(tmp_path):
    t = SpanTracer()
    t.arm(str(tmp_path / "t.json"), tags={"rank": 0})
    with t.span("outer", round=3):
        with t.span("inner"):
            pass
    doc = json.load(open(t.dump()))
    evs = doc["traceEvents"]
    outer = next(e for e in evs if e["name"] == "outer")
    inner = next(e for e in evs if e["name"] == "inner")
    # Chrome "X" events nest by time containment per tid — the property
    # Perfetto renders as parent/child
    assert outer["tid"] == inner["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]
    assert outer["args"] == {"rank": 0, "round": 3}


def test_span_thread_safety(tmp_path):
    t = SpanTracer()
    t.arm(str(tmp_path / "t.json"))
    N, MSPANS = 8, 50
    barrier = threading.Barrier(N)  # all alive together -> distinct
    # OS thread idents (a finished thread's ident is reusable)

    def worker(i):
        barrier.wait()
        for j in range(MSPANS):
            with t.span("w", thread=i, j=j):
                pass

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(N)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    evs = t.events()
    assert len(evs) == N * MSPANS
    # every event intact (no torn/interleaved records) and thread ids
    # distinguish the tracks
    assert {e["args"]["thread"] for e in evs} == set(range(N))
    assert len({e["tid"] for e in evs}) == N
    json.load(open(t.dump()))  # parses


def test_tracer_disarmed_is_free_noop():
    t = SpanTracer()
    s1 = t.span("a", x=1)
    s2 = t.span("b")
    # disarmed: the SAME shared no-op object — no per-span allocation
    assert s1 is s2
    with s1:
        pass
    t.instant("never")
    assert t.events() == []
    assert t.dump() is None  # no path armed


def test_tracer_buffer_bounded(tmp_path):
    """A multi-hour armed run must not grow host memory without bound:
    events past the cap are dropped and counted in the dump."""
    t = SpanTracer()
    t.arm(str(tmp_path / "t.json"), max_events=5)
    for i in range(9):
        with t.span("s", i=i):
            pass
    assert len(t.events()) == 5
    doc = json.load(open(t.dump()))
    assert len(doc["traceEvents"]) == 5
    assert doc["nidtDroppedEvents"] == 4


def test_chrome_trace_event_schema(tmp_path):
    t = SpanTracer()
    t.arm(str(tmp_path / "t.json"), tags={"role": "server"})
    with t.span("round", round=0):
        pass
    t.instant("mark", k="v")
    doc = json.load(open(t.dump()))
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    for e in doc["traceEvents"]:
        assert e["ph"] in ("X", "i")
        assert isinstance(e["name"], str)
        assert isinstance(e["ts"], float) and e["ts"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["args"]["role"] == "server"
        if e["ph"] == "X":
            assert isinstance(e["dur"], float) and e["dur"] >= 0


# ------------------------------------------------ metrics registry


def test_registry_idempotent_and_conflicts():
    reg = MetricsRegistry()
    c1 = reg.counter("x_total", "h", labelnames=("a",))
    c2 = reg.counter("x_total", "other help ignored", labelnames=("a",))
    assert c1 is c2
    with pytest.raises(ValueError, match="already registered as"):
        reg.gauge("x_total")
    with pytest.raises(ValueError, match="labels"):
        reg.counter("x_total", labelnames=("b",))
    with pytest.raises(ValueError, match="cannot decrease"):
        c1.inc(-1, a="1")
    with pytest.raises(ValueError, match="takes labels"):
        c1.inc(1)  # missing label
    # a histogram re-registered with DIFFERENT buckets must raise —
    # silently keeping the first spec would collapse the second
    # caller's range into +Inf with no signal
    reg.histogram("h", buckets=(1, 2))
    reg.histogram("h", buckets=(2, 1))  # same set, order-insensitive
    with pytest.raises(ValueError, match="buckets"):
        reg.histogram("h", buckets=(1, 10, 100))


def test_histogram_bucket_math():
    reg = MetricsRegistry()
    h = reg.histogram("lat", "h", buckets=(1.0, 2.0, 5.0))
    for v in (0.5, 1.0, 1.5, 2.0, 4.9, 100.0):
        h.observe(v)
    snap = reg.snapshot()["lat"]["values"][0]["value"]
    # le semantics: a value ON the bound lands IN that bucket
    assert snap["buckets"] == {"1": 2, "2": 2, "5": 1, "+Inf": 1}
    assert snap["count"] == 6
    assert snap["sum"] == pytest.approx(109.9)
    text = reg.prometheus_text()
    # exposition is CUMULATIVE per Prometheus histogram semantics
    assert 'lat_bucket{le="1"} 2' in text
    assert 'lat_bucket{le="2"} 4' in text
    assert 'lat_bucket{le="5"} 5' in text
    assert 'lat_bucket{le="+Inf"} 6' in text
    assert "lat_count 6" in text


def test_registry_disable_enable_switch():
    reg = MetricsRegistry()
    c = reg.counter("c_total")
    g = reg.gauge("g")
    h = reg.histogram("h", buckets=(1,))
    reg.disable()
    c.inc()
    g.set(5)
    h.observe(0.5)
    assert c.get() == 0 and g.get() == 0
    assert reg.snapshot()["h"]["values"] == []
    reg.enable()
    c.inc(2)
    assert c.get() == 2


def test_jsonl_sink(tmp_path):
    reg = MetricsRegistry()
    reg.counter("c_total").inc(3)
    p = str(tmp_path / "m.jsonl")
    reg.dump_jsonl(p, phase="a")
    reg.counter("c_total").inc()
    reg.dump_jsonl(p, phase="b")
    lines = [json.loads(ln) for ln in open(p)]
    assert len(lines) == 2
    assert lines[0]["phase"] == "a"
    assert lines[0]["metrics"]["c_total"]["values"][0]["value"] == 3
    assert lines[1]["metrics"]["c_total"]["values"][0]["value"] == 4


def test_nonfinite_values_render_canonically(tmp_path):
    """A NaN train_loss is reachable (losses diverge — that is why the
    non-finite guards exist): the exposition must use the canonical
    NaN/+Inf tokens, and the JSONL sink must stay strict-JSON."""
    reg = MetricsRegistry()
    reg.gauge("g_nan").set(float("nan"))
    reg.gauge("g_inf").set(float("inf"))
    text = reg.prometheus_text()
    assert "g_nan NaN" in text  # not repr()'s lowercase 'nan'
    assert "g_inf +Inf" in text  # not 'inf'
    p = str(tmp_path / "m.jsonl")
    reg.dump_jsonl(p)

    def _reject(tok):
        raise ValueError(f"bare {tok} token in JSONL")

    rec = json.loads(open(p).read(), parse_constant=_reject)
    assert rec["metrics"]["g_nan"]["values"][0]["value"] == "NaN"
    assert rec["metrics"]["g_inf"]["values"][0]["value"] == "+Inf"


_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? "
    r"(-?\d+(\.\d+)?([eE][+-]?\d+)?|\+Inf|-Inf|NaN)$")


def test_prometheus_exposition_live_scrape():
    """Scrape a LIVE in-process /metrics endpoint and validate the text
    exposition format line by line (+ /healthz and 404 routing)."""
    reg = MetricsRegistry()
    reg.counter("up_total", "uploads", labelnames=("outcome",)).inc(
        7, outcome='we"ird\nlabel')
    reg.gauge("occ", "occupancy").set(3)
    reg.histogram("tau", "staleness", buckets=(0, 1, 4)).observe(2)
    srv = MetricsServer(0, registry=reg,
                        health_probe=lambda: {"round": 5})
    try:
        base = f"http://127.0.0.1:{srv.port}"
        resp = urllib.request.urlopen(f"{base}/metrics")
        assert resp.headers["Content-Type"].startswith(
            "text/plain; version=0.0.4")
        body = resp.read().decode()
        for line in body.strip().splitlines():
            if line.startswith("#"):
                assert re.match(r"^# (HELP|TYPE) [a-zA-Z_:]", line)
            else:
                assert _SAMPLE_RE.match(line), line
        assert 'outcome="we\\"ird\\nlabel"' in body  # label escaping
        assert "occ 3" in body
        hz = json.loads(urllib.request.urlopen(f"{base}/healthz").read())
        assert hz["ok"] is True and hz["round"] == 5
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope")
    finally:
        srv.close()


# ------------------------------------------------ flight recorder


def test_flight_ring_bounded_and_dump_schema(tmp_path):
    fr = FlightRecorder(capacity=4)
    for i in range(7):
        fr.record("ev", i=i)
    assert [e["i"] for e in fr.events()] == [3, 4, 5, 6]
    out = fr.dump(str(tmp_path / "f.json"), reason="test")
    doc = json.load(open(out))
    assert doc["reason"] == "test"
    assert doc["capacity"] == 4 and doc["evicted"] == 3
    assert [e["i"] for e in doc["events"]] == [3, 4, 5, 6]
    for e in doc["events"]:
        assert e["kind"] == "ev"
        assert e["t_mono"] > 0 and e["t_wall"] > 0
    # resize keeps the newest events
    fr.configure(capacity=2)
    assert [e["i"] for e in fr.events()] == [5, 6]
    assert fr.dump() is None  # no path configured -> no dump


def test_failure_context_dumps_flight(tmp_path):
    from neuroimagedisttraining_tpu.utils.profiling import failure_context

    path = str(tmp_path / "flight.json")
    obs_flight.configure(capacity=64, path=path)
    try:
        with pytest.raises(RuntimeError, match="boom"):
            with failure_context(name="obs-test"):
                obs_flight.record("before_failure", x=1)
                raise RuntimeError("boom")
        doc = json.load(open(path))
        kinds = [e["kind"] for e in doc["events"]]
        assert "before_failure" in kinds and "failure" in kinds
        fail = next(e for e in doc["events"] if e["kind"] == "failure")
        assert fail["name"] == "obs-test"
        assert "RuntimeError: boom" in fail["error"]
    finally:
        obs_flight.configure(path="")
        obs_flight.clear()


# ------------------------------------------------ async-server parity


class _CaptureComm:
    """Minimal BaseCommManager stand-in (test_asyncfl.py idiom)."""

    def __init__(self):
        self.sent = []

    def send_message(self, msg, **kw):
        self.sent.append(msg)

    def add_observer(self, obs):
        pass

    def remove_observer(self, obs):
        pass

    def handle_receive_message(self):
        pass

    def stop_receive_message(self):
        pass

    def byte_stats(self):
        return {}


def _tree(seed, scale=1.0):
    rng = np.random.default_rng(seed)
    return {"params": {"w": (scale * rng.standard_normal(12)
                             ).astype(np.float32)}}


def _upload(sender, tree, n, version, seq=None):
    msg = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, sender, 0)
    msg.add(M.ARG_MODEL_PARAMS, tree)
    msg.add(M.ARG_NUM_SAMPLES, float(n))
    msg.add(M.ARG_ROUND_IDX, int(version))
    if seq is not None:
        msg.add(M.ARG_UPLOAD_SEQ, int(seq))
    return msg


def _metric_value(snap, name, **labels):
    for v in snap[name]["values"]:
        if v["labels"] == {k: str(val) for k, val in labels.items()}:
            return v["value"]
    return None


def test_async_upload_stats_mirror_registry_exactly():
    """Every upload_stats bump goes through ONE helper that also bumps
    the registry counter — the audit dict and a /metrics scrape can
    never disagree (no double counting, no second measurement)."""
    from neuroimagedisttraining_tpu.asyncfl.server import (
        BufferedFedAvgServer,
    )

    obs_metrics.reset()
    srv = BufferedFedAvgServer(_tree(0), 10, 3, buffer_k=2,
                               max_staleness=1, comm=_CaptureComm())
    srv._on_model(_upload(1, _tree(1), 4.0, version=0, seq=0))
    srv._on_model(_upload(2, _tree(2), 5.0, version=0, seq=0))  # -> agg
    assert srv.round_idx == 1
    # duplicate (same seq), future tag, and an accepted stale upload
    srv._on_model(_upload(1, _tree(1), 4.0, version=0, seq=0))
    srv._on_model(_upload(1, _tree(3), 4.0, version=7, seq=1))
    srv._on_model(_upload(3, _tree(4), 6.0, version=0, seq=0))  # tau=1
    stats = dict(srv.upload_stats)
    assert stats["received"] == 5 and stats["dropped_duplicate"] == 1 \
        and stats["dropped_future"] == 1
    snap = obs_metrics.snapshot()
    for key, want in stats.items():
        got = _metric_value(snap, "nidt_async_uploads_total",
                            outcome=key)
        assert (got or 0) == want, (key, got, want)
    # staleness histogram saw exactly the accepted taus (0, 0, 1)
    tau = _metric_value(snap, "nidt_async_staleness")
    assert tau["count"] == stats["accepted"] == 3
    assert tau["buckets"]["0"] == 2 and tau["buckets"]["1"] == 1
    # buffer occupancy gauge tracks the live buffer
    assert _metric_value(snap, "nidt_async_buffer_occupancy") \
        == len(srv._buffer) == 1
    audit = srv.upload_audit()
    assert audit["received_accounted"] and audit["accepted_accounted"]


def test_upload_audit_failure_dumps_flight(tmp_path):
    from neuroimagedisttraining_tpu.asyncfl.server import (
        BufferedFedAvgServer,
    )

    obs_metrics.reset()
    path = str(tmp_path / "audit_flight.json")
    obs_flight.configure(capacity=64, path=path)
    try:
        srv = BufferedFedAvgServer(_tree(0), 10, 2, buffer_k=2,
                                   comm=_CaptureComm())
        srv._on_model(_upload(1, _tree(1), 4.0, version=0, seq=0))
        # simulate the accounting bug the audit exists to catch
        srv.upload_stats["received"] += 1
        audit = srv.upload_audit()
        assert not audit["received_accounted"]
        doc = json.load(open(path))
        kinds = [e["kind"] for e in doc["events"]]
        assert "audit_failure" in kinds
        assert "accept" in kinds  # the decisions leading up to it
    finally:
        obs_flight.configure(path="")
        obs_flight.clear()


# ------------------------------------------------ comm byte parity


def test_socket_byte_stats_mirror_registry(tmp_path):
    from neuroimagedisttraining_tpu.distributed.comm import (
        SocketCommManager,
    )
    from neuroimagedisttraining_tpu.distributed.ports import (
        free_port_block,
    )

    obs_metrics.reset()
    port = free_port_block(4)
    a = SocketCommManager(0, 2, base_port=port)
    b = SocketCommManager(1, 2, base_port=port)
    try:
        msg = M.Message("ping", 0, 1)
        msg.add("x", 123)
        a.send_message(msg)
        got = b._q.get(timeout=10)
        assert got.get("x") == 123
        snap = obs_metrics.snapshot()
        sa, sb = a.byte_stats(), b.byte_stats()
        assert sa["bytes_sent"] > 0
        assert _metric_value(snap, "nidt_comm_bytes_sent_total",
                             rank=0) == sa["bytes_sent"]
        assert _metric_value(snap, "nidt_comm_frames_sent_total",
                             rank=0) == sa["frames_sent"] == 1
        assert _metric_value(snap, "nidt_comm_bytes_recv_total",
                             rank=1) == sb["bytes_recv"]
        assert sa["bytes_sent"] == sb["bytes_recv"]
    finally:
        a.stop_receive_message()
        b.stop_receive_message()


# ------------------------------------------------ ExperimentLogger


def test_experiment_logger_handler_leak_fixed(tmp_path):
    """Regression (ISSUE 9 satellite): constructing twice with the same
    identity used to stack duplicate handlers on the name-cached logger
    and duplicate every line."""
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    lg1 = ExperimentLogger(str(tmp_path), "synthetic", "leak_test")
    lg1.info("first line")
    lg2 = ExperimentLogger(str(tmp_path), "synthetic", "leak_test")
    underlying = logging.getLogger("nidt.exp.leak_test")
    # exactly one FileHandler + one StreamHandler, not 2 + 2
    assert len(underlying.handlers) == 2
    lg2.info("second line")
    lg2.close()
    text = open(lg2.log_path).read()
    assert text.count("second line") == 1


def test_logger_metrics_route_through_registry(tmp_path):
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    obs_metrics.reset()
    lg = ExperimentLogger(str(tmp_path), "synthetic", "route_test",
                          console=False)
    lg.metrics(4, train_loss=1.5, nested={"acc": 0.75}, note="text")
    lg.close()
    snap = obs_metrics.snapshot()
    assert _metric_value(snap, "nidt_exp_metric",
                         key="train_loss") == 1.5
    assert _metric_value(snap, "nidt_exp_metric",
                         key="nested_acc") == 0.75
    assert _metric_value(snap, "nidt_exp_round") == 4
    # non-numeric values stay JSONL-only
    assert _metric_value(snap, "nidt_exp_metric", key="note") is None
    rec = json.loads(open(lg.jsonl_path).read().strip())
    assert rec["note"] == "text" and rec["round"] == 4


# ------------------------------------------------ engine smoke parity


def _build_engine(tmp_path, synthetic_cohort):
    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.federate import federate_cohort
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm="fedavg",
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=OptimConfig(lr=1e-3, batch_size=8, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=2,
                      frequency_of_the_test=1, ci=True),
        log_dir=str(tmp_path))
    mesh = make_mesh()
    fed, _ = federate_cohort(synthetic_cohort, partition_method="site",
                             mesh=mesh)
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1),
                           cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    return cfg, create_engine("fedavg", cfg, fed, trainer, mesh=mesh,
                              logger=log)


def test_engine_publish_stat_info_parity(tmp_path, synthetic_cohort):
    """Tier-1 pin of the publish path itself (the full-train smoke is
    the slow twin below): whatever the accumulators hold at a host
    boundary, the nidt_stat gauges equal it after publish."""
    obs_metrics.reset()
    _, engine = _build_engine(tmp_path, synthetic_cohort)
    engine.stat_info["sum_comm_bytes"] = 12345.0
    engine.stat_info["nonfinite_uploads"] = 2.0
    engine.publish_stat_info(3)
    snap = obs_metrics.snapshot()
    for k, v in engine.stat_info.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            assert _metric_value(snap, "nidt_stat", key=k) == float(v), k
    assert _metric_value(snap, "nidt_engine_round") == 3


@pytest.mark.slow  # tier-1 window (PR 9): full-train smoke twin; the
# publish-path parity pin above stays tier-1
def test_engine_stat_info_publishes_to_registry(tmp_path,
                                                synthetic_cohort):
    """Smoke federation: after train(), the registry's nidt_stat gauges
    equal the legacy stat_info accumulators (single source, gauge
    semantics — no double counting), and the round-metric gauges carry
    the last eval."""
    obs_metrics.reset()
    cfg, engine = _build_engine(tmp_path, synthetic_cohort)
    result = engine.train()
    assert np.isfinite(result["history"][-1]["train_loss"])
    snap = obs_metrics.snapshot()
    for k, v in engine.stat_info.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            assert _metric_value(snap, "nidt_stat", key=k) == float(v), k
    # ExperimentLogger.metrics routed the eval series through too
    assert _metric_value(snap, "nidt_exp_metric", key="train_loss") \
        is not None
    assert _metric_value(snap, "nidt_engine_round") == cfg.fed.comm_round - 1
