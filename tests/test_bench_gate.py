"""Bench regression gate (ISSUE 13, analysis/bench_gate.py).

Covers the dotted-path extractor, every judgment kind (true /
ratio_min / ratio_max / abs_max / eq), the skip-vs-fail contract for
missing artifacts/paths (and --strict), the verdict aggregation +
exit codes, the self-diff canary against the COMMITTED bench_matrix/
(spec paths must keep matching the artifacts — schema drift fails
here, not silently), and the bench_diff wrapper's artifact shaping.
"""

import json
import os

import pytest

from neuroimagedisttraining_tpu.analysis import bench_gate
from neuroimagedisttraining_tpu.analysis.bench_gate import (
    Check,
    extract,
    gate,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(d, name, doc):
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, name), "w") as f:
        json.dump(doc, f)


# ------------------------------------------------ extractor / judge


def test_extract_dotted_paths():
    doc = {"a": {"b": {"c": 3}}, "top": True}
    assert extract(doc, "a.b.c") == 3
    assert extract(doc, "top") is True
    assert extract(doc, "a.missing") is None
    assert extract(doc, "a.b.c.too_deep") is None


def test_judge_kinds():
    j = bench_gate._judge
    assert j(Check("p", "true"), True, None)[0]
    assert not j(Check("p", "true"), False, None)[0]
    assert j(Check("p", "ratio_min", 0.5), 60.0, 100.0)[0]
    assert not j(Check("p", "ratio_min", 0.5), 40.0, 100.0)[0]
    assert j(Check("p", "ratio_max", 2.0), 150.0, 100.0)[0]
    assert not j(Check("p", "ratio_max", 2.0), 250.0, 100.0)[0]
    assert j(Check("p", "abs_max", 0.02), 0.01, None)[0]
    assert not j(Check("p", "abs_max", 0.02), 0.05, None)[0]
    assert j(Check("p", "eq"), 2.67, 2.67)[0]
    assert not j(Check("p", "eq"), 2.67, 1.0)[0]
    # malformed values fail with a reason, never raise
    ok, detail = j(Check("p", "ratio_min", 0.5), "junk", 100.0)
    assert not ok and "non-numeric" in detail
    ok, detail = j(Check("p", "ratio_min", 0.5), 10.0, 0.0)
    assert not ok and "ratio undefined" in detail


# ------------------------------------------------ gate semantics


@pytest.fixture()
def spec_sandbox(monkeypatch):
    monkeypatch.setattr(bench_gate, "SPECS", {
        "cell.json": (
            Check("speed", "ratio_min", 0.5),
            Check("audits", "true"),
            Check("optional.deep", "ratio_max", 2.0),
        ),
    })


def test_gate_green_red_and_skips(tmp_path, spec_sandbox):
    committed = str(tmp_path / "committed")
    fresh = str(tmp_path / "fresh")
    _write(committed, "cell.json",
           {"speed": 100.0, "audits": True, "optional": {"deep": 1.0}})
    _write(fresh, "cell.json", {"speed": 80.0, "audits": True})
    res = gate(fresh, committed_dir=committed)
    assert res["verdict"] == "green"
    assert res["checked"] == 2  # optional.deep missing in fresh ->
    assert res["skipped"] == 1  # skipped, not red
    assert not res["self_diff"]
    # strict upgrades the skip to a failure
    assert gate(fresh, committed_dir=committed,
                strict=True)["verdict"] == "red"
    # a regressed cell goes red
    _write(fresh, "cell.json", {"speed": 20.0, "audits": True})
    res = gate(fresh, committed_dir=committed)
    assert res["verdict"] == "red"
    bad = next(c for c in res["cells"] if not c["ok"])
    assert bad["path"] == "speed" and "0.200" in bad["detail"]


def test_gate_missing_artifacts_skip(tmp_path, spec_sandbox):
    committed = str(tmp_path / "committed")
    fresh = str(tmp_path / "fresh")
    _write(committed, "cell.json", {"speed": 100.0, "audits": True})
    os.makedirs(fresh)
    res = gate(fresh, committed_dir=committed)
    assert res["verdict"] == "empty" and res["skipped"] == 1
    assert res["skips"][0]["reason"] == "no fresh artifact"
    # and the reverse: fresh exists, committed missing
    _write(fresh, "cell.json", {"speed": 100.0, "audits": True})
    res = gate(fresh, committed_dir=str(tmp_path / "nowhere"))
    assert res["verdict"] == "empty"
    assert res["skips"][0]["reason"] == "no committed artifact"


def test_gate_unknown_artifact_raises(tmp_path):
    with pytest.raises(ValueError, match="unknown artifacts"):
        gate(str(tmp_path), artifacts=["nope.json"])


def test_main_exit_codes(tmp_path, spec_sandbox, capsys):
    committed = str(tmp_path / "committed")
    fresh = str(tmp_path / "fresh")
    _write(committed, "cell.json", {"speed": 100.0, "audits": True})
    _write(fresh, "cell.json", {"speed": 90.0, "audits": True})
    out_json = str(tmp_path / "verdict.json")
    rc = bench_gate.main(["--fresh", fresh, "--committed", committed,
                          "--json", out_json, "--quiet"])
    assert rc == 0
    assert json.load(open(out_json))["verdict"] == "green"
    assert json.loads(capsys.readouterr().out)["verdict"] == "green"
    _write(fresh, "cell.json", {"speed": 10.0, "audits": True})
    assert bench_gate.main(["--fresh", fresh, "--committed", committed,
                            "--quiet"]) == 1
    assert bench_gate.main(["--artifact", "nope.json"]) == 2


# ------------------------------------------------ committed canary


def test_self_diff_of_committed_matrix_is_green():
    """The spec-path canary the bare CLI runs: every SPECS path must
    still resolve in the committed artifacts and self-compare green —
    an artifact schema change must fail HERE, not silently skip
    forever."""
    res = gate(None, committed_dir=os.path.join(REPO, "bench_matrix"))
    assert res["self_diff"] is True
    assert res["verdict"] == "green", [c for c in res["cells"]
                                       if not c["ok"]]
    # every artifact named in SPECS is committed, and every spec path
    # resolves — the ONLY tolerated skips are the armed-but-waiting
    # MFU ratio cells, null in the committed artifacts until the first
    # TPU-session regeneration records a known device peak
    assert all(s.get("path", "").endswith(".mfu")
               for s in res["skips"]), res["skips"]
    assert res["checked"] + res["skipped"] == sum(
        len(v) for v in bench_gate.SPECS.values())


# ------------------------------------------------ bench_diff wrapper


def test_bench_diff_gates_produced_artifact(tmp_path, monkeypatch):
    """bench_diff with a pre-produced fresh dir (no --produce): the
    wrapper must route through the same gate and exit green/red on the
    same thresholds."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_diff", os.path.join(REPO, "scripts", "bench_diff.py"))
    bd = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bd)
    committed = json.load(
        open(os.path.join(REPO, "bench_matrix", "ingest_bench.json")))
    fresh_doc = {
        "bench": "ingest_plane",
        "async": {"uploads_per_s_sustained":
                  committed["async"]["uploads_per_s_sustained"]},
        "ingest_w2": {"uploads_per_s_sustained":
                      committed["ingest_w2"]["uploads_per_s_sustained"]},
        "summary": {"audits_green": True},
    }
    fresh = str(tmp_path / "fresh")
    _write(fresh, "ingest_bench.json", fresh_doc)
    rc = bd.main(["--fresh", fresh,
                  "--committed", os.path.join(REPO, "bench_matrix"),
                  "--artifact", "ingest_bench.json"])
    assert rc == 0
    # halve the sharded throughput past the 0.5 tripwire -> red
    fresh_doc["ingest_w2"]["uploads_per_s_sustained"] = (
        0.3 * committed["ingest_w2"]["uploads_per_s_sustained"])
    _write(fresh, "ingest_bench.json", fresh_doc)
    rc = bd.main(["--fresh", fresh,
                  "--committed", os.path.join(REPO, "bench_matrix"),
                  "--artifact", "ingest_bench.json"])
    assert rc == 1
