"""Round-program builder tests (ISSUE 11, engines/program.py).

Contracts:

(a) Newly-declared engines (ditto / dpsgd / subavg) gain fused
    ``--rounds_per_dispatch`` windows: a K=4 window dispatched through
    ``program.run_window`` equals four K=1 single dispatches BITWISE
    (params, batch_stats, persistent per-client state, per-round
    losses), with ONE compiled program per window (the ``built`` /
    ``dispatches`` counters pin it). fedavg/fedprox/salientgrads keep
    their pre-builder pins in tests/test_dispatch.py — unchanged, the
    regression oracle of the port.
(b) The same engines gain ``--client_mesh`` cohort sharding: the
    sharded round from identical state matches the sequential C-loop
    (losses bitwise, state to the ~1-ulp compile-context residue —
    parallel/cohort.py contract, same bounds as tests/test_cohort.py).
(c) Fallback reporting is unified: every reason is a key of
    ``program.REASONS``, engines that declared stages stopped reporting
    the old no-fused-body reason, and each announcement increments the
    structured ``nidt_fallback_total{plane, engine, reason}`` counter
    (value-pinned).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.config import (
    DataConfig, ExperimentConfig, FedConfig, OptimConfig,
)
from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
from neuroimagedisttraining_tpu.data.federate import federate_cohort
from neuroimagedisttraining_tpu.engines import ENGINES, create_engine
from neuroimagedisttraining_tpu.engines import program as round_program
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.obs import compute as obs_compute
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

ULP_RTOL = 1e-6
ULP_ATOL = 1e-6


def _engine(tmp_path, cohort, algorithm="ditto", K=1, comm_round=4,
            freq=4, tag="p", epochs=1, client_mesh=0, seq=False,
            donate=True, val_fraction=0.0, **fed_kw):
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm=algorithm,
        data=DataConfig(dataset="synthetic", partition_method="site",
                        val_fraction=val_fraction),
        optim=OptimConfig(lr=1e-3, batch_size=8, epochs=epochs),
        fed=FedConfig(client_num_in_total=4, comm_round=comm_round,
                      frequency_of_the_test=freq, rounds_per_dispatch=K,
                      client_mesh=client_mesh, **fed_kw),
        log_dir=str(tmp_path), tag=tag)
    mesh = make_mesh()
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1),
                           cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    fed, _ = federate_cohort(cohort, partition_method="site", mesh=mesh,
                             val_fraction=val_fraction)
    eng = create_engine(algorithm, cfg, fed, trainer, mesh=mesh,
                        logger=log)
    eng._donate = donate
    if seq:
        eng._cohort_sequential = True
    return eng


def _assert_trees_bitwise(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _assert_trees_ulp(a, b, rtol=ULP_RTOL, atol=ULP_ATOL):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x, np.float64),
                                   np.asarray(y, np.float64),
                                   rtol=rtol, atol=atol)


# ---------------------------------------------------------------------------
# per-engine sequential references / initial carries
# ---------------------------------------------------------------------------

def _init_carry(eng):
    gs = eng.init_global_state()
    if eng.name == "local":
        per = eng.broadcast_states(gs, eng.num_clients)
        return (per.params, per.batch_stats)
    if eng.name in ("ditto", "salientgrads"):
        per = eng.broadcast_states(gs, eng.num_clients)
        return (gs.params, gs.batch_stats, per.params, per.batch_stats)
    if eng.name == "subavg":
        from neuroimagedisttraining_tpu.ops.masks import ones_mask

        masks = eng.broadcast_states(ones_mask(gs.params),
                                     eng.num_clients)
        return (gs.params, gs.batch_stats, masks)
    if eng.name == "dpsgd":
        per = eng.broadcast_states(gs, eng.num_clients)
        return (per.params, per.batch_stats)
    return (gs.params, gs.batch_stats)


def _one_round(eng, carry, r):
    """One K=1 dispatch through the engine's legacy round adapter;
    returns (new_carry, loss)."""
    lr = eng.round_lr(r)
    if eng.name == "dpsgd":
        M_np = eng.mixing_matrix(r)
        plan, plan_arrays = eng.gossip_plan(M_np)
        rngs = eng.per_client_rngs(r, np.arange(eng.num_clients))
        out = eng._round_jit_for(plan)(*carry, eng.data,
                                       jnp.asarray(M_np), rngs, lr,
                                       plan_arrays)
        return out[:2], out[4]
    if eng.name == "local":
        rngs = eng.per_client_rngs(r, np.arange(eng.num_clients))
        out = eng._round_jit(*carry, eng.data, rngs, lr)
        return out[:2], out[2]
    sampled = eng.client_sampling(r)
    rngs = eng.per_client_rngs(r, sampled)
    n = len(carry)
    out = eng._round_jit(*carry, eng.data, jnp.asarray(sampled),
                         rngs, lr)
    return out[:n], out[n]


# ---------------------------------------------------------------------------
# (a) fused K=4 == 4 x K=1, bitwise, one compiled program per window
# ---------------------------------------------------------------------------

# tier-1 window budget (PR 2/7/9 precedent): the heavy bitwise pins ride
# the full suite; tier-1 keeps the cheap fallback/counter/reason pins
# below plus the builder coverage every per-round engine test already
# exercises (all K=1 dispatches now route through engines/program.py)
@pytest.mark.parametrize("algorithm,fed_kw", [
    pytest.param("ditto", {"frac": 0.5}, marks=pytest.mark.slow),
    pytest.param("subavg", {"frac": 0.5}, marks=pytest.mark.slow),
    pytest.param("dpsgd", {"cs": "ring", "frac": 0.5},
                 marks=pytest.mark.slow),
    pytest.param("dpsgd", {"cs": "random", "frac": 0.5},
                 marks=pytest.mark.slow),
    # ROADMAP 1(a): the local engine's trivial carry on the builder
    pytest.param("local", {}, marks=pytest.mark.slow),
    # ROADMAP 1(b): the secure-quant codec-family stage composes with
    # fused windows — the field fold rides the scan bitwise
    pytest.param("fedavg", {"frac": 0.5, "secure_quant": True,
                            "secure_quant_field_bits": 32},
                 marks=pytest.mark.slow),
])
def test_fused_window_bitwise_equals_sequential(tmp_path,
                                                synthetic_cohort,
                                                algorithm, fed_kw):
    """The newly-declared engines' K-round scan: a K=4 window equals
    four single dispatches bitwise in the full carried state and the
    per-round losses — and the window is ONE compiled program, dispatched
    once (program.built / program.dispatches pins)."""
    seq = _engine(tmp_path, synthetic_cohort, algorithm, K=1,
                  tag=f"sq-{algorithm}-{len(fed_kw)}", **fed_kw)
    carry = _init_carry(seq)
    losses = []
    for r in range(4):
        carry, loss = _one_round(seq, carry, r)
        losses.append(float(loss))
    # the dispatch counter is the bench's evidence: 4 sequential rounds
    # = 4 invocations of 1 compiled program
    assert seq.program.dispatches == 4
    assert seq.program.built == 1

    fz = _engine(tmp_path, synthetic_cohort, algorithm, K=4,
                 tag=f"fz-{algorithm}-{len(fed_kw)}", **fed_kw)
    assert fz.fused_fallback_reason() is None
    fcarry = _init_carry(fz)
    built0 = fz.program.built
    # the compiled-programs-per-window pin re-asserted through the
    # scrapeable counter (ISSUE 14): nidt_compiles_total moves in the
    # SAME increment as program.built — one measurement, not a second
    # bookkeeping path
    ctr0 = obs_compute.compiles_total(engine=algorithm)
    fcarry, _, outs, wi = fz.program.run_window(fcarry, 0, 4)
    assert wi.k == 4
    assert [float(x) for x in np.asarray(outs["loss"])] == losses
    _assert_trees_bitwise(carry, fcarry)
    # one compiled program, one dispatch, for the whole window
    assert fz.program.built - built0 == 1
    assert obs_compute.compiles_total(engine=algorithm) - ctr0 == 1.0
    assert fz.program.dispatches == 1
    assert len(fz.__dict__["_fused_round_jit_cache"]) == 1


@pytest.mark.slow
def test_fused_driver_end_to_end_bitwise_ditto(tmp_path,
                                               synthetic_cohort):
    """The full ditto driver: a K=4 train() — windows planned around the
    eval cadence, personal stacks carried, hooks at boundaries — equals
    the K=1 run bitwise in global AND personal state, metrics history
    included."""
    r1 = _engine(tmp_path, synthetic_cohort, "ditto", K=1, frac=0.5,
                 tag="dk1").train()
    e4 = _engine(tmp_path, synthetic_cohort, "ditto", K=4, frac=0.5,
                 tag="dk4")
    r4 = e4.train()
    _assert_trees_bitwise(r1["params"], r4["params"])
    _assert_trees_bitwise(r1["personal_params"], r4["personal_params"])
    assert r1["history"] == r4["history"]
    # windows reused ONE fused program per distinct plan
    assert len(e4.__dict__["_fused_round_jit_cache"]) == 1


@pytest.mark.slow
@pytest.mark.parametrize("algorithm,key", [
    ("subavg", "params"),
    ("dpsgd", "personal_params"),
])
def test_fused_driver_end_to_end_bitwise(tmp_path, synthetic_cohort,
                                         algorithm, key):
    kw = {"cs": "ring", "frac": 0.5} if algorithm == "dpsgd" \
        else {"frac": 0.5}
    r1 = _engine(tmp_path, synthetic_cohort, algorithm, K=1,
                 tag=f"ek1{algorithm}", **kw).train()
    r4 = _engine(tmp_path, synthetic_cohort, algorithm, K=4,
                 tag=f"ek4{algorithm}", **kw).train()
    _assert_trees_bitwise(r1[key], r4[key])
    assert r1["history"] == r4["history"]


# ---------------------------------------------------------------------------
# (b) cohort sharding for the newly-declared engines
# ---------------------------------------------------------------------------

def _one_sharded_round(eng, r=0):
    carry = _init_carry(eng)
    lr = eng.round_lr(r)
    if eng.name == "local":
        # no sampling: the full (mesh-padded) cohort trains; _round_jit
        # IS the sharded program when _cohort_on
        rngs = eng.per_client_rngs(r, np.arange(eng.num_clients))
        return eng._round_jit(*carry, eng.data, rngs, lr)
    if eng.name == "dpsgd":
        M_np = eng.mixing_matrix(r)
        plan, plan_arrays = eng.gossip_plan(M_np)
        rngs = eng.per_client_rngs(r, np.arange(eng.num_clients))
        return eng._round_jit_for(plan)(*carry, eng.data,
                                        jnp.asarray(M_np), rngs, lr,
                                        plan_arrays)
    sampled = eng.client_sampling(r)
    ids, n_real = eng._cohort_pad(sampled)
    rngs = eng.per_client_rngs(r, ids)
    return eng._sharded_round_jit(n_real)(*carry, eng.data,
                                          jnp.asarray(ids), rngs, lr)


@pytest.mark.parametrize("algorithm,loss_i,epochs", [
    pytest.param("ditto", 4, 1, marks=pytest.mark.slow),
    pytest.param("subavg", 3, 2, marks=pytest.mark.slow),
    pytest.param("dpsgd", 4, 1, marks=pytest.mark.slow),
    pytest.param("local", 2, 1, marks=pytest.mark.slow),
])
def test_sharded_round_vs_sequential_loop(tmp_path, synthetic_cohort,
                                          algorithm, loss_i, epochs):
    """The sharded round vs the sequential C-loop reference
    (_cohort_sequential): per-client work identical by construction, so
    the round loss is bitwise (ditto/dpsgd; subavg's two-phase masked
    composite is allowed the same 1-ulp seam as salientgrads' masked
    round) and state agrees to the ~1-ulp compile-context residue
    (parallel/cohort.py). subavg runs epochs=2 so the hoisted two-call
    permutation chain (epoch-1 + tail) is load-bearing; an rng-replay
    drift would show as 1e-0-level loss divergence."""
    eng_sh = _engine(tmp_path, synthetic_cohort, algorithm,
                     client_mesh=8, epochs=epochs, donate=False,
                     tag=f"sh{algorithm}")
    eng_sq = _engine(tmp_path, synthetic_cohort, algorithm,
                     client_mesh=8, epochs=epochs, donate=False,
                     seq=True, tag=f"sq{algorithm}")
    assert eng_sh._cohort_on and eng_sq._cohort_on
    out_sh = _one_sharded_round(eng_sh)
    out_sq = _one_sharded_round(eng_sq)
    if algorithm == "subavg":
        np.testing.assert_allclose(float(out_sh[loss_i]),
                                   float(out_sq[loss_i]), rtol=3e-7)
    else:
        np.testing.assert_array_equal(np.asarray(out_sh[loss_i]),
                                      np.asarray(out_sq[loss_i]))
    _assert_trees_ulp(out_sh, out_sq)


# ---------------------------------------------------------------------------
# (c) unified fallback reporting
# ---------------------------------------------------------------------------

def test_reason_table_has_no_orphans(tmp_path, synthetic_cohort):
    """Single source of truth: every engine's fallback keys resolve in
    REASONS, declared engines stopped reporting the old no-fused-body
    reason, and no key in the table is unreachable by construction (the
    lint rule round-program-reason rejects ad-hoc strings)."""
    declared = {"fedavg", "fedprox", "salientgrads", "ditto", "dpsgd",
                "subavg", "local"}
    seen = set()
    for name, cls in ENGINES.items():
        if name in ("sailentgrads", "sub-fedavg"):  # registry aliases
            continue
        kw = {"val_fraction": 0.25} if name == "fedfomo" else {}
        eng = _engine(tmp_path, synthetic_cohort, name, K=4,
                      tag=f"rt-{name}", **kw)
        key = eng.fused_fallback_key()
        ckey = eng.program.cohort_fallback_key()
        for k in (key, ckey):
            if k is not None:
                assert k in round_program.REASONS, (name, k)
                seen.add(k)
        if name in declared:
            assert key is None, (name, key)
            assert eng.fused_fallback_reason() is None
        else:
            assert key is not None
            assert eng.fused_fallback_reason() == \
                round_program.reason(key)
    # every key the engine matrix announced is a table key, and every
    # message renders from the table (no orphaned ad-hoc strings — the
    # round-program-reason lint rule enforces the source side)
    for k in seen:
        assert round_program.REASONS[k][0] in ("fused", "sharding",
                                               "streaming")


def test_fallback_counter_value_pinned(tmp_path, synthetic_cohort):
    """Every announced fallback increments
    nidt_fallback_total{plane, engine, reason} — scrapeable, not
    grep-able. Constructing a K=4 fedfomo engine announces exactly one
    fused fallback with the table key."""
    c = obs_metrics.counter(
        "nidt_fallback_total", labelnames=("plane", "engine", "reason"))
    labels = dict(plane="fused", engine="fedfomo",
                  reason="no-fused-body")
    before = c.get(**labels)
    _engine(tmp_path, synthetic_cohort, "fedfomo", K=4,
            val_fraction=0.25, tag="ctr")
    assert c.get(**labels) == before + 1.0
    # and a sharding fallback announcement rides the same counter —
    # local now DECLARES its round (ROADMAP 1(a)) and ARMS sharding on
    # the mesh-padded cohort, so the undeclared fedfomo carries this pin
    sh_labels = dict(plane="sharding", engine="fedfomo",
                     reason="no-sharded-body")
    before_sh = c.get(**sh_labels)
    eng = _engine(tmp_path, synthetic_cohort, "fedfomo", K=1,
                  client_mesh=8, val_fraction=0.25, tag="ctr2")
    assert not eng._cohort_on
    assert c.get(**sh_labels) == before_sh + 1.0
    # the newly-declared local engine arms instead of announcing
    eng_l = _engine(tmp_path, synthetic_cohort, "local", K=1,
                    client_mesh=8, tag="ctr3")
    assert eng_l._cohort_on


def test_wire_codec_still_collapses_with_counted_reason(
        tmp_path, synthetic_cohort):
    """Declared engines still fall back per MODE: fedavg + --wire_codec
    reports the wire-codec-host-bytes key (counted), not the stale
    no-fused-body reason."""
    eng = _engine(tmp_path, synthetic_cohort, "fedavg", K=4,
                  wire_codec="delta+quant", tag="wck")
    assert eng.fused_fallback_key() == "wire-codec-host-bytes"


# ---------------------------------------------------------------------------
# (d) --secure_quant as an in-process CODEC-family stage (ROADMAP 1(b))
# ---------------------------------------------------------------------------


def _sq_host_fold(upload, ref, w, spec, scales, shift):
    """THE reference the jitted stage is pinned against: integer fold
    weights from the identical f32 formula, ``encode_secure_quant``
    frames folded through a ``SlotAccumulator`` (privacy/secure_quant's
    host fold — masks cancel exactly mod p), finalized and divided by
    the integer mass in f32."""
    from neuroimagedisttraining_tpu.privacy import (
        SlotAccumulator, encode_secure_quant,
    )

    w = np.asarray(w, np.float32)
    wn = w / np.float32(np.max(w))
    wi = np.maximum(np.rint(wn * np.float32(1 << shift)),
                    np.float32(1.0)).astype(np.int64)
    denom = np.float32(wi.sum())
    acc = SlotAccumulator(spec, like=ref)
    C = int(wi.size)
    for c in range(C):
        u_c = jax.tree.map(lambda t: np.asarray(t)[c], upload)
        frame = encode_secure_quant(u_c, 1.0, spec,
                                    np.random.default_rng(1000 + c),
                                    scales=scales)
        acc.fold(frame, weight_int=int(wi[c]))
    host = acc.finalize(like=ref, rescale=1.0, scales=scales)
    return jax.tree.map(
        lambda t: (np.asarray(t, np.float32) / denom).astype(t.dtype),
        host)


def test_secure_quant_stage_bitwise_vs_host_fold():
    """The satellite's core pin: the jitted in-process secure-quant
    stage (program.secure_quant_aggregate) produces BITWISE the
    aggregate of privacy.secure_quant's host fold — SlotAccumulator
    over encode_secure_quant frames at the same (p, frac_bits, scales,
    integer weights). Exact field/integer algebra plus single
    correctly-rounded f32 ops on both sides is what makes the equality
    exact, not approximate. Includes a BatchNorm-magnitude leaf (the
    leaf_scales path) and a NaN row (quantizes to the neutral zero
    residue on both sides)."""
    import types

    from neuroimagedisttraining_tpu.privacy import QuantSpec, leaf_scales

    rng = np.random.default_rng(7)
    C = 5
    upload = {
        "params": {
            "k": (3.0 * rng.standard_normal((C, 3, 4))).astype(
                np.float32),
            "b": rng.standard_normal((C, 7)).astype(np.float32)},
        "batch_stats": {
            "m": (40.0 * rng.standard_normal((C, 6))).astype(
                np.float32)}}
    upload["params"]["b"][2, 3] = np.nan  # neutral zero residue
    ref = {
        "params": {"k": rng.standard_normal((3, 4)).astype(np.float32),
                   "b": rng.standard_normal(7).astype(np.float32)},
        "batch_stats": {
            "m": (50.0 * rng.standard_normal(6)).astype(np.float32)}}
    w = np.asarray([8.0, 11.0, 9.0, 12.0, 10.0], np.float32)
    losses = np.asarray([0.5, 0.6, 0.4, 0.7, 0.55], np.float32)
    spec = QuantSpec.from_bits(32, 10, 3)
    scales = leaf_scales(ref)
    shift = 6
    eng = types.SimpleNamespace(
        cfg=types.SimpleNamespace(
            fed=types.SimpleNamespace(defense_type="none")),
        sq_spec=spec, sq_scales=scales, sq_weight_shift=shift)
    params, bstats, mean_loss, n_bad = jax.jit(
        lambda u, rf, ww, ls: round_program.secure_quant_aggregate(
            eng, u, rf, ww, ls))(upload, ref, jnp.asarray(w),
                                 jnp.asarray(losses))
    host = _sq_host_fold(upload, ref, w, spec, scales, shift)
    _assert_trees_bitwise({"params": params, "batch_stats": bstats},
                          host)
    assert int(n_bad) == 1  # counted, not gated — protocol-faithful


def test_secure_quant_engine_round_near_plain(tmp_path,
                                              synthetic_cohort):
    """Wiring sanity: a fedavg round with --secure_quant armed agrees
    with the plain round to quantization error (the per-leaf scale's
    2^-frac_bits lattice), not more — the stage replaced the tail, it
    did not corrupt it. The fused-window bitwise pin rides the slow
    matrix above."""
    pl = _engine(tmp_path, synthetic_cohort, "fedavg", K=1, frac=0.5,
                 tag="sqp")
    sq = _engine(tmp_path, synthetic_cohort, "fedavg", K=1, frac=0.5,
                 secure_quant=True, secure_quant_field_bits=32,
                 tag="sqs")
    assert sq.sq_spec is not None and sq.sq_weight_shift >= 1
    pcarry, _ = _one_round(pl, _init_carry(pl), 0)
    scarry, _ = _one_round(sq, _init_carry(sq), 0)
    for a, b in zip(jax.tree.leaves(scarry), jax.tree.leaves(pcarry)):
        np.testing.assert_allclose(np.asarray(a, np.float64),
                                   np.asarray(b, np.float64),
                                   atol=0.05, rtol=0)


def test_secure_quant_startup_rejections(tmp_path, synthetic_cohort):
    """The privacy-plane matrix fails at STARTUP, never mid-round:
    engines without the default tail, the wire codec, order-statistic
    defenses, and a too-small field are all named errors."""
    with pytest.raises(ValueError, match="does not simulate"):
        _engine(tmp_path, synthetic_cohort, "dpsgd", secure_quant=True,
                secure_quant_field_bits=32, tag="sjd")
    with pytest.raises(ValueError, match="wire_codec"):
        _engine(tmp_path, synthetic_cohort, "fedavg", secure_quant=True,
                secure_quant_field_bits=32, wire_codec="delta+quant",
                tag="sjw")
    with pytest.raises(ValueError, match="clip family"):
        _engine(tmp_path, synthetic_cohort, "fedavg", secure_quant=True,
                secure_quant_field_bits=32, defense_type="trimmed_mean",
                tag="sjt")
    with pytest.raises(ValueError, match="field_bits 32"):
        _engine(tmp_path, synthetic_cohort, "fedavg", secure_quant=True,
                secure_quant_field_bits=16, tag="sjf")


