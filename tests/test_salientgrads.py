"""SalientGrads end-to-end: global SNIP mask density, masked training keeps
params sparse, dense escape hatch, learning above chance."""

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.config import (
    DataConfig, ExperimentConfig, FedConfig, OptimConfig, SparsityConfig,
)
from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
from neuroimagedisttraining_tpu.data.federate import federate_cohort
from neuroimagedisttraining_tpu.engines import create_engine
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.ops.masks import is_weight_kernel
from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger


def _engine(tmp_path, cohort, **sparsity_kw):
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm="salientgrads",
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=OptimConfig(lr=1e-3, batch_size=8, epochs=2),
        fed=FedConfig(client_num_in_total=4, comm_round=4,
                      frequency_of_the_test=1),
        sparsity=SparsityConfig(dense_ratio=0.3, itersnip_iterations=2,
                                **sparsity_kw),
        log_dir=str(tmp_path),
    )
    mesh = make_mesh()
    fed, _ = federate_cohort(cohort, partition_method="site", mesh=mesh)
    model = create_model(cfg.model, num_classes=1)
    trainer = LocalTrainer(model, cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    return create_engine("salientgrads", cfg, fed, trainer, mesh=mesh,
                         logger=log)


def test_salientgrads_end_to_end(tmp_path, synthetic_cohort):
    engine = _engine(tmp_path, synthetic_cohort)
    result = engine.train()
    # mask density near dense_ratio target
    assert abs(result["mask_density"] - 0.3) < 0.02
    # final global params actually sparse on maskable kernels
    flat = jax.tree_util.tree_leaves_with_path(result["params"])
    masked_kernels = 0
    for path, leaf in flat:
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        if is_weight_kernel(name, leaf):
            density = float(jnp.mean(leaf != 0))
            assert density < 0.99
            masked_kernels += 1
    assert masked_kernels >= 2
    # learning signal present (loss must have moved; AUC off the floor).
    # the strong above-chance assertion lives in the FedAvg e2e test — here
    # the model is 70%-sparse and trained 4 tiny rounds.
    assert np.isfinite(result["history"][-1]["train_loss"])
    assert result["final_global"]["auc"] > 0.45
    # flops accounting ran and reflects sparsity
    assert engine.stat_info["sum_training_flops"] > 0


def test_dense_escape_hatch(tmp_path, synthetic_cohort):
    engine = _engine(tmp_path, synthetic_cohort, snip_mask=False)
    masks, _ = engine.generate_global_mask(
        *(lambda gs: (gs.params, gs.batch_stats))(engine.init_global_state()))
    assert all(bool(jnp.all(m == 1)) for m in jax.tree.leaves(masks))
