"""Privacy plane (privacy/, ISSUE 8): RDP accountant pins, secure
quantized aggregation (bitwise parity, dropout, wire size, headroom),
the cross-silo/async protocol integration, and the CLI startup matrix."""

import math
import threading

import numpy as np
import pytest

from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.distributed.ports import free_port_block
from neuroimagedisttraining_tpu.ops import mpc
from neuroimagedisttraining_tpu.privacy import (
    DEFAULT_ORDERS,
    QuantSpec,
    RDPAccountant,
    SlotAccumulator,
    check_headroom,
    encode_secure_quant,
    integer_weights,
    quantized_weighted_mean,
    rdp_gaussian,
    rdp_to_epsilon,
    weak_dp_noise_multiplier,
)

SPEC = QuantSpec()  # 16-bit field, frac_bits 10, 3 shares


# ------------------------------------------------ accountant


def test_rdp_gaussian_q1_closed_form():
    """Full participation collapses to the Gaussian mechanism's
    RDP(alpha) = alpha / (2 sigma^2) — THE single-round reference."""
    for sigma in (0.5, 1.0, 2.0, 7.3):
        got = rdp_gaussian(1.0, sigma, orders=(2, 3, 8, 64))
        want = np.asarray([2, 3, 8, 64]) / (2.0 * sigma * sigma)
        np.testing.assert_allclose(got, want, rtol=1e-12)


def test_rdp_subsampling_amplifies():
    """q < 1 strictly reduces per-step RDP at every order, and RDP is
    monotone in q (more sampling, more loss)."""
    full = rdp_gaussian(1.0, 1.5)
    for q in (0.01, 0.1, 0.5):
        sub = rdp_gaussian(q, 1.5)
        assert np.all(sub < full)
    a, b = rdp_gaussian(0.05, 1.5), rdp_gaussian(0.2, 1.5)
    assert np.all(a < b)
    assert np.all(rdp_gaussian(0.0, 1.5) == 0.0)


def test_epsilon_single_round_pinned_against_hand_conversion():
    """epsilon(delta) must equal the hand-computed min over the order
    grid of alpha/(2 sigma^2) + log(1/delta)/(alpha-1) for one q=1
    round — the closed-form pin the acceptance criteria name."""
    sigma, delta = 2.0, 1e-5
    acct = RDPAccountant(delta=delta)
    acct.step(1.0, sigma)
    hand = min(a / (2 * sigma * sigma) + math.log(1 / delta) / (a - 1)
               for a in DEFAULT_ORDERS)
    assert acct.epsilon() == pytest.approx(hand, rel=1e-12)
    # and the accountant is additive: T rounds = T * rdp before the
    # conversion, NOT T * epsilon (the whole point of RDP composition)
    acct10 = RDPAccountant(delta=delta)
    acct10.step(1.0, sigma, steps=10)
    hand10 = min(10 * a / (2 * sigma * sigma)
                 + math.log(1 / delta) / (a - 1) for a in DEFAULT_ORDERS)
    assert acct10.epsilon() == pytest.approx(hand10, rel=1e-12)
    assert acct10.epsilon() < 10 * acct.epsilon()


def test_epsilon_monotonicity():
    """More steps -> more epsilon; more noise -> less; more delta ->
    less. The sanity surface a broken accountant fails first."""
    def eps(sigma=1.0, steps=10, q=0.1, delta=1e-5):
        a = RDPAccountant(delta=delta)
        a.step(q, sigma, steps=steps)
        return a.epsilon()

    assert eps(steps=1) < eps(steps=10) < eps(steps=100)
    assert eps(sigma=4.0) < eps(sigma=1.0) < eps(sigma=0.5)
    assert eps(q=0.01) < eps(q=0.1) < eps(q=1.0)
    assert eps(delta=1e-3) < eps(delta=1e-7)
    assert RDPAccountant().epsilon() == 0.0


def test_accountant_validation():
    with pytest.raises(ValueError, match="q must be"):
        rdp_gaussian(1.5, 1.0)
    with pytest.raises(ValueError, match="noise_multiplier"):
        rdp_gaussian(0.5, 0.0)
    with pytest.raises(ValueError, match="orders"):
        rdp_gaussian(0.5, 1.0, orders=(1.5, 2))
    with pytest.raises(ValueError, match="delta"):
        rdp_to_epsilon(np.zeros(len(DEFAULT_ORDERS)), delta=0.0)
    with pytest.raises(ValueError, match="norm_bound"):
        weak_dp_noise_multiplier(0.0, 5.0, [1.0])


def test_weak_dp_noise_multiplier_geometry():
    """Uniform weights: z = stddev * sqrt(C) / norm_bound; skewed
    weights use the exact sqrt(sum w^2)/max(w) ratio (a heavy silo gets
    LESS amplification, never more)."""
    assert weak_dp_noise_multiplier(0.05, 5.0, [3.0] * 4) == \
        pytest.approx(0.05 * 2 / 5.0)
    w = [10.0, 1.0, 1.0]
    z = weak_dp_noise_multiplier(0.05, 5.0, w)
    assert z == pytest.approx(
        0.05 * math.sqrt(102.0) / (5.0 * 10.0))
    assert z < weak_dp_noise_multiplier(0.05, 5.0, [1.0] * 3)


# ------------------------------------------------ secure_quant core


def _trees(n=4, seed=0, size=40):
    rng = np.random.default_rng(seed)
    return [{"w": (rng.standard_normal(size) * 0.5).astype(np.float32),
             "b": (rng.standard_normal(3) * 0.5).astype(np.float32)}
            for _ in range(n)]


def test_fold_equals_quantized_weighted_mean_bitwise():
    """THE parity pin: seed-expanded masked frames folded slot-major and
    dequantized == the plain quantized weighted mean, BITWISE (the mask
    material cancels exactly in GF(p))."""
    trees, ns = _trees(), [10.0, 20.0, 5.0, 7.0]
    W = sum(ns)
    acc = SlotAccumulator(SPEC)
    for i, (t, n) in enumerate(zip(trees, ns)):
        acc.fold(encode_secure_quant(t, n / W, SPEC,
                                     np.random.default_rng(100 + i)))
    got = acc.finalize(like=trees[0])
    want = quantized_weighted_mean(trees, ns, SPEC)
    for k in ("w", "b"):
        assert got[k].tobytes() == want[k].tobytes()


def test_fold_matches_device_program_bitwise():
    """host==device pin: the jitted uint32 mod-p pipeline
    (ops/mpc_device.secure_sum_device) at this spec's (p, frac_bits)
    over the client-weighted stack lands on the identical bytes — both
    reduce to the same float32 embedding, and masks cancel in both."""
    import jax

    from neuroimagedisttraining_tpu.ops import mpc_device as D

    trees, ns = _trees(), [10.0, 20.0, 5.0, 7.0]
    W = sum(ns)
    want = quantized_weighted_mean(trees, ns, SPEC)
    stack = np.stack([np.concatenate([np.float32(n / W) * t["w"],
                                      np.float32(n / W) * t["b"]])
                      for t, n in zip(trees, ns)])
    dev = np.asarray(D.secure_sum_device(
        stack, jax.random.key(0), n_shares=SPEC.n_shares,
        frac_bits=SPEC.frac_bits, p=SPEC.p))
    assert dev.tobytes() == np.concatenate([want["w"],
                                            want["b"]]).tobytes()


def test_dropout_fold_rescale_parity():
    """Bonawitz discard: a dropped client's frame is simply never
    folded (atomic), and the 1/W survivor rescale recovers the weighted
    mean over the survivors — bitwise vs the survivor-only reference."""
    trees, ns = _trees(seed=3), [10.0, 20.0, 5.0, 7.0]
    W = sum(ns)
    frames = [encode_secure_quant(t, n / W, SPEC,
                                  np.random.default_rng(7 + i))
              for i, (t, n) in enumerate(zip(trees, ns))]
    surv = [0, 1, 3]  # client 2 dies between phases
    acc = SlotAccumulator(SPEC)
    for i in surv:
        acc.fold(frames[i])
    w_surv = sum(ns[i] for i in surv) / W
    got = acc.finalize(like=trees[0], rescale=1.0 / w_surv)
    # reference: same client-side weights w_i = n_i / W, then rescale
    ref_acc = None
    for i in surv:
        q = {k: mpc.quantize32(
            np.float32(ns[i] / W) * trees[i][k].reshape(-1),
            p=SPEC.p, frac_bits=SPEC.frac_bits) for k in trees[i]}
        ref_acc = q if ref_acc is None else {
            k: (ref_acc[k] + q[k]) % SPEC.p for k in q}
    for k in ("w", "b"):
        deq = mpc.dequantize32(ref_acc[k], p=SPEC.p,
                               frac_bits=SPEC.frac_bits)
        want = np.asarray((1.0 / w_surv) * deq, np.float64).reshape(
            trees[0][k].shape).astype(np.float32)
        assert got[k].tobytes() == want.tobytes()


def test_slot_intermediates_never_equal_plaintext():
    """Privacy invariant (the dense protocol's, preserved): no recorded
    slot-accumulator state equals any client's quantized update."""
    trees, ns = _trees(seed=5), [1.0, 1.0, 1.0, 1.0]
    tr = []
    acc = SlotAccumulator(SPEC, trace=tr)
    for i, (t, n) in enumerate(zip(trees, ns)):
        acc.fold(encode_secure_quant(t, 0.25, SPEC,
                                     np.random.default_rng(50 + i)))
    qs = [np.concatenate([
        mpc.quantize32(np.float32(0.25) * t["w"], p=SPEC.p,
                       frac_bits=SPEC.frac_bits),
        mpc.quantize32(np.float32(0.25) * t["b"], p=SPEC.p,
                       frac_bits=SPEC.frac_bits)]) for t in trees]
    assert len(tr) == 4 * SPEC.n_shares
    for inter in tr:
        for q in qs:
            assert not np.array_equal(inter, q), \
                "slot accumulator equals a client's plaintext update"


def test_wire_bytes_beat_dense_secure_5x():
    """The bandwidth claim at unit level (the socket-measured version
    lives in scripts/run_secure_bench.sh): a field-element frame is
    >= 5x smaller than the dense protocol's int64 share slots for the
    same update — uint16 residues + 8-byte seeds vs n_shares x int64."""
    from neuroimagedisttraining_tpu.codec.wire import frame_nbytes

    tree = {"w": np.random.default_rng(0).standard_normal(4096)
            .astype(np.float32)}
    frame = encode_secure_quant(tree, 0.5, SPEC,
                                np.random.default_rng(1))
    dense_shares = {"w": mpc.additive_shares(
        mpc.quantize(0.5 * np.asarray(tree["w"], np.float64)),
        SPEC.n_shares, rng=np.random.default_rng(2))}
    ratio = frame_nbytes(dense_shares) / frame_nbytes(frame)
    assert ratio >= 5.0, f"only {ratio:.1f}x smaller than dense-secure"


def test_leaf_scales_extend_range_bitwise():
    """Per-leaf power-of-two scales (derived from the shared reference)
    carry BatchNorm-magnitude leaves through the 16-bit field: values
    far beyond VALUE_BOUND aggregate correctly, and the scaled fold
    still equals the scaled reference BITWISE (powers of two are exact
    in float32)."""
    from neuroimagedisttraining_tpu.privacy.secure_quant import (
        leaf_scales,
    )

    ref = {"params": np.zeros(8, np.float32),
           "bn_var": np.full(8, 300.0, np.float32)}
    scales = leaf_scales(ref)
    assert scales["params"] == 1.0
    assert scales["bn_var"] >= 300.0 * 2 / 16.0
    assert math.log2(scales["bn_var"]) == int(math.log2(
        scales["bn_var"]))
    rng = np.random.default_rng(0)
    trees = [{"params": (rng.standard_normal(8) * 0.3
                         ).astype(np.float32),
              "bn_var": (300.0 + rng.standard_normal(8) * 20
                         ).astype(np.float32)} for _ in range(3)]
    ns = [1.0, 2.0, 3.0]
    acc = SlotAccumulator(SPEC)
    for i, (t, n) in enumerate(zip(trees, ns)):
        acc.fold(encode_secure_quant(t, n / 6.0, SPEC,
                                     np.random.default_rng(i),
                                     scales=scales))
    got = acc.finalize(like=trees[0], scales=scales)
    want = quantized_weighted_mean(trees, ns, SPEC, scales=scales)
    for k in ref:
        assert got[k].tobytes() == want[k].tobytes()
    # and the scaled aggregate is actually CLOSE to the float mean
    # (unscaled it would saturate at VALUE_BOUND and be wildly wrong)
    fmean = np.average(np.stack([t["bn_var"] for t in trees]), axis=0,
                       weights=ns)
    np.testing.assert_allclose(got["bn_var"], fmean,
                               atol=scales["bn_var"] * 2.0 ** -10 * 4)


def test_headroom_checked_at_startup():
    check_headroom(SPEC, 21)  # the flagship geometry fits
    with pytest.raises(ValueError, match="headroom"):
        check_headroom(QuantSpec(p=mpc.FIELD_PRIMES[16], frac_bits=16), 4)
    with pytest.raises(ValueError, match="n_shares"):
        check_headroom(QuantSpec(n_shares=1), 4)
    with pytest.raises(ValueError, match="field_bits"):
        QuantSpec.from_bits(12)


def test_frame_spec_mismatch_rejected():
    frame = encode_secure_quant({"w": np.ones(4, np.float32)}, 1.0,
                                SPEC, np.random.default_rng(0))
    other = QuantSpec.from_bits(32)
    acc = SlotAccumulator(other)
    with pytest.raises(ValueError, match="spec mismatch"):
        acc.fold(frame)
    with pytest.raises(ValueError, match="frame magic"):
        SlotAccumulator(SPEC).fold({"w": np.ones(4)})


def test_plain_codec_rejects_secure_quant_frame():
    """A field-element frame reaching the PLAIN decode path must die
    loudly (masked residues decoded as floats would silently poison the
    aggregate), with the fix named."""
    from neuroimagedisttraining_tpu.codec import decode_update

    frame = encode_secure_quant({"w": np.ones(4, np.float32)}, 1.0,
                                SPEC, np.random.default_rng(0))
    with pytest.raises(ValueError, match="secure_quant"):
        decode_update(frame, like={"w": np.ones(4, np.float32)})


def test_integer_weights_preserve_ratios_and_cap():
    spec32 = QuantSpec.from_bits(32)
    w = [6.0, 3.0, 1.5]
    wi, denom = integer_weights(w, spec32)
    assert denom == float(np.sum(wi))
    np.testing.assert_allclose(wi / wi[0], np.asarray(w) / w[0],
                               rtol=0.02)
    # a 16-bit field cannot fold a buffer of integer weights
    with pytest.raises(ValueError, match="field_bits 32"):
        integer_weights([5.0, 4.0, 3.0, 2.0], SPEC)


def test_quantize32_nan_is_neutral_and_matches_device():
    """A NaN coordinate maps to the ZERO residue (neutral contribution)
    on host and device alike — never INT_MIN's arbitrary out-of-field
    value — so one diverged client cannot corrupt the aggregate through
    the cast."""
    import jax
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.ops import mpc_device as D

    xs = np.asarray([np.nan, 1.0, -np.inf, np.inf, 0.5], np.float32)
    host = mpc.quantize32(xs, p=SPEC.p, frac_bits=SPEC.frac_bits)
    dev = np.asarray(jax.jit(
        lambda v: D.quantize_device(v, p=SPEC.p,
                                    frac_bits=SPEC.frac_bits))(
        jnp.asarray(xs))).astype(np.int64)
    np.testing.assert_array_equal(host, dev)
    assert host[0] == 0  # NaN -> zero residue
    assert (host < SPEC.p).all()
    # inf saturates sign-preservingly
    back = mpc.dequantize32(host, p=SPEC.p, frac_bits=SPEC.frac_bits)
    assert back[2] < 0 < back[3]


def test_fold_is_atomic_on_structure_skew():
    """A frame with a mismatched leaf set must be rejected BEFORE any
    accumulator mutation — the Bonawitz 'folds whole or not at all'
    contract — so the surviving fold still finalizes correctly."""
    good = [{"a": np.full(4, 0.5, np.float32),
             "b": np.full(2, 0.25, np.float32)} for _ in range(2)]
    acc = SlotAccumulator(SPEC)
    for i, t in enumerate(good):
        acc.fold(encode_secure_quant(t, 0.5, SPEC,
                                     np.random.default_rng(i)))
    skew = encode_secure_quant({"a": np.ones(4, np.float32),
                                "c": np.ones(2, np.float32)}, 0.5,
                               SPEC, np.random.default_rng(9))
    with pytest.raises(ValueError, match="structure mismatch"):
        acc.fold(skew)
    got = acc.finalize(like=good[0])
    want = quantized_weighted_mean(good, [1.0, 1.0], SPEC)
    for k in ("a", "b"):
        assert got[k].tobytes() == want[k].tobytes()
    # with a template, even the FIRST frame is gated pre-mutation
    acc2 = SlotAccumulator(SPEC, like=good[0])
    with pytest.raises(ValueError, match="structure mismatch"):
        acc2.fold(skew)
    # seed-count skew (a truncated sharing) is rejected too
    bad = encode_secure_quant(good[0], 0.5, SPEC,
                              np.random.default_rng(1))
    bad["seeds"] = bad["seeds"][:1]
    with pytest.raises(ValueError, match="mask seeds"):
        SlotAccumulator(SPEC).fold(bad)


# ------------------------------------------------ protocol integration


def _make_train_fn(c, lr=0.5):
    def train_fn(params, round_idx):
        p = {k: np.asarray(v, np.float32) for k, v in params.items()}
        p["w"] = p["w"] + lr * ((c + 1) - p["w"])
        return p, 10.0 * (c + 1)

    return train_fn


def _run(server, clients, timeout=60):
    threads = [threading.Thread(target=m.run)
               for m in [server] + clients]
    for t in threads:
        t.start()
    assert server._done.wait(timeout=timeout), "protocol stalled"
    for t in threads:
        t.join(timeout=10)
    return server


def test_cross_silo_secure_quant_bitwise_vs_quantized_replay():
    """The full two-phase protocol over REAL sockets: the secure-quant
    aggregate equals a host replay of the plain quantized weighted mean
    round by round, BITWISE — and stays within quantization tolerance
    of the plain dense protocol."""
    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        FedAvgClientProc, FedAvgServer, SecureFedAvgClientProc,
        SecureFedAvgServer,
    )

    num_clients, comm_round = 3, 2
    init = {"w": np.zeros((3,), np.float32)}
    bp = free_port_block(8)
    plain = _run(
        FedAvgServer(init, comm_round, num_clients, base_port=bp),
        [FedAvgClientProc(c + 1, num_clients, _make_train_fn(c),
                          base_port=bp) for c in range(num_clients)])
    bp = free_port_block(8)
    sq = _run(
        SecureFedAvgServer(init, comm_round, num_clients, base_port=bp,
                           quant_spec=SPEC),
        [SecureFedAvgClientProc(c + 1, num_clients, _make_train_fn(c),
                                quant_spec=SPEC, mpc_seed=c,
                                base_port=bp)
         for c in range(num_clients)])
    assert len(sq.history) == comm_round
    np.testing.assert_allclose(sq.params["w"], plain.params["w"],
                               atol=4 * 2.0 ** -SPEC.frac_bits)
    from neuroimagedisttraining_tpu.privacy.secure_quant import (
        leaf_scales,
    )

    params = init
    for r in range(comm_round):
        trees = [_make_train_fn(c)(params, r)[0]
                 for c in range(num_clients)]
        params = quantized_weighted_mean(
            trees, [10.0 * (c + 1) for c in range(num_clients)], SPEC,
            scales=leaf_scales(params))
    assert params["w"].tobytes() == sq.params["w"].tobytes()


class _NullComm:
    def send_message(self, msg, **kw):
        pass

    def add_observer(self, o):
        pass

    def handle_receive_message(self):
        pass

    def stop_receive_message(self):
        pass

    def byte_stats(self):
        return {}


def test_secure_quant_phase_b_dropout_kill_one():
    """kill-1 between phases (the Bonawitz dropout cell): a client that
    got a weight but never uploads its frame is discarded atomically at
    the deadline, and the survivor aggregate is re-weighted — equal to
    the survivor-only quantized mean bitwise."""
    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        SecureFedAvgServer,
    )

    server = SecureFedAvgServer({"w": np.zeros(2, np.float32)}, 5, 2,
                                comm=_NullComm(), round_deadline=60.0,
                                quorum=1, quant_spec=SPEC)
    server.register_message_receive_handlers()
    for c in (1, 2):
        server._on_register(M.Message(M.MSG_TYPE_C2S_REGISTER, c, 0))
    for c, n in ((1, 10.0), (2, 30.0)):  # -> w_1 = 0.25, w_2 = 0.75
        msg = M.Message(M.MSG_TYPE_C2S_NUM_SAMPLES, c, 0)
        msg.add(M.ARG_NUM_SAMPLES, n)
        msg.add(M.ARG_ROUND_IDX, 0)
        server._on_num_samples(msg)
    assert server._phase == "B"
    x = {"w": np.asarray([1.5, -2.0], np.float32)}
    up = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, 1, 0)
    up.add(M.ARG_MODEL_PARAMS,
           encode_secure_quant(x, 0.25, SPEC, np.random.default_rng(0)))
    up.add(M.ARG_ROUND_IDX, 0)
    server._on_model(up)
    # client 2 never uploads; quorum=1 holds at the deadline
    server._on_deadline(0, server._deadline_gen)
    if server._timer is not None:
        server._timer.cancel()
    assert server.round_idx == 1
    q = mpc.quantize32(np.float32(0.25) * x["w"], p=SPEC.p,
                       frac_bits=SPEC.frac_bits)
    want = np.asarray(
        (1.0 / 0.25) * mpc.dequantize32(q, p=SPEC.p,
                                        frac_bits=SPEC.frac_bits),
        np.float64).astype(np.float32)
    assert server.params["w"].tobytes() == want.tobytes()


def test_weak_dp_server_accounting_pinned():
    """The plain server's weak_dp rounds report per-silo epsilon from
    the RDP ledger, pinned against the closed-form single-round
    conversion; a silo absent from a round is not charged for it."""
    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        FedAvgServer,
    )

    init = {"w": np.zeros(3, np.float32)}
    server = FedAvgServer(init, 3, 2, comm=_NullComm(),
                          defense="weak_dp", stddev=0.05,
                          norm_bound=5.0, dp_delta=1e-5)
    t1 = {"w": np.full(3, 1.0, np.float32)}
    t2 = {"w": np.full(3, 2.0, np.float32)}
    with server._rlock:
        server._updates = {1: (t1, 10.0), 2: (t2, 20.0)}
        server._aggregate_and_advance()
        server._updates = {1: (t1, 10.0)}  # silo 2 misses round 1
        server._aggregate_and_advance()
    e0 = server.history[0]["weak_dp"]
    assert e0["norm_bound"] == 5.0 and e0["stddev"] == 0.05
    z0 = weak_dp_noise_multiplier(0.05, 5.0, [10.0, 20.0])
    assert e0["noise_multiplier"] == pytest.approx(z0, abs=1e-6)
    eps1 = rdp_to_epsilon(rdp_gaussian(1.0, z0), delta=1e-5)[0]
    assert e0["epsilon_per_silo"][1] == pytest.approx(eps1, abs=5e-4)
    rep = server.dp_report()
    # silo 1: two rounds (z0 then z1); silo 2: one round — less spent
    assert rep["epsilon_per_silo"][1] > rep["epsilon_per_silo"][2]
    assert rep["epsilon_per_silo"][2] == pytest.approx(eps1, abs=5e-4)


def test_async_secure_quant_one_phase_buffer():
    """The buffered server + secure_quant (the lifted rejection):
    one-phase frames fold with integer-scaled staleness weights; the
    16-bit field is rejected at startup with the fix named."""
    from neuroimagedisttraining_tpu.asyncfl.server import (
        BufferedFedAvgServer,
    )

    init = {"w": np.zeros((3,), np.float32)}
    with pytest.raises(ValueError, match="field_bits 32"):
        BufferedFedAvgServer(init, 3, 3, buffer_k=3, comm=_NullComm(),
                             secure_quant=SPEC)
    spec32 = QuantSpec.from_bits(32)
    srv = BufferedFedAvgServer(init, 3, 3, buffer_k=3, comm=_NullComm(),
                               secure_quant=spec32)
    trees = [_make_train_fn(c)(init, 0)[0] for c in range(3)]
    ns = [10.0, 20.0, 30.0]
    for c, (t, n) in enumerate(zip(trees, ns), start=1):
        m = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, c, 0)
        m.add(M.ARG_MODEL_PARAMS, encode_secure_quant(
            t, 1.0, spec32, np.random.default_rng(c)))
        m.add(M.ARG_NUM_SAMPLES, float(n))
        m.add(M.ARG_ROUND_IDX, 0)
        m.add(M.ARG_UPLOAD_SEQ, 0)
        srv._on_model(m)
    assert srv.round_idx == 1, srv.upload_stats
    want = np.average(np.stack([t["w"] for t in trees]), axis=0,
                      weights=ns)
    # integer-scaled weights quantize the ratios to ~2^-6 relative
    np.testing.assert_allclose(srv.params["w"], want, atol=0.02)
    assert srv.history[0]["secure_quant"] is True
    audit = srv.upload_audit()
    assert audit["received_accounted"] and audit["accepted_accounted"]
    # a malformed frame (spec skew) is dropped, never a dead thread
    bad = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, 1, 0)
    bad.add(M.ARG_MODEL_PARAMS, encode_secure_quant(
        trees[0], 1.0, SPEC, np.random.default_rng(9)))
    bad.add(M.ARG_NUM_SAMPLES, 1.0)
    bad.add(M.ARG_ROUND_IDX, 1)
    bad.add(M.ARG_UPLOAD_SEQ, 1)
    srv._on_model(bad)
    assert srv.upload_stats["dropped_undecodable"] == 1
    # a STRUCTURALLY skewed frame (right spec, wrong leaf set) is also
    # dropped at admission — never a mid-buffer fold failure
    skew = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, 2, 0)
    skew.add(M.ARG_MODEL_PARAMS, encode_secure_quant(
        {"other": np.ones(5, np.float32)}, 1.0, spec32,
        np.random.default_rng(11)))
    skew.add(M.ARG_NUM_SAMPLES, 1.0)
    skew.add(M.ARG_ROUND_IDX, 1)
    skew.add(M.ARG_UPLOAD_SEQ, 1)
    srv._on_model(skew)
    assert srv.upload_stats["dropped_undecodable"] == 2
    audit = srv.upload_audit()
    assert audit["received_accounted"] and audit["accepted_accounted"]


def test_weak_dp_zero_stddev_is_warning_not_crash():
    """--defense weak_dp --stddev 0 (a no-noise ablation that predates
    the accountant) must keep aggregating — the ledger records nothing
    and warns once, instead of raising on the dispatch/timer thread."""
    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        FedAvgServer,
    )

    server = FedAvgServer({"w": np.zeros(3, np.float32)}, 2, 2,
                          comm=_NullComm(), defense="weak_dp",
                          stddev=0.0, norm_bound=5.0)
    t = {"w": np.full(3, 1.0, np.float32)}
    with server._rlock:
        server._updates = {1: (t, 10.0), 2: (t, 20.0)}
        server._aggregate_and_advance()
    assert server.round_idx == 1
    assert "weak_dp" not in server.history[0]
    assert server.dp_report() is None


def test_secure_server_quant_matrix():
    """The ctor compatibility matrix: quant lifts the clip-family
    rejection (client-side enforcement), keeps the order-statistic +
    quarantine + aggregator + codec rejections."""
    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        SecureFedAvgClientProc, SecureFedAvgServer,
    )

    init = {"w": np.zeros(3, np.float32)}
    bp = free_port_block(4)
    # clip family now composes (was rejected outright in dense mode)
    SecureFedAvgServer(init, 1, 2, base_port=bp, quant_spec=SPEC,
                       defense="weak_dp")._done.set()
    with pytest.raises(ValueError, match="neither order-statistic"):
        SecureFedAvgServer(init, 1, 2, base_port=bp, quant_spec=SPEC,
                           defense="trimmed_mean")
    with pytest.raises(ValueError, match="neither order-statistic"):
        SecureFedAvgServer(init, 1, 2, base_port=bp, quant_spec=SPEC,
                           quarantine_rounds=2)
    with pytest.raises(ValueError, match="n_aggregators"):
        SecureFedAvgServer(init, 1, 2, base_port=bp, quant_spec=SPEC,
                           n_aggregators=3)
    with pytest.raises(ValueError, match="incompatible"):
        SecureFedAvgServer(init, 1, 2, base_port=bp, quant_spec=SPEC,
                           wire_masks={"w": np.ones(3)})
    # dense mode still rejects the clip family (pointing at the fix)
    with pytest.raises(ValueError, match="secure_quant"):
        SecureFedAvgServer(init, 1, 2, base_port=bp,
                           defense="norm_diff_clipping")
    with pytest.raises(ValueError, match="clip family"):
        SecureFedAvgClientProc(1, 2, lambda p, r: (p, 1.0),
                               base_port=bp + 2, quant_spec=SPEC,
                               defense="median")
    with pytest.raises(ValueError, match="one_phase"):
        SecureFedAvgClientProc(1, 2, lambda p, r: (p, 1.0),
                               base_port=bp + 2, one_phase=True)


def test_client_side_defense_clips_before_share():
    """secure_quant + norm_diff_clipping: the CLIENT bounds its own
    update — a huge trained delta reaches the server clipped to
    norm_bound (verified through the full two-phase protocol)."""
    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        SecureFedAvgClientProc, SecureFedAvgServer,
    )

    init = {"w": np.zeros((4,), np.float32)}

    def wild(params, round_idx):
        return {"w": np.full(4, 100.0, np.float32)}, 10.0

    bp = free_port_block(8)
    server = _run(
        SecureFedAvgServer(init, 1, 1, base_port=bp, quant_spec=SPEC,
                           defense="norm_diff_clipping", norm_bound=2.0),
        [SecureFedAvgClientProc(1, 1, wild, quant_spec=SPEC,
                                defense="norm_diff_clipping",
                                norm_bound=2.0, base_port=bp)])
    norm = float(np.linalg.norm(server.params["w"]))
    assert norm == pytest.approx(2.0, abs=0.01), \
        f"update delta reached the server unclipped (|w| = {norm})"


# ------------------------------------------------ CLI startup matrix


def test_run_cli_privacy_matrix_rejections(capsys):
    from neuroimagedisttraining_tpu.distributed.run import main

    def err(argv, n="2"):
        with pytest.raises(SystemExit) as e:
            main(["--role", "server", "--num_clients", n, *argv])
        assert e.value.code == 2
        return capsys.readouterr().err

    # --secure + codec points at --secure_quant
    assert "--secure_quant" in err(["--secure", "--wire_codec",
                                    "delta+quant"])
    # --secure + defense points at --secure_quant
    assert "--secure_quant" in err(["--secure", "--defense", "weak_dp"])
    # secure_quant + order statistic stays rejected (n=4 keeps the
    # breakdown-point check out of the way — this is the secure error)
    assert "clip family" in err(["--secure_quant", "--defense",
                                 "trimmed_mean"], n="4")
    # secure_quant + aggregators rejected (seed expansion)
    assert "seeds" in err(["--secure_quant", "--n_aggregators", "3",
                           "--mpc_n_shares", "3"])
    # dense secure + async still rejected, quant named as the fix
    assert "--secure_quant" in err(["--async_server", "--secure"])
    # async + quant at 16 bits: capacity error names the 32-bit fix
    assert "field_bits 32" in err(["--async_server", "--secure_quant"])
    # headroom misconfig dies at argparse
    assert "headroom" in err(["--secure_quant",
                              "--secure_quant_frac_bits", "16"])


def test_main_cli_privacy_rejections(capsys):
    from neuroimagedisttraining_tpu.__main__ import main

    def err(argv):
        with pytest.raises(SystemExit) as e:
            main(argv)
        assert e.value.code == 2
        return capsys.readouterr().err

    assert "secure_quant" in err(["--algorithm", "turboaggregate",
                                  "--wire_codec", "delta+quant"])
    assert "clip family" in err(["--algorithm", "turboaggregate",
                                 "--defense", "krum"])
    assert "--dp_clip" in err(["--algorithm", "dpsgd",
                               "--dp_sigma", "1.0"])
    assert "dpsgd" in err(["--algorithm", "fedavg", "--dp_clip", "1.0"])


# ------------------------------------------------ engine integration


def test_dpsgd_dp_noise_and_accounting(tmp_path, synthetic_cohort):
    """dpsgd with --dp_clip/--dp_sigma: noise actually perturbs the
    models (vs the un-noised run), everything stays finite, and
    stat_info reports the accountant's per-round epsilon pinned against
    the closed-form full-participation composition."""
    import jax

    from tests.test_fedavg import _make_engine

    rounds = 2
    plain = _make_engine(tmp_path, synthetic_cohort, algorithm="dpsgd",
                         comm_round=rounds)
    noised = _make_engine(tmp_path, synthetic_cohort, algorithm="dpsgd",
                          comm_round=rounds, dp_clip=1.0, dp_sigma=1.0)
    res_p = plain.train()
    res_n = noised.train()
    leaves_p = jax.tree.leaves(res_p["global_params"])
    leaves_n = jax.tree.leaves(res_n["global_params"])
    assert all(np.all(np.isfinite(np.asarray(x))) for x in leaves_n)
    assert any(not np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(leaves_p, leaves_n)), \
        "dp_sigma=1.0 left the models bitwise identical to the " \
        "un-noised run — the noise path never ran"
    dp = noised.stat_info["dp"]
    assert len(dp["epsilon_per_round"]) == rounds
    want = [rdp_to_epsilon(r * rdp_gaussian(1.0, 1.0),
                           delta=1e-5)[0] for r in (1, 2)]
    np.testing.assert_allclose(dp["epsilon_per_round"], want, atol=5e-4)
    assert dp["epsilon"] == dp["epsilon_per_round"][-1]
    assert set(dp["epsilon_per_silo"]) == set(range(plain.real_clients))
    assert "dp" not in plain.stat_info


def test_engine_rejects_dp_flags_without_support(tmp_path,
                                                 synthetic_cohort):
    from tests.test_fedavg import _make_engine

    with pytest.raises(ValueError, match="dpsgd"):
        _make_engine(tmp_path, synthetic_cohort, algorithm="fedavg",
                     dp_clip=1.0, dp_sigma=1.0)
    with pytest.raises(ValueError, match="dp_clip"):
        _make_engine(tmp_path, synthetic_cohort, algorithm="dpsgd",
                     dp_sigma=1.0)


def test_fedavg_weak_dp_stat_info_observability(tmp_path,
                                                synthetic_cohort):
    """The weak_dp observability gap (satellite): the clip bound, sigma,
    effective noise multiplier, and running epsilon land in stat_info
    EVERY round, pinned against a direct ledger replay over the same
    deterministic cohorts."""
    from tests.test_fedavg import _make_engine

    rounds = 3
    eng = _make_engine(tmp_path, synthetic_cohort,
                       defense_type="weak_dp", comm_round=rounds,
                       norm_bound=5.0, stddev=0.05)
    eng.train()
    wd = eng.stat_info["weak_dp"]
    assert wd["norm_bound"] == 5.0 and wd["stddev"] == 0.05
    assert len(wd["epsilon_per_round"]) == rounds
    assert len(wd["noise_multiplier_per_round"]) == rounds
    # replay: same sampling contract, same weights, same ledger
    rdp = 0.0
    for r in range(rounds):
        sampled = eng.client_sampling(r)
        w = eng._n_train_host[np.asarray(sampled)]
        z = weak_dp_noise_multiplier(0.05, 5.0, w)
        assert wd["noise_multiplier_per_round"][r] == \
            pytest.approx(z, abs=1e-6)
        rdp = rdp + rdp_gaussian(len(sampled) / eng.real_clients, z)
        eps = rdp_to_epsilon(rdp, delta=1e-5)[0]
        assert wd["epsilon_per_round"][r] == pytest.approx(eps,
                                                           abs=5e-4)
    assert wd["epsilon"] == wd["epsilon_per_round"][-1]
