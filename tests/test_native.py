"""Native C++ host-data-path library: build, correctness vs numpy, and the
fetch_rows integration (native/gather.cpp via utils/native.py)."""

import numpy as np
import pytest

from neuroimagedisttraining_tpu.utils import native


@pytest.fixture(scope="module")
def lib():
    handle = native.load()
    if handle is None:
        pytest.skip("g++ unavailable: native library could not be built")
    return handle


def test_gather_rows_matches_numpy(lib):
    rng = np.random.default_rng(0)
    src = rng.integers(0, 256, size=(64, 7, 9), dtype=np.uint8)
    idx = rng.integers(0, 64, size=50)
    got = native.gather_rows(src, idx)
    np.testing.assert_array_equal(got, src[idx])


def test_gather_rows_into_preallocated(lib):
    rng = np.random.default_rng(1)
    src = rng.integers(0, 256, size=(32, 5), dtype=np.uint8)
    idx = np.asarray([3, 3, 0, 31])
    out = np.zeros((10, 5), np.uint8)
    res = native.gather_rows(src, idx, out=out)
    assert res is out
    np.testing.assert_array_equal(out[:4], src[idx])
    np.testing.assert_array_equal(out[4:], 0)


def test_numpy_fallback_for_non_u8():
    src = np.random.default_rng(3).normal(size=(8, 4)).astype(np.float32)
    idx = np.asarray([1, 5, 5])
    np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])


def test_fetch_rows_uses_native_path():
    from neuroimagedisttraining_tpu.data.hdf5 import fetch_rows

    rng = np.random.default_rng(4)
    src = rng.integers(0, 256, size=(40, 6, 6), dtype=np.uint8)
    idx = np.asarray([7, 2, 2, 39, 0])
    np.testing.assert_array_equal(fetch_rows(src, idx), src[idx])


def test_failed_build_logs_gpp_stderr(tmp_path, monkeypatch, caplog):
    """A compiler failure must not be silent: the g++ stderr is logged at
    warning level so the numpy-fallback slow path is diagnosable."""
    import logging

    bad_src = tmp_path / "broken.cpp"
    bad_src.write_text("this is not C++\n")
    monkeypatch.setattr(native, "_SRC", str(bad_src))
    monkeypatch.setattr(native, "_SO", str(tmp_path / "broken.so"))
    with caplog.at_level(logging.WARNING,
                         logger="neuroimagedisttraining_tpu.native"):
        assert native._build() is False
    assert any("native gather build failed" in r.message
               for r in caplog.records)
    # the g++ diagnostic itself (or, without a toolchain, the OSError)
    # made it into the log record
    assert any("error" in r.message.lower() or "No such file" in r.message
               for r in caplog.records)
