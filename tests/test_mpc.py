"""Finite-field MPC toolkit (ops/mpc.py): BGW/LCC encode-decode roundtrips,
Lagrange coefficient algebra, additive shares, fixed-point quantization,
and the secure-aggregation engine matching plain FedAvg
(mpc_function.py:4-275 capability parity)."""

import numpy as np

from neuroimagedisttraining_tpu.ops import mpc
import pytest

P = mpc.P_DEFAULT


def test_mod_inv_is_inverse():
    rng = np.random.default_rng(0)
    a = rng.integers(1, P, size=64)
    inv = mpc.mod_inv(a, P)
    np.testing.assert_array_equal((a * inv) % P, np.ones(64, np.int64))


def test_lagrange_reproduces_polynomial():
    # interpolating a degree-2 polynomial through 3 points must re-evaluate
    # it exactly anywhere in the field
    def f(x):
        return (3 + 5 * x + 7 * x * x) % P

    betas = np.asarray([1, 2, 3], np.int64)
    targets = np.asarray([0, 10, 1000], np.int64)
    U = mpc.lagrange_coeffs(targets, betas, P)
    vals = f(betas)
    got = (U @ vals) % P
    np.testing.assert_array_equal(got, f(targets))


def test_bgw_roundtrip_and_secrecy_threshold():
    rng = np.random.default_rng(1)
    secret = rng.integers(0, 1000, size=(4, 6)).astype(np.int64)
    N, T = 7, 2
    shares = mpc.bgw_encode(secret, N, T, rng=rng)
    assert shares.shape == (N, 4, 6)
    # any T+1 shares reconstruct
    idx = np.asarray([0, 3, 6])
    rec = mpc.bgw_decode(shares[idx], idx)
    np.testing.assert_array_equal(rec, secret)
    # a different subset agrees
    idx2 = np.asarray([1, 2, 4, 5])
    rec2 = mpc.bgw_decode(shares[idx2], idx2)
    np.testing.assert_array_equal(rec2, secret)


def test_bgw_linear_homomorphism():
    # sum of two parties' shares decodes to the sum of secrets — the property
    # secure aggregation relies on
    rng = np.random.default_rng(2)
    a = rng.integers(0, 1000, size=(3, 2)).astype(np.int64)
    b = rng.integers(0, 1000, size=(3, 2)).astype(np.int64)
    sa = mpc.bgw_encode(a, 5, 1, rng=rng)
    sb = mpc.bgw_encode(b, 5, 1, rng=rng)
    idx = np.asarray([0, 2, 4])
    rec = mpc.bgw_decode((sa + sb)[idx] % P, idx)
    np.testing.assert_array_equal(rec, (a + b) % P)


def test_lcc_roundtrip():
    rng = np.random.default_rng(3)
    X = rng.integers(0, 1000, size=(8, 5)).astype(np.int64)  # K=4 chunks of 2
    N, K, T = 9, 4, 2
    shares = mpc.lcc_encode(X, N, K, T, rng=rng)
    assert shares.shape == (N, 2, 5)
    idx = np.arange(K + T)  # K+T evaluations suffice for degree K+T-1
    rec = mpc.lcc_decode(shares[idx], N, K, T, idx)
    np.testing.assert_array_equal(rec, X)


def test_lcc_shares_never_leak_plaintext_chunks():
    """Evaluation points must be disjoint from interpolation points — the
    reference's overlapping grids hand workers raw data chunks in the
    clear (deliberate deviation, see _lcc_points docstring)."""
    rng = np.random.default_rng(7)
    X = rng.integers(0, 1000, size=(8, 5)).astype(np.int64)
    N, K, T = 9, 4, 2
    shares = mpc.lcc_encode(X, N, K, T, rng=rng)
    chunks = X.reshape(K, 2, 5)
    for i in range(N):
        for j in range(K):
            assert not np.array_equal(shares[i] % P, chunks[j] % P), (i, j)


def test_additive_shares_sum_and_mask():
    rng = np.random.default_rng(4)
    x = rng.integers(0, 1000, size=(10,)).astype(np.int64)
    shares = mpc.additive_shares(x, 4, rng=rng)
    np.testing.assert_array_equal(shares.sum(axis=0) % P, x)
    # no single share equals the secret (overwhelmingly likely)
    assert not any(np.array_equal(shares[i] % P, x) for i in range(4))


def test_quantize_roundtrip():
    rng = np.random.default_rng(5)
    x = rng.normal(size=(100,)).astype(np.float32)
    q = mpc.quantize(x)
    back = mpc.dequantize(q)
    np.testing.assert_allclose(back, x, atol=2.0 ** -16)


def test_quantized_additive_aggregation_exact():
    # the full TurboAggregate path on vectors: quantize -> share -> sum of
    # ALL shares -> dequantize == plain sum (to fixed-point precision)
    rng = np.random.default_rng(6)
    xs = [rng.normal(size=(32,)) * 0.1 for _ in range(5)]
    acc = np.zeros(32, np.int64)
    for x in xs:
        sh = mpc.additive_shares(mpc.quantize(x), 3, rng=rng)
        acc = (acc + sh.sum(axis=0)) % P
    got = mpc.dequantize(acc)
    np.testing.assert_allclose(got, np.sum(xs, axis=0), atol=5 * 2.0 ** -16)


def test_secure_sum_matches_plain_sum():
    rng = np.random.default_rng(11)
    stack = rng.normal(size=(6, 40)) * 0.2
    got = mpc.secure_sum(stack, n_shares=3, rng=np.random.default_rng(1))
    np.testing.assert_allclose(got, stack.sum(axis=0), atol=6 * 2.0 ** -16)
    # rng only decorrelates the masking material — aggregate is invariant
    got2 = mpc.secure_sum(stack, n_shares=5, frac_bits=16,
                          rng=np.random.default_rng(999))
    np.testing.assert_allclose(got2, got, atol=1e-12)


def test_secure_sum_never_materializes_client_updates():
    """The privacy invariant (VERDICT r2 weak #2): share slots accumulate
    across ALL clients before any slots are combined, so no server-side
    intermediate array ever equals an individual client's quantized
    update."""
    rng = np.random.default_rng(7)
    stack = rng.normal(size=(4, 64)) * 0.5
    qs = [mpc.quantize(x) for x in stack]
    trace = []
    got = mpc.secure_sum(stack, n_shares=3, rng=np.random.default_rng(7),
                         trace=trace)
    # 3 slot-accumulator states recorded after each of 4 clients
    assert len(trace) == 12
    for inter in trace:
        for q in qs:
            assert not np.array_equal(inter, q), \
                "server-side intermediate equals a client's plaintext update"
    np.testing.assert_allclose(got, stack.sum(axis=0), atol=4 * 2.0 ** -16)


def test_secure_sum_device_matches_plain_sum_and_host():
    """On-device MPC (ops/mpc_device.py): the jitted uint32 mod-p pipeline
    reconstructs the plain sum to quantization tolerance, is invariant to
    the masking key, and agrees with the host numpy path."""
    import jax

    from neuroimagedisttraining_tpu.ops import mpc_device as D

    rng = np.random.default_rng(11)
    stack = (rng.normal(size=(6, 40)) * 0.2).astype(np.float32)
    got = np.asarray(jax.jit(
        lambda s, k: D.secure_sum_device(s, k, n_shares=3))(
            stack, jax.random.key(0)))
    np.testing.assert_allclose(got, stack.sum(axis=0), atol=6 * 2.0 ** -16)
    # key/n_shares only decorrelate the masking material
    got2 = np.asarray(D.secure_sum_device(stack, jax.random.key(99),
                                          n_shares=5))
    np.testing.assert_allclose(got2, got, atol=1e-6)
    # and the two backends implement the same aggregation (float32 vs
    # float64 quantize rounding can differ by one LSB per element)
    host = mpc.secure_sum(stack, n_shares=3, rng=np.random.default_rng(1))
    np.testing.assert_allclose(got, host, atol=8 * 2.0 ** -16)


def test_secure_sum_device_slots_are_masked():
    """Privacy invariant on device: the only server-visible intermediates
    (per-slot totals) are uniformly-random masked material — none equals
    any client's quantized update or the plain quantized sum."""
    import jax

    from neuroimagedisttraining_tpu.ops import mpc_device as D

    rng = np.random.default_rng(7)
    stack = (rng.normal(size=(4, 64)) * 0.5).astype(np.float32)
    out, slots = D.secure_sum_device(stack, jax.random.key(3), n_shares=3,
                                     return_slots=True)
    np.testing.assert_allclose(np.asarray(out), stack.sum(axis=0),
                               atol=4 * 2.0 ** -16)
    qs = [np.asarray(D.quantize_device(x)) for x in stack]
    q_total = np.asarray(D.quantize_device(stack)).astype(np.int64)
    q_sum = np.mod(q_total.sum(axis=0), mpc.P_DEFAULT)
    for slot in np.asarray(slots):
        for q in qs:
            assert not np.array_equal(slot, q), \
                "slot total equals a client's plaintext update"
        assert not np.array_equal(slot.astype(np.int64), q_sum), \
            "slot total equals the plain quantized sum"


@pytest.mark.slow
def test_secure_sum_device_fori_bitwise_equals_unrolled():
    """ADVICE r5: the fori_loop reductions (trace size O(1) in clients
    and shares) must be BITWISE-equal to the historical Python-unrolled
    accumulation — same ascending order, same _addmod lattice."""
    import jax
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.ops import mpc_device as D

    def unrolled(stack, key, n_shares, p=mpc.P_DEFAULT):
        q = D.quantize_device(stack)
        r = jax.random.randint(key, (n_shares - 1,) + q.shape, 0, p,
                               dtype=jnp.int32).astype(jnp.uint32)
        rsum = r[0]
        for j in range(1, n_shares - 1):
            rsum = D._addmod(rsum, r[j], jnp.uint32(p))
        last = D._addmod(q, jnp.uint32(p) - rsum, jnp.uint32(p))

        def client_sum(slot):
            acc = slot[0]
            for c in range(1, stack.shape[0]):
                acc = D._addmod(acc, slot[c], jnp.uint32(p))
            return acc

        slots = [client_sum(r[j]) for j in range(n_shares - 1)]
        slots.append(client_sum(last))
        total = slots[0]
        for j in range(1, n_shares):
            total = D._addmod(total, slots[j], jnp.uint32(p))
        return (D.dequantize_device(total), jnp.stack(slots))

    rng = np.random.default_rng(0)
    for S, n_shares in ((1, 2), (2, 3), (5, 2), (4, 6)):
        stack = (rng.normal(size=(S, 17)) * 0.7).astype(np.float32)
        key = jax.random.key(S * 10 + n_shares)
        got, gslots = D.secure_sum_device(stack, key, n_shares,
                                          return_slots=True)
        want, wslots = unrolled(stack, key, n_shares)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        np.testing.assert_array_equal(np.asarray(gslots),
                                      np.asarray(wslots))


def test_quantize_device_overflow_boundary_guard():
    """ADVICE r5: |x|*2^frac_bits beyond int32 range must SATURATE
    sign-preservingly inside the field instead of XLA's cast-to-2^31-1
    (== p, an out-of-field residue the host path never produces)."""
    import jax
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.ops import mpc_device as D

    # in-range boundary neighborhood: device == host embedding exactly
    xs = np.asarray([16383.0, -16383.0, 1.0, -1.0, 0.0], np.float32)
    dev = np.asarray(jax.jit(D.quantize_device)(jnp.asarray(xs)))
    host = mpc.quantize(np.asarray(xs, np.float64))
    np.testing.assert_array_equal(dev, host)
    # overflow: residues stay strictly inside the field with the sign
    # preserved through dequantize (no silent wrap/flip)
    big = np.asarray([1e9, -1e9], np.float32)  # * 2^16 >> 2^31
    q = np.asarray(jax.jit(D.quantize_device)(jnp.asarray(big)))
    assert (q < mpc.P_DEFAULT).all()
    dq = np.asarray(D.dequantize_device(jnp.asarray(q)))
    assert dq[0] > 0 and dq[1] < 0, "saturation must preserve sign"


@pytest.mark.slow
def test_turboaggregate_host_backend_still_works(tmp_path,
                                                 synthetic_cohort):
    """mpc_backend='host' keeps the boundary-modeling numpy path alive."""
    import jax

    from tests.test_fedavg import _make_engine

    eng = _make_engine(tmp_path, synthetic_cohort,
                       algorithm="turboaggregate", mpc_backend="host")
    assert eng.cfg.fed.mpc_backend == "host"
    res = eng.train()
    assert np.isfinite(res["history"][-1]["train_loss"])
    assert all(np.all(np.isfinite(np.asarray(l)))
               for l in jax.tree.leaves(res["params"]))


def test_key_agreement_symmetric():
    p, g = 2**31 - 1, 5
    sk_a, sk_b = 123457, 987653
    pk_a, pk_b = mpc.pk_gen(sk_a, p, g), mpc.pk_gen(sk_b, p, g)
    assert mpc.key_agreement(sk_a, pk_b, p, g) == \
        mpc.key_agreement(sk_b, pk_a, p, g)


@pytest.mark.slow
def test_turboaggregate_engine_matches_fedavg(tmp_path, synthetic_cohort):
    """Secure aggregation must equal plain FedAvg up to fixed-point
    rounding: train 2 rounds with each, compare final params."""
    import jax

    from tests.test_fedavg import _make_engine

    eng_plain = _make_engine(tmp_path, synthetic_cohort, algorithm="fedavg")
    eng_sec = _make_engine(tmp_path, synthetic_cohort,
                           algorithm="turboaggregate")
    res_p = eng_plain.train()
    res_s = eng_sec.train()
    for lp, ls in zip(jax.tree.leaves(res_p["params"]),
                      jax.tree.leaves(res_s["params"])):
        # two rounds of quantization error, amplified through training; the
        # trajectories stay close but not bitwise
        np.testing.assert_allclose(np.asarray(lp), np.asarray(ls),
                                   atol=5e-3)


# ---------------------------------------------------------------------------
# GF(p) host==device boundary sweep (ISSUE 8 satellite): the float32
# embedding (mpc.quantize32) must be BITWISE-identical to the device one
# across the secure-quant field tier, including the field-edge clamp,
# and a dropped-client round must reconstruct over the survivors only
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("p,frac_bits", [
    (mpc.FIELD_PRIMES[8], 2),
    (mpc.FIELD_PRIMES[16], 8),
    (mpc.FIELD_PRIMES[16], 10),
    (mpc.FIELD_PRIMES[32], 16),
])
def test_quantize32_host_device_bitwise_sweep(p, frac_bits):
    """Host int64 path (x64 numpy) vs device path (x64-disabled jax):
    identical residues over ordinary values, the exact field-edge
    neighborhood, and the saturating overflow region."""
    import jax
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.ops import mpc_device as D

    edge = (p - 1) // 2 / float(1 << frac_bits)
    rng = np.random.default_rng(p % 1000 + frac_bits)
    xs = np.concatenate([
        (rng.standard_normal(64) * 0.5).astype(np.float32),
        np.asarray([edge, -edge, edge * 0.999, -edge * 0.999,
                    edge * 2, -edge * 2, 1e9, -1e9, 0.0],
                   np.float32),
    ])
    host = mpc.quantize32(xs, p=p, frac_bits=frac_bits)
    dev = np.asarray(jax.jit(
        lambda v: D.quantize_device(v, p=p, frac_bits=frac_bits))(
        jnp.asarray(xs))).astype(np.int64)
    np.testing.assert_array_equal(host, dev)
    assert (host < p).all()  # residues stay strictly inside the field
    # the centered lifts agree bitwise too
    hback = mpc.dequantize32(host, p=p, frac_bits=frac_bits)
    dback = np.asarray(D.dequantize_device(jnp.asarray(dev, jnp.uint32),
                                           p=p, frac_bits=frac_bits))
    assert hback.tobytes() == dback.tobytes()


@pytest.mark.parametrize("p,frac_bits", [
    (mpc.FIELD_PRIMES[16], 10),
    (mpc.FIELD_PRIMES[32], 16),
])
def test_secure_sum_device_small_field_matches_host_fold(p, frac_bits):
    """The device fori_loop pipeline at the secure-quant field tiers
    equals the host slot fold bitwise — the mask material cancels in
    both lattices, leaving the identical quantized sum."""
    import jax

    from neuroimagedisttraining_tpu.ops import mpc_device as D

    rng = np.random.default_rng(17)
    stack = (rng.standard_normal((5, 33)) * 0.3).astype(np.float32)
    dev = np.asarray(D.secure_sum_device(stack, jax.random.key(3),
                                         n_shares=3,
                                         frac_bits=frac_bits, p=p))
    acc = np.zeros(33, np.int64)
    for row in stack:
        acc = (acc + mpc.quantize32(row, p=p, frac_bits=frac_bits)) % p
    host = mpc.dequantize32(acc, p=p, frac_bits=frac_bits)
    assert dev.tobytes() == host.tobytes()


def test_secure_quant_dropped_client_round_host_device():
    """Dropped-client reconstruction (the Bonawitz discard): the host
    fold over the SURVIVOR frames equals the device program over the
    survivor stack bitwise — the dropped client's mask material never
    entered either side, so nothing needs unmasking."""
    import jax

    from neuroimagedisttraining_tpu.ops import mpc_device as D
    from neuroimagedisttraining_tpu.privacy import (
        QuantSpec, SlotAccumulator, encode_secure_quant,
    )

    spec = QuantSpec()
    rng = np.random.default_rng(23)
    trees = [{"w": (rng.standard_normal(21) * 0.4).astype(np.float32)}
             for _ in range(4)]
    ws = [0.4, 0.3, 0.2, 0.1]
    surv = [0, 2, 3]  # client 1 dies mid-round
    acc = SlotAccumulator(spec)
    for i in surv:
        acc.fold(encode_secure_quant(trees[i], ws[i], spec,
                                     np.random.default_rng(80 + i)))
    host = acc.finalize(like=trees[0])["w"]
    stack = np.stack([np.float32(ws[i]) * trees[i]["w"] for i in surv])
    dev = np.asarray(D.secure_sum_device(stack, jax.random.key(5),
                                         n_shares=spec.n_shares,
                                         frac_bits=spec.frac_bits,
                                         p=spec.p))
    assert host.tobytes() == dev.tobytes()


def test_secure_sum_device_rejects_oversized_field():
    import jax

    from neuroimagedisttraining_tpu.ops import mpc_device as D

    with pytest.raises(ValueError, match="2\\^31"):
        D.secure_sum_device(np.ones((2, 3), np.float32),
                            jax.random.key(0), n_shares=2, p=1 << 31)
