"""asyncfl/: the buffered asynchronous control plane (ISSUE 7).

Covers the FedBuff-style server's numerical contract (buffered aggregate
with all-current uploads and ``buffer_k == cohort`` is BITWISE one
synchronous ``tree_weighted_mean`` round; staleness weights pinned
against a host replay), the version ring's codec-reference threading
(a stale delta frame decodes against the base the sender trained from —
and provably NOT against the current model), admission control
(max_staleness / future tags / seq-watermark dedup), the selector comm
core (mid-frame disconnect, slow-reader backpressure, legacy dial-in
interop), startup rejections, and a ``slow``-marked 200-client loadgen
smoke with seeded crash/rejoin churn.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from neuroimagedisttraining_tpu.asyncfl.loop import SelectorCommManager
from neuroimagedisttraining_tpu.asyncfl.server import (
    BufferedFedAvgServer,
    staleness_weight,
)
from neuroimagedisttraining_tpu.codec import wire as codec
from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.distributed.comm import Observer
from neuroimagedisttraining_tpu.distributed.cross_silo import (
    FedAvgClientProc,
    survivor_weighted_mean,
)
from neuroimagedisttraining_tpu.distributed.ports import free_port_block


class _CaptureComm:
    """Minimal BaseCommManager stand-in for handler-level unit tests."""

    def __init__(self):
        self.sent = []

    def send_message(self, msg, **kw):
        self.sent.append(msg)

    def add_observer(self, obs):
        pass

    def remove_observer(self, obs):
        pass

    def handle_receive_message(self):
        pass

    def stop_receive_message(self):
        pass

    def byte_stats(self):
        return {}


def _tree(seed, scale=1.0):
    rng = np.random.default_rng(seed)
    return {"params": {"w": (scale * rng.standard_normal(12)
                             ).astype(np.float32),
                       "b": (scale * rng.standard_normal(3)
                             ).astype(np.float32)}}


def _upload(sender, tree, n, version, seq=None):
    msg = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, sender, 0)
    msg.add(M.ARG_MODEL_PARAMS, tree)
    msg.add(M.ARG_NUM_SAMPLES, float(n))
    msg.add(M.ARG_ROUND_IDX, int(version))
    if seq is not None:
        msg.add(M.ARG_UPLOAD_SEQ, int(seq))
    return msg


def _server(num_clients=3, comm_round=10, **kw):
    kw.setdefault("buffer_k", num_clients)
    return BufferedFedAvgServer(_tree(0), comm_round, num_clients,
                                comm=_CaptureComm(), **kw)


# ------------------------------------------------ numerical contract


def test_buffer_k_cohort_zero_staleness_is_sync_round_bitwise():
    """THE equivalence pin: all-current uploads filling a cohort-sized
    buffer reproduce one synchronous round — the very
    ``survivor_weighted_mean`` (jitted ``tree_weighted_mean``) call the
    synchronous server's ``_aggregate_and_advance`` makes over the same
    upload set, bitwise."""
    srv = _server(num_clients=3, buffer_k=3, staleness_alpha=0.7)
    trees = [_tree(s + 1) for s in range(3)]
    ns = [5.0, 9.0, 2.0]
    for s, (t, n) in enumerate(zip(trees, ns), start=1):
        srv._on_model(_upload(s, t, n, version=0, seq=0))
    assert srv.round_idx == 1
    expect = survivor_weighted_mean(trees, ns)
    for k in ("w", "b"):
        got, want = srv.params["params"][k], expect["params"][k]
        assert got.tobytes() == want.tobytes()
    # the recorded weights are EXACTLY the sample counts (tau == 0)
    assert srv.history[0]["weights"] == ns
    assert srv.history[0]["taus"] == [0, 0, 0]


def test_staleness_weights_pinned_against_host_replay():
    srv = _server(num_clients=2, buffer_k=1, staleness_alpha=0.5,
                  max_staleness=10)
    # two k=1 aggregations advance the version to 2
    srv._on_model(_upload(1, _tree(1), 4.0, version=0, seq=0))
    srv._on_model(_upload(1, _tree(2), 4.0, version=1, seq=1))
    assert srv.round_idx == 2
    # an upload still based on version 0 arrives: tau = 2
    srv._on_model(_upload(2, _tree(3), 6.0, version=0, seq=0))
    assert srv.round_idx == 3
    entry = srv.history[-1]
    assert entry["taus"] == [2]
    replay = staleness_weight(6.0, 2, 0.5)
    assert entry["weights"] == [replay]
    assert replay == 6.0 * (1.0 + 2.0) ** -0.5
    # zero staleness is an EXACT passthrough of the sample count
    assert staleness_weight(7.0, 0, 0.5) == 7.0


def test_stale_upload_is_delta_transported_to_current_base():
    """A stale model u (trained from ring[v]) must contribute
    ``u + (params_now - ring[v])`` — its learning delta applied to the
    current anchor — replayed here in host numpy, bitwise."""
    srv = _server(num_clients=2, buffer_k=2, comm_round=10)
    ref0 = srv.params
    srv._on_model(_upload(1, _tree(1), 4.0, version=0, seq=0))
    srv._on_model(_upload(2, _tree(2), 4.0, version=0, seq=0))
    assert srv.round_idx == 1
    stale = _tree(5)
    srv._on_model(_upload(1, stale, 4.0, version=0, seq=1))
    buffered = srv._buffer[-1]["tree"]
    for k in ("w", "b"):
        want = (stale["params"][k]
                + (srv.params["params"][k] - ref0["params"][k]))
        assert buffered["params"][k].tobytes() == want.tobytes()


# ------------------------------------------------ version-tagged codec


def test_stale_delta_frame_decodes_against_its_ring_reference():
    """PR 3 reference threading generalized: the server must decode a
    delta frame against the EXACT tree it broadcast under the frame's
    version tag, not the current model — pinned both ways."""
    spec = codec.parse_wire_spec("delta")
    srv = _server(num_clients=2, buffer_k=1, comm_round=10,
                  max_staleness=5)
    ref0 = srv.params
    srv._on_model(_upload(2, _tree(9), 4.0, version=0, seq=0))
    assert srv.round_idx == 1 and np.any(
        srv.params["params"]["w"] != ref0["params"]["w"])
    # client 1 trained from version 0 and encodes its delta against it
    u = _tree(4)
    frame, _ = codec.encode_update(spec, u, reference=ref0)
    srv._on_model(_upload(1, frame, 4.0, version=0, seq=0))
    assert srv.upload_stats["accepted"] == 2
    # the aggregate consumed decode(frame, ref0) delta-transported to
    # the current base — replay the whole pipeline on host
    decoded = codec.decode_update(frame, like=ref0, reference=ref0)
    agg = srv.history[-1]
    u_eff = {"params": {
        k: decoded["params"][k]
        + (srv._ring[1]["params"][k] - ref0["params"][k])
        for k in ("w", "b")}}
    expect = survivor_weighted_mean([u_eff], agg["weights"])
    for k in ("w", "b"):
        assert srv.params["params"][k].tobytes() == \
            expect["params"][k].tobytes()
    # decoding against the WRONG (current) reference is provably a
    # different update — the bug the ring exists to prevent
    wrong = codec.decode_update(frame, like=ref0,
                                reference=srv._ring[1])
    assert np.any(wrong["params"]["w"] != decoded["params"]["w"])


# ------------------------------------------------ admission control


def test_max_staleness_future_and_seq_dedup_gates():
    srv = _server(num_clients=2, buffer_k=1, max_staleness=2,
                  comm_round=50)
    # future tag
    srv._on_model(_upload(1, _tree(1), 4.0, version=7, seq=0))
    assert srv.upload_stats["dropped_future"] == 1
    # advance 4 versions; a version-0 upload is now ancient
    for i in range(4):
        srv._on_model(_upload(1, _tree(i), 4.0, version=srv.round_idx,
                              seq=i + 1))
    assert srv.round_idx == 4
    srv._on_model(_upload(2, _tree(2), 4.0, version=0, seq=0))
    assert srv.upload_stats["dropped_stale"] == 1
    # the ring holds exactly max_staleness + 1 versions
    assert sorted(srv._ring) == [2, 3, 4]
    # transport re-delivery (same seq) is dropped; an honest repeat
    # contribution from the same base version (fresh seq) is accepted
    srv._on_model(_upload(2, _tree(3), 4.0, version=4, seq=5))
    srv._on_model(_upload(2, _tree(3), 4.0, version=srv.round_idx,
                          seq=5))
    assert srv.upload_stats["dropped_duplicate"] == 1
    before = srv.upload_stats["accepted"]
    srv._on_model(_upload(2, _tree(4), 4.0, version=srv.round_idx,
                          seq=6))
    assert srv.upload_stats["accepted"] == before + 1
    audit = srv.upload_audit()
    assert audit["received_accounted"] and audit["accepted_accounted"]


def test_every_upload_gets_a_sync_reply_and_nonfinite_rejected():
    srv = _server(num_clients=2, buffer_k=2, comm_round=50)
    comm = srv.com_manager
    bad = _tree(1)
    bad["params"]["w"][0] = np.nan
    srv._on_model(_upload(1, bad, 4.0, version=0, seq=0))
    assert srv.upload_stats["dropped_nonfinite"] == 1
    srv._on_model(_upload(2, _tree(2), 4.0, version=0, seq=0))
    # both senders were re-synced (liveness never depends on the verdict)
    syncs = [m for m in comm.sent
             if m.msg_type == M.MSG_TYPE_S2C_SYNC_MODEL]
    assert {m.receiver_id for m in syncs} == {1, 2}
    assert all(int(m.get(M.ARG_ROUND_IDX)) == srv.round_idx
               for m in syncs)


def test_duplicated_nonfinite_frame_strikes_once():
    """A transport-duplicated REJECTED frame must repeat the VERDICT,
    not the processing: the watermark advances at the gate, so the
    re-delivery is duplicate-dropped and an honest silo's one transient
    NaN cannot strike (and eventually quarantine) twice."""
    srv = _server(num_clients=3, buffer_k=3, comm_round=50,
                  quarantine_rounds=2, outlier_threshold=2)
    bad = _tree(1)
    bad["params"]["w"][0] = np.nan
    srv._on_model(_upload(1, bad, 4.0, version=0, seq=0))
    srv._on_model(_upload(1, bad, 4.0, version=0, seq=0))  # dup
    assert srv.upload_stats["dropped_nonfinite"] == 1
    assert srv.upload_stats["dropped_duplicate"] == 1
    assert srv._strikes.get(1, 0) == 1
    assert srv.quarantined_clients() == set()
    audit = srv.upload_audit()
    assert audit["received_accounted"] and audit["accepted_accounted"]


def test_register_has_no_barrier_and_resets_seq_watermark():
    srv = _server(num_clients=3, buffer_k=3, comm_round=50)
    comm = srv.com_manager
    srv._on_register(M.Message(M.MSG_TYPE_C2S_REGISTER, 1, 0))
    # ONE registration already got the model (no barrier)
    assert comm.sent[-1].msg_type == M.MSG_TYPE_S2C_INIT_CONFIG
    assert comm.sent[-1].receiver_id == 1
    srv._on_model(_upload(1, _tree(1), 4.0, version=0, seq=7))
    assert srv._seq_seen[1] == 7
    # a restarted process re-registers and restarts its counter
    srv._on_register(M.Message(M.MSG_TYPE_C2S_REGISTER, 1, 0))
    assert comm.sent[-1].msg_type == M.MSG_TYPE_S2C_SYNC_MODEL
    srv._on_model(_upload(1, _tree(2), 4.0, version=0, seq=0))
    assert srv.upload_stats["dropped_duplicate"] == 0
    assert srv.upload_stats["accepted"] == 2


def test_fast_client_holds_one_buffer_slot():
    """A client lapping the buffer REPLACES its own entry instead of
    occupying extra slots — the armed defense's f-bound is per CLIENT
    (robust._check_f validates entries, so entries must be clients),
    and fast clients cannot outweigh slow ones by pace alone."""
    srv = _server(num_clients=3, buffer_k=3, comm_round=50)
    srv._on_model(_upload(1, _tree(1), 4.0, version=0, seq=0))
    srv._on_model(_upload(1, _tree(2), 4.0, version=0, seq=1))
    srv._on_model(_upload(1, _tree(3), 4.0, version=0, seq=2))
    # three accepted uploads, ONE buffer slot, no aggregation yet
    assert srv.upload_stats["accepted"] == 3
    assert srv.upload_stats["superseded_in_buffer"] == 2
    assert len(srv._buffer) == 1 and srv.round_idx == 0
    # the surviving entry is the NEWEST
    assert srv._buffer[0]["tree"]["params"]["w"].tobytes() == \
        _tree(3)["params"]["w"].tobytes()
    srv._on_model(_upload(2, _tree(4), 4.0, version=0, seq=0))
    srv._on_model(_upload(3, _tree(5), 4.0, version=0, seq=0))
    assert srv.round_idx == 1
    assert srv.history[-1]["contributors"] == [1, 2, 3]
    audit = srv.upload_audit()
    assert audit["received_accounted"] and audit["accepted_accounted"]


def test_malformed_upload_fields_never_kill_dispatch():
    """A frame that decodes as a Message but carries broken FIELDS
    (missing num_samples, non-numeric tags) must be dropped and
    counted, not raise through the dispatch thread."""
    srv = _server(num_clients=2, buffer_k=2, comm_round=50)
    bad = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, 1, 0)
    bad.add(M.ARG_MODEL_PARAMS, _tree(1))
    bad.add(M.ARG_ROUND_IDX, 0)  # no ARG_NUM_SAMPLES
    srv._on_model(bad)
    worse = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, 2, 0)
    worse.add(M.ARG_MODEL_PARAMS, _tree(2))
    worse.add(M.ARG_NUM_SAMPLES, 4.0)
    worse.add(M.ARG_ROUND_IDX, "not-a-version")
    srv._on_model(worse)
    assert srv.upload_stats["dropped_malformed"] == 2
    # the server still works afterwards
    srv._on_model(_upload(1, _tree(1), 4.0, version=0, seq=0))
    srv._on_model(_upload(2, _tree(2), 4.0, version=0, seq=0))
    assert srv.round_idx == 1
    audit = srv.upload_audit()
    assert audit["received_accounted"] and audit["accepted_accounted"]


def test_aggregation_is_client_id_ordered_not_arrival_ordered():
    """Float reduction order must not depend on OS scheduling: the
    buffer aggregates in client-id order (the sync server's sorted-
    senders discipline), so any arrival order of the same upload set
    produces the same model bitwise."""
    trees = {1: _tree(1), 2: _tree(2), 3: _tree(3)}
    ns = {1: 5.0, 2: 9.0, 3: 2.0}

    def run(order):
        srv = _server(num_clients=3, buffer_k=3)
        for s in order:
            srv._on_model(_upload(s, trees[s], ns[s], version=0, seq=0))
        assert srv.round_idx == 1
        return srv
    a, b = run([3, 1, 2]), run([1, 2, 3])
    assert a.history[0]["contributors"] == [1, 2, 3]
    for k in ("w", "b"):
        assert a.params["params"][k].tobytes() == \
            b.params["params"][k].tobytes()


def test_loadgen_cohort_buffer_survives_permanent_crash():
    """buffer_k=0 (cohort-sized) plus one PERMANENT crash must not hang
    the harness: the corpse report shrinks the effective threshold."""
    from neuroimagedisttraining_tpu.asyncfl.loadgen import run_load

    r = run_load(mode="async", num_clients=8, aggregations=4,
                 buffer_k=0, fault_spec="crash:3@1", seed=2)
    assert r["rounds_or_aggregations"] == 4
    assert r["frames_reconciled"], r
    assert r["client_stats"]["crashes"] == 1


def test_suspect_corpse_lowers_buffer_threshold():
    """One slot per sender means a cohort-sized buffer can never fill
    once a client is permanently gone — a new heartbeat suspect must
    lower the effective threshold and flush the waiting buffer (what
    the monitor's _maybe_complete call does), not deadlock."""
    srv = _server(num_clients=3, buffer_k=3, comm_round=50)
    srv._on_model(_upload(1, _tree(1), 4.0, version=0, seq=0))
    srv._on_model(_upload(2, _tree(2), 4.0, version=0, seq=0))
    assert srv.round_idx == 0  # still waiting for client 3
    with srv._rlock:
        srv._suspect.add(3)
        srv._maybe_complete()
    assert srv.round_idx == 1
    assert srv.history[-1]["contributors"] == [1, 2]
    audit = srv.upload_audit()
    assert audit["received_accounted"] and audit["accepted_accounted"]


def test_run_cli_rejects_rejoin_fault_spec():
    """The multiprocess runner cannot revive a crashed client process:
    a rejoin: directive must die at startup, not silently never fire."""
    from neuroimagedisttraining_tpu.distributed.run import main

    with pytest.raises(SystemExit) as e:
        main(["--role", "client", "--rank", "1", "--num_clients", "2",
              "--fault_spec", "crash:1@1,rejoin:1@3"])
    assert e.value.code == 2


def test_quarantine_discard_keeps_accounting_reconciled():
    """An upload accepted into the buffer and then discarded because
    THIS aggregation's outlier scoring quarantined its sender is the
    one way accepted work is never aggregated — the audit must account
    it explicitly, and the quarantined silo is excluded from the very
    aggregation that convicted it."""
    srv = _server(num_clients=3, buffer_k=3, comm_round=50,
                  quarantine_rounds=3, outlier_threshold=1)
    srv._on_model(_upload(1, _tree(1), 4.0, version=0, seq=0))
    srv._on_model(_upload(2, _tree(2), 4.0, version=0, seq=0))
    srv._on_model(_upload(3, _tree(3, scale=1e4), 4.0, version=0,
                          seq=0))
    assert srv.round_idx == 1
    assert srv.quarantined_clients() == {3}
    assert srv.history[-1]["contributors"] == [1, 2]
    audit = srv.upload_audit()
    assert audit["quarantine_discarded"] == 1
    assert audit["accepted"] == 3 and audit["aggregated"] == 2
    assert audit["received_accounted"] and audit["accepted_accounted"]


# ------------------------------------------------ startup rejections


def test_async_misconfig_fails_at_startup():
    with pytest.raises(ValueError, match="no round barrier"):
        _server(round_deadline=5.0)
    with pytest.raises(ValueError, match="staleness_alpha"):
        _server(staleness_alpha=-1.0)
    with pytest.raises(ValueError, match="max_staleness"):
        _server(max_staleness=-1)
    # an order-statistic defense must be feasible over the BUFFER
    with pytest.raises(ValueError, match="trimmed_mean"):
        _server(num_clients=8, buffer_k=2, defense="trimmed_mean",
                byz_f=1)
    # ... and over the COHORT: one slot per sender caps every real
    # aggregation at num_clients, so buffer_k > cohort must not slip an
    # infeasible defense past the startup check (it would silently fall
    # back to the plain mean on every aggregation)
    with pytest.raises(ValueError, match="krum"):
        _server(num_clients=3, buffer_k=8, defense="krum", byz_f=1)


def test_run_cli_rejects_async_combos():
    from neuroimagedisttraining_tpu.distributed.run import main

    for extra in (["--secure"], ["--transport", "broker"],
                  ["--round_deadline", "5"]):
        with pytest.raises(SystemExit) as e:
            main(["--role", "server", "--num_clients", "2",
                  "--async_server", *extra])
        assert e.value.code == 2


def test_config_roundtrips_async_fields():
    from neuroimagedisttraining_tpu.config import (
        ExperimentConfig, FedConfig,
    )

    cfg = ExperimentConfig(fed=FedConfig(
        async_server=True, buffer_k=7, staleness_alpha=0.25,
        max_staleness=11))
    back = ExperimentConfig.from_dict(
        __import__("json").loads(cfg.to_json()))
    assert back.fed.async_server is True
    assert back.fed.buffer_k == 7
    assert back.fed.staleness_alpha == 0.25
    assert back.fed.max_staleness == 11


# ------------------------------------------------ selector comm core


class _Collector(Observer):
    def __init__(self):
        self.msgs = []
        self.evt = threading.Event()

    def receive_message(self, msg_type, msg):
        self.msgs.append(msg)
        self.evt.set()


def _raw_frame(msg):
    raw = msg.to_bytes()
    return struct.pack("!Q", len(raw)) + raw


def _mk_selector(n=4):
    port = free_port_block(2)
    mgr = SelectorCommManager(0, n, base_port=port,
                              max_pending_frames=4, send_timeout=5.0)
    col = _Collector()
    mgr.add_observer(col)
    t = threading.Thread(target=mgr.handle_receive_message, daemon=True)
    t.start()
    return mgr, col, port, t


def test_selector_survives_midframe_disconnect_and_malformed():
    mgr, col, port, t = _mk_selector()
    try:
        # 1: a peer promises 100 bytes, sends 10, slams the connection
        with socket.create_connection(("127.0.0.1", port)) as s:
            s.sendall(struct.pack("!Q", 100) + b"x" * 10)
        # 2: a peer sends garbage with a valid length prefix
        with socket.create_connection(("127.0.0.1", port)) as s:
            s.sendall(struct.pack("!Q", 5) + b"junk!")
        # 3: a well-formed frame still gets through afterwards
        with socket.create_connection(("127.0.0.1", port)) as s:
            s.sendall(_raw_frame(M.Message("hello", 3, 0)))
        assert col.evt.wait(5.0)
        assert [m.msg_type for m in col.msgs] == ["hello"]
        stats = mgr.byte_stats()
        assert stats["frames_recv"] == 1  # torn/garbage never counted
    finally:
        mgr.stop_receive_message()
        t.join(5.0)


def test_selector_slow_reader_backpressure_loses_nothing():
    """A reader that stops draining must stall the sender on the bounded
    write queue — and once it resumes, every frame arrives intact and in
    order (bytes are never dropped, never interleaved)."""
    mgr, col, port, t = _mk_selector()
    n_frames, payload = 12, np.zeros(1_000_000, np.uint8)
    sent_done = threading.Event()
    try:
        # a small receive window keeps the kernel from absorbing the
        # whole burst — the pressure must land on the write queue
        cli = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        cli.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 32768)
        cli.connect(("127.0.0.1", port))
        reg = M.Message(M.MSG_TYPE_C2S_REGISTER, 2, 0)
        reg.add(M.ARG_CONN_PERSISTENT, True)
        cli.sendall(_raw_frame(reg))
        assert col.evt.wait(5.0)  # rank 2 is now routable

        def _send_all():
            for i in range(n_frames):
                msg = M.Message("bulk", 0, 2)
                msg.add("i", i)
                msg.add("blob", payload)
                mgr.send_message(msg)
            sent_done.set()

        sender = threading.Thread(target=_send_all, daemon=True)
        sender.start()
        # the un-drained client caps the queue: the sender must still be
        # blocked after a grace period (4-frame bound << 12 frames)
        time.sleep(0.5)
        assert not sent_done.is_set(), \
            "sender ran ahead of the bounded write queue"
        # drain everything client-side; each frame must parse
        got = []
        buf = b""
        cli.settimeout(10.0)
        while len(got) < n_frames:
            while len(buf) < 8:
                buf += cli.recv(65536)
            (length,) = struct.unpack("!Q", buf[:8])
            while len(buf) < 8 + length:
                buf += cli.recv(65536)
            got.append(M.Message.from_bytes(buf[8:8 + length]))
            buf = buf[8 + length:]
        assert sent_done.wait(10.0)
        assert [int(m.get("i")) for m in got] == list(range(n_frames))
        assert all(np.asarray(m.get("blob")).nbytes == payload.nbytes
                   for m in got)
        assert mgr.byte_stats()["frames_sent"] == n_frames
        cli.close()
    finally:
        mgr.stop_receive_message()
        t.join(5.0)


# ------------------------------------------------ e2e with real clients


def test_threaded_clients_and_codec_against_async_server():
    """The existing threaded client side plugs in unchanged: two
    FedAvgClientProc (legacy dial-in transport, delta wire codec)
    complete a 4-aggregation federation against the buffered server,
    with at least one stale contribution decoded through the ring."""
    port = free_port_block(8)
    init = {"params": {"w": np.zeros(16, np.float32)}}
    srv = BufferedFedAvgServer(init, 4, 2, buffer_k=1, max_staleness=10,
                               base_port=port)
    st = threading.Thread(target=srv.run, daemon=True)
    st.start()

    def mk_train(delta):
        def train_fn(params, round_idx):
            w = np.asarray(params["params"]["w"]) + np.float32(delta)
            return {"params": {"w": w}}, 5.0
        return train_fn

    clients = [FedAvgClientProc(r, 2, mk_train(0.1 * r), base_port=port,
                                wire_codec="delta") for r in (1, 2)]
    cts = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for ct in cts:
        ct.start()
    st.join(60.0)
    for ct in cts:
        ct.join(20.0)
    assert srv._done.is_set()
    assert srv.round_idx == 4
    audit = srv.upload_audit()
    assert audit["received_accounted"] and audit["accepted_accounted"]
    assert audit["accepted"] == 4
    assert np.all(np.isfinite(srv.params["params"]["w"]))


# ------------------------------------------------ load harness


@pytest.mark.slow
def test_loadgen_200_clients_with_churn_smoke():
    from neuroimagedisttraining_tpu.asyncfl.loadgen import run_load

    r = run_load(mode="async", num_clients=200, aggregations=10,
                 buffer_k=40, max_staleness=50,
                 fault_spec="crash:7@2,rejoin:7@6,crash:11@3", seed=3)
    assert r["rounds_or_aggregations"] == 10
    assert r["peak_connections"] >= 200
    assert r["frames_reconciled"], r
    assert r["upload_audit"]["received_accounted"]
    assert r["upload_audit"]["accepted_accounted"]
    assert r["client_stats"]["crashes"] >= 2
    assert r["client_stats"]["rejoins"] >= 1
    assert r["client_stats"]["errors"] == 0
