"""Reflex-plane tests (ISSUE 20).

Five contracts:

(a) The :class:`ActionBus` matrix: ``off`` dispatches nothing;
    ``dry_run`` logs what WOULD fire without running a handler; ``on``
    runs the registered handler and contains every failure mode
    (unhandled / handler-reported skip / raised exception) as a log
    status — never an exception into the host boundary. Unknown names
    fail loudly at registration, the log ring evicts with a counted
    eviction, the module-level conveniences no-op unarmed.
(b) Rule -> action provenance: a firing rule that declares an
    ``action`` dispatches on its RISING edge with the rule name,
    severity, round, and value carried into the action log and the
    flight ring; rules without an action dispatch nothing; an unknown
    action name fails rule validation at startup.
(c) The seeded chaos scenario ACTS deterministically: under
    ``--actions on`` a 1-of-4 sign-flip silo gets quarantined with the
    firing rule as provenance (the next cohort excludes it), and two
    identical seeded runs produce byte-identical action logs; the
    ``dry_run`` twin records the same would-fire dispatch while
    changing NOTHING (no quarantine window, full cohort, config
    defense).
(d) Freeze-and-rollback restores the pinned healthy state bitwise at
    a host boundary and zeroes the codec error-feedback accumulators;
    the healthy pin is only taken under ``--actions on`` while the
    rule engine reads ok.
(e) The elastic compute plane: a ``preempt:NDEV@ROUND`` fault shrinks
    the mesh to the survivors mid-run, resumes from the last
    donation-safe checkpoint, and the post-resume trajectory is
    BITWISE-identical to a fresh-process resume of the same checkpoint
    on a mesh of that size (the replay-parity pin ISSUE 20's
    acceptance asks for).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.config import (
    DataConfig, ExperimentConfig, FedConfig, OptimConfig,
)
from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
from neuroimagedisttraining_tpu.data.federate import federate_cohort
from neuroimagedisttraining_tpu.data.synthetic import (
    generate_synthetic_abcd,
)
from neuroimagedisttraining_tpu.engines import create_engine
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.obs import actions as obs_actions
from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import names as N
from neuroimagedisttraining_tpu.obs import rules as obs_rules
from neuroimagedisttraining_tpu.obs.actions import ActionBus
from neuroimagedisttraining_tpu.obs.rules import HealthRule, RuleEngine
from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger


@pytest.fixture(scope="module")
def cohort64():
    """Same cohort as tests/test_health.py: enough shared signal that
    honest site updates cohere, so a sign-flip silo separates from
    non-IID noise."""
    return generate_synthetic_abcd(num_subjects=64, shape=(12, 14, 12),
                                   num_sites=4, seed=0)


def _engine(tmp_path, cohort, n_dev=None, algorithm="fedavg",
            health=True, comm_round=2, freq=1, client_mesh=0, tag="a",
            seed=1024, checkpoint_dir="", checkpoint_every=0, **fed_kw):
    """test_health's engine builder plus the reflex knobs: mesh width
    (client_mesh must equal it when sharding) and checkpointing."""
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm=algorithm,
        seed=seed,
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=OptimConfig(lr=1e-3, batch_size=8, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=comm_round,
                      frequency_of_the_test=freq,
                      client_mesh=client_mesh, **fed_kw),
        log_dir=str(tmp_path), tag=tag, health_stats=health,
        checkpoint_dir=checkpoint_dir,
        checkpoint_every=checkpoint_every)
    mesh = make_mesh(num_devices=n_dev)
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1),
                           cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic",
                           cfg.identity() + tag, console=False)
    fed, _ = federate_cohort(cohort, partition_method="site", mesh=mesh)
    return create_engine(algorithm, cfg, fed, trainer, mesh=mesh,
                         logger=log)


def _bitwise(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _snap(value, metric=N.HEALTH_COSINE_MIN):
    return {metric: {"kind": "gauge", "help": "",
                     "values": [{"labels": {}, "value": value}]}}


_BYZ = "byz:1@0:sign_flip,byz:1@1:sign_flip"


# ---------------------------------------------------------------------------
# (a) the ActionBus matrix
# ---------------------------------------------------------------------------


def test_bus_off_dispatches_nothing():
    bus = ActionBus("off")
    calls = []
    bus.register("quarantine_silo", lambda **kw: calls.append(kw))
    assert bus.on_alert("quarantine_silo", rule="r") is None
    assert calls == []
    blk = bus.actions_block()
    assert blk["mode"] == "off" and blk["total"] == 0 and blk["log"] == []


def test_bus_dry_run_logs_without_running_handler():
    obs_flight.clear()
    bus = ActionBus("dry_run")
    calls = []
    bus.register("quarantine_silo", lambda **kw: calls.append(kw))
    e = bus.on_alert("quarantine_silo", rule="client-divergence",
                     severity="critical", round_idx=3, value=-0.4)
    assert calls == []
    assert e["status"] == "dry_run" and e["dry_run"] is True
    assert e["rule"] == "client-divergence" and e["round"] == 3
    assert e["value"] == pytest.approx(-0.4)
    kinds = [ev["kind"] for ev in obs_flight.events()]
    assert "action_dry_run" in kinds and "action" not in kinds


def test_bus_on_applies_handler_detail():
    bus = ActionBus("on")
    bus.register("escalate_defense",
                 lambda **kw: {"from": "none", "to": "trimmed_mean"})
    e = bus.on_alert("escalate_defense", rule="r", round_idx=1)
    assert e["status"] == "applied" and e["dry_run"] is False
    assert e["detail"] == {"from": "none", "to": "trimmed_mean"}


def test_bus_on_contains_every_failure_mode():
    bus = ActionBus("on")
    # no handler on this plane -> unhandled, not an error
    assert bus.on_alert("adapt_buffer", rule="r")["status"] == "unhandled"
    # handler-reported skip rides the status channel with its reason
    bus.register("freeze_rollback",
                 lambda **kw: {"status": "skipped", "reason": "no pin"})
    e = bus.on_alert("freeze_rollback", rule="r")
    assert e["status"] == "skipped" and e["detail"] == {"reason": "no pin"}

    def _boom(**kw):
        raise RuntimeError("handler exploded")

    bus.register("quarantine_silo", _boom)
    e = bus.on_alert("quarantine_silo", rule="r")
    assert e["status"] == "error"
    assert "handler exploded" in e["detail"]["error"]


def test_bus_unknown_names():
    bus = ActionBus("on")
    with pytest.raises(ValueError, match="unknown action"):
        bus.register("reboot_universe", lambda **kw: None)
    # a hand-built RuleEngine cannot crash a boundary through the bus
    e = bus.on_alert("reboot_universe", rule="r")
    assert e["status"] == "error"
    with pytest.raises(ValueError, match="--actions"):
        ActionBus("sometimes")


def test_bus_log_ring_evicts_counted():
    bus = ActionBus("dry_run", log_cap=4)
    for i in range(6):
        bus.on_alert("quarantine_silo", rule=f"r{i}")
    blk = bus.actions_block()
    assert blk["total"] == 6 and blk["evicted"] == 2
    assert [e["rule"] for e in blk["log"]] == ["r2", "r3", "r4", "r5"]


def test_module_level_unarmed_noops():
    assert obs_actions.active() is None
    obs_actions.register("quarantine_silo", lambda **kw: None)
    assert obs_actions.on_alert("quarantine_silo", rule="r") is None
    assert obs_actions.record_action("shrink_mesh", rule="r") is None
    assert obs_actions.actions_block() == {"mode": "unarmed"}


# ---------------------------------------------------------------------------
# (b) rule -> action provenance
# ---------------------------------------------------------------------------


def test_rule_action_dispatches_on_rising_edge():
    obs_flight.clear()
    try:
        bus = obs_actions.configure("dry_run")
        eng = RuleEngine([HealthRule(
            name="div", metric=N.HEALTH_COSINE_MIN, op="<",
            threshold=-0.2, severity="critical",
            action="quarantine_silo")])
        eng.observe(0, _snap(0.3))      # healthy: no edge
        eng.observe(1, _snap(-0.9))     # rising edge -> dispatch
        eng.observe(2, _snap(-0.9))     # still firing: no NEW edge
        blk = bus.actions_block()
        assert blk["total"] == 1
        (e,) = blk["log"]
        assert e["action"] == "quarantine_silo" and e["rule"] == "div"
        assert e["severity"] == "critical" and e["round"] == 1
        assert e["value"] == pytest.approx(-0.9)
        flights = [ev for ev in obs_flight.events()
                   if ev["kind"] == "action_dry_run"]
        assert [(f["rule"], f["round"]) for f in flights] == [("div", 1)]
        # the verdict rows carry the binding for run_report provenance
        (row,) = eng.verdict()["rules"]
        assert row["action"] == "quarantine_silo"
    finally:
        obs_actions.disarm()


def test_rule_without_action_dispatches_nothing():
    try:
        bus = obs_actions.configure("dry_run")
        eng = RuleEngine([HealthRule(
            name="div", metric=N.HEALTH_COSINE_MIN, op="<",
            threshold=-0.2, severity="critical")])
        eng.observe(0, _snap(-0.9))
        assert bus.actions_block()["total"] == 0
    finally:
        obs_actions.disarm()


def test_rule_unknown_action_fails_validation():
    with pytest.raises(ValueError, match="unknown action"):
        RuleEngine([HealthRule(
            name="div", metric=N.HEALTH_COSINE_MIN, op="<",
            threshold=-0.2, action="reboot_universe")])


# ---------------------------------------------------------------------------
# (c) the seeded chaos scenario acts deterministically
# ---------------------------------------------------------------------------


def _chaos_log(tmp_path, cohort, mode, tag, comm_round=2):
    """One seeded sign-flip run with the builtin rules and the action
    bus at ``mode``; returns (engine, actions block)."""
    obs_flight.clear()
    try:
        obs_rules.configure()
        bus = obs_actions.configure(mode)
        eng = _engine(tmp_path, cohort, tag=tag, comm_round=comm_round,
                      fault_spec=_BYZ, defense_type="none")
        res = eng.train()
        for leaf in jax.tree.leaves(res["params"]):
            assert np.isfinite(np.asarray(leaf)).all()
        return eng, bus.actions_block()
    finally:
        obs_actions.disarm()
        obs_rules.disarm()


def test_chaos_quarantine_applied_with_provenance(tmp_path, cohort64):
    eng, blk = _chaos_log(tmp_path, cohort64, "on", "on1")
    q = [e for e in blk["log"] if e["action"] == "quarantine_silo"
         and e["status"] == "applied"]
    assert q, f"no applied quarantine in {blk['log']}"
    assert q[0]["rule"] == "client-divergence"
    offender = q[0]["detail"]["client"]
    assert eng._is_quarantined(offender, q[0]["detail"]["from_round"])
    # the NEXT round's cohort excluded the quarantined silo
    sampled_next = eng._sampled_by_round.get(
        q[0]["detail"]["from_round"])
    assert sampled_next is not None and offender not in list(sampled_next)
    # replay determinism: an identical seeded run acts byte-identically
    _, blk2 = _chaos_log(tmp_path, cohort64, "on", "on2")
    assert (json.dumps(blk["log"], sort_keys=True)
            == json.dumps(blk2["log"], sort_keys=True))


def test_chaos_dry_run_observes_without_acting(tmp_path, cohort64):
    eng, blk = _chaos_log(tmp_path, cohort64, "dry_run", "dry",
                          comm_round=1)
    q = [e for e in blk["log"] if e["action"] == "quarantine_silo"]
    assert q and all(e["status"] == "dry_run" for e in q)
    assert eng._quarantine_windows == {}
    assert eng.active_defense() == "none"
    # the cohort never shrank: every sampled round saw all 4 clients
    assert all(len(s) == 4 for s in eng._sampled_by_round.values())


# ---------------------------------------------------------------------------
# (d) escalation + freeze-and-rollback handlers
# ---------------------------------------------------------------------------


def test_escalate_defense_walks_the_ladder(tmp_path, cohort64):
    try:
        bus = obs_actions.configure("on")
        eng = _engine(tmp_path, cohort64, tag="esc",
                      defense_type="none")
        eng._register_reflexes()
        eng.program  # build the plan the escalation must invalidate
        e = bus.on_alert("escalate_defense", rule="defense-escalation",
                         round_idx=0)
        assert e["status"] == "applied"
        assert e["detail"] == {"from": "none",
                               "to": "norm_diff_clipping"}
        assert eng.active_defense() == "norm_diff_clipping"
        assert "program" not in eng.__dict__  # re-plan forced
        e = bus.on_alert("escalate_defense", rule="defense-escalation",
                         round_idx=1)
        assert e["detail"] == {"from": "norm_diff_clipping",
                               "to": "trimmed_mean"}
        # the config literal is never touched — only the override moves
        assert eng.cfg.fed.defense_type == "none"
        e = bus.on_alert("escalate_defense", rule="defense-escalation",
                         round_idx=2)
        assert e["status"] == "skipped"
        assert "top rung" in e["detail"]["reason"]
    finally:
        obs_actions.disarm()


def test_escalate_skips_outside_the_ladder(tmp_path, cohort64):
    try:
        bus = obs_actions.configure("on")
        eng = _engine(tmp_path, cohort64, tag="lad",
                      defense_type="weak_dp")
        eng._register_reflexes()
        e = bus.on_alert("escalate_defense", rule="r", round_idx=0)
        assert e["status"] == "skipped"
        assert "outside the escalation ladder" in e["detail"]["reason"]
        assert eng.active_defense() == "weak_dp"
    finally:
        obs_actions.disarm()


def test_freeze_rollback_restores_pin_bitwise(tmp_path, cohort64):
    obs_flight.clear()
    try:
        bus = obs_actions.configure("on")
        eng = _engine(tmp_path, cohort64, tag="rb")
        eng._register_reflexes()
        # no pin yet -> the handler reports the skip, nothing pends
        e = bus.on_alert("freeze_rollback", rule="update-norm-blowup",
                         round_idx=0)
        assert e["status"] == "skipped" and eng._pending_rollback is None
        # a healthy boundary pins (mode on, no rule engine -> healthy)
        good_p = {"w": jnp.arange(4.0)}
        good_b = {"m": jnp.ones(3)}
        p, b = eng._reflex_boundary(3, good_p, good_b)
        assert eng._healthy_pin is not None
        assert eng._healthy_pin["round"] == 3
        # the pin owns copies: consuming the originals cannot kill it
        _bitwise(eng._healthy_pin["params"], good_p)
        e = bus.on_alert("freeze_rollback", rule="update-norm-blowup",
                         round_idx=5, value=80.0)
        assert e["status"] == "applied" and e["detail"]["pin_round"] == 3
        eng._wire_ef = {"e": jnp.full(3, 7.0)}
        bad_p = {"w": jnp.full(4, jnp.nan)}
        p, b = eng._reflex_boundary(5, bad_p, {"m": jnp.zeros(3)})
        _bitwise(p, good_p)
        _bitwise(b, good_b)
        # codec-EF reset invariant: stale error must not be replayed
        _bitwise(eng._wire_ef, {"e": jnp.zeros(3)})
        rb = [ev for ev in obs_flight.events()
              if ev["kind"] == "rollback"]
        assert [(r["rule"], r["pin_round"]) for r in rb] \
            == [("update-norm-blowup", 3)]
    finally:
        obs_actions.disarm()


def test_no_pin_outside_actions_on(tmp_path, cohort64):
    """dry_run must not even pin: pinning is reflex machinery, and the
    dry_run contract is 'behavior never changes silently'."""
    try:
        obs_actions.configure("dry_run")
        eng = _engine(tmp_path, cohort64, tag="np")
        eng._reflex_boundary(0, {"w": jnp.zeros(2)}, {})
        assert eng._healthy_pin is None
    finally:
        obs_actions.disarm()


# ---------------------------------------------------------------------------
# (e) the elastic compute plane
# ---------------------------------------------------------------------------


def test_preempt_shrinks_mesh_and_resumes_bitwise(tmp_path, cohort64):
    """``preempt:2@2`` on a 4-device/4-way-sharded run: the mesh
    shrinks to the 2 survivors, cfg.fed.client_mesh follows (the
    startup invariant), the shrink is flight-recorded with device-loss
    provenance, and rounds 2..3 after the in-process resume are
    BITWISE what a fresh process restoring the same checkpoint on a
    2-device mesh computes."""
    ckA, ckB = str(tmp_path / "ckA"), str(tmp_path / "ckB")
    try:
        bus = obs_actions.configure("dry_run")
        a = _engine(tmp_path, cohort64, n_dev=4, client_mesh=4,
                    health=False, comm_round=4, tag="elA",
                    checkpoint_dir=ckA, checkpoint_every=1,
                    fault_spec="preempt:2@2")
        res_a = a.train()
        assert a.mesh.devices.size == 2
        assert a.cfg.fed.client_mesh == 2
        shrinks = [e for e in bus.actions_block()["log"]
                   if e["action"] == "shrink_mesh"]
        assert [e["status"] for e in shrinks] == ["applied"]
        assert shrinks[0]["rule"] == "device-loss"
        assert shrinks[0]["detail"] == {
            "devices_before": 4, "devices_after": 2,
            "scheduled_round": 2, "resume_round": 2}
    finally:
        obs_actions.disarm()
    # prefix twin: same seeded run, stopped where the preemption hit —
    # its checkpoint is the state the elastic resume restored
    pre = _engine(tmp_path, cohort64, n_dev=4, client_mesh=4,
                  health=False, comm_round=2, tag="elP",
                  checkpoint_dir=ckB, checkpoint_every=1)
    pre.train()
    # fresh-process resume of that checkpoint on a 2-device mesh
    b = _engine(tmp_path, cohort64, n_dev=2, client_mesh=2,
                health=False, comm_round=4, tag="elB",
                checkpoint_dir=ckB, checkpoint_every=1)
    res_b = b.train()
    _bitwise(res_a["params"], res_b["params"])
    _bitwise(res_a["batch_stats"], res_b["batch_stats"])
    # the post-resume metric trajectory is pinned too
    tail_a = [h for h in res_a["history"] if h["round"] >= 2]
    tail_b = [h for h in res_b["history"] if h["round"] >= 2]
    assert tail_a == tail_b


def test_preempt_without_checkpoint_continues_live(tmp_path, cohort64):
    """No checkpoint configured: the shrink still happens, training
    continues on the live state over the survivors (the record carries
    the live resume round)."""
    try:
        bus = obs_actions.configure("dry_run")
        eng = _engine(tmp_path, cohort64, n_dev=4, health=False,
                      comm_round=2, tag="elL", fault_spec="preempt:2@1")
        res = eng.train()
        assert eng.mesh.devices.size == 2
        for leaf in jax.tree.leaves(res["params"]):
            assert np.isfinite(np.asarray(leaf)).all()
        (e,) = [x for x in bus.actions_block()["log"]
                if x["action"] == "shrink_mesh"]
        assert e["status"] == "applied"
        assert e["detail"]["resume_round"] == 1
    finally:
        obs_actions.disarm()
