"""Cross-engine integration tests: Local, Ditto, D-PSGD on synthetic data.

Each runs 2-3 rounds on the tiny 3D CNN over the 8-virtual-device mesh and
checks engine-specific invariants."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.config import (
    DataConfig, ExperimentConfig, FedConfig, OptimConfig,
)
from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
from neuroimagedisttraining_tpu.data.federate import federate_cohort
from neuroimagedisttraining_tpu.engines import create_engine
from neuroimagedisttraining_tpu.engines.dpsgd import benefit_choose
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger


def _engine(tmp_path, cohort, algorithm, comm_round=2, **fed_kw):
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm=algorithm,
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=OptimConfig(lr=1e-3, batch_size=8, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=comm_round,
                      frequency_of_the_test=1, **fed_kw),
        log_dir=str(tmp_path),
    )
    mesh = make_mesh()
    fed, _ = federate_cohort(cohort, partition_method="site", mesh=mesh)
    model = create_model(cfg.model, num_classes=1)
    trainer = LocalTrainer(model, cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    return create_engine(algorithm, cfg, fed, trainer, mesh=mesh, logger=log)


@pytest.mark.slow  # tier-1 window (PR 7): single-engine behavioral e2e, engine keeps dispatch/stream/cohort coverage
def test_local_engine_personal_models_diverge(tmp_path, synthetic_cohort):
    engine = _engine(tmp_path, synthetic_cohort, "local")
    result = engine.train()
    # clients never communicate => personal models differ across clients
    k = jax.tree.leaves(result["personal_params"])[0]
    assert not np.allclose(np.asarray(k[0]), np.asarray(k[1]))
    assert np.isfinite(result["history"][-1]["train_loss"])


@pytest.mark.slow  # tier-1 window (PR 7): single-engine behavioral e2e, engine keeps dispatch/stream/cohort coverage
def test_ditto_personal_pulled_toward_global(tmp_path, synthetic_cohort):
    engine = _engine(tmp_path, synthetic_cohort, "ditto", lamda=0.5,
                     local_epochs=1)
    result = engine.train()
    assert np.isfinite(result["history"][-1]["train_loss"])
    assert "final_personal" in result
    # lamda=BIG pins personal models to the global track start point:
    # with huge lamda the proximal term dominates, keeping the personal
    # models close to global; just sanity-check both exist and differ.
    g = jax.tree.leaves(result["params"])[0]
    p = jax.tree.leaves(result["personal_params"])[0]
    assert p.shape[0] == engine.num_clients
    assert not np.allclose(np.asarray(g), np.asarray(p[0]))


@pytest.mark.slow  # tier-1 window (PR 7): single-engine behavioral e2e, engine keeps dispatch/stream/cohort coverage
def test_fedprox_end_to_end_and_prox_pull_direction(tmp_path,
                                                    synthetic_cohort):
    """BASELINE.json configs[3] (FedProx half): the engine trains, and a
    large mu keeps the round's aggregate measurably closer to the incoming
    global model than plain FedAvg's (the proximal term's defining
    effect)."""
    from neuroimagedisttraining_tpu.utils import pytree as pt

    engine = _engine(tmp_path, synthetic_cohort, "fedprox", lamda=0.5)
    result = engine.train()
    assert np.isfinite(result["history"][-1]["train_loss"])

    def one_round_drift(algorithm, **fed_kw):
        e = _engine(tmp_path, synthetic_cohort, algorithm, **fed_kw)
        e._donate = False  # gs.params is reread after the dispatch
        gs = e.init_global_state()
        sampled = jnp.asarray(e.client_sampling(0))
        rngs = e.per_client_rngs(0, np.asarray(sampled))
        params, _, _, _ = e._round_jit(gs.params, gs.batch_stats, e.data,
                                       sampled, rngs, jnp.float32(1e-3))
        return float(pt.tree_norm(pt.tree_sub(params, gs.params)))

    drift_avg = one_round_drift("fedavg")
    # lr * mu = 0.9: each post-step pull keeps only 10% of the deviation
    # from the incoming global, so the round's aggregate stays pinned near
    # it (still contractive: lr * mu < 1)
    drift_prox = one_round_drift("fedprox", lamda=900.0)
    assert drift_prox < 0.5 * drift_avg


def test_fedprox_composes_with_byzantine_clipping(tmp_path,
                                                  synthetic_cohort):
    """BASELINE.json configs[3], both halves: FedProx + norm_diff_clipping
    under a poisoned client — the post-round drift is bounded by the clip
    norm (robust_aggregation.py:32-55 semantics through the FedProx
    round)."""
    from neuroimagedisttraining_tpu.utils import pytree as pt

    def poisoned_round(**fed_kw):
        e = _engine(tmp_path, synthetic_cohort, "fedprox", lamda=0.01,
                    **fed_kw)
        e._donate = False  # gs.params is reread after the dispatch
        gs = e.init_global_state()
        data = e.data
        Xb = data.X_train.at[0].set(255)
        yb = data.y_train.at[0].set(1 - data.y_train[0])
        data = data.replace(X_train=Xb, y_train=yb)
        sampled = jnp.asarray(e.client_sampling(0))
        rngs = e.per_client_rngs(0, np.asarray(sampled))
        params, _, _, _ = e._round_jit(gs.params, gs.batch_stats, data,
                                       sampled, rngs, jnp.float32(0.5))
        return float(pt.tree_norm(pt.tree_sub(params, gs.params)))

    drift_plain = poisoned_round()
    drift_clip = poisoned_round(defense_type="norm_diff_clipping",
                                norm_bound=0.5)
    assert drift_clip <= 0.5 + 1e-4
    assert drift_plain > drift_clip


def test_fedprox_cli_config_builds(tmp_path):
    """The blueprint config is runnable from the CLI surface: flags parse,
    the experiment builds, and the engine is the FedProx class."""
    from neuroimagedisttraining_tpu.__main__ import (
        add_args, build_experiment, config_from_args,
    )
    import argparse

    args = add_args(argparse.ArgumentParser()).parse_args([
        "--algorithm", "fedprox", "--dataset", "synthetic",
        "--model", "3dcnn_tiny", "--synthetic_num_subjects", "16",
        "--synthetic_shape", "8", "8", "8", "--client_num_in_total", "4",
        "--comm_round", "1", "--batch_size", "4", "--lamda", "0.3",
        "--defense_type", "norm_diff_clipping", "--norm_bound", "2.0",
        "--log_dir", str(tmp_path)])
    cfg = config_from_args(args)
    assert cfg.algorithm == "fedprox" and cfg.fed.lamda == 0.3
    assert cfg.fed.defense_type == "norm_diff_clipping"
    engine = build_experiment(cfg, console=False)
    from neuroimagedisttraining_tpu.engines.fedprox import FedProxEngine

    assert isinstance(engine, FedProxEngine)


def test_dpsgd_neighbor_choose_parity():
    # reference: np.random.seed(round+clnt); resample while self included
    for (r, c) in [(0, 1), (3, 2)]:
        got = benefit_choose(r, c, 10, 3, "random")
        np.random.seed(r + c)
        want = np.random.choice(range(10), 3, replace=False)
        while c in want:
            want = np.random.choice(range(10), 3, replace=False)
        np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(benefit_choose(0, 0, 5, 2, "ring"), [4, 1])
    np.testing.assert_array_equal(benefit_choose(0, 2, 4, 2, "full"),
                                  [0, 1, 3])


def test_dpsgd_mixing_matrix_row_stochastic(tmp_path, synthetic_cohort):
    engine = _engine(tmp_path, synthetic_cohort, "dpsgd", cs="ring",
                     frac=0.5)
    M = engine.mixing_matrix(0)
    np.testing.assert_allclose(M.sum(axis=1), 1.0, rtol=1e-6)
    # ring: each real client mixes with exactly itself + 2 neighbors
    for c in range(engine.real_clients):
        assert int((M[c] > 0).sum()) == 3


def test_dpsgd_end_to_end(tmp_path, synthetic_cohort):
    engine = _engine(tmp_path, synthetic_cohort, "dpsgd", cs="ring",
                     frac=0.5)
    result = engine.train()
    assert np.isfinite(result["history"][-1]["train_loss"])
    assert 0.0 <= result["final_global"]["acc"] <= 1.0


def _dispfl_engine(tmp_path, cohort, sparsity=None, **fed_kw):
    from neuroimagedisttraining_tpu.config import SparsityConfig

    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm="dispfl",
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=OptimConfig(lr=1e-3, batch_size=8, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=3,
                      frequency_of_the_test=1, **fed_kw),
        sparsity=sparsity or SparsityConfig(dense_ratio=0.5),
        log_dir=str(tmp_path),
    )
    mesh = make_mesh()
    fed, _ = federate_cohort(cohort, partition_method="site", mesh=mesh)
    model = create_model(cfg.model, num_classes=1)
    trainer = LocalTrainer(model, cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    return create_engine("dispfl", cfg, fed, trainer, mesh=mesh, logger=log)


@pytest.mark.slow  # tier-1 window (PR 7): single-engine behavioral e2e, engine keeps dispatch/stream/cohort coverage
def test_dispfl_end_to_end_with_dropout(tmp_path, synthetic_cohort):
    """active=0.7 fault injection: rounds run, metrics finite, masks evolve."""
    engine = _dispfl_engine(tmp_path, synthetic_cohort, active=0.7)
    result = engine.train()
    assert np.isfinite(result["history"][-1]["train_loss"])
    assert 0.0 <= result["final_personal"]["acc"] <= 1.0
    # fire/regrow happened: mask_change > 0 after round 0
    assert result["history"][1]["mask_change"] > 0
    # all-pairs hamming matrix is symmetric with zero diagonal
    D = result["mask_dis_matrix"]
    np.testing.assert_allclose(D, D.T)
    assert np.all(np.diag(D) == 0)
    assert engine.stat_info["sum_comm_params"] > 0
    assert engine.stat_info["sum_training_flops"] > 0


def test_dispfl_nnz_preserved_across_rounds(tmp_path, synthetic_cohort):
    """fire drops exactly k per layer and regrow adds back exactly k, so
    per-client per-layer nnz is invariant across rounds."""
    from neuroimagedisttraining_tpu.engines.dispfl import DisPFLEngine

    engine = _dispfl_engine(tmp_path, synthetic_cohort)
    gs = engine.init_global_state()
    masks0, _ = engine.init_masks_all(gs.params)
    nnz0 = [int(np.asarray(m).sum())
            for m in DisPFLEngine._maskable_leaves(masks0)]
    result = engine.train()
    nnz1 = [int(np.asarray(m).sum())
            for m in DisPFLEngine._maskable_leaves(result["masks"])]
    assert nnz0 == nnz1


def test_dispfl_diff_spa_densities(tmp_path, synthetic_cohort):
    from neuroimagedisttraining_tpu.config import SparsityConfig
    from neuroimagedisttraining_tpu.ops.masks import is_weight_kernel

    engine = _dispfl_engine(
        tmp_path, synthetic_cohort,
        sparsity=SparsityConfig(dense_ratio=0.5, diff_spa=True, uniform=True))
    gs = engine.init_global_state()
    masks, w_spa = engine.init_masks_all(gs.params)
    assert w_spa[:4] == [0.2, 0.4, 0.6, 0.8]
    # per-client overall density over maskable leaves tracks w_spa
    flat = jax.tree_util.tree_leaves_with_path(masks)
    per_client_nnz = np.zeros(4)
    per_client_tot = np.zeros(4)
    for path, m in flat:
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        if is_weight_kernel(name, m[0]):
            per_client_nnz += np.asarray(m).reshape(m.shape[0], -1).sum(1)[:4]
            per_client_tot += m[0].size
    dens = per_client_nnz / per_client_tot
    np.testing.assert_allclose(dens, [0.2, 0.4, 0.6, 0.8], atol=0.05)


def test_dispfl_adjacency_semantics(tmp_path, synthetic_cohort):
    engine = _dispfl_engine(tmp_path, synthetic_cohort, active=1.0, frac=0.5)
    active = np.ones(engine.num_clients, bool)
    A = engine.adjacency(0, active)
    # every row includes self; padding clients are isolated
    assert np.all(np.diag(A) == 1)
    for c in range(engine.real_clients, engine.num_clients):
        assert A[c].sum() == 1
    # inactive client receives nothing but itself
    active2 = active.copy()
    active2[1] = False
    A2 = engine.adjacency(0, active2)
    assert A2[1].sum() == 1 and A2[1, 1] == 1


# ---------------- Sub-FedAvg ----------------

def test_subavg_fake_prune_percentile_matches_numpy():
    from neuroimagedisttraining_tpu.ops import prune as P

    rng = np.random.default_rng(0)
    w = {"layer": {"kernel": jnp.asarray(rng.normal(size=(8, 16)),
                                         jnp.float32)}}
    m = {"layer": {"kernel": jnp.ones((8, 16), jnp.float32)}}
    # knock out some entries so "alive" is a strict subset
    m["layer"]["kernel"] = m["layer"]["kernel"].at[0, :8].set(0.0)
    new = P.fake_prune(0.3, w, m)
    # numpy reference: percentile over alive |w|, then |w| < thr -> 0
    wn = np.asarray(w["layer"]["kernel"])
    mn = np.asarray(m["layer"]["kernel"])
    alive = np.abs(wn[mn > 0])
    thr = np.percentile(alive, 30)
    want = np.where(np.abs(wn) < thr, 0.0, mn)
    np.testing.assert_allclose(np.asarray(new["layer"]["kernel"]), want)


@pytest.mark.slow
def test_subavg_end_to_end_prunes(tmp_path, synthetic_cohort):
    """Loose thresholds so the accept-test fires: density drops below 1."""
    from neuroimagedisttraining_tpu.config import SparsityConfig

    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm="subavg",
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=OptimConfig(lr=1e-2, batch_size=8, epochs=2),
        fed=FedConfig(client_num_in_total=4, comm_round=3,
                      frequency_of_the_test=1),
        sparsity=SparsityConfig(each_prune_ratio=0.2, dist_thresh=0.0,
                                acc_thresh=0.0, dense_ratio=0.1),
        log_dir=str(tmp_path))
    mesh = make_mesh()
    fed, _ = federate_cohort(synthetic_cohort, partition_method="site",
                             mesh=mesh)
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1), cfg.optim,
                           num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    engine = create_engine("subavg", cfg, fed, trainer, mesh=mesh, logger=log)
    result = engine.train()
    assert np.isfinite(result["history"][-1]["train_loss"])
    assert result["history"][-1]["prunes_accepted"] > 0
    assert np.all(result["client_densities"] < 1.0)
    assert np.all(result["client_densities"] > 0.0)


@pytest.mark.slow  # tier-1 window (PR 7): single-engine behavioral e2e, engine keeps dispatch/stream/cohort coverage
def test_subavg_accept_test_rejects(tmp_path, synthetic_cohort):
    """Impossible acc threshold -> no prune ever accepted, masks stay ones."""
    from neuroimagedisttraining_tpu.config import SparsityConfig

    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm="subavg",
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=OptimConfig(lr=1e-2, batch_size=8, epochs=2),
        fed=FedConfig(client_num_in_total=4, comm_round=2,
                      frequency_of_the_test=1),
        sparsity=SparsityConfig(each_prune_ratio=0.2, dist_thresh=0.0,
                                acc_thresh=2.0, dense_ratio=0.1),
        log_dir=str(tmp_path))
    mesh = make_mesh()
    fed, _ = federate_cohort(synthetic_cohort, partition_method="site",
                             mesh=mesh)
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1), cfg.optim,
                           num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    engine = create_engine("subavg", cfg, fed, trainer, mesh=mesh, logger=log)
    result = engine.train()
    assert result["history"][-1]["prunes_accepted"] == 0
    for m in jax.tree.leaves(result["mask_pers"]):
        assert bool(jnp.all(m == 1))


# ---------------- FedFomo ----------------

def _fomo_engine(tmp_path, cohort, **fed_kw):
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm="fedfomo",
        data=DataConfig(dataset="synthetic", partition_method="site",
                        val_fraction=0.25),
        optim=OptimConfig(lr=1e-2, batch_size=8, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=3,
                      frequency_of_the_test=1, **fed_kw),
        log_dir=str(tmp_path))
    mesh = make_mesh()
    fed, _ = federate_cohort(cohort, partition_method="site", mesh=mesh,
                             val_fraction=0.25)
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1), cfg.optim,
                           num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    return create_engine("fedfomo", cfg, fed, trainer, mesh=mesh, logger=log)


def test_fedfomo_requires_val_split(tmp_path, synthetic_cohort):
    cfg = ExperimentConfig(model="3dcnn_tiny", algorithm="fedfomo",
                           log_dir=str(tmp_path))
    mesh = make_mesh()
    fed, _ = federate_cohort(synthetic_cohort, partition_method="site",
                             mesh=mesh)  # no val_fraction
    trainer = LocalTrainer(create_model("3dcnn_tiny", num_classes=1),
                           cfg.optim, num_classes=1)
    with pytest.raises(ValueError, match="val_fraction"):
        create_engine("fedfomo", cfg, fed, trainer, mesh=mesh,
                      logger=ExperimentLogger(str(tmp_path), "synthetic",
                                              "x", console=False))


@pytest.mark.slow
def test_fedfomo_end_to_end(tmp_path, synthetic_cohort):
    engine = _fomo_engine(tmp_path, synthetic_cohort)
    result = engine.train()
    assert np.isfinite(result["history"][-1]["train_loss"])
    assert 0.0 <= result["final_personal"]["acc"] <= 1.0
    # fomo state evolved away from its init
    W = np.asarray(result["weights"])
    assert not np.allclose(W[: engine.real_clients, : engine.real_clients],
                           1.0 / engine.real_clients)
    P = np.asarray(result["p_choose"])
    assert not np.allclose(P, 1.0)
    # aggregation stayed float (dtype discipline, SURVEY §3.5)
    for leaf in jax.tree.leaves(result["personal_params"]):
        assert jnp.issubdtype(leaf.dtype, jnp.floating)


@pytest.mark.slow
def test_fedfomo_partial_participation_uses_fomo_m(tmp_path,
                                                   synthetic_cohort):
    engine = _fomo_engine(tmp_path, synthetic_cohort, frac=0.5, fomo_m=1)
    # neighbor sets: 1 chosen + self
    for c in range(engine.real_clients):
        nei = engine.benefit_choose(0, c, np.ones(engine.num_clients))
        assert len(np.unique(nei)) <= 2
        assert c in nei
    result = engine.train()
    assert np.isfinite(result["history"][-1]["train_loss"])


@pytest.mark.slow  # tier-1 window (PR 7): single-engine behavioral e2e, engine keeps dispatch/stream/cohort coverage
def test_fedfomo_neighbor_masked_eval_count(tmp_path, synthetic_cohort):
    """The val-loss/distance matrices are computed only at neighbor pairs
    (reference evaluates just the RECEIVED models, fedfomo_api.py:147-171):
    the per-round eval count scales with the neighbor set, not C^2
    (VERDICT r2 weak #3)."""
    engine = _fomo_engine(tmp_path, synthetic_cohort, frac=0.5, fomo_m=1)
    real = engine.real_clients
    result = engine.train()
    # <= real * (fomo_m + 1) pairs actually evaluated, strictly < C^2
    assert engine._last_eval_pairs <= real * 2
    assert engine._last_eval_pairs < real * real
    assert np.isfinite(result["history"][-1]["train_loss"])


def test_fedfomo_full_participation_pairs_cover_matrix(tmp_path,
                                                       synthetic_cohort):
    """At full participation the pair list degenerates to all C^2 entries
    — the masked path must reproduce the dense behavior."""
    engine = _fomo_engine(tmp_path, synthetic_cohort)
    A = np.zeros((engine.num_clients,) * 2, np.float32)
    for c in range(engine.real_clients):
        A[c, np.unique(engine.benefit_choose(0, c,
                                             np.ones(engine.num_clients)))] = 1.0
    pc, pn, n_pairs = engine.pairs_from_adjacency(A)
    assert n_pairs == engine.real_clients ** 2
    got = set(zip(pc[:n_pairs].tolist(), pn[:n_pairs].tolist()))
    assert got == {(c, n) for c in range(engine.real_clients)
                   for n in range(engine.real_clients)}


def test_fedfomo_per_round_exceeding_real_clients_terminates(
        tmp_path, synthetic_cohort):
    """Regression: default 21-client config on a 4-site cohort used to spin
    forever in benefit_choose's resample loop."""
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm="fedfomo",
        data=DataConfig(dataset="synthetic", partition_method="site",
                        val_fraction=0.25),
        fed=FedConfig(client_num_in_total=21, comm_round=1),
        log_dir=str(tmp_path))
    mesh = make_mesh()
    fed, _ = federate_cohort(synthetic_cohort, partition_method="site",
                             mesh=mesh, val_fraction=0.25)
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1), cfg.optim,
                           num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    engine = create_engine("fedfomo", cfg, fed, trainer, mesh=mesh,
                           logger=log)
    nei = engine.benefit_choose(0, 1, np.ones(engine.num_clients))
    np.testing.assert_array_equal(np.sort(np.unique(nei)),
                                  np.arange(engine.real_clients))


def test_client_sampling_empty_cohort_config_error(tmp_path,
                                                   synthetic_cohort):
    """ADVICE r5: an empty sampled set (every real client lost its data —
    e.g. a partition that starved the cohort) used to surface as a bare
    IndexError from stream_sampling's ``sampled[-1]`` pad fill; it must
    be a clear config error instead. (Fault schedules cannot produce the
    empty set — FaultSchedule.survivors keeps the original cohort when
    everyone would die — so the data-starved path is the live one.)"""
    engine = _engine(tmp_path, synthetic_cohort, "fedavg")
    engine.real_clients = 0  # cohort with no training data anywhere
    with pytest.raises(ValueError, match="empty"):
        engine.client_sampling(0)
    with pytest.raises(ValueError, match="empty"):
        engine.stream_sampling(0, np.asarray([], np.int64))


def test_warn_if_masks_collapsed_flags_empty_mask(tmp_path,
                                                  synthetic_cohort):
    """ADVICE r5 NaN-mask diagnosability: an all-False per-client mask in
    the stacked evolution state triggers the post-round warning naming
    the collapsed clients (ExperimentLogger does not propagate, so the
    log FILE is the observable)."""
    engine = _engine(tmp_path, synthetic_cohort, "fedavg")
    masks = {"k": jnp.ones((engine.num_clients, 6, 5), jnp.float32)}
    masks["k"] = masks["k"].at[2].set(0.0)  # client 2's mask collapsed
    nnz = engine.warn_if_masks_collapsed(masks, round_idx=3)
    assert nnz[2] == 0 and (nnz[:2] > 0).all()
    with open(engine.log.log_path) as f:
        text = f.read()
    assert "EMPTY mask" in text and "[2]" in text
