"""Cross-engine integration tests: Local, Ditto, D-PSGD on synthetic data.

Each runs 2-3 rounds on the tiny 3D CNN over the 8-virtual-device mesh and
checks engine-specific invariants."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.config import (
    DataConfig, ExperimentConfig, FedConfig, OptimConfig,
)
from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
from neuroimagedisttraining_tpu.data.federate import federate_cohort
from neuroimagedisttraining_tpu.engines import create_engine
from neuroimagedisttraining_tpu.engines.dpsgd import benefit_choose
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger


def _engine(tmp_path, cohort, algorithm, comm_round=2, **fed_kw):
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm=algorithm,
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=OptimConfig(lr=1e-3, batch_size=8, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=comm_round,
                      frequency_of_the_test=1, **fed_kw),
        log_dir=str(tmp_path),
    )
    mesh = make_mesh()
    fed, _ = federate_cohort(cohort, partition_method="site", mesh=mesh)
    model = create_model(cfg.model, num_classes=1)
    trainer = LocalTrainer(model, cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    return create_engine(algorithm, cfg, fed, trainer, mesh=mesh, logger=log)


def test_local_engine_personal_models_diverge(tmp_path, synthetic_cohort):
    engine = _engine(tmp_path, synthetic_cohort, "local")
    result = engine.train()
    # clients never communicate => personal models differ across clients
    k = jax.tree.leaves(result["personal_params"])[0]
    assert not np.allclose(np.asarray(k[0]), np.asarray(k[1]))
    assert np.isfinite(result["history"][-1]["train_loss"])


def test_ditto_personal_pulled_toward_global(tmp_path, synthetic_cohort):
    engine = _engine(tmp_path, synthetic_cohort, "ditto", lamda=0.5,
                     local_epochs=1)
    result = engine.train()
    assert np.isfinite(result["history"][-1]["train_loss"])
    assert "final_personal" in result
    # lamda=BIG pins personal models to the global track start point:
    # with huge lamda the proximal term dominates, keeping the personal
    # models close to global; just sanity-check both exist and differ.
    g = jax.tree.leaves(result["params"])[0]
    p = jax.tree.leaves(result["personal_params"])[0]
    assert p.shape[0] == engine.num_clients
    assert not np.allclose(np.asarray(g), np.asarray(p[0]))


def test_dpsgd_neighbor_choose_parity():
    # reference: np.random.seed(round+clnt); resample while self included
    for (r, c) in [(0, 1), (3, 2)]:
        got = benefit_choose(r, c, 10, 3, "random")
        np.random.seed(r + c)
        want = np.random.choice(range(10), 3, replace=False)
        while c in want:
            want = np.random.choice(range(10), 3, replace=False)
        np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(benefit_choose(0, 0, 5, 2, "ring"), [4, 1])
    np.testing.assert_array_equal(benefit_choose(0, 2, 4, 2, "full"),
                                  [0, 1, 3])


def test_dpsgd_mixing_matrix_row_stochastic(tmp_path, synthetic_cohort):
    engine = _engine(tmp_path, synthetic_cohort, "dpsgd", cs="ring",
                     frac=0.5)
    M = engine.mixing_matrix(0)
    np.testing.assert_allclose(M.sum(axis=1), 1.0, rtol=1e-6)
    # ring: each real client mixes with exactly itself + 2 neighbors
    for c in range(engine.real_clients):
        assert int((M[c] > 0).sum()) == 3


def test_dpsgd_end_to_end(tmp_path, synthetic_cohort):
    engine = _engine(tmp_path, synthetic_cohort, "dpsgd", cs="ring",
                     frac=0.5)
    result = engine.train()
    assert np.isfinite(result["history"][-1]["train_loss"])
    assert 0.0 <= result["final_global"]["acc"] <= 1.0
