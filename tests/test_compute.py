"""Compute-plane observability tests (ISSUE 14, obs/compute.py +
obs/probe.py).

Contracts:

(a) Cost-model parity: XLA ``cost_analysis()`` FLOPs of one lowered
    training step vs the analytic ``ops/flops.py`` counter at the
    FLAGSHIP AlexNet3D shape — fully abstract (nothing materialized,
    nothing compiled), pinned within the stated tolerance, discrepancy
    recorded rather than silently trusted either way.
(b) Dispatch accounting: every round-program invocation lands one
    ``nidt_dispatch_ms`` sample (compile-vs-execute phase split) and
    every build moves ``nidt_compiles_total`` in the SAME increment as
    ``program.built``; a rebuild of the same plan-cache key is a
    recompile — warning-logged and flight-recorded.
(c) Zero-sync / zero-perturbation: a profiler-armed round is BITWISE
    identical to a disarmed one (params and loss) — the profiler never
    touches a device buffer.
(d) MFU gauges: ``boundary()`` divides analytic FLOPs dispatched by
    synced boundary-to-boundary wall; ``nidt_mfu`` publishes only when
    a peak is known, ``nidt_sustained_tflops`` always.
(e) ``/healthz`` compute block: dispatch liveness over real HTTP.
(f) The declarative probe manifest validates its cells, and one probe
    runs end-to-end through the SHIPPED driver (the session smoke).
"""

import json
import logging
from urllib.request import urlopen

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.config import (
    DataConfig, ExperimentConfig, FedConfig, OptimConfig,
)
from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
from neuroimagedisttraining_tpu.data.federate import FederatedData
from neuroimagedisttraining_tpu.engines import create_engine
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.obs import compute as obs_compute
from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import probe as obs_probe
from neuroimagedisttraining_tpu.obs import trace as obs_trace
from neuroimagedisttraining_tpu.obs.http import MetricsServer
from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

FLAGSHIP_SHAPE = (121, 145, 121)


# ---------------------------------------------------------------------------
# (a) cost-model parity at the flagship shape
# ---------------------------------------------------------------------------


def test_flops_parity_flagship_alexnet3d():
    """XLA vs analytic FLOPs on the flagship AlexNet3D shape, abstract
    end to end on the CPU harness. Stated tolerance: the analytic
    3x-inference convention (the reference's, ops/flops.py) undercounts
    backward-pass transpose convs, so XLA reads ~1.1x at this shape —
    the pin brackets [0.8, 1.5] and the artifact carries the exact
    ratio."""
    trainer = LocalTrainer(create_model("3DCNN", num_classes=1),
                           OptimConfig(), num_classes=1)
    out = obs_compute.analyze_train_step(trainer, FLAGSHIP_SHAPE, 8,
                                         compile=False)
    assert out["xla_flops"] is not None and out["xla_flops"] > 0
    assert out["analytic_flops"] > 0
    assert out["parity_ratio"] is not None
    assert 0.8 <= out["parity_ratio"] <= 1.5, out
    # flagship-scale sanity: one step at b8 is tens of GFLOPs, not MFLOPs
    assert out["analytic_flops"] > 1e10
    # the reconciliation published as gauges (recorded, not trusted)
    snap = obs_metrics.REGISTRY.snapshot()
    assert "nidt_flops_parity_ratio" in snap
    assert "nidt_xla_flops" in snap


def test_analytic_flops_abstract_matches_concrete_callers():
    """The abstract path (eval_shape params) equals the number the
    engines' concrete-params call sites compute — the flops.py
    refactor (eval_shape args, not closure) changed nothing for them."""
    trainer = LocalTrainer(create_model("3dcnn_tiny", num_classes=1),
                           OptimConfig(), num_classes=1)
    shape = (12, 14, 12)
    abstract = obs_compute.analytic_sample_flops(trainer, shape)
    from neuroimagedisttraining_tpu.ops import flops as flops_ops

    cs = trainer.init_client_state(
        jax.random.key(0), jnp.zeros((1,) + shape, jnp.float32))
    concrete = flops_ops.count_training_flops_per_sample(
        trainer.model, cs.params,
        trainer._prep(jnp.zeros((1,) + shape, jnp.float32)))
    assert abstract == concrete


def test_lower_train_step_memory_analysis_smoke():
    """``compile=True`` adds the memory_analysis byte accounting on the
    tiny shape (backend-best-effort — assert the dict shape when the
    backend provides it)."""
    trainer = LocalTrainer(create_model("3dcnn_tiny", num_classes=1),
                           OptimConfig(), num_classes=1)
    out = obs_compute.analyze_train_step(trainer, (12, 14, 12), 4,
                                         compile=True)
    if out["memory"] is not None:
        assert set(out["memory"]) == {"temp_bytes", "argument_bytes",
                                      "output_bytes", "peak_bytes"}
        assert out["memory"]["peak_bytes"] >= out["memory"]["temp_bytes"]
        hbm = obs_metrics.REGISTRY.snapshot().get("nidt_hbm_peak_bytes")
        assert hbm is not None and len(hbm["values"]) >= 4


# ---------------------------------------------------------------------------
# engine harness (tiny, bench-cell construction)
# ---------------------------------------------------------------------------


def _tiny_engine(tmp_path, tag, rounds=2, K=1):
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm="fedavg",
        data=DataConfig(dataset="synthetic"),
        optim=OptimConfig(lr=1e-3, batch_size=8, epochs=1),
        fed=FedConfig(client_num_in_total=2, comm_round=rounds,
                      rounds_per_dispatch=K,
                      frequency_of_the_test=10 ** 9),
        log_dir=str(tmp_path), tag=tag)
    kx, ky = jax.random.split(jax.random.key(3))
    X = jax.random.randint(kx, (2, 16, 12, 14, 12), 0, 255,
                           dtype=jnp.int32).astype(jnp.uint8)
    y = jax.random.randint(ky, (2, 16), 0, 2, dtype=jnp.int32)
    n = jnp.full((2,), 16, jnp.int32)
    fed = FederatedData(X_train=X, y_train=y, n_train=n,
                        X_test=X[:, :4], y_test=y[:, :4],
                        n_test=jnp.full((2,), 4, jnp.int32))
    trainer = LocalTrainer(create_model("3dcnn_tiny", num_classes=1),
                           cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    eng = create_engine("fedavg", cfg, fed, trainer, logger=log)
    eng._donate = False  # tests replay state through the programs
    return eng


def _one_round(eng, params, bstats, r=0):
    sampled = jnp.asarray(eng.client_sampling(r))
    rngs = eng.per_client_rngs(r, np.arange(2))
    return eng._round_jit(params, bstats, eng.data, sampled, rngs,
                          eng.round_lr(r))


# ---------------------------------------------------------------------------
# (b) dispatch + compile accounting
# ---------------------------------------------------------------------------


def test_dispatch_histogram_and_compile_counter(tmp_path):
    eng = _tiny_engine(tmp_path, "acct")
    gs = eng.init_global_state()
    h0 = obs_compute.PROFILER.health()
    ctr0 = obs_compute.compiles_total(engine="fedavg", program="round")
    out = _one_round(eng, gs.params, gs.batch_stats)
    out = _one_round(eng, out[0], out[1], r=1)
    jax.block_until_ready(out[0])
    # counter moved with built — one measurement
    assert eng.program.built == 1
    assert obs_compute.compiles_total(engine="fedavg",
                                      program="round") - ctr0 == 1.0
    # two dispatches: one compile-phase, one execute-phase sample
    hist = obs_metrics.REGISTRY.snapshot()["nidt_dispatch_ms"]
    phases = {(v["labels"]["engine"], v["labels"]["phase"]):
              v["value"]["count"] for v in hist["values"]
              if v["labels"]["program"] == "round"}
    assert phases.get(("fedavg", "compile"), 0) >= 1
    assert phases.get(("fedavg", "execute"), 0) >= 1
    h1 = obs_compute.PROFILER.health()
    assert h1["dispatches"] >= h0["dispatches"] + 2
    assert h1["last_dispatch_age_s"] is not None
    assert h1["last_dispatch_age_s"] >= 0


def test_recompile_storm_warns_and_flight_records(tmp_path, caplog):
    eng = _tiny_engine(tmp_path, "storm")
    obs_flight.clear()
    prog = eng.program
    with caplog.at_level(logging.WARNING,
                         logger="neuroimagedisttraining_tpu.obs"):
        prog._note_build("round", ("round", None, None, False))
        prog._note_build("round", ("round", None, None, False))
    assert any("RECOMPILED" in r.message for r in caplog.records)
    kinds = [e["kind"] for e in obs_flight.events()]
    assert "recompile" in kinds
    rec = [e for e in obs_flight.events() if e["kind"] == "recompile"][0]
    assert rec["engine"] == "fedavg" and rec["program"] == "round"
    # distinct keys are specializations, not recompiles: no new warning
    n_warn = len([r for r in caplog.records if "RECOMPILED" in r.message])
    with caplog.at_level(logging.WARNING,
                         logger="neuroimagedisttraining_tpu.obs"):
        prog._note_build("round_sharded", ("round", 2, None, True))
    assert len([r for r in caplog.records
                if "RECOMPILED" in r.message]) == n_warn


# ---------------------------------------------------------------------------
# (c) armed == disarmed, bitwise
# ---------------------------------------------------------------------------


def test_profiler_armed_vs_disarmed_bitwise(tmp_path):
    """The acceptance pin: the profiler adds clock reads and registry
    mutations around the ENQUEUE — never a device touch — so the round
    is bitwise-identical armed vs disarmed (and the overhead rides the
    obs_overhead <= 2% cell, bench.py)."""
    eng_a = _tiny_engine(tmp_path, "armed")
    eng_d = _tiny_engine(tmp_path, "disarmed")
    gs_a = eng_a.init_global_state()
    gs_d = eng_d.init_global_state()
    obs_metrics.enable()
    obs_trace.arm(str(tmp_path / "t.json"))
    try:
        out_a = _one_round(eng_a, gs_a.params, gs_a.batch_stats)
        eng_a._flush_nonfinite(0)
    finally:
        obs_trace.disarm()
    obs_metrics.disable()
    try:
        out_d = _one_round(eng_d, gs_d.params, gs_d.batch_stats)
        eng_d._flush_nonfinite(0)
    finally:
        obs_metrics.enable()
    for a, d in zip(jax.tree.leaves(out_a[0]), jax.tree.leaves(out_d[0])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(d))
    assert float(out_a[2]) == float(out_d[2])


# ---------------------------------------------------------------------------
# (d) MFU / sustained-TFLOPs boundary math
# ---------------------------------------------------------------------------


def test_boundary_publishes_mfu_and_tflops():
    obs_compute.PROFILER.arm_model("unit", flops_per_round=2e9,
                                   peak_flops=1e12)
    obs_compute.note_dispatch("unit", "round", 0.001, rounds=3)
    mfu = obs_compute.boundary("unit")
    assert mfu is not None and 0 < mfu
    snap = obs_metrics.REGISTRY.snapshot()
    cells = {v["labels"]["engine"]: v["value"]
             for v in snap["nidt_mfu"]["values"]}
    assert cells["unit"] == pytest.approx(mfu)
    tf = {v["labels"]["engine"]: v["value"]
          for v in snap["nidt_sustained_tflops"]["values"]}
    # 3 rounds x 2 GFLOP over the measured wall; mfu = tflops*1e12/peak
    assert tf["unit"] * 1e12 / 1e12 == pytest.approx(mfu, rel=1e-6)
    h = obs_compute.PROFILER.health()
    assert h["last_mfu"] == pytest.approx(mfu)
    # empty window: no sample (no division by zero rounds)
    assert obs_compute.boundary("unit") is None
    # unarmed engines never publish
    assert obs_compute.boundary("someone-else") is None


def test_boundary_without_peak_publishes_tflops_only():
    obs_compute.PROFILER.arm_model("unit2", flops_per_round=1e9,
                                   peak_flops=0.0)
    obs_compute.note_dispatch("unit2", "round", 0.001, rounds=1)
    assert obs_compute.boundary("unit2") is None  # no peak -> no MFU
    h = obs_compute.PROFILER.health()
    assert h["last_sustained_tflops"] is not None
    assert h["last_mfu"] is None


def test_peak_flops_env_override(monkeypatch):
    monkeypatch.setenv("NIDT_PEAK_FLOPS", "123e12")
    assert obs_compute.peak_flops_estimate() == 123e12
    monkeypatch.setenv("NIDT_PEAK_FLOPS", "not-a-number")
    assert obs_compute.peak_flops_estimate() == 0.0  # cpu harness
    monkeypatch.delenv("NIDT_PEAK_FLOPS")
    assert obs_compute.peak_flops_estimate() == 0.0


def test_set_peak_flops_override_sticks_across_arm():
    """--peak_flops must survive the engine's lazy arm_model (the CLI
    sets it before any dispatch)."""
    obs_compute.PROFILER.set_peak_flops(7e12)
    obs_compute.PROFILER.arm_model("unit3", flops_per_round=1e9)
    assert obs_compute.PROFILER.health()["peak_flops"] == 7e12


# ---------------------------------------------------------------------------
# (e) /healthz compute block over real HTTP
# ---------------------------------------------------------------------------


def test_healthz_compute_block_http(tmp_path):
    eng = _tiny_engine(tmp_path, "health")
    gs = eng.init_global_state()
    out = _one_round(eng, gs.params, gs.batch_stats)
    jax.block_until_ready(out[0])
    srv = MetricsServer(0, health_probe=lambda: {
        "compute": obs_compute.PROFILER.health()})
    try:
        doc = json.loads(urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=5).read())
    finally:
        srv.close()
    assert doc["ok"] is True
    comp = doc["compute"]
    assert comp["dispatches"] >= 1
    assert comp["compiles"] >= 1
    assert comp["last_dispatch_age_s"] is not None
    assert "recompiles" in comp and "last_mfu" in comp


# ---------------------------------------------------------------------------
# (f) the declarative probe manifest + session driver
# ---------------------------------------------------------------------------


def test_probe_manifest_validates_cells(tmp_path):
    with pytest.raises(ValueError, match="unknown cell keys"):
        obs_probe.Probe("bad", {"not_a_knob": 1})
    man = tmp_path / "m.json"
    man.write_text(json.dumps(
        [{"name": "a", "cell": {"precision": "fp32"}}]))
    probes = obs_probe.load_manifest(str(man))
    assert probes[0].name == "a"
    assert probes[0].cell == {"precision": "fp32"}
    man.write_text("{}")
    with pytest.raises(ValueError, match="non-empty JSON list"):
        obs_probe.load_manifest(str(man))


def test_default_manifest_arms_sharded_probe_with_devices():
    names1 = [p.name for p in obs_probe.default_manifest(1)]
    names2 = [p.name for p in obs_probe.default_manifest(2)]
    assert "cohort_sharded" not in names1
    assert "cohort_sharded" in names2


def test_run_probe_shipped_driver(tmp_path, monkeypatch):
    """One probe through the SHIPPED driver (engine.train()) on the
    smoke shape: deterministic dispatch/compile counts + profiler
    samples in the cell (the tier-1 sibling of the slow full-session
    smoke)."""
    monkeypatch.setenv("PROFILE_ROUNDS", "2")
    meta = obs_probe._env_meta()
    fed = obs_probe._make_fed(meta)
    log = ExperimentLogger(str(tmp_path), "synthetic", "probe-t",
                           console=False)
    cell = obs_probe.run_probe(
        obs_probe.Probe("fp32_baseline", {"precision": "fp32"}),
        meta, fed, log)
    assert cell["ran"] is True
    assert cell["dispatches"] == 2  # one round program, two rounds
    assert cell["compiles"] == 1
    assert cell["wall_s"] > 0
    assert cell["sustained_tflops"] is not None


def test_run_probe_skips_unprovisionable_mesh(tmp_path, monkeypatch):
    monkeypatch.setenv("PROFILE_ROUNDS", "2")
    meta = obs_probe._env_meta()
    fed = obs_probe._make_fed(meta)
    log = ExperimentLogger(str(tmp_path), "synthetic", "probe-s",
                           console=False)
    cell = obs_probe.run_probe(
        obs_probe.Probe("cohort_sharded",
                        {"precision": "fp32", "client_mesh": 64}),
        meta, fed, log)
    assert cell["ran"] is False
    assert "64 devices" in cell["skip_reason"]


@pytest.mark.slow
def test_profile_session_end_to_end(tmp_path, monkeypatch):
    """The full push-button session on a 2-probe manifest: artifact
    schema, live /metrics self-scrape, healthz compute block, and the
    bench gate's spec paths all resolve against the fresh artifact."""
    monkeypatch.setenv("PROFILE_ROUNDS", "2")
    manifest = (
        obs_probe.Probe("fp32_baseline", {"precision": "fp32"}),
        obs_probe.Probe("fused_dispatch_k4",
                        {"precision": "fp32",
                         "rounds_per_dispatch": 4}),
    )
    out = tmp_path / "profile_session.json"
    doc = obs_probe.run_session(manifest, str(out))
    assert out.exists()
    assert doc["session"]["probes_completed"] == 2
    assert doc["session"]["metrics_scrape_ok"] is True
    assert doc["session"]["healthz_compute_ok"] is True
    assert doc["xla"]["train_step"]["parity_ratio"] is not None
    # the gate resolves the fresh artifact's spec paths (self-diff:
    # fresh == committed == this artifact -> ratios 1.0, eq green)
    from neuroimagedisttraining_tpu.analysis import bench_gate

    res = bench_gate.gate(str(tmp_path), committed_dir=str(tmp_path),
                          artifacts=["profile_session.json"])
    assert res["verdict"] == "green", res
