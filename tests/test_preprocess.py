"""L0 preprocessing CLI: synthetic NIfTI cohort -> X/y/site HDF5 round
trip (Preprocess_ABCD.ipynb cells 3-37 parity; VERDICT r2 next-step #5).
Runs entirely through the built-in NIfTI reader/writer (nibabel optional).
"""

import csv
import os
import subprocess
import sys

import numpy as np
import pytest

from neuroimagedisttraining_tpu import preprocess as PP
from neuroimagedisttraining_tpu.data import partition as P
from neuroimagedisttraining_tpu.data.hdf5 import load_abcd_hdf5

SHAPE = (12, 14, 12)


@pytest.fixture()
def raw_cohort(tmp_path):
    """8 subjects in the reference's directory layout + info CSV."""
    rng = np.random.default_rng(5)
    vols = []
    for i in range(8):
        # positive 'brain' blob in the middle, near-zero rim -> the
        # mean-threshold mask keeps the middle only
        v = rng.uniform(0.0, 0.05, SHAPE).astype(np.float32)
        v[3:9, 4:10, 3:9] += rng.uniform(0.5, 1.0, (6, 6, 6))
        vols.append(v)
        d = tmp_path / f"sub{i:02d}" / "Baseline" / "anat_20180101"
        os.makedirs(d)
        PP.write_nifti(str(d / "Sm6mwc1pT1.nii"), v)
    # a subject dir without anatomy -> must be skipped
    os.makedirs(tmp_path / "sub_broken" / "Baseline")
    info = tmp_path / "info.csv"
    with open(info, "w", newline="") as f:
        w = csv.DictWriter(f, ["subject", "female", "abcd_site"])
        w.writeheader()
        for i in range(8):
            w.writerow({"subject": f"sub{i:02d}", "female": i % 2,
                        "abcd_site": f"site{i % 3:02d}"})
    return tmp_path, vols, info


def test_nifti_roundtrip(tmp_path):
    vol = np.random.default_rng(0).normal(size=SHAPE).astype(np.float32)
    for name in ("v.nii", "v.nii.gz"):
        p = str(tmp_path / name)
        PP.write_nifti(p, vol)
        got = PP.read_nifti(p)
        np.testing.assert_allclose(got, vol, rtol=1e-6)


def test_preprocess_pipeline_schema_and_values(raw_cohort, tmp_path):
    root, vols, info = raw_cohort
    out = str(tmp_path / "cohort.h5")
    summary = PP.preprocess_cohort(str(root), str(info), out,
                                   mask_threshold=0.2, log=lambda *a: None)
    assert summary["subjects"] == 8 and summary["sites"] == 3

    cohort = load_abcd_hdf5(out, lazy=False)
    assert cohort["X"].shape == (8,) + SHAPE
    assert cohort["X"].dtype == np.uint8
    np.testing.assert_array_equal(cohort["y"], [i % 2 for i in range(8)])
    np.testing.assert_array_equal(cohort["site"],
                                  [i % 3 for i in range(8)])

    # mask semantics: voxels where the cohort MEAN <= threshold are zeroed
    mean = np.mean(vols, axis=0)
    mask = mean > 0.2
    assert not mask.all() and mask.any()
    # per-subject quantization parity with cell 37 on a probe subject
    masked = vols[3] * mask
    lo, hi = masked.min(), masked.max()
    want = ((masked - lo) / (hi - lo) * 255).astype(np.uint8)
    np.testing.assert_array_equal(cohort["X"][3], want)
    # masked-out voxels quantize to the per-subject minimum code
    assert cohort["X"][3][~mask].max() <= cohort["X"][3][mask].max()

    # the output is directly consumable by the training data layer
    train_map, test_map, _ = P.site_partition(cohort["site"], seed=42)
    assert set(train_map) == {0, 1, 2}


def test_preprocess_store_float_matches_notebook_values(raw_cohort,
                                                        tmp_path):
    root, vols, info = raw_cohort
    out = str(tmp_path / "cohort_f.h5")
    PP.preprocess_cohort(str(root), str(info), out, store_float=True,
                         log=lambda *a: None)
    import h5py

    with h5py.File(out) as f:
        X = f["X"][()]
    assert X.dtype == np.float32
    assert 0.0 <= X.min() and X.max() <= 1.0
    # exactly the notebook's uint8/255 grid (cell 37)
    np.testing.assert_array_equal(X * 255, np.round(X * 255))


def test_preprocess_joins_by_id_when_rows_outnumber_volumes(raw_cohort,
                                                            tmp_path):
    """A CSV row whose volume was skipped by discovery (sub_broken, no
    anat dir) must not shift later subjects' labels (ADVICE r3 #3)."""
    root, _, _ = raw_cohort
    info = tmp_path / "info_extra.csv"
    with open(info, "w", newline="") as f:
        w = csv.DictWriter(f, ["subject", "female", "abcd_site"])
        w.writeheader()
        for i in range(8):
            w.writerow({"subject": f"sub{i:02d}", "female": i % 2,
                        "abcd_site": f"site{i % 3:02d}"})
            if i == 3:  # mid-file row for the discovered-skipped subject,
                # carrying NOVEL categorical values: codes must be computed
                # after the join or these would shift every kept code
                w.writerow({"subject": "sub_broken", "female": "NA",
                            "abcd_site": "site_zz"})
    out = str(tmp_path / "joined.h5")
    PP.preprocess_cohort(str(root), str(info), out, log=lambda *a: None)
    cohort = load_abcd_hdf5(out, lazy=False)
    np.testing.assert_array_equal(cohort["y"], [i % 2 for i in range(8)])
    np.testing.assert_array_equal(cohort["site"],
                                  [i % 3 for i in range(8)])


def test_preprocess_rejects_positional_count_mismatch(raw_cohort, tmp_path):
    """Without an id column, a row-count mismatch is an error, never a
    silent truncation (ADVICE r3 #3)."""
    root, _, _ = raw_cohort
    info = tmp_path / "info_noid.csv"
    with open(info, "w", newline="") as f:
        w = csv.DictWriter(f, ["female", "abcd_site"])
        w.writeheader()
        for i in range(9):  # one extra row vs the 8 discovered volumes
            w.writerow({"female": i % 2, "abcd_site": f"site{i % 3:02d}"})
    with pytest.raises(ValueError, match="misalign"):
        PP.preprocess_cohort(str(root), str(info),
                             str(tmp_path / "bad.h5"), log=lambda *a: None)


def test_preprocess_rejects_duplicate_ids(raw_cohort, tmp_path):
    root, _, _ = raw_cohort
    info = tmp_path / "info_dupe.csv"
    with open(info, "w", newline="") as f:
        w = csv.DictWriter(f, ["subject", "female", "abcd_site"])
        w.writeheader()
        for i in range(8):
            w.writerow({"subject": f"sub{i:02d}", "female": i % 2,
                        "abcd_site": f"site{i % 3:02d}"})
        w.writerow({"subject": "sub03", "female": 0,  # conflicting re-row
                    "abcd_site": "site01"})
    with pytest.raises(ValueError, match="duplicate ids"):
        PP.preprocess_cohort(str(root), str(info),
                             str(tmp_path / "bad3.h5"), log=lambda *a: None)


def test_preprocess_errors_on_missing_id_row(raw_cohort, tmp_path):
    root, _, _ = raw_cohort
    info = tmp_path / "info_short.csv"
    with open(info, "w", newline="") as f:
        w = csv.DictWriter(f, ["subject", "female", "abcd_site"])
        w.writeheader()
        for i in range(7):  # sub07's row missing
            w.writerow({"subject": f"sub{i:02d}", "female": i % 2,
                        "abcd_site": f"site{i % 3:02d}"})
    with pytest.raises(ValueError, match="missing 'subject' rows"):
        PP.preprocess_cohort(str(root), str(info),
                             str(tmp_path / "bad2.h5"), log=lambda *a: None)


def test_preprocess_cli_subprocess(raw_cohort, tmp_path):
    root, _, info = raw_cohort
    out = str(tmp_path / "cli.h5")
    r = subprocess.run(
        [sys.executable, "-m", "neuroimagedisttraining_tpu.preprocess",
         "--raw_dir", str(root), "--subject_info", str(info),
         "--out", out],
        capture_output=True, text=True, cwd="/root/repo", timeout=300)
    assert r.returncode == 0, r.stderr[-500:]
    assert os.path.exists(out)
    assert "wrote" in r.stdout
