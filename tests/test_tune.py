"""Closed-loop autotuner (ISSUE 19, tune/).

Pins the five contracts the subsystem ships on:

1. **Determinism** — same seed + space reproduce the same winner AND
   the same recipe BYTES, pinned against the committed
   ``bench_matrix/recipes/cpu.json`` artifact (the virtual backend
   derives every score from sha256(seed, fingerprint, fidelity), so
   this is an exact byte pin, not a tolerance).
2. **Resume** — a search killed mid-screen completes from the JSONL
   journal without re-measuring finished cells (fresh-measurement
   counts prove it).
3. **Recipe application** — ``--recipe`` reproduces the winner's
   effective config exactly; an explicitly-spelled flag wins and the
   override rides the structured fallback counter.
4. **Loud failure modes** — unknown axis, out-of-domain value, recipe
   naming an undeclared knob, device-kind mismatch, truncated JSON,
   sha mismatch: each dies with a specific ValueError at startup.
5. **Drift loop** — the armed ``mfu-below-recipe`` rule fires after
   the debounce window and drops a ``retune_recommended`` event into
   the flight recorder.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

import pytest

from neuroimagedisttraining_tpu.core.optim import (
    remat_auto_samples_threshold,
)
from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import names as N
from neuroimagedisttraining_tpu.obs import probe as obs_probe
from neuroimagedisttraining_tpu.obs import rules as obs_rules
from neuroimagedisttraining_tpu.tune import recipe as tune_recipe
from neuroimagedisttraining_tpu.tune import search as tune_search
from neuroimagedisttraining_tpu.tune import space as tune_space

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMMITTED_RECIPE = os.path.join(REPO, "bench_matrix", "recipes",
                                "cpu.json")
COMMITTED_SESSION = os.path.join(REPO, "bench_matrix",
                                 "autotune_session.json")

#: the committed artifact's search configuration (scripts/
#: run_autotune.sh defaults) — the tests re-run it in-process
SEED, SCREEN, COMMIT, SURVIVORS = 20, 2, 5, 4


def _committed_space() -> tune_space.Space:
    return tune_space.build_space("cpu", n_devices=2)


def _search(journal=None, measure=tune_search.virtual_measure):
    return tune_search.run_search(
        _committed_space(), SEED, measure, journal,
        screen_fidelity=SCREEN, commit_fidelity=COMMIT,
        survivors=SURVIVORS, log=lambda *a: None)


# ---------------------------------------------------------------------------
# space
# ---------------------------------------------------------------------------


def test_space_unknown_axis_is_loud():
    with pytest.raises(ValueError, match="unknown axes.*bogus"):
        tune_space.Space(axes=(("bogus", (1, 2)),))


def test_space_out_of_domain_value_is_loud():
    with pytest.raises(ValueError, match="out of domain"):
        tune_space.Space(axes=(("precision", ("fp32", "fp64")),))
    with pytest.raises(ValueError, match="no values"):
        tune_space.Space(axes=(("precision", ()),))


def test_space_census_is_deterministic_and_device_aware():
    s2 = _committed_space()
    valid2, rej2 = s2.cells()
    assert len(valid2) == 96 and not rej2
    # one visible device: every client_mesh=2 cell is rejected WITH a
    # reason (the driver would skip it), never silently dropped
    s1 = tune_space.build_space("cpu", n_devices=1)
    valid1, rej1 = s1.cells()
    assert len(valid1) == 48 and len(rej1) == 48
    assert all("client_mesh=2" in r["reason"] for r in rej1)
    assert s1.fingerprint() != s2.fingerprint()
    # enumeration order is declared order — the determinism anchor
    assert [c["precision"] for c in valid2[:2]] == ["fp32", "fp32"]


def test_space_hbm_bound_drops_only_oversized_cells():
    # a deliberately tiny HBM forces the estimator to reject the
    # biggest-batch fp32 cells while bf16 (half the activation bytes)
    # at the same batch survives — the bound is cell-aware, not global
    hbm = int((tune_space.est_step_bytes((12, 14, 12), 16, "fp32",
                                         "none")) / 0.92) - 1
    s = tune_space.Space(axes=tune_space.DEFAULT_AXES, n_devices=2,
                         hbm_bytes=hbm)
    valid, rej = s.cells()
    assert rej and all(r["cell"]["precision"] == "fp32"
                       and r["cell"]["batch"] == 16
                       and r["cell"]["remat"] == "none"
                       for r in rej)
    assert any(c["precision"] == "bf16_mixed" and c["batch"] == 16
               for c in valid)
    assert all("hbm-bound" in r["reason"] for r in rej)


def test_compat_rows_relevant_to_the_space_are_satisfied():
    rows = tune_space.relevant_compat_rows()
    # the two committed rejection rows whose knobs the tuner touches:
    # fused_update requires sgd (pinned), loss_scale composes with
    # precision (pinned 1.0)
    knob_sets = {r["knobs"] for r in rows}
    assert ("client_optimizer", "fused_update") in knob_sets
    assert ("loss_scale", "precision") in knob_sets
    assert tune_space.PINNED["client_optimizer"] == "sgd"
    assert tune_space.PINNED["loss_scale"] == 1.0


# ---------------------------------------------------------------------------
# search: determinism + resume
# ---------------------------------------------------------------------------


def test_search_reproduces_committed_recipe_bytes(tmp_path):
    """Same seed + space => same winner and same artifact BYTES,
    pinned against the committed bench_matrix/recipes/cpu.json."""
    res = _search()
    doc = tune_recipe.recipe_doc_from_search(res, "cpu")
    out = tmp_path / "cpu.json"
    tune_recipe.write_recipe(doc, str(out))
    committed = open(COMMITTED_RECIPE, "rb").read()
    assert out.read_bytes() == committed
    # and a second in-process run produces the same bytes again
    res2 = _search()
    assert (tune_recipe.recipe_doc_from_search(res2, "cpu") == doc)


def test_committed_session_artifact_matches_recipe():
    session = json.load(open(COMMITTED_SESSION))
    recipe = json.load(open(COMMITTED_RECIPE))
    assert session["winner"]["fingerprint"] == recipe["fingerprint"]
    assert session["winner"]["score"] == recipe["score"]
    assert session["space"]["fingerprint"] == recipe["space_fingerprint"]
    assert session["recipe"]["sha256"] == recipe["sha256"]
    assert session["session"]["deterministic"] is True
    assert session["winner_validation"]["ran"] is True
    assert session["winner_validation"]["status"] == "ok"


def test_search_failed_cells_lose_not_crash():
    def flaky(cell, fidelity, seed):
        if cell["precision"] == "bf16_mixed":
            return {"status": "failed", "reason": "recompile-storm",
                    "score": None, "score_metric": "none", "metrics": {}}
        return tune_search.virtual_measure(cell, fidelity, seed)

    res = tune_search.run_search(
        _committed_space(), SEED, flaky,
        screen_fidelity=SCREEN, commit_fidelity=COMMIT,
        survivors=SURVIVORS, log=lambda *a: None)
    assert res["winner"]["cell"]["precision"] == "fp32"
    failed = [m for m in res["screened"] if m["status"] == "failed"]
    assert len(failed) == 48
    assert all(m["reason"] == "recompile-storm" for m in failed)


def test_journal_resume_skips_finished_measurements(tmp_path):
    journal_path = str(tmp_path / "journal.jsonl")
    calls = {"n": 0}

    def counting(cell, fidelity, seed):
        calls["n"] += 1
        return tune_search.virtual_measure(cell, fidelity, seed)

    res = _search(tune_search.Journal(journal_path), counting)
    total = calls["n"]
    assert res["fresh_measurements"] == total == 100  # 96 + 4 refines

    # kill mid-screen: keep only the first 40 journal lines (the run
    # died partway through the screen rung), then rerun
    lines = open(journal_path).read().splitlines(keepends=True)
    with open(journal_path, "w") as f:
        f.writelines(lines[:40])
    calls["n"] = 0
    res2 = _search(tune_search.Journal(journal_path), counting)
    assert calls["n"] == total - 40
    assert res2["journal_reused"] == 40
    assert res2["winner"] == res["winner"]

    # full journal: zero fresh measurements, identical winner
    calls["n"] = 0
    res3 = _search(tune_search.Journal(journal_path), counting)
    assert calls["n"] == 0 and res3["journal_reused"] == total
    assert res3["winner"] == res["winner"]


def test_journal_tolerates_torn_tail_line(tmp_path):
    journal_path = str(tmp_path / "j.jsonl")
    j = tune_search.Journal(journal_path)
    j.record({"fingerprint": "abc", "fidelity": 2, "status": "ok",
              "score": 1.0, "score_metric": "s", "cell": {},
              "reason": "", "metrics": {}})
    with open(journal_path, "a") as f:
        f.write('{"fingerprint": "torn')  # kill mid-write
    j2 = tune_search.Journal(journal_path)
    assert len(j2) == 1 and j2.get("abc", 2)["score"] == 1.0


# ---------------------------------------------------------------------------
# recipe: load + apply
# ---------------------------------------------------------------------------


def _parse_main(argv):
    from neuroimagedisttraining_tpu.__main__ import add_args
    parser = argparse.ArgumentParser()
    add_args(parser)
    return parser.parse_args(argv)


def _fallback_count() -> float:
    snap = obs_metrics.REGISTRY.snapshot()
    total = 0.0
    for v in snap.get(N.FALLBACK_TOTAL, {}).get("values", ()):
        if v["labels"].get("reason") == "recipe-override":
            total += v["value"]
    return total


def test_apply_recipe_reproduces_winner_config_exactly():
    doc = tune_recipe.load_recipe(COMMITTED_RECIPE)
    args = _parse_main([])
    overridden = tune_recipe.apply_recipe(args, doc, [])
    assert overridden == []
    cell = doc["cell"]
    assert args.precision == cell["precision"]
    assert args.fused_update == cell["fused_update"]
    assert args.remat == cell["remat"]
    assert args.client_mesh == cell["client_mesh"]
    assert args.rounds_per_dispatch == cell["rounds_per_dispatch"]
    assert args.batch_size == cell["batch"]
    # the recipe's score is published for the drift rule's scrape
    snap = obs_metrics.REGISTRY.snapshot()
    vals = snap[N.RECIPE_SCORE]["values"]
    assert vals and vals[0]["value"] == pytest.approx(doc["score"])


def test_apply_recipe_explicit_flag_wins_and_is_counted(capsys):
    doc = tune_recipe.load_recipe(COMMITTED_RECIPE)
    before = _fallback_count()
    argv = ["--batch_size", "4"]
    args = _parse_main(argv)
    overridden = tune_recipe.apply_recipe(args, doc, argv)
    assert overridden == ["batch"]
    assert args.batch_size == 4  # the CLI value, not the recipe's 16
    assert args.precision == doc["cell"]["precision"]  # rest applied
    assert _fallback_count() == before + 1
    assert "--batch_size" in capsys.readouterr().err


def test_recipe_failure_modes_are_loud(tmp_path):
    doc = tune_recipe.load_recipe(COMMITTED_RECIPE)

    def _write(mutate):
        d = {k: v for k, v in doc.items() if k != "_path"}
        mutate(d)
        p = tmp_path / "r.json"
        p.write_text(json.dumps(d))
        return str(p)

    def _repin(d):
        d["sha256"] = tune_recipe.recipe_sha(d)

    # truncated JSON
    p = tmp_path / "trunc.json"
    p.write_text(json.dumps(doc)[:40])
    with pytest.raises(ValueError, match="invalid JSON"):
        tune_recipe.load_recipe(str(p))
    # hand-edited file: sha self-pin trips
    with pytest.raises(ValueError, match="sha256 mismatch"):
        tune_recipe.load_recipe(_write(
            lambda d: d.__setitem__("score", 99.0)))
    # recipe naming a knob with no config-field mapping
    def _unknown(d):
        d["cell"] = dict(d["cell"], loss_scale=2.0)
        d["fingerprint"] = tune_space.cell_fingerprint(d["cell"])
        _repin(d)
    with pytest.raises(ValueError, match="no config-field mapping"):
        tune_recipe.load_recipe(_write(_unknown))
    # out-of-domain value for a known knob
    def _bad_value(d):
        d["cell"] = dict(d["cell"], precision="fp64")
        d["fingerprint"] = tune_space.cell_fingerprint(d["cell"])
        _repin(d)
    with pytest.raises(ValueError, match="out of domain"):
        tune_recipe.load_recipe(_write(_bad_value))
    # device-kind mismatch vs the live backend
    def _wrong_kind(d):
        d["device_kind"] = "TPU v4"
        _repin(d)
    with pytest.raises(ValueError, match="device_kind"):
        tune_recipe.load_recipe(_write(_wrong_kind),
                                expected_kind="cpu")
    # missing committed recipe for this device kind (auto)
    with pytest.raises(ValueError, match="no committed recipe"):
        orig = tune_recipe.recipes_dir
        tune_recipe.recipes_dir = lambda: str(tmp_path / "none")
        try:
            tune_recipe.resolve_and_load("auto")
        finally:
            tune_recipe.recipes_dir = orig


def test_recipe_keys_cover_every_searchable_axis():
    # an axis the space can search but no recipe can ship is a dead
    # end; RECIPE_KEYS must cover the probe cell keys exactly
    assert set(tune_recipe.RECIPE_KEYS) == set(obs_probe.CELL_KEYS)


# ---------------------------------------------------------------------------
# drift loop
# ---------------------------------------------------------------------------


def _snap(metric, value):
    return {metric: {"kind": "gauge", "help": "",
                     "values": [{"labels": {}, "value": value}]}}


def test_drift_rule_fires_and_records_retune_event():
    doc = tune_recipe.load_recipe(COMMITTED_RECIPE)
    (rule,) = tune_recipe.drift_rules(doc)
    assert rule.name == "mfu-below-recipe"
    assert rule.metric == N.SUSTAINED_TFLOPS  # committed score metric
    assert rule.threshold == pytest.approx(0.8 * doc["score"])
    assert rule.on_fire_event == "retune_recommended"

    obs_flight.clear()
    eng = obs_rules.RuleEngine([rule])
    low = 0.5 * doc["score"]
    for r in range(rule.for_rounds):
        eng.observe(r, _snap(rule.metric, low))
    assert eng.health_block()["firing"] == {"mfu-below-recipe": "warn"}
    kinds = [e["kind"] for e in obs_flight.events()]
    assert "retune_recommended" in kinds
    ev = next(e for e in obs_flight.events()
              if e["kind"] == "retune_recommended")
    assert ev["rule"] == "mfu-below-recipe"

    # healthy scores: never fires, no event
    obs_flight.clear()
    eng2 = obs_rules.RuleEngine([rule])
    for r in range(4):
        eng2.observe(r, _snap(rule.metric, doc["score"]))
    assert eng2.health_block()["firing"] == {}
    assert not [e for e in obs_flight.events()
                if e["kind"] == "retune_recommended"]


def test_configure_merges_drift_rules_with_builtins():
    doc = tune_recipe.load_recipe(COMMITTED_RECIPE)
    eng = obs_rules.configure(extra_rules=tune_recipe.drift_rules(doc))
    names = {r.name for r in eng.rules}
    assert "mfu-below-recipe" in names
    assert "mfu-floor" in names  # builtins still present


def test_mfu_score_metric_arms_the_mfu_gauge():
    doc = dict(json.load(open(COMMITTED_RECIPE)))
    doc["score_metric"] = "mfu"
    (rule,) = tune_recipe.drift_rules(doc)
    assert rule.metric == N.MFU


# ---------------------------------------------------------------------------
# satellites: batch axis + precision-aware remat threshold
# ---------------------------------------------------------------------------


def test_batch_is_a_declared_validated_cell_key():
    assert "batch" in obs_probe.CELL_KEYS
    obs_probe.validate_cell_value("batch", 8)
    with pytest.raises(ValueError, match="out of domain"):
        obs_probe.validate_cell_value("batch", 0)
    with pytest.raises(ValueError, match="out of domain"):
        obs_probe.validate_cell_value("batch", True)
    with pytest.raises(ValueError, match="unknown cell key"):
        obs_probe.validate_cell_value("batchsize", 8)
    # manifest-loadable: a Probe declaring batch validates eagerly
    obs_probe.Probe("b", {"batch": 4})
    with pytest.raises(ValueError, match="probe 'b'.*out of domain"):
        obs_probe.Probe("b", {"batch": -1})


def test_remat_auto_threshold_is_precision_aware():
    fp32 = remat_auto_samples_threshold("fp32")
    bf16 = remat_auto_samples_threshold("bf16_mixed")
    # bf16 halves activation bytes => 2x the per-device sample budget
    # before remat pays for itself; the ratio IS the contract
    assert bf16 == 2 * fp32
    assert fp32 == 128
    with pytest.raises(ValueError):
        remat_auto_samples_threshold("fp64")


# ---------------------------------------------------------------------------
# CLIs (subprocess)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_tune_cli_emits_committed_artifacts(tmp_path):
    """The CLI at the committed seed/space reproduces the committed
    recipe byte-for-byte and reports deterministic=true."""
    out = subprocess.run(
        [sys.executable, "-m", "neuroimagedisttraining_tpu.tune",
         "--backend", "virtual", "--seed", str(SEED),
         "--virtual_devices", "2",
         "--out", str(tmp_path / "cpu.json"),
         "--session_out", str(tmp_path / "session.json"),
         "--journal", str(tmp_path / "journal.jsonl")],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    session = json.loads(out.stdout.strip().splitlines()[-1])
    assert session["session"]["deterministic"] is True
    assert (tmp_path / "cpu.json").read_bytes() == \
        open(COMMITTED_RECIPE, "rb").read()


@pytest.mark.slow
def test_trainer_cli_rejects_bad_recipe_loudly(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text('{"cell": {')
    out = subprocess.run(
        [sys.executable, "-m", "neuroimagedisttraining_tpu",
         "--dataset", "synthetic", "--recipe", str(bad)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 2
    assert "invalid JSON" in out.stderr
