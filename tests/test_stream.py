"""HDF5 ingestion + host-streaming data path.

The streaming feed must be an exact drop-in: a streamed FedAvg run sees
bitwise-identical inputs to the device-resident run, so its metrics are
identical (VERDICT r1 missing #2 acceptance)."""

import jax
import numpy as np
import pytest

from neuroimagedisttraining_tpu.config import (
    DataConfig, ExperimentConfig, FedConfig, OptimConfig,
)
from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
from neuroimagedisttraining_tpu.data import partition as P
from neuroimagedisttraining_tpu.data.federate import federate_cohort
from neuroimagedisttraining_tpu.data.hdf5 import fetch_rows, load_abcd_hdf5
from neuroimagedisttraining_tpu.data.stream import StreamingFederation
from neuroimagedisttraining_tpu.data.synthetic import write_synthetic_hdf5
from neuroimagedisttraining_tpu.engines import create_engine
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger


@pytest.fixture(scope="module")
def h5_cohort(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("h5") / "cohort.h5")
    data = write_synthetic_hdf5(path, num_subjects=48, shape=(12, 14, 12),
                                num_sites=4, seed=0)
    return path, data


def test_load_abcd_hdf5_lazy_and_eager(h5_cohort):
    path, data = h5_cohort
    lazy = load_abcd_hdf5(path, lazy=True)
    assert lazy["file"] is not None
    np.testing.assert_array_equal(lazy["y"], data["y"])
    np.testing.assert_array_equal(lazy["site"], data["site"])
    # X is a lazy handle, row-sliceable
    np.testing.assert_array_equal(np.asarray(lazy["X"][3]), data["X"][3])
    lazy["file"].close()
    eager = load_abcd_hdf5(path, lazy=False)
    assert isinstance(eager["X"], np.ndarray)
    np.testing.assert_array_equal(eager["X"], data["X"])


def test_load_abcd_hdf5_missing_key(tmp_path):
    import h5py

    path = str(tmp_path / "bad.h5")
    with h5py.File(path, "w") as f:
        f.create_dataset("X", data=np.zeros((2, 3, 3, 3), np.uint8))
        f.create_dataset("y", data=np.zeros(2, np.int8))
    with pytest.raises(KeyError, match="site"):
        load_abcd_hdf5(path)


def test_fetch_rows_unsorted_and_duplicate_indices(h5_cohort):
    path, data = h5_cohort
    lazy = load_abcd_hdf5(path, lazy=True)
    idx = np.array([7, 2, 2, 41, 0, 7])
    got = fetch_rows(lazy["X"], idx)
    np.testing.assert_array_equal(got, data["X"][idx])
    lazy["file"].close()


def _assert_final_metrics(a, b):
    """Final-eval parity with float32-ulp slack on the mean losses: the
    resident and streamed paths of these engines run STRUCTURALLY
    different programs (one fused round vs consensus/agg + chunked
    blocks), and buffer donation (ISSUE 4) changes XLA's in-place fusion
    layout, which can reassociate the scalar loss reductions by an ulp.
    Count-based metrics (acc/auc) must still match exactly."""
    assert set(a) == set(b)
    for k in sorted(a):
        if k == "loss":
            np.testing.assert_allclose(b[k], a[k], rtol=1e-6)
        else:
            assert a[k] == b[k], (k, a, b)


def _run_algo(algo, cohort_or_stream, streaming: bool, tmp_path, tag,
              mesh=None, val_fraction=0.0, **cfg_extra):
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm=algo,
        data=DataConfig(dataset="synthetic", partition_method="site",
                        val_fraction=val_fraction),
        optim=OptimConfig(lr=1e-2, batch_size=4, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=3, frac=0.5,
                      frequency_of_the_test=1),
        log_dir=str(tmp_path), tag=tag, **cfg_extra)
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1), cfg.optim,
                           num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    if streaming:
        engine = create_engine(algo, cfg, None, trainer, mesh=mesh,
                               logger=log, stream=cohort_or_stream)
    else:
        fed, _ = federate_cohort(cohort_or_stream, partition_method="site",
                                 mesh=mesh, val_fraction=val_fraction)
        engine = create_engine(algo, cfg, fed, trainer, mesh=mesh,
                               logger=log)
    return engine.train()


def _run_fedavg(cohort_or_stream, streaming: bool, tmp_path, tag):
    return _run_algo("fedavg", cohort_or_stream, streaming, tmp_path, tag)


def test_streaming_fedavg_identical_to_resident(h5_cohort, tmp_path):
    path, data = h5_cohort
    # device-resident run straight from the in-memory cohort
    res = _run_fedavg(data, streaming=False, tmp_path=tmp_path, tag="res")
    # streaming run from the HDF5 file with the same partition maps
    lazy = load_abcd_hdf5(path, lazy=True)
    train_map, test_map, _ = P.site_partition(lazy["site"], seed=42)
    stream = StreamingFederation(lazy["X"], lazy["y"], train_map, test_map)
    try:
        st = _run_fedavg(stream, streaming=True, tmp_path=tmp_path,
                         tag="st")
    finally:
        stream.close()
        lazy["file"].close()

    # identical inputs -> identical round losses and metrics
    for r_res, r_st in zip(res["history"], st["history"]):
        assert r_res["train_loss"] == r_st["train_loss"], (r_res, r_st)
        assert r_res["acc"] == r_st["acc"]
        assert r_res["auc"] == r_st["auc"]
    assert res["final_global"] == st["final_global"]
    assert res["final_personal"]["acc"] == st["final_personal"]["acc"]


def test_streaming_salientgrads_identical_to_resident(h5_cohort, tmp_path):
    """The FLAGSHIP algorithm streams: phase-1 SNIP scores accumulate over
    streamed client chunks, phase-2 masked rounds stream the sampled
    clients' shards — bitwise equal to the device-resident run
    (VERDICT r2 next-step #1 acceptance)."""
    path, data = h5_cohort
    res = _run_algo("salientgrads", data, streaming=False,
                    tmp_path=tmp_path, tag="sgres")
    lazy = load_abcd_hdf5(path, lazy=True)
    train_map, test_map, _ = P.site_partition(lazy["site"], seed=42)
    stream = StreamingFederation(lazy["X"], lazy["y"], train_map, test_map)
    try:
        st = _run_algo("salientgrads", stream, streaming=True,
                       tmp_path=tmp_path, tag="sgst")
    finally:
        stream.close()
        lazy["file"].close()

    # identical mask...
    assert st["mask_density"] == res["mask_density"]
    for a, b in zip(jax.tree.leaves(res["masks"]),
                    jax.tree.leaves(st["masks"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # ...identical rounds, metrics, and personal models
    for r_res, r_st in zip(res["history"], st["history"]):
        assert r_res["train_loss"] == r_st["train_loss"], (r_res, r_st)
        assert r_res["acc"] == r_st["acc"]
        assert r_res["auc"] == r_st["auc"]
        assert r_res["personal_acc"] == r_st["personal_acc"]
    assert res["final_global"] == st["final_global"]
    assert res["final_personal"] == st["final_personal"]


def _open_stream(path):
    lazy = load_abcd_hdf5(path, lazy=True)
    train_map, test_map, _ = P.site_partition(lazy["site"], seed=42)
    return lazy, StreamingFederation(lazy["X"], lazy["y"], train_map,
                                     test_map)


@pytest.mark.slow  # tier-1 870s window (PR 11, the PR 2/7 precedent): per-engine streamed==resident e2e twins ride the full suite; the fedavg/salientgrads/local siblings + the streamed machinery tests keep tier-1 coverage
def test_streaming_subavg_identical_to_resident(h5_cohort, tmp_path):
    """Sub-FedAvg streams its sampled clients' shards per round; personal
    masks stay resident. Streamed == resident bitwise."""
    path, data = h5_cohort
    res = _run_algo("subavg", data, streaming=False, tmp_path=tmp_path,
                    tag="sares")
    lazy, stream = _open_stream(path)
    try:
        st = _run_algo("subavg", stream, streaming=True, tmp_path=tmp_path,
                       tag="sast")
    finally:
        stream.close()
        lazy["file"].close()
    for r_res, r_st in zip(res["history"], st["history"]):
        assert r_res["train_loss"] == r_st["train_loss"], (r_res, r_st)
        assert r_res["personal_acc"] == r_st["personal_acc"]
    assert res["final_personal"] == st["final_personal"]
    np.testing.assert_array_equal(res["client_densities"],
                                  st["client_densities"])


@pytest.mark.slow  # tier-1 870s window (PR 11, the PR 2/7 precedent): per-engine streamed==resident e2e twins ride the full suite; the fedavg/salientgrads/local siblings + the streamed machinery tests keep tier-1 coverage
def test_streaming_dispfl_identical_to_resident(h5_cohort, tmp_path):
    """DisPFL trains every client per round, so the streamed round chunks
    local training (chunk=2 < 4 clients exercises real chunking); the
    consensus einsum runs on resident state. Streamed == resident."""
    path, data = h5_cohort
    res = _run_algo("dispfl", data, streaming=False, tmp_path=tmp_path,
                    tag="dpres")
    lazy, stream = _open_stream(path)
    try:
        st = _run_algo("dispfl", stream, streaming=True, tmp_path=tmp_path,
                       tag="dpst", stream_chunk_clients=2)
    finally:
        stream.close()
        lazy["file"].close()
    for r_res, r_st in zip(res["history"], st["history"]):
        # the scalar loss DIAGNOSTIC is reduced inside the fused resident
        # program but in a separate program when chunked — XLA may
        # reassociate that one reduce, so allow ulp-level slack there; the
        # STATE comparisons below stay exact
        np.testing.assert_allclose(r_st["train_loss"], r_res["train_loss"],
                                   rtol=1e-6)
        assert r_res["personal_acc"] == r_st["personal_acc"]
        assert r_res["mask_change"] == r_st["mask_change"]
    assert res["final_personal"] == st["final_personal"]
    np.testing.assert_array_equal(res["mask_dis_matrix"],
                                  st["mask_dis_matrix"])


def test_streaming_salientgrads_chunked_phase1(h5_cohort, tmp_path):
    """Phase-1 SNIP accumulation over chunk=2 < 4 clients (two chunks)
    still reproduces the resident global mask and rounds."""
    path, data = h5_cohort
    res = _run_algo("salientgrads", data, streaming=False,
                    tmp_path=tmp_path, tag="sgres2")
    lazy, stream = _open_stream(path)
    try:
        st = _run_algo("salientgrads", stream, streaming=True,
                       tmp_path=tmp_path, tag="sgst2",
                       stream_chunk_clients=2)
    finally:
        stream.close()
        lazy["file"].close()
    assert st["mask_density"] == res["mask_density"]
    for a, b in zip(jax.tree.leaves(res["masks"]),
                    jax.tree.leaves(st["masks"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for r_res, r_st in zip(res["history"], st["history"]):
        assert r_res["train_loss"] == r_st["train_loss"], (r_res, r_st)
    assert res["final_global"] == st["final_global"]


@pytest.mark.slow  # tier-1 870s window (PR 11, the PR 2/7 precedent): per-engine streamed==resident e2e twins ride the full suite; the fedavg/salientgrads/local siblings + the streamed machinery tests keep tier-1 coverage
def test_streaming_ditto_identical_to_resident(h5_cohort, tmp_path):
    """Ditto's two tracks only consume sampled clients' shards — the
    streamed round is shape-identical to resident, so bitwise equal."""
    path, data = h5_cohort
    res = _run_algo("ditto", data, streaming=False, tmp_path=tmp_path,
                    tag="dtres")
    lazy, stream = _open_stream(path)
    try:
        st = _run_algo("ditto", stream, streaming=True, tmp_path=tmp_path,
                       tag="dtst")
    finally:
        stream.close()
        lazy["file"].close()
    for r_res, r_st in zip(res["history"], st["history"]):
        assert r_res["train_loss"] == r_st["train_loss"], (r_res, r_st)
        assert r_res["personal_acc"] == r_st["personal_acc"]
        assert r_res["global_acc"] == r_st["global_acc"]
    assert res["final_personal"] == st["final_personal"]


def test_streaming_local_identical_to_resident(h5_cohort, tmp_path):
    """Local-only streams client chunks (chunk=2 < 4 exercises real
    chunking); per-client training is independent so state is exact."""
    path, data = h5_cohort
    res = _run_algo("local", data, streaming=False, tmp_path=tmp_path,
                    tag="lores")
    lazy, stream = _open_stream(path)
    try:
        st = _run_algo("local", stream, streaming=True, tmp_path=tmp_path,
                       tag="lost", stream_chunk_clients=2)
    finally:
        stream.close()
        lazy["file"].close()
    for r_res, r_st in zip(res["history"], st["history"]):
        np.testing.assert_allclose(r_st["train_loss"], r_res["train_loss"],
                                   rtol=1e-6)  # chunked scalar reduce
        assert r_res["acc"] == r_st["acc"]
    assert res["final_personal"] == st["final_personal"]


@pytest.mark.slow  # tier-1 870s window (PR 11, the PR 2/7 precedent): per-engine streamed==resident e2e twins ride the full suite; the fedavg/salientgrads/local siblings + the streamed machinery tests keep tier-1 coverage
def test_streaming_dpsgd_identical_to_resident(h5_cohort, tmp_path):
    """D-PSGD: state-only gossip consensus + chunked local training."""
    path, data = h5_cohort
    res = _run_algo("dpsgd", data, streaming=False, tmp_path=tmp_path,
                    tag="dgres")
    lazy, stream = _open_stream(path)
    try:
        st = _run_algo("dpsgd", stream, streaming=True, tmp_path=tmp_path,
                       tag="dgst", stream_chunk_clients=2)
    finally:
        stream.close()
        lazy["file"].close()
    for r_res, r_st in zip(res["history"], st["history"]):
        np.testing.assert_allclose(r_st["train_loss"], r_res["train_loss"],
                                   rtol=1e-6)
        assert r_res["personal_acc"] == r_st["personal_acc"]
        assert r_res["global_acc"] == r_st["global_acc"]
    _assert_final_metrics(res["final_global"], st["final_global"])


@pytest.mark.slow  # tier-1 870s window (PR 11, the PR 2/7 precedent): per-engine streamed==resident e2e twins ride the full suite; the fedavg/salientgrads/local siblings + the streamed machinery tests keep tier-1 coverage
def test_streaming_turboaggregate_identical_to_resident(h5_cohort,
                                                        tmp_path):
    """TurboAggregate inherits FedAvg's streamed loop; the MPC stage is
    host-side and rng-independent either way — bitwise equal."""
    path, data = h5_cohort
    res = _run_algo("turboaggregate", data, streaming=False,
                    tmp_path=tmp_path, tag="tares")
    lazy, stream = _open_stream(path)
    try:
        st = _run_algo("turboaggregate", stream, streaming=True,
                       tmp_path=tmp_path, tag="tast")
    finally:
        stream.close()
        lazy["file"].close()
    for r_res, r_st in zip(res["history"], st["history"]):
        assert r_res["train_loss"] == r_st["train_loss"], (r_res, r_st)
        assert r_res["acc"] == r_st["acc"]
    assert res["final_global"] == st["final_global"]


@pytest.mark.slow  # tier-1 870s window (PR 11, the PR 2/7 precedent): per-engine streamed==resident e2e twins ride the full suite; the fedavg/salientgrads/local siblings + the streamed machinery tests keep tier-1 coverage
def test_streaming_fedfomo_identical_to_resident(h5_cohort, tmp_path):
    """FedFomo — the last engine onto the streaming list (VERDICT r3
    next-step #5): train shards chunk through stream_map_train_chunks
    (chunk=2 < 4 exercises real chunking), the val_fraction-small val
    shards are fetched resident once, and the pair-list evaluation gathers
    from resident per-client models. Streamed == resident."""
    from neuroimagedisttraining_tpu.data.federate import carve_val_split

    path, data = h5_cohort
    res = _run_algo("fedfomo", data, streaming=False, tmp_path=tmp_path,
                    tag="ffres", val_fraction=0.25)
    lazy = load_abcd_hdf5(path, lazy=True)
    train_map, test_map, _ = P.site_partition(lazy["site"], seed=42)
    # same carve the resident federate_cohort(val_fraction=0.25) applies
    val_map, train_map = carve_val_split(train_map, 0.25, seed=42)
    stream = StreamingFederation(lazy["X"], lazy["y"], train_map, test_map,
                                 val_map=val_map)
    try:
        st = _run_algo("fedfomo", stream, streaming=True, tmp_path=tmp_path,
                       tag="ffst", val_fraction=0.25,
                       stream_chunk_clients=2)
    finally:
        stream.close()
        lazy["file"].close()
    for r_res, r_st in zip(res["history"], st["history"]):
        # chunked scalar loss reduce may reassociate (same slack as the
        # dispfl/local streamed tests); state comparisons are exact
        np.testing.assert_allclose(r_st["train_loss"], r_res["train_loss"],
                                   rtol=1e-6)
        assert r_res["personal_acc"] == r_st["personal_acc"]
    _assert_final_metrics(res["final_personal"], st["final_personal"])
    # fomo weights divide ulp-scale val-loss gaps by small parameter
    # distances, so the resident-vs-streamed codegen difference donation
    # introduces (see _assert_final_metrics) is AMPLIFIED here — the
    # matrices agree to ~1e-5 relative, not bitwise
    np.testing.assert_allclose(np.asarray(res["weights"]),
                               np.asarray(st["weights"]),
                               rtol=5e-5, atol=5e-5)
    np.testing.assert_allclose(np.asarray(res["p_choose"]),
                               np.asarray(st["p_choose"]),
                               rtol=5e-5, atol=5e-5)


def test_streaming_fedfomo_requires_val_map(h5_cohort, tmp_path):
    """A StreamingFederation built without a val split must be refused
    with a clear error (FedFomo's pair evals need val shards)."""
    path, data = h5_cohort
    lazy = load_abcd_hdf5(path, lazy=True)
    train_map, test_map, _ = P.site_partition(lazy["site"], seed=42)
    stream = StreamingFederation(lazy["X"], lazy["y"], train_map, test_map)
    try:
        with pytest.raises(ValueError, match="requires a val split"):
            _run_algo("fedfomo", stream, streaming=True,
                      tmp_path=tmp_path, tag="rej")
    finally:
        stream.close()
        lazy["file"].close()


def test_stream_transfer_stats_and_two_level_put(h5_cohort):
    """The reader thread does fetch AND device_put (VERDICT r3 weak #2):
    transfer_stats accumulates both stages, prefetched get_train returns
    already-transferred arrays, and with a two-level (silos, clients) mesh
    the round buffer shards over BOTH axes silo-major (VERDICT r3
    next-step #10)."""
    from neuroimagedisttraining_tpu.parallel.hierarchical import (
        make_two_level_mesh,
    )

    path, data = h5_cohort
    lazy = load_abcd_hdf5(path, lazy=True)
    train_map, test_map, _ = P.site_partition(lazy["site"], seed=42)
    mesh = make_two_level_mesh(2, 2)  # 4 clients over 2 silos x 2 cores
    stream = StreamingFederation(lazy["X"], lazy["y"], train_map, test_map,
                                 mesh=mesh)
    try:
        stream.prefetch_train(np.arange(4))
        Xs, ys, ns = stream.get_train(np.arange(4))
        assert stream.transfer_stats["fetches"] == 1
        assert stream.transfer_stats["host_gather_ms"] > 0
        assert stream.transfer_stats["device_put_ms"] > 0
        assert stream.transfer_stats["bytes"] == (
            np.asarray(Xs).nbytes + np.asarray(ys).nbytes
            + np.asarray(ns).nbytes)
        # obs gauge parity (ISSUE 10 satellite): every registry series
        # equals the legacy dict entry, no double counting
        from neuroimagedisttraining_tpu.obs import metrics as obs_metrics

        snap = obs_metrics.snapshot()["nidt_stream_transfer"]["values"]
        got = {v["labels"]["key"]: v["value"] for v in snap}
        for k, v in stream.transfer_stats.items():
            assert got[k] == float(v), (k, got[k], v)
        # sharded over all 4 mesh devices, one client per device,
        # silo-major placement = mesh device order
        assert len(Xs.sharding.device_set) == 4
        assert not Xs.sharding.is_fully_replicated
        assert {s.data.shape[0] for s in Xs.addressable_shards} == {1}
        mesh_order = [d.id for d in mesh.devices.reshape(-1)]
        shard_dev = sorted((s.index[0].start, s.device.id)
                           for s in Xs.addressable_shards)
        assert [d for _, d in shard_dev] == mesh_order
        # the silo-first two-level reduction accepts this layout directly
        from neuroimagedisttraining_tpu.parallel.hierarchical import (
            silo_then_global_mean,
        )
        from neuroimagedisttraining_tpu.utils.pytree import (
            tree_weighted_mean,
        )

        w = ns.astype(np.float32)
        got = silo_then_global_mean({"x": Xs.astype(np.float32)}, w, mesh)
        want = tree_weighted_mean({"x": Xs.astype(np.float32)}, w)
        np.testing.assert_allclose(np.asarray(got["x"]),
                                   np.asarray(want["x"]), rtol=1e-6)
    finally:
        stream.close()
        lazy["file"].close()


def test_streaming_checkpoint_resume(h5_cohort, tmp_path):
    """Checkpoint/resume also works in streaming mode: kill back to the
    round-0 checkpoint, resume, final metrics equal the uninterrupted run."""
    import os

    from neuroimagedisttraining_tpu.utils import checkpoint as ckpt

    path, data = h5_cohort
    ck = str(tmp_path / "ck")

    def run():
        lazy = load_abcd_hdf5(path, lazy=True)
        train_map, test_map, _ = P.site_partition(lazy["site"], seed=42)
        stream = StreamingFederation(lazy["X"], lazy["y"], train_map,
                                     test_map)
        cfg = ExperimentConfig(
            model="3dcnn_tiny", num_classes=1, algorithm="fedavg",
            data=DataConfig(dataset="synthetic", partition_method="site"),
            optim=OptimConfig(lr=1e-2, batch_size=4, epochs=1),
            fed=FedConfig(client_num_in_total=4, comm_round=2,
                          frequency_of_the_test=1),
            checkpoint_dir=ck, checkpoint_every=1,
            log_dir=str(tmp_path), tag="stck")
        trainer = LocalTrainer(create_model(cfg.model, num_classes=1),
                               cfg.optim, num_classes=1)
        log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                               console=False)
        engine = create_engine("fedavg", cfg, None, trainer, mesh=None,
                               logger=log, stream=stream)
        try:
            return engine.train()
        finally:
            stream.close()
            lazy["file"].close()

    full = run()
    assert ckpt.list_checkpoints(ck) == [0, 1]
    os.unlink(os.path.join(ck, "ckpt_00000001.msgpack"))  # kill after r0
    resumed = run()
    assert resumed["final_global"] == full["final_global"]
    assert len(resumed["history"]) == 2


def test_streaming_sharded_over_client_mesh(h5_cohort, tmp_path):
    """Sharded streaming: the round's host-fetched buffers are device_put
    SHARDED over a 1-D client mesh (the full-scale deployment path:
    host-stream a > HBM cohort INTO a multi-chip federation). Metrics
    match the unsharded streamed run; cross-device reduction may
    reassociate, so the comparison is allclose not bitwise."""
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh

    path, data = h5_cohort
    lazy, stream_plain = _open_stream(path)
    try:
        st = _run_algo("fedavg", stream_plain, streaming=True,
                       tmp_path=tmp_path, tag="shpl")
    finally:
        stream_plain.close()
        lazy["file"].close()

    mesh = make_mesh(shape=(2,))  # frac 0.5 of 4 clients = 2 sampled: tiles
    lazy2 = load_abcd_hdf5(path, lazy=True)
    train_map, test_map, _ = P.site_partition(lazy2["site"], seed=42)
    stream_sh = StreamingFederation(lazy2["X"], lazy2["y"], train_map,
                                    test_map, mesh=mesh)
    try:
        # the feed really shards: one round's buffer spans both devices
        Xs, _, _ = stream_sh.get_train(np.array([0, 1]))
        assert len(Xs.sharding.device_set) == 2
        st_sh = _run_algo("fedavg", stream_sh, streaming=True,
                          tmp_path=tmp_path, tag="shme", mesh=mesh)
    finally:
        stream_sh.close()
        lazy2["file"].close()

    for r_a, r_b in zip(st["history"], st_sh["history"]):
        np.testing.assert_allclose(r_b["train_loss"], r_a["train_loss"],
                                   rtol=2e-5)
        np.testing.assert_allclose(r_b["acc"], r_a["acc"], atol=1e-6)
    np.testing.assert_allclose(st_sh["final_global"]["loss"],
                               st["final_global"]["loss"], rtol=2e-5)


def test_streaming_salientgrads_checkpoint_resume(h5_cohort, tmp_path):
    """Flagship streaming + checkpoint/resume: kill back to the round-0
    checkpoint, resume (phase-1 masks restored, NOT recomputed), final
    metrics equal the uninterrupted run."""
    import os

    from neuroimagedisttraining_tpu.utils import checkpoint as ckpt

    path, data = h5_cohort
    ck = str(tmp_path / "sgck")

    def run():
        lazy, stream = _open_stream(path)
        try:
            return _run_algo("salientgrads", stream, streaming=True,
                             tmp_path=tmp_path, tag="sgck",
                             checkpoint_dir=ck, checkpoint_every=1)
        finally:
            stream.close()
            lazy["file"].close()

    full = run()
    assert ckpt.list_checkpoints(ck) == [0, 1, 2]
    os.unlink(os.path.join(ck, "ckpt_00000002.msgpack"))
    os.unlink(os.path.join(ck, "ckpt_00000001.msgpack"))  # kill after r0
    resumed = run()
    assert resumed["final_global"] == full["final_global"]
    assert resumed["final_personal"] == full["final_personal"]
    assert resumed["mask_density"] == full["mask_density"]


def test_stream_window_feed_matches_per_round(h5_cohort):
    """The window-granular feed (ISSUE 10): ``get_window``'s [K, S, ...]
    stacks equal the per-round ``get_train`` buffers round for round,
    a matching ``prefetch_window`` is served (fetches accounted one per
    round), and a mismatched prefetch is fetched fresh, never stale."""
    path, data = h5_cohort
    lazy = load_abcd_hdf5(path, lazy=True)
    train_map, test_map, _ = P.site_partition(lazy["site"], seed=42)
    stream = StreamingFederation(lazy["X"], lazy["y"], train_map, test_map)
    try:
        ids = [np.array([0, 2]), np.array([1, 3]), np.array([0, 1])]
        stream.prefetch_window(ids)
        f0 = stream.transfer_stats["fetches"]
        Xw, yw, nw = stream.get_window(ids)
        assert stream.transfer_stats["fetches"] - f0 == len(ids)
        assert Xw.shape[0] == len(ids)
        for k, round_ids in enumerate(ids):
            Xr, yr, nr = stream.get_train(round_ids)
            np.testing.assert_array_equal(np.asarray(Xw)[k], np.asarray(Xr))
            np.testing.assert_array_equal(np.asarray(yw)[k], np.asarray(yr))
            np.testing.assert_array_equal(np.asarray(nw)[k], np.asarray(nr))
        # mismatched window prefetch is ignored, not served stale
        stream.prefetch_window([np.array([0, 1])])
        X1, _, n1 = stream.get_window([np.array([2, 3])])
        assert int(np.asarray(n1)[0, 0]) == len(train_map[2])
    finally:
        stream.close()
        lazy["file"].close()


def test_streaming_double_buffer_prefetch(h5_cohort):
    path, data = h5_cohort
    lazy = load_abcd_hdf5(path, lazy=True)
    train_map, test_map, _ = P.site_partition(lazy["site"], seed=42)
    stream = StreamingFederation(lazy["X"], lazy["y"], train_map, test_map)
    try:
        stream.prefetch_train(np.array([0, 2]))
        X1, y1, n1 = stream.get_train(np.array([0, 2]))     # hits prefetch
        X2, y2, n2 = stream.get_train(np.array([0, 2]))     # cold read
        np.testing.assert_array_equal(np.asarray(X1), np.asarray(X2))
        np.testing.assert_array_equal(np.asarray(n1), np.asarray(n2))
        # mismatched prefetch is ignored, not served stale
        stream.prefetch_train(np.array([1]))
        X3, _, n3 = stream.get_train(np.array([3]))
        assert int(np.asarray(n3)[0]) == len(train_map[3])
    finally:
        stream.close()
        lazy["file"].close()
