"""HDF5 ingestion + host-streaming data path.

The streaming feed must be an exact drop-in: a streamed FedAvg run sees
bitwise-identical inputs to the device-resident run, so its metrics are
identical (VERDICT r1 missing #2 acceptance)."""

import numpy as np
import pytest

from neuroimagedisttraining_tpu.config import (
    DataConfig, ExperimentConfig, FedConfig, OptimConfig,
)
from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
from neuroimagedisttraining_tpu.data import partition as P
from neuroimagedisttraining_tpu.data.federate import federate_cohort
from neuroimagedisttraining_tpu.data.hdf5 import fetch_rows, load_abcd_hdf5
from neuroimagedisttraining_tpu.data.stream import StreamingFederation
from neuroimagedisttraining_tpu.data.synthetic import write_synthetic_hdf5
from neuroimagedisttraining_tpu.engines import create_engine
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger


@pytest.fixture(scope="module")
def h5_cohort(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("h5") / "cohort.h5")
    data = write_synthetic_hdf5(path, num_subjects=48, shape=(12, 14, 12),
                                num_sites=4, seed=0)
    return path, data


def test_load_abcd_hdf5_lazy_and_eager(h5_cohort):
    path, data = h5_cohort
    lazy = load_abcd_hdf5(path, lazy=True)
    assert lazy["file"] is not None
    np.testing.assert_array_equal(lazy["y"], data["y"])
    np.testing.assert_array_equal(lazy["site"], data["site"])
    # X is a lazy handle, row-sliceable
    np.testing.assert_array_equal(np.asarray(lazy["X"][3]), data["X"][3])
    lazy["file"].close()
    eager = load_abcd_hdf5(path, lazy=False)
    assert isinstance(eager["X"], np.ndarray)
    np.testing.assert_array_equal(eager["X"], data["X"])


def test_load_abcd_hdf5_missing_key(tmp_path):
    import h5py

    path = str(tmp_path / "bad.h5")
    with h5py.File(path, "w") as f:
        f.create_dataset("X", data=np.zeros((2, 3, 3, 3), np.uint8))
        f.create_dataset("y", data=np.zeros(2, np.int8))
    with pytest.raises(KeyError, match="site"):
        load_abcd_hdf5(path)


def test_fetch_rows_unsorted_and_duplicate_indices(h5_cohort):
    path, data = h5_cohort
    lazy = load_abcd_hdf5(path, lazy=True)
    idx = np.array([7, 2, 2, 41, 0, 7])
    got = fetch_rows(lazy["X"], idx)
    np.testing.assert_array_equal(got, data["X"][idx])
    lazy["file"].close()


def _run_fedavg(cohort_or_stream, streaming: bool, tmp_path, tag):
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm="fedavg",
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=OptimConfig(lr=1e-2, batch_size=4, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=3, frac=0.5,
                      frequency_of_the_test=1),
        log_dir=str(tmp_path), tag=tag)
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1), cfg.optim,
                           num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    if streaming:
        engine = create_engine("fedavg", cfg, None, trainer, mesh=None,
                               logger=log, stream=cohort_or_stream)
    else:
        fed, _ = federate_cohort(cohort_or_stream, partition_method="site",
                                 mesh=None)
        engine = create_engine("fedavg", cfg, fed, trainer, mesh=None,
                               logger=log)
    return engine.train()


def test_streaming_fedavg_identical_to_resident(h5_cohort, tmp_path):
    path, data = h5_cohort
    # device-resident run straight from the in-memory cohort
    res = _run_fedavg(data, streaming=False, tmp_path=tmp_path, tag="res")
    # streaming run from the HDF5 file with the same partition maps
    lazy = load_abcd_hdf5(path, lazy=True)
    train_map, test_map, _ = P.site_partition(lazy["site"], seed=42)
    stream = StreamingFederation(lazy["X"], lazy["y"], train_map, test_map)
    try:
        st = _run_fedavg(stream, streaming=True, tmp_path=tmp_path,
                         tag="st")
    finally:
        stream.close()
        lazy["file"].close()

    # identical inputs -> identical round losses and metrics
    for r_res, r_st in zip(res["history"], st["history"]):
        assert r_res["train_loss"] == r_st["train_loss"], (r_res, r_st)
        assert r_res["acc"] == r_st["acc"]
        assert r_res["auc"] == r_st["auc"]
    assert res["final_global"] == st["final_global"]
    assert res["final_personal"]["acc"] == st["final_personal"]["acc"]


def test_streaming_checkpoint_resume(h5_cohort, tmp_path):
    """Checkpoint/resume also works in streaming mode: kill back to the
    round-0 checkpoint, resume, final metrics equal the uninterrupted run."""
    import os

    from neuroimagedisttraining_tpu.utils import checkpoint as ckpt

    path, data = h5_cohort
    ck = str(tmp_path / "ck")

    def run():
        lazy = load_abcd_hdf5(path, lazy=True)
        train_map, test_map, _ = P.site_partition(lazy["site"], seed=42)
        stream = StreamingFederation(lazy["X"], lazy["y"], train_map,
                                     test_map)
        cfg = ExperimentConfig(
            model="3dcnn_tiny", num_classes=1, algorithm="fedavg",
            data=DataConfig(dataset="synthetic", partition_method="site"),
            optim=OptimConfig(lr=1e-2, batch_size=4, epochs=1),
            fed=FedConfig(client_num_in_total=4, comm_round=2,
                          frequency_of_the_test=1),
            checkpoint_dir=ck, checkpoint_every=1,
            log_dir=str(tmp_path), tag="stck")
        trainer = LocalTrainer(create_model(cfg.model, num_classes=1),
                               cfg.optim, num_classes=1)
        log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                               console=False)
        engine = create_engine("fedavg", cfg, None, trainer, mesh=None,
                               logger=log, stream=stream)
        try:
            return engine.train()
        finally:
            stream.close()
            lazy["file"].close()

    full = run()
    assert ckpt.list_checkpoints(ck) == [0, 1]
    os.unlink(os.path.join(ck, "ckpt_00000001.msgpack"))  # kill after r0
    resumed = run()
    assert resumed["final_global"] == full["final_global"]
    assert len(resumed["history"]) == 2


def test_streaming_double_buffer_prefetch(h5_cohort):
    path, data = h5_cohort
    lazy = load_abcd_hdf5(path, lazy=True)
    train_map, test_map, _ = P.site_partition(lazy["site"], seed=42)
    stream = StreamingFederation(lazy["X"], lazy["y"], train_map, test_map)
    try:
        stream.prefetch_train(np.array([0, 2]))
        X1, y1, n1 = stream.get_train(np.array([0, 2]))     # hits prefetch
        X2, y2, n2 = stream.get_train(np.array([0, 2]))     # cold read
        np.testing.assert_array_equal(np.asarray(X1), np.asarray(X2))
        np.testing.assert_array_equal(np.asarray(n1), np.asarray(n2))
        # mismatched prefetch is ignored, not served stale
        stream.prefetch_train(np.array([1]))
        X3, _, n3 = stream.get_train(np.array([3]))
        assert int(np.asarray(n3)[0]) == len(train_map[3])
    finally:
        stream.close()
        lazy["file"].close()
