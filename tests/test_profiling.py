"""Profiler hooks + failure context (SURVEY §5.1/§5.3 aux subsystems)."""

import logging
import os

import jax.numpy as jnp
import pytest

from neuroimagedisttraining_tpu.utils.profiling import (
    annotate, failure_context, profile_trace,
)


@pytest.mark.slow  # tier-1 window (PR 7): heavy twin/artifact test, core pin covered by a lighter tier-1 sibling
def test_profile_trace_writes_artifacts(tmp_path):
    d = str(tmp_path / "trace")
    with profile_trace(d):
        with annotate("toy-span"):
            x = jnp.arange(128.0)
            (x * 2).block_until_ready()
    found = [f for _, _, fs in os.walk(d) for f in fs]
    assert found, "profiler produced no trace files"


def test_profile_trace_noop_when_disabled(tmp_path):
    with profile_trace("", enabled=False):
        pass  # must not raise or create anything


def test_failure_context_logs_and_tears_down(caplog):
    torn = []
    with pytest.raises(RuntimeError):
        with caplog.at_level(logging.ERROR):
            with failure_context(teardown=lambda: torn.append(1),
                                 name="boom-test"):
                raise RuntimeError("boom")
    assert torn == [1]
    assert any("boom-test" in r.message for r in caplog.records)
