"""Sharding assertions (VERDICT round-1 weak #10): prove the round program
actually partitions client state over the 8-device mesh — data sharded one
client-block per device, cross-client aggregation lowered to a collective —
rather than silently replicating everything 8x."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.test_fedavg import _make_engine


def test_federation_data_is_client_sharded(tmp_path, synthetic_cohort):
    engine = _make_engine(tmp_path, synthetic_cohort)
    X = engine.data.X_train
    sharding = X.sharding
    # spans all 8 mesh devices, and is NOT fully replicated
    assert len(sharding.device_set) == 8
    assert not sharding.is_fully_replicated
    # 4 real sites padded to 8 mesh clients -> exactly 1 client per device
    shard_rows = {s.data.shape[0] for s in X.addressable_shards}
    assert shard_rows == {1}
    # every federation leaf shares the client-axis layout
    for name in ("y_train", "n_train", "X_test", "y_test", "n_test"):
        leaf = getattr(engine.data, name)
        assert len(leaf.sharding.device_set) == 8, name
        assert not leaf.sharding.is_fully_replicated, name


def test_round_program_contains_cross_client_collective(tmp_path,
                                                        synthetic_cohort):
    """The compiled FedAvg round must aggregate via a collective / sharded
    reduction, and its output params must come back replicated (the global
    model) — not 8 divergent copies."""
    engine = _make_engine(tmp_path, synthetic_cohort)
    gs = engine.init_global_state()
    sampled = jnp.asarray(engine.client_sampling(0))
    rngs = engine.per_client_rngs(0, np.asarray(engine.client_sampling(0)))
    compiled = engine._round_jit.lower(
        gs.params, gs.batch_stats, engine.data, sampled, rngs,
        jnp.float32(0.01)).compile()
    txt = compiled.as_text()
    assert ("all-reduce" in txt) or ("all-gather" in txt) or \
        ("reduce-scatter" in txt), "no cross-device collective in the round"

    params, bstats, loss, _ = engine._round_jit(
        gs.params, gs.batch_stats, engine.data, sampled, rngs,
        jnp.float32(0.01))
    jax.block_until_ready(params)
    leaf = jax.tree.leaves(params)[0]
    # aggregated global params are replicated across the mesh
    assert leaf.sharding.is_fully_replicated


def test_trainer_default_batch_order_is_epoch_shuffle(tmp_path,
                                                      synthetic_cohort):
    """The round-3 with-replacement deviation is gone: the default batch
    order walks a per-epoch permutation covering every valid sample exactly
    once (reference DataLoader semantics); the old i.i.d. draw survives
    only behind batch_order='replacement'."""
    from neuroimagedisttraining_tpu.config import OptimConfig
    from neuroimagedisttraining_tpu.core.trainer import (
        epoch_permutations, shuffle_batch_indices,
    )

    assert OptimConfig().batch_order == "shuffle"
    engine = _make_engine(tmp_path, synthetic_cohort)
    assert engine.trainer.optim_cfg.batch_order == "shuffle"

    n, b, max_samples, epochs = 21, 8, 32, 2
    perms = epoch_permutations(jax.random.key(3), epochs, max_samples, n)
    steps_per_epoch = -(-max_samples // b)
    for e in range(epochs):
        seen: list[int] = []
        for s in range(steps_per_epoch):
            t = e * steps_per_epoch + s
            idx, w = shuffle_batch_indices(perms, t, steps_per_epoch, b, n)
            seen.extend(np.asarray(idx)[np.asarray(w) > 0].tolist())
        # exactly-once coverage of the n valid rows per epoch
        assert sorted(seen) == list(range(n))


def test_two_level_aggregation_matches_flat_and_bounds_byzantine_silo():
    """parallel/hierarchical.py: silo-local (ICI) then cross-silo (DCN)
    weighted mean == the flat client mean; with norm_bound, a Byzantine
    SILO's pull on the global params is bounded as a unit."""
    from neuroimagedisttraining_tpu.parallel.hierarchical import (
        make_two_level_mesh, silo_then_global_mean,
    )
    from neuroimagedisttraining_tpu.utils.pytree import tree_weighted_mean

    mesh = make_two_level_mesh(2, 4)  # 2 silos x 4 cores on the 8-dev mesh
    C = 16
    rng = np.random.default_rng(0)
    stacked = {"w": jnp.asarray(rng.normal(size=(C, 6, 5)), jnp.float32),
               "b": jnp.asarray(rng.normal(size=(C, 5)), jnp.float32)}
    weights = jnp.asarray(rng.uniform(1, 3, size=C), jnp.float32)

    got = silo_then_global_mean(stacked, weights, mesh)
    want = tree_weighted_mean(stacked, weights)
    for k in stacked:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=1e-5)

    # Byzantine silo: clients 8..15 (the whole second silo) send 100x
    # params; silo-granular clipping bounds the silo aggregate
    glob = {"w": jnp.zeros((6, 5)), "b": jnp.zeros((5,))}
    poisoned = {k: v.at[8:].set(100.0) for k, v in stacked.items()}
    clipped = silo_then_global_mean(poisoned, weights, mesh,
                                    global_params=glob, norm_bound=1.0)
    unclipped = silo_then_global_mean(poisoned, weights, mesh)
    # each silo mean is pulled to within norm_bound of glob -> global mean
    # norm <= 1.0; without clipping the poisoned silo dominates
    norm_c = float(jnp.sqrt(sum(jnp.sum(v ** 2) for v in clipped.values())))
    norm_u = float(jnp.sqrt(sum(jnp.sum(v ** 2)
                                for v in unclipped.values())))
    assert norm_c <= 1.0 + 1e-5
    assert norm_u > 50.0


def test_mesh_shape_two_level_cli_layout():
    """--mesh_shape 2 4 semantics: make_mesh builds the (silos, clients)
    mesh and client_sharding splits the leading axis over BOTH axes."""
    from neuroimagedisttraining_tpu.parallel.mesh import (
        client_sharding, make_mesh,
    )

    mesh = make_mesh(shape=(2, 4))
    assert mesh.axis_names == ("silos", "clients")
    assert mesh.devices.shape == (2, 4)
    x = jnp.arange(16 * 3, dtype=jnp.float32).reshape(16, 3)
    xs = jax.device_put(x, client_sharding(mesh))
    # 16 clients over 8 devices -> 2 clients per device shard
    assert xs.sharding.shard_shape(x.shape) == (2, 3)


def test_fedavg_round_identical_on_flat_and_two_level_mesh(tmp_path):
    """--mesh_shape routing: the fedavg round program on a (2,4) silo mesh
    produces the same aggregate as on the flat 8-device clients mesh."""
    from neuroimagedisttraining_tpu.data.synthetic import generate_synthetic_abcd

    cohort = generate_synthetic_abcd(num_subjects=32, shape=(12, 14, 12),
                                     num_sites=8, seed=0)
    outs = []
    for shape in ((), (2, 4)):
        eng = _make_engine(tmp_path, cohort, mesh_shape=shape,
                           client_num_in_total=8)
        gs = eng.init_global_state()
        sampled = eng.client_sampling(0)
        p, b, loss, _ = eng._round_jit(gs.params, gs.batch_stats, eng.data,
                                       jnp.asarray(sampled),
                                       eng.per_client_rngs(0, sampled),
                                       eng.round_lr(0))
        outs.append((p, float(loss)))
    (p_flat, l_flat), (p_two, l_two) = outs
    np.testing.assert_allclose(l_flat, l_two, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(p_flat), jax.tree.leaves(p_two)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


@pytest.mark.slow  # tier-1 window (PR 7): heavy twin/artifact test, core pin covered by a lighter tier-1 sibling
def test_salientgrads_round_identical_on_flat_and_two_level_mesh(tmp_path):
    """VERDICT r4 #1: the FLAGSHIP's aggregation now routes through the
    silo-aware path — a masked SalientGrads round on the (2,4) silo mesh
    must equal the flat 8-device round bitwise (same mask, same aggregate),
    and must NOT have taken the flat fallback on the two-level mesh."""
    from neuroimagedisttraining_tpu.data.synthetic import generate_synthetic_abcd

    # 64 subjects so every one of the 8 sites draws train data: the
    # 8-client sampled set then tiles the 8-device grid, which the
    # silo-first routing requires (a smaller cohort can leave a site
    # empty -> 7 sampled clients -> legitimate flat fallback)
    cohort = generate_synthetic_abcd(num_subjects=64, shape=(12, 14, 12),
                                     num_sites=8, seed=0)
    outs = []
    for shape in ((), (2, 4)):
        eng = _make_engine(tmp_path, cohort, algorithm="salientgrads",
                           mesh_shape=shape, client_num_in_total=8)
        assert eng.real_clients == 8  # every site has train data
        gs = eng.init_global_state()
        masks, _ = eng.generate_global_mask(gs.params, gs.batch_stats)
        per = eng.broadcast_states(gs, eng.num_clients)
        sampled = eng.client_sampling(0)
        out = eng._round_jit(gs.params, gs.batch_stats, per.params,
                             per.batch_stats, eng.data, masks,
                             jnp.asarray(sampled),
                             eng.per_client_rngs(0, sampled),
                             eng.round_lr(0))
        if shape:  # the silo-first path must actually have been routed
            assert not getattr(eng, "_warned_flat_fallback", False)
        outs.append((masks, out[0], float(out[4])))  # out[4] = mean loss
    (m_flat, p_flat, l_flat), (m_two, p_two, l_two) = outs
    for a, b in zip(jax.tree.leaves(m_flat), jax.tree.leaves(m_two)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_allclose(l_flat, l_two, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(p_flat), jax.tree.leaves(p_two)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


@pytest.mark.slow
def test_ditto_round_identical_on_flat_and_two_level_mesh(tmp_path):
    """Ditto's global track likewise routes silo-aware (VERDICT r4 #1)."""
    from neuroimagedisttraining_tpu.data.synthetic import generate_synthetic_abcd

    cohort = generate_synthetic_abcd(num_subjects=64, shape=(12, 14, 12),
                                     num_sites=8, seed=0)
    outs = []
    for shape in ((), (2, 4)):
        eng = _make_engine(tmp_path, cohort, algorithm="ditto",
                           mesh_shape=shape, client_num_in_total=8)
        gs = eng.init_global_state()
        per = eng.broadcast_states(gs, eng.num_clients)
        sampled = eng.client_sampling(0)
        out = eng._round_jit(gs.params, gs.batch_stats, per.params,
                             per.batch_stats, eng.data,
                             jnp.asarray(sampled),
                             eng.per_client_rngs(0, sampled),
                             eng.round_lr(0))
        if shape:
            assert not getattr(eng, "_warned_flat_fallback", False)
        outs.append((out[0], float(out[-1])))
    (p_flat, l_flat), (p_two, l_two) = outs
    np.testing.assert_allclose(l_flat, l_two, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(p_flat), jax.tree.leaves(p_two)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_make_mesh_usage_errors():
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh

    with pytest.raises(ValueError, match="1 or 2 positive"):
        make_mesh(shape=(2, 2, 2))
    with pytest.raises(ValueError, match="1 or 2 positive"):
        make_mesh(shape=(0,))
    with pytest.raises(ValueError, match="needs 16 devices"):
        make_mesh(shape=(4, 4), devices=jax.devices()[:8])
