"""Vision data layer: CIFAR-style partition modes (n_cls/dir/my_part),
label-proportional test splits, npz ingestion, and an end-to-end 2D CNN
federation (cifar10/data_loader.py:75-249 parity)."""

import numpy as np
import pytest

from neuroimagedisttraining_tpu.data import partition as P
from neuroimagedisttraining_tpu.data import vision as V


def _labels(n=1000, n_cls=10, seed=0):
    return np.random.default_rng(seed).integers(0, n_cls, n).astype(np.int32)


def test_n_cls_partition_limits_classes_per_client():
    y = _labels()
    m = V.vision_partition(y, client_number=8, alpha=2, method="n_cls",
                           seed=3)
    sizes = [len(m[c]) for c in range(8)]
    assert sum(sizes) == len(y)
    # every client holds samples from (at most) alpha distinct classes
    for c in range(8):
        assert len(np.unique(y[m[c]])) <= 2


def test_dir_partition_covers_everything_once():
    y = _labels()
    m = V.vision_partition(y, client_number=5, alpha=0.3, method="dir",
                           seed=1)
    allidx = np.sort(np.concatenate([m[c] for c in range(5)]))
    # dir mode never refills class pools: exact cover, no duplicates
    np.testing.assert_array_equal(allidx, np.arange(len(y)))
    # heterogeneity: per-client class distributions differ
    stats = P.record_data_stats(y, m)
    h0 = np.asarray([stats[0].get(k, 0) for k in range(10)], float)
    h1 = np.asarray([stats[1].get(k, 0) for k in range(10)], float)
    assert not np.allclose(h0 / h0.sum(), h1 / h1.sum(), atol=0.02)


def test_my_part_groups_share_priors():
    y = _labels(2000)
    m = V.vision_partition(y, client_number=8, alpha=4, method="my_part",
                           seed=2)
    assert sum(len(m[c]) for c in range(8)) == len(y)
    stats = P.record_data_stats(y, m)
    # clients 0,1 share a shard-group prior; 0 and 7 don't. Compare class
    # histograms: same-group pairs should be closer than cross-group.
    def hist(c):
        h = np.asarray([stats[c].get(k, 0) for k in range(10)], float)
        return h / max(h.sum(), 1)

    same = np.abs(hist(0) - hist(1)).sum()
    cross = np.abs(hist(0) - hist(7)).sum()
    assert same < cross + 0.5  # statistical, loose


def test_proportional_test_split_matches_train_mix():
    y_tr = _labels(4000, seed=5)
    y_te = _labels(1000, seed=6)
    m = V.vision_partition(y_tr, client_number=4, alpha=2, method="n_cls",
                           seed=7)
    stats = P.record_data_stats(y_tr, m)
    tmap = V.proportional_test_split(y_te, stats, 4, seed=8)
    for c in range(4):
        train_classes = set(stats[c])
        test_classes = set(np.unique(y_te[tmap[c]]).tolist())
        # client's test classes only come from its train classes
        assert test_classes <= train_classes


def test_npz_ingestion_roundtrip(tmp_path):
    Xtr, ytr, Xte, yte = V.synthetic_vision_cohort(64, 16, hw=8)
    path = str(tmp_path / "toy.npz")
    np.savez(path, X_train=Xtr, y_train=ytr, X_test=Xte, y_test=yte)
    gXtr, gytr, gXte, gyte = V.load_vision_dataset("tiny", path)
    np.testing.assert_allclose(gXtr, Xtr)
    np.testing.assert_array_equal(gyte, yte)


def test_uint8_pickle_batches_normalized(tmp_path):
    # fabricate a cifar-10-batches-py folder and check normalization
    import pickle

    folder = tmp_path / "cifar-10-batches-py"
    folder.mkdir()
    rng = np.random.default_rng(0)
    for name, n in [("data_batch_1", 20), ("test_batch", 10)]:
        d = {b"data": rng.integers(0, 256, size=(n, 3072), dtype=np.uint8),
             b"labels": rng.integers(0, 10, size=n).tolist()}
        with open(folder / name, "wb") as f:
            pickle.dump(d, f)
    for i in range(2, 6):
        with open(folder / f"data_batch_{i}", "wb") as f:
            pickle.dump({b"data": rng.integers(0, 256, size=(4, 3072),
                                               dtype=np.uint8),
                         b"labels": rng.integers(0, 10, size=4).tolist()}, f)
    Xtr, ytr, Xte, yte = V.load_vision_dataset("cifar10", str(tmp_path))
    assert Xtr.shape[1:] == (32, 32, 3)
    assert Xtr.dtype == np.float32
    assert abs(float(Xtr.mean())) < 0.3  # roughly centered after normalize


def test_tiny_imagenet_folder_reader(tmp_path):
    """Fabricate the canonical tiny-imagenet-200 layout and read it."""
    PIL = pytest.importorskip("PIL")
    from PIL import Image

    root = tmp_path / "tiny-imagenet-200"
    rng = np.random.default_rng(0)
    wnids = ["n01443537", "n01629819"]
    (root / "train").mkdir(parents=True)
    for w in wnids:
        d = root / "train" / w / "images"
        d.mkdir(parents=True)
        for i in range(3):
            arr = rng.integers(0, 256, size=(64, 64, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{w}_{i}.JPEG")
    vd = root / "val" / "images"
    vd.mkdir(parents=True)
    lines = []
    for i, w in enumerate(wnids):
        arr = rng.integers(0, 256, size=(64, 64, 3), dtype=np.uint8)
        Image.fromarray(arr).save(vd / f"val_{i}.JPEG")
        lines.append(f"val_{i}.JPEG\t{w}\t0\t0\t10\t10\n")
    (root / "val" / "val_annotations.txt").write_text("".join(lines))

    Xtr, ytr, Xte, yte = V.load_vision_dataset("tiny", str(tmp_path))
    assert Xtr.shape == (6, 64, 64, 3) and Xtr.dtype == np.float32
    np.testing.assert_array_equal(np.unique(ytr), [0, 1])
    assert Xte.shape[0] == 2
    np.testing.assert_array_equal(yte, [0, 1])


@pytest.mark.slow
def test_salientgrads_on_vision_smoke(tmp_path):
    """The flagship algorithm on the public data path (SURVEY hard-part #5:
    CIFAR is the parity cross-check the private cohort can't provide):
    SNIP mask + masked rounds on a 2D CNN over the synthetic vision cohort."""
    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig, SparsityConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.vision import federate_vision
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    mesh = make_mesh()
    fed, _ = federate_vision("cifar10", "", "n_cls", 2, 4, mesh=mesh,
                             seed=1, synthetic=True)
    cfg = ExperimentConfig(
        model="cnn_cifar10", num_classes=10, algorithm="salientgrads",
        data=DataConfig(dataset="cifar10", partition_method="n_cls"),
        optim=OptimConfig(lr=0.01, batch_size=16, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=1),
        sparsity=SparsityConfig(dense_ratio=0.3),
        log_dir=str(tmp_path))
    model = create_model("cnn_cifar10", num_classes=10)
    trainer = LocalTrainer(model, cfg.optim, num_classes=10)
    log = ExperimentLogger(str(tmp_path), "cifar10", cfg.identity(),
                           console=False)
    engine = create_engine("salientgrads", cfg, fed, trainer, mesh=mesh,
                           logger=log)
    res = engine.train()
    # mask respects the density target on a 2D model too
    assert abs(res["mask_density"] - 0.3) < 0.1
    assert np.isfinite(res["history"][-1]["train_loss"])


@pytest.mark.slow
def test_federated_vision_end_to_end(tmp_path):
    """2D CNN federation over the synthetic vision cohort: accuracy beats
    chance after a few FedAvg rounds (public cross-check path,
    SURVEY hard-part #5)."""
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.vision import federate_vision
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    mesh = make_mesh()
    # deliberately tiny: on this 1-core harness every mesh program runs its
    # 8 shards serially, so the e2e checks learning DIRECTION, not a
    # converged accuracy (PROFILE.md; real training happens on TPU)
    fed, info = federate_vision("cifar10", "", "dir", 0.5, 4, mesh=mesh,
                                seed=0, synthetic=True,
                                synthetic_num=(128, 64))
    assert fed.X_train.ndim == 5  # [C, N, H, W, 3]
    cfg = ExperimentConfig(
        model="cnn_cifar10", num_classes=10, algorithm="fedavg",
        data=DataConfig(dataset="cifar10", partition_method="dir"),
        optim=OptimConfig(lr=0.05, batch_size=16, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=2,
                      frequency_of_the_test=1),
        log_dir=str(tmp_path))
    model = create_model("cnn_cifar10", num_classes=10)
    trainer = LocalTrainer(model, cfg.optim, num_classes=10)
    log = ExperimentLogger(str(tmp_path), "cifar10", cfg.identity(),
                           console=False)
    engine = create_engine("fedavg", cfg, fed, trainer, mesh=mesh,
                           logger=log)
    res = engine.train()
    hist = res["history"]
    assert jnp.isfinite(hist[-1]["train_loss"])
    # learning direction: loss dropped and accuracy is at least chance-ish
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]
    assert res["final_global"]["acc"] > 0.1
