"""Serving plane (ISSUE 17, serve/): checkpoint→bundle contract,
micro-batched inference engine, per-site routing, live HTTP workers.

Layout mirrors tests/test_ingest.py:
  (a) bundle contract — round-trip determinism, precision, sparse
      masks, loud drift rejection;
  (b) engine — bucketed compile pins, recompile tripwire, shape fence;
  (c) live multi-process serving — one fast 2-worker HTTP cell in
      tier-1, the loadgen serve fleet marked slow.
"""

import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flax import serialization

from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.obs import compute as obs_compute
from neuroimagedisttraining_tpu.serve.bundle import (
    BundleError,
    GLOBAL_KEY,
    MANIFEST_NAME,
    WEIGHTS_NAME,
    build_bundle,
    load_bundle,
)
from neuroimagedisttraining_tpu.serve.engine import ServeEngine
from neuroimagedisttraining_tpu.utils.checkpoint import save_checkpoint

SHAPE = (12, 14, 12)


def _init_tree(seed=0):
    m = create_model("3dcnn_tiny", num_classes=1)
    v = m.init({"params": jax.random.PRNGKey(seed),
                "dropout": jax.random.PRNGKey(seed + 1)},
               jnp.zeros((1, *SHAPE, 1)), train=False)
    return v["params"], v.get("batch_stats", {})


def _stack(tree, n):
    # i+1: row 0 must NOT equal the global params, or the per-site
    # digests would collide with the global one
    return jax.tree.map(
        lambda x: jnp.stack([x * (1.0 + 0.1 * (i + 1))
                             for i in range(n)]),
        tree)


@pytest.fixture(scope="module")
def ditto_ckpt(tmp_path_factory):
    """One ditto-flavor checkpoint (2 personalized sites) shared by the
    module — model init dominates the cost, the checkpoint is
    read-only."""
    params, bstats = _init_tree()
    state = {"params": params, "batch_stats": bstats,
             "per_params": _stack(params, 2),
             "per_bstats": _stack(bstats, 2)}
    ck = str(tmp_path_factory.mktemp("serve") / "ck")
    save_checkpoint(ck, 5, state)
    return ck


def _build(ck, out, **kw):
    kw.setdefault("model", "3dcnn_tiny")
    kw.setdefault("num_classes", 1)
    kw.setdefault("input_shape", SHAPE)
    return build_bundle(ck, str(out), **kw)


# ---------------------------------------------------------------------------
# (a) bundle contract
# ---------------------------------------------------------------------------


def test_bundle_roundtrip_bitwise(ditto_ckpt, tmp_path):
    """save→load→save is bitwise-stable: a rebuild from the same
    checkpoint reproduces both files byte for byte, and re-serializing
    the LOADED weight trees reproduces the committed payload (bf16
    survives the msgpack round trip exactly)."""
    d1, d2 = tmp_path / "b1", tmp_path / "b2"
    m1 = _build(ditto_ckpt, d1)
    m2 = _build(ditto_ckpt, d2)
    assert m1 == m2
    for name in (MANIFEST_NAME, WEIGHTS_NAME):
        b1 = (d1 / name).read_bytes()
        assert b1 == (d2 / name).read_bytes(), name
    bundle = load_bundle(str(d1))
    payload = serialization.msgpack_serialize(
        {k: bundle.models[k] for k in sorted(bundle.models)})
    assert payload == (d1 / WEIGHTS_NAME).read_bytes()
    # the manifest is exactly its own sorted-keys dump (timestamp-free)
    assert ((d1 / MANIFEST_NAME).read_text()
            == json.dumps(bundle.manifest, indent=1, sort_keys=True)
            + "\n")
    assert bundle.source_round == 5
    assert bundle.sites == ("0", "1")


def test_bundle_bf16_predictions_near_f32(ditto_ckpt, tmp_path):
    """bf16 serving stays within the pinned tolerance of the f32
    escape hatch on the same checkpoint."""
    bf = load_bundle(_bundle_dir(ditto_ckpt, tmp_path / "bf", "bf16"))
    fp = load_bundle(_bundle_dir(ditto_ckpt, tmp_path / "fp", "fp32"))
    assert bf.precision == "bf16" and fp.precision == "fp32"
    e_bf = ServeEngine(bf, batch_buckets=(1,), max_queue_ms=0.5)
    e_fp = ServeEngine(fp, batch_buckets=(1,), max_queue_ms=0.5)
    try:
        x = np.random.default_rng(0).normal(size=SHAPE)
        y_bf, _ = e_bf.predict(None, x)
        y_fp, _ = e_fp.predict(None, x)
        # tiny-model logits are O(1); bf16 carries ~8 mantissa bits
        assert np.max(np.abs(y_bf - y_fp)) < 0.1, (y_bf, y_fp)
    finally:
        e_bf.close()
        e_fp.close()


def _bundle_dir(ck, out, precision):
    _build(ck, out, precision=precision)
    return str(out)


def test_salientgrads_bundle_applies_mask(tmp_path):
    """A salientgrads checkpoint serves SPARSE params: the mask is
    multiplied in at build, nnz is pinned in the manifest, and the
    loaded weights honor it."""
    params, bstats = _init_tree()
    rng = np.random.default_rng(7)
    masks = jax.tree.map(
        lambda p: (rng.random(np.shape(p)) < 0.5).astype(np.float32),
        jax.tree.map(np.asarray, params))
    state = {"params": params, "batch_stats": bstats, "masks": masks,
             "history": []}
    ck = str(tmp_path / "ck")
    save_checkpoint(ck, 2, state)
    manifest = _build(ck, tmp_path / "bundle")
    assert manifest["flavor"] == "salientgrads"
    expect_nnz = int(sum(
        np.count_nonzero(np.asarray(p) * m) for p, m in zip(
            jax.tree.leaves(params), jax.tree.leaves(masks))))
    assert manifest["sparse_nnz"] == expect_nnz
    assert 0 < expect_nnz < manifest["total_params"]
    bundle = load_bundle(str(tmp_path / "bundle"))
    got_nnz = int(sum(
        np.count_nonzero(np.asarray(x, np.float32)) for x in
        jax.tree.leaves(bundle.models[GLOBAL_KEY]["params"])))
    assert got_nnz == expect_nnz


def test_fedfomo_bundle_serves_mean_global(tmp_path):
    """fedfomo checkpoints keep no global model — the bundle's global
    fallback is the uniform mean of the personalized stack."""
    params, bstats = _init_tree()
    state = {"per_params": _stack(params, 3),
             "per_bstats": _stack(bstats, 3),
             "weights": np.eye(3, dtype=np.float32),
             "p_choose": np.ones((3, 3), np.float32) / 3,
             "history": []}
    ck = str(tmp_path / "ck")
    save_checkpoint(ck, 1, state)
    manifest = _build(ck, tmp_path / "bundle", precision="fp32")
    assert manifest["flavor"] == "fedfomo"
    bundle = load_bundle(str(tmp_path / "bundle"))
    assert bundle.sites == ("0", "1", "2")
    # mean of x*(1.1, 1.2, 1.3) == x*1.2
    lead = jax.tree.leaves(params)[0]
    got = jax.tree.leaves(bundle.models[GLOBAL_KEY]["params"])[0]
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(lead) * 1.2, rtol=1e-5)


def test_corrupt_and_stale_bundles_rejected(ditto_ckpt, tmp_path):
    bdir = tmp_path / "bundle"
    _build(ditto_ckpt, bdir)
    mpath, wpath = bdir / MANIFEST_NAME, bdir / WEIGHTS_NAME

    with pytest.raises(BundleError, match="not a bundle"):
        load_bundle(str(tmp_path / "nowhere"))

    good = mpath.read_text()
    mpath.write_text(good[:-20])  # truncate: invalid JSON
    with pytest.raises(BundleError, match="corrupt manifest"):
        load_bundle(str(bdir))

    doc = json.loads(good)
    del doc["sites"]
    mpath.write_text(json.dumps(doc))
    with pytest.raises(BundleError, match="stale manifest"):
        load_bundle(str(bdir))

    doc = json.loads(good)
    doc["bundle_version"] = 99
    mpath.write_text(json.dumps(doc))
    with pytest.raises(BundleError, match="version mismatch"):
        load_bundle(str(bdir))

    mpath.write_text(good)
    raw = bytearray(wpath.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    wpath.write_bytes(bytes(raw))
    with pytest.raises(BundleError, match="weights drift"):
        load_bundle(str(bdir))

    # per-model digest drift with a still-valid payload sha: swap the
    # declared digests of two models in the manifest
    _build(ditto_ckpt, bdir)  # restore
    doc = json.loads(mpath.read_text())
    a, b = doc["models"]["site:0"], doc["models"]["site:1"]
    doc["models"]["site:0"], doc["models"]["site:1"] = b, a
    mpath.write_text(json.dumps(doc))
    with pytest.raises(BundleError, match="drift"):
        load_bundle(str(bdir))


def test_bundle_missing_checkpoint_and_bad_precision(tmp_path):
    with pytest.raises(BundleError, match="no checkpoints"):
        _build(str(tmp_path / "empty"), tmp_path / "b")
    with pytest.raises(BundleError, match="precision"):
        _build(str(tmp_path / "empty"), tmp_path / "b",
               precision="fp16")


def test_routing_distinct_digests(ditto_ckpt, tmp_path):
    bundle = load_bundle(_bundle_dir(ditto_ckpt, tmp_path / "b",
                                     "bf16"))
    assert bundle.route("0") == "site:0"
    assert bundle.route("1") == "site:1"
    # unknown or absent site falls back to the global model
    assert bundle.route("7") == GLOBAL_KEY
    assert bundle.route(None) == GLOBAL_KEY
    digests = {bundle.digest(k) for k in
               (GLOBAL_KEY, "site:0", "site:1")}
    assert len(digests) == 3, "personalized models must differ"


# ---------------------------------------------------------------------------
# (b) engine: buckets, compile pins, tripwire
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def ditto_bundle(ditto_ckpt, tmp_path_factory):
    out = tmp_path_factory.mktemp("serve") / "bundle"
    _build(ditto_ckpt, out)
    return load_bundle(str(out))


def test_engine_one_program_per_bucket(ditto_bundle):
    """The compile pin at engine level: N distinct (model, bucket)
    shapes → exactly N programs on the SHARED compute-plane counter
    (``nidt_compiles_total{engine="serve"}``), zero recompiles, and
    re-dispatching an existing bucket never traces again."""
    c0 = obs_compute.compiles_total(engine="serve")
    eng = ServeEngine(ditto_bundle, batch_buckets=(1, 4),
                      max_queue_ms=200.0)
    try:
        x = np.zeros(SHAPE, np.float32)
        # 4 concurrent submissions fill the max bucket in one dispatch
        pends = [eng.submit("0", x)[0] for _ in range(4)]
        for p in pends:
            assert p.event.wait(60.0)
            assert p.error is None and p.result is not None
        s = eng.stats()
        assert s["dispatches"] == 1 and s["batches"] == {"4": 1}, s
        assert s["compiles"] == 1 and s["compiled"] == ["site:0/b4"]
        # same bucket again: execute, no new program
        pends = [eng.submit("0", x)[0] for _ in range(4)]
        for p in pends:
            assert p.event.wait(60.0)
        assert eng.stats()["compiles"] == 1
        # a lone request pads to bucket 1 → second program
        y, key = eng.predict("0", x, timeout=60.0)
        assert key == "site:0" and y.shape == (1,)
        s = eng.stats()
        assert s["compiles"] == 2 and s["recompiles"] == 0, s
        assert s["requests_dispatched"] == 9
        assert obs_compute.compiles_total(engine="serve") == c0 + 2
    finally:
        eng.close()


def test_engine_recompile_tripwire(ditto_bundle):
    """A second build of the SAME (model, bucket) key — the declared-
    bucket fence leaking a shape — must hit the recompile counter, not
    pass silently."""
    eng = ServeEngine(ditto_bundle, batch_buckets=(1,),
                      max_queue_ms=0.5)
    try:
        x = np.zeros(SHAPE, np.float32)
        eng.predict(None, x, timeout=60.0)
        assert eng.stats()["recompiles"] == 0
        # poison the recorded signature to simulate a shape leak
        eng._sigs[(GLOBAL_KEY, 1)] = ("poisoned",)
        eng.predict(None, x, timeout=60.0)
        s = eng.stats()
        assert s["recompiles"] == 1 and s["compiles"] == 1, s
    finally:
        eng.close()


def test_engine_shape_fence_and_validation(ditto_bundle):
    eng = ServeEngine(ditto_bundle, batch_buckets=(2,),
                      max_queue_ms=0.5)
    try:
        with pytest.raises(ValueError, match="input shape"):
            eng.submit(None, np.zeros((3, 3), np.float32))
    finally:
        eng.close()
    with pytest.raises(ValueError, match="batch_buckets"):
        ServeEngine(ditto_bundle, batch_buckets=())
    with pytest.raises(ValueError, match="precision"):
        ServeEngine(ditto_bundle, precision="fp16")


def test_engine_precision_override(ditto_bundle):
    """The fp32 flag re-casts a bf16 bundle at load (escape hatch)."""
    eng = ServeEngine(ditto_bundle, batch_buckets=(1,),
                      max_queue_ms=0.5, precision="fp32")
    try:
        assert eng.precision == "fp32"
        lead = jax.tree.leaves(eng._weights[GLOBAL_KEY][0])[0]
        assert lead.dtype == jnp.float32
        y, _ = eng.predict(None, np.zeros(SHAPE, np.float32),
                           timeout=60.0)
        assert np.all(np.isfinite(y))
    finally:
        eng.close()


def test_engine_concurrent_sites_route_differently(ditto_bundle):
    """Two sites served concurrently come back from DIFFERENT
    personalized weights (routing happens per request, inside one
    engine)."""
    eng = ServeEngine(ditto_bundle, batch_buckets=(1, 2),
                      max_queue_ms=1.0)
    try:
        x = np.random.default_rng(1).normal(size=SHAPE)
        results = {}

        def hit(site):
            y, key = eng.predict(site, x, timeout=60.0)
            results[site] = (float(y[0]), key)

        ts = [threading.Thread(target=hit, args=(s,))
              for s in ("0", "1")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(90.0)
        assert results["0"][1] == "site:0"
        assert results["1"][1] == "site:1"
        # per-site weights differ by construction → logits differ
        assert results["0"][0] != results["1"][0], results
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# (c) live multi-process serving
# ---------------------------------------------------------------------------


def test_http_two_workers_live(ditto_ckpt, tmp_path):
    """Tier-1 live cell: 2 SO_REUSEPORT workers on one port, JSON and
    raw-array /predict, per-site routing digests distinct, malformed
    and unknown-site verdicts recorded, shutdown audit reconciles."""
    import urllib.error
    import urllib.request

    from neuroimagedisttraining_tpu.serve.server import (
        ShardedServeServer,
    )

    bdir = _bundle_dir(ditto_ckpt, tmp_path / "bundle", "bf16")
    srv = ShardedServeServer(bdir, serve_workers=2,
                             batch_buckets=(1, 2), max_queue_ms=1.0)
    try:
        url = f"http://127.0.0.1:{srv.port}"
        x = np.random.default_rng(0).normal(size=SHAPE).astype(
            np.float32)

        def post(data, headers):
            req = urllib.request.Request(f"{url}/predict", data=data,
                                         headers=headers,
                                         method="POST")
            return json.loads(
                urllib.request.urlopen(req, timeout=120).read())

        r0 = post(json.dumps({"x": x.tolist(), "site": "0"}).encode(),
                  {"Content-Type": "application/json"})
        r1 = post(x.tobytes(),
                  {"Content-Type": "application/octet-stream",
                   "X-NIDT-Shape": "12,14,12", "X-NIDT-Site": "1"})
        assert r0["model"] == "site:0" and r1["model"] == "site:1"
        assert r0["digest"] != r1["digest"]
        assert r0["model_version"] == 5
        # unknown site → served by the global model, verdict recorded
        ru = post(x.tobytes(),
                  {"Content-Type": "application/octet-stream",
                   "X-NIDT-Shape": "12,14,12", "X-NIDT-Site": "9"})
        assert ru["model"] == GLOBAL_KEY
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(b"not json", {"Content-Type": "application/json"})
        assert ei.value.code == 400
        h = json.loads(urllib.request.urlopen(
            f"{url}/healthz", timeout=30).read())
        assert h["ok"] and h["model"] == "3dcnn_tiny"
        assert h["model_version"] == 5
    finally:
        audit = srv.stop()
    assert audit["reconciled"], audit
    assert audit["served"] == 3 and audit["rejected"] == 1, audit
    assert audit["unknown_site"] == 1, audit


@pytest.mark.slow
def test_loadgen_serve_fleet_end_to_end(ditto_ckpt, tmp_path):
    from neuroimagedisttraining_tpu.asyncfl.loadgen import run_load

    bdir = _bundle_dir(ditto_ckpt, tmp_path / "bundle", "bf16")
    res = run_load(mode="serve", num_clients=16, serve_bundle=bdir,
                   serve_workers=2, serve_requests=48,
                   batch_buckets=(1, 2, 4), fleet_procs=1)
    assert res["frames_reconciled"], res["serve_audit"]
    assert res["requests_ok"] == 48
    assert res["compile_pin_ok"], res["compiled_programs"]
    assert res["routing"]["distinct_site_models"], res["routing"]
    assert res["merged_metrics"]["worker_labeled"] == [0, 1]
    assert res["merged_metrics"]["has_serve_latency"]
    assert res["merged_metrics"]["has_rtt_samples"]
