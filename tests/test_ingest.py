"""Sharded ingest plane tests (ISSUE 12, asyncfl/ingest.py).

Contracts:

(a) THE sharded-ingest invariant: any partitioning of the same uploads
    into per-worker ``PartialAccumulator``s, merged in any order, equals
    one accumulator that folded everything — BITWISE, for the dense
    int64 lattice AND the secure-quant ``SlotAccumulator`` chunk fold
    (exact integer/field algebra; a float tree-sum could never give
    this, its reduction tree changes with the partitioning).
(b) The worker admission gates render the same verdicts as the
    single-process ``BufferedFedAvgServer`` key for key (stale /
    duplicate / future / non-finite / malformed / after-done), and a
    re-register resets the sender's dedup state.
(c) Live multi-process runs (SO_REUSEPORT workers + root): audits green
    — ``received == accepted + dropped`` and
    ``accepted == aggregated + buffered + lost_with_worker`` — across
    processes, including the kill-one-worker chaos case where a
    SIGKILLed worker's buffered uploads are counted, never silently
    vanished.
(d) The cached-sync reply contract: a body-less sync at an unchanged
    version reuses the silo's cached tree; body-less before any full
    sync is a dropped protocol error.
"""

import dataclasses

import jax
import numpy as np
import pytest

from neuroimagedisttraining_tpu.asyncfl.ingest import (
    IngestWorkerCore,
    PartialAccumulator,
    ShardedIngestServer,
    make_fold_spec,
    model_sizes,
    single_process_fold,
)
from neuroimagedisttraining_tpu.asyncfl.loadgen import (
    canned_update_tree,
    run_load,
)
from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.privacy import (
    QuantSpec,
    encode_secure_quant,
)

LIKE = canned_update_tree(0, 64)


def _dense_entries(n, leaf_elems=64):
    return [(canned_update_tree(r, leaf_elems), 100 + 7 * r)
            for r in range(1, n + 1)]


def _secure_entries(n, spec, leaf_elems=64):
    return [(encode_secure_quant(canned_update_tree(r, leaf_elems), 1.0,
                                 spec, np.random.default_rng(r)),
             200 + 11 * r)
            for r in range(1, n + 1)]


def _merge_partition(entries, spec, parts):
    """Fold ``entries`` split into ``parts``-sized per-worker
    accumulators, then merge the exported partials in order."""
    merged = PartialAccumulator(spec, model_sizes(LIKE))
    i = 0
    for n in parts:
        acc = PartialAccumulator(spec, model_sizes(LIKE))
        for payload, w in entries[i:i + n]:
            if spec.quant is not None:
                acc.fold_frame(payload, w)
            else:
                acc.fold_dense(payload, w)
        i += n
        p = acc.export()
        if p is not None:
            merged.merge_payload(p)
    return merged


# ---------------------------------------------------------------------------
# (a) partition-independent exact merge
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("parts", [[12], [4, 4, 4], [1, 11], [6, 3, 3],
                                   [2, 2, 2, 2, 2, 2]])
def test_dense_merge_partition_independent_bitwise(parts):
    spec = make_fold_spec(LIKE)
    entries = _dense_entries(12)
    ref = single_process_fold(entries, spec, LIKE)
    merged = _merge_partition(entries, spec, parts)
    assert merged.w_int_total == ref.w_int_total
    assert merged.count == ref.count
    for name, _ in model_sizes(LIKE):
        np.testing.assert_array_equal(merged.totals[name],
                                      ref.totals[name])
    # and the dequantized model is bitwise too (same totals, same denom)
    for a, b in zip(jax.tree.leaves(merged.finalize(LIKE)),
                    jax.tree.leaves(ref.finalize(LIKE))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("parts", [[9], [3, 3, 3], [1, 8], [5, 2, 2]])
def test_secure_merge_partition_independent_bitwise(parts):
    """The SlotAccumulator chunk fold: per-worker chunks lift into plain
    int64 at partition-dependent boundaries, yet the totals are exact
    integer sums — bitwise identical for every partitioning (the
    center-lift is exact while the folded mass stays inside the field's
    range, which the chunk capacity guarantees)."""
    quant = QuantSpec.from_bits(32, 10, 3)
    spec = make_fold_spec(LIKE, quant=quant)
    entries = _secure_entries(9, quant)
    ref = single_process_fold(entries, spec, LIKE)
    refp = ref.export()
    merged = _merge_partition(entries, spec, parts)
    assert merged.w_int_total == refp["w_int"]
    for name, _ in model_sizes(LIKE):
        np.testing.assert_array_equal(merged.totals[name],
                                      refp["slots"][name])


def test_dense_fold_nan_and_saturation():
    """The dense lattice's documented edges: NaN coordinates fold as the
    neutral zero contribution; +/-inf saturates sign-preservingly at the
    clamp edge (never wraps)."""
    spec = make_fold_spec(LIKE)
    bad = canned_update_tree(1, 64)
    k = bad["params"]["dense"]["kernel"]
    k[0], k[1], k[2] = np.nan, np.inf, -np.inf
    acc = PartialAccumulator(spec, model_sizes(LIKE))
    acc.fold_dense(bad, 3)
    t = acc.totals["params/dense/kernel"]
    assert t[0] == 0
    assert t[1] == 3 * spec.q_max
    assert t[2] == -3 * spec.q_max


def test_fold_spec_headroom_validation():
    with pytest.raises(ValueError, match="field too small"):
        make_fold_spec(LIKE, quant=QuantSpec.from_bits(16, 10, 3))
    spec = make_fold_spec(LIKE, quant=QuantSpec.from_bits(32, 10, 3))
    assert spec.weight_cap >= 1 << 10
    assert spec.mass_bound() > 0


def test_root_rejects_defenses():
    with pytest.raises(ValueError, match="defenses"):
        ShardedIngestServer(LIKE, 2, 4, ingest_workers=1,
                            defense="trimmed_mean")


# ---------------------------------------------------------------------------
# (b) worker-core admission gates (socket-free)
# ---------------------------------------------------------------------------


def _upload(c, tag=None, n=8.0, seq=None, tree=None, leaf_elems=64):
    msg = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, c, 0)
    msg.add(M.ARG_MODEL_PARAMS,
            tree if tree is not None else canned_update_tree(c,
                                                             leaf_elems))
    msg.add(M.ARG_NUM_SAMPLES, n)
    if tag is not None:
        msg.add(M.ARG_ROUND_IDX, tag)
    if seq is not None:
        msg.add(M.ARG_UPLOAD_SEQ, seq)
    return msg


def _core(wid=0, quant=None, max_staleness=4):
    spec = make_fold_spec(LIKE, quant=quant)
    return IngestWorkerCore(wid, spec, LIKE,
                            max_staleness=max_staleness,
                            staleness_alpha=0.5)


def test_worker_admission_verdicts():
    core = _core()
    assert core.handle_upload(_upload(1, tag=0, seq=0)) == "accepted"
    # transport re-delivery repeats the VERDICT, never the processing
    assert core.handle_upload(_upload(1, tag=0, seq=0)) == \
        "dropped_duplicate"
    # fresh seq, same base version: an honest re-contribution
    assert core.handle_upload(_upload(1, tag=0, seq=1)) == "accepted"
    # future tag (worker lags the root by the pipe latency)
    assert core.handle_upload(_upload(2, tag=7, seq=0)) == \
        "dropped_future"
    core.set_model(6, canned_update_tree(99, 64))
    # ancient tag beyond the ring
    assert core.handle_upload(_upload(2, tag=1, seq=1)) == \
        "dropped_stale"
    # non-finite decoded upload is rejected at the gate
    bad = canned_update_tree(3, 64)
    bad["params"]["dense"]["bias"][0] = np.nan
    assert core.handle_upload(_upload(3, tag=6, seq=0, tree=bad)) == \
        "dropped_nonfinite"
    # broken FIELDS are a dropped upload, never a dead dispatch thread
    assert core.handle_upload(_upload(4, tag=6, seq=0, n=float("nan"))) \
        == "dropped_malformed"
    nomsg = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, 5, 0)
    nomsg.add(M.ARG_MODEL_PARAMS, canned_update_tree(5, 64))
    nomsg.add(M.ARG_ROUND_IDX, 6)  # no num_samples at all
    assert core.handle_upload(nomsg) == "dropped_malformed"
    core.done = True
    assert core.handle_upload(_upload(1, tag=6, seq=2)) == \
        "dropped_after_done"
    s = core.stats
    assert s["received"] == sum(v for k, v in s.items()
                                if k != "received")


def test_worker_legacy_dedup_and_reregister_reset():
    core = _core()
    # legacy sender (no seq): at most one contribution per base version
    assert core.handle_upload(_upload(1, tag=0)) == "accepted"
    assert core.handle_upload(_upload(1, tag=0)) == "dropped_duplicate"
    # a re-register (also how a connection migrates workers after a
    # kill) resets the sender's dedup state, like the single-process
    # server's restarted-process contract
    core.handle_register(1)
    assert core.handle_upload(_upload(1, tag=0)) == "accepted"


def test_worker_entries_match_partial():
    core = _core()
    for c in range(1, 5):
        assert core.handle_upload(_upload(c, tag=0, seq=0)) == "accepted"
    payload = core.export_partial()
    assert payload["count"] == 4
    assert len(payload["entries"]) == 4
    assert core.export_partial() is None  # swapped out clean
    # the exported partial equals a single-process fold of the same
    # decoded uploads at the same integer weights (tau=0: decode is a
    # bitwise passthrough for dense pytrees)
    spec = core.spec
    entries = [(canned_update_tree(c, 64),
                spec.weight_int(8.0, 0, 0.5)) for c in range(1, 5)]
    ref = single_process_fold(entries, spec, LIKE)
    for name, _ in model_sizes(LIKE):
        np.testing.assert_array_equal(payload["slots"][name],
                                      ref.totals[name])


def test_worker_secure_frame_gate():
    quant = QuantSpec.from_bits(32, 10, 3)
    core = _core(quant=quant)
    frame = encode_secure_quant(canned_update_tree(1, 64), 1.0, quant,
                                np.random.default_rng(0))
    assert core.handle_upload(_upload(1, tag=0, seq=0, tree=frame)) == \
        "accepted"
    # a dense pytree on the secure path is an invalid frame
    assert core.handle_upload(_upload(2, tag=0, seq=0)) == \
        "dropped_undecodable"
    # spec mismatch (config skew) is named, not folded
    other = encode_secure_quant(canned_update_tree(3, 64), 1.0,
                                QuantSpec.from_bits(32, 8, 3),
                                np.random.default_rng(1))
    assert core.handle_upload(_upload(3, tag=0, seq=0, tree=other)) == \
        "dropped_undecodable"


# ---------------------------------------------------------------------------
# (d) cached-sync reply contract (cross_silo client side)
# ---------------------------------------------------------------------------


def test_cached_sync_reuses_model_body():
    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        FedAvgClientProc,
    )

    silo = object.__new__(FedAvgClientProc)
    silo.rank = 1
    silo._last_sync_params = None
    silo._wire_spec = None
    silo._wire_ef = None
    silo.wire_masks = None
    silo.fault_schedule = None
    silo._upload_seq = 0
    trained_from = []
    silo.train_fn = lambda p, r: (trained_from.append(p) or p, 4.0)
    sent = []
    silo.send_message = sent.append

    def sync(params, version):
        msg = M.Message(M.MSG_TYPE_S2C_SYNC_MODEL, 0, 1)
        if params is not None:
            msg.add(M.ARG_MODEL_PARAMS, params)
        msg.add(M.ARG_ROUND_IDX, version)
        silo._on_sync(msg)

    # body-less sync before any full sync: protocol error, dropped
    sync(None, 0)
    assert not sent and not trained_from
    # full sync caches; the next body-less sync trains from the cache
    tree = canned_update_tree(1, 8)
    sync(tree, 0)
    sync(None, 0)
    assert len(sent) == 2 and len(trained_from) == 2
    for a, b in zip(jax.tree.leaves(trained_from[0]),
                    jax.tree.leaves(trained_from[1])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # seq advanced per upload (the root's watermark dedup relies on it)
    assert [m.get(M.ARG_UPLOAD_SEQ) for m in sent] == [0, 1]


# ---------------------------------------------------------------------------
# (c) live multi-process runs — slow (spawned workers + asyncio fleet)
# ---------------------------------------------------------------------------


def _assert_green(res):
    audit = res["upload_audit"]
    assert audit["received_accounted"], audit
    assert audit["accepted_accounted"], audit
    assert res["frames_reconciled"], res
    assert res["rounds_or_aggregations"] == res["target"], res


@pytest.mark.slow
def test_ingest_two_workers_end_to_end(tmp_path):
    trace_out = str(tmp_path / "merged_trace.json")
    res = run_load(mode="ingest", num_clients=24, aggregations=5,
                   buffer_k=8, ingest_workers=2, leaf_elems=64,
                   trace_out=trace_out,
                   flight_out=str(tmp_path / "merged_flight.json"))
    _assert_green(res)
    assert res["lost_with_worker"] == 0
    assert res["workers_live_at_end"] == []  # clean shutdown
    # federation-wide obs (ISSUE 13): BOTH workers shipped registries
    # (worker-labeled merged /metrics incl. stage + rtt histograms),
    # and at least one upload's client->worker->root lifecycle is
    # flow-linked in the MERGED, Perfetto-loadable trace
    fan = res["obs_fanin"]
    assert fan["0"]["has_metrics"] and fan["1"]["has_metrics"], fan
    assert res["merged_metrics"]["worker_labeled"] == [0, 1]
    assert res["merged_metrics"]["has_stage_samples"]
    assert res["merged_metrics"]["has_rtt_samples"]
    assert res["merged_trace"]["flow_linked"] >= 1, res["merged_trace"]
    import json as _json

    doc = _json.load(open(trace_out))
    assert doc["traceEvents"], "merged trace dumped at the bare path"
    fl = _json.load(open(str(tmp_path / "merged_flight.json")))
    assert any(e["proc"].startswith("worker") for e in fl["events"])


@pytest.mark.slow
def test_ingest_kill_one_worker_audits_green():
    """The chaos case: SIGKILL worker 0 mid-run. Its clients reconnect
    onto the surviving SO_REUSEPORT listener, every aggregation still
    lands, and the audit reconciles — uploads the dead worker accepted
    but never shipped are counted lost_with_worker, never silently
    vanished."""
    res = run_load(mode="ingest", num_clients=24, aggregations=6,
                   buffer_k=8, ingest_workers=2, ingest_kill_at=2,
                   leaf_elems=64)
    _assert_green(res)
    audit = res["upload_audit"]
    assert not audit["workers"][0]["alive"]
    # worker 0's acceptances are all accounted: folded (merged or
    # counted lost) — the invariant, not a specific loss count
    w0 = audit["workers"][0]
    assert w0["acc"] == w0["folded"]
    assert res["client_stats"]["rejoins"] >= 1
    # fan-in across the kill (ISSUE 13): the dead worker's LAST
    # snapshot is still served (marked dead) and the survivor's
    # samples stay worker-labeled — the merged /metrics never loses a
    # worker silently
    fan = res["obs_fanin"]
    assert fan["0"]["alive"] is False
    assert fan["0"]["has_metrics"], fan  # stale snapshot retained
    assert 1 in res["merged_metrics"]["worker_labeled"]


@pytest.mark.slow
def test_ingest_secure_quant_end_to_end():
    res = run_load(mode="ingest", num_clients=16, aggregations=4,
                   buffer_k=6, ingest_workers=2,
                   ingest_secure_quant=True, leaf_elems=64)
    _assert_green(res)
    assert res["secure_quant"] is True
