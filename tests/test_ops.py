"""Sparsity ops tests: top-k selection vs numpy, ERK sparsities, mask init
exact counts, fire/regrow semantics, SNIP identity, FLOPs counter."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.config import OptimConfig
from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
from neuroimagedisttraining_tpu.models import Tiny3DCNN
from neuroimagedisttraining_tpu.ops import flops as F
from neuroimagedisttraining_tpu.ops import masks as M
from neuroimagedisttraining_tpu.ops import snip as S
from neuroimagedisttraining_tpu.ops.topk import kth_largest


def test_kth_largest_matches_numpy():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=100_003).astype(np.float32))
    for k in (1, 7, 1000, 50_000, 100_003):
        got = float(kth_largest(x, k))
        want = float(np.sort(np.asarray(x))[::-1][k - 1])
        assert got == pytest.approx(want, rel=1e-6), k
        # mask semantics: >= threshold keeps at least k
        assert int(np.sum(np.asarray(x) >= got)) >= k


def test_kth_largest_with_duplicates():
    x = jnp.asarray(np.array([1.0, 2.0, 2.0, 2.0, 3.0], np.float32))
    assert float(kth_largest(x, 2)) == 2.0
    assert float(kth_largest(x, 4)) == 2.0
    assert float(kth_largest(x, 5)) == 1.0


def test_kth_largest_nan_input_yields_nan_not_garbage():
    """VERDICT r4 #7: a single NaN score (one client's diverged loss) must
    not silently produce a wrong-but-finite threshold."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=1000).astype(np.float32)
    x[137] = np.nan
    assert np.isnan(float(kth_largest(jnp.asarray(x), 10)))
    x[137] = np.inf
    assert np.isnan(float(kth_largest(jnp.asarray(x), 10)))


def test_mask_from_scores_raises_on_nonfinite():
    _, _, cs = _toy_trainer()
    rng = np.random.default_rng(0)
    scores = jax.tree.map(
        lambda p: jnp.asarray(np.abs(rng.normal(size=p.shape)), jnp.float32),
        cs.params)
    # poison ONE maskable leaf with a single NaN
    k = scores["f0"]["conv"]["kernel"]
    scores["f0"]["conv"]["kernel"] = k.at[(0,) * k.ndim].set(jnp.nan)
    with pytest.raises(FloatingPointError, match="non-finite"):
        S.mask_from_scores(scores, keep_ratio=0.3)


def test_mask_from_scores_raises_on_all_zero():
    """Degenerate phase-1 probe (zero gradients everywhere) must get its
    own diagnostic, not the non-finite one."""
    _, _, cs = _toy_trainer()
    scores = jax.tree.map(jnp.zeros_like, cs.params)
    with pytest.raises(FloatingPointError, match="identically zero"):
        S.mask_from_scores(scores, keep_ratio=0.3)


def _toy_trainer():
    model = Tiny3DCNN(num_classes=1)
    trainer = LocalTrainer(model, OptimConfig(batch_size=4), num_classes=1)
    cs = trainer.init_client_state(jax.random.key(0),
                                   jnp.zeros((1, 12, 12, 12, 1)))
    return model, trainer, cs


def test_erk_sparsities_hit_target_density():
    _, _, cs = _toy_trainer()
    for dr in (0.5, 0.2):
        sp = M.calculate_sparsities(cs.params, "ERK", dense_ratio=dr)
        shapes = {k: v for k, v in sp.items()}
        assert shapes  # found maskable kernels
        total = kept = 0
        flat = jax.tree_util.tree_leaves_with_path(cs.params)
        for path, leaf in flat:
            name = "/".join(str(getattr(p, "key", p)) for p in path)
            if name in sp:
                total += leaf.size
                kept += leaf.size * (1 - sp[name])
        assert kept / total == pytest.approx(dr, rel=0.05)
        assert all(0.0 <= s < 1.0 for s in sp.values())


def test_uniform_sparsities():
    _, _, cs = _toy_trainer()
    sp = M.calculate_sparsities(cs.params, "uniform", dense_ratio=0.3)
    assert all(s == pytest.approx(0.7) for s in sp.values())


def test_init_masks_exact_counts_and_ones_elsewhere():
    _, _, cs = _toy_trainer()
    sp = M.calculate_sparsities(cs.params, "uniform", dense_ratio=0.5)
    masks = M.init_masks(jax.random.key(1), cs.params, sp)
    flat = jax.tree_util.tree_leaves_with_path(masks)
    for path, m in flat:
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        if name in sp:
            assert int(jnp.sum(m)) == int((1 - sp[name]) * m.size)
        else:
            assert bool(jnp.all(m == 1))


def test_fire_and_regrow_roundtrip_preserves_nnz():
    _, _, cs = _toy_trainer()
    sp = M.calculate_sparsities(cs.params, "uniform", dense_ratio=0.5)
    masks = M.init_masks(jax.random.key(1), cs.params, sp)
    grads = jax.tree.map(
        lambda p: jnp.asarray(
            np.random.default_rng(3).normal(size=p.shape), jnp.float32),
        cs.params)
    fired, num_remove = M.fire_mask(masks, cs.params, round_idx=0,
                                    comm_round=10, anneal_factor=0.5)
    # fire drops exactly num_remove per layer
    flat_m = jax.tree_util.tree_leaves_with_path(masks)
    for path, m in flat_m:
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        if name in num_remove:
            before = int(jnp.sum(m))
            after = int(jnp.sum(M._by_name(fired, name)))
            assert before - after == int(num_remove[name])
    regrown = M.regrow_mask(fired, num_remove, grads)
    for path, m in flat_m:
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        if name in num_remove:
            assert int(jnp.sum(M._by_name(regrown, name))) == int(jnp.sum(m))


def test_snip_score_equals_w_times_grad():
    _, trainer, cs = _toy_trainer()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 12, 12, 12)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=4), jnp.int32)
    scores = S.snip_scores(trainer, cs, x, y)
    _, grads, _, _ = trainer.loss_and_grad(cs, x, y)
    w = cs.params["f0"]["conv"]["kernel"]
    g = grads["f0"]["conv"]["kernel"]
    np.testing.assert_allclose(np.asarray(scores["f0"]["conv"]["kernel"]),
                               np.abs(np.asarray(w) * np.asarray(g)),
                               rtol=1e-5)
    # bias leaves get zero scores
    assert bool(jnp.all(scores["f0"]["conv"]["bias"] == 0))


def test_mask_from_scores_keep_ratio():
    _, trainer, cs = _toy_trainer()
    rng = np.random.default_rng(0)
    scores = jax.tree.map(
        lambda p: jnp.asarray(np.abs(rng.normal(size=p.shape)), jnp.float32),
        cs.params)
    masks, thr = S.mask_from_scores(scores, keep_ratio=0.3)
    total = kept = 0
    flat = jax.tree_util.tree_leaves_with_path(masks)
    for path, m in flat:
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        if M.is_weight_kernel(name, m):
            total += m.size
            kept += int(jnp.sum(m))
        else:
            assert bool(jnp.all(m == 1))
    assert kept == pytest.approx(0.3 * total, rel=0.01)


def test_flops_counter_conv_and_dense():
    model, trainer, cs = _toy_trainer()
    x = jnp.zeros((1, 12, 12, 12, 1))
    dense_flops = F.count_inference_flops(model, cs.params, x)
    # hand count (12^3 input): conv f0 VALID -> 10^3 spatial, kernel
    # 3^3*1*8=216 MACs/pos -> 2*216*1000; pool2 -> 5^3; conv f1 -> 3^3,
    # kernel 3^3*8*16=3456 -> 2*3456*27; pool2 -> 1^3, flatten 16;
    # fc1: 2*16*32; fc2: 2*32*1
    want = (2 * 216 * 1000) + (2 * 3456 * 27) + (2 * 16 * 32) + (2 * 32 * 1)
    assert dense_flops == pytest.approx(want, rel=1e-6)
    # sparsity-aware: half density halves kernel MACs
    dens = {k: 0.5 for k in F.densities_from_masks(
        jax.tree.map(jnp.ones_like, cs.params))}
    sparse_flops = F.count_inference_flops(model, cs.params, x,
                                           mask_density=dens)
    assert sparse_flops == pytest.approx(dense_flops / 2, rel=1e-6)
    assert F.count_training_flops_per_sample(model, cs.params, x) == \
        pytest.approx(3 * dense_flops)


def test_prep_channel_dim_gated_on_input_rank():
    """ADVICE r1: a 4-D [B,H,W,C] batch into a 2D model must NOT grow a
    5th dim; a 4-D [B,D,H,W] batch into a 3D model must."""
    from neuroimagedisttraining_tpu.models import CNNCifar

    t3 = LocalTrainer(Tiny3DCNN(num_classes=1), OptimConfig(), num_classes=1)
    assert t3._prep(jnp.zeros((2, 12, 12, 12))).shape == (2, 12, 12, 12, 1)
    assert t3._prep(jnp.zeros((2, 12, 12, 12, 1))).shape == (2, 12, 12, 12, 1)
    t2 = LocalTrainer(CNNCifar(num_classes=10), OptimConfig(), num_classes=10)
    assert t2._prep(jnp.zeros((2, 32, 32, 3))).shape == (2, 32, 32, 3)


def test_stratified_indices_balance_classes():
    y = jnp.asarray([0] * 90 + [1] * 10 + [0] * 28, jnp.int32)  # 28 padding
    idx = S._stratified_indices(jax.random.key(0), y, n_valid=100,
                                batch_size=2000)
    labels = np.asarray(y)[np.asarray(idx)]
    assert np.all(np.asarray(idx) < 100)          # never samples padding
    assert 0.4 < labels.mean() < 0.6              # ~50/50 despite 90/10 data


def test_kth_largest_rejects_bad_nbins():
    x = jnp.arange(512, dtype=jnp.float32)
    with pytest.raises(AssertionError):
        kth_largest(x, 5, nbins=100)


def test_fast_maxpool_matches_xla_fwd_and_bwd():
    """ops/pooling.py scatter-free non-overlapping max-pool backward ==
    XLA SelectAndScatter reference, fwd bitwise + bwd to f32 tolerance
    (ties are measure-zero on continuous inputs; see module docstring)."""
    import flax.linen as nn

    from neuroimagedisttraining_tpu.ops.pooling import max_pool_3d_nonoverlap

    x = jax.random.normal(jax.random.key(7), (2, 7, 9, 7, 3))
    np.testing.assert_array_equal(
        np.asarray(max_pool_3d_nonoverlap(x, 3)),
        np.asarray(nn.max_pool(x, (3, 3, 3), (3, 3, 3), "VALID")))

    def loss(pool):
        return lambda x: jnp.sum(pool(x) ** 2)

    g_fast = jax.grad(loss(lambda x: max_pool_3d_nonoverlap(x, 3)))(x)
    g_ref = jax.grad(loss(
        lambda x: nn.max_pool(x, (3, 3, 3), (3, 3, 3), "VALID")))(x)
    np.testing.assert_allclose(np.asarray(g_fast), np.asarray(g_ref),
                               atol=1e-6)


def test_stemconv_pallas_dw_matches_xla():
    """ops/stemconv.py split-K weight-gradient == XLA kernel-grad
    (interpret mode exercises the real kernel grid incl. the ragged-K
    tail; shapes sized so R > one 8192 block)."""
    from neuroimagedisttraining_tpu.ops import stemconv as SC

    kx, kg = jax.random.split(jax.random.key(3))
    x = jax.random.normal(kx, (4, 29, 31, 29, 1), jnp.float32)
    w = jax.random.normal(kg, (5, 5, 5, 1, 64), jnp.float32)
    g = jax.random.normal(jax.random.key(4), SC._conv(x, w).shape,
                          jnp.float32)
    dw_ref = np.asarray(SC._dw_reference(x, g))
    dw_pal = np.asarray(SC._dw_pallas(x, g, interpret=True))
    err = np.max(np.abs(dw_pal - dw_ref)) / np.max(np.abs(dw_ref))
    assert err < 2e-2, err  # bf16 products, f32 accumulation


def test_stemconv_custom_vjp_grads(monkeypatch):
    """stem_conv3d's custom VJP returns the same (dx, dw) as plain XLA
    autodiff (the CPU fallback path IS autodiff for dw; dx always the
    transposed conv), and the NIDT_FAST_STEM=1 module keeps the nn.Conv
    param tree."""
    from neuroimagedisttraining_tpu.models.neuro3d import ConvBNReLU3D
    from neuroimagedisttraining_tpu.ops import stemconv as SC

    kx, kw = jax.random.split(jax.random.key(5))
    x = jax.random.normal(kx, (2, 13, 15, 13, 1), jnp.float32)
    w = jax.random.normal(kw, (5, 5, 5, 1, 8), jnp.float32)

    def loss(f):
        return lambda x, w: jnp.sum(f(x, w) ** 2)

    gx, gw = jax.grad(loss(SC.stem_conv3d), argnums=(0, 1))(x, w)
    rx, rw = jax.grad(loss(SC._conv), argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), atol=1e-4)

    blk = ConvBNReLU3D(features=8, kernel=5, stride=2, pad=0)
    monkeypatch.setenv("NIDT_FAST_STEM", "1")
    params = blk.init(jax.random.key(6), x, train=False)
    assert set(params["params"]["conv"]) == {"kernel", "bias"}
    out_fast = blk.apply(params, x, train=False)  # env read at apply time
    monkeypatch.delenv("NIDT_FAST_STEM")
    out_ref = blk.apply(params, x, train=False)
    np.testing.assert_allclose(np.asarray(out_fast), np.asarray(out_ref),
                               atol=1e-5)


def test_fast_maxpool_tie_gradient_is_conserved():
    """Equal-split tie rule: a window of identical values (the post-ReLU
    all-zeros case) distributes the window's gradient, conserving total
    mass — sum(dx) == sum(g) regardless of tie count."""
    from neuroimagedisttraining_tpu.ops.pooling import max_pool_3d_nonoverlap

    x = jnp.zeros((1, 6, 6, 6, 2))  # every 3x3x3 window fully tied
    g = jax.grad(lambda x: jnp.sum(max_pool_3d_nonoverlap(x, 3) *
                                   jnp.arange(16.0).reshape(1, 2, 2, 2, 2)))(x)
    np.testing.assert_allclose(float(jnp.sum(g)), float(jnp.sum(jnp.arange(16.0))),
                               rtol=1e-6)
    # each element of a fully-tied window gets 1/27 of that window's grad
    np.testing.assert_allclose(np.asarray(g[0, :3, :3, :3, 0]),
                               np.full((3, 3, 3), 0.0), atol=1e-7)
    np.testing.assert_allclose(np.asarray(g[0, :3, :3, :3, 1]),
                               np.full((3, 3, 3), 1.0 / 27), rtol=1e-6)
