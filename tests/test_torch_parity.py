"""Direct numerical parity against torch (CPU) for the local-update math
the framework claims to reproduce (SURVEY hard-part #5: parity validation
without the private dataset — torch is the reference's substrate, so
matching its optimizer/loss/clip semantics bit-for-bit-ish IS the parity
proof for the trainer contract):

- torch.optim.SGD(momentum, weight_decay) update order
  (my_model_trainer.py:209,225) vs core/optim.make_local_optimizer
- torch.nn.utils.clip_grad_norm_(10) (my_model_trainer.py:224) vs our
  optax global-norm clip
- BCEWithLogitsLoss (fedavg/my_model_trainer.py:91-105) and CrossEntropyLoss
  vs core/losses.make_loss
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from neuroimagedisttraining_tpu.config import OptimConfig  # noqa: E402
from neuroimagedisttraining_tpu.core.losses import make_loss  # noqa: E402
from neuroimagedisttraining_tpu.core.optim import make_local_optimizer  # noqa: E402


def _run_torch_sgd(params0, grads_seq, lr, momentum, wd, clip):
    ps = [torch.nn.Parameter(torch.tensor(p, dtype=torch.float64))
          for p in params0]
    opt = torch.optim.SGD(ps, lr=lr, momentum=momentum, weight_decay=wd)
    for grads in grads_seq:
        opt.zero_grad()
        for p, g in zip(ps, grads):
            p.grad = torch.tensor(g, dtype=torch.float64)
        if clip > 0:
            torch.nn.utils.clip_grad_norm_(ps, clip)
        opt.step()
    return [p.detach().numpy() for p in ps]


def _run_ours(params0, grads_seq, lr, momentum, wd, clip):
    cfg = OptimConfig(lr=lr, momentum=momentum, wd=wd, grad_clip=clip)
    opt = make_local_optimizer(cfg)
    params = {f"p{i}": jnp.asarray(p) for i, p in enumerate(params0)}
    state = opt.init(params)
    for grads in grads_seq:
        g = {f"p{i}": jnp.asarray(x) for i, x in enumerate(grads)}
        updates, state = opt.update(g, state, params, jnp.float32(lr))
        params = jax.tree.map(jnp.add, params, updates)
    return [np.asarray(params[f"p{i}"]) for i in range(len(params0))]


@pytest.mark.parametrize("momentum,wd,clip", [
    (0.9, 5e-4, 10.0),   # the reference's canonical config
    (0.9, 0.0, 0.0),
    (0.0, 5e-4, 10.0),
    (0.9, 5e-4, 0.1),    # clip actually active every step
])
def test_sgd_update_matches_torch(momentum, wd, clip):
    rng = np.random.default_rng(0)
    params0 = [rng.normal(size=(4, 3)).astype(np.float32),
               rng.normal(size=(5,)).astype(np.float32)]
    grads_seq = [[rng.normal(size=p.shape).astype(np.float32) * 3
                  for p in params0] for _ in range(5)]
    want = _run_torch_sgd(params0, grads_seq, 0.01, momentum, wd, clip)
    got = _run_ours(params0, grads_seq, 0.01, momentum, wd, clip)
    for w, g in zip(want, got):
        np.testing.assert_allclose(g, w, rtol=1e-5, atol=1e-6)


def test_bce_with_logits_matches_torch():
    rng = np.random.default_rng(1)
    logits = rng.normal(size=(16, 1)).astype(np.float32)
    y = rng.integers(0, 2, size=16).astype(np.int32)
    loss_fn = make_loss(num_classes=1)
    ours = float(loss_fn(jnp.asarray(logits), jnp.asarray(y)))
    want = float(torch.nn.BCEWithLogitsLoss()(
        torch.tensor(logits).squeeze(-1), torch.tensor(y, dtype=torch.float32)))
    assert abs(ours - want) < 1e-6


def test_cross_entropy_matches_torch():
    rng = np.random.default_rng(2)
    logits = rng.normal(size=(16, 10)).astype(np.float32)
    y = rng.integers(0, 10, size=16).astype(np.int64)
    loss_fn = make_loss(num_classes=10)
    ours = float(loss_fn(jnp.asarray(logits), jnp.asarray(y)))
    want = float(torch.nn.CrossEntropyLoss()(
        torch.tensor(logits), torch.tensor(y)))
    assert abs(ours - want) < 1e-6


def test_grad_clip_matches_torch_global_norm():
    rng = np.random.default_rng(3)
    grads = [rng.normal(size=(6, 2)).astype(np.float32) * 50,
             rng.normal(size=(7,)).astype(np.float32) * 50]
    # torch: clip to total norm 10 across ALL tensors
    ts = [torch.nn.Parameter(torch.zeros(g.shape)) for g in grads]
    for t, g in zip(ts, grads):
        t.grad = torch.tensor(g)
    torch.nn.utils.clip_grad_norm_(ts, 10.0)
    want = [t.grad.numpy() for t in ts]
    # ours via one momentum-free, wd-free step at lr=1 => update == -clipped
    got = _run_ours([np.zeros_like(g) for g in grads], [grads],
                    lr=1.0, momentum=0.0, wd=0.0, clip=10.0)
    for w, g in zip(want, got):
        np.testing.assert_allclose(-g, w, rtol=1e-5, atol=1e-6)


def test_local_train_shuffle_matches_torch_epoch_walk():
    """WHOLE local_train parity in the default shuffle mode: given the
    same per-epoch permutations, E epochs of the jitted scan == a torch
    loop walking the shuffled epoch in batch_size strides (reference
    my_model_trainer.py:213-236), INCLUDING the weighted partial final
    batch (n % B != 0) and the masked no-op steps beyond the quota."""
    from neuroimagedisttraining_tpu.core.trainer import (
        ClientState, LocalTrainer, epoch_permutations, shuffle_batch_indices,
    )
    import flax.linen as nn

    class TinyMLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(16)(x))
            return nn.Dense(10)(x)

    n, b, max_samples, epochs = 20, 8, 32, 2  # last batch = 4 rows
    lr, momentum, wd, clip = 0.05, 0.9, 5e-4, 10.0
    rng = np.random.default_rng(11)
    X = np.zeros((max_samples, 6), np.float32)
    X[:n] = rng.normal(size=(n, 6)).astype(np.float32)
    y = np.zeros((max_samples,), np.int32)
    y[:n] = rng.integers(0, 10, n)

    cfg = OptimConfig(lr=lr, momentum=momentum, wd=wd, grad_clip=clip,
                      batch_size=b, epochs=epochs, batch_order="shuffle")
    trainer = LocalTrainer(TinyMLP(), cfg, num_classes=10)
    cs = trainer.init_client_state(jax.random.key(5), jnp.asarray(X[:1]))
    new_cs, _ = trainer.local_train(cs, jnp.asarray(X), jnp.asarray(y),
                                    jnp.int32(n), jnp.float32(lr),
                                    epochs=epochs, batch_size=b,
                                    max_samples=max_samples)

    # reconstruct the trainer's own permutations from its rng split
    prng = jax.random.split(cs.rng)[1]
    perms = epoch_permutations(prng, epochs, max_samples, n)
    steps_per_epoch = -(-max_samples // b)

    k0 = np.asarray(cs.params["Dense_0"]["kernel"])
    ps = [torch.nn.Parameter(torch.tensor(np.asarray(v)))
          for v in (cs.params["Dense_0"]["kernel"],
                    cs.params["Dense_0"]["bias"],
                    cs.params["Dense_1"]["kernel"],
                    cs.params["Dense_1"]["bias"])]

    def fwd(xb):
        h = torch.relu(xb @ ps[0] + ps[1])
        return h @ ps[2] + ps[3]

    opt = torch.optim.SGD(ps, lr=lr, momentum=momentum, weight_decay=wd)
    X_t, y_t = torch.tensor(X), torch.tensor(y.astype(np.int64))
    for t in range(epochs * steps_per_epoch):
        idx, w = shuffle_batch_indices(perms, t, steps_per_epoch, b, n)
        keep = np.asarray(idx)[np.asarray(w) > 0]
        if len(keep) == 0:  # masked no-op step beyond the quota
            continue
        opt.zero_grad()
        loss = torch.nn.CrossEntropyLoss()(fwd(X_t[keep]), y_t[keep])
        loss.backward()
        torch.nn.utils.clip_grad_norm_(ps, clip)
        opt.step()

    got = [np.asarray(v) for v in (new_cs.params["Dense_0"]["kernel"],
                                   new_cs.params["Dense_0"]["bias"],
                                   new_cs.params["Dense_1"]["kernel"],
                                   new_cs.params["Dense_1"]["bias"])]
    assert not np.allclose(got[0], k0)  # training actually moved params
    for g, p in zip(got, ps):
        np.testing.assert_allclose(g, p.detach().numpy(),
                                   rtol=2e-4, atol=2e-5)


def test_local_train_prox_matches_torch_epoch_walk():
    """FedProx local objective parity: the same epoch walk as above with a
    post-step proximal pull ``w -= lr * mu * (w - w_ref)`` on BOTH sides
    (the reference's Ditto-trainer update, ditto/my_model_trainer.py:63-64,
    referenced to a fixed incoming global model as FedProx prescribes)."""
    from neuroimagedisttraining_tpu.core.trainer import (
        LocalTrainer, epoch_permutations, shuffle_batch_indices,
    )
    import flax.linen as nn

    class TinyMLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(16)(x))
            return nn.Dense(10)(x)

    n, b, max_samples, epochs = 20, 8, 32, 2
    lr, momentum, wd, clip, mu = 0.05, 0.9, 5e-4, 10.0, 0.7
    rng = np.random.default_rng(13)
    X = np.zeros((max_samples, 6), np.float32)
    X[:n] = rng.normal(size=(n, 6)).astype(np.float32)
    y = np.zeros((max_samples,), np.int32)
    y[:n] = rng.integers(0, 10, n)

    cfg = OptimConfig(lr=lr, momentum=momentum, wd=wd, grad_clip=clip,
                      batch_size=b, epochs=epochs, batch_order="shuffle")
    trainer = LocalTrainer(TinyMLP(), cfg, num_classes=10)
    cs = trainer.init_client_state(jax.random.key(6), jnp.asarray(X[:1]))
    # prox reference = a DIFFERENT point than the start (as in a real round,
    # where the client may start from its personal state)
    ref = jax.tree.map(
        lambda p: p + 0.1 * jnp.asarray(
            np.random.default_rng(21).normal(size=p.shape), jnp.float32),
        cs.params)
    new_cs, _ = trainer.local_train(cs, jnp.asarray(X), jnp.asarray(y),
                                    jnp.int32(n), jnp.float32(lr),
                                    epochs=epochs, batch_size=b,
                                    max_samples=max_samples,
                                    prox_lamda=mu, prox_ref=ref)

    prng = jax.random.split(cs.rng)[1]
    perms = epoch_permutations(prng, epochs, max_samples, n)
    steps_per_epoch = -(-max_samples // b)

    names = [("Dense_0", "kernel"), ("Dense_0", "bias"),
             ("Dense_1", "kernel"), ("Dense_1", "bias")]
    ps = [torch.nn.Parameter(torch.tensor(np.asarray(cs.params[m][k])))
          for m, k in names]
    refs = [torch.tensor(np.asarray(ref[m][k])) for m, k in names]

    def fwd(xb):
        h = torch.relu(xb @ ps[0] + ps[1])
        return h @ ps[2] + ps[3]

    opt = torch.optim.SGD(ps, lr=lr, momentum=momentum, weight_decay=wd)
    X_t, y_t = torch.tensor(X), torch.tensor(y.astype(np.int64))
    for t in range(epochs * steps_per_epoch):
        idx, w = shuffle_batch_indices(perms, t, steps_per_epoch, b, n)
        keep = np.asarray(idx)[np.asarray(w) > 0]
        if len(keep) == 0:
            continue
        opt.zero_grad()
        loss = torch.nn.CrossEntropyLoss()(fwd(X_t[keep]), y_t[keep])
        loss.backward()
        torch.nn.utils.clip_grad_norm_(ps, clip)
        opt.step()
        with torch.no_grad():  # the proximal pull after each step
            for p, r in zip(ps, refs):
                p.data -= lr * mu * (p.data - r)

    for (m, k), p in zip(names, ps):
        np.testing.assert_allclose(np.asarray(new_cs.params[m][k]),
                                   p.detach().numpy(), rtol=2e-4, atol=2e-5)


def _torch_sepconv(c, k, stride, w):
    """Reference SepConv (operations.py:55-71) rebuilt in torch with the
    given flax weights: dw-conv(k,s) -> 1x1 -> BN -> relu -> dw-conv(k,1)
    -> 1x1 -> BN (BNs affine=False, eval-mode identity stats)."""
    pad = (k - 1) // 2
    m = torch.nn.Sequential(
        torch.nn.Conv2d(c, c, k, stride, pad, groups=c, bias=False),
        torch.nn.Conv2d(c, c, 1, bias=False),
        torch.nn.BatchNorm2d(c, affine=False),
        torch.nn.ReLU(),
        torch.nn.Conv2d(c, c, k, 1, pad, groups=c, bias=False),
        torch.nn.Conv2d(c, c, 1, bias=False),
        torch.nn.BatchNorm2d(c, affine=False),
    )
    convs = [m[0], m[1], m[4], m[5]]
    for tconv, fw in zip(convs, w):
        # flax [kh, kw, in/groups, out] -> torch [out, in/groups, kh, kw]
        tconv.weight.data = torch.tensor(
            np.transpose(np.asarray(fw), (3, 2, 0, 1)))
    return m.eval()


def test_darts_sepconv_matches_torch_reference():
    """DARTS SepConv forward == the reference torch operator with shared
    weights (BN in batch-stats mode on both sides; relu leading both)."""
    from neuroimagedisttraining_tpu.models.darts import SepConv

    c, k = 4, 3
    x = np.random.default_rng(0).normal(size=(2, 8, 8, c)).astype(np.float32)
    op = SepConv(c_out=c, kernel=k, stride=1, affine=False)
    params = op.init(jax.random.key(0), jnp.asarray(x), train=True)["params"]
    ours = np.asarray(op.apply({"params": params}, jnp.asarray(x), train=True))

    w = [params[f"Conv_{i}"]["kernel"] for i in range(4)]
    tm = _torch_sepconv(c, k, 1, w)
    xt = torch.tensor(np.transpose(x, (0, 3, 1, 2)))
    with torch.no_grad():
        # torch pre-op relu (the reference's op starts with ReLU), and
        # train-mode BN (batch statistics) to match the search-mode _BN
        h = torch.relu(xt)
        h = tm[0](h); h = tm[1](h)
        h = torch.nn.functional.batch_norm(h, None, None, training=True)
        h = torch.relu(h)
        h = tm[4](h); h = tm[5](h)
        h = torch.nn.functional.batch_norm(h, None, None, training=True)
    want = np.transpose(h.numpy(), (0, 2, 3, 1))
    np.testing.assert_allclose(ours, want, atol=2e-5)


def test_darts_pools_match_torch_reference():
    """avg_pool_3x3 replicates torch count_include_pad=False; max_pool_3x3
    replicates torch MaxPool2d(3, stride, padding=1)."""
    from neuroimagedisttraining_tpu.models.darts import (
        avg_pool_3x3, max_pool_3x3,
    )

    x = np.random.default_rng(1).normal(size=(2, 9, 9, 3)).astype(np.float32)
    xt = torch.tensor(np.transpose(x, (0, 3, 1, 2)))
    for stride in (1, 2):
        got_a = np.asarray(avg_pool_3x3(jnp.asarray(x), stride))
        want_a = torch.nn.AvgPool2d(3, stride, 1, count_include_pad=False)(xt)
        np.testing.assert_allclose(
            got_a, np.transpose(want_a.numpy(), (0, 2, 3, 1)), atol=1e-6)
        got_m = np.asarray(max_pool_3x3(jnp.asarray(x), stride))
        want_m = torch.nn.MaxPool2d(3, stride, 1)(xt)
        np.testing.assert_allclose(
            got_m, np.transpose(want_m.numpy(), (0, 2, 3, 1)), atol=1e-6)
