"""DARTS suite: search supernet, bilevel step, genotype derivation,
fixed-genotype network, GDAS gumbel path, meta models.

Shapes are kept tiny (C=4, 2-3 cells, 8x8 or 16x16 inputs) — the point is
semantics, not capacity: reference model_search.py / model.py / architect.py
/ cnn_meta.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.models.darts import (
    DARTS_V2,
    DartsNetwork,
    DartsSearch,
    DartsSearchNet,
    PRIMITIVES,
    arch_grad_regularized,
    arch_grad_unrolled,
    derive_genotype,
    num_edges,
    split_arch,
)
from neuroimagedisttraining_tpu.models.meta import CNNCifarMeta, MetaNet


def _tiny_net(**kw):
    return DartsSearchNet(c=4, num_classes=10, layers=3, steps=2,
                          multiplier=2, **kw)


@pytest.fixture(scope="module")
def search_setup():
    net = _tiny_net()
    x = jax.random.normal(jax.random.key(0), (2, 16, 16, 3))
    params = net.init(jax.random.key(1), x, train=False)["params"]
    return net, x, params


def test_search_net_forward_and_alpha_shapes(search_setup):
    net, x, params = search_setup
    k = num_edges(2)
    assert params["alphas_normal"].shape == (k, len(PRIMITIVES))
    assert params["alphas_reduce"].shape == (k, len(PRIMITIVES))
    logits = net.apply({"params": params}, x, train=True)
    assert logits.shape == (2, 10)
    assert np.all(np.isfinite(np.asarray(logits)))


@pytest.mark.slow
def test_bilevel_search_step_moves_alphas_and_weights(search_setup):
    net, x, _ = search_setup
    y = jnp.array([1, 3])
    search = DartsSearch(net, num_classes=10, total_steps=4)
    state = search.init(jax.random.key(2), x)
    a0, w0 = split_arch(state["params"])
    state, loss = search.step(state, (x, y), (x, y))
    a1, w1 = split_arch(state["params"])
    assert np.isfinite(float(loss))
    # arch Adam step moved alphas; weight SGD step moved weights
    assert not np.allclose(np.asarray(a0["alphas_normal"]),
                           np.asarray(a1["alphas_normal"]))
    moved = jax.tree.leaves(jax.tree.map(
        lambda p, q: float(jnp.max(jnp.abs(p - q))), w0, w1))
    assert max(moved) > 0


@pytest.mark.slow
def test_arch_grads_unrolled_vs_regularized(search_setup):
    net, x, params = search_setup
    y = jnp.array([0, 2])

    def loss_fn(p, batch):
        bx, by = batch
        logits = net.apply({"params": p}, bx, train=True)
        lab = jax.nn.one_hot(by, 10)
        return jnp.mean(-jnp.sum(lab * jax.nn.log_softmax(logits), -1))

    g_u = arch_grad_unrolled(loss_fn, params, (x, y), (x, y), eta=0.025)
    g_r = arch_grad_regularized(loss_fn, params, (x, y), (x, y))
    for g in (g_u, g_r):
        assert set(g) == {"alphas_normal", "alphas_reduce"}
        assert all(np.all(np.isfinite(np.asarray(v))) for v in g.values())
    # the unrolled (2nd-order) gradient differs from the 1st-order one
    assert not np.allclose(np.asarray(g_u["alphas_normal"]),
                           np.asarray(g_r["alphas_normal"]))


def test_derive_genotype_semantics(search_setup):
    _, _, params = search_setup
    geno = derive_genotype(params["alphas_normal"], params["alphas_reduce"],
                           steps=2, multiplier=2)
    # 2 edges per node x 2 nodes, never 'none', indices point at valid
    # predecessor states (model_search.py:266-283)
    for gene in (geno.normal, geno.reduce):
        assert len(gene) == 4
        for pos, (op, idx) in enumerate(gene):
            assert op in PRIMITIVES and op != "none"
            assert 0 <= idx < 2 + pos // 2
    assert list(geno.normal_concat) == [2, 3]


def test_gdas_gumbel_hard_mixture(search_setup):
    _, x, _ = search_setup
    net = _tiny_net(gumbel=True)
    params = net.init({"params": jax.random.key(3),
                       "gumbel": jax.random.key(4)}, x, train=False)["params"]
    logits = net.apply({"params": params}, x, train=True, tau=0.5,
                       rngs={"gumbel": jax.random.key(5)})
    assert logits.shape == (2, 10)
    assert np.all(np.isfinite(np.asarray(logits)))
    # eval path is deterministic (argmax one-hot, no rng needed)
    e1 = net.apply({"params": params}, x, train=False)
    e2 = net.apply({"params": params}, x, train=False)
    assert np.allclose(np.asarray(e1), np.asarray(e2))


def test_fixed_network_from_genotype_with_aux():
    net = DartsNetwork(genotype=DARTS_V2, c=4, num_classes=10, layers=3,
                       auxiliary=True)
    x = jax.random.normal(jax.random.key(0), (2, 32, 32, 3))
    variables = net.init({"params": jax.random.key(1),
                          "droppath": jax.random.key(2)}, x, train=False)
    logits, aux = net.apply(variables, x, train=True, drop_path_prob=0.2,
                            rngs={"droppath": jax.random.key(3)},
                            mutable=["batch_stats"])[0]
    assert logits.shape == (2, 10)
    assert aux is not None and aux.shape == (2, 10)
    # eval mode: running stats consumed, no aux head
    logits_e, aux_e = net.apply(variables, x, train=False)
    assert logits_e.shape == (2, 10) and aux_e is None


def test_meta_models():
    model = CNNCifarMeta(num_classes=10)
    x = jax.random.normal(jax.random.key(0), (2, 32, 32, 3))
    params = model.init(jax.random.key(1), x)["params"]
    masks = CNNCifarMeta.init_masks(jax.random.key(2), params,
                                    dense_ratio=0.2)
    assert set(masks) == {"meta_conv1", "meta_conv2", "meta_fc1"}
    for name, m in masks.items():
        n = m.size
        assert int(np.asarray(m).sum()) == int(0.2 * n)  # exact density
    dense = model.apply({"params": params}, x)
    sparse = model.apply({"params": params}, x, masks=masks)
    assert dense.shape == sparse.shape == (2, 10)
    assert not np.allclose(np.asarray(dense), np.asarray(sparse))

    # hypernetwork: mask -> weight tensor of the same shape
    hyper = MetaNet()
    m = masks["meta_conv1"]
    hp = hyper.init(jax.random.key(3), m)
    w = hyper.apply(hp, m)
    assert w.shape == m.shape
    assert np.all(np.isfinite(np.asarray(w)))


def test_resnet_meta_slimmable_widths():
    """ResNetMeta (resnet_meta_2.py analog): one parameter set serves every
    width in CHANNEL_SCALE; kernels are hypernetwork-generated from the
    scale vector and inactive channels are hard-masked to zero."""
    from neuroimagedisttraining_tpu.models.meta import CHANNEL_SCALE, ResNetMeta

    assert len(CHANNEL_SCALE) == 31                    # resnet_meta_2.py:8-10
    assert CHANNEL_SCALE[0] == 0.10 and CHANNEL_SCALE[-1] == 1.00

    model = ResNetMeta(num_classes=10)
    x = jax.random.normal(jax.random.key(0), (2, 32, 32, 3))
    variables = model.init(jax.random.key(1), x)
    full = model.apply(variables, x, train=False)
    assert full.shape == (2, 10)
    assert np.all(np.isfinite(np.asarray(full)))

    # a narrow width id produces a DIFFERENT function from the same params
    narrow_ids = jnp.zeros((4,), jnp.int32)      # 0.10 width everywhere
    narrow_mid = jnp.zeros((3,), jnp.int32)
    narrow = model.apply(variables, x, stage_ids=narrow_ids,
                         mid_ids=narrow_mid, train=False)
    assert narrow.shape == (2, 10)
    assert not np.allclose(np.asarray(full), np.asarray(narrow))

    # the whole width sweep is ONE jitted program (scale ids are traced)
    f = jax.jit(lambda sid, mid: model.apply(variables, x, stage_ids=sid,
                                             mid_ids=mid, train=False))
    a = f(narrow_ids, narrow_mid)
    b = f(jnp.full((4,), 30, jnp.int32), jnp.full((3,), 30, jnp.int32))
    np.testing.assert_allclose(np.asarray(a), np.asarray(narrow),
                               rtol=2e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(b), np.asarray(full),
                               rtol=2e-5, atol=1e-5)

    # train mode collects batch stats like the reference's affine-less BNs
    out, mut = model.apply(variables, x, train=True,
                           mutable=["batch_stats"])
    assert "batch_stats" in mut


def test_darts_trainer_step():
    """DartsTrainer (train.py semantics): aux-weighted loss, scheduled
    drop-path inside one jitted step; loss finite, params move, batch
    stats update."""
    from neuroimagedisttraining_tpu.models.darts import DartsTrainer

    net = DartsNetwork(genotype=DARTS_V2, c=4, num_classes=10, layers=3,
                       auxiliary=True)
    x = jax.random.normal(jax.random.key(0), (2, 32, 32, 3))
    y = jnp.array([1, 7])
    tr = DartsTrainer(net, num_classes=10, total_steps=4)
    state = tr.init(jax.random.key(1), x)
    p0 = jax.tree.leaves(state["variables"]["params"])[0]
    state, loss = tr.step(state, (x, y), jax.random.key(2))
    assert np.isfinite(float(loss))
    p1 = jax.tree.leaves(state["variables"]["params"])[0]
    assert not np.allclose(np.asarray(p0), np.asarray(p1))
    assert int(state["step"]) == 1
