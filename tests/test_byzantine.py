"""Byzantine value faults + robust aggregation (ISSUE 5): the ``byz:``
fault grammar and adversary transforms, the order-statistic aggregators'
breakdown points, the engines' non-finite upload guard, fused-dispatch
bitwise parity with a defense enabled, and the cross-silo server's
detection/quarantine control plane."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.core import robust
from neuroimagedisttraining_tpu.distributed.cross_silo import (
    FedAvgClientProc,
    FedAvgServer,
    SecureFedAvgServer,
    survivor_defended_mean,
    tree_all_finite,
    update_outlier_flags,
)
from neuroimagedisttraining_tpu.distributed.ports import free_port_block
from neuroimagedisttraining_tpu.faults import (
    FaultSchedule,
    adversary,
    parse_byz_kind,
    parse_fault_spec,
)
from neuroimagedisttraining_tpu.utils import pytree as pt


# ------------------------------------------------- byz grammar + schedule


def test_parse_byz_spec_grammar():
    spec = parse_fault_spec("byz:1@0:sign_flip,byz:3@2:scale:10,"
                            "byz_prob:0.25:gauss:0.5,crash:2@1")
    assert spec.byz == ((1, 0, "sign_flip"), (3, 2, "scale:10.0"))
    assert spec.byz_prob == 0.25
    assert spec.byz_kind == "gauss:0.5"
    assert spec.crashes == ((2, 1),)
    assert spec.any_faults and spec.any_value_faults
    # omission-only specs carry no value faults
    assert not parse_fault_spec("crash:2@1,drop:0.5").any_value_faults
    assert parse_byz_kind("nonfinite") == "nonfinite"
    assert parse_byz_kind("scale: -4 ") == "scale:-4.0"


def test_parse_byz_spec_malformed_fails_loudly():
    for bad in ("byz:1@0", "byz:1@0:evil", "byz:1@0:scale",
                "byz:1@0:gauss:-1", "byz:1@0:sign_flip:2",
                "byz_prob:1.5", "byz_prob:0.2:bogus"):
        with pytest.raises(ValueError):
            parse_fault_spec(bad)


def test_byz_schedule_deterministic_and_permanent():
    spec = parse_fault_spec("byz:2@1:sign_flip,byz_prob:0.3:scale:5")
    a = FaultSchedule(spec, seed=7)
    b = FaultSchedule(spec, seed=7)
    got = [[a.byzantine_kind(r, c) for c in range(1, 5)] for r in range(6)]
    assert got == [[b.byzantine_kind(r, c) for c in range(1, 5)]
                   for r in range(6)]
    # the deterministic directive is permanent from its round on and
    # wins over the probabilistic draw
    assert a.byzantine_kind(0, 2) in (None, "scale:5.0")
    for r in range(1, 6):
        assert a.byzantine_kind(r, 2) == "sign_flip"
    # a different seed redraws the transient stream
    c = FaultSchedule(spec, seed=8)
    trans = [(r, k) for r in range(20) for k in (1, 3, 4)]
    assert [a.byzantine_kind(r, k) for r, k in trans] != \
        [c.byzantine_kind(r, k) for r, k in trans]


# ------------------------------------------------- adversary transforms


def _toy_tree(rng, scale=1.0):
    return {"w": np.asarray(rng.normal(size=(4, 3)) * scale, np.float32),
            "b": np.asarray(rng.normal(size=(5,)) * scale, np.float32)}


def test_adversary_kinds_math():
    rng = np.random.default_rng(0)
    ref = _toy_tree(rng)
    u = {k: v + np.float32(0.5) for k, v in ref.items()}
    sched = FaultSchedule(parse_fault_spec("byz:1@0:sign_flip"), seed=0)

    flip = adversary.attack_update(sched, 0, 0, 1, u, ref)
    for k in ref:
        # sign_flip: ref - (u - ref)
        np.testing.assert_allclose(flip[k], ref[k] - (u[k] - ref[k]),
                                   rtol=1e-6)
    sched = FaultSchedule(parse_fault_spec("byz:1@0:scale:-10"), seed=0)
    sc = adversary.attack_update(sched, 0, 0, 1, u, ref)
    for k in ref:
        np.testing.assert_allclose(sc[k], ref[k] - 10 * (u[k] - ref[k]),
                                   rtol=1e-5)
    sched = FaultSchedule(parse_fault_spec("byz:1@0:nonfinite"), seed=0)
    bad = adversary.attack_update(sched, 0, 0, 1, u, ref)
    assert all(np.isnan(v).all() for v in bad.values())
    # honest rank / pre-attack round: the upload passes through BITWISE
    sched = FaultSchedule(parse_fault_spec("byz:1@3:sign_flip"), seed=0)
    for (r, c) in ((0, 1), (3, 2)):
        out = adversary.attack_update(sched, 0, r, c, u, ref)
        for k in ref:
            np.testing.assert_array_equal(out[k], u[k])


def test_adversary_stacked_matches_per_client_path():
    """The engines' vmapped plan path and the cross-silo client's eager
    ``attack_update`` inject bitwise-identical values — gauss noise
    included (one seed, one attack trace in both federations)."""
    rng = np.random.default_rng(1)
    ref = _toy_tree(rng)
    ups = [_toy_tree(rng) for _ in range(4)]
    sched = FaultSchedule(
        parse_fault_spec("byz:2@0:gauss:0.3,byz:4@0:sign_flip"), seed=5)
    ranks = np.arange(1, 5)
    mult, std, nan = adversary.plan_arrays(sched, 0, ranks)
    np.testing.assert_array_equal(mult, np.float32([1, 1, 1, -1]))
    np.testing.assert_array_equal(std, np.float32([0, 0.3, 0, 0]))
    keys = adversary.attack_keys(5, 0, ranks)
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *ups)
    got = adversary.apply_attack_stacked(stacked, ref, jnp.asarray(mult),
                                         jnp.asarray(std),
                                         jnp.asarray(nan), keys)
    for i, u in enumerate(ups):
        want = adversary.attack_update(sched, 5, 0, i + 1, u, ref)
        for k in ref:
            np.testing.assert_array_equal(
                np.asarray(got[k][i]), np.asarray(want[k]))


# ------------------------------------------------- robust aggregators


def _stack(trees):
    return jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x)
                                               for x in xs]), *trees)


def test_trimmed_mean_discards_planted_outliers():
    honest = [{"w": jnp.full((3,), float(v))} for v in (1.0, 2.0, 3.0)]
    byz = [{"w": jnp.full((3,), 1e6)}, {"w": jnp.full((3,), -1e6)}]
    stacked = _stack(honest + byz)
    w = jnp.ones((5,), jnp.float32)
    out = robust.trimmed_mean(stacked, w, f=2)  # 2f < n = 5
    np.testing.assert_allclose(np.asarray(out["w"]), 2.0, rtol=1e-6)
    # weighted: surviving coordinates renormalize the sample weights
    w2 = jnp.asarray([1.0, 3.0, 1.0, 7.0, 7.0], jnp.float32)
    out2 = robust.trimmed_mean(stacked, w2, f=2)
    np.testing.assert_allclose(np.asarray(out2["w"]), 2.0, rtol=1e-6)


def test_trimmed_mean_zero_weight_rows_never_vote():
    """Zero-weight rows (non-finite uploads sanitized to the broadcast
    reference, streaming mesh pads) are not client updates: they must
    not occupy trim slots — a kept window holding ONLY zero-weight rows
    used to 0/eps-collapse the coordinate to 0.0."""
    # C=3, f=1: honest at 1 and 3 (w>0), a sanitized reference row at 2
    # (w=0) — the old positional trim kept exactly the w=0 row
    stacked = _stack([{"w": jnp.full((2,), 1.0)},
                      {"w": jnp.full((2,), 2.0)},
                      {"w": jnp.full((2,), 3.0)}])
    w = jnp.asarray([1.0, 0.0, 1.0], jnp.float32)
    out = robust.trimmed_mean(stacked, w, f=1)
    np.testing.assert_allclose(np.asarray(out["w"]), 2.0, rtol=1e-6)
    # a voting cohort deep enough to really trim still sheds the outlier
    stacked5 = _stack([{"w": jnp.full((2,), v)}
                       for v in (1.0, 2.0, 3.0, 1e6, 2.0)])
    w5 = jnp.asarray([1.0, 1.0, 1.0, 1.0, 0.0], jnp.float32)
    out5 = robust.trimmed_mean(stacked5, w5, f=1)
    np.testing.assert_allclose(np.asarray(out5["w"]), 2.5, rtol=1e-6)
    # pathological all-zero cohort degrades to the uniform trimmed mean
    out0 = robust.trimmed_mean(stacked, jnp.zeros((3,), jnp.float32), f=1)
    np.testing.assert_allclose(np.asarray(out0["w"]), 2.0, rtol=1e-6)
    # the weighted median shares the fallback (masking EVERY row past
    # the voting window used to return +inf and destroy the model)
    med0 = robust.coordinate_median(stacked, jnp.zeros((3,), jnp.float32))
    np.testing.assert_allclose(np.asarray(med0["w"]), 2.0, rtol=1e-6)


def test_krum_mechanical_floor_vs_blanchard_bound():
    """n >= f+3 is the mechanical floor (selection defined); the
    provable Blanchard guarantee needs n >= 2f+3 — in the gap the
    defense runs but ``effective_defense`` warns that f colluding
    attackers can win the selection."""
    calls = []

    def warn(msg, *a):
        calls.append(msg % a if a else msg)

    assert robust.effective_defense("krum", 4, 1, warn=warn) == "krum"
    assert any("2f+3" in c for c in calls)
    calls.clear()
    assert robust.effective_defense("krum", 5, 1, warn=warn) == "krum"
    assert not calls  # at/above the provable bound: silent
    assert robust.effective_defense("krum", 3, 1, warn=warn) == "none"
    assert calls  # below the mechanical floor: falls back with warning


def test_coordinate_median_breakdown():
    honest = [{"w": jnp.asarray([1.0, 5.0])}, {"w": jnp.asarray([2.0, 6.0])},
              {"w": jnp.asarray([3.0, 7.0])}]
    byz = [{"w": jnp.asarray([1e8, -1e8])}]
    out = robust.coordinate_median(_stack(honest + byz))
    got = np.asarray(out["w"])
    assert 1.0 <= got[0] <= 3.0 and 5.0 <= got[1] <= 7.0


def test_krum_selects_honest_cluster():
    rng = np.random.default_rng(3)
    honest = [{"w": jnp.asarray(rng.normal(size=(6,)) * 0.1 + 1.0,
                                jnp.float32)} for _ in range(4)]
    byz = [{"w": jnp.full((6,), -50.0)}]
    stacked = _stack(honest + byz)
    w = jnp.ones((5,), jnp.float32)
    sel = robust.krum_select(stacked, w, f=1, m=1)
    assert int(sel[0]) < 4  # never the planted outlier
    out = robust.krum(stacked, w, f=1)
    assert abs(float(np.asarray(out["w"]).mean()) - 1.0) < 0.5
    multi = robust.krum(stacked, w, f=1, multi=True)
    assert abs(float(np.asarray(multi["w"]).mean()) - 1.0) < 0.5
    # zero-weight rows (sanitized non-finite uploads) leave the selection
    w0 = jnp.asarray([0.0, 1.0, 1.0, 1.0, 1.0], jnp.float32)
    sel0 = robust.krum_select(stacked, w0, f=1, m=4)
    assert 0 not in set(np.asarray(sel0).tolist())


def test_geometric_median_resists_outlier():
    honest = [{"w": jnp.full((4,), float(v))} for v in (0.9, 1.0, 1.1)]
    byz = [{"w": jnp.full((4,), 1e5)}]
    out = robust.geometric_median(_stack(honest + byz),
                                  jnp.ones((4,), jnp.float32), iters=32)
    got = float(np.asarray(out["w"]).mean())
    assert 0.8 < got < 1.3  # the mean would sit at ~25000


def test_breakdown_point_checks_fail_loudly():
    with pytest.raises(ValueError):
        robust._check_f(4, 2, "trimmed_mean")  # 2f >= n
    with pytest.raises(ValueError):
        robust._check_f(3, 1, "krum")          # n < f + 3
    with pytest.raises(ValueError):
        robust._check_f(4, -1, "median")
    assert robust._check_f(5, 2, "median") == 2
    with pytest.raises(ValueError):
        robust.validate_defense("bogus_defense")
    with pytest.raises(ValueError):
        robust.robust_aggregate(_stack([{"w": jnp.ones(2)}] * 4),
                                jnp.ones((4,)), defense="weak_dp", byz_f=1)


def test_aggregate_with_defense_dispatch():
    """One entry point: the clip family clips-then-means; the order-
    statistic family ignores the mean entirely."""
    rng = np.random.default_rng(4)
    ref = {k: jnp.asarray(v) for k, v in _toy_tree(rng).items()}
    honest = [jax.tree.map(
        lambda x: x + jnp.float32(0.01) * (i + 1), ref) for i in range(3)]
    byz = [jax.tree.map(lambda x: x + jnp.float32(1e4), ref)]
    stacked = _stack(honest + byz)
    w = jnp.ones((4,), jnp.float32)
    mean = robust.aggregate_with_defense(stacked, ref, w, defense="none")
    for a, b in zip(jax.tree.leaves(mean),
                    jax.tree.leaves(pt.tree_weighted_mean(stacked, w))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    trimmed = robust.aggregate_with_defense(stacked, ref, w,
                                            defense="trimmed_mean",
                                            byz_f=1)
    err = float(pt.tree_norm(pt.tree_sub(trimmed, ref)))
    assert err < 1.0  # the undefended mean would sit ~2500 away
    clipped = robust.aggregate_with_defense(
        stacked, ref, w, defense="norm_diff_clipping", norm_bound=0.5)
    assert float(pt.tree_norm(pt.tree_sub(clipped, ref))) <= 0.5 + 1e-4


def test_finite_per_client_and_replacement():
    ref = {"w": jnp.ones((2, 2), jnp.float32), "b": jnp.zeros(3)}
    rows = [jax.tree.map(lambda x: x * (i + 1), ref) for i in range(3)]
    rows[1] = {"w": jnp.full((2, 2), jnp.nan), "b": jnp.zeros(3)}
    stacked = _stack(rows)
    finite = robust.finite_per_client(stacked)
    np.testing.assert_array_equal(np.asarray(finite), [True, False, True])
    fixed = robust.replace_nonfinite_clients(stacked, ref, finite)
    np.testing.assert_array_equal(np.asarray(fixed["w"][1]),
                                  np.asarray(ref["w"]))
    np.testing.assert_array_equal(np.asarray(fixed["w"][0]),
                                  np.asarray(stacked["w"][0]))
    assert tree_all_finite(fixed)
    assert not tree_all_finite(stacked)


# ------------------------------------------------- engine integration


@pytest.mark.slow  # tier-1 window (PR 7): single-engine behavioral e2e, engine keeps dispatch/stream/cohort coverage
def test_engine_nonfinite_guard_independent_of_defense(tmp_path,
                                                       synthetic_cohort):
    """A silo uploading NaN every round must not poison the aggregate —
    with --defense none. The guard zero-weights the row and emits the
    counted warning (ISSUE 5 satellite)."""
    from tests.test_fedavg import _make_engine

    engine = _make_engine(tmp_path, synthetic_cohort, comm_round=2,
                          fault_spec="byz:1@0:nonfinite")
    result = engine.train()
    assert all(np.isfinite(np.asarray(x)).all()
               for x in jax.tree.leaves(result["params"]))
    assert np.isfinite(result["history"][-1]["train_loss"])
    # one rejection per round, counted into stat_info
    assert engine.stat_info["nonfinite_uploads"] >= 2


def test_wire_codec_ef_resets_for_nonfinite_uploads(tmp_path,
                                                    synthetic_cohort):
    """A non-finite upload must not park NaN in the codec error-feedback
    stack: EF = u - decode(u) of a NaN row is NaN, every later encode
    consumes it, and a one-round value fault would zero-weight the
    client FOREVER. The round zeroes those EF rows instead."""
    from tests.test_fedavg import _make_engine

    e = _make_engine(tmp_path, synthetic_cohort,
                     fault_spec="byz:1@0:nonfinite",
                     wire_codec="delta+sparse+quant")
    e._donate = False
    gs = e.init_global_state()
    sampled = e.client_sampling(0)
    rngs = e.per_client_rngs(0, np.asarray(sampled))
    byz = e._byz_round_plan(0, np.asarray(sampled))
    assert byz is not None
    efs = jax.tree.map(
        lambda x: jnp.zeros((len(sampled),) + x.shape, jnp.float32),
        {"params": gs.params, "batch_stats": gs.batch_stats})
    new_params, _, _, _, new_efs, _ = e._round_jit(
        gs.params, gs.batch_stats, e.data, jnp.asarray(sampled), rngs,
        jnp.float32(2e-3), efs, byz)
    # byz rank 1 == engine client 0 (the faults/ contract)
    atk = int(np.flatnonzero(np.asarray(sampled) == 0)[0])
    hon = [i for i in range(len(sampled)) if i != atk]
    for leaf in jax.tree.leaves(new_efs):
        a = np.asarray(leaf)
        assert np.isfinite(a).all()  # the NaN residual never lands
        assert not np.any(a[atk])    # the attacked row is exactly zero
    # honest rows carry real lossy-roundtrip residuals
    assert sum(float(np.abs(np.asarray(leaf)[hon]).sum())
               for leaf in jax.tree.leaves(new_efs)) > 0.0
    assert all(np.isfinite(np.asarray(x)).all()
               for x in jax.tree.leaves(new_params))


def test_client_ef_dropped_after_nonfinite_upload():
    """Cross-silo mirror of the engine EF reset: a client whose upload
    goes non-finite (its frame bounces at the server's hard gate) drops
    the consumed EF stack instead of absorbing the NaN residual, so the
    next honest round encodes from a clean accumulator."""
    from neuroimagedisttraining_tpu.codec import parse_wire_spec
    from neuroimagedisttraining_tpu.distributed import message as M

    c = FedAvgClientProc.__new__(FedAvgClientProc)
    c.rank = 1
    c.seed = 0
    c.fault_schedule = None
    c._wire_spec = parse_wire_spec("delta+sparse+quant")
    c.wire_masks = None
    c._wire_ef = None
    sent = []
    c.send_message = sent.append
    ref = {"w": np.zeros((4, 4), np.float32)}
    outs = iter([({"w": np.full((4, 4), np.nan, np.float32)}, 8.0),
                 ({"w": np.full((4, 4), 0.5, np.float32)}, 8.0)])
    c.train_fn = lambda params, r: next(outs)

    def sync(r):
        m = M.Message(M.MSG_TYPE_S2C_SYNC_MODEL, 0, 1)
        m.add(M.ARG_MODEL_PARAMS, ref)
        m.add(M.ARG_ROUND_IDX, r)
        c._on_sync(m)

    sync(0)  # NaN upload: the consumed EF must be dropped, not parked
    assert c._wire_ef is None
    sync(1)  # honest round: EF threads again, finite
    assert c._wire_ef is not None
    assert all(np.isfinite(np.asarray(x)).all()
               for x in jax.tree.leaves(c._wire_ef))
    assert len(sent) == 2


def test_engine_rejects_unknown_or_unsupported_defense(tmp_path,
                                                       synthetic_cohort):
    from tests.test_fedavg import _make_engine

    with pytest.raises(ValueError, match="unknown defense"):
        _make_engine(tmp_path, synthetic_cohort,
                     defense_type="krumm")  # typo fails at startup
    # local's round has no defended aggregation path (no declared
    # aggregate stage routes through the builder's defense dispatch —
    # ditto gained one in ISSUE 11): loud, at startup
    with pytest.raises(ValueError, match="does not support"):
        _make_engine(tmp_path, synthetic_cohort, algorithm="local",
                     defense_type="trimmed_mean")
    # breakdown point vs the sampled cohort: krum needs n >= f + 3
    with pytest.raises(ValueError, match="f \\+ 3"):
        _make_engine(tmp_path, synthetic_cohort, defense_type="krum",
                     byz_f=2)


def test_engine_without_byz_support_rejects_value_faults(tmp_path,
                                                         synthetic_cohort):
    from tests.test_fedavg import _make_engine

    # local never puts uploads on a wire — no attack surface, and no
    # builder attack stage to route them through (ditto gained byz
    # support with its stage declaration, ISSUE 11)
    with pytest.raises(ValueError, match="byz"):
        _make_engine(tmp_path, synthetic_cohort, algorithm="local",
                     fault_spec="byz:1@0:sign_flip")
    # omission faults keep working everywhere
    e = _make_engine(tmp_path, synthetic_cohort, algorithm="local",
                     fault_spec="crash:1@1")
    assert e.fault_schedule is not None


@pytest.mark.slow
def test_fedavg_defense_recovers_under_sign_flip(tmp_path,
                                                 synthetic_cohort):
    """Engine-level measured contract: 1-of-4 sign-flip degrades the
    undefended round drift; trimmed_mean pulls the aggregate back toward
    the honest mean (the byz_bench.json claim at CI scale)."""
    from tests.test_fedavg import _make_engine

    def drift(defense, spec):
        e = _make_engine(tmp_path, synthetic_cohort, comm_round=2,
                         fault_spec=spec, defense_type=defense, byz_f=1)
        e._donate = False
        gs = e.init_global_state()
        sampled = jnp.asarray(e.client_sampling(0))
        rngs = e.per_client_rngs(0, np.asarray(sampled))
        byz = e._byz_round_plan(0, np.asarray(sampled))
        if byz is not None:
            p, _, _, _ = e._round_jit(gs.params, gs.batch_stats, e.data,
                                      sampled, rngs, jnp.float32(2e-3),
                                      None, byz)
        else:
            p, _, _, _ = e._round_jit(gs.params, gs.batch_stats, e.data,
                                      sampled, rngs, jnp.float32(2e-3))
        return p, gs

    p_clean, gs = drift("none", "")
    p_atk, _ = drift("none", "byz:1@0:scale:30")
    p_def, _ = drift("trimmed_mean", "byz:1@0:scale:30")
    err_atk = float(pt.tree_norm(pt.tree_sub(p_atk, p_clean)))
    err_def = float(pt.tree_norm(pt.tree_sub(p_def, p_clean)))
    assert err_atk > 5 * err_def  # the defense recovers most of the gap


@pytest.mark.slow
def test_fused_dispatch_bitwise_with_defense(tmp_path, synthetic_cohort):
    """K-fused dispatch with a Byzantine schedule AND a defense enabled
    is bitwise-equal to the sequential loop (the ISSUE 5 acceptance
    pin), for fedavg and salientgrads."""
    from tests.test_engines import _engine

    def run(algorithm, k):
        e = _engine(tmp_path, synthetic_cohort, algorithm, comm_round=4,
                    fault_spec="byz:1@0:sign_flip",
                    defense_type="trimmed_mean", byz_f=1,
                    rounds_per_dispatch=k)
        e._donate = False
        return e.train()

    for algorithm in ("fedavg", "salientgrads"):
        seq = run(algorithm, 1)
        fused = run(algorithm, 4)
        for a, b in zip(jax.tree.leaves(seq["params"]),
                        jax.tree.leaves(fused["params"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert [h["round"] for h in seq["history"]] == \
            [h["round"] for h in fused["history"]]


# ------------------------------------------------- cross-silo control plane


def test_update_outlier_flags_scoring():
    rng = np.random.default_rng(6)
    ref = _toy_tree(rng)
    honest = [{k: v + rng.normal(size=v.shape).astype(np.float32) * 0.01
               + np.float32(0.1)
               for k, v in ref.items()} for _ in range(3)]
    flipped = {k: ref[k] - (honest[0][k] - ref[k]) for k in ref}
    huge = {k: v + np.float32(50.0) for k, v in ref.items()}
    flags, norms = update_outlier_flags(honest + [flipped], ref)
    assert flags == [False, False, False, True]   # cosine catches the flip
    flags2, _ = update_outlier_flags(honest + [huge], ref)
    assert flags2 == [False, False, False, True]  # norm catches the blowup
    flags3, _ = update_outlier_flags(honest, ref)
    assert flags3 == [False, False, False]


def _toy_train(rank, lr=0.5):
    def fn(params, round_idx):
        p = {k: np.asarray(v, np.float32) for k, v in params.items()}
        p["w"] = p["w"] + np.float32(lr) * (np.float32(rank) - p["w"])
        return p, 10.0 * rank
    return fn


def _aligned_train(rank, lr=0.1):
    """Near-parallel honest updates (real silos training on similar
    cohorts): every client steps the same direction with a tiny
    per-rank wobble, so the outlier scorer has no false positives."""
    def fn(params, round_idx):
        p = {k: np.asarray(v, np.float32) for k, v in params.items()}
        p["w"] = p["w"] + np.float32(lr) * (np.float32(1.0 + 0.01 * rank)
                                            - 0.1 * p["w"])
        return p, 10.0
    return fn


def _make_client(rank, num_clients, bp, *, spec=None, seed=0, hb=0.0,
                 train=None):
    sched = (FaultSchedule(parse_fault_spec(spec), seed) if spec else None)
    return FedAvgClientProc(rank, num_clients, train or _toy_train(rank),
                            base_port=bp, fault_schedule=sched, seed=seed,
                            heartbeat_interval=hb)


def _run_federation(server, clients, timeout=90):
    threads = [threading.Thread(target=m.run, daemon=True)
               for m in [server] + clients]
    for t in threads:
        t.start()
    assert server._done.wait(timeout=timeout), "byz protocol stalled"
    for t in threads:
        t.join(timeout=15)


def test_server_defended_round_matches_engine_dispatch():
    """In-thread 4-silo federation, silo 1 sign-flips from round 0, the
    server aggregates with trimmed_mean: the final model is bitwise-
    equal to a host replay through the SAME jitted core/robust.py
    dispatch (survivor_defended_mean) over the same uploads."""
    num_clients, rounds = 4, 2
    bp = free_port_block(num_clients + 2)
    init = {"w": np.zeros(3, np.float32)}
    spec, seed = "byz:1@0:sign_flip", 11
    server = FedAvgServer(init, rounds, num_clients, base_port=bp,
                          defense="trimmed_mean", byz_f=1)
    clients = [_make_client(c, num_clients, bp, spec=spec, seed=seed)
               for c in range(1, num_clients + 1)]
    _run_federation(server, clients)
    assert len(server.history) == rounds

    sched = FaultSchedule(parse_fault_spec(spec), seed)
    params = init
    for r in range(rounds):
        outs = {c: _toy_train(c)(params, r)
                for c in range(1, num_clients + 1)}
        trees, ns = [], []
        for c in sorted(outs):
            u, n = outs[c]
            trees.append(adversary.attack_update(sched, seed, r, c, u,
                                                 params))
            ns.append(n)
        params = survivor_defended_mean(trees, ns, params,
                                        defense="trimmed_mean", byz_f=1)
    np.testing.assert_array_equal(server.params["w"], params["w"])


def test_server_quarantines_nonfinite_uploader():
    """Silo 2 uploads NaN every round: the server hard-rejects each
    frame (counted), strikes it, quarantines it at the threshold, keeps
    completing rounds over the honest silos, and schedules the post-
    window ef_reset."""
    num_clients, rounds = 4, 4
    bp = free_port_block(num_clients + 2)
    init = {"w": np.zeros(3, np.float32)}
    server = FedAvgServer(init, rounds, num_clients, base_port=bp,
                          round_deadline=1.5, quorum=2,
                          heartbeat_timeout=30.0,
                          quarantine_rounds=2, outlier_threshold=2)
    # heartbeats keep the rejected silo EXPECTED (alive straggler, not
    # corpse) so the strike counter — not the suspicion set — is what
    # eventually excludes it; honest trains are aligned so the outlier
    # scorer never false-positives into the byz_f=1 quarantine budget
    clients = [_make_client(c, num_clients, bp, spec="byz:2@0:nonfinite",
                            seed=3, hb=0.3, train=_aligned_train(c))
               for c in range(1, num_clients + 1)]
    _run_federation(server, clients, timeout=120)
    assert len(server.history) == rounds
    assert server.byz_stats["nonfinite_rejected"] >= 2
    qs = server.byz_stats["quarantines"]
    assert qs and qs[0]["client"] == 2
    q_from = qs[0]["from_round"]
    for e in server.history:
        if q_from <= e["round"] < qs[0]["until_round"]:
            assert 2 in e.get("quarantined", [])
            assert 2 not in e["survivors"]
    # the model never saw a NaN
    assert tree_all_finite(server.params)
    # the post-window sync owes silo 2 an EF reset (delivered on the
    # next sync after the window — here training may end first, so the
    # pending marker is the observable)
    assert 2 in server._ef_reset_pending or rounds >= qs[0]["until_round"]


def test_server_all_rejected_round_advances_without_deadline():
    """Every live silo's upload bounces at the non-finite gate in the
    same round of a NO-deadline federation: with heartbeats fresh the
    suspicion monitor never fires and no timer exists, so the server
    must advance with the global model unchanged instead of waiting
    forever on its own rejection set."""
    num_clients, rounds = 2, 3
    bp = free_port_block(num_clients + 2)
    init = {"w": np.asarray([1.0, 2.0, 3.0], np.float32)}
    server = FedAvgServer(init, rounds, num_clients, base_port=bp,
                          quorum=1, heartbeat_timeout=30.0)
    spec = "byz:1@0:nonfinite,byz:2@0:nonfinite"
    clients = [_make_client(c, num_clients, bp, spec=spec, hb=0.3)
               for c in range(1, num_clients + 1)]
    _run_federation(server, clients, timeout=60)
    assert len(server.history) == rounds
    assert all(e["clients"] == 0 for e in server.history)
    assert server.byz_stats["nonfinite_rejected"] == num_clients * rounds
    # nothing was ever aggregated: the model is bitwise the init
    np.testing.assert_array_equal(server.params["w"], init["w"])


def test_secure_server_rejects_defense_and_quarantine():
    init = {"w": np.zeros(3, np.float32)}
    bp = free_port_block(4)
    with pytest.raises(ValueError, match="neither"):
        SecureFedAvgServer(init, 1, 2, base_port=bp,
                           defense="trimmed_mean")
    with pytest.raises(ValueError, match="neither"):
        SecureFedAvgServer(init, 1, 2, base_port=bp, quarantine_rounds=2)


def test_server_unknown_defense_fails_at_construction():
    init = {"w": np.zeros(3, np.float32)}
    with pytest.raises(ValueError, match="unknown defense"):
        FedAvgServer(init, 1, 4, base_port=free_port_block(6),
                     defense="trimmed")
    with pytest.raises(ValueError, match="f \\+ 3"):
        FedAvgServer(init, 1, 4, base_port=free_port_block(6),
                     defense="krum", byz_f=2)


@pytest.mark.slow
def test_multiprocess_byzantine_one_of_four(tmp_path):
    """Real OS-process federation (distributed/run.py CLI): 4 silos
    train the tiny 3D CNN, silo 1 sign-flips every round, the server
    defends with trimmed_mean + quarantine armed. All rounds complete
    and the final model is finite."""
    import json
    import subprocess
    import sys

    bp = free_port_block(16)
    common = ["--num_clients", "4", "--comm_round", "3",
              "--model", "3dcnn_tiny", "--dataset", "synthetic",
              "--synthetic_num_subjects", "24",
              "--synthetic_shape", "12", "14", "12",
              "--batch_size", "4", "--base_port", str(bp), "--force_cpu",
              "--fault_spec", "byz:1@0:sign_flip",
              "--defense", "trimmed_mean", "--byz_f", "1",
              "--quarantine_rounds", "2", "--outlier_threshold", "2",
              "--round_deadline", "60", "--quorum", "2"]
    cmd = [sys.executable, "-m",
           "neuroimagedisttraining_tpu.distributed.run"]
    server = subprocess.Popen(cmd + ["--role", "server"] + common,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
    procs = [subprocess.Popen(cmd + ["--role", "client", "--rank",
                                     str(r)] + common,
                              stdout=subprocess.DEVNULL,
                              stderr=subprocess.DEVNULL)
             for r in range(1, 5)]
    try:
        out, _ = server.communicate(timeout=600)
    finally:
        for p in procs:
            p.kill()
    assert server.returncode == 0, out
    res = json.loads([ln for ln in out.splitlines()
                      if ln.startswith("{")][-1])
    assert res["rounds_completed"] == 3
    assert res["defense"] == "trimmed_mean"
    assert np.isfinite(res["final_param_norm"])
