"""Cross-silo control plane: message codec, TCP transport, handler-registry
managers, and the full register->broadcast->train->upload->aggregate->finish
protocol loop (fedml_core/distributed semantics, SURVEY §2.2/§2.3)."""

import multiprocessing as mp
import threading
import time

import numpy as np

from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.distributed.comm import SocketCommManager
from neuroimagedisttraining_tpu.distributed.cross_silo import (
    FedAvgClientProc, FedAvgServer,
)


def test_message_codec_roundtrip():
    msg = M.Message(M.MSG_TYPE_S2C_SYNC_MODEL, 0, 3)
    msg.add(M.ARG_MODEL_PARAMS, {"w": np.arange(6, dtype=np.float32)
                                 .reshape(2, 3), "b": np.float32(1.5)})
    msg.add(M.ARG_ROUND_IDX, 7)
    back = M.Message.from_bytes(msg.to_bytes())
    assert back.msg_type == M.MSG_TYPE_S2C_SYNC_MODEL
    assert back.sender_id == 0 and back.receiver_id == 3
    assert back.get(M.ARG_ROUND_IDX) == 7
    np.testing.assert_array_equal(back.get(M.ARG_MODEL_PARAMS)["w"],
                                  np.arange(6, dtype=np.float32)
                                  .reshape(2, 3))


def test_socket_transport_point_to_point():
    a = SocketCommManager(0, 2, base_port=52210)
    b = SocketCommManager(1, 2, base_port=52210)
    got = []

    class Obs:
        def receive_message(self, t, m):
            got.append((t, int(np.asarray(m.get("x")))))
            b.stop_receive_message()

    b.add_observer(Obs())
    runner = threading.Thread(target=b.handle_receive_message)
    runner.start()
    msg = M.Message("ping", 0, 1)
    msg.add("x", np.int64(41))
    a.send_message(msg)
    runner.join(timeout=10)
    a.stop_receive_message()
    assert got == [("ping", 41)]


def _run_protocol(num_clients, comm_round, base_port, lr=0.5):
    """Server + clients on real sockets; client c's 'training' moves params
    toward the constant c+1, weight n_c = 10*(c+1)."""
    init = {"w": np.zeros((3,), np.float32)}

    def make_train_fn(c):
        def train_fn(params, round_idx):
            p = {k: np.asarray(v, np.float32) for k, v in params.items()}
            p["w"] = p["w"] + lr * ((c + 1) - p["w"])
            return p, 10.0 * (c + 1)

        return train_fn

    server = FedAvgServer(init, comm_round, num_clients,
                          base_port=base_port)
    clients = [FedAvgClientProc(c + 1, num_clients,
                                make_train_fn(c), base_port=base_port)
               for c in range(num_clients)]
    threads = [threading.Thread(target=m.run)
               for m in [server] + clients]
    for t in threads:
        t.start()
    server._done.wait(timeout=60)
    for t in threads:
        t.join(timeout=10)
    return server


def test_cross_silo_fedavg_protocol():
    server = _run_protocol(num_clients=3, comm_round=2, base_port=52300)
    assert len(server.history) == 2
    # closed-form check: one round from w=0 gives w_c = lr*(c+1);
    # weighted mean with weights (1,2,3)/6 -> lr * (1*1+2*2+3*3)/6
    lr = 0.5
    r1 = lr * (1 * 1 + 2 * 2 + 3 * 3) / 6.0
    # round 2: each client pulls r1 toward (c+1) then weighted mean again
    vals = [r1 + lr * ((c + 1) - r1) for c in range(3)]
    r2 = sum((c + 1) * v for c, v in enumerate(vals)) / 6.0
    np.testing.assert_allclose(server.params["w"],
                               np.full(3, r2, np.float32), rtol=1e-6)


def _spawn_client(rank, num_clients, base_port):
    # separate PROCESS: genuine cross-address-space message loop
    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        FedAvgClientProc,
    )

    def train_fn(params, round_idx):
        p = {k: np.asarray(v, np.float32) + rank for k, v in params.items()}
        return p, float(rank)

    FedAvgClientProc(rank, num_clients, train_fn,
                     base_port=base_port).run()


def test_cross_silo_multiprocess_smoke():
    """Two real OS processes register, train, and the server aggregates —
    the multi-process capability check (VERDICT round-1 item 9)."""
    ctx = mp.get_context("spawn")
    base_port = 52400
    procs = [ctx.Process(target=_spawn_client, args=(r, 2, base_port),
                         daemon=True) for r in (1, 2)]
    for p in procs:
        p.start()
    server = FedAvgServer({"w": np.zeros((2,), np.float32)}, 1, 2,
                          base_port=base_port)
    t = threading.Thread(target=server.run)
    t.start()
    assert server._done.wait(timeout=120), "protocol did not complete"
    t.join(timeout=10)
    for p in procs:
        p.join(timeout=10)
    # weighted mean of (0+1) w=1 and (0+2) w=2 -> (1*1 + 2*2)/3
    np.testing.assert_allclose(server.params["w"],
                               np.full(2, 5.0 / 3.0, np.float32), rtol=1e-6)
    time.sleep(0.1)
