"""Cross-silo control plane: message codec, TCP transport, handler-registry
managers, and the full register->broadcast->train->upload->aggregate->finish
protocol loop (fedml_core/distributed semantics, SURVEY §2.2/§2.3)."""

import multiprocessing as mp
import os
import threading
import time

import numpy as np
import pytest

from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.distributed.comm import SocketCommManager
from neuroimagedisttraining_tpu.distributed.cross_silo import (
    FedAvgClientProc, FedAvgServer,
)
from neuroimagedisttraining_tpu.distributed.ports import free_port_block


def _base_port() -> int:
    """Kernel-probed free port block (distributed/ports.py): unlike the
    old hardcoded 51000+pid scheme, parallel CI runs never collide on
    bind — the kernel hands out an ephemeral anchor and the whole block
    is proven bindable."""
    return free_port_block(8)


def test_message_codec_roundtrip():
    msg = M.Message(M.MSG_TYPE_S2C_SYNC_MODEL, 0, 3)
    msg.add(M.ARG_MODEL_PARAMS, {"w": np.arange(6, dtype=np.float32)
                                 .reshape(2, 3), "b": np.float32(1.5)})
    msg.add(M.ARG_ROUND_IDX, 7)
    back = M.Message.from_bytes(msg.to_bytes())
    assert back.msg_type == M.MSG_TYPE_S2C_SYNC_MODEL
    assert back.sender_id == 0 and back.receiver_id == 3
    assert back.get(M.ARG_ROUND_IDX) == 7
    np.testing.assert_array_equal(back.get(M.ARG_MODEL_PARAMS)["w"],
                                  np.arange(6, dtype=np.float32)
                                  .reshape(2, 3))


def test_socket_transport_point_to_point():
    bp = _base_port()
    a = SocketCommManager(0, 2, base_port=bp)
    b = SocketCommManager(1, 2, base_port=bp)
    got = []

    class Obs:
        def receive_message(self, t, m):
            got.append((t, int(np.asarray(m.get("x")))))
            b.stop_receive_message()

    b.add_observer(Obs())
    runner = threading.Thread(target=b.handle_receive_message)
    runner.start()
    msg = M.Message("ping", 0, 1)
    msg.add("x", np.int64(41))
    a.send_message(msg)
    runner.join(timeout=10)
    a.stop_receive_message()
    assert got == [("ping", 41)]


def test_listener_survives_malformed_frame():
    """A corrupt frame or aborted connection must not kill the rank's only
    listener thread — later well-formed messages still arrive."""
    import socket
    import struct

    bp = _base_port()
    b = SocketCommManager(1, 2, base_port=bp)
    got = []

    class Obs:
        def receive_message(self, t, m):
            got.append(t)
            b.stop_receive_message()

    b.add_observer(Obs())
    runner = threading.Thread(target=b.handle_receive_message)
    runner.start()
    # garbage frame: valid length prefix, bad magic
    with socket.create_connection(("127.0.0.1", bp + 1), timeout=5) as c:
        c.sendall(struct.pack("!Q", 4) + b"junk")
    # aborted connection: length prefix promising more than is sent
    with socket.create_connection(("127.0.0.1", bp + 1), timeout=5) as c:
        c.sendall(struct.pack("!Q", 1 << 20) + b"partial")
    # a real message still gets through
    a = SocketCommManager(0, 2, base_port=bp)
    a.send_message(M.Message("after-junk", 0, 1))
    runner.join(timeout=15)
    a.stop_receive_message()
    assert got == ["after-junk"]


def _run_protocol(num_clients, comm_round, base_port, lr=0.5):
    """Server + clients on real sockets; client c's 'training' moves params
    toward the constant c+1, weight n_c = 10*(c+1)."""
    init = {"w": np.zeros((3,), np.float32)}

    def make_train_fn(c):
        def train_fn(params, round_idx):
            p = {k: np.asarray(v, np.float32) for k, v in params.items()}
            p["w"] = p["w"] + lr * ((c + 1) - p["w"])
            return p, 10.0 * (c + 1)

        return train_fn

    server = FedAvgServer(init, comm_round, num_clients,
                          base_port=base_port)
    clients = [FedAvgClientProc(c + 1, num_clients,
                                make_train_fn(c), base_port=base_port)
               for c in range(num_clients)]
    threads = [threading.Thread(target=m.run)
               for m in [server] + clients]
    for t in threads:
        t.start()
    server._done.wait(timeout=60)
    for t in threads:
        t.join(timeout=10)
    return server


def test_cross_silo_fedavg_protocol():
    server = _run_protocol(num_clients=3, comm_round=2, base_port=_base_port())
    assert len(server.history) == 2
    # closed-form check: one round from w=0 gives w_c = lr*(c+1);
    # weighted mean with weights (1,2,3)/6 -> lr * (1*1+2*2+3*3)/6
    lr = 0.5
    r1 = lr * (1 * 1 + 2 * 2 + 3 * 3) / 6.0
    # round 2: each client pulls r1 toward (c+1) then weighted mean again
    vals = [r1 + lr * ((c + 1) - r1) for c in range(3)]
    r2 = sum((c + 1) * v for c, v in enumerate(vals)) / 6.0
    np.testing.assert_allclose(server.params["w"],
                               np.full(3, r2, np.float32), rtol=1e-6)


def test_cross_silo_with_real_trainer(tmp_path):
    """Real flax model pytrees ride the control plane: each silo trains the
    tiny 3D CNN with the shipped LocalTrainer on its own shard; the server
    aggregate equals the in-process weighted mean of the silos' results."""
    import jax
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.config import OptimConfig
    from neuroimagedisttraining_tpu.core.trainer import ClientState, LocalTrainer
    from neuroimagedisttraining_tpu.models import create_model

    model = create_model("3dcnn_tiny", num_classes=1)
    trainer = LocalTrainer(model, OptimConfig(batch_size=4, epochs=1),
                           num_classes=1)
    shape = (10, 12, 10)
    gs = trainer.init_client_state(jax.random.key(0),
                                   jnp.zeros((1,) + shape))
    rng = np.random.default_rng(0)
    shards = []
    for c in range(2):
        X = jnp.asarray(rng.integers(0, 255, size=(8,) + shape), jnp.uint8)
        y = jnp.asarray(rng.integers(0, 2, size=(8,)), jnp.int32)
        shards.append((X, y))

    def make_train_fn(c):
        X, y = shards[c]

        def train_fn(params, round_idx):
            p32 = jax.tree.map(jnp.asarray, params)
            cs = ClientState(params=p32, batch_stats=gs.batch_stats,
                             opt_state=trainer.opt.init(p32),
                             rng=jax.random.fold_in(jax.random.key(5), c))
            cs, _ = trainer.local_train(cs, X, y, jnp.int32(8),
                                        jnp.float32(1e-3), epochs=1,
                                        batch_size=4, max_samples=8)
            return jax.tree.map(np.asarray, cs.params), 8.0

        return train_fn

    base_port = _base_port()
    server = FedAvgServer(gs.params, 1, 2, base_port=base_port)
    clients = [FedAvgClientProc(c + 1, 2, make_train_fn(c),
                                base_port=base_port) for c in range(2)]
    threads = [threading.Thread(target=m.run) for m in [server] + clients]
    for t in threads:
        t.start()
    assert server._done.wait(timeout=300)
    for t in threads:
        t.join(timeout=10)

    # in-process control: the same two local_trains, plain weighted mean
    want_parts = [make_train_fn(c)(gs.params, 0)[0] for c in range(2)]
    want = jax.tree.map(lambda a, b: (a.astype(np.float64)
                                      + b.astype(np.float64)) / 2.0,
                        *want_parts)
    for ls, lw in zip(jax.tree.leaves(server.params),
                      jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(ls, np.float64), lw,
                                   rtol=1e-5, atol=1e-7)


def _spawn_client(rank, num_clients, base_port):
    # separate PROCESS: genuine cross-address-space message loop
    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        FedAvgClientProc,
    )

    def train_fn(params, round_idx):
        p = {k: np.asarray(v, np.float32) + rank for k, v in params.items()}
        return p, float(rank)

    FedAvgClientProc(rank, num_clients, train_fn,
                     base_port=base_port).run()


def test_cross_silo_multiprocess_smoke():
    """Two real OS processes register, train, and the server aggregates —
    the multi-process capability check (VERDICT round-1 item 9)."""
    ctx = mp.get_context("spawn")
    base_port = _base_port()
    procs = [ctx.Process(target=_spawn_client, args=(r, 2, base_port),
                         daemon=True) for r in (1, 2)]
    for p in procs:
        p.start()
    server = FedAvgServer({"w": np.zeros((2,), np.float32)}, 1, 2,
                          base_port=base_port)
    t = threading.Thread(target=server.run)
    t.start()
    assert server._done.wait(timeout=120), "protocol did not complete"
    t.join(timeout=10)
    for p in procs:
        p.join(timeout=10)
    # weighted mean of (0+1) w=1 and (0+2) w=2 -> (1*1 + 2*2)/3
    np.testing.assert_allclose(server.params["w"],
                               np.full(2, 5.0 / 3.0, np.float32), rtol=1e-6)
    time.sleep(0.1)


def test_init_multihost_single_process():
    """Drive the init_multihost hook for real (VERDICT r2 missing #3): a
    1-process jax.distributed runtime comes up, serves devices, and shuts
    down. Multi-process CPU clustering is disabled in this jax build (see
    init_multihost docstring), so >1-process coordination is exercised via
    the socket protocol tests instead; on a real pod this same hook spans
    hosts. Runs in a subprocess (backend init is irreversible) and SKIPs
    where the runtime cannot bind."""
    import subprocess
    import sys

    port = free_port_block(1)
    code = (
        "from neuroimagedisttraining_tpu.distributed.cross_silo import "
        "init_multihost\n"
        "import jax\n"
        f"init_multihost('127.0.0.1:{port}', 1, 0)\n"
        "assert jax.process_count() == 1, jax.process_count()\n"
        "assert jax.device_count() >= 1\n"
        "jax.distributed.shutdown()\n"
        "print('MULTIHOST_OK')\n")
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd="/root/repo")
    if "MULTIHOST_OK" not in out.stdout:
        import pytest

        pytest.skip(f"jax.distributed unavailable here: {out.stderr[-300:]}")


def test_cross_silo_secure_aggregation_protocol():
    """Secure aggregation rides the REAL socket control plane (VERDICT r2
    next-step #2 stretch): clients upload additive share slots of their
    scaled quantized updates; the server's slot-major accumulation
    reconstructs only the aggregate — which must match the PLAIN protocol's
    weighted mean to fixed-point precision."""
    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        SecureFedAvgClientProc, SecureFedAvgServer,
    )

    num_clients, comm_round, lr = 3, 2, 0.5
    init = {"w": np.zeros((3,), np.float32)}

    def make_train_fn(c):
        def train_fn(params, round_idx):
            p = {k: np.asarray(v, np.float32) for k, v in params.items()}
            p["w"] = p["w"] + lr * ((c + 1) - p["w"])
            return p, 10.0 * (c + 1)

        return train_fn

    # plain protocol (existing) as the ground truth
    plain = _run_protocol(num_clients, comm_round, _base_port(), lr=lr)

    bp = _base_port()
    server = SecureFedAvgServer(init, comm_round, num_clients,
                                base_port=bp)
    clients = [SecureFedAvgClientProc(c + 1, num_clients, make_train_fn(c),
                                      n_shares=3, mpc_seed=c, base_port=bp)
               for c in range(num_clients)]
    threads = [threading.Thread(target=m.run) for m in [server] + clients]
    for t in threads:
        t.start()
    assert server._done.wait(timeout=60), "secure protocol did not complete"
    for t in threads:
        t.join(timeout=10)
    assert len(server.history) == comm_round
    # quantization error per round is 2^-16-scale; trajectories stay close
    np.testing.assert_allclose(server.params["w"], plain.params["w"],
                               atol=1e-3)


def test_cross_silo_multi_aggregator_privacy_and_correctness():
    """TurboAggregate's grouped aggregation for real (VERDICT r3 next-step
    #4): 2 clients, 3 slot-aggregator nodes, slot j routed to aggregator
    j over the socket plane. Trace-style privacy assertion (as
    test_mpc.py:129): no single process's received data reconstructs any
    client's quantized update — each aggregator holds ONE uniform share
    slot per client, the server holds only cross-client totals. The
    reconstructed aggregate must match the plain protocol."""
    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        SecureFedAvgClientProc, SecureFedAvgServer, SlotAggregatorProc,
    )
    from neuroimagedisttraining_tpu.ops import mpc

    num_clients, n_agg, comm_round, lr = 2, 3, 2, 0.5
    init = {"w": np.zeros((3,), np.float32)}  # _run_protocol's shape

    trained: dict[int, list] = {1: [], 2: []}

    def make_train_fn(c):
        def train_fn(params, round_idx):
            p = {k: np.asarray(v, np.float32) for k, v in params.items()}
            p["w"] = p["w"] + lr * ((c + 1) - p["w"])
            trained[c + 1].append(p["w"].copy())
            return p, 10.0 * (c + 1)

        return train_fn

    plain = _run_protocol(num_clients, comm_round, _base_port(), lr=lr)

    bp = _base_port()
    server = SecureFedAvgServer(init, comm_round, num_clients,
                                n_aggregators=n_agg, base_port=bp,
                                record_trace=True)
    aggs = [SlotAggregatorProc(j, num_clients, n_agg, base_port=bp,
                               record_trace=True)
            for j in range(n_agg)]
    clients = [SecureFedAvgClientProc(c + 1, num_clients, make_train_fn(c),
                                      n_shares=n_agg, n_aggregators=n_agg,
                                      mpc_seed=c, base_port=bp)
               for c in range(num_clients)]
    threads = [threading.Thread(target=m.run)
               for m in [server] + aggs + clients]
    for t in threads:
        t.start()
    assert server._done.wait(timeout=60), "multi-agg protocol stalled"
    for t in threads:
        t.join(timeout=10)

    assert len(server.history) == comm_round
    np.testing.assert_allclose(server.params["w"], plain.params["w"],
                               atol=1e-3)

    # ---- trace-style privacy assertions ----
    # every client's plaintext-equivalent: quantize(w_c * trained params)
    n1, n2 = 10.0, 20.0
    q_updates = []
    for c, ws in trained.items():
        w_c = (n1 if c == 1 else n2) / (n1 + n2)
        for w_arr in ws:
            q_updates.append(mpc.quantize(w_c * np.asarray(w_arr,
                                                           np.float64)))
    # aggregator j saw exactly one slot per client per round, and NONE of
    # them equals any client's quantized update
    for j, agg in enumerate(aggs):
        assert sorted(agg.received) == [1, 2], "wrong senders"
        for sender, slots in agg.received.items():
            assert len(slots) == comm_round  # one slot per round
            for slot in slots:
                for q in q_updates:
                    assert not np.array_equal(
                        np.asarray(slot["w"], np.int64) % mpc.P_DEFAULT,
                        q % mpc.P_DEFAULT), \
                        f"aggregator {j} received a plaintext update"
    # the server saw ONLY cross-client slot totals — none reconstructs a
    # client either
    assert len(server.received_totals) == n_agg * comm_round
    for tot in server.received_totals:
        for q in q_updates:
            assert not np.array_equal(
                np.asarray(tot["w"], np.int64) % mpc.P_DEFAULT,
                q % mpc.P_DEFAULT), "server received a plaintext update"


def _run_cross_silo_cli(base_port, extra=(), timeout=420,
                        n_aggregators=0):
    """Launch 1 server + 2 silo client processes through the CLI runner
    (+ one OS process per slot aggregator when ``n_aggregators``)."""
    import subprocess
    import sys

    common = ["--num_clients", "2", "--comm_round", "2",
              "--model", "3dcnn_tiny", "--dataset", "synthetic",
              "--synthetic_num_subjects", "24",
              "--synthetic_shape", "12", "14", "12",
              "--batch_size", "4", "--base_port", str(base_port),
              "--force_cpu", *extra]
    cmd = [sys.executable, "-m",
           "neuroimagedisttraining_tpu.distributed.run"]
    server = subprocess.Popen(cmd + ["--role", "server"] + common,
                              stdout=subprocess.PIPE, text=True,
                              cwd="/root/repo")
    aggs = [subprocess.Popen(
        cmd + ["--role", "aggregator", "--slot_index", str(j)] + common,
        stdout=subprocess.PIPE, text=True, cwd="/root/repo")
        for j in range(n_aggregators)]
    clients = [subprocess.Popen(
        cmd + ["--role", "client", "--rank", str(r)] + common,
        stdout=subprocess.PIPE, text=True, cwd="/root/repo")
        for r in (1, 2)]
    try:
        out, _ = server.communicate(timeout=timeout)
        for c in clients:
            c.wait(timeout=60)
        # a failed server never sends FINISH — surface ITS error, not an
        # aggregator TimeoutExpired
        assert server.returncode == 0, out[-500:]
        agg_outs = []
        for a in aggs:
            a_out, _ = a.communicate(timeout=60)
            agg_outs.append(a_out)
    finally:
        for p in [server, *clients, *aggs]:
            if p.poll() is None:
                p.kill()
    last = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
    import json

    res = json.loads(last)
    if n_aggregators:
        res["aggregators"] = [
            json.loads([ln for ln in a_out.splitlines()
                        if ln.startswith("{")][-1]) for a_out in agg_outs]
    return res


def test_cross_silo_cli_runner():
    """The cross-silo federation is drivable from the CLI: 3 real OS
    processes (server + 2 silos, each training with the jitted
    LocalTrainer on its own site shard) complete the full protocol."""
    res = _run_cross_silo_cli(_base_port())
    assert res["rounds_completed"] == 2
    assert res["secure"] is False
    assert res["final_param_norm"] > 0


@pytest.mark.slow
def test_cross_silo_cli_runner_secure():
    """Same run under --secure: additive-share slots ride the control
    plane; the aggregate must match the plain run to fixed-point
    precision (same seeds => same training trajectories)."""
    plain = _run_cross_silo_cli(_base_port())
    sec = _run_cross_silo_cli(_base_port(), extra=("--secure",))
    assert sec["rounds_completed"] == 2 and sec["secure"] is True
    np.testing.assert_allclose(sec["final_param_norm"],
                               plain["final_param_norm"], rtol=1e-4)


@pytest.mark.slow
def test_cross_silo_cli_runner_secure_multi_aggregator():
    """Full grouped deployment across SIX OS processes: server + 2 silo
    trainers + 3 slot aggregators. Slot j rides to aggregator j; the
    server combines only cross-client totals; the aggregate matches the
    plain run to fixed-point precision."""
    plain = _run_cross_silo_cli(_base_port())
    sec = _run_cross_silo_cli(
        _base_port(),
        extra=("--secure", "--n_aggregators", "3", "--mpc_n_shares", "3"),
        n_aggregators=3)
    assert sec["rounds_completed"] == 2 and sec["secure"] is True
    np.testing.assert_allclose(sec["final_param_norm"],
                               plain["final_param_norm"], rtol=1e-4)
    assert len(sec["aggregators"]) == 3
    for a in sec["aggregators"]:
        assert a["clients_seen"] == 2  # each aggregator heard both silos


def test_broker_pubsub_transport():
    """Broker pub/sub transport with the reference's MQTT topic scheme
    (mqtt_comm_manager.py:47-117): server(0) <-> 2 clients through one
    fan-out broker; tensors survive the round trip."""
    from neuroimagedisttraining_tpu.distributed.broker import (
        BrokerCommManager, MessageBroker,
    )

    broker = MessageBroker()
    mgrs = {cid: BrokerCommManager("127.0.0.1", broker.port,
                                   client_id=cid, client_num=2)
            for cid in (0, 1, 2)}
    got: dict[int, list] = {0: [], 1: [], 2: []}

    class Rec:
        def __init__(self, cid):
            self.cid = cid

        def receive_message(self, msg_type, msg):
            # record only — stopping here would close the manager's socket
            # while the main thread may still be sending through it
            got[self.cid].append((msg_type, msg))

    threads = {}
    for cid, mgr in mgrs.items():
        mgr.add_observer(Rec(cid))
        threads[cid] = threading.Thread(target=mgr.handle_receive_message,
                                        daemon=True)
        threads[cid].start()
    time.sleep(0.2)  # let SUB frames land before publishing

    # server -> each client; clients -> server
    for cid in (1, 2):
        msg = M.Message(M.MSG_TYPE_S2C_SYNC_MODEL, 0, cid)
        msg.add(M.ARG_MODEL_PARAMS, {"w": np.full((3,), cid, np.float32)})
        mgrs[0].send_message(msg)
    up = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, 1, 0)
    up.add(M.ARG_MODEL_PARAMS, {"w": np.ones((3,), np.float32)})
    mgrs[1].send_message(up)

    deadline = time.time() + 20
    while time.time() < deadline and not (got[0] and got[1] and got[2]):
        time.sleep(0.05)
    assert got[1] and got[2] and got[0], got
    t, m = got[2][0]
    assert t == M.MSG_TYPE_S2C_SYNC_MODEL
    np.testing.assert_array_equal(m.get(M.ARG_MODEL_PARAMS)["w"],
                                  np.full((3,), 2, np.float32))
    assert got[0][0][0] == M.MSG_TYPE_C2S_SEND_MODEL
    for mgr in mgrs.values():
        mgr.stop_receive_message()
    broker.stop()


def test_broker_retains_for_late_subscriber():
    """MQTT-retain semantics: a PUB that lands before the receiver's SUB is
    delivered at subscribe time instead of being lost (otherwise a blind
    broadcast races the SUB frame and deadlocks the protocol)."""
    from neuroimagedisttraining_tpu.distributed.broker import (
        BrokerCommManager, MessageBroker,
    )

    broker = MessageBroker()
    srv = BrokerCommManager("127.0.0.1", broker.port, client_id=0,
                            client_num=1)
    msg = M.Message(M.MSG_TYPE_S2C_SYNC_MODEL, 0, 1)
    msg.add(M.ARG_ROUND_IDX, 42)
    srv.send_message(msg)  # published before client exists
    time.sleep(0.2)

    got = []
    cli = BrokerCommManager("127.0.0.1", broker.port, client_id=1,
                            client_num=1)

    class Obs:
        def receive_message(self, t, m):
            got.append(m)
            cli.stop_receive_message()

    cli.add_observer(Obs())
    t = threading.Thread(target=cli.handle_receive_message, daemon=True)
    t.start()
    t.join(timeout=20)
    assert got and got[0].get(M.ARG_ROUND_IDX) == 42
    srv.stop_receive_message()
    broker.stop()


def test_broker_retains_latest_frame_for_late_subscriber():
    """MQTT-retain keeps only the NEWEST frame per topic: a subscriber
    attaching after several publishes receives the latest state, not the
    first — resuming peers must never train from a stale global model."""
    import socket as sock

    from neuroimagedisttraining_tpu.distributed.broker import (
        _OP_PUB, _OP_SUB, MessageBroker, _read_frame, _write_frame,
    )

    broker = MessageBroker()
    pub = sock.create_connection(("127.0.0.1", broker.port), timeout=10)
    _write_frame(pub, _OP_PUB, "model", b"round-1")
    _write_frame(pub, _OP_PUB, "model", b"round-2")
    time.sleep(0.3)  # let the broker's serve thread process both frames

    sub = sock.create_connection(("127.0.0.1", broker.port), timeout=10)
    sub.settimeout(10)
    _write_frame(sub, _OP_SUB, "model")
    frame = _read_frame(sub)
    assert frame is not None and frame[2] == b"round-2"
    for c in (pub, sub):
        c.close()
    broker.stop()


def test_broker_retained_frame_never_overtakes_live_pub():
    """Concurrency contract (broker.py:20-26): retained delivery happens
    under the new subscriber's write lock taken BEFORE registration, so a
    subscriber that attaches mid-stream may first see the stale retained
    frame but every following frame must be newer — monotone sequence
    numbers prove no live PUB was overtaken."""
    import socket as sock

    from neuroimagedisttraining_tpu.distributed.broker import (
        _OP_PUB, _OP_SUB, MessageBroker, _read_frame, _write_frame,
    )

    broker = MessageBroker()
    pub = sock.create_connection(("127.0.0.1", broker.port), timeout=10)
    _write_frame(pub, _OP_PUB, "seq", b"%08d" % 0)  # the stale retainee
    time.sleep(0.2)

    stop = threading.Event()

    def publisher():
        i = 0
        while not stop.is_set():
            i += 1
            try:
                _write_frame(pub, _OP_PUB, "seq", b"%08d" % i)
            except OSError:
                return
            time.sleep(0.001)

    th = threading.Thread(target=publisher, daemon=True)
    th.start()
    try:
        for _ in range(8):  # subscribers attach while PUBs are in flight
            sub = sock.create_connection(("127.0.0.1", broker.port),
                                         timeout=10)
            sub.settimeout(10)
            _write_frame(sub, _OP_SUB, "seq")
            seq = []
            for _ in range(5):
                frame = _read_frame(sub)
                assert frame is not None
                seq.append(int(frame[2]))
            assert seq == sorted(seq), (
                f"stale retained frame overtook a live PUB: {seq}")
            sub.close()
    finally:
        stop.set()
        th.join(timeout=10)
        pub.close()
        broker.stop()
