"""Checkpoint/resume: round-granular save/restore with bitwise-identical
replay (SURVEY §5.4 rebuild requirement — the reference lost 3-day runs at
the SLURM time limit)."""

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.utils import checkpoint as ckpt
import pytest


def test_roundtrip_arrays_and_keys(tmp_path):
    state = {
        "params": {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3)},
        "rng": jax.random.key(42),
        "history": [{"round": 0, "loss": 0.5}],
        "round_float": 3.25,
    }
    ckpt.save_checkpoint(str(tmp_path), 7, state)
    r, got = ckpt.load_checkpoint(str(tmp_path))
    assert r == 7
    np.testing.assert_array_equal(np.asarray(got["params"]["w"]),
                                  np.asarray(state["params"]["w"]))
    # PRNG key survives the trip and generates the same stream
    a = jax.random.uniform(state["rng"], (4,))
    b = jax.random.uniform(got["rng"], (4,))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert got["history"] == [{"round": 0, "loss": 0.5}]
    assert got["round_float"] == 3.25


def test_prune_keeps_newest(tmp_path):
    for r in range(6):
        ckpt.save_checkpoint(str(tmp_path), r, {"x": jnp.zeros(1)}, keep=2)
    assert ckpt.list_checkpoints(str(tmp_path)) == [4, 5]


def test_load_missing_returns_none(tmp_path):
    assert ckpt.load_checkpoint(str(tmp_path / "nope")) is None


def _engine_with_ckpt(tmp_path, cohort, ckpt_dir, comm_round, algorithm):
    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.federate import federate_cohort
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm=algorithm,
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=OptimConfig(lr=5e-4, batch_size=8, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=comm_round),
        checkpoint_dir=ckpt_dir, checkpoint_every=2 if ckpt_dir else 0,
        log_dir=str(tmp_path),
    )
    mesh = make_mesh()
    fed, _ = federate_cohort(cohort, partition_method="site", mesh=mesh)
    model = create_model(cfg.model, num_classes=1)
    trainer = LocalTrainer(model, cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    return create_engine(algorithm, cfg, fed, trainer, mesh=mesh, logger=log)


def _kill_after_round(ckpt_dir, keep_round):
    """Simulate a mid-run kill: drop every checkpoint after ``keep_round``
    so resume starts from it (schedules like DisPFL's fire-mask cosine
    anneal depend on comm_round, so the interrupted and control runs must
    share ONE comm_round — we run to completion then forget the tail)."""
    import os

    for r in ckpt.list_checkpoints(ckpt_dir):
        if r != keep_round:
            os.unlink(os.path.join(ckpt_dir, f"ckpt_{r:08d}.msgpack"))


def test_resume_bitwise_identical_fedavg(tmp_path, synthetic_cohort):
    """Run 4 rounds checkpointed, 'kill' back to the round-1 checkpoint,
    resume rounds 2-3; final params must be BITWISE identical."""
    ckpt_dir = str(tmp_path / "ck")
    eng_a = _engine_with_ckpt(tmp_path, synthetic_cohort, ckpt_dir, 4,
                              "fedavg")
    res_a = eng_a.train()
    assert ckpt.list_checkpoints(ckpt_dir) == [1, 3]
    _kill_after_round(ckpt_dir, 1)
    eng_b = _engine_with_ckpt(tmp_path, synthetic_cohort, ckpt_dir, 4,
                              "fedavg")
    res_b = eng_b.train()
    assert len(res_b["history"]) == 4  # restored history + replayed rounds
    for leaf_b, leaf_a in zip(jax.tree.leaves(res_b["params"]),
                              jax.tree.leaves(res_a["params"])):
        np.testing.assert_array_equal(np.asarray(leaf_b), np.asarray(leaf_a))


@pytest.mark.slow
def test_resume_bitwise_identical_dispfl(tmp_path, synthetic_cohort):
    """Same bitwise-resume contract for the most stateful engine (personal
    params + evolving masks)."""
    ckpt_dir = str(tmp_path / "ck2")
    eng_a = _engine_with_ckpt(tmp_path, synthetic_cohort, ckpt_dir, 4,
                              "dispfl")
    res_a = eng_a.train()
    _kill_after_round(ckpt_dir, 1)
    eng_b = _engine_with_ckpt(tmp_path, synthetic_cohort, ckpt_dir, 4,
                              "dispfl")
    res_b = eng_b.train()
    for lb, la in zip(jax.tree.leaves(res_b["personal_params"]),
                      jax.tree.leaves(res_a["personal_params"])):
        np.testing.assert_array_equal(np.asarray(lb), np.asarray(la))
    for lb, la in zip(jax.tree.leaves(res_b["masks"]),
                      jax.tree.leaves(res_a["masks"])):
        np.testing.assert_array_equal(np.asarray(lb), np.asarray(la))
