"""Wire codec (codec/, ISSUE 3): per-stage and composed encode/decode
round-trips, host==device bitwise parity, error-feedback conservation,
the tagged frame riding the message envelope, codec traffic on the REAL
socket control plane (threaded federation, byte counters, chaos), and
the engines' in-sim codec integration (mask handoff + EF threading)."""

import threading

import numpy as np
import pytest

from neuroimagedisttraining_tpu.codec import (
    FRAME_KEY,
    decode_update,
    encode_update,
    frame_nbytes,
    is_codec_frame,
    lossy_roundtrip,
    parse_wire_spec,
)
from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.distributed.ports import free_port_block


def _trees(seed=0, n=512):
    rng = np.random.default_rng(seed)
    upd = {"a": {"kernel": rng.normal(0, 0.02, (n // 8, 8))
                 .astype(np.float32)},
           "bias": rng.normal(0, 0.1, (13,)).astype(np.float32)}
    ref = {"a": {"kernel": upd["a"]["kernel"]
                 + rng.normal(0, 0.004, (n // 8, 8)).astype(np.float32)},
           "bias": upd["bias"]
           + rng.normal(0, 0.01, (13,)).astype(np.float32)}
    return upd, ref


# ---------------------------------------------------------------------------
# spec parsing + per-stage round-trips
# ---------------------------------------------------------------------------

def test_parse_wire_spec():
    assert parse_wire_spec("none") is None and parse_wire_spec("") is None
    s = parse_wire_spec("delta+sparse+quant")
    assert s.delta and s.sparse and s.quant == "int8"
    # order-insensitive canonical form
    assert parse_wire_spec("quant+delta").canonical == \
        parse_wire_spec("delta+quant").canonical == "delta+quant"
    assert parse_wire_spec("quant16").quant == "bf16"
    with pytest.raises(ValueError, match="unknown stage"):
        parse_wire_spec("delta+gzip")
    with pytest.raises(ValueError, match="cannot compose"):
        parse_wire_spec("delta+none")
    with pytest.raises(ValueError, match="topk_ratio"):
        parse_wire_spec("sparse", topk_ratio=0.0)


def test_delta_stage_roundtrip_value_exact():
    upd, ref = _trees()
    frame, ef = encode_update(parse_wire_spec("delta"), upd, reference=ref)
    assert ef is None and is_codec_frame(frame)
    dec = decode_update(frame, like=upd, reference=ref)
    # exact up to ONE float32 rounding of (u - r) + r
    np.testing.assert_allclose(dec["a"]["kernel"], upd["a"]["kernel"],
                               atol=1e-8)


def test_quant_stages_bounded_error_and_idempotent_bytes():
    upd, ref = _trees()
    for spec_str in ("quant", "quant16"):
        spec = parse_wire_spec(spec_str)
        frame, _ = encode_update(spec, upd)
        dec = decode_update(frame, like=upd)
        for name in ("bias",):
            amax = np.max(np.abs(upd[name]))
            bound = (amax / 127 / 2 * 1.001 if spec.quant == "int8"
                     else amax * 2 ** -8)  # bf16: 8 mantissa bits
            assert np.max(np.abs(dec[name] - upd[name])) <= bound
        # re-encoding the decoded values is byte-identical (values sit on
        # the quantization grid, scales reproduce exactly) — the property
        # that lets the engines account bytes from roundtripped updates
        frame2, _ = encode_update(spec, dec)
        from flax import serialization

        assert serialization.msgpack_serialize({"f": frame}) == \
            serialization.msgpack_serialize({"f": frame2})


def test_mask_sparse_stage_identity_on_support():
    upd, ref = _trees()
    rng = np.random.default_rng(3)
    mask = {"a": {"kernel": (rng.random(upd["a"]["kernel"].shape) < 0.5)
                  .astype(np.float32)},
            "bias": np.ones(13, np.float32)}
    masked_upd = {"a": {"kernel": upd["a"]["kernel"] * mask["a"]["kernel"]},
                  "bias": upd["bias"]}
    spec = parse_wire_spec("sparse")  # no quant: support values exact
    for mask_on_wire in (True, False):
        frame, ef = encode_update(spec, masked_upd, masks=mask,
                                  mask_on_wire=mask_on_wire)
        assert ef is None  # mask mode needs no error feedback
        dec = decode_update(frame, like=upd, masks=mask)
        np.testing.assert_array_equal(dec["a"]["kernel"],
                                      masked_upd["a"]["kernel"])
    # shared-mask frames fail loudly without the receiver's mask
    frame, _ = encode_update(spec, masked_upd, masks=mask,
                             mask_on_wire=False)
    with pytest.raises(ValueError, match="shared-mask"):
        decode_update(frame, like=upd)


def test_masked_delta_reconstructs_zero_off_mask():
    """Round-0 shape: the delta reference is DENSE (init) while the
    client's masked params are exactly zero off-mask — the decode must
    return 0 there, never the reference."""
    upd, ref = _trees()
    mask = {"a": {"kernel": np.zeros_like(upd["a"]["kernel"])},
            "bias": np.ones(13, np.float32)}
    mask["a"]["kernel"][::2] = 1.0
    masked_upd = {"a": {"kernel": upd["a"]["kernel"] * mask["a"]["kernel"]},
                  "bias": upd["bias"]}
    spec = parse_wire_spec("delta+sparse+quant")
    for mask_on_wire in (True, False):
        frame, _ = encode_update(spec, masked_upd, reference=ref,
                                 masks=mask, mask_on_wire=mask_on_wire)
        dec = decode_update(frame, like=upd, reference=ref, masks=mask)
        off = mask["a"]["kernel"] == 0
        assert np.all(dec["a"]["kernel"][off] == 0.0)


def test_topk_error_feedback_conservation():
    """EF invariant: decoded + new_ef == residual + old_ef — no gradient
    mass is lost, only deferred (quantization error included)."""
    upd, ref = _trees(seed=5)
    spec = parse_wire_spec("delta+sparse+quant", topk_ratio=0.25)
    ef = None
    prev_params = ref
    for _ in range(3):  # thread EF across several rounds
        frame, new_ef = encode_update(spec, upd, reference=prev_params,
                                      ef=ef)
        dec = decode_update(frame, like=upd, reference=prev_params)
        for name, leaf in (("bias", upd["bias"]),):
            resid = leaf - prev_params[name]
            corrected = resid + (ef[name] if ef is not None else 0.0)
            got = (dec[name] - prev_params[name]) + new_ef[name]
            np.testing.assert_allclose(got, corrected, atol=1e-6)
        # kept fraction ~ topk_ratio globally
        total = sum(v.size for v in (upd["a"]["kernel"], upd["bias"]))
        kept = sum(int(np.sum(v != 0))
                   for v in ((dec["a"]["kernel"] - prev_params["a"]["kernel"]),))
        assert kept <= total  # sanity; exact k is checked via support below
        ef = new_ef
        prev_params = dec


def test_host_device_bitwise_parity():
    """wire.py (numpy) encode->decode == device.py jitted lossy_roundtrip,
    bitwise — the contract that lets simulated engines reproduce exactly
    what the socket plane aggregates."""
    upd, ref = _trees(seed=7)
    rng = np.random.default_rng(11)
    mask = {"a": {"kernel": (rng.random(upd["a"]["kernel"].shape) < 0.4)
                  .astype(np.float32)},
            "bias": np.ones(13, np.float32)}
    cases = [("delta+quant", None), ("delta+sparse+quant", None),
             ("sparse+quant", None), ("quant16", None),
             ("delta+sparse+quant", mask),
             # masks supplied but NO sparse stage: the full residual
             # ships dense, masks are simply unused — must not crash
             # (salientgrads passes its mask for every spec combo)
             ("delta+quant", mask), ("quant", mask)]
    for spec_str, m in cases:
        spec = parse_wire_spec(spec_str)
        frame, ef_h = encode_update(spec, upd, reference=ref, masks=m,
                                    mask_on_wire=False)
        dec_h = decode_update(frame, like=upd, reference=ref, masks=m)
        dec_d, ef_d = lossy_roundtrip(spec, upd, reference=ref, masks=m)
        np.testing.assert_array_equal(dec_h["a"]["kernel"],
                                      np.asarray(dec_d["a"]["kernel"]),
                                      err_msg=spec_str)
        np.testing.assert_array_equal(dec_h["bias"],
                                      np.asarray(dec_d["bias"]),
                                      err_msg=spec_str)
        if ef_h is not None:
            np.testing.assert_array_equal(np.asarray(ef_h["bias"]),
                                          np.asarray(ef_d["bias"]))
    # jax-backend encode produces byte-identical frames to the numpy path
    from flax import serialization

    spec = parse_wire_spec("delta+sparse+quant")
    f_np, _ = encode_update(spec, upd, reference=ref)
    f_jx, _ = encode_update(spec, upd, reference=ref, backend="jax")
    assert serialization.msgpack_serialize({"f": f_np}) == \
        serialization.msgpack_serialize({"f": f_jx})


# ---------------------------------------------------------------------------
# frame format + message envelope
# ---------------------------------------------------------------------------

def test_frame_rides_message_envelope_and_dense_fallback():
    upd, ref = _trees()
    frame, _ = encode_update(parse_wire_spec("delta+quant"), upd,
                             reference=ref)
    msg = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, 1, 0)
    msg.add(M.ARG_MODEL_PARAMS, frame)
    msg.add(M.ARG_ROUND_IDX, 4)
    back = M.Message.from_bytes(msg.to_bytes())
    got = back.get(M.ARG_MODEL_PARAMS)
    assert is_codec_frame(got)
    dec = decode_update(got, like=upd, reference=ref)
    np.testing.assert_allclose(dec["bias"], upd["bias"], atol=1e-2)
    # dense fallback passes through untouched
    assert decode_update(upd, like=upd) is upd
    # unknown frame versions are rejected loudly, not mis-parsed
    bad = dict(frame)
    bad[FRAME_KEY] = 99
    with pytest.raises(ValueError, match="version"):
        decode_update(bad, like=upd, reference=ref)
    # delta frames refuse to decode without the reference
    with pytest.raises(ValueError, match="reference"):
        decode_update(frame, like=upd)


# ---------------------------------------------------------------------------
# socket control plane: encoded federations, bytes, chaos
# ---------------------------------------------------------------------------

def _run_federation(wire_codec="none", wire_masks=None, comm_round=3,
                    fault_spec="", num_clients=3, n=4096):
    """Threaded server + clients with a cheap numpy train_fn (client c
    pulls params toward c+1); returns the finished server."""
    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        FedAvgClientProc, FedAvgServer,
    )

    init = {"w": np.zeros((n,), np.float32)}

    def mk(c):
        def train_fn(params, r):
            p = {k: np.asarray(v, np.float32) for k, v in params.items()}
            p["w"] = p["w"] + 0.5 * ((c + 1) - p["w"])
            if wire_masks is not None:
                p["w"] = p["w"] * wire_masks["w"]
            return p, 10.0 * (c + 1)

        return train_fn

    bp = free_port_block(num_clients + 2)
    server = FedAvgServer(init, comm_round, num_clients, base_port=bp,
                          wire_masks=wire_masks,
                          round_deadline=30.0 if fault_spec else 0.0,
                          quorum=num_clients if fault_spec else 0)
    clients = []
    for c in range(num_clients):
        cl = FedAvgClientProc(c + 1, num_clients, mk(c), base_port=bp,
                              wire_codec=wire_codec, wire_masks=wire_masks)
        if fault_spec:
            from neuroimagedisttraining_tpu.faults import (
                FaultSchedule, FaultyCommManager, parse_fault_spec,
            )

            cl.com_manager = FaultyCommManager(
                cl.com_manager,
                FaultSchedule(parse_fault_spec(fault_spec), 7), c + 1)
        clients.append(cl)
    threads = [threading.Thread(target=m.run) for m in [server] + clients]
    for t in threads:
        t.start()
    assert server._done.wait(timeout=90), "federation did not complete"
    for t in threads:
        t.join(timeout=10)
    return server


def test_socket_federation_codec_parity_and_bytes():
    """The encoded federation reaches the dense run's aggregate (to
    quantization error) and the server's byte counters show the
    reduction — real sockets, real frames."""
    dense = _run_federation()
    enc = _run_federation("delta+quant")
    np.testing.assert_allclose(enc.params["w"], dense.params["w"],
                               atol=1e-2)
    assert enc.com_manager.byte_stats()["bytes_recv"] < \
        0.6 * dense.com_manager.byte_stats()["bytes_recv"]


def test_socket_federation_masked_shared_mode():
    """Mask handoff on the wire: both endpoints hold the same mask, the
    frames carry no bitmap, and off-mask entries stay exactly zero."""
    mask = {"w": (np.random.default_rng(0).random(4096) < 0.5)
            .astype(np.float32)}
    dense = _run_federation(wire_masks=mask)
    enc = _run_federation("delta+sparse+quant", wire_masks=mask)
    np.testing.assert_allclose(enc.params["w"], dense.params["w"],
                               atol=1e-2)
    assert np.all(enc.params["w"][mask["w"] == 0] == 0)


def test_chaos_duplicates_on_encoded_frames():
    """FaultyCommManager dup:1.0 re-delivers EVERY encoded upload; the
    server's round-tag dedup must keep the aggregate identical to the
    unfaulted encoded run."""
    clean = _run_federation("delta+quant")
    dup = _run_federation("delta+quant", fault_spec="dup:1.0")
    assert len(dup.history) == len(clean.history)
    np.testing.assert_allclose(dup.params["w"], clean.params["w"],
                               atol=1e-6)


def test_truncated_encoded_frame_dropped_then_delivery():
    """A mid-frame disconnect on an ENCODED frame (the chaos wrapper's
    torn write) must not kill the listener; a retransmitted whole frame
    still decodes."""
    import socket
    import struct

    from neuroimagedisttraining_tpu.distributed.comm import (
        SocketCommManager,
    )

    upd, ref = _trees()
    frame, _ = encode_update(parse_wire_spec("delta+quant"), upd,
                             reference=ref)
    msg = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, 0, 1)
    msg.add(M.ARG_MODEL_PARAMS, frame)
    raw = msg.to_bytes()
    bp = free_port_block(4)
    b = SocketCommManager(1, 2, base_port=bp)
    got = []

    class Obs:
        def receive_message(self, t, m):
            got.append(m)
            b.stop_receive_message()

    b.add_observer(Obs())
    runner = threading.Thread(target=b.handle_receive_message)
    runner.start()
    # torn frame: full length prefix, half the encoded payload
    with socket.create_connection(("127.0.0.1", bp + 1), timeout=5) as c:
        c.sendall(struct.pack("!Q", len(raw)) + raw[: len(raw) // 2])
    a = SocketCommManager(0, 2, base_port=bp)
    a.send_message(msg)
    runner.join(timeout=15)
    a.stop_receive_message()
    assert len(got) == 1
    dec = decode_update(got[0].get(M.ARG_MODEL_PARAMS), like=upd,
                        reference=ref)
    np.testing.assert_allclose(dec["bias"], upd["bias"], atol=1e-2)


def test_secure_mode_rejects_wire_codec():
    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        SecureFedAvgClientProc, SecureFedAvgServer,
    )

    bp = free_port_block(4)
    with pytest.raises(ValueError, match="incompatible"):
        SecureFedAvgServer({"w": np.zeros(3, np.float32)}, 1, 1,
                           base_port=bp, wire_masks={"w": np.ones(3)})
    with pytest.raises(ValueError, match="incompatible"):
        SecureFedAvgClientProc(1, 1, lambda p, r: (p, 1.0),
                               base_port=bp + 2, wire_codec="delta+quant")


# ---------------------------------------------------------------------------
# engine integration (in-sim codec, mask handoff, EF threading)
# ---------------------------------------------------------------------------

def _engine(tmp_path, cohort, algorithm, wire_codec, **fed_kw):
    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig,
        SparsityConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.federate import federate_cohort
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm=algorithm,
        data=DataConfig(dataset="synthetic"),
        optim=OptimConfig(lr=1e-3, batch_size=8, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=2,
                      frequency_of_the_test=1, wire_codec=wire_codec,
                      **fed_kw),
        sparsity=SparsityConfig(dense_ratio=0.5),
        log_dir=str(tmp_path))
    mesh = make_mesh()
    fed, _ = federate_cohort(cohort, partition_method="site", mesh=mesh)
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1),
                           cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    return create_engine(algorithm, cfg, fed, trainer, mesh=mesh,
                         logger=log)


@pytest.mark.slow
def test_fedavg_engine_wire_codec_ef_and_bytes(tmp_path, synthetic_cohort):
    """FedAvg with delta+sparse+quant: rounds run, encoded bytes are
    accounted below the dense wire, and the per-client error-feedback
    stacks are threaded (nonzero after a lossy round)."""
    import jax

    e = _engine(tmp_path, synthetic_cohort, "fedavg", "delta+sparse+quant")
    r = e.train()
    assert np.isfinite(r["history"][-1]["train_loss"])
    enc = e.stat_info["sum_comm_bytes"]
    den = e.stat_info["sum_comm_bytes_dense"]
    assert 0 < enc < den / 3  # sparse+quant must beat 3x on the uplink
    ef_leaf = jax.tree.leaves(e._wire_ef)[0]
    assert float(np.max(np.abs(np.asarray(ef_leaf)))) > 0.0


@pytest.mark.slow
def test_salientgrads_engine_mask_handoff(tmp_path, synthetic_cohort):
    """SalientGrads with the codec: the engine hands its phase-1 mask to
    the wire (wire_masks), aggregation stays masked (off-mask zeros
    survive the encoded roundtrip), and masked-sparse bytes beat the
    dense wire."""
    import jax

    e = _engine(tmp_path, synthetic_cohort, "salientgrads",
                "delta+sparse+quant")
    r = e.train()
    assert np.isfinite(r["history"][-1]["train_loss"])
    masks = e.wire_masks()
    assert masks is not None
    # off-mask entries of the aggregate are exactly zero (mask-zero wire
    # semantics composed with masked training)
    for name_leaf, mask_leaf in zip(jax.tree.leaves(r["params"]),
                                    jax.tree.leaves(masks)):
        arr = np.asarray(name_leaf)
        m = np.asarray(mask_leaf)
        if m.min() == 0:  # a genuinely masked leaf
            assert np.all(arr[m == 0] == 0.0)
    assert 0 < e.stat_info["sum_comm_bytes"] < \
        e.stat_info["sum_comm_bytes_dense"] / 3


def test_wire_codec_streaming_unsupported(tmp_path, synthetic_cohort):
    """The in-sim codec is resident-path only; --streaming + --wire_codec
    must fail with the documented config error, not misbehave."""
    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model

    class _FakeStream:
        num_clients = 4
        n_train = np.ones(4)
        sample_shape = (12, 14, 12)

    cfg = ExperimentConfig(
        model="3dcnn_tiny", algorithm="fedavg",
        data=DataConfig(dataset="synthetic"),
        optim=OptimConfig(batch_size=8, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=1,
                      wire_codec="delta+quant"),
        log_dir=str(tmp_path))
    trainer = LocalTrainer(create_model("3dcnn_tiny", num_classes=1),
                           cfg.optim, num_classes=1)
    with pytest.raises(ValueError, match="wire_codec"):
        create_engine("fedavg", cfg, None, trainer, stream=_FakeStream())


def test_server_drops_undecodable_frame_without_dying():
    """A frame with a future codec version (or any decode failure) is a
    DROPPED upload — the dispatch thread survives and a good retransmit
    completes the round."""
    from neuroimagedisttraining_tpu.distributed.cross_silo import (
        FedAvgClientProc, FedAvgServer,
    )

    init = {"w": np.zeros((32,), np.float32)}
    bp = free_port_block(4)
    server = FedAvgServer(init, 1, 1, base_port=bp)
    st = threading.Thread(target=server.run)
    st.start()

    sent_bad = []

    class BadThenGoodClient(FedAvgClientProc):
        def _on_sync(self, msg):
            if not sent_bad:
                sent_bad.append(True)
                frame, _ = encode_update(
                    parse_wire_spec("quant"),
                    {"w": np.ones(32, np.float32)})
                bad = dict(frame)
                bad[FRAME_KEY] = 99  # future version: must not kill dispatch
                out = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, self.rank, 0)
                out.add(M.ARG_MODEL_PARAMS, bad)
                out.add(M.ARG_NUM_SAMPLES, 1.0)
                out.add(M.ARG_ROUND_IDX, int(msg.get(M.ARG_ROUND_IDX)))
                self.send_message(out)
            super()._on_sync(msg)  # then the good (dense) upload

    client = BadThenGoodClient(1, 1, lambda p, r: (
        {"w": np.full(32, 2.0, np.float32)}, 8.0), base_port=bp)
    ct = threading.Thread(target=client.run)
    ct.start()
    assert server._done.wait(timeout=60), "server died on a bad frame"
    st.join(timeout=10)
    ct.join(timeout=10)
    np.testing.assert_array_equal(server.params["w"],
                                  np.full(32, 2.0, np.float32))


def test_unsupported_engine_rejects_wire_codec(tmp_path, synthetic_cohort):
    """Engines whose round program does not run the codec roundtrip must
    reject --wire_codec loudly (silently training dense while reporting
    sum_comm_bytes=0 — or TurboAggregate's inherited 7-arg call into its
    6-arg round — would be worse)."""
    for algo in ("turboaggregate", "dispfl"):
        with pytest.raises(ValueError, match="wire_codec"):
            _engine(tmp_path, synthetic_cohort, algo, "delta+quant")
