"""Federation-wide telemetry fan-in (ISSUE 13, obs/fanin.py).

Covers the clock-offset handshake estimator (recovery within the
rtt/2 bound), the per-process artifact path suffixing, the merged
Prometheus exposition (worker labels, one TYPE block per name,
cumulative histogram rendering, staleness gauges across a dead
worker), the merged Chrome trace (clock rebase math, process
metadata), the merged flight dump (per-worker provenance), the
incremental shipper, the wire trace context roundtrip (worker-core
flow step + buffered-server flow end linking to a client flow start),
and the upload-stage histograms.
"""

import json
import re

import numpy as np
import pytest

from neuroimagedisttraining_tpu.asyncfl.ingest import (
    IngestWorkerCore,
    make_fold_spec,
)
from neuroimagedisttraining_tpu.asyncfl.loadgen import canned_update_tree
from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.obs import fanin as obs_fanin
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import trace as obs_trace
from neuroimagedisttraining_tpu.obs.fanin import (
    TelemetryFanIn,
    WorkerObsShipper,
    estimate_clock_offset,
    linked_flow_ids,
    suffixed_path,
)
from neuroimagedisttraining_tpu.obs.flight import FlightRecorder
from neuroimagedisttraining_tpu.obs.metrics import MetricsRegistry
from neuroimagedisttraining_tpu.obs.trace import SpanTracer

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? "
    r"(-?\d+(\.\d+)?([eE][+-]?\d+)?|\+Inf|-Inf|NaN)$")


# ------------------------------------------------ clock handshake


def test_clock_offset_recovered_within_rtt_bound():
    """Synthetic handshake with a KNOWN worker-vs-root skew: the
    estimator must land within rtt/2 of the truth for any placement of
    the worker's reply inside the round trip."""
    true_offset = 5_000_000  # worker clock runs 5 ms ahead
    t0 = 1_000_000_000
    rtt = 2_000_000
    t1 = t0 + rtt
    for frac in (0.0, 0.25, 0.5, 0.9, 1.0):
        # the worker read its clock somewhere inside the round trip
        t_read_root = t0 + int(frac * rtt)
        t_worker = t_read_root + true_offset
        off, err = estimate_clock_offset(t0, t_worker, t1)
        assert err == rtt // 2
        assert abs(off - true_offset) <= err, (frac, off)


def test_clock_offset_zero_rtt_exact():
    off, err = estimate_clock_offset(100, 350, 100)
    assert off == 250 and err == 0


# ------------------------------------------------ path suffixing


def test_suffixed_path_inserts_before_extension():
    assert suffixed_path("out/trace.json", 0) == "out/trace.w0.json"
    assert suffixed_path("flight", 3) == "flight.w3"
    assert suffixed_path("", 1) == ""


# ------------------------------------------------ merged exposition


def _worker_payload(wid, extra_metric=None):
    reg = MetricsRegistry()
    reg.counter("nidt_w_uploads_total", "uploads",
                labelnames=("outcome",)).inc(10 + wid, outcome="accepted")
    reg.histogram("nidt_w_lat_ms", "latency",
                  buckets=(1.0, 5.0)).observe(2.0)
    if extra_metric:
        reg.gauge(extra_metric).set(wid)
    t = SpanTracer()
    t.arm(tags={"worker": wid})
    with t.span("w_span"):
        pass
    fl = FlightRecorder(capacity=16)
    fl.record("dropped_stale", client=1, worker=wid)
    return WorkerObsShipper(registry=reg, tracer=t,
                            flight=fl).payload(force=True)


def _fanin_with_two_workers():
    root_reg = MetricsRegistry()
    root_reg.gauge("nidt_root_round").set(4)
    root_t = SpanTracer()
    root_t.arm()
    with root_t.span("aggregate", version=1):
        pass
    root_fl = FlightRecorder(capacity=16)
    root_fl.record("aggregate", version=1)
    fi = TelemetryFanIn(registry=root_reg, tracer=root_t,
                        flight=root_fl)
    for wid in (0, 1):
        fi.register_worker(wid)
        fi.ingest(wid, _worker_payload(wid))
    return fi


def test_merged_exposition_labels_types_and_staleness():
    fi = _fanin_with_two_workers()
    fi.mark_dead(1)  # SIGKILL: snapshot stays, staleness reads it
    text = fi.prometheus_text()
    for line in text.strip().splitlines():
        assert line.startswith("#") or _SAMPLE_RE.match(line), line
    # one TYPE block per metric name — duplicate blocks are invalid
    # exposition and what a naive per-source concatenation produces
    types = [ln.split()[2] for ln in text.splitlines()
             if ln.startswith("# TYPE")]
    names = [ln.split()[2] for ln in text.splitlines()
             if ln.startswith("# TYPE")]
    assert len(names) == len(set(names))
    assert types  # non-empty
    # BOTH workers' samples, worker-labeled; root sample unlabeled
    assert re.search(r'nidt_w_uploads_total\{[^}]*worker="0"[^}]*\} 10',
                     text)
    assert re.search(r'nidt_w_uploads_total\{[^}]*worker="1"[^}]*\} 11',
                     text)
    assert "nidt_root_round 4" in text
    # histograms render CUMULATIVE with worker labels
    assert re.search(
        r'nidt_w_lat_ms_bucket\{[^}]*worker="0"[^}]*le="5"[^}]*\} 1',
        text) or re.search(
        r'nidt_w_lat_ms_bucket\{[^}]*le="5"[^}]*worker="0"[^}]*\} 1',
        text)
    # staleness plane: ages for both, alive 1/0 across the kill
    assert re.search(r'nidt_obs_worker_snapshot_age_s\{worker="0"\} ',
                     text)
    assert 'nidt_obs_worker_alive{worker="0"} 1' in text
    assert 'nidt_obs_worker_alive{worker="1"} 0' in text
    # the dead worker's LAST snapshot is still served
    assert re.search(r'nidt_w_uploads_total\{[^}]*worker="1"', text)


def test_merged_view_serves_over_http():
    from neuroimagedisttraining_tpu.obs.http import MetricsServer
    import urllib.request

    fi = _fanin_with_two_workers()
    srv = MetricsServer(0, registry=fi.metrics_view())
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics").read().decode()
        assert 'worker="0"' in body and 'worker="1"' in body
    finally:
        srv.close()


# ------------------------------------------------ merged trace


def test_merged_trace_rebases_worker_timelines():
    root_t = SpanTracer()
    root_t.arm()
    with root_t.span("root_span"):
        pass
    fi = TelemetryFanIn(registry=MetricsRegistry(), tracer=root_t,
                        flight=FlightRecorder())
    fi.register_worker(0)
    # synthetic worker: epoch 7 ms after the root's, clock 2 ms ahead
    root_epoch = root_t.epoch_ns
    w_epoch = root_epoch + 7_000_000
    offset = 2_000_000
    t0 = 10_000
    fi.note_clock(0, t0, (t0 + t0) // 2 + offset, t0)  # rtt 0 -> exact
    fi.ingest(0, {
        "metrics": None, "pid": 4242, "epoch_ns": w_epoch,
        "spans": [{"name": "w_span", "ph": "X", "ts": 100.0,
                   "dur": 5.0, "pid": 4242, "tid": 1, "args": {}}],
        "spans_dropped": 0, "flight": [], "t_wall": 0.0})
    doc = fi.merged_trace_doc()
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    evs = doc["traceEvents"]
    w = next(e for e in evs if e["name"] == "w_span")
    # 100 µs past the worker epoch = root-relative
    # 100 + (epoch_w - offset - epoch_root)/1e3 = 100 + 7000 - 2000
    assert w["ts"] == pytest.approx(100.0 + 5000.0)
    names = {e["args"]["name"] for e in evs if e["ph"] == "M"}
    assert names == {"ingest-root", "ingest-worker-0"}
    assert any(e["name"] == "root_span" for e in evs)


def test_merged_trace_dump_and_drop_accounting(tmp_path):
    fi = _fanin_with_two_workers()
    fi.ingest(0, {"metrics": None, "spans": [], "spans_dropped": 3,
                  "flight": [], "t_wall": 0.0})
    out = fi.dump_trace(str(tmp_path / "merged.json"))
    doc = json.load(open(out))
    assert doc["nidtDroppedEvents"] == 3
    assert any(e.get("ph") == "X" for e in doc["traceEvents"])


# ------------------------------------------------ merged flight


def test_merged_flight_carries_worker_provenance(tmp_path):
    fi = _fanin_with_two_workers()
    doc = fi.merged_flight_doc(reason="test")
    procs = {e["proc"] for e in doc["events"]}
    assert procs == {"root", "worker0", "worker1"}
    w_ev = next(e for e in doc["events"] if e["proc"] == "worker0")
    assert w_ev["worker"] == 0 and w_ev["kind"] == "dropped_stale"
    # wall-clock ordered (the cross-process join key)
    walls = [e.get("t_wall", 0.0) for e in doc["events"]]
    assert walls == sorted(walls)
    out = fi.dump_flight(str(tmp_path / "merged_flight.json"),
                         reason="test")
    assert json.load(open(out))["workers"]["1"]["alive"] is True


# ------------------------------------------------ incremental shipper


def test_shipper_ships_only_new_events_and_rate_limits():
    reg = MetricsRegistry()
    t = SpanTracer()
    t.arm()
    fl = FlightRecorder(capacity=8)
    sh = WorkerObsShipper(interval_s=3600.0, registry=reg, tracer=t,
                          flight=fl)
    with t.span("a"):
        pass
    fl.record("x", i=1)
    p1 = sh.payload(force=True)
    assert [e["name"] for e in p1["spans"]] == ["a"]
    assert [e["i"] for e in p1["flight"]] == [1]
    # nothing new -> empty chunks; rate limit blocks unforced ships
    assert sh.payload() is None
    p2 = sh.payload(force=True)
    assert p2["spans"] == [] and p2["flight"] == []
    with t.span("b"):
        pass
    fl.record("y", i=2)
    p3 = sh.payload(force=True)
    assert [e["name"] for e in p3["spans"]] == ["b"]
    assert [e["i"] for e in p3["flight"]] == [2]


# ------------------------------------------------ trace-context flows


LIKE = canned_update_tree(0, 64)


def _upload_msg(c, seq, ctx=True):
    msg = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, c, 0)
    msg.add(M.ARG_MODEL_PARAMS, canned_update_tree(c, 64))
    msg.add(M.ARG_NUM_SAMPLES, 8.0)
    msg.add(M.ARG_ROUND_IDX, 0)
    msg.add(M.ARG_UPLOAD_SEQ, seq)
    if ctx:
        msg.add(M.ARG_TRACE_CTX, obs_trace.make_trace_ctx(c, seq))
    return msg


def test_trace_ctx_helpers():
    ctx = obs_trace.make_trace_ctx(3, 7)
    assert obs_trace.flow_id_of(ctx) == (3 << 24) | 7
    assert obs_trace.flow_id_of(None) is None
    assert obs_trace.flow_id_of({"trace_id": "junk"}) is None
    assert obs_trace.flow_id_of("nonsense") is None


def test_worker_core_emits_flow_step_and_threads_ctx():
    obs_metrics.reset()
    obs_trace.arm()
    try:
        core = IngestWorkerCore(0, make_fold_spec(LIKE), LIKE,
                                max_staleness=4, staleness_alpha=0.5)
        msg = _upload_msg(3, 0)
        assert core.handle_upload(msg) == "accepted"
        fid = obs_trace.flow_id_of(msg.get(M.ARG_TRACE_CTX))
        # ctx rides the entry (element 6) to the root's flow END
        assert core.entries[-1][6] == fid
        evs = obs_trace.TRACER.events()
        steps = [e for e in evs if e.get("ph") == "t"]
        assert steps and steps[0]["id"] == fid
        # the step is INSIDE the ingest_upload span (Perfetto binding)
        slab = next(e for e in evs if e["name"] == "ingest_upload")
        assert slab["ts"] <= steps[0]["ts"] <= slab["ts"] + slab["dur"]
        # a ctx-less upload processes identically, just unlinked
        assert core.handle_upload(_upload_msg(4, 0, ctx=False)) == \
            "accepted"
        assert core.entries[-1][6] is None
    finally:
        obs_trace.disarm()


class _CaptureComm:
    def __init__(self):
        self.sent = []

    def send_message(self, msg, **kw):
        self.sent.append(msg)

    def add_observer(self, obs):
        pass

    def remove_observer(self, obs):
        pass

    def handle_receive_message(self):
        pass

    def stop_receive_message(self):
        pass

    def byte_stats(self):
        return {}


def test_flow_roundtrip_client_to_aggregate():
    """The linkage oracle: a client flow start + the server's
    admission step + the aggregation end share one id — what the
    merged trace renders as a causally-linked upload."""
    from neuroimagedisttraining_tpu.asyncfl.server import (
        BufferedFedAvgServer,
    )

    obs_metrics.reset()
    obs_trace.arm()
    try:
        srv = BufferedFedAvgServer(canned_update_tree(0, 12), 10, 3,
                                   buffer_k=2, comm=_CaptureComm())

        def up(c, seq):
            m = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, c, 0)
            m.add(M.ARG_MODEL_PARAMS, canned_update_tree(c, 12))
            m.add(M.ARG_NUM_SAMPLES, 4.0)
            m.add(M.ARG_ROUND_IDX, 0)
            m.add(M.ARG_UPLOAD_SEQ, seq)
            ctx = obs_trace.make_trace_ctx(c, seq)
            m.add(M.ARG_TRACE_CTX, ctx)
            with obs_trace.span("client_upload", client=c):
                obs_trace.flow("upload", obs_trace.flow_id_of(ctx),
                               "s", client=c)
            return m

        srv._on_model(up(1, 0))
        srv._on_model(up(2, 0))
        assert srv.round_idx == 1
        flows = linked_flow_ids(obs_trace.TRACER.events())
        assert len(flows["linked"]) == 2
        ends = [e for e in obs_trace.TRACER.events()
                if e.get("ph") == "f"]
        assert all(e["bp"] == "e" for e in ends)
    finally:
        obs_trace.disarm()


# ------------------------------------------------ stage histograms


def test_upload_stage_histograms_observed():
    import time

    obs_metrics.reset()
    core = IngestWorkerCore(0, make_fold_spec(LIKE), LIKE,
                            max_staleness=4, staleness_alpha=0.5)
    msg = _upload_msg(1, 0)
    msg.recv_ns = time.perf_counter_ns()  # the loop.py stamp
    assert core.handle_upload(msg) == "accepted"
    snap = obs_metrics.snapshot()
    by_stage = {v["labels"]["stage"]: v["value"]
                for v in snap["nidt_upload_stage_ms"]["values"]}
    assert set(by_stage) == {"queue", "decode", "admit", "fold"}
    for stage, cell in by_stage.items():
        assert cell["count"] == 1, stage
    # a gate rejection before decode observes no decode/fold stage
    stale = _upload_msg(1, 0)  # duplicate seq -> dropped at the gate
    assert core.handle_upload(stale) == "dropped_duplicate"
    snap = obs_metrics.snapshot()
    by_stage = {v["labels"]["stage"]: v["value"]
                for v in snap["nidt_upload_stage_ms"]["values"]}
    assert by_stage["admit"]["count"] == 2
    assert by_stage["decode"]["count"] == 1


def test_rtt_histogram_registers_and_observes():
    obs_metrics.reset()
    h = obs_fanin.rtt_histogram()
    h.observe(42.0)
    snap = obs_metrics.snapshot()
    cell = snap["nidt_client_rtt_ms"]["values"][0]["value"]
    assert cell["count"] == 1
    assert cell["buckets"]["50"] == 1


# ------------------------------------------------ flight seq plumbing


def test_flight_events_from_watermark():
    fl = FlightRecorder(capacity=3)
    for i in range(5):
        fl.record("e", i=i)
    evs, mark = fl.events_from(0)
    # ring evicted 0 and 1 — bounded-ring honesty, not an error
    assert [e["i"] for e in evs] == [2, 3, 4] and mark == 5
    evs2, mark2 = fl.events_from(mark)
    assert evs2 == [] and mark2 == 5
    fl.record("e", i=5)
    evs3, _ = fl.events_from(mark)
    assert [e["i"] for e in evs3] == [5]


def test_linked_flow_ids_groups_phases():
    evs = [{"ph": "s", "id": 1}, {"ph": "t", "id": 1},
           {"ph": "f", "id": 1}, {"ph": "s", "id": 2},
           {"ph": "X", "name": "slice"}]
    flows = linked_flow_ids(evs)
    assert flows["linked"] == {1}
    assert flows["s"] == {1, 2}
