"""Cohort sharding tests (ISSUE 6): one dispatched program, all sites.

The contract (parallel/cohort.py, stated with the precision the
measurements force):

(a) vs the sequential C-loop (the same unbatched per-client loop in an
    unpartitioned program): a FedAvg round's training losses from
    identical state are BITWISE equal — the proof that batch selection,
    masking, weighting, every semantic choice is identical (the masked
    salientgrads round's mean loss sits exactly 1 float32 ulp off: the
    mask multiply adds a fusion seam) — and trained state
    agrees to ~1 ulp of its own magnitude (an XLA compile-context
    tiling artifact — measured, documented in parallel/cohort.py — NOT
    a semantic divergence; the SEMANTIC divergence partitioned compiles
    DO produce, the in-partition random-sort miscompile, is hoisted
    away by design and would resurface here as 1e-0-level loss
    divergence if it regressed).
(b) MESH-WIDTH INDEPENDENCE to the same ~1 ulp through different pad
    counts (21 real sites pad to 22 rows on 2 devices, 24 on 8);
    exactly-bitwise equality holds where the compiled module is shared:
    a K=4 fused window == four single sharded dispatches, BITWISE.
(c) K=4 fused windows, the Byzantine attack/defense tail, and the wire
    codec's EF stacks all compose on the sharded path under (a)/(b).
(d) Engines/modes without a sharded round body fall back to the
    unsharded round with a logged reason (the fused-dispatch pattern);
    config mismatches fail loudly at startup.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.config import (
    DataConfig, ExperimentConfig, FedConfig, OptimConfig,
)
from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
from neuroimagedisttraining_tpu.data import partition as P
from neuroimagedisttraining_tpu.data.federate import federate_cohort
from neuroimagedisttraining_tpu.data.stream import StreamingFederation
from neuroimagedisttraining_tpu.data.synthetic import generate_synthetic_abcd
from neuroimagedisttraining_tpu.engines import create_engine
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.parallel import cohort
from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

#: bounds for the measured ~1-ulp compile-context residue between
#: partitioned and unpartitioned programs (parallel/cohort.py); relative
#: 1e-6 ≈ 8 float32 ulps of headroom on each leaf's own magnitude (BN
#: running vars sit near 1e2, params near 1e0), atol covers near-zero
#: entries — both far below any training-relevant scale
ULP_RTOL = 1e-6
ULP_ATOL = 1e-6


@pytest.fixture(scope="module")
def cohort21():
    """The flagship pad case: 21 real acquisition sites (seed-picked so
    every site survives the 80/20 split), padding to 24 rows on the
    8-device mesh and 22 on a 2-device mesh."""
    return generate_synthetic_abcd(num_subjects=84, shape=(12, 14, 12),
                                   num_sites=21, seed=5)


def _engine(tmp_path, cohort_data, algorithm="fedavg", client_mesh=8,
            n_dev=None, seq=False, C=21, comm_round=2, freq=2, tag="c",
            stream=False, val_fraction=0.0, mesh=None, **fed_kw):
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm=algorithm,
        data=DataConfig(dataset="synthetic", partition_method="site",
                        val_fraction=val_fraction),
        optim=OptimConfig(lr=1e-3, batch_size=8, epochs=1),
        fed=FedConfig(client_num_in_total=C, comm_round=comm_round,
                      frequency_of_the_test=freq, client_mesh=client_mesh,
                      **fed_kw),
        log_dir=str(tmp_path), tag=tag)
    if mesh is None:
        mesh = make_mesh(num_devices=n_dev)
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1),
                           cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    if stream:
        train_map, test_map, _ = P.site_partition(cohort_data["site"],
                                                  seed=42)
        feed = StreamingFederation(np.asarray(cohort_data["X"]),
                                   np.asarray(cohort_data["y"]),
                                   train_map, test_map, mesh=mesh)
        eng = create_engine(algorithm, cfg, None, trainer, mesh=mesh,
                            logger=log, stream=feed)
    else:
        fed, _ = federate_cohort(cohort_data, partition_method="site",
                                 mesh=mesh, val_fraction=val_fraction)
        eng = create_engine(algorithm, cfg, fed, trainer, mesh=mesh,
                            logger=log)
    eng._donate = False
    if seq:
        # the sequential C-loop reference: same padded program shape,
        # local stage lowered as ONE unpartitioned per-client loop
        eng._cohort_sequential = True
    return eng


def _assert_trees_bitwise(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _assert_trees_ulp(a, b, rtol=ULP_RTOL, atol=ULP_ATOL):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x, np.float64),
                                   np.asarray(y, np.float64),
                                   rtol=rtol, atol=atol)


def _log_text(eng) -> str:
    with open(eng.log.log_path) as f:
        return f.read()


# ---------------------------------------------------------------------------
# pad helpers (the shared rule the mesh-pad-weights lint enforces)
# ---------------------------------------------------------------------------

def test_pad_cohort_rules():
    # tiling: untouched
    ids, n = cohort.pad_cohort(np.arange(8), 8, 8, 8)
    assert n == 8 and np.array_equal(ids, np.arange(8))
    # non-tiling with a zero-sample pool: pool rows first
    ids, n = cohort.pad_cohort(np.arange(21), 21, 24, 8)
    assert n == 21 and len(ids) == 24
    assert ids[21:].tolist() == [21, 22, 23]
    # pool exhausted: repeat the last sampled id (the DUPLICATE case the
    # position mask exists for)
    ids, n = cohort.pad_cohort(np.array([0, 1, 2]), 3, 3, 2)
    assert n == 3 and ids.tolist() == [0, 1, 2, 2]
    with pytest.raises(ValueError, match="empty sampled set"):
        cohort.pad_cohort(np.array([], dtype=int), 3, 3, 2)


def test_pad_row_weights_zero_by_position():
    ns = jnp.asarray([5, 3, 7, 7], jnp.int32)  # row 3 duplicates row 2
    out = np.asarray(cohort.pad_row_weights(ns, 3))
    assert out.tolist() == [5, 3, 7, 0]  # position, not sample count


def test_cohort_map_rejects_non_tiling_and_two_level():
    mesh = make_mesh()
    with pytest.raises(ValueError, match="does not tile"):
        cohort.cohort_map(mesh, lambda x: x, jnp.zeros((21, 2)))
    mesh2 = make_mesh(shape=(2, 4))
    with pytest.raises(ValueError, match="1-D client mesh"):
        cohort.cohort_map(mesh2, lambda x: x, jnp.zeros((8, 2)))


# ---------------------------------------------------------------------------
# (b) sharded round vs the sequential C-loop (program level)
# ---------------------------------------------------------------------------

def _one_sharded_round(eng, round_idx=0, efs=None, masks=None):
    gs = eng.init_global_state()
    sampled = eng.client_sampling(round_idx)
    ids, n_real = eng._cohort_pad(sampled)
    rngs = eng.per_client_rngs(round_idx, ids)
    byz = eng._byz_round_plan(round_idx, sampled)
    lr = eng.round_lr(round_idx)
    if eng.name == "salientgrads":
        if masks is None:
            masks, _ = eng.generate_global_mask(gs.params,
                                                gs.batch_stats)
        per = eng.broadcast_states(gs, eng.num_clients)
        out = eng._sharded_round_jit(n_real)(
            gs.params, gs.batch_stats, per.params, per.batch_stats,
            eng.data, masks, jnp.asarray(ids), rngs, lr, byz)
        return out
    if efs is not None:
        efs = jax.tree.map(
            lambda x: jnp.zeros((n_real,) + x.shape, jnp.float32),
            {"params": gs.params, "batch_stats": gs.batch_stats})
    out = eng._sharded_round_jit(n_real)(
        gs.params, gs.batch_stats, eng.data, jnp.asarray(ids), rngs, lr,
        efs, byz)
    return out


@pytest.mark.parametrize("algorithm", [
    "fedavg",
    pytest.param("salientgrads", marks=pytest.mark.slow),  # tier-1 window (PR 7): fedavg twin stays; salientgrads keeps the 1-ulp mask pin in the slow suite
])
def test_sharded_round_vs_sequential_loop(tmp_path, cohort21, algorithm):
    """The non-tiling flagship case (21 sites -> 24 rows on 8 devices):
    per-round loss bitwise, state within the 1-ulp compile-context
    residue of the sequential C-loop. Salientgrads rounds run on ONE
    shared phase-1 mask (the mask pipelines are cross-checked in
    test_salientgrads_sharded_mask below): its own sharded scores carry
    the same 1-ulp residue, so a mask threshold from the sharded
    pipeline sits an ulp off the sequential one's — with the mask held
    fixed, the round itself is exactly as tight as FedAvg's."""
    eng_sh = _engine(tmp_path, cohort21, algorithm, tag="sh")
    eng_sq = _engine(tmp_path, cohort21, algorithm, seq=True, tag="sq")
    masks = None
    if algorithm == "salientgrads":
        gs = eng_sq.init_global_state()
        masks, _ = eng_sq.generate_global_mask(gs.params, gs.batch_stats)
    out_sh = _one_sharded_round(eng_sh, masks=masks)
    out_sq = _one_sharded_round(eng_sq, masks=masks)
    loss_i = 4 if algorithm == "salientgrads" else 2
    if algorithm == "fedavg":
        # bitwise: the semantic proof (identical batch selection/
        # masking/weighting on both paths)
        np.testing.assert_array_equal(np.asarray(out_sh[loss_i]),
                                      np.asarray(out_sq[loss_i]))
    else:
        # the per-step mask multiply adds one more fusion seam, which
        # tiles a loss reduction differently — measured at exactly 1
        # float32 ulp on this seed (0x1p-24 relative); anything larger
        # would be the miscompile class the hoist guards against
        np.testing.assert_allclose(float(out_sh[loss_i]),
                                   float(out_sq[loss_i]), rtol=3e-7)
    _assert_trees_ulp(out_sh, out_sq)


def test_salientgrads_sharded_mask(tmp_path, cohort21):
    """Phase-1 under the sharded driver: scores carry the 1-ulp SPMD
    residue, so the top-k threshold may sit an ulp off the sequential
    pipeline's — but on this seed no score lands inside that window and
    the emitted MASKS are identical (density is pinned either way)."""
    eng_sh = _engine(tmp_path, cohort21, "salientgrads", tag="msh")
    eng_sq = _engine(tmp_path, cohort21, "salientgrads", seq=True,
                     tag="msq")
    gs = eng_sh.init_global_state()
    mk_sh, thr_sh = eng_sh.generate_global_mask(gs.params, gs.batch_stats)
    gs2 = eng_sq.init_global_state()
    mk_sq, thr_sq = eng_sq.generate_global_mask(gs2.params,
                                                gs2.batch_stats)
    np.testing.assert_allclose(float(thr_sh), float(thr_sq), rtol=1e-6)
    _assert_trees_bitwise(mk_sh, mk_sq)


def test_sharded_round_byz_defense_composes(tmp_path, synthetic_cohort):
    """Attack + sanitize + defend tail on the sharded path: the byz plan
    covers the REAL sampled set (pads sliced off before the tail)."""
    kw = dict(algorithm="fedavg", C=4, tag="byz",
              fault_spec="byz:3@0:sign_flip", defense_type="trimmed_mean",
              byz_f=1)
    out_sh = _one_sharded_round(_engine(tmp_path, synthetic_cohort, **kw))
    out_sq = _one_sharded_round(
        _engine(tmp_path, synthetic_cohort, seq=True, **kw))
    np.testing.assert_array_equal(np.asarray(out_sh[2]),
                                  np.asarray(out_sq[2]))
    _assert_trees_ulp(out_sh, out_sq)


def test_sharded_round_wire_codec_ef_composes(tmp_path, synthetic_cohort):
    """The codec roundtrip + per-client EF stacks ride the sharded round:
    EF rows are sized for the REAL sampled set and the decoded uploads /
    new EF rows match the sequential loop's within the ulp residue."""
    kw = dict(algorithm="fedavg", C=4, tag="ef",
              wire_codec="delta+sparse+quant")
    out_sh = _one_sharded_round(
        _engine(tmp_path, synthetic_cohort, **kw), efs=True)
    out_sq = _one_sharded_round(
        _engine(tmp_path, synthetic_cohort, seq=True, **kw), efs=True)
    assert len(out_sh) == 6  # params, bstats, loss, n_bad, new_efs, u0
    np.testing.assert_array_equal(np.asarray(out_sh[2]),
                                  np.asarray(out_sq[2]))
    _assert_trees_ulp(out_sh, out_sq)


# ---------------------------------------------------------------------------
# (a) mesh-width independence (incl. pad-count change)
# ---------------------------------------------------------------------------

def _assert_history_close(h1, h2, rtol=1e-4):
    assert len(h1) == len(h2)
    for a, b in zip(h1, h2):
        assert a.keys() == b.keys()
        for k in a:
            np.testing.assert_allclose(a[k], b[k], rtol=rtol, atol=1e-7)


@pytest.mark.slow
def test_sharded_train_mesh_width_independent(tmp_path, cohort21):
    """A full sharded fedavg train() — rounds, eval cadence, final
    fine-tune — matches across a 2-device and an 8-device client mesh to
    the ~1-ulp compile-context residue, although the 21 real sites pad
    to 22 rows on one and 24 on the other and every device's work list
    differs (different padded shapes = different compiled modules, so
    exactly-bitwise is out of reach by construction — the SEMANTIC
    equality shows as bitwise-equal round-1 losses in the
    vs-sequential pins above; parallel/cohort.py)."""
    r8 = _engine(tmp_path, cohort21, "fedavg", client_mesh=8, n_dev=8,
                 tag="w8").train()
    r2 = _engine(tmp_path, cohort21, "fedavg", client_mesh=2, n_dev=2,
                 tag="w2").train()
    _assert_trees_ulp(r8["params"], r2["params"], rtol=1e-5, atol=1e-6)
    _assert_trees_ulp(r8["batch_stats"], r2["batch_stats"], rtol=1e-5,
                      atol=1e-6)
    _assert_history_close(r8["history"], r2["history"])


@pytest.mark.slow
def test_sharded_train_mesh_width_independent_salientgrads(tmp_path,
                                                           cohort21):
    """The flagship end to end (phase-1 sharded scores -> mask -> masked
    sharded rounds -> personal stacks): 2- vs 8-device meshes within the
    ulp residue, and the phase-1 MASK itself identical."""
    r8 = _engine(tmp_path, cohort21, "salientgrads", client_mesh=8,
                 n_dev=8, tag="sw8").train()
    r2 = _engine(tmp_path, cohort21, "salientgrads", client_mesh=2,
                 n_dev=2, tag="sw2").train()
    _assert_trees_bitwise(r8["masks"], r2["masks"])
    _assert_trees_ulp(r8["params"], r2["params"], rtol=1e-5, atol=1e-6)
    _assert_history_close(r8["history"], r2["history"])


# ---------------------------------------------------------------------------
# (c) K=4 fused windows on the sharded path
# ---------------------------------------------------------------------------

def test_sharded_fused_k4_window_bitwise(tmp_path, cohort21):
    """ONE dispatched program per fused window on the sharded path: a
    K=4 window equals four single sharded dispatches bitwise (same
    compile context), and its losses equal the sequential C-loop's
    bitwise. frac=0.5 keeps per-round sampling (and the mesh pad of each
    10-client cohort to 16 rows) load-bearing."""
    eng = _engine(tmp_path, cohort21, "fedavg", comm_round=4,
                  freq=4, frac=0.5, rounds_per_dispatch=4, tag="fk")
    gs = eng.init_global_state()
    p, b = gs.params, gs.batch_stats
    losses = []
    for r in range(4):
        sampled = eng.client_sampling(r)
        ids, n_real = eng._cohort_pad(sampled)
        p, b, loss, _ = eng._sharded_round_jit(n_real)(
            p, b, eng.data, jnp.asarray(ids),
            eng.per_client_rngs(r, ids), eng.round_lr(r))
        losses.append(float(loss))

    fz = _engine(tmp_path, cohort21, "fedavg", comm_round=4, freq=4,
                 frac=0.5, rounds_per_dispatch=4, tag="fk2")
    gs2 = fz.init_global_state()
    fp, fb, last_loss, k = fz._run_fused_window(gs2.params,
                                                gs2.batch_stats, 0, 4)
    assert k == 4
    assert float(last_loss) == losses[-1]
    _assert_trees_bitwise((p, b), (fp, fb))
    # the window is ONE compiled program: exactly one cache entry for
    # this (k, n_real) plan, dispatched once
    assert len(fz.__dict__["_fused_round_jit_cache"]) == 1


@pytest.mark.slow
def test_sharded_fused_window_losses_match_sequential(tmp_path, cohort21):
    """Across a K=4 window the per-round ~1-ulp state residue feeds back
    through training, so the window's LAST loss matches the sequential
    C-loop's to float noise rather than bitwise (round-1-from-identical-
    state losses are pinned bitwise above)."""
    sq = _engine(tmp_path, cohort21, "fedavg", comm_round=4, freq=4,
                 frac=0.5, rounds_per_dispatch=4, seq=True, tag="fsq")
    gs = sq.init_global_state()
    _, _, loss_sq, k = sq._run_fused_window(gs.params, gs.batch_stats,
                                            0, 4)
    sh = _engine(tmp_path, cohort21, "fedavg", comm_round=4, freq=4,
                 frac=0.5, rounds_per_dispatch=4, tag="fsh")
    gs2 = sh.init_global_state()
    _, _, loss_sh, k2 = sh._run_fused_window(gs2.params, gs2.batch_stats,
                                             0, 4)
    assert k == k2 == 4
    np.testing.assert_allclose(float(loss_sq), float(loss_sh), rtol=1e-4)


# ---------------------------------------------------------------------------
# (d) fallbacks with logged reasons + loud config errors
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("algorithm,needle", [
    ("fedfomo", "no cohort-sharded round body"),
    ("dispfl", "gossip collectives"),
    # local declared its round on the builder (ROADMAP 1(a)) and now
    # ARMS cohort sharding — its positive pins live in tests/
    # test_program.py (arm assertion + sharded==sequential-C-loop)
    ("turboaggregate", "MPC share boundary"),
])
def test_engines_without_sharded_round_fall_back(tmp_path,
                                                 synthetic_cohort,
                                                 algorithm, needle):
    eng = _engine(tmp_path, synthetic_cohort, algorithm, C=4,
                  tag=f"fb-{algorithm}",
                  val_fraction=0.25 if algorithm == "fedfomo" else 0.0)
    assert not eng._cohort_on
    text = _log_text(eng)
    assert "running the unsharded round program" in text
    assert needle in text


def test_replacement_batch_order_falls_back(tmp_path, synthetic_cohort):
    """batch_order=replacement draws per-step randint batches INSIDE the
    shard_map partition — the in-partition RNG lowering this toolchain
    miscompiles (parallel/cohort.py; the shuffle path hoists its
    permutations out, i.i.d. draws cannot be hoisted) — so --client_mesh
    collapses to the unsharded round with the logged reason."""
    cohort_data = synthetic_cohort
    cfg = ExperimentConfig(
        model="3dcnn_tiny", algorithm="fedavg",
        data=DataConfig(dataset="synthetic"),
        optim=OptimConfig(lr=1e-3, batch_size=8, epochs=1,
                          batch_order="replacement"),
        fed=FedConfig(client_num_in_total=4, comm_round=1, client_mesh=8),
        log_dir=str(tmp_path), tag="rep")
    mesh = make_mesh()
    fed, _ = federate_cohort(cohort_data, partition_method="site",
                             mesh=mesh)
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1),
                           cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    eng = create_engine("fedavg", cfg, fed, trainer, mesh=mesh, logger=log)
    assert not eng._cohort_on
    assert "replacement" in _log_text(eng)


def test_streaming_falls_back_with_logged_reason(tmp_path,
                                                 synthetic_cohort):
    eng = _engine(tmp_path, synthetic_cohort, "fedavg", C=4, stream=True,
                  tag="fbstream")
    try:
        assert not eng._cohort_on
        assert "streamed feed" in _log_text(eng)
    finally:
        eng.stream.close()


def test_two_level_mesh_falls_back_with_logged_reason(tmp_path,
                                                      synthetic_cohort):
    eng = _engine(tmp_path, synthetic_cohort, "fedavg", C=4,
                  mesh=make_mesh(shape=(2, 4)), tag="fb2l")
    assert not eng._cohort_on
    assert "silo-first" in _log_text(eng)


def test_single_device_mesh_falls_back(tmp_path, synthetic_cohort):
    eng = _engine(tmp_path, synthetic_cohort, "fedavg", C=4,
                  client_mesh=1, n_dev=1, tag="fb1")
    assert not eng._cohort_on
    assert "only one device" in _log_text(eng)


def test_client_mesh_size_mismatch_raises(tmp_path, synthetic_cohort):
    with pytest.raises(ValueError, match="does not match"):
        _engine(tmp_path, synthetic_cohort, "fedavg", C=4, client_mesh=4,
                n_dev=8, tag="mm")


def test_client_mesh_without_mesh_raises(tmp_path, synthetic_cohort):
    cfg = ExperimentConfig(
        model="3dcnn_tiny", algorithm="fedavg",
        data=DataConfig(dataset="synthetic"),
        optim=OptimConfig(lr=1e-3, batch_size=8, epochs=1),
        fed=FedConfig(client_num_in_total=4, comm_round=1, client_mesh=8),
        log_dir=str(tmp_path), tag="nm")
    fed, _ = federate_cohort(synthetic_cohort, partition_method="site",
                             mesh=None)
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1),
                           cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    with pytest.raises(ValueError, match="no device mesh"):
        create_engine("fedavg", cfg, fed, trainer, mesh=None, logger=log)


def test_distributed_cli_cohort_note(capsys):
    from neuroimagedisttraining_tpu.distributed import run as drun

    assert drun.cohort_fallback_note(0) is None
    assert "no in-process client axis" in drun.cohort_fallback_note(8)
    with pytest.raises(SystemExit):
        drun.main(["--role", "aggregator", "--num_clients", "1",
                   "--client_mesh", "8"])
    assert "no in-process client axis" in capsys.readouterr().out


def test_armed_engine_logs_and_flags(tmp_path, cohort21):
    eng = _engine(tmp_path, cohort21, "fedavg", tag="armed")
    assert eng._cohort_on
    assert "cohort sharding armed" in _log_text(eng)
