"""Mixed-precision + fused-update contract (ISSUE 10).

Pins, in contract order:

- config validation dies at startup (unknown precision, loss_scale under
  fp32, fused_update off the SGD chain);
- ``ops/fused_update.fused_sgd_step`` is BITWISE-equal to the unfused
  optax chain (clip -> wd -> momentum -> -lr update -> mask) across the
  stage on/off matrix, and the Pallas kernel (interpreter mode on this
  CPU tier) matches the XLA fallback within tolerance;
- the plain fp32 path is bitwise-unchanged with the fused flag on, at
  engine-round granularity, for the dense (fedavg) and masked
  (salientgrads) flagship shapes — masks and metrics identical;
- bf16_mixed keeps f32 MASTER weights, reproduces the fp32 metrics
  within the stated tolerance on the fp32-safe tiny model, and the
  fixed loss-scale constant is exact: scale 1024 == scale 1 bitwise
  (power-of-two scaling of an f32 loss);
- bf16_mixed composes with the fused K-window driver (bitwise vs the
  sequential loop) and with checkpoint resume landing mid-window
  (extends tests/test_dispatch.py's resume pin): restored master
  weights are float32 and the resumed run equals the unbroken run
  bitwise.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.config import (
    DataConfig, ExperimentConfig, FedConfig, OptimConfig,
)
from neuroimagedisttraining_tpu.core.optim import (
    compute_dtype, make_local_optimizer, validate_precision,
)
from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
from neuroimagedisttraining_tpu.data.federate import federate_cohort
from neuroimagedisttraining_tpu.engines import create_engine
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.ops import fused_update as fu
from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger


def _bitwise(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _engine(tmp_path, cohort, algorithm="fedavg", precision="fp32",
            fused=False, loss_scale=1.0, K=1, comm_round=2,
            freq=10 ** 9, tag="p", checkpoint_dir="", checkpoint_every=0,
            **fed_kw):
    optim = OptimConfig(lr=1e-3, batch_size=8, epochs=1,
                        precision=precision, loss_scale=loss_scale,
                        fused_update=fused)
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm=algorithm,
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=optim,
        fed=FedConfig(client_num_in_total=4, comm_round=comm_round,
                      frequency_of_the_test=freq, rounds_per_dispatch=K,
                      **fed_kw),
        checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
        log_dir=str(tmp_path), tag=tag)
    trainer = LocalTrainer(
        create_model(cfg.model, num_classes=1,
                     dtype=compute_dtype(precision)),
        optim, num_classes=1)
    fed, _ = federate_cohort(cohort, partition_method="site")
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    return create_engine(algorithm, cfg, fed, trainer, logger=log)


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------

def test_precision_validation_rejects_bad_configs():
    with pytest.raises(ValueError, match="unknown precision"):
        validate_precision(OptimConfig(precision="fp16"))
    with pytest.raises(ValueError, match="bf16_mixed"):
        validate_precision(OptimConfig(loss_scale=128.0))
    with pytest.raises(ValueError, match="positive finite"):
        validate_precision(OptimConfig(precision="bf16_mixed",
                                       loss_scale=0.0))
    with pytest.raises(ValueError, match="fused"):
        validate_precision(OptimConfig(client_optimizer="adam",
                                       fused_update=True))
    # the trainer enforces the same contract at build
    with pytest.raises(ValueError, match="bf16_mixed"):
        LocalTrainer(create_model("3dcnn_tiny", num_classes=1),
                     OptimConfig(loss_scale=2.0), num_classes=1)
    assert compute_dtype("bf16_mixed") == jnp.bfloat16
    assert compute_dtype("fp32") == jnp.float32


# ---------------------------------------------------------------------------
# fused step vs the optax chain
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("clip,wd,mom", [
    (10.0, 5e-4, 0.9),     # the flagship chain, clip triggered below
    (1e-3, 5e-4, 0.9),     # clip rescale branch taken
    (0.0, 0.0, 0.9),       # momentum only
    (10.0, 0.0, 0.0),      # clip only (no trace state)
])
def test_fused_step_bitwise_equals_optax_chain(clip, wd, mom):
    key = jax.random.key(0)
    params = {"w": jax.random.normal(key, (37, 129)),
              "b": jax.random.normal(jax.random.fold_in(key, 1), (129,))}
    grads = jax.tree.map(lambda x: x * 0.1 + 0.3, params)
    mask = {"w": (jax.random.uniform(jax.random.fold_in(key, 2),
                                     (37, 129)) > 0.5).astype(jnp.float32),
            "b": jnp.ones((129,))}
    cfg = OptimConfig(grad_clip=clip, wd=wd, momentum=mom)
    opt = make_local_optimizer(cfg)
    opt_f = make_local_optimizer(dataclasses.replace(cfg,
                                                     fused_update=True))
    assert opt_f.fused_apply is not None
    st = opt.init(params)
    lr = jnp.float32(0.01)

    @jax.jit
    def unfused(p, s):
        updates, s2 = opt.update(grads, s, p, lr)
        p = jax.tree.map(jnp.add, p, updates)
        return jax.tree.map(jnp.multiply, p, mask), s2

    @jax.jit
    def fused(p, s):
        return opt_f.fused_apply(grads, s, p, lr, mask)

    _bitwise(unfused(params, st), fused(params, st))
    # dense (mask=None) variant
    @jax.jit
    def unfused_dense(p, s):
        updates, s2 = opt.update(grads, s, p, lr)
        return jax.tree.map(jnp.add, p, updates), s2

    @jax.jit
    def fused_dense(p, s):
        return opt_f.fused_apply(grads, s, p, lr, None)

    _bitwise(unfused_dense(params, st), fused_dense(params, st))


@pytest.mark.parametrize("clip,wd,mom,masked", [
    (10.0, 5e-4, 0.9, True),
    (1e-3, 0.0, 0.0, False),
])
def test_fused_kernel_interpret_matches_fallback(clip, wd, mom, masked):
    """The Pallas kernel (interpreter mode on this CPU tier — the
    blocking/padding plumbing under test) matches the XLA fallback
    within tolerance; on-TPU bit-equality is the bench's pin
    (bench_matrix/precision_bench.json on a chip session)."""
    key = jax.random.key(7)
    # a deliberately lane-unaligned leaf exercises the padding path
    params = {"w": jax.random.normal(key, (13, 57)),
              "b": jax.random.normal(jax.random.fold_in(key, 3), (5,))}
    grads = jax.tree.map(lambda x: x * 0.3 + 0.1, params)
    trace = jax.tree.map(jnp.ones_like, params) if mom > 0 else None
    mask = (jax.tree.map(
        lambda x: (x > 0).astype(jnp.float32), params) if masked else None)
    lr = jnp.float32(0.05)
    p_i, t_i = fu.fused_sgd_step(params, grads, trace, mask, clip=clip,
                                 wd=wd, momentum=mom, lr=lr,
                                 use_pallas=False, interpret=True)
    p_x, t_x = fu.fused_sgd_step(params, grads, trace, mask, clip=clip,
                                 wd=wd, momentum=mom, lr=lr,
                                 use_pallas=False)
    for a, b in zip(jax.tree.leaves(p_i), jax.tree.leaves(p_x)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    if mom > 0:
        for a, b in zip(jax.tree.leaves(t_i), jax.tree.leaves(t_x)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# engine rounds: fused on/off, fp32 bitwise; masked engine identical
# ---------------------------------------------------------------------------

def _one_round(eng):
    gs = eng.init_global_state()
    sampled = eng.client_sampling(0)
    rngs = eng.per_client_rngs(0, sampled)
    lr = eng.round_lr(0)
    if eng.name == "salientgrads":
        masks, _ = eng.generate_global_mask(gs.params, gs.batch_stats)
        per = eng.broadcast_states(gs, eng.num_clients)
        out = eng._round_jit(gs.params, gs.batch_stats, per.params,
                             per.batch_stats, eng.data, masks,
                             jnp.asarray(sampled), rngs, lr)
        return out[:2] + (masks,)
    out = eng._round_jit(gs.params, gs.batch_stats, eng.data,
                         jnp.asarray(sampled), rngs, lr)
    return out[:2]


@pytest.mark.parametrize("algorithm", ["fedavg", "salientgrads"])
def test_fused_round_bitwise_equals_unfused_fp32(tmp_path, synthetic_cohort,
                                                 algorithm):
    """The acceptance pin: fp32 + fused_update is bitwise the fp32 tree,
    dense and masked — identical params, batch_stats, and (masked) the
    identical mask."""
    out_u = _one_round(_engine(tmp_path, synthetic_cohort, algorithm,
                               fused=False, tag="uf"))
    out_f = _one_round(_engine(tmp_path, synthetic_cohort, algorithm,
                               fused=True, tag="fu"))
    _bitwise(out_u, out_f)


# ---------------------------------------------------------------------------
# bf16_mixed: master weights, tolerance, loss-scale exactness
# ---------------------------------------------------------------------------

def test_bf16_mixed_masters_f32_and_metrics_within_tolerance(
        tmp_path, synthetic_cohort):
    """bf16_mixed on the fp32-safe tiny model reproduces the fp32 round
    within the STATED tolerance — end-round loss within 2e-3 absolute,
    master weights within 5e-3 — and every master-weight leaf stays
    float32 (what checkpoints and aggregation see)."""
    eng32 = _engine(tmp_path, synthetic_cohort, tag="f32")
    eng16 = _engine(tmp_path, synthetic_cohort, precision="bf16_mixed",
                    tag="b16")
    gs32, gs16 = eng32.init_global_state(), eng16.init_global_state()
    _bitwise(gs32.params, gs16.params)  # identical f32 init
    s = eng32.client_sampling(0)
    r = eng32.per_client_rngs(0, s)
    p32, b32, l32, _ = eng32._round_jit(gs32.params, gs32.batch_stats,
                                        eng32.data, jnp.asarray(s), r,
                                        eng32.round_lr(0))
    p16, b16, l16, _ = eng16._round_jit(gs16.params, gs16.batch_stats,
                                        eng16.data, jnp.asarray(s), r,
                                        eng16.round_lr(0))
    for leaf in jax.tree.leaves(p16):
        assert leaf.dtype == jnp.float32
    for leaf in jax.tree.leaves(b16):
        assert leaf.dtype == jnp.float32
    assert abs(float(l16) - float(l32)) < 2e-3
    for a, b in zip(jax.tree.leaves(p32), jax.tree.leaves(p16)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3)


def test_loss_scale_pin_power_of_two_is_exact(tmp_path, synthetic_cohort):
    """The fixed loss-scale contract: scale 1024 (power of two — exact
    f32 multiply/divide) reproduces scale 1 BITWISE under bf16_mixed."""
    e1 = _engine(tmp_path, synthetic_cohort, precision="bf16_mixed",
                 loss_scale=1.0, tag="s1")
    e2 = _engine(tmp_path, synthetic_cohort, precision="bf16_mixed",
                 loss_scale=1024.0, tag="s1024")
    _bitwise(_one_round(e1), _one_round(e2))


# ---------------------------------------------------------------------------
# composition: fused windows + checkpoint resume under bf16_mixed
# ---------------------------------------------------------------------------

def test_bf16_fused_window_bitwise_equal_sequential(tmp_path,
                                                    synthetic_cohort):
    """bf16_mixed under the K-fused driver equals the sequential loop
    bitwise — same pin as test_dispatch's, at the new precision (frac<1
    keeps per-round sampling load-bearing)."""
    base = _engine(tmp_path, synthetic_cohort, precision="bf16_mixed",
                   K=1, comm_round=4, freq=4, frac=0.5, tag="bk1").train()
    fused = _engine(tmp_path, synthetic_cohort, precision="bf16_mixed",
                    K=4, comm_round=4, freq=4, frac=0.5, tag="bk4").train()
    _bitwise(base["params"], fused["params"])
    _bitwise(base["batch_stats"], fused["batch_stats"])
    assert base["history"] == fused["history"]


def test_bf16_checkpoint_resume_mid_window_bitwise(tmp_path,
                                                   synthetic_cohort):
    """Checkpoint round-trip under bf16_mixed (ISSUE 10 satellite,
    extending test_dispatch's resume-mid-window pin): the saved state IS
    the f32 master weights (restored bitwise, dtype float32), and a
    K=4 resume landing mid-window reproduces the unbroken K=1 run
    bitwise."""
    from neuroimagedisttraining_tpu.utils import checkpoint as ckpt

    full = _engine(tmp_path, synthetic_cohort, precision="bf16_mixed",
                   K=1, comm_round=4, tag="cfull").train()
    ck = str(tmp_path / "ck_bf16")
    part = _engine(tmp_path, synthetic_cohort, precision="bf16_mixed",
                   K=4, comm_round=2, checkpoint_dir=ck,
                   checkpoint_every=2, tag="cpart").train()
    # the checkpoint carries f32 master weights bitwise
    r, state = ckpt.load_checkpoint(ck)
    assert r == 1
    for leaf in jax.tree.leaves(state["params"]):
        assert np.asarray(leaf).dtype == np.float32
    _bitwise(state["params"], part["params"])
    resumed = _engine(tmp_path, synthetic_cohort, precision="bf16_mixed",
                      K=4, comm_round=4, checkpoint_dir=ck,
                      checkpoint_every=2, tag="cres").train()
    _bitwise(full["params"], resumed["params"])
    _bitwise(full["batch_stats"], resumed["batch_stats"])
