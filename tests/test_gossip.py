"""ppermute gossip consensus (VERDICT r3 next-step #3): ring/k-lattice
mixing matrices lower to collective-permutes of |k|-row slices, NOT a
full-stack all-to-all/all-gather, and match the dense einsum numerically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.parallel.gossip import (
    circulant_plan, gossip_apply, plan_fits_mesh,
)
from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
from neuroimagedisttraining_tpu.parallel.topology import (
    SymmetricTopologyManager, ring_mixing_matrix,
)


def test_circulant_plan_detection():
    # plain ring: self + two neighbors at 1/3
    plan = circulant_plan(ring_mixing_matrix(8))
    assert plan == ((-1, pytest.approx(1 / 3)), (0, pytest.approx(1 / 3)),
                    (1, pytest.approx(1 / 3)))
    # Watts-Strogatz ring ∪ 4-lattice (reference symmetric topology):
    # offsets ±1, ±2, 0 at 1/5
    tm = SymmetricTopologyManager(8, neighbor_num=4)
    plan_ws = circulant_plan(tm.generate_topology())
    assert plan_ws is not None
    assert sorted(k for k, _ in plan_ws) == [-2, -1, 0, 1, 2]
    # a padded-diagonal row (mesh padding clients) breaks circulance
    M = ring_mixing_matrix(8)
    M[7] = 0.0
    M[7, 7] = 1.0
    assert circulant_plan(M) is None
    # random row-stochastic matrix is not circulant
    rng = np.random.default_rng(0)
    R = rng.uniform(size=(6, 6)).astype(np.float32)
    R /= R.sum(1, keepdims=True)
    assert circulant_plan(R) is None


def test_gossip_apply_empty_plan_is_zero():
    """An all-zero matrix is trivially circulant -> empty plan; the
    consensus it defines is identically zero, and gossip_apply must
    return that (matching the einsum path) rather than crash on an
    empty accumulation."""
    mesh = make_mesh()
    Z = np.zeros((8, 8), np.float32)
    plan = circulant_plan(Z)
    assert plan == ()
    assert plan_fits_mesh(plan, mesh, 8)
    tree = {"w": jnp.ones((8, 3, 2), jnp.float32)}
    out = gossip_apply(tree, plan, mesh)
    np.testing.assert_array_equal(np.asarray(out["w"]), 0.0)
    want = jnp.einsum("cj,j...->c...", jnp.asarray(Z), tree["w"])
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(want))


def test_gossip_apply_rejects_none_plan():
    """ADVICE r4: plan=None is the 'not circulant' sentinel — passing it
    through must raise, not silently return an all-zero consensus."""
    mesh = make_mesh()
    tree = {"w": jnp.ones((8, 3), jnp.float32)}
    with pytest.raises(ValueError, match="plan=None"):
        gossip_apply(tree, None, mesh)


def test_plan_fits_mesh_bounds():
    mesh = make_mesh()
    plan = circulant_plan(ring_mixing_matrix(8))
    assert plan_fits_mesh(plan, mesh, 8)          # 1 client/device, |k|=1
    assert plan_fits_mesh(plan, mesh, 16)         # 2 clients/device
    assert not plan_fits_mesh(plan, mesh, 12)     # 12 % 8 != 0
    assert not plan_fits_mesh(plan, None, 8)
    # offset beyond the per-device block cannot single-hop
    far = tuple([(0, 0.5), (3, 0.5)])
    assert not plan_fits_mesh(far, mesh, 8)       # block=1 < 3
    assert plan_fits_mesh(far, mesh, 24)          # block=3 >= 3


@pytest.mark.parametrize("C", [8, 16])
def test_gossip_apply_matches_einsum(C):
    """ppermute path == dense einsum on the 8-device mesh, both at one and
    multiple clients per device (the multi-row case exercises the
    slice+concat composition)."""
    mesh = make_mesh()
    M = ring_mixing_matrix(C)
    plan = circulant_plan(M)
    assert plan_fits_mesh(plan, mesh, C)
    rng = np.random.default_rng(1)
    tree = {"w": jnp.asarray(rng.normal(size=(C, 5, 3)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(C, 7)), jnp.float32)}
    got = jax.jit(lambda t: gossip_apply(t, plan, mesh))(tree)
    want = jax.tree.map(
        lambda x: jnp.einsum("cj,j...->c...", jnp.asarray(M), x), tree)
    for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-6, atol=1e-7)


def test_gossip_apply_bitwise_exact_binary_weights():
    """With power-of-two weights and integer-valued params every float op
    is exact, so ppermute == einsum BITWISE — pinning that the two paths
    compute the same function, not merely close ones."""
    mesh = make_mesh()
    C = 8
    base = np.zeros(C, np.float32)
    base[0], base[1], base[C - 1] = 0.5, 0.25, 0.25
    M = np.stack([np.roll(base, i) for i in range(C)])
    plan = circulant_plan(M)
    assert plan is not None
    rng = np.random.default_rng(2)
    x = {"w": jnp.asarray(rng.integers(-8, 8, size=(C, 4, 6)), jnp.float32)}
    got = jax.jit(lambda t: gossip_apply(t, plan, mesh))(x)
    want = jnp.einsum("cj,j...->c...", jnp.asarray(M), x["w"])
    np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(want))


def test_gossip_lowering_collective_permute_not_allgather():
    """The compiled consensus must contain collective-permute and NOT
    materialize the full stack via all-gather (the whole point of the
    sparse path)."""
    mesh = make_mesh()
    C = 8
    plan = circulant_plan(ring_mixing_matrix(C))
    tree = {"w": jnp.zeros((C, 64, 32), jnp.float32)}
    txt = (jax.jit(lambda t: gossip_apply(t, plan, mesh))
           .lower(tree).compile().as_text())
    assert "collective-permute" in txt
    assert "all-gather" not in txt
    assert "all-to-all" not in txt


def test_dpsgd_ring_round_ppermute_matches_einsum(tmp_path,
                                                  synthetic_cohort8):
    """Engine-level: a D-PSGD ring round on the 8-device mesh takes the
    ppermute plan and produces the same state as the dense-einsum trace."""
    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.federate import federate_cohort
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    mesh = make_mesh()
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm="dpsgd",
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=OptimConfig(lr=1e-2, batch_size=4, epochs=1),
        # frac < 1: at full participation the reference's benefit_choose
        # early-returns ALL clients regardless of cs (dpsgd_api.py:116-120),
        # which is a dense 1/C matrix — ring needs partial participation
        fed=FedConfig(client_num_in_total=8, comm_round=1, cs="ring",
                      frac=0.25, frequency_of_the_test=1),
        log_dir=str(tmp_path))
    fed, _ = federate_cohort(synthetic_cohort8, partition_method="site",
                             mesh=mesh)
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1),
                           cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    engine = create_engine("dpsgd", cfg, fed, trainer, mesh=mesh,
                           logger=log)
    engine._donate = False  # same buffers replayed through both lowerings
    M_np = engine.mixing_matrix(0)
    plan, plan_arrays = engine.gossip_plan(M_np)
    assert plan is not None, "ring @ 8 real clients on 8 devices must plan"
    assert plan_arrays == {}  # circulant: no routing operands

    gs = engine.init_global_state()
    per = engine.broadcast_states(gs, engine.num_clients)
    rngs = engine.per_client_rngs(0, np.arange(engine.num_clients))
    args = (per.params, per.batch_stats, engine.data,
            jnp.asarray(M_np), rngs, jnp.float32(0.01))
    out_pp = engine._round_jit_for(plan)(*args, {})
    out_ein = engine._round_jit_for(None)(*args, {})
    for a, b in zip(jax.tree.leaves(out_pp), jax.tree.leaves(out_ein)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)
    # and the ppermute trace really lowers to collective-permute
    txt = engine._round_jit_for(plan).lower(*args, {}).compile().as_text()
    assert "collective-permute" in txt


# ---------- general sparse (per-round random) topologies ----------


def _k_regular(C, k, seed, binary=False):
    """Row c = {k random neighbors} ∪ {c}; uniform weights unless binary."""
    rng = np.random.default_rng(seed)
    M = np.zeros((C, C), np.float32)
    for c in range(C):
        nei = rng.choice([j for j in range(C) if j != c], k, replace=False)
        sel = np.append(nei, c)
        M[c, sel] = 1.0 if binary else 1.0 / len(sel)
    return M


def test_sparse_plan_routing_exact_vs_einsum():
    """Routing exactness: on integer-valued inputs (exact f32 arithmetic,
    any summation order) the routed all_to_all consensus must equal the
    dense einsum BITWISE — same rows gathered, same weights, no
    duplicates/omissions. Float inputs agree to reduction-order
    tolerance."""
    from neuroimagedisttraining_tpu.parallel.gossip import (
        SparseSpec, gossip_apply_sparse, sparse_plan,
    )

    mesh = make_mesh()
    C, k = 40, 2
    M = _k_regular(C, k, seed=1)
    out = sparse_plan(M, mesh, C)
    assert out is not None
    spec, arrays = out
    assert isinstance(spec, SparseSpec)
    assert spec.m < spec.B  # strictly below the all-gather volume
    # integer-valued weights too, so every product/sum is exact: use the
    # binary adjacency with integer payloads
    A = _k_regular(C, k, seed=1, binary=True)
    spec_b, arrays_b = sparse_plan(A, mesh, C)
    rng = np.random.default_rng(3)
    xi = {"w": jnp.asarray(rng.integers(-64, 64, size=(C, 5, 3)),
                           jnp.float32)}
    got = gossip_apply_sparse(xi, spec_b, arrays_b, mesh)
    want = jnp.einsum("cj,j...->c...", jnp.asarray(A), xi["w"])
    np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(want))
    # float payloads + uniform weights: equal up to reduction order
    xf = {"w": jnp.asarray(rng.normal(size=(C, 5, 3)), jnp.float32)}
    gotf = gossip_apply_sparse(xf, spec, arrays, mesh)
    wantf = jnp.einsum("cj,j...->c...", jnp.asarray(M), xf["w"])
    np.testing.assert_allclose(np.asarray(gotf["w"]), np.asarray(wantf),
                               rtol=2e-6, atol=2e-6)


def test_sparse_lowering_all_to_all_not_allgather():
    """The compiled sparse consensus must move rows via all-to-all and NOT
    materialize the client stack via all-gather."""
    from neuroimagedisttraining_tpu.parallel.gossip import (
        gossip_apply_sparse, sparse_plan,
    )

    mesh = make_mesh()
    C = 40
    spec, arrays = sparse_plan(_k_regular(C, 2, seed=1), mesh, C)
    tree = {"w": jnp.zeros((C, 64, 32), jnp.float32)}
    txt = (jax.jit(lambda t, a: gossip_apply_sparse(t, spec, a, mesh))
           .lower(tree, arrays).compile().as_text())
    assert "all-to-all" in txt
    assert "all-gather" not in txt


def test_sparse_plan_rejects_dense_and_single_row_blocks():
    from neuroimagedisttraining_tpu.parallel.gossip import sparse_plan

    mesh = make_mesh()
    # full participation: every pair would exchange whole blocks
    assert sparse_plan(np.ones((16, 16), np.float32), mesh, 16) is None
    # one client per device: every row is a full block, no sparse win
    assert sparse_plan(_k_regular(8, 3, seed=0), mesh, 8) is None


@pytest.mark.slow  # tier-1 window (PR 7): heavy twin/artifact test, core pin covered by a lighter tier-1 sibling
def test_dpsgd_random_round_sparse_matches_einsum(tmp_path):
    """Engine-level: a D-PSGD cs=random round (fresh k-regular draw) takes
    the routed-all_to_all plan and produces the same state as the
    dense-einsum trace."""
    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.federate import federate_cohort
    from neuroimagedisttraining_tpu.data.synthetic import (
        generate_synthetic_abcd,
    )
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.parallel.gossip import SparseSpec
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    mesh = make_mesh()
    C = 32
    cohort = generate_synthetic_abcd(num_subjects=4 * C, shape=(12, 14, 12),
                                     num_sites=C, seed=0)
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm="dpsgd",
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=OptimConfig(lr=1e-2, batch_size=4, epochs=1),
        # frac 1/16 -> 2 random neighbors per client: sparse rows
        fed=FedConfig(client_num_in_total=C, comm_round=1, cs="random",
                      frac=1 / 16, frequency_of_the_test=1),
        log_dir=str(tmp_path))
    fed, _ = federate_cohort(cohort, partition_method="site", mesh=mesh)
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1),
                           cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    engine = create_engine("dpsgd", cfg, fed, trainer, mesh=mesh,
                           logger=log)
    engine._donate = False  # same buffers replayed through both lowerings
    M_np = engine.mixing_matrix(0)
    plan, plan_arrays = engine.gossip_plan(M_np)
    assert isinstance(plan, SparseSpec), "cs=random must take the sparse plan"

    gs = engine.init_global_state()
    per = engine.broadcast_states(gs, engine.num_clients)
    rngs = engine.per_client_rngs(0, np.arange(engine.num_clients))
    args = (per.params, per.batch_stats, engine.data,
            jnp.asarray(M_np), rngs, jnp.float32(0.01))
    out_sp = engine._round_jit_for(plan)(*args, plan_arrays)
    out_ein = engine._round_jit_for(None)(*args, {})
    for a, b in zip(jax.tree.leaves(out_sp), jax.tree.leaves(out_ein)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)
    # the consensus program routes via all-to-all, no client-stack
    # all-gather
    chlo = engine._consensus_jit_for(plan).lower(
        per.params, per.batch_stats, jnp.asarray(M_np),
        plan_arrays).compile().as_text()
    assert "all-to-all" in chlo
    assert "all-gather" not in chlo


def test_dispfl_random_consensus_sparse_matches_einsum(tmp_path):
    """Engine-level: DisPFL's forced-default random adjacency
    (dispfl_api.py:200) takes the sparse plan; the mask-overlap consensus
    (all three mixed trees) matches the einsum trace."""
    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.federate import federate_cohort
    from neuroimagedisttraining_tpu.data.synthetic import (
        generate_synthetic_abcd,
    )
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.parallel.gossip import SparseSpec
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    mesh = make_mesh()
    C = 32
    cohort = generate_synthetic_abcd(num_subjects=4 * C, shape=(12, 14, 12),
                                     num_sites=C, seed=0)
    cfg = ExperimentConfig(
        model="3dcnn_tiny", num_classes=1, algorithm="dispfl",
        data=DataConfig(dataset="synthetic", partition_method="site"),
        optim=OptimConfig(lr=1e-2, batch_size=4, epochs=1),
        fed=FedConfig(client_num_in_total=C, comm_round=1, cs="random",
                      frac=1 / 16, frequency_of_the_test=1),
        log_dir=str(tmp_path))
    fed, _ = federate_cohort(cohort, partition_method="site", mesh=mesh)
    trainer = LocalTrainer(create_model(cfg.model, num_classes=1),
                           cfg.optim, num_classes=1)
    log = ExperimentLogger(str(tmp_path), "synthetic", cfg.identity(),
                           console=False)
    engine = create_engine("dispfl", cfg, fed, trainer, mesh=mesh,
                           logger=log)
    engine._donate = False  # same buffers replayed through both lowerings
    A_np = engine.adjacency(0, engine.active_draw(0))
    plan, plan_arrays = engine.gossip_plan(A_np)
    assert isinstance(plan, SparseSpec), "random adjacency must plan sparse"

    gs = engine.init_global_state()
    masks_local, _ = engine.init_masks_all(gs.params)
    per = engine.broadcast_states(gs, engine.num_clients)
    per_params = jax.tree.map(jnp.multiply, per.params, masks_local)
    args = (per_params, per.batch_stats, masks_local, masks_local,
            jnp.asarray(A_np))
    w_sp, b_sp = engine._consensus_jit_for(plan)(*args, plan_arrays)
    w_ein, b_ein = engine._consensus_jit_for(None)(*args, {})
    for a, b in zip(jax.tree.leaves((w_sp, b_sp)),
                    jax.tree.leaves((w_ein, b_ein))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)
