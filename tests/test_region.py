"""Hierarchical aggregation tier tests (ISSUE 18, asyncfl/region.py,
the shm partial hand-off, and the downlink delta-sync).

Contracts:

(a) THE tree invariant: any (region x worker) partitioning of the same
    uploads — workers fold, each region merges its workers' partials,
    the root merges the region partials in region-id order — equals one
    accumulator that folded everything, BITWISE, for the dense int64
    lattice AND the secure-quant chunk fold. Exact integer algebra is
    commutative and associative, so the tree's merge ORDER and SHAPE
    both cancel out.
(b) The shm slab transport is a bitwise-faithful carrier: a partial
    written through a real ``multiprocessing.shared_memory`` slab and
    read back under the seqlock generation check reproduces the flat
    int64 vector exactly — including the NaN-as-zero and +/-inf
    saturation edge encodings — and a torn/stale generation raises
    instead of returning a silently-wrong vector.
(c) Downlink delta-sync: a changed-version sync reply's delta frame,
    decoded against the client's last-synced tree, is BITWISE the dense
    reply; a base that left the broadcast ring falls back to dense with
    the reason logged and counted, never silently.
(d) Cross-worker exactly-once (the forced-migration regression): a
    sender reconnecting onto a DIFFERENT worker with the same
    incarnation gets the root's seq watermark floor applied before its
    register is answered, so a re-sent upload the old worker already
    accepted is a duplicate — while a NEW incarnation legitimately
    restarts from seq 0.
(e) Live multi-process tree runs (region children owning SO_REUSEPORT
    worker fleets): audits green across three processes tiers, both
    transports, dense and secure_quant.
"""

import logging

import numpy as np
import pytest

from neuroimagedisttraining_tpu.asyncfl.ingest import (
    IngestWorkerCore,
    PartialAccumulator,
    SeqWatermarks,
    _ShmSlabReader,
    _ShmSlabWriter,
    make_fold_spec,
    model_sizes,
    single_process_fold,
)
from neuroimagedisttraining_tpu.asyncfl.loadgen import (
    canned_update_tree,
    run_load,
)
from neuroimagedisttraining_tpu.codec import wire
from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.privacy import (
    QuantSpec,
    encode_secure_quant,
)

LIKE = canned_update_tree(0, 64)


def _dense_entries(n, leaf_elems=64):
    return [(canned_update_tree(r, leaf_elems), 100 + 7 * r)
            for r in range(1, n + 1)]


def _secure_entries(n, spec, leaf_elems=64):
    return [(encode_secure_quant(canned_update_tree(r, leaf_elems), 1.0,
                                 spec, np.random.default_rng(r)),
             200 + 11 * r)
            for r in range(1, n + 1)]


def _merge_tree(entries, spec, topology):
    """Fold ``entries`` through a (region x worker) tree: ``topology``
    is a list of regions, each a list of per-worker entry counts. Each
    worker folds its slice into its own accumulator; each region merges
    its workers' exported partials; the root merges the region partials
    in region-id order — exactly the live tier's merge shape."""
    root = PartialAccumulator(spec, model_sizes(LIKE))
    i = 0
    for region_workers in topology:
        region = PartialAccumulator(spec, model_sizes(LIKE))
        for n in region_workers:
            worker = PartialAccumulator(spec, model_sizes(LIKE))
            for payload, w in entries[i:i + n]:
                if spec.quant is not None:
                    worker.fold_frame(payload, w)
                else:
                    worker.fold_dense(payload, w)
            i += n
            p = worker.export()
            if p is not None:
                region.merge_payload(p)
        p = region.export()
        if p is not None:
            root.merge_payload(p)
    assert i == len(entries), "topology must cover every entry"
    return root


# three-plus (region x worker) partitionings of the same 12 uploads:
# one fat region, two symmetric shapes, a ragged tree, a deep one
TOPOLOGIES = [
    [[12]],                      # 1 region x 1 worker (degenerate)
    [[6], [6]],                  # 2 regions x 1 worker
    [[3, 3], [3, 3]],            # 2 regions x 2 workers (the bench)
    [[4, 2], [1, 5]],            # ragged loads
    [[2, 2], [2, 2], [2, 2]],    # 3 regions x 2 workers
]


# ---------------------------------------------------------------------------
# (a) tree merge == single-process fold, bitwise, any partitioning
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("topology", TOPOLOGIES)
def test_dense_tree_merge_partition_independent_bitwise(topology):
    spec = make_fold_spec(LIKE)
    entries = _dense_entries(12)
    ref = single_process_fold(entries, spec, LIKE)
    merged = _merge_tree(entries, spec, topology)
    assert merged.w_int_total == ref.w_int_total
    assert merged.count == ref.count
    for name, _ in model_sizes(LIKE):
        np.testing.assert_array_equal(merged.totals[name],
                                      ref.totals[name])


@pytest.mark.parametrize("topology", TOPOLOGIES)
def test_secure_tree_merge_partition_independent_bitwise(topology):
    quant = QuantSpec.from_bits(32, 10, 3)
    spec = make_fold_spec(LIKE, quant=quant)
    entries = _secure_entries(12, quant)
    ref = single_process_fold(entries, spec, LIKE)
    refp = ref.export()
    merged = _merge_tree(entries, spec, topology)
    assert merged.w_int_total == refp["w_int"]
    for name, _ in model_sizes(LIKE):
        np.testing.assert_array_equal(merged.totals[name],
                                      refp["slots"][name])


# ---------------------------------------------------------------------------
# (b) the shm slab is a bitwise-faithful, torn-read-detecting carrier
# ---------------------------------------------------------------------------


def test_shm_slab_roundtrip_bitwise_with_edge_encodings():
    """NaN/saturation edges cross the slab unchanged: the writer's flat
    int64 vector — including NaN-as-zero and the +/-inf sign-preserving
    clamp encodings — reads back bitwise under the generation check."""
    spec = make_fold_spec(LIKE)
    bad = canned_update_tree(1, 64)
    k = bad["params"]["dense"]["kernel"]
    k[0], k[1], k[2] = np.nan, np.inf, -np.inf
    acc = PartialAccumulator(spec, model_sizes(LIKE))
    acc.fold_dense(bad, 3)
    payload = acc.export()
    segs = [payload["slots"][name] for name, _ in model_sizes(LIKE)]
    total = sum(s.size for s in segs)

    writer = _ShmSlabWriter(total)
    reader = _ShmSlabReader(writer.name, total)
    try:
        gen = writer.write(segs, payload["w_int"], payload["count"])
        flat, w_int, count = reader.read(gen)
        np.testing.assert_array_equal(flat, np.concatenate(segs))
        assert w_int == payload["w_int"]
        assert count == payload["count"]
        # the edge encodings specifically: NaN folded as zero, inf
        # saturated at +/- w * q_max — visible IN the slab copy
        kernel = flat[:segs[0].size] if model_sizes(LIKE)[0][0] == \
            "params/dense/kernel" else None
        t = acc.totals["params/dense/kernel"]
        assert t[0] == 0
        assert t[1] == 3 * spec.q_max and t[2] == -3 * spec.q_max
        if kernel is not None:
            np.testing.assert_array_equal(kernel, t)
        # a second write without an ack bumps the generation: reading
        # at the OLD generation is a loudly-detected stale read
        writer.write(segs, 1, 1)
        with pytest.raises(RuntimeError, match="torn read"):
            reader.read(gen)
    finally:
        reader.close()
        writer.destroy()
    # owner teardown unlinked the name: a re-attach must fail
    with pytest.raises(FileNotFoundError):
        _ShmSlabReader(writer.name, total)


# ---------------------------------------------------------------------------
# (c) downlink delta-sync: bitwise replies, honest fallback
# ---------------------------------------------------------------------------


def _core(wid=0, max_staleness=4):
    spec = make_fold_spec(LIKE)
    return IngestWorkerCore(wid, spec, LIKE,
                            max_staleness=max_staleness,
                            staleness_alpha=0.5)


def _tree_equal(a, b):
    la, lb = list(wire._named_leaves(a)), list(wire._named_leaves(b))
    assert [n for n, _ in la] == [n for n, _ in lb]
    for (_, x), (_, y) in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_byte_shuffle_is_a_permutation():
    """The stride-4 byte-plane shuffle inverts exactly, tail included
    (lengths not divisible by 4 carry the remainder through raw)."""
    rng = np.random.default_rng(7)
    for n in (0, 1, 3, 4, 5, 8, 257, 4096, 4097):
        x = rng.integers(0, 256, n, dtype=np.uint8)
        np.testing.assert_array_equal(
            wire._byte_unshuffle(wire._byte_shuffle(x)), x)


def test_delta_sync_reply_decodes_bitwise_to_dense_reply():
    core = _core()
    core.handle_register(1, incarnation=9, delta_ok=True)
    core.handle_register(2, incarnation=9, delta_ok=False)
    base = core.params
    core.last_synced[1] = 0
    core.last_synced[2] = 0
    core.set_model(1, canned_update_tree(42, 64))

    dense, kind_dense = core.build_sync_body(2)
    assert kind_dense == "dense"
    frame, kind = core.build_sync_body(1)
    assert kind == "delta"
    assert wire.is_sync_delta_frame(frame)
    assert int(frame["base"]) == 0
    decoded = wire.decode_sync_delta(frame, base)
    _tree_equal(decoded, dense)
    assert core.sync_stats["sync_delta_sent"] == 1
    assert core.sync_stats["sync_dense_sent"] == 1
    # the frame is cached per (base, version): same object, no
    # re-encode for the next client syncing the same pair
    frame2, _ = core.build_sync_body(1)
    assert frame2 is frame


def test_delta_sync_roundtrip_with_nonfinite_leaves():
    """The XOR/shuffle/deflate pipeline is a BITWISE codec — NaN and
    +/-inf payload bytes survive it (a float-arithmetic delta could
    never say this)."""
    a = canned_update_tree(3, 65)  # odd leaf size: exercises the tail
    b = canned_update_tree(4, 65)
    k = a["params"]["dense"]["kernel"]
    k[0], k[1], k[2] = np.nan, np.inf, -np.inf
    frame = wire.encode_sync_delta(a, b, base_version=5)
    out = wire.decode_sync_delta(frame, b)
    la = list(wire._named_leaves(a))
    lo = list(wire._named_leaves(out))
    for (_, x), (_, y) in zip(la, lo):
        np.testing.assert_array_equal(
            np.asarray(x).view(np.uint8), np.asarray(y).view(np.uint8))


def test_delta_sync_base_off_ring_falls_back_dense_logged(caplog):
    core = _core(max_staleness=2)
    core.handle_register(1, incarnation=9, delta_ok=True)
    core.last_synced[1] = 0
    # advance far enough that version 0 leaves the broadcast ring
    for v in (1, 2, 3, 4):
        core.set_model(v, canned_update_tree(v, 64))
    assert 0 not in core._ring
    with caplog.at_level(logging.INFO,
                         logger="neuroimagedisttraining_tpu.asyncfl"):
        body, kind = core.build_sync_body(1)
    assert kind == "dense_fallback_ring"
    assert body is core.params  # the dense tree, not a frame
    assert core.sync_stats["sync_dense_fallback_ring"] == 1
    assert any("left the broadcast ring" in r.message
               for r in caplog.records)


# ---------------------------------------------------------------------------
# (d) cross-worker exactly-once: watermark floors under forced migration
# ---------------------------------------------------------------------------


def _upload(c, tag, seq, n=8.0):
    msg = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, c, 0)
    msg.add(M.ARG_MODEL_PARAMS, canned_update_tree(c, 64))
    msg.add(M.ARG_NUM_SAMPLES, n)
    msg.add(M.ARG_ROUND_IDX, tag)
    msg.add(M.ARG_UPLOAD_SEQ, seq)
    return msg


def test_forced_migration_replay_is_duplicate_not_double_count():
    """The regression the watermark plane exists for: worker A dies
    after accepting seqs 0..2 from client 7; the client reconnects onto
    worker B (same incarnation) and — not having heard A's verdicts —
    re-sends seq 2. Without the root floor, B's fresh dedup state would
    accept it again and the upload would double-contribute."""
    wm = SeqWatermarks()
    a, b = _core(wid=0), _core(wid=1)
    c, inc = 7, 3

    assert wm.register(c, inc) == -1
    a.handle_register(c, incarnation=inc)
    a.note_seqfloor(c, inc, -1)
    for s in range(3):
        assert a.handle_upload(_upload(c, 0, s)) == "accepted"
    # the accepted marks ride A's verdict batch up to the root
    wm.advance(c, inc, 2)

    # forced migration: same incarnation re-registers on B; the root's
    # floor reaches B BEFORE the register is answered
    floor = wm.register(c, inc)
    assert floor == 2
    b.handle_register(c, incarnation=inc)
    b.note_seqfloor(c, inc, floor)
    assert b.handle_upload(_upload(c, 0, 2)) == "dropped_duplicate"
    assert b.handle_upload(_upload(c, 0, 3)) == "accepted"

    # a RESTART (new incarnation) is not a migration: fresh floor,
    # seq 0 legitimate again
    assert wm.register(c, inc + 1) == -1
    b.handle_register(c, incarnation=inc + 1)
    b.note_seqfloor(c, inc + 1, wm.register(c, inc + 1))
    # a stale floor from the superseded incarnation must not poison
    # the fresh seq space...
    b.note_seqfloor(c, inc, 99)
    assert b.handle_upload(_upload(c, 0, 0)) == "accepted"
    # ...and neither must a superseded incarnation's draining marks
    wm.advance(c, inc, 50)
    assert wm.floor(c, inc + 1) == -1
    wm.advance(c, inc + 1, 0)
    assert wm.floor(c, inc + 1) == 0


# ---------------------------------------------------------------------------
# (e) live multi-process tree runs — slow (region children + fleets)
# ---------------------------------------------------------------------------


def _assert_green(res):
    audit = res["upload_audit"]
    assert audit["received_accounted"], audit
    assert audit["accepted_accounted"], audit
    assert res["frames_reconciled"], res
    assert res["rounds_or_aggregations"] == res["target"], res


@pytest.mark.slow
def test_region_tree_end_to_end_shm_and_delta():
    res = run_load(mode="ingest", num_clients=24, aggregations=6,
                   buffer_k=8, regions=2, ingest_workers=2,
                   ingest_shm=True, sync_delta=True,
                   upload_local_scale=1e-6, leaf_elems=64)
    _assert_green(res)
    assert res["regions"] == 2 and res["workers_per_region"] == 2
    assert res["lost_with_region"] == 0
    xs = res["worker_xstats"]
    assert xs["shm_exports"] > 0
    assert res["client_stats"]["delta_syncs"] > 0
    assert res["client_stats"]["delta_errors"] == 0
    # the fan-in is two-tier labeled: region="R" on top of worker="N"
    assert res["merged_metrics"]["region_labeled"] == [0, 1]
    assert res["merged_metrics"]["worker_labeled"] == [0, 1, 2, 3]


@pytest.mark.slow
def test_region_tree_secure_quant_end_to_end():
    res = run_load(mode="ingest", num_clients=16, aggregations=4,
                   buffer_k=6, regions=2, ingest_workers=2,
                   ingest_secure_quant=True, leaf_elems=64)
    _assert_green(res)
    assert res["secure_quant"] is True
    assert res["regions"] == 2
