#!/usr/bin/env bash
# Reproducible perf table (VERDICT r2 next-step #10): bench the flagship
# configurations as a matrix and collect one JSON artifact per cell, so
# round-over-round perf claims come from a rerunnable script instead of a
# hand-run number.
#
#   ./scripts/run_bench_matrix.sh [outdir]
#
# Cells:
#   {fedavg fast-path, salientgrads mask} x batch 16 x remat {none, stem}
#   + per-algorithm round timings (ALL engines incl. the flagship's
#     masked round, ditto, local, turboaggregate + MPC stage; phase 3)
#   + streaming samples/s on a synthetic larger-than-HBM-budget cohort
#     with host-gather / device-put / wall attribution
#   + ring-gossip ppermute-vs-einsum lowering & traffic cell
#
# Each bench.py invocation prints ONE JSON line; cells land in
# $OUT/bench_<cell>.json and a combined $OUT/BENCH_MATRIX.json.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT="${1:-bench_matrix}"
mkdir -p "$OUT"

run_cell() { # name, env...
    local name="$1"; shift
    echo "=== cell: $name ($*)" >&2
    if env "$@" python bench.py > "$OUT/bench_$name.json"; then
        echo "    -> $(cut -c1-160 "$OUT/bench_$name.json")" >&2
    else
        echo "    -> FAILED" >&2
        echo "{\"metric\": \"$name\", \"error\": \"bench failed\"}" \
            > "$OUT/bench_$name.json"
    fi
}

# main matrix (phase-3 per-algorithm timings ride along in the flagship
# cell only — they construct their own engines and dominate compile time
# otherwise):
#   flagship = 1 client/chip, b128 (the deployment layout; bench default)
#   parity   = 4 clients x b16 (the reference-canonical configuration)
run_cell flagship_b128       BENCH_REMAT=0 BENCH_ALGO_PHASES=1
run_cell flagship_b128_stem  BENCH_REMAT=stem BENCH_ALGO_PHASES=0
run_cell parity_b16_4c       BENCH_CLIENTS=4 BENCH_BATCH=16 BENCH_LOCAL=64 \
                             BENCH_REMAT=0 BENCH_ALGO_PHASES=0

# streaming throughput on a synthetic cohort sized beyond the resident
# budget (round-granular host feed, double-buffered)
python scripts/bench_streaming.py > "$OUT/bench_streaming.json" \
    || echo '{"metric": "streaming", "error": "failed"}' \
        > "$OUT/bench_streaming.json"
echo "    -> $(cut -c1-160 "$OUT/bench_streaming.json")" >&2

# ring-gossip consensus: ppermute vs dense einsum (8-virtual-device mesh;
# lowering + per-device traffic cell — multi-chip collectives don't run
# on the single real chip)
python scripts/bench_gossip.py > "$OUT/bench_gossip.json" \
    || echo '{"metric": "gossip", "error": "failed"}' \
        > "$OUT/bench_gossip.json"
echo "    -> $(cut -c1-160 "$OUT/bench_gossip.json")" >&2

# random-topology gossip: routed capped all_to_all vs dense einsum (the
# reference's per-round k-regular draw — DisPFL default, dpsgd cs=random)
env GOSSIP_MODE=random python scripts/bench_gossip.py \
    > "$OUT/bench_gossip_random.json" \
    || echo '{"metric": "gossip_random", "error": "failed"}' \
        > "$OUT/bench_gossip_random.json"
echo "    -> $(cut -c1-160 "$OUT/bench_gossip_random.json")" >&2

python - "$OUT" <<'EOF'
import json, sys, glob, os
out = sys.argv[1]
combined = {}
for p in sorted(glob.glob(os.path.join(out, "bench_*.json"))):
    cell = os.path.basename(p)[len("bench_"):-len(".json")]
    try:
        combined[cell] = json.loads(open(p).read().strip().splitlines()[-1])
    except Exception as e:
        combined[cell] = {"error": str(e)}
with open(os.path.join(out, "BENCH_MATRIX.json"), "w") as f:
    json.dump(combined, f, indent=1)
print(json.dumps({"cells": list(combined)}, indent=None))
EOF
