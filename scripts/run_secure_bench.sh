#!/usr/bin/env bash
# Secure-aggregation wire A/B over the REAL socket transport (ISSUE 8
# acceptance): four 2-silo federations through distributed/run.py —
#   plain         dense float32 pytrees (the baseline wire)
#   codec         --wire_codec delta+quant (the compression story)
#   secure_dense  --secure (int64 share slots: privacy at 6x the wire)
#   secure_quant  --secure_quant (field-element frames: privacy at a
#                 FRACTION of the dense-secure wire)
# The server's transport byte counters give true server-received bytes;
# wall time per run / per round rides along. The summary asserts
#   - secure_quant >= 5x fewer server-received bytes than secure_dense,
#   - final_param_norm parity between secure_quant and plain (same
#     seeds => same trajectories up to fixed-point quantization),
# and writes the artifact to bench_matrix/secure_bench.json.
#
# The model is 3dcnn_tiny on small volumes: bytes ratios are param-tree
# properties (uintN residues + seeds vs n_shares x int64 slots per
# parameter), not input-size properties — CPU step time is what the
# small shape buys.
set -uo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
PY=${PYTHON:-python}
ROUNDS=${SECURE_BENCH_ROUNDS:-3}
CLIENTS=2
MODEL=${SECURE_BENCH_MODEL:-3dcnn_tiny}
SHAPE=${SECURE_BENCH_SHAPE:-"12 14 12"}
OUT=bench_matrix/secure_bench.json
mkdir -p bench_matrix /tmp/secure_bench

run_one() {
    local tag=$1; shift
    local port
    port=$($PY -c "from neuroimagedisttraining_tpu.distributed.ports \
import free_port_block; print(free_port_block(8))")
    # shellcheck disable=SC2086 — SHAPE expands to three ints
    local common=(--num_clients "$CLIENTS" --comm_round "$ROUNDS"
                  --model "$MODEL" --dataset synthetic
                  --synthetic_num_subjects 24
                  --synthetic_shape $SHAPE --batch_size 4
                  --base_port "$port" --force_cpu --seed 7 "$@")
    echo "== secure bench [$tag] (port $port): $* =="
    local out="/tmp/secure_bench/${tag}.log"
    local t0
    t0=$($PY -c "import time; print(time.monotonic())")
    $PY -m neuroimagedisttraining_tpu.distributed.run \
        --role server "${common[@]}" > "$out" 2>&1 &
    local server_pid=$!
    local pids=()
    for r in $(seq 1 "$CLIENTS"); do
        $PY -m neuroimagedisttraining_tpu.distributed.run \
            --role client --rank "$r" "${common[@]}" \
            > "/tmp/secure_bench/${tag}_c${r}.log" 2>&1 &
        pids+=($!)
    done
    if ! wait "$server_pid"; then
        echo "FAIL($tag): server exited non-zero"; tail -20 "$out"; return 1
    fi
    for p in "${pids[@]}"; do wait "$p" 2>/dev/null || true; done
    local t1
    t1=$($PY -c "import time; print(time.monotonic())")
    grep -a -o '^{.*}' "$out" | tail -1 > "/tmp/secure_bench/${tag}.json"
    $PY - "$tag" "$t0" "$t1" <<'PYEOF'
import json, sys
tag, t0, t1 = sys.argv[1], float(sys.argv[2]), float(sys.argv[3])
path = f"/tmp/secure_bench/{tag}.json"
res = json.load(open(path))
res["wall_s"] = round(t1 - t0, 3)
json.dump(res, open(path, "w"))
print(json.dumps({k: res[k] for k in
                  ("rounds_completed", "bytes_recv", "wall_s")}))
PYEOF
}

rc=0
run_one plain                                  || rc=1
run_one codec         --wire_codec delta+quant || rc=1
run_one secure_dense  --secure                 || rc=1
run_one secure_quant  --secure_quant           || rc=1
[ $rc -ne 0 ] && exit $rc

$PY - "$OUT" "$ROUNDS" "$MODEL" "$SHAPE" <<'EOF'
import json, sys

out_path, rounds, model, shape = (sys.argv[1], int(sys.argv[2]),
                                  sys.argv[3], sys.argv[4])
runs = {t: json.load(open(f"/tmp/secure_bench/{t}.json"))
        for t in ("plain", "codec", "secure_dense", "secure_quant")}
summary = {"rounds": rounds, "model": model, "shape": shape,
           "runs": runs,
           "cells": {t: {"bytes_recv": runs[t]["bytes_recv"],
                         "wall_s": runs[t]["wall_s"],
                         "round_wall_s": round(
                             runs[t]["wall_s"] / rounds, 3)}
                     for t in runs}}
ratio = runs["secure_dense"]["bytes_recv"] / max(
    runs["secure_quant"]["bytes_recv"], 1)
vs_plain = runs["plain"]["bytes_recv"] / max(
    runs["secure_quant"]["bytes_recv"], 1)
a = runs["secure_quant"]["final_param_norm"]
b = runs["plain"]["final_param_norm"]
parity = abs(a - b) / max(abs(b), 1e-9)
summary["secure_quant_vs_dense"] = {
    "bytes_reduction_x": round(ratio, 2), "target_x": 5.0,
    "bytes_vs_plain_x": round(vs_plain, 2),
    "param_norm_rel_err_vs_plain": round(parity, 6),
    "pass": bool(ratio >= 5.0 and parity < 2e-2),
}
print(f"secure_quant vs secure_dense: {ratio:.2f}x fewer bytes "
      f"(target >= 5x); vs plain dense wire: {vs_plain:.2f}x; "
      f"param-norm rel err {parity:.2e} -> "
      f"{'PASS' if summary['secure_quant_vs_dense']['pass'] else 'FAIL'}")
json.dump(summary, open(out_path, "w"), indent=1, sort_keys=True)
print(f"artifact -> {out_path}")
sys.exit(0 if summary["secure_quant_vs_dense"]["pass"] else 1)
EOF
