#!/usr/bin/env bash
# The reference's flagship job (fedml_experiments/standalone/sailentgrads/
# Jobs/sailentgradsjob.sh:39-51): SalientGrads on ABCD sex classification,
# 21 site-clients, 200 rounds, density sweep. One TPU host replaces the
# 1xV100 SLURM allocation; no scheduler pragmas needed.
set -euo pipefail

H5=${1:?usage: run_abcd_salientgrads.sh /path/to/abcd.h5 [density]}
DENSITY=${2:-0.5}

python -m neuroimagedisttraining_tpu \
    --algorithm salientgrads --dataset abcd_h5 --data_dir "$H5" \
    --model 3DCNN --num_classes 1 --partition_method site \
    --client_num_in_total 21 --frac 1.0 --comm_round 200 \
    --batch_size 16 --epochs 2 --lr 0.01 --lr_decay 0.998 --wd 5e-4 \
    --dense_ratio "$DENSITY" --itersnip_iteration 1 \
    --checkpoint_dir "ckpt_salientgrads_d${DENSITY}" --checkpoint_every 10 \
    --tag "d${DENSITY}"
