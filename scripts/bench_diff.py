#!/usr/bin/env python
"""Produce fresh bench cells, then gate them against the committed
``bench_matrix/`` artifacts (ISSUE 13).

The thin driver around ``analysis/bench_gate.py`` for the common
session shape: regenerate the cheap cells you touched, diff them
against the committed matrix, get one machine-readable verdict.

    scripts/bench_diff.py --produce ingest       # ~2-3 min on this box
    scripts/bench_diff.py                        # pure diff of --fresh
    scripts/bench_diff.py --fresh /tmp/mybench --strict

``--produce ingest`` reruns the ingest-plane loadgen cells (the
single-process async baseline + the 2-worker sharded cell) at the
committed cohort AND window shape (1000 clients, buffer_k 50, 300
aggregations — run_ingest_bench.sh's own warning applies: a short
window is dominated by the 1k-client connection ramp and makes the
sustained number incomparably low), writes a fresh
``ingest_bench.json`` into ``--fresh`` and gates it: throughput cells
judged at the gate's drift-tolerant ratio thresholds, audits exactly.
Cells not regenerated (w1/w4) skip — that is the gate's contract, not
a failure.

Exit code: 0 green, 1 red, 2 usage error (bench_gate convention).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from neuroimagedisttraining_tpu.analysis import bench_gate  # noqa: E402


def produce_ingest(fresh_dir: str, clients: int, aggregations: int,
                   buffer_k: int, fleet_procs: int) -> str:
    """Regenerate the ingest-plane cells loadgen-style: the committed
    artifact's cohort/buffer shape, fewer aggregations (the sustained
    window still dominates the connection ramp)."""
    from neuroimagedisttraining_tpu.asyncfl.loadgen import run_load

    common = dict(num_clients=clients, aggregations=aggregations,
                  buffer_k=buffer_k, leaf_elems=256,
                  fleet_procs=fleet_procs)
    cells = {"async": run_load(mode="async", **common)}
    print(json.dumps({"cell": "async",
                      "uploads_per_s_sustained":
                          cells["async"]["uploads_per_s_sustained"]}),
          flush=True)
    cells["ingest_w2"] = run_load(mode="ingest", ingest_workers=2,
                                  **common)
    print(json.dumps({"cell": "ingest_w2",
                      "uploads_per_s_sustained":
                          cells["ingest_w2"]["uploads_per_s_sustained"]}),
          flush=True)
    out = {
        "bench": "ingest_plane",
        **cells,
        "summary": {
            "audits_green": all(
                c["upload_audit"]["received_accounted"]
                and c["upload_audit"]["accepted_accounted"]
                for c in cells.values()),
            "produced_by": "scripts/bench_diff.py --produce ingest",
            "aggregations": aggregations,
        },
    }
    os.makedirs(fresh_dir, exist_ok=True)
    path = os.path.join(fresh_dir, "ingest_bench.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True, default=str)
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="scripts/bench_diff.py",
        description=__doc__.split("\n\n")[0])
    ap.add_argument("--fresh", type=str, default="/tmp/nidt_bench_fresh")
    ap.add_argument("--committed", type=str,
                    default=bench_gate.DEFAULT_COMMITTED)
    ap.add_argument("--produce", choices=("none", "ingest"),
                    default="none",
                    help="regenerate these cells into --fresh before "
                         "gating (ingest = async baseline + w2 sharded "
                         "cell via asyncfl/loadgen.py)")
    ap.add_argument("--clients", type=int, default=1000,
                    help="--produce ingest cohort (default matches the "
                         "committed artifact)")
    ap.add_argument("--aggregations", type=int, default=300,
                    help="keep the committed window: short cells are "
                         "ramp-dominated and gate red spuriously")
    ap.add_argument("--buffer_k", type=int, default=50)
    ap.add_argument("--fleet_procs", type=int, default=3)
    ap.add_argument("--artifact", action="append", default=None)
    ap.add_argument("--strict", action="store_true")
    ap.add_argument("--json", type=str, default="")
    args = ap.parse_args(argv)

    artifacts = args.artifact
    if args.produce == "ingest":
        path = produce_ingest(args.fresh, args.clients,
                              args.aggregations, args.buffer_k,
                              args.fleet_procs)
        print(f"[bench_diff] fresh cell -> {path}", flush=True)
        if artifacts is None:
            # gate what was produced; other artifacts have no fresh
            # copy and would all read as skips anyway
            artifacts = ["ingest_bench.json"]
    try:
        res = bench_gate.gate(args.fresh, committed_dir=args.committed,
                              artifacts=artifacts, strict=args.strict)
    except ValueError as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1, sort_keys=True)
    print(json.dumps(res, indent=1, default=str))
    return 0 if res["verdict"] != "red" else 1


if __name__ == "__main__":
    sys.exit(main())
