#!/usr/bin/env bash
# Push-button profile session (ISSUE 14): run the declarative probe
# manifest (obs/probe.py — PROFILE.md's hand-run checklist, declared)
# through the SHIPPED driver with the dispatch-boundary profiler armed
# (obs/compute.py), gate the FRESH artifact against the COMMITTED
# baseline (analysis/bench_gate.py: structural cells exact, wall/TFLOPs
# at drift-tolerant ratios), then install it as
# bench_matrix/profile_session.json.
#
# Order matters: the session writes to a temp dir FIRST and gates
# before installing — gating after overwriting the committed path would
# compare the fresh artifact against itself and pass vacuously
# (scripts/bench_diff.py's --fresh discipline).
#
# Config-mismatch regenerations: the eq cells (dispatch counts,
# manifest fingerprint) are deterministic AT a config — a session run
# at a different shape/rounds/device count (e.g. the flagship TPU
# recipe below replacing the CPU smoke baseline) legitimately differs,
# so when the fresh meta block != the committed meta block the gate
# verdict is REPORTED but not fatal: the operator is establishing a new
# baseline and reviews + commits it.
#
# Defaults are the CPU-harness smoke shape; a TPU session exports the
# flagship recipe before running (PROFILE.md round 10):
#
#   PROFILE_MODEL=3DCNN PROFILE_SHAPE=121,145,121 \
#   PROFILE_BATCH=128 PROFILE_LOCAL=512 PROFILE_CLIENTS=21 \
#   PROFILE_ROUNDS=8 NIDT_PEAK_FLOPS=<chip bf16 peak * chips> \
#   scripts/run_profile_session.sh
#
# Env:
#   PROFILE_OUT       install path (default bench_matrix/profile_session.json)
#   PROFILE_DEVICES   virtual CPU devices for the cohort_sharded probe
#                     (default 2; ignored on real multi-device backends)
#   PROFILE_MANIFEST  JSON manifest replacing the default probe list
#   NIDT_PEAK_FLOPS   total device peak flop/s -> arms the nidt_mfu gauge
set -euo pipefail
cd "$(dirname "$0")/.."

PY="${PYTHON:-python}"
OUT="${PROFILE_OUT:-bench_matrix/profile_session.json}"
DEVICES="${PROFILE_DEVICES:-2}"
MANIFEST="${PROFILE_MANIFEST:-}"

fresh_dir="$(mktemp -d)"
trap 'rm -rf "$fresh_dir"' EXIT
fresh="$fresh_dir/profile_session.json"

args=(--out "$fresh" --virtual_devices "$DEVICES")
if [[ -n "$MANIFEST" ]]; then
    args+=(--manifest "$MANIFEST")
fi

echo "== profile session (fresh) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    "$PY" -m neuroimagedisttraining_tpu.obs.probe "${args[@]}"

if [[ -f "$OUT" ]]; then
    echo "== bench gate: fresh session vs committed baseline ($OUT) =="
    same_config="$("$PY" - "$fresh" "$OUT" <<'EOF'
import json, sys
fresh = json.load(open(sys.argv[1]))
committed = json.load(open(sys.argv[2]))
print("1" if fresh.get("meta") == committed.get("meta")
      and fresh["session"]["structural_fingerprint"]
      == committed["session"]["structural_fingerprint"] else "0")
EOF
)"
    gate_rc=0
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        "$PY" -m neuroimagedisttraining_tpu.analysis.bench_gate \
        --fresh "$fresh_dir" --committed "$(dirname "$OUT")" \
        --artifact profile_session.json --quiet || gate_rc=$?
    if [[ "$same_config" == "1" && "$gate_rc" -ne 0 ]]; then
        echo "profile session REGRESSED vs the committed baseline at" \
             "the SAME config — not installing $OUT" >&2
        exit "$gate_rc"
    elif [[ "$same_config" != "1" ]]; then
        echo "NOTE: session config differs from the committed baseline" \
             "(new shape/rounds/devices/manifest) — gate verdict above" \
             "is informational; installing as the NEW baseline." \
             "Review the diff before committing."
    fi
else
    echo "== no committed baseline at $OUT yet (first session) =="
fi

mkdir -p "$(dirname "$OUT")"
cp "$fresh" "$OUT"
echo "profile session complete: $OUT"
