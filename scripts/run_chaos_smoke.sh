#!/usr/bin/env bash
# Multiprocess kill-k chaos smoke (ISSUE 2): a 4-silo FedAvg federation
# where client 3 crashes at round 1 (deterministic FaultSchedule via
# --fault_spec) must still complete every round on BOTH control-plane
# transports — the deadline+quorum server aggregates the survivors with
# sample-count re-weighting and flags the corpse via heartbeats.
#
# Heavier than the tier-1 suite (each run trains the tiny 3D CNN in 5
# real OS processes), so it lives here as a CI smoke, not a pytest.
set -uo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
PY=${PYTHON:-python}
ROUNDS=3
CLIENTS=4

run_one() {
    local transport=$1
    local port
    port=$($PY -c "from neuroimagedisttraining_tpu.distributed.ports \
import free_port_block; print(free_port_block(16))")
    local common=(--num_clients "$CLIENTS" --comm_round "$ROUNDS"
                  --model 3dcnn_tiny --dataset synthetic
                  --synthetic_num_subjects 24
                  --synthetic_shape 12 14 12 --batch_size 4
                  --base_port "$port" --force_cpu
                  --transport "$transport"
                  --fault_spec "crash:3@1"
                  --round_deadline 30 --quorum 2
                  --heartbeat_interval 0.5 --heartbeat_timeout 5)
    echo "== chaos smoke ($transport transport, port $port): kill client 3 at round 1 =="
    local out="/tmp/chaos_smoke_${transport}.log"
    $PY -m neuroimagedisttraining_tpu.distributed.run \
        --role server "${common[@]}" > "$out" 2>&1 &
    local server_pid=$!
    local pids=()
    for r in $(seq 1 "$CLIENTS"); do
        $PY -m neuroimagedisttraining_tpu.distributed.run \
            --role client --rank "$r" "${common[@]}" \
            > "/tmp/chaos_smoke_${transport}_c${r}.log" 2>&1 &
        pids+=($!)
    done
    if ! wait "$server_pid"; then
        echo "FAIL($transport): server exited non-zero"; cat "$out"; return 1
    fi
    for p in "${pids[@]}"; do wait "$p" 2>/dev/null || true; done
    local json
    # -o '{.*}' keeps the JSON object even if an interleaved stderr line
    # lands on the same stdout line (both streams share the log file)
    json=$(grep -a -o '^{.*}' "$out" | tail -1)
    echo "$json"
    $PY - "$json" <<EOF
import json, sys
res = json.loads(sys.argv[1])
assert res["rounds_completed"] == $ROUNDS, res
assert 3 in res["suspects"], f"killed client not flagged suspect: {res}"
print(f"OK({res['transport']}): {res['rounds_completed']} rounds, "
      f"suspects={res['suspects']}")
EOF
}

rc=0
run_one socket || rc=1
run_one broker || rc=1
exit $rc
