#!/usr/bin/env bash
# Multiprocess chaos smoke: a 4-silo FedAvg federation must complete
# every round on BOTH control-plane transports under
#   - kill-k (ISSUE 2): client 3 crashes at round 1 (deterministic
#     FaultSchedule via --fault_spec) — the deadline+quorum server
#     aggregates the survivors with sample-count re-weighting and flags
#     the corpse via heartbeats;
#   - Byzantine (ISSUE 5): client 1 sign-flips its upload delta every
#     round — the server defends with trimmed_mean (byz_f=1) and the
#     outlier-scorer/quarantine control plane armed, and the final
#     model must come out finite;
#   - async (ISSUE 7): the FedBuff-style buffered server (asyncfl/) on
#     the selector comm core, kill-k churn + trimmed_mean armed, no
#     round barrier — every aggregation must land, the model stay
#     finite, and BOTH accounting audits (received == accepted +
#     dropped; accepted == aggregated + buffered) come back green;
#   - secure_quant + kill-k (ISSUE 8): client 3 crashes at round 1
#     under secure QUANTIZED aggregation (privacy/secure_quant.py) —
#     the two-phase Bonawitz discard drops the corpse's frame whole,
#     the survivor re-weighting keeps the aggregate a true weighted
#     mean, and every round still completes over field-element frames.
#
# Heavier than the tier-1 suite (each run trains the tiny 3D CNN in 5
# real OS processes), so it lives here as a CI smoke, not a pytest.
set -uo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
PY=${PYTHON:-python}
ROUNDS=3
CLIENTS=4

run_one() {
    local transport=$1 mode=$2
    local port
    port=$($PY -c "from neuroimagedisttraining_tpu.distributed.ports \
import free_port_block; print(free_port_block(16))")
    local common=(--num_clients "$CLIENTS" --comm_round "$ROUNDS"
                  --model 3dcnn_tiny --dataset synthetic
                  --synthetic_num_subjects 24
                  --synthetic_shape 12 14 12 --batch_size 4
                  --base_port "$port" --force_cpu
                  --transport "$transport"
                  --round_deadline 30 --quorum 2
                  --heartbeat_interval 0.5 --heartbeat_timeout 5)
    local what
    if [ "$mode" = byz ]; then
        common+=(--fault_spec "byz:1@0:sign_flip"
                 --defense trimmed_mean --byz_f 1
                 --quarantine_rounds 2 --outlier_threshold 2)
        what="client 1 sign-flips every round (defense=trimmed_mean)"
    else
        common+=(--fault_spec "crash:3@1")
        what="kill client 3 at round 1"
    fi
    echo "== chaos smoke ($transport transport, $mode cell, port $port): $what =="
    local out="/tmp/chaos_smoke_${transport}_${mode}.log"
    $PY -m neuroimagedisttraining_tpu.distributed.run \
        --role server "${common[@]}" > "$out" 2>&1 &
    local server_pid=$!
    local pids=()
    for r in $(seq 1 "$CLIENTS"); do
        $PY -m neuroimagedisttraining_tpu.distributed.run \
            --role client --rank "$r" "${common[@]}" \
            > "/tmp/chaos_smoke_${transport}_c${r}.log" 2>&1 &
        pids+=($!)
    done
    if ! wait "$server_pid"; then
        echo "FAIL($transport/$mode): server exited non-zero"
        cat "$out"; return 1
    fi
    for p in "${pids[@]}"; do wait "$p" 2>/dev/null || true; done
    local json
    # -o '{.*}' keeps the JSON object even if an interleaved stderr line
    # lands on the same stdout line (both streams share the log file)
    json=$(grep -a -o '^{.*}' "$out" | tail -1)
    echo "$json"
    $PY - "$json" "$mode" <<EOF
import json, math, sys
res = json.loads(sys.argv[1])
mode = sys.argv[2]
assert res["rounds_completed"] == $ROUNDS, res
if mode == "byz":
    assert res["defense"] == "trimmed_mean", res
    assert math.isfinite(res["final_param_norm"]), res
    print(f"OK({res['transport']}/byz): {res['rounds_completed']} rounds "
          f"defended, |params|={res['final_param_norm']:.3f}")
else:
    assert 3 in res["suspects"], f"killed client not flagged suspect: {res}"
    print(f"OK({res['transport']}/crash): {res['rounds_completed']} rounds, "
          f"suspects={res['suspects']}")
EOF
}

run_async() {
    local port
    port=$($PY -c "from neuroimagedisttraining_tpu.distributed.ports \
import free_port_block; print(free_port_block(16))")
    # NOTE: no --round_deadline/--quorum — the buffered server has no
    # round barrier and rejects them at startup by design
    local common=(--num_clients "$CLIENTS" --comm_round "$ROUNDS"
                  --model 3dcnn_tiny --dataset synthetic
                  --synthetic_num_subjects 24
                  --synthetic_shape 12 14 12 --batch_size 4
                  --base_port "$port" --force_cpu
                  --async_server --buffer_k 3 --max_staleness 8
                  --fault_spec "crash:3@1"
                  --defense trimmed_mean --byz_f 1
                  --heartbeat_interval 0.5 --heartbeat_timeout 5)
    echo "== chaos smoke (asyncfl buffered server, port $port): kill" \
         "client 3 at version 1, buffer_k=3, trimmed_mean armed =="
    local out="/tmp/chaos_smoke_async.log"
    $PY -m neuroimagedisttraining_tpu.distributed.run \
        --role server "${common[@]}" > "$out" 2>&1 &
    local server_pid=$!
    local pids=()
    for r in $(seq 1 "$CLIENTS"); do
        $PY -m neuroimagedisttraining_tpu.distributed.run \
            --role client --rank "$r" "${common[@]}" \
            > "/tmp/chaos_smoke_async_c${r}.log" 2>&1 &
        pids+=($!)
    done
    if ! wait "$server_pid"; then
        echo "FAIL(async): server exited non-zero"
        cat "$out"; return 1
    fi
    for p in "${pids[@]}"; do wait "$p" 2>/dev/null || true; done
    local json
    json=$(grep -a -o '^{.*}' "$out" | tail -1)
    echo "$json"
    $PY - "$json" <<EOF
import json, math, sys
res = json.loads(sys.argv[1])
assert res["async_server"] is True, res
assert res["rounds_completed"] == $ROUNDS, res
assert res["defense"] == "trimmed_mean", res
assert math.isfinite(res["final_param_norm"]), res
audit = res["upload_audit"]
# byte/frame accounting audit 1: every received upload accounted once
assert audit["received_accounted"], audit
# audit 2: every accepted upload aggregated or still buffered
assert audit["accepted_accounted"], audit
assert res["frames_recv"] > 0 and res["bytes_recv"] > 0, res
print(f"OK(async): {res['rounds_completed']} aggregations, "
      f"{audit['accepted']} uploads accepted "
      f"(taus={res['staleness_taus']}), audits green, "
      f"|params|={res['final_param_norm']:.3f}")
EOF
}

run_secure_quant() {
    local port
    port=$($PY -c "from neuroimagedisttraining_tpu.distributed.ports \
import free_port_block; print(free_port_block(16))")
    local common=(--num_clients "$CLIENTS" --comm_round "$ROUNDS"
                  --model 3dcnn_tiny --dataset synthetic
                  --synthetic_num_subjects 24
                  --synthetic_shape 12 14 12 --batch_size 4
                  --base_port "$port" --force_cpu
                  --secure_quant
                  --fault_spec "crash:3@1"
                  --round_deadline 30 --quorum 2
                  --heartbeat_interval 0.5 --heartbeat_timeout 5)
    echo "== chaos smoke (secure_quant cell, port $port): kill client 3" \
         "at round 1 under secure quantized aggregation =="
    local out="/tmp/chaos_smoke_secure_quant.log"
    $PY -m neuroimagedisttraining_tpu.distributed.run \
        --role server "${common[@]}" > "$out" 2>&1 &
    local server_pid=$!
    local pids=()
    for r in $(seq 1 "$CLIENTS"); do
        $PY -m neuroimagedisttraining_tpu.distributed.run \
            --role client --rank "$r" "${common[@]}" \
            > "/tmp/chaos_smoke_secure_quant_c${r}.log" 2>&1 &
        pids+=($!)
    done
    if ! wait "$server_pid"; then
        echo "FAIL(secure_quant): server exited non-zero"
        cat "$out"; return 1
    fi
    for p in "${pids[@]}"; do wait "$p" 2>/dev/null || true; done
    local json
    json=$(grep -a -o '^{.*}' "$out" | tail -1)
    echo "$json"
    $PY - "$json" <<EOF
import json, math, sys
res = json.loads(sys.argv[1])
assert res["secure_quant"] is True, res
assert res["rounds_completed"] == $ROUNDS, res
assert 3 in res["suspects"], f"killed client not flagged suspect: {res}"
assert math.isfinite(res["final_param_norm"]), res
print(f"OK(secure_quant/crash): {res['rounds_completed']} rounds over "
      f"field-element frames, suspects={res['suspects']}, "
      f"|params|={res['final_param_norm']:.3f}")
EOF
}

rc=0
run_one socket crash || rc=1
run_one broker crash || rc=1
run_one socket byz   || rc=1
run_one broker byz   || rc=1
run_async            || rc=1
run_secure_quant     || rc=1
exit $rc
