#!/usr/bin/env bash
# Multiprocess chaos smoke: a 4-silo FedAvg federation must complete
# every round on BOTH control-plane transports under
#   - kill-k (ISSUE 2): client 3 crashes at round 1 (deterministic
#     FaultSchedule via --fault_spec) — the deadline+quorum server
#     aggregates the survivors with sample-count re-weighting and flags
#     the corpse via heartbeats;
#   - Byzantine (ISSUE 5): client 1 sign-flips its upload delta every
#     round — the server defends with trimmed_mean (byz_f=1) and the
#     outlier-scorer/quarantine control plane armed, and the final
#     model must come out finite;
#   - async (ISSUE 7): the FedBuff-style buffered server (asyncfl/) on
#     the selector comm core, kill-k churn + trimmed_mean armed, no
#     round barrier — every aggregation must land, the model stay
#     finite, and BOTH accounting audits (received == accepted +
#     dropped; accepted == aggregated + buffered) come back green;
#     the cell also exercises the obs plane (ISSUE 9): a background
#     scraper hits the live /metrics endpoint MID-chaos (Prometheus
#     text must parse and carry the staleness histogram + buffer
#     occupancy), and after the kill-k run the server's flight-recorder
#     dump (--flight_out) must exist and parse with the control-plane
#     decisions in it;
#   - secure_quant + kill-k (ISSUE 8): client 3 crashes at round 1
#     under secure QUANTIZED aggregation (privacy/secure_quant.py) —
#     the two-phase Bonawitz discard drops the corpse's frame whole,
#     the survivor re-weighting keeps the aggregate a true weighted
#     mean, and every round still completes over field-element frames;
#   - reflex actions (ISSUE 20): a sign-flip silo under --actions on
#     with the defense starting at NONE — the firing health rules must
#     ACT (quarantine the silo, escalate the defense ladder) with rule
#     provenance on every dispatch, and the federation finish finite.
#
# Heavier than the tier-1 suite (each run trains the tiny 3D CNN in 5
# real OS processes), so it lives here as a CI smoke, not a pytest.
set -uo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
PY=${PYTHON:-python}
ROUNDS=3
CLIENTS=4

# metric-closure gate (ISSUE 16): the shipped example health-rule
# manifest must name only obs/names.py-declared metrics BEFORE any
# federation boots — a drifted manifest would load into every silo and
# watch a metric that no longer exists, permanently dark
echo "== validate scripts/health_rules.example.json (metric-name closure) =="
$PY -m neuroimagedisttraining_tpu.analysis \
    --check-manifest scripts/health_rules.example.json || exit 1

run_one() {
    local transport=$1 mode=$2
    local port
    port=$($PY -c "from neuroimagedisttraining_tpu.distributed.ports \
import free_port_block; print(free_port_block(16))")
    local common=(--num_clients "$CLIENTS" --comm_round "$ROUNDS"
                  --model 3dcnn_tiny --dataset synthetic
                  --synthetic_num_subjects 24
                  --synthetic_shape 12 14 12 --batch_size 4
                  --base_port "$port" --force_cpu
                  --transport "$transport"
                  --round_deadline 30 --quorum 2
                  --heartbeat_interval 0.5 --heartbeat_timeout 5)
    local what
    if [ "$mode" = byz ]; then
        common+=(--fault_spec "byz:1@0:sign_flip"
                 --defense trimmed_mean --byz_f 1
                 --quarantine_rounds 2 --outlier_threshold 2)
        what="client 1 sign-flips every round (defense=trimmed_mean)"
    else
        common+=(--fault_spec "crash:3@1")
        what="kill client 3 at round 1"
    fi
    echo "== chaos smoke ($transport transport, $mode cell, port $port): $what =="
    local out="/tmp/chaos_smoke_${transport}_${mode}.log"
    $PY -m neuroimagedisttraining_tpu.distributed.run \
        --role server "${common[@]}" > "$out" 2>&1 &
    local server_pid=$!
    local pids=()
    for r in $(seq 1 "$CLIENTS"); do
        $PY -m neuroimagedisttraining_tpu.distributed.run \
            --role client --rank "$r" "${common[@]}" \
            > "/tmp/chaos_smoke_${transport}_c${r}.log" 2>&1 &
        pids+=($!)
    done
    if ! wait "$server_pid"; then
        echo "FAIL($transport/$mode): server exited non-zero"
        cat "$out"; return 1
    fi
    for p in "${pids[@]}"; do wait "$p" 2>/dev/null || true; done
    local json
    # -o '{.*}' keeps the JSON object even if an interleaved stderr line
    # lands on the same stdout line (both streams share the log file)
    json=$(grep -a -o '^{.*}' "$out" | tail -1)
    echo "$json"
    $PY - "$json" "$mode" <<EOF
import json, math, sys
res = json.loads(sys.argv[1])
mode = sys.argv[2]
assert res["rounds_completed"] == $ROUNDS, res
if mode == "byz":
    assert res["defense"] == "trimmed_mean", res
    assert math.isfinite(res["final_param_norm"]), res
    print(f"OK({res['transport']}/byz): {res['rounds_completed']} rounds "
          f"defended, |params|={res['final_param_norm']:.3f}")
else:
    assert 3 in res["suspects"], f"killed client not flagged suspect: {res}"
    print(f"OK({res['transport']}/crash): {res['rounds_completed']} rounds, "
          f"suspects={res['suspects']}")
EOF
}

run_async() {
    local port
    port=$($PY -c "from neuroimagedisttraining_tpu.distributed.ports \
import free_port_block; print(free_port_block(16))")
    # NOTE: no --round_deadline/--quorum — the buffered server has no
    # round barrier and rejects them at startup by design
    local metrics_port=$((port + 8))
    local flight_out="/tmp/chaos_smoke_async_flight.json"
    local scrape_out="/tmp/chaos_smoke_async_metrics.txt"
    rm -f "$flight_out" "$scrape_out"
    local common=(--num_clients "$CLIENTS" --comm_round "$ROUNDS"
                  --model 3dcnn_tiny --dataset synthetic
                  --synthetic_num_subjects 24
                  --synthetic_shape 12 14 12 --batch_size 4
                  --base_port "$port" --force_cpu
                  --async_server --buffer_k 3 --max_staleness 8
                  --fault_spec "crash:3@1"
                  --defense trimmed_mean --byz_f 1
                  --heartbeat_interval 0.5 --heartbeat_timeout 5)
    echo "== chaos smoke (asyncfl buffered server, port $port): kill" \
         "client 3 at version 1, buffer_k=3, trimmed_mean armed," \
         "/metrics on $metrics_port =="
    local out="/tmp/chaos_smoke_async.log"
    $PY -m neuroimagedisttraining_tpu.distributed.run \
        --role server "${common[@]}" \
        --metrics_port "$metrics_port" --flight_out "$flight_out" \
        --flight_events 512 > "$out" 2>&1 &
    local server_pid=$!
    # obs cell (ISSUE 9): scrape the LIVE /metrics endpoint mid-chaos —
    # the scrape must be valid Prometheus text carrying the staleness
    # histogram and an accepted-uploads sample before the run ends
    $PY - "$metrics_port" "$scrape_out" <<'PYEOF' &
import sys, time, urllib.request
port, out = int(sys.argv[1]), sys.argv[2]
deadline = time.time() + 240
while time.time() < deadline:
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=2).read().decode()
        if ('nidt_async_uploads_total{outcome="accepted"}' in body
                and "nidt_async_staleness_bucket" in body
                and "nidt_async_buffer_occupancy" in body
                and "nidt_alert{" in body):
            open(out, "w").write(body)
            sys.exit(0)
    except Exception:
        pass
    time.sleep(0.3)
sys.exit(1)
PYEOF
    local scraper_pid=$!
    local pids=()
    for r in $(seq 1 "$CLIENTS"); do
        $PY -m neuroimagedisttraining_tpu.distributed.run \
            --role client --rank "$r" "${common[@]}" \
            > "/tmp/chaos_smoke_async_c${r}.log" 2>&1 &
        pids+=($!)
    done
    if ! wait "$server_pid"; then
        echo "FAIL(async): server exited non-zero"
        kill "$scraper_pid" 2>/dev/null
        cat "$out"; return 1
    fi
    for p in "${pids[@]}"; do wait "$p" 2>/dev/null || true; done
    if ! wait "$scraper_pid"; then
        echo "FAIL(async/obs): mid-chaos /metrics scrape never saw the "\
"staleness histogram + buffer occupancy"
        return 1
    fi
    local json
    json=$(grep -a -o '^{.*}' "$out" | tail -1)
    echo "$json"
    $PY - "$json" "$scrape_out" "$flight_out" <<EOF
import json, math, re, sys
res = json.loads(sys.argv[1])
assert res["async_server"] is True, res
assert res["rounds_completed"] == $ROUNDS, res
assert res["defense"] == "trimmed_mean", res
assert math.isfinite(res["final_param_norm"]), res
audit = res["upload_audit"]
# byte/frame accounting audit 1: every received upload accounted once
assert audit["received_accounted"], audit
# audit 2: every accepted upload aggregated or still buffered
assert audit["accepted_accounted"], audit
assert res["frames_recv"] > 0 and res["bytes_recv"] > 0, res
# obs cell (ISSUE 9): the mid-chaos scrape is valid Prometheus text
# with the async distributions present
sample = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? '
                    r'(-?\d+(\.\d+)?([eE][+-]?\d+)?|\+Inf|-Inf|NaN)$')
scrape = open(sys.argv[2]).read()
for line in scrape.strip().splitlines():
    assert line.startswith("#") or sample.match(line), line
assert "nidt_async_staleness_bucket" in scrape
assert "nidt_async_buffer_occupancy" in scrape
assert 'nidt_async_uploads_total{outcome="accepted"}' in scrape
# training-health cell (ISSUE 15): the anomaly-rule engine evaluates
# at every version advance, so the MID-chaos scrape carries nidt_alert
# samples (one per built-in rule, 0 while not firing)
assert "nidt_alert{" in scrape, "no nidt_alert samples mid-chaos"
assert 'rule="staleness-runaway"' in scrape, "builtin rules missing"
# and the kill-k run left a parseable flight-recorder post-mortem
flight = json.load(open(sys.argv[3]))
kinds = [e["kind"] for e in flight["events"]]
assert "accept" in kinds and "aggregate" in kinds, kinds[:20]
print(f"OK(async): {res['rounds_completed']} aggregations, "
      f"{audit['accepted']} uploads accepted "
      f"(taus={res['staleness_taus']}), audits green, "
      f"|params|={res['final_param_norm']:.3f}; obs: /metrics scraped "
      f"mid-chaos ({len(scrape.splitlines())} lines), flight dump "
      f"{len(flight['events'])} events")
EOF
}

run_secure_quant() {
    local port
    port=$($PY -c "from neuroimagedisttraining_tpu.distributed.ports \
import free_port_block; print(free_port_block(16))")
    local common=(--num_clients "$CLIENTS" --comm_round "$ROUNDS"
                  --model 3dcnn_tiny --dataset synthetic
                  --synthetic_num_subjects 24
                  --synthetic_shape 12 14 12 --batch_size 4
                  --base_port "$port" --force_cpu
                  --secure_quant
                  --fault_spec "crash:3@1"
                  --round_deadline 30 --quorum 2
                  --heartbeat_interval 0.5 --heartbeat_timeout 5)
    echo "== chaos smoke (secure_quant cell, port $port): kill client 3" \
         "at round 1 under secure quantized aggregation =="
    local out="/tmp/chaos_smoke_secure_quant.log"
    $PY -m neuroimagedisttraining_tpu.distributed.run \
        --role server "${common[@]}" > "$out" 2>&1 &
    local server_pid=$!
    local pids=()
    for r in $(seq 1 "$CLIENTS"); do
        $PY -m neuroimagedisttraining_tpu.distributed.run \
            --role client --rank "$r" "${common[@]}" \
            > "/tmp/chaos_smoke_secure_quant_c${r}.log" 2>&1 &
        pids+=($!)
    done
    if ! wait "$server_pid"; then
        echo "FAIL(secure_quant): server exited non-zero"
        cat "$out"; return 1
    fi
    for p in "${pids[@]}"; do wait "$p" 2>/dev/null || true; done
    local json
    json=$(grep -a -o '^{.*}' "$out" | tail -1)
    echo "$json"
    $PY - "$json" <<EOF
import json, math, sys
res = json.loads(sys.argv[1])
assert res["secure_quant"] is True, res
assert res["rounds_completed"] == $ROUNDS, res
assert 3 in res["suspects"], f"killed client not flagged suspect: {res}"
assert math.isfinite(res["final_param_norm"]), res
print(f"OK(secure_quant/crash): {res['rounds_completed']} rounds over "
      f"field-element frames, suspects={res['suspects']}, "
      f"|params|={res['final_param_norm']:.3f}")
EOF
}

run_ingest() {
    # sharded ingest plane (ISSUE 12, asyncfl/ingest.py), two cells:
    # (1) a REAL cross-silo federation served by 2 SO_REUSEPORT worker
    #     processes + the merging root — every aggregation lands, both
    #     accounting audits green across processes;
    # (2) the loadgen kill-one-worker chaos cell — worker 0 SIGKILLed
    #     mid-run, clients reconnect onto the surviving listener, the
    #     audit reconciles with the dead worker's buffered uploads
    #     counted lost_with_worker, never silently vanished.
    local port
    port=$($PY -c "from neuroimagedisttraining_tpu.distributed.ports \
import free_port_block; print(free_port_block(16))")
    local common=(--num_clients "$CLIENTS" --comm_round "$ROUNDS"
                  --model 3dcnn_tiny --dataset synthetic
                  --synthetic_num_subjects 24
                  --synthetic_shape 12 14 12 --batch_size 4
                  --base_port "$port" --force_cpu
                  --async_server --buffer_k 3 --max_staleness 8
                  --ingest_workers 2)
    local metrics_port=$((port + 8))
    local scrape_out="/tmp/chaos_smoke_ingest_metrics.txt"
    rm -f "$scrape_out"
    echo "== chaos smoke (sharded ingest cell, port $port): real" \
         "federation on 2 SO_REUSEPORT workers + merging root," \
         "MERGED /metrics on $metrics_port =="
    local out="/tmp/chaos_smoke_ingest.log"
    $PY -m neuroimagedisttraining_tpu.distributed.run \
        --role server "${common[@]}" \
        --metrics_port "$metrics_port" > "$out" 2>&1 &
    local server_pid=$!
    # obs fan-in cell (ISSUE 13): the scrape must be the MERGED
    # exposition — worker-labeled samples from BOTH worker registries
    # plus the snapshot-staleness gauges — captured MID-chaos
    $PY - "$metrics_port" "$scrape_out" <<'PYEOF' &
import sys, time, urllib.request
port, out = int(sys.argv[1]), sys.argv[2]
deadline = time.time() + 240
while time.time() < deadline:
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=2).read().decode()
        if ('worker="0"' in body and 'worker="1"' in body
                and "nidt_obs_worker_snapshot_age_s" in body
                and "nidt_upload_stage_ms_bucket" in body):
            open(out, "w").write(body)
            sys.exit(0)
    except Exception:
        pass
    time.sleep(0.3)
sys.exit(1)
PYEOF
    local scraper_pid=$!
    local pids=()
    for r in $(seq 1 "$CLIENTS"); do
        $PY -m neuroimagedisttraining_tpu.distributed.run \
            --role client --rank "$r" "${common[@]}" \
            > "/tmp/chaos_smoke_ingest_c${r}.log" 2>&1 &
        pids+=($!)
    done
    if ! wait "$server_pid"; then
        echo "FAIL(ingest): server exited non-zero"
        kill "$scraper_pid" 2>/dev/null
        cat "$out"; return 1
    fi
    for p in "${pids[@]}"; do wait "$p" 2>/dev/null || true; done
    if ! wait "$scraper_pid"; then
        echo "FAIL(ingest/obs): mid-chaos MERGED /metrics scrape never "\
"saw worker-labeled samples from both workers + staleness gauges"
        return 1
    fi
    local json
    json=$(grep -a -o '^{.*}' "$out" | tail -1)
    echo "$json"
    $PY - "$json" "$scrape_out" <<EOF
import json, math, re, sys
res = json.loads(sys.argv[1])
assert res.get("ingest_workers") == 2, res
assert res["rounds_completed"] == $ROUNDS, res
audit = res["upload_audit"]
assert audit["received_accounted"], audit
assert audit["accepted_accounted"], audit
assert audit["lost_with_worker"] == 0, audit
assert math.isfinite(res["final_param_norm"]), res
assert res["frames_recv"] > 0, res
# obs fan-in (ISSUE 13): the mid-chaos scrape is valid Prometheus text
# carrying BOTH workers' registries (worker label) + staleness gauges +
# the upload-stage histogram
sample = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? '
                    r'(-?\d+(\.\d+)?([eE][+-]?\d+)?|\+Inf|-Inf|NaN)$')
scrape = open(sys.argv[2]).read()
for line in scrape.strip().splitlines():
    assert line.startswith("#") or sample.match(line), line
workers = sorted(set(re.findall(r'worker="(\d+)"', scrape)))
assert workers == ["0", "1"], workers
assert "nidt_obs_worker_snapshot_age_s" in scrape
assert "nidt_upload_stage_ms_bucket" in scrape
print(f"OK(ingest/federation): {res['rounds_completed']} aggregations "
      f"over 2 workers, audits green, |params|="
      f"{res['final_param_norm']:.3f}; obs: MERGED /metrics scraped "
      f"mid-chaos ({len(scrape.splitlines())} lines, workers {workers})")
EOF
    local irc=$?
    [ $irc -ne 0 ] && return $irc
    echo "== chaos smoke (sharded ingest kill-one-worker cell):" \
         "SIGKILL worker 0 at version 2, audits must stay green =="
    # a real file, not a '$PY -' heredoc: the ingest root spawns worker
    # processes with the 'spawn' context, which re-imports the parent's
    # main module — '<stdin>' has no path to re-import
    local killpy="/tmp/chaos_smoke_ingest_kill.py"
    cat > "$killpy" <<'EOF'
from neuroimagedisttraining_tpu.asyncfl.loadgen import run_load

# the __main__ guard matters: the spawn context re-imports this file in
# every worker child
if __name__ == "__main__":
    res = run_load(mode="ingest", num_clients=60, aggregations=8,
                   buffer_k=20, ingest_workers=3, ingest_kill_at=2,
                   leaf_elems=64)
    audit = res["upload_audit"]
    assert audit["received_accounted"], audit
    assert audit["accepted_accounted"], audit
    assert res["frames_reconciled"], res
    assert res["rounds_or_aggregations"] == 8, res
    assert not audit["workers"][0]["alive"], audit
    print(f"OK(ingest/kill-worker): 8 aggregations, worker 0 killed, "
          f"{res['lost_with_worker']} buffered uploads accounted "
          f"lost_with_worker, {res['client_stats']['rejoins']} client "
          "rejoins, audits green")
EOF
    # PYTHONPATH: running a file from /tmp drops the repo cwd from
    # sys.path ('python -' used to add it); worker children inherit it
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" $PY "$killpy"
}

run_serve() {
    # serving plane (ISSUE 17, serve/): SIGKILL one serve worker
    # mid-load — the surviving SO_REUSEPORT listener absorbs the whole
    # request fleet (clients reconnect, the kernel re-hashes their new
    # connections), the MERGED /metrics scrape mid-chaos carries both
    # workers' serve-latency samples, and the shutdown audit leaves
    # zero dropped-but-unaccounted requests: every attempt lands in
    # exactly one client bucket, every server verdict reconciles, and
    # the corpse's unflushed tail is pinned to it, never vanished.
    local mport
    mport=$($PY -c "from neuroimagedisttraining_tpu.distributed.ports \
import free_port_block; print(free_port_block(2))")
    local scrape_out="/tmp/chaos_smoke_serve_metrics.txt"
    rm -f "$scrape_out"
    echo "== chaos smoke (serving cell): SIGKILL serve worker 0" \
         "mid-load, survivor absorbs the fleet, MERGED /metrics on" \
         "$mport =="
    # mid-chaos scraper: the merged exposition must carry BOTH workers'
    # registries (worker label) + the serve-latency histogram while the
    # fleet is still running
    $PY - "$mport" "$scrape_out" <<'PYEOF' &
import sys, time, urllib.request
port, out = int(sys.argv[1]), sys.argv[2]
deadline = time.time() + 240
while time.time() < deadline:
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=2).read().decode()
        if ('worker="0"' in body and 'worker="1"' in body
                and "nidt_serve_latency_ms_bucket" in body
                and "nidt_serve_requests_total" in body):
            open(out, "w").write(body)
            sys.exit(0)
    except Exception:
        pass
    time.sleep(0.2)
sys.exit(1)
PYEOF
    local scraper_pid=$!
    # a real file, not a '$PY -' heredoc: the serve root spawns worker
    # processes with the 'spawn' context, which re-imports the parent's
    # main module — '<stdin>' has no path to re-import
    local servepy="/tmp/chaos_smoke_serve.py"
    cat > "$servepy" <<'EOF'
import os
import sys
import tempfile

# the __main__ guard matters: the spawn context re-imports this file in
# every worker child
if __name__ == "__main__":
    import jax
    import jax.numpy as jnp
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.utils.checkpoint import save_checkpoint
    from neuroimagedisttraining_tpu.serve.bundle import build_bundle
    from neuroimagedisttraining_tpu.asyncfl.loadgen import run_load

    mport = int(sys.argv[1])
    shape = (12, 14, 12)
    m = create_model("3dcnn_tiny", num_classes=1)
    v = m.init({"params": jax.random.PRNGKey(0),
                "dropout": jax.random.PRNGKey(1)},
               jnp.zeros((1, *shape, 1)), train=False)
    params, bstats = v["params"], v.get("batch_stats", {})

    def stack(t):
        return jax.tree.map(
            lambda x: jnp.stack([x * (1.0 + 0.1 * i)
                                 for i in range(2)]), t)

    state = {"params": params, "batch_stats": bstats,
             "per_params": stack(params), "per_bstats": stack(bstats)}
    td = tempfile.mkdtemp(prefix="nidt_chaos_serve.")
    ck, bd = os.path.join(td, "ck"), os.path.join(td, "bundle")
    save_checkpoint(ck, 3, state)
    build_bundle(ck, bd, model="3dcnn_tiny", num_classes=1,
                 input_shape=shape)

    res = run_load(mode="serve", num_clients=80, serve_bundle=bd,
                   serve_workers=2, serve_requests=400,
                   serve_kill_at=80, fleet_procs=2,
                   batch_buckets=(1, 2, 4), metrics_port=mport)
    audit = res["serve_audit"]
    assert res["worker_killed"], res
    assert audit["dead_workers"] == 1, audit
    assert res["workers_live_at_end"] == [1], res["workers_live_at_end"]
    assert res["frames_reconciled"], audit
    # the fleet was absorbed: post-kill attempts reconnected onto the
    # survivor, and every attempt landed in exactly one client bucket
    assert res["client_reconnects"] > 0, res
    assert res["requests_sent"] == (res["requests_ok"]
                                    + res["requests_rejected"]
                                    + res["client_errors"]), res
    assert res["requests_ok"] > 80, res
    print(f"OK(serve/kill-worker): {res['requests_ok']}/"
          f"{res['requests_sent']} served, worker 0 SIGKILLed after "
          f"80 served, {res['client_reconnects']} reconnects absorbed "
          f"by the survivor, {res['unflushed_with_worker']} in-flight "
          "verdicts pinned to the corpse, audits green")
EOF
    # PYTHONPATH: running a file from /tmp drops the repo cwd from
    # sys.path; worker children inherit it
    if ! PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" $PY "$servepy" \
            "$mport"; then
        kill "$scraper_pid" 2>/dev/null
        echo "FAIL(serve): kill-one-worker serving cell"
        return 1
    fi
    if ! wait "$scraper_pid"; then
        echo "FAIL(serve/obs): mid-chaos MERGED /metrics scrape never "\
"saw both workers' serve-latency samples"
        return 1
    fi
    $PY - "$scrape_out" <<'EOF'
import re, sys
sample = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? '
                    r'(-?\d+(\.\d+)?([eE][+-]?\d+)?|\+Inf|-Inf|NaN)$')
scrape = open(sys.argv[1]).read()
for line in scrape.strip().splitlines():
    assert line.startswith("#") or sample.match(line), line
workers = sorted(set(re.findall(r'worker="(\d+)"', scrape)))
assert workers == ["0", "1"], workers
assert "nidt_serve_latency_ms_bucket" in scrape
print(f"OK(serve/obs): MERGED /metrics scraped mid-chaos "
      f"({len(scrape.splitlines())} lines, workers {workers})")
EOF
}

run_region() {
    # hierarchical aggregation tier (ISSUE 18, asyncfl/region.py):
    # SIGKILL an entire REGION process (its worker fleet dies with it)
    # mid-load — clients reconnect onto the surviving region's
    # SO_REUSEPORT listeners, the corpse's unshipped partial is
    # accounted lost_with_region (never silently vanished), and a
    # MID-chaos scrape of the MERGED /metrics must read the death:
    # region 0's fan-in rows stale (nidt_obs_worker_alive 0) while
    # region 1 stays live, with the per-region staleness gauges
    # present.
    local mport
    mport=$($PY -c "from neuroimagedisttraining_tpu.distributed.ports \
import free_port_block; print(free_port_block(2))")
    local scrape_out="/tmp/chaos_smoke_region_metrics.txt"
    rm -f "$scrape_out"
    echo "== chaos smoke (region-kill cell): SIGKILL region 0 of a" \
         "2x2 tree at version 4, MERGED /metrics on $mport =="
    # mid-chaos scraper: succeeds only on an exposition that shows
    # region 0 DEAD and region 1 ALIVE at the same instant — by
    # construction a mid-chaos capture (the server is still serving)
    $PY - "$mport" "$scrape_out" <<'PYEOF' &
import re, sys, time, urllib.request
port, out = int(sys.argv[1]), sys.argv[2]
dead = re.compile(r'nidt_obs_worker_alive\{[^}]*region="0"[^}]*\} 0(\.0)?$',
                  re.M)
live = re.compile(r'nidt_obs_worker_alive\{[^}]*region="1"[^}]*\} 1(\.0)?$',
                  re.M)
deadline = time.time() + 240
while time.time() < deadline:
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=2).read().decode()
        if (dead.search(body) and live.search(body)
                and "nidt_region_staleness" in body
                and "nidt_region_partial_age_s" in body):
            open(out, "w").write(body)
            sys.exit(0)
    except Exception:
        pass
    time.sleep(0.1)
sys.exit(1)
PYEOF
    local scraper_pid=$!
    # a real file, not a '$PY -' heredoc: the region tier spawns its
    # children with the 'spawn' context, which re-imports the parent's
    # main module — '<stdin>' has no path to re-import
    local killpy="/tmp/chaos_smoke_region_kill.py"
    cat > "$killpy" <<'EOF'
import sys

from neuroimagedisttraining_tpu.asyncfl.loadgen import run_load

# the __main__ guard matters: the spawn context re-imports this file in
# every region/worker child
if __name__ == "__main__":
    res = run_load(mode="ingest", num_clients=60, aggregations=24,
                   buffer_k=20, regions=2, ingest_workers=2,
                   ingest_kill_at=4, leaf_elems=64, ingest_shm=True,
                   metrics_port=int(sys.argv[1]))
    audit = res["upload_audit"]
    assert audit["received_accounted"], audit
    assert audit["accepted_accounted"], audit
    assert res["frames_reconciled"], res
    assert res["rounds_or_aggregations"] == 24, res
    assert res["regions"] == 2, res
    # region 0 died mid-run (region 1 reads not-alive too by now —
    # that is the CLEAN end-of-run teardown, which the mid-chaos
    # /metrics scrape disambiguates)
    assert not audit["regions"][0]["alive"], audit
    r0, r1 = audit["regions"][0], audit["regions"][1]
    # the corpse's acceptances are all accounted: folded or counted
    # lost_with_region — the invariant, not a specific loss count
    assert r0["acc"] == r0["folded"], audit
    # the fleet was absorbed: region 1 kept folding partials after the
    # kill and region 0's clients re-registered onto its listeners
    assert r1["partials"] > r0["partials"], audit
    assert res["client_stats"]["rejoins"] > 0, res["client_stats"]
    print(f"OK(region/kill-region): 24 aggregations, region 0 "
          f"SIGKILLed, {res['lost_with_region']} buffered uploads "
          f"accounted lost_with_region, "
          f"{res['client_stats']['rejoins']} client rejoins onto the "
          "survivor, audits green")
EOF
    # PYTHONPATH: running a file from /tmp drops the repo cwd from
    # sys.path; region/worker children inherit it
    if ! PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" $PY "$killpy" \
            "$mport"; then
        kill "$scraper_pid" 2>/dev/null
        echo "FAIL(region): kill-one-region cell"
        return 1
    fi
    if ! wait "$scraper_pid"; then
        echo "FAIL(region/obs): mid-chaos MERGED /metrics scrape never "\
"read region 0 dead + region 1 alive with the staleness gauges"
        return 1
    fi
    $PY - "$scrape_out" <<'EOF'
import re, sys
sample = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? '
                    r'(-?\d+(\.\d+)?([eE][+-]?\d+)?|\+Inf|-Inf|NaN)$')
scrape = open(sys.argv[1]).read()
for line in scrape.strip().splitlines():
    assert line.startswith("#") or sample.match(line), line
regions = sorted(set(re.findall(r'region="(\d+)"', scrape)))
assert regions == ["0", "1"], regions
assert "nidt_region_staleness" in scrape
assert "nidt_region_partial_age_s" in scrape
print(f"OK(region/obs): MERGED /metrics scraped mid-chaos "
      f"({len(scrape.splitlines())} lines, regions {regions}, "
      "region 0 read dead while region 1 served)")
EOF
}

run_actions() {
    # reflex plane (ISSUE 20, obs/actions.py): a 1-of-4 sign-flip silo
    # under --actions on, starting from defense NONE — the health rules
    # must ACT, not just alert: client-divergence quarantines the
    # offending silo (next cohort excludes it) and defense-escalation
    # steps the robust-aggregation ladder none -> norm_diff_clipping,
    # every dispatch flight-recorded with the firing rule as
    # provenance in the verdict's actions block; the federation still
    # finishes with finite metrics.
    local out="/tmp/chaos_smoke_actions"
    rm -rf "$out"; mkdir -p "$out"
    echo "== chaos smoke (reflex-actions cell): sign-flip silo," \
         "--actions on, defense starts at none =="
    if ! $PY -m neuroimagedisttraining_tpu \
            --algorithm fedavg --dataset synthetic --model 3dcnn_tiny \
            --synthetic_num_subjects 64 --synthetic_shape 12 14 12 \
            --client_num_in_total 4 --comm_round 2 --batch_size 8 \
            --epochs 2 --lr 3e-3 --seed 1024 --log_dir "$out" \
            --tag actions --health_stats --actions on --defense none \
            --fault_spec "byz:1@0:sign_flip,byz:1@1:sign_flip" \
            > "$out/run.log" 2>&1; then
        echo "FAIL(actions): reflex run exited non-zero"
        tail -30 "$out/run.log"; return 1
    fi
    $PY - "$out" <<'EOF'
import glob, json, math, sys
(vp,) = glob.glob(sys.argv[1] + "/synthetic/*.health.json")
doc = json.load(open(vp))
acts = doc["actions"]
assert acts["mode"] == "on", acts
by = {e["action"]: e for e in acts["log"] if e["status"] == "applied"}
q = by.get("quarantine_silo")
assert q is not None, f"no applied quarantine in {acts['log']}"
assert q["rule"] == "client-divergence", q
assert q["detail"]["client"] == 0, q     # byz rank 1 == client 0
e = by.get("escalate_defense")
assert e is not None, f"no applied escalation in {acts['log']}"
assert e["rule"] == "defense-escalation", e
assert e["detail"] == {"from": "none", "to": "norm_diff_clipping"}, e
assert all(not x["dry_run"] for x in acts["log"]), acts["log"]
assert doc["rounds_evaluated"] == 2, doc
# the run's summary JSON (last {...} line of the log) must be finite
(summary,) = [l for l in open(sys.argv[1] + "/run.log")
              if l.startswith("{")][-1:]
fin = json.loads(summary)["final_global"]
assert all(math.isfinite(v) for v in fin.values()), fin
print(f"OK(actions): quarantined client {q['detail']['client']} "
      f"(cos {q['detail']['cos']:.3f}) and escalated "
      f"{e['detail']['from']} -> {e['detail']['to']}, rule provenance "
      "on every dispatch, federation finished")
EOF
}

rc=0
run_one socket crash || rc=1
run_one broker crash || rc=1
run_one socket byz   || rc=1
run_one broker byz   || rc=1
run_async            || rc=1
run_secure_quant     || rc=1
run_ingest           || rc=1
run_region           || rc=1
run_serve            || rc=1
run_actions          || rc=1
exit $rc
