#!/usr/bin/env bash
# The reference's CIFAR sweep config (Jobs/sailentgradsjob.sh:39-51,
# BASELINE.md): ResNet-18, Dirichlet alpha=0.3, 100 clients, frac 0.1,
# 500 rounds. Expects cifar-10-batches-py/ (or data.npz) under DATA_DIR.
set -euo pipefail

DATA_DIR=${1:-./data}
DENSITY=${2:-0.5}

python -m neuroimagedisttraining_tpu \
    --algorithm salientgrads --dataset cifar10 --data_dir "$DATA_DIR" \
    --model resnet18 --partition_method dir --partition_alpha 0.3 \
    --client_num_in_total 100 --frac 0.1 --comm_round 500 \
    --batch_size 16 --epochs 2 --lr 0.01 --dense_ratio "$DENSITY" \
    --tag "cifar_d${DENSITY}"
