#!/usr/bin/env bash
# Training-health exemplar (ISSUE 15): the seeded divergence scenario
# and its clean twin, end to end through the shipped CLI.
#
#   1. clean twin: tiny synthetic fedavg run with the in-dispatch
#      health stats leg armed (--health_stats), the per-round metrics
#      JSONL sink (--metrics_out) and the health gate — must exit 0
#      with zero alerts;
#   2. divergence run: identical config plus a 1-of-4 sign-flip
#      Byzantine silo (--fault_spec byz:1@R:sign_flip) — the
#      client-divergence rule must fire (nidt_alert sample, flight
#      `alert` event, degraded worst status) and --health_gate must
#      exit NONZERO;
#   3. seeded actions-replay twins (ISSUE 20): the same chaos scenario
#      twice under --actions on — the reflex dispatches (quarantine,
#      defense escalation) must be BYTE-identical across the twins,
#      the replay-determinism contract of the timestamp-free action
#      log;
#   4. analysis/run_report.py joins each run's metrics JSONL + health
#      verdict into run_report.json/md; the two reports must visibly
#      differ in the alert timeline;
#   5. the combined exemplar lands in bench_matrix/health_report.json,
#      regression-gated by analysis/bench_gate.py (the health_report
#      SPEC) like every other committed artifact.
#
# Tiny and CPU-safe (the tier-1 test suite pins the same scenario as a
# pytest cell); this script is the push-button artifact regenerator.
set -uo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
PY=${PYTHON:-python}
OUT_DIR=${1:-bench_matrix}
WORK=$(mktemp -d /tmp/nidt_health.XXXXXX)
trap 'rm -rf "$WORK"' EXIT

# the manifest both runs load must be metric-closed against obs/names.py
# BEFORE burning any training time (the --project metric-closure pass,
# applied to manifests; ISSUE 16)
RULES_MANIFEST=scripts/health_rules.example.json
echo "== validate $RULES_MANIFEST (metric-name closure) =="
$PY -m neuroimagedisttraining_tpu.analysis \
    --check-manifest "$RULES_MANIFEST" || exit 1

# 64 subjects: enough shared signal that honest site updates COHERE
# (clean leave-one-out cosines ~ +0.2..+0.4); at 24 subjects the tiny
# task saturates instantly and honest non-IID pulls genuinely oppose
# each other, which is divergence the rule would rightly flag
COMMON=(--algorithm fedavg --dataset synthetic --model 3dcnn_tiny
        --synthetic_num_subjects 64 --synthetic_shape 12 14 12
        --client_num_in_total 4 --comm_round 3 --batch_size 8
        --epochs 1 --lr 1e-3 --seed 1024 --log_dir "$WORK/LOG"
        --health_stats --health_gate
        # manifest rules ride along with the builtins; its thresholds
        # sit far above anything these tiny runs reach, so the clean
        # twin's zero-alert contract is unchanged
        --health_rules "$RULES_MANIFEST")

echo "== clean twin =="
$PY -m neuroimagedisttraining_tpu "${COMMON[@]}" --tag health_clean \
    --metrics_out "$WORK/clean.metrics.jsonl"
rc_clean=$?
if [ $rc_clean -ne 0 ]; then
    echo "FAIL: clean twin exited $rc_clean (expected 0: a healthy run"\
         "must pass its gate)" >&2
    exit 1
fi

echo "== 1-of-4 sign-flip divergence run =="
$PY -m neuroimagedisttraining_tpu "${COMMON[@]}" --tag health_byz \
    --metrics_out "$WORK/byz.metrics.jsonl" \
    --fault_spec "byz:1@0:sign_flip,byz:1@1:sign_flip,byz:1@2:sign_flip"
rc_byz=$?
if [ $rc_byz -eq 0 ]; then
    echo "FAIL: sign-flip run exited 0 (expected nonzero: the" \
         "client-divergence rule must fire and fail the gate)" >&2
    exit 1
fi

echo "== seeded actions-replay twins (reflex plane, ISSUE 20) =="
# two IDENTICAL seeded chaos runs under --actions on: the reflex
# dispatches (quarantine + escalation, rule provenance on each) must
# come out BYTE-IDENTICAL — the action log is deliberately
# timestamp-free so seeded chaos replays deterministically
for twin in twin_a twin_b; do
    $PY -m neuroimagedisttraining_tpu "${COMMON[@]}" --tag "act_$twin" \
        --comm_round 2 --epochs 2 --lr 3e-3 --actions on \
        --defense none --metrics_out "$WORK/$twin.metrics.jsonl" \
        --fault_spec "byz:1@0:sign_flip,byz:1@1:sign_flip" \
        > "$WORK/$twin.log" 2>&1
    rc_twin=$?
    # the gate exits nonzero BY DESIGN here (the divergence rules fire
    # before the reflex contains them); the verdict must still land
    if ! ls "$WORK"/LOG/synthetic/*act_$twin*.health.json >/dev/null; then
        echo "FAIL: actions twin $twin left no verdict (rc=$rc_twin)" >&2
        tail -20 "$WORK/$twin.log" >&2
        exit 1
    fi
done
$PY - "$WORK" <<'EOF'
import glob, json, sys
blocks = []
for twin in ("act_twin_a", "act_twin_b"):
    (vp,) = glob.glob(sys.argv[1] + f"/LOG/synthetic/*{twin}*.health.json")
    blocks.append(json.load(open(vp))["actions"])
a, b = blocks
assert a["mode"] == "on", a
applied = {e["action"] for e in a["log"] if e["status"] == "applied"}
assert {"quarantine_silo", "escalate_defense"} <= applied, a["log"]
assert all(e["rule"] for e in a["log"]), a["log"]
ja = json.dumps(a, sort_keys=True)
jb = json.dumps(b, sort_keys=True)
assert ja == jb, ("seeded actions replay diverged:\n"
                  f"A: {ja}\nB: {jb}")
print(f"OK(actions-replay): {len(a['log'])} dispatches byte-identical "
      f"across twins; applied={sorted(applied)}")
EOF
[ $? -ne 0 ] && exit 1

clean_verdict=$(ls "$WORK"/LOG/synthetic/*health_clean*.health.json)
byz_verdict=$(ls "$WORK"/LOG/synthetic/*health_byz*.health.json)

echo "== run_report on both runs =="
$PY -m neuroimagedisttraining_tpu.analysis.run_report \
    --metrics "$WORK/clean.metrics.jsonl" --verdict "$clean_verdict" \
    --out "$WORK/report_clean" || exit 1
$PY -m neuroimagedisttraining_tpu.analysis.run_report \
    --metrics "$WORK/byz.metrics.jsonl" --verdict "$byz_verdict" \
    --out "$WORK/report_byz" || exit 1

echo "== combined exemplar -> $OUT_DIR/health_report.json =="
$PY - "$WORK" "$OUT_DIR" <<'EOF'
import json, os, sys

work, out_dir = sys.argv[1], sys.argv[2]
clean = json.load(open(os.path.join(work, "report_clean",
                                    "run_report.json")))
byz = json.load(open(os.path.join(work, "report_byz",
                                  "run_report.json")))
contrast = {
    "clean_worst": clean["summary"]["worst_status"],
    "byz_worst": byz["summary"]["worst_status"],
    "clean_alerts": clean["summary"]["alerts_total"],
    "byz_alerts": byz["summary"]["alerts_total"],
    "byz_rules_fired": sorted({e["rule"] for e in byz["alerts"]
                               if e["kind"] == "alert"}),
    # the acceptance criterion verbatim: both artifacts gate-pass,
    # and the alert timelines visibly differ
    "timelines_differ": clean["alerts"] != byz["alerts"]
                        and byz["summary"]["alerts_total"] > 0
                        and clean["summary"]["alerts_total"] == 0,
}
assert contrast["timelines_differ"], contrast
assert "client-divergence" in contrast["byz_rules_fired"], contrast
doc = {"note": ("seeded sign-flip divergence exemplar vs its clean "
                "twin (scripts/run_health_report.sh); gated by "
                "analysis/bench_gate.py health_report SPEC"),
       "clean": clean, "byz": byz, "contrast": contrast}
os.makedirs(out_dir, exist_ok=True)
path = os.path.join(out_dir, "health_report.json")
with open(path, "w") as f:
    json.dump(doc, f, indent=1, sort_keys=True)
print("wrote", path)
print(json.dumps(contrast, indent=1))
EOF
rc=$?
[ $rc -ne 0 ] && exit $rc

echo "== bench gate (health_report cell) =="
$PY -m neuroimagedisttraining_tpu.analysis.bench_gate \
    --artifact health_report.json --quiet
