#!/usr/bin/env bash
# Density sweep 0.05-0.5 (the reference's salientgradssparsity* job family,
# Jobs/salientgradssparsitywith100iteration70sps.sh) with IterSNIP 100.
set -euo pipefail

H5=${1:?usage: run_abcd_density_sweep.sh /path/to/abcd.h5}

for d in 0.05 0.1 0.2 0.3 0.5; do
    python -m neuroimagedisttraining_tpu \
        --algorithm salientgrads --dataset abcd_h5 --data_dir "$H5" \
        --model 3DCNN --num_classes 1 --client_num_in_total 21 \
        --comm_round 200 --batch_size 16 --dense_ratio "$d" \
        --itersnip_iteration 100 --tag "sweep_d${d}"
done
