#!/usr/bin/env bash
# Fast CPU smoke of every algorithm engine on synthetic data (the CI-mode
# role of the reference's --ci flag, sailentgrads_api.py:260-265).
set -euo pipefail

COMMON="--dataset synthetic --model 3dcnn_tiny --synthetic_num_subjects 32 \
  --synthetic_shape 12 14 12 --client_num_in_total 4 --comm_round 2 \
  --batch_size 4 --epochs 1 --lr 5e-4 --virtual_devices 8 --log_dir /tmp/nidt_smoke"

for algo in fedavg salientgrads dispfl subavg dpsgd ditto local turboaggregate; do
    echo "=== $algo ==="
    python -m neuroimagedisttraining_tpu --algorithm "$algo" $COMMON
done
# fedfomo needs a validation split
echo "=== fedfomo ==="
python -m neuroimagedisttraining_tpu --algorithm fedfomo --val_fraction 0.2 $COMMON
