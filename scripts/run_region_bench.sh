#!/usr/bin/env bash
# Hierarchical aggregation tier bench (ISSUE 18, asyncfl/region.py):
# a 2-region x 2-worker-per-region tree under the committed
# ingest_bench load (1k open-loop clients, the SAME cohort / buffer /
# canned-update configuration as bench_matrix/ingest_bench.json), plus
# the downlink delta-sync A/B.
#
# Four cells:
#   tree_shm       2x2 tree, shared-memory partial hand-off (headline)
#   tree_pipe      same tree, pickled-pipe hand-off (transport A/B)
#   downlink_delta small-local-update fleet, delta-sync replies ON
#   downlink_dense same fleet, dense replies (downlink-bytes A/B)
#
# Acceptance (judged by the bench itself into summary.* booleans, then
# re-judged by the gate): the tree sustains >= the committed
# single-root best (ingest_bench ingest_w*); shm beats pipe on mean
# per-export latency; delta replies carry >=3x fewer bytes per
# changed-version sync than dense with ZERO base-mismatch errors; every
# cell's received/accepted accounting audits exactly through the tier.
#
# The downlink cells run the small-local-update regime
# (--upload_local_scale, clients upload synced_params + eps*canned):
# the throughput cells' replacement aggregation makes consecutive
# versions statistically independent — incompressible by construction —
# while real FL rounds move the model a small step, which is the regime
# delta-sync exists for.
#
# Writes bench_matrix/region_bench.json (committed artifact), then
# gates it against the committed copy.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
PY=${PYTHON:-python}
OUT=${1:-bench_matrix/region_bench.json}

$PY -m neuroimagedisttraining_tpu.asyncfl.loadgen \
    --mode region_bench \
    --clients "${BENCH_CLIENTS:-1000}" \
    --aggregations "${BENCH_AGGREGATIONS:-300}" \
    --buffer_k "${BENCH_BUFFER_K:-50}" \
    --leaf_elems "${BENCH_LEAF_ELEMS:-256}" \
    --regions "${BENCH_REGIONS:-2}" \
    --ingest_workers "${BENCH_WORKERS_PER_REGION:-2}" \
    --downlink_clients "${BENCH_DOWNLINK_CLIENTS:-600}" \
    --downlink_aggregations "${BENCH_DOWNLINK_AGGREGATIONS:-80}" \
    --downlink_leaf_elems "${BENCH_DOWNLINK_LEAF_ELEMS:-4096}" \
    --out "$OUT"

$PY - "$OUT" <<'EOF'
import json, sys
res = json.load(open(sys.argv[1]))
s = res["summary"]
assert s["audits_green"], "region bench: an accounting audit came back red"
print(f"tree ({s['regions']}x{s['workers_per_region']}): "
      f"{s['tree_uploads_per_s_sustained']} uploads/s sustained "
      f"(committed single-root best: {s['committed_single_root_uploads_per_s']})")
print(f"  shm export: {s['shm_export_us_mean']}us mean  "
      f"pipe export: {s['pipe_export_us_mean']}us mean  "
      f"(shm fallback-to-pipe: {s['shm_fallback_busy']})")
print(f"downlink: {s['sync_body_bytes_per_changed_sync_delta']} B/sync delta vs "
      f"{s['sync_body_bytes_per_changed_sync_dense']} B/sync dense "
      f"({s['delta_sync_bytes_ratio']}x; {s['delta_syncs']} deltas decoded, "
      f"{s['delta_errors']} errors, {s['sync_dense_fallback_ring']} ring fallbacks)")
bad = [k for k in ("tree_at_least_committed_single_root", "shm_beats_pipe",
                   "delta_sync_3x") if not s[k]]
if bad or s["delta_errors"]:
    print(f"WARNING: region bench acceptance red: {bad or ''} "
          f"delta_errors={s['delta_errors']}")
    sys.exit(1)
print("OK: tree >= committed single-root, shm beats pipe, delta-sync >= 3x, "
      "audits green")
EOF

$PY -m neuroimagedisttraining_tpu.analysis.bench_gate \
    --fresh "$(dirname "$OUT")" --artifact region_bench.json --quiet
