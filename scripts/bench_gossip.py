"""Gossip-consensus bench: sparse lowerings vs the dense all-gather einsum.

Two cells (GOSSIP_MODE env):
- "ring" (default): circulant ring/k-lattice mixing lowers to
  collective-permutes of |k|-row slices — per-device traffic
  O(k_max x model) instead of the einsum's O(C x model) stack.
- "random": the reference's per-round k-regular random adjacency
  (DisPFL's forced default, dispfl_api.py:200) lowers to a routed,
  capped lax.all_to_all with traced routing tables
  (parallel/gossip.py::sparse_plan) — per-device traffic
  O(D x m x model), m < B rows per pair, one compiled program per size
  bucket across rounds of changing topologies.

Each cell pins wall time for both paths, the HLO collective ops each
lowers to, and the analytic per-device receive volume on the 8-device
mesh.

Multi-device collectives need >= 2 devices and the harness exposes ONE
real TPU chip, so this cell self-provisions the 8-virtual-CPU-device mesh
(same substrate as tests/ and dryrun_multichip) — the LOWERING and
traffic claims are device-count facts, not chip-speed facts; wall times
here are CPU-mesh times and marked as such.

Env: GOSSIP_MODE (ring), GOSSIP_CLIENTS (16; 40 for random),
GOSSIP_NEIGHBORS (2, random mode), GOSSIP_PARAMS (4_000_000 floats),
BENCH_REPS (5). Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from neuroimagedisttraining_tpu.parallel.mesh import (  # noqa: E402
    provision_virtual_devices,
)

provision_virtual_devices(8)


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from neuroimagedisttraining_tpu.parallel.gossip import (
        circulant_plan, gossip_apply, gossip_apply_sparse, plan_fits_mesh,
        sparse_plan,
    )
    from neuroimagedisttraining_tpu.parallel.mesh import (
        client_sharding, make_mesh,
    )
    from neuroimagedisttraining_tpu.parallel.topology import (
        ring_mixing_matrix,
    )

    mode = os.environ.get("GOSSIP_MODE", "ring")
    C = int(os.environ.get("GOSSIP_CLIENTS", 40 if mode == "random" else 16))
    # rounded down to the 128-lane layout so the timed array, the label,
    # and the traffic figures all describe the same element count
    n_params = int(os.environ.get("GOSSIP_PARAMS", 4_000_000)) // 128 * 128
    reps = int(os.environ.get("BENCH_REPS", 5))
    mesh = make_mesh()
    D = mesh.devices.size

    if mode == "random":
        k = int(os.environ.get("GOSSIP_NEIGHBORS", 2))
        rng = np.random.default_rng(1)
        M = np.zeros((C, C), np.float32)
        for c in range(C):
            nei = rng.choice([j for j in range(C) if j != c], k,
                             replace=False)
            sel = np.append(nei, c)
            M[c, sel] = 1.0 / len(sel)
        out = sparse_plan(M, mesh, C)
        assert out is not None, (
            f"no sparse plan for C={C}, k={k} on the {D}-device mesh "
            "(C must tile the mesh and the padded per-pair cap must stay "
            "below a full block) — pick a sparser GOSSIP_NEIGHBORS / "
            "larger GOSSIP_CLIENTS")
        spec, arrays = out
    else:
        M = ring_mixing_matrix(C)
        plan = circulant_plan(M)
        assert plan_fits_mesh(plan, mesh, C), (C, D)

    x = jax.device_put(
        np.random.default_rng(0).normal(size=(C, n_params // 128, 128))
        .astype(np.float32), client_sharding(mesh))
    tree = {"w": x}
    Md = jnp.asarray(M)

    if mode == "random":
        arrays_d = jax.device_put(arrays)
        pp = jax.jit(lambda t: gossip_apply_sparse(t, spec, arrays_d, mesh))
    else:
        pp = jax.jit(lambda t: gossip_apply(t, plan, mesh))
    ein = jax.jit(lambda t: jax.tree.map(
        lambda v: jnp.einsum("cj,j...->c...", Md, v), t))

    got = pp(tree)
    want = ein(tree)
    np.testing.assert_allclose(np.asarray(got["w"]), np.asarray(want["w"]),
                               rtol=1e-5, atol=1e-6)

    hlo_pp = pp.lower(tree).compile().as_text()
    hlo_ein = ein.lower(tree).compile().as_text()

    def bestof(fn):
        fn(tree)["w"].block_until_ready()
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn(tree)["w"].block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best

    t_pp, t_ein = bestof(pp), bestof(ein)

    bytes_per_row = 4 * n_params
    # analytic per-device RECEIVE volume per consensus
    if mode == "random":
        # all_to_all: D-1 remote slots of m padded rows each
        pp_rx = (D - 1) * spec.m * bytes_per_row
    else:
        offs = [abs(k) for k, _ in plan if k != 0]
        pp_rx = sum(offs) * bytes_per_row
    ein_rx = (C - C // D) * bytes_per_row  # the all-gathered remote stack

    if mode == "random":
        label = (f"routed all_to_all path (m={spec.m}/B={spec.B} padded "
                 f"rows per pair, {int(os.environ.get('GOSSIP_NEIGHBORS', 2))} "
                 "random neighbors/client)")
    else:
        label = "ppermute path"
    print(json.dumps({
        "metric": f"gossip_consensus_{mode}",
        "value": round(t_pp * 1e3, 2),
        "unit": f"ms/consensus ({label}, C={C} clients x "
                f"{n_params / 1e6:.1f}M params, {D}-device VIRTUAL CPU "
                "mesh — lowering/traffic cell, not a chip-speed cell)",
        "einsum_ms": round(t_ein * 1e3, 2),
        "speedup_vs_einsum": round(t_ein / t_pp, 2),
        ("sparse_rx_mb_per_device" if mode == "random"
         else "ppermute_rx_mb_per_device"): round(pp_rx / 1e6, 2),
        "einsum_rx_mb_per_device": round(ein_rx / 1e6, 2),
        "traffic_ratio": round(ein_rx / pp_rx, 1),
        "sparse_hlo" if mode == "random" else "ppermute_hlo": {
            "collective-permute": hlo_pp.count("collective-permute"),
            "all-gather": hlo_pp.count("all-gather"),
            "all-to-all": hlo_pp.count("all-to-all")},
        "einsum_hlo": {
            "collective-permute": hlo_ein.count("collective-permute"),
            "all-gather": hlo_ein.count("all-gather"),
            "all-to-all": hlo_ein.count("all-to-all")},
        "timing": f"best of {reps}",
    }))


if __name__ == "__main__":
    main()
