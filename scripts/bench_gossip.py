"""Gossip-consensus bench: ppermute ring vs dense all-to-all einsum.

The claim (parallel/gossip.py): for circulant ring/k-lattice mixing
matrices, consensus lowers to collective-permutes of |k|-row slices, so
per-device traffic is O(k_max x model) instead of the einsum's O(C x
model) stack materialization. This bench pins that on the 8-device mesh:
wall time for both paths, the HLO collective ops each lowers to, and the
analytic per-device receive volume.

Multi-device collectives need >= 2 devices and the harness exposes ONE
real TPU chip, so this cell self-provisions the 8-virtual-CPU-device mesh
(same substrate as tests/ and dryrun_multichip) — the LOWERING and
traffic claims are device-count facts, not chip-speed facts; wall times
here are CPU-mesh times and marked as such.

Env: GOSSIP_CLIENTS (16), GOSSIP_PARAMS (4_000_000 floats), BENCH_REPS (5).
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from neuroimagedisttraining_tpu.parallel.mesh import (  # noqa: E402
    provision_virtual_devices,
)

provision_virtual_devices(8)


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from neuroimagedisttraining_tpu.parallel.gossip import (
        circulant_plan, gossip_apply, plan_fits_mesh,
    )
    from neuroimagedisttraining_tpu.parallel.mesh import (
        client_sharding, make_mesh,
    )
    from neuroimagedisttraining_tpu.parallel.topology import (
        ring_mixing_matrix,
    )

    C = int(os.environ.get("GOSSIP_CLIENTS", 16))
    # rounded down to the 128-lane layout so the timed array, the label,
    # and the traffic figures all describe the same element count
    n_params = int(os.environ.get("GOSSIP_PARAMS", 4_000_000)) // 128 * 128
    reps = int(os.environ.get("BENCH_REPS", 5))
    mesh = make_mesh()
    D = mesh.devices.size

    M = ring_mixing_matrix(C)
    plan = circulant_plan(M)
    assert plan_fits_mesh(plan, mesh, C), (C, D)

    x = jax.device_put(
        np.random.default_rng(0).normal(size=(C, n_params // 128, 128))
        .astype(np.float32), client_sharding(mesh))
    tree = {"w": x}
    Md = jnp.asarray(M)

    pp = jax.jit(lambda t: gossip_apply(t, plan, mesh))
    ein = jax.jit(lambda t: jax.tree.map(
        lambda v: jnp.einsum("cj,j...->c...", Md, v), t))

    got = pp(tree)
    want = ein(tree)
    np.testing.assert_allclose(np.asarray(got["w"]), np.asarray(want["w"]),
                               rtol=1e-5, atol=1e-6)

    hlo_pp = pp.lower(tree).compile().as_text()
    hlo_ein = ein.lower(tree).compile().as_text()

    def bestof(fn):
        fn(tree)["w"].block_until_ready()
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn(tree)["w"].block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best

    t_pp, t_ein = bestof(pp), bestof(ein)

    bytes_per_row = 4 * n_params
    # analytic per-device RECEIVE volume per consensus
    offs = [abs(k) for k, _ in plan if k != 0]
    pp_rx = sum(offs) * bytes_per_row
    ein_rx = (C - C // D) * bytes_per_row  # the all-gathered remote stack

    print(json.dumps({
        "metric": "gossip_consensus_ring",
        "value": round(t_pp * 1e3, 2),
        "unit": f"ms/consensus (ppermute path, C={C} clients x "
                f"{n_params / 1e6:.1f}M params, {D}-device VIRTUAL CPU "
                "mesh — lowering/traffic cell, not a chip-speed cell)",
        "einsum_ms": round(t_ein * 1e3, 2),
        "speedup_vs_einsum": round(t_ein / t_pp, 2),
        "ppermute_rx_mb_per_device": round(pp_rx / 1e6, 2),
        "einsum_rx_mb_per_device": round(ein_rx / 1e6, 2),
        "traffic_ratio": round(ein_rx / pp_rx, 1),
        "ppermute_hlo": {
            "collective-permute": hlo_pp.count("collective-permute"),
            "all-gather": hlo_pp.count("all-gather"),
            "all-to-all": hlo_pp.count("all-to-all")},
        "einsum_hlo": {
            "collective-permute": hlo_ein.count("collective-permute"),
            "all-gather": hlo_ein.count("all-gather"),
            "all-to-all": hlo_ein.count("all-to-all")},
        "timing": f"best of {reps}",
    }))


if __name__ == "__main__":
    main()
