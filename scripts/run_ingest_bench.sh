#!/usr/bin/env bash
# Sharded ingest plane headline bench (ISSUE 12, asyncfl/ingest.py):
# the committed single-process selector baseline (BufferedFedAvgServer,
# the async_bench cell's server) vs the sharded plane at N in {1, 2, 4}
# SO_REUSEPORT worker processes, SAME cohort / buffer / canned-update
# configuration. Metric: sustained accepted uploads/s over the accept
# window (fleet start -> last aggregation; the teardown tail measures
# shutdown, not ingest). Acceptance: >= 3x at N=4 with every
# received==accepted+dropped / accepted==aggregated+buffered audit
# green across processes.
#
# Writes bench_matrix/ingest_bench.json (committed artifact).
#
# BENCH_AGGREGATIONS defaults high (300) on purpose: the metric is
# SUSTAINED throughput, and the accept window opens at fleet start — a
# short cell is dominated by the 1k-client connection ramp (~2 s at the
# ~500 connects/s stagger), not by steady-state ingest.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
PY=${PYTHON:-python}
OUT=${1:-bench_matrix/ingest_bench.json}

$PY -m neuroimagedisttraining_tpu.asyncfl.loadgen \
    --mode ingest_bench \
    --clients "${BENCH_CLIENTS:-1000}" \
    --aggregations "${BENCH_AGGREGATIONS:-300}" \
    --buffer_k "${BENCH_BUFFER_K:-50}" \
    --leaf_elems "${BENCH_LEAF_ELEMS:-256}" \
    --out "$OUT"

$PY - "$OUT" <<'EOF'
import json, sys
res = json.load(open(sys.argv[1]))
s = res["summary"]
assert s["audits_green"], "ingest bench: an accounting audit came back red"
print(f"baseline (1-process selector, in-run): {s['baseline_uploads_per_s']} uploads/s sustained")
print(f"baseline (committed, async_bench.json): {s['committed_baseline_uploads_per_s']} uploads/s")
for n in (1, 2, 4):
    print(f"  ingest x{n} workers: {res[f'ingest_w{n}']['uploads_per_s_sustained']} uploads/s "
          f"({s[f'speedup_w{n}']}x in-run, {s[f'speedup_w{n}_vs_committed']}x vs committed)")
# the ISSUE's yardstick: >=3x sustained uploads/s at 4 workers vs the
# COMMITTED single-process selector baseline (~256/s, PR 7). The in-run
# ratio is reported too but is a moving target: the baseline cell
# already rides this PR's selector-core syscall optimizations.
target = s["speedup_w4_vs_committed"]
if target is None or target < 3.0:
    print(f"WARNING: speedup at 4 workers {target}x vs committed < 3x target")
    sys.exit(1)
if s["speedup_w4"] < 3.0:
    print(f"note: in-run ratio {s['speedup_w4']}x < 3x — the baseline cell shares "
          "this PR's selector optimizations; see summary.notes for the box ceiling")
print(f"OK: {target}x at 4 workers vs the committed baseline (>= 3x), all audits green")
EOF
