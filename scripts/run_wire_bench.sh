#!/usr/bin/env bash
# Wire-codec A/B over the REAL socket transport (ISSUE 3 acceptance):
# four 2-silo federations through distributed/run.py — dense vs encoded,
# unmasked (FedAvg shape) and masked (the SalientGrads deployment shape:
# every rank derives the same seeded pruning mask at the flagship's
# default density 0.5, silos train masked, the codec packs uploads
# bitmap-free via the mask handoff). The server's byte counters
# (distributed/comm.py byte_stats) give true bytes-on-wire; the summary
# asserts
#   - masked sparse+quant  >= 10x fewer server-received bytes,
#   - fedavg delta+quant   >=  3x,
#   - final_param_norm parity between each encoded run and its dense
#     twin (same seeds => same trajectories up to quantization error),
# and writes the artifact to bench_matrix/wire_bench.json.
#
# The model defaults to 3dcnn_tiny on 56x64x56 volumes (1.0 M params,
# kernel fraction 0.9999 — the same conv-kernel-dominated tree shape as
# the flagship 2.6 M-param AlexNet3D, whose CPU step time is too slow
# for CI): bytes ratios are param-tree properties, not input-size
# properties, and the flagship model measured 10.3x masked / 5.0x
# delta+quant on the same real-trained deltas (WIRE_BENCH_MODEL=3DCNN
# WIRE_BENCH_SHAPE="72 88 72" reproduces it off-CI).
set -uo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
PY=${PYTHON:-python}
ROUNDS=${WIRE_BENCH_ROUNDS:-3}
CLIENTS=2
MODEL=${WIRE_BENCH_MODEL:-3dcnn_tiny}
SHAPE=${WIRE_BENCH_SHAPE:-"56 64 56"}
OUT=bench_matrix/wire_bench.json
mkdir -p bench_matrix /tmp/wire_bench

run_one() {
    local tag=$1; shift
    local port
    port=$($PY -c "from neuroimagedisttraining_tpu.distributed.ports \
import free_port_block; print(free_port_block(8))")
    # shellcheck disable=SC2086 — SHAPE expands to three ints
    local common=(--num_clients "$CLIENTS" --comm_round "$ROUNDS"
                  --model "$MODEL" --dataset synthetic
                  --synthetic_num_subjects 24
                  --synthetic_shape $SHAPE --batch_size 4
                  --base_port "$port" --force_cpu --seed 7 "$@")
    echo "== wire bench [$tag] (port $port): $* =="
    local out="/tmp/wire_bench/${tag}.log"
    $PY -m neuroimagedisttraining_tpu.distributed.run \
        --role server "${common[@]}" > "$out" 2>&1 &
    local server_pid=$!
    local pids=()
    for r in $(seq 1 "$CLIENTS"); do
        $PY -m neuroimagedisttraining_tpu.distributed.run \
            --role client --rank "$r" "${common[@]}" \
            > "/tmp/wire_bench/${tag}_c${r}.log" 2>&1 &
        pids+=($!)
    done
    if ! wait "$server_pid"; then
        echo "FAIL($tag): server exited non-zero"; tail -20 "$out"; return 1
    fi
    for p in "${pids[@]}"; do wait "$p" 2>/dev/null || true; done
    grep -a -o '^{.*}' "$out" | tail -1 > "/tmp/wire_bench/${tag}.json"
    cat "/tmp/wire_bench/${tag}.json"
}

rc=0
run_one dense_fedavg                                          || rc=1
run_one codec_fedavg  --wire_codec delta+quant                || rc=1
run_one dense_masked  --wire_mask_density 0.5                 || rc=1
run_one codec_masked  --wire_mask_density 0.5 \
                      --wire_codec delta+sparse+quant         || rc=1
[ $rc -ne 0 ] && exit $rc

$PY - "$OUT" "$ROUNDS" <<'EOF'
import json, sys

out_path, rounds = sys.argv[1], int(sys.argv[2])
runs = {t: json.load(open(f"/tmp/wire_bench/{t}.json"))
        for t in ("dense_fedavg", "codec_fedavg",
                  "dense_masked", "codec_masked")}
summary = {"rounds": rounds, "runs": runs}
for enc, den, floor, key in (
        ("codec_fedavg", "dense_fedavg", 3.0, "fedavg_delta_quant"),
        ("codec_masked", "dense_masked", 10.0, "masked_sparse_quant")):
    ratio = runs[den]["bytes_recv"] / max(runs[enc]["bytes_recv"], 1)
    a, b = runs[enc]["final_param_norm"], runs[den]["final_param_norm"]
    parity = abs(a - b) / max(abs(b), 1e-9)
    summary[key] = {
        "bytes_recv_dense": runs[den]["bytes_recv"],
        "bytes_recv_encoded": runs[enc]["bytes_recv"],
        "bytes_reduction_x": round(ratio, 2),
        "target_x": floor,
        "param_norm_rel_err": round(parity, 6),
        "pass": bool(ratio >= floor and parity < 2e-2),
    }
    print(f"{key}: {ratio:.2f}x reduction (target >= {floor}x), "
          f"param-norm rel err {parity:.2e} -> "
          f"{'PASS' if summary[key]['pass'] else 'FAIL'}")
json.dump(summary, open(out_path, "w"), indent=1, sort_keys=True)
print(f"artifact -> {out_path}")
sys.exit(0 if all(summary[k]["pass"] for k in
                  ("fedavg_delta_quant", "masked_sparse_quant")) else 1)
EOF
