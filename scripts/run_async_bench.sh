#!/usr/bin/env bash
# Async control-plane load benchmark (ISSUE 7): drive 1,000 concurrent
# simulated clients (asyncio fleet, canned update pytrees, seeded
# crash/rejoin churn) against ONE server process and A/B the buffered
# asynchronous control plane (asyncfl/BufferedFedAvgServer) against the
# round-synchronous baseline (FedAvgServer) on the SAME selector comm
# core — the comparison isolates the control-plane discipline, not the
# socket implementation.
#
# Emits bench_matrix/async_bench.json with, per mode: sustained
# uploads/s (accepted), aggregations/s, p50/p99 version-advance latency,
# peak concurrent connections, byte/frame counters, and the accounting
# audits (zero lost / double-counted uploads). The script FAILS unless
# both modes reconcile their frame accounting and the async cell
# actually held >= the requested client count concurrently.
set -uo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
PY=${PYTHON:-python}
CLIENTS=${CLIENTS:-1000}
AGGREGATIONS=${AGGREGATIONS:-40}
BUFFER_K=${BUFFER_K:-100}
# deterministic churn keyed at rounds BOTH modes actually reach (the
# sync baseline runs aggregations*buffer_k/clients rounds): two
# crash/rejoin cycles plus one permanent corpse — the sync barrier pays
# its deadline for them, the async buffer just keeps aggregating
FAULTS="crash:7@1,rejoin:7@3,crash:13@2,crash:21@1,rejoin:21@2"
OUT=bench_matrix/async_bench.json

$PY -m neuroimagedisttraining_tpu.asyncfl.loadgen \
    --clients "$CLIENTS" --mode both \
    --aggregations "$AGGREGATIONS" --buffer_k "$BUFFER_K" \
    --max_staleness 50 --staleness_alpha 0.5 \
    --fault_spec "$FAULTS" --seed 7 \
    --out "$OUT" || exit 1

$PY - "$OUT" "$CLIENTS" <<'EOF'
import json, sys
res = json.load(open(sys.argv[1]))
want = int(sys.argv[2])
for mode in ("async", "sync"):
    cell = res[mode]
    assert cell["frames_reconciled"], (mode, cell)
    assert cell["upload_audit"]["received_accounted"], (mode, cell)
    assert cell["upload_audit"]["accepted_accounted"], (mode, cell)
    assert cell["peak_connections"] >= want, (mode, cell)
a, s = res["async"], res["sync"]
print(f"OK: {want} concurrent clients held on one server process")
print(f"  async: {a['uploads_per_s']} uploads/s, "
      f"{a['aggregations_per_s']} agg/s, "
      f"p99 advance {a['version_advance_p99_ms']} ms")
print(f"  sync : {s['uploads_per_s']} uploads/s, "
      f"{s['aggregations_per_s']} rounds/s, "
      f"p99 advance {s['version_advance_p99_ms']} ms")
print(f"  summary: {res['summary']}")
EOF
