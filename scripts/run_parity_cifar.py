"""End-to-end test-metric parity: this framework vs a minimal torch
reference loop (BASELINE.json "test-metric parity" clause; VERDICT r2
next-step #3).

Both sides train federated averaging on the SAME CIFAR-shaped cohort with
the SAME n_cls partition, the SAME initial weights (converted from the flax
init), and the same optimizer semantics (SGD momentum 0.9, wd 5e-4, global
grad-norm clip 10, per-round lr decay — my_model_trainer.py:209,224-225):

- framework side: the shipped FedAvgEngine round program (one jitted SPMD
  program per round);
- torch side: an independent reimplementation of the reference's round loop
  semantics (fedavg_api.py:40-117: sample -> per-client local epochs from
  the global model -> sample-count-weighted average), written against
  torch.nn like the reference's trainers. It is NOT a copy of the reference
  (no HDF5, no CUDA, argparse-free); file:line citations mark which
  semantics each block mirrors.

Both sides walk a fresh per-epoch shuffle of each client shard in
batch-size strides (reference DataLoader semantics, my_model_trainer.py:213
— the framework's default batch_order="shuffle" since round 4; the exact
scan-vs-torch step parity given one permutation is pinned by
tests/test_torch_parity.py::test_local_train_shuffle_matches_torch_epoch_walk).
The two runs draw different permutations (independent RNG streams), so the
comparison is statistical: same semantics, same expected curve, small
tolerance on the converged level.

CIFAR-10 itself cannot be downloaded in this environment (zero egress), so
the cohort is the package's class-separable synthetic CIFAR-shaped dataset
(data/vision.py synthetic_vision_cohort) — the comparison exercises the
full public CIFAR code path (same loaders, partitioners, model) with both
frameworks consuming identical arrays.

Usage:  python scripts/run_parity_cifar.py [--rounds 25] [--out PARITY]
Emits:  PARITY.json (curves + verdict) and prints a summary table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the parity claim is about f32 math, so pin JAX to the CPU backend before
# any backend touch (the axon TPU plugin ignores JAX_PLATFORMS env; TPU
# matmuls default to bf16-reduced precision, which is exactly the class of
# difference this experiment must NOT contain)
from neuroimagedisttraining_tpu.parallel.mesh import provision_virtual_devices  # noqa: E402

provision_virtual_devices(1)

# ---------------------------------------------------------------- config

DEF = dict(
    num_train=2000, num_test=500, hw=32, data_seed=3,
    clients=10, alpha=2, partition="n_cls", seed=1024,
    lr=0.01, lr_decay=0.998, wd=5e-4, momentum=0.9,
    batch_size=32, epochs=1, rounds=40,  # protocol round cap
    tolerance=0.05,   # |final mean-over-clients acc delta| bound
)


def build_cohort(p):
    from neuroimagedisttraining_tpu.data import partition as P
    from neuroimagedisttraining_tpu.data.vision import (
        proportional_test_split, synthetic_vision_cohort, vision_partition,
    )

    Xtr, ytr, Xte, yte = synthetic_vision_cohort(
        num_train=p["num_train"], num_test=p["num_test"], hw=p["hw"],
        seed=p["data_seed"])
    train_map = vision_partition(ytr, p["clients"], p["alpha"],
                                 p["partition"], seed=p["seed"],
                                 num_classes=10)
    stats = P.record_data_stats(ytr, train_map)
    test_map = proportional_test_split(yte, stats, p["clients"],
                                       seed=p["seed"], num_classes=10)
    return Xtr, ytr, Xte, yte, train_map, test_map


# ---------------------------------------------------------------- framework side

def run_framework(p, Xtr, ytr, Xte, yte, train_map, test_map, tmp="/tmp"):
    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig, SparsityConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.federate import build_federated_data
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    algo = p.get("algorithm", "fedavg")
    model_name = p.get("model", "cnn_cifar10")
    cfg = ExperimentConfig(
        model=model_name, num_classes=10, algorithm=algo,
        seed=p["seed"], tag="parity",
        data=DataConfig(dataset="synthetic_vision",
                        partition_method=p["partition"],
                        partition_alpha=p["alpha"]),
        optim=OptimConfig(lr=p["lr"], lr_decay=p["lr_decay"], wd=p["wd"],
                          momentum=p["momentum"],
                          batch_size=p["batch_size"], epochs=p["epochs"]),
        fed=FedConfig(client_num_in_total=p["clients"], frac=1.0,
                      comm_round=p["rounds"], frequency_of_the_test=1),
        sparsity=SparsityConfig(
            dense_ratio=p.get("dense_ratio", 0.5),
            itersnip_iterations=p.get("itersnip_iterations", 1)),
        log_dir=tmp)
    fed = build_federated_data(Xtr, ytr, train_map, test_map, mesh=None,
                               X_eval=Xte, y_eval=yte)
    trainer = LocalTrainer(create_model(model_name, num_classes=10),
                           cfg.optim, num_classes=10)
    log = ExperimentLogger(tmp, "synthetic_vision", cfg.identity(),
                           console=False)
    engine = create_engine(algo, cfg, fed, trainer, mesh=None,
                           logger=log)
    init_params = engine.init_global_state()  # same seed the run re-inits with
    t0 = time.time()
    res = engine.train()
    curve = [{"round": h["round"], "acc": h["acc"],
              "acc_pooled": h["acc_pooled"], "loss": h["loss"]}
             for h in res["history"]]
    return init_params, curve, time.time() - t0, res


# ---------------------------------------------------------------- torch side

def _flax_to_torch_state(params):
    """Convert the flax CNNCifar init into a torch state dict.

    Layout notes: flax Conv kernels are HWIO -> torch OIHW; flax Dense
    kernels are (in, out) -> torch (out, in); fc1 consumes the flattened
    conv feature map, which flax flattens H,W,C-major (models/
    vision2d.py:83) but torch flattens C,H,W-major, so fc1's input rows
    are permuted accordingly."""
    import torch

    p = {k: np.asarray(v) for k, v in {
        "conv1.k": params["conv1"]["kernel"],
        "conv1.b": params["conv1"]["bias"],
        "conv2.k": params["conv2"]["kernel"],
        "conv2.b": params["conv2"]["bias"],
        "fc1.k": params["fc1"]["kernel"],
        "fc1.b": params["fc1"]["bias"],
        "fc2.k": params["fc2"]["kernel"],
        "fc2.b": params["fc2"]["bias"],
        "fc3.k": params["fc3"]["kernel"],
        "fc3.b": params["fc3"]["bias"],
    }.items()}
    # fc1 rows: flax order (h, w, c) -> torch order (c, h, w)
    fc1 = p["fc1.k"].reshape(5, 5, 64, 384).transpose(2, 0, 1, 3)
    fc1 = fc1.reshape(5 * 5 * 64, 384)
    sd = {
        "conv1.weight": p["conv1.k"].transpose(3, 2, 0, 1),
        "conv1.bias": p["conv1.b"],
        "conv2.weight": p["conv2.k"].transpose(3, 2, 0, 1),
        "conv2.bias": p["conv2.b"],
        "fc1.weight": fc1.T, "fc1.bias": p["fc1.b"],
        "fc2.weight": p["fc2.k"].T, "fc2.bias": p["fc2.b"],
        "fc3.weight": p["fc3.k"].T, "fc3.bias": p["fc3.b"],
    }
    return {k: torch.tensor(np.ascontiguousarray(v), dtype=torch.float32)
            for k, v in sd.items()}


def _flax_to_torch_state_bn(init_state):
    """CNNCifarBN init (params + batch_stats) -> torch state dict. Same
    layout transposes as ``_flax_to_torch_state``; BN scale/bias map to
    weight/bias and batch_stats mean/var to running_mean/running_var."""
    import torch

    params, bstats = init_state.params, init_state.batch_stats
    base = _flax_to_torch_state(params)
    for i in (1, 2):
        bn = params[f"bn{i}"]
        st = bstats[f"bn{i}"]
        base[f"bn{i}.weight"] = torch.tensor(np.asarray(bn["scale"]))
        base[f"bn{i}.bias"] = torch.tensor(np.asarray(bn["bias"]))
        base[f"bn{i}.running_mean"] = torch.tensor(np.asarray(st["mean"]))
        base[f"bn{i}.running_var"] = torch.tensor(np.asarray(st["var"]))
        base[f"bn{i}.num_batches_tracked"] = torch.tensor(0,
                                                          dtype=torch.int64)
    return base


def _torch_cnn_cifar_bn():
    """Torch twin of the flax CNNCifarBN (models/vision2d.py) with
    torch BatchNorm2d defaults — the reference's BN-in-FL semantics:
    running stats live in the state dict and are averaged by the
    state-dict FedAvg like every other key (fedavg_api.py:102-117).
    Shared by the parity run and the partial-batch probe so the two can
    never diverge."""
    import torch
    import torch.nn as nn

    class CNNCifarBN(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 64, 5)
            self.bn1 = nn.BatchNorm2d(64)
            self.conv2 = nn.Conv2d(64, 64, 5)
            self.bn2 = nn.BatchNorm2d(64)
            self.fc1 = nn.Linear(5 * 5 * 64, 384)
            self.fc2 = nn.Linear(384, 192)
            self.fc3 = nn.Linear(192, 10)

        def forward(self, x):
            pool = nn.functional.max_pool2d
            x = pool(torch.relu(self.bn1(self.conv1(x))), 2, 2)
            x = pool(torch.relu(self.bn2(self.conv2(x))), 2, 2)
            x = x.reshape(x.shape[0], -1)
            x = torch.relu(self.fc1(x))
            x = torch.relu(self.fc2(x))
            return self.fc3(x)

    return CNNCifarBN()


_MASKABLE = ("conv1.weight", "conv2.weight", "fc1.weight", "fc2.weight",
             "fc3.weight")


def _torch_fwd_masked(sd, masks, x):
    """CNNCifar forward from a raw state dict with multiplicative weight
    masks — the functional equivalent of the reference's monkey-patched
    ``w * weight_mask`` forwards (snip.py:9-16)."""
    import torch
    import torch.nn.functional as F

    h = F.max_pool2d(torch.relu(F.conv2d(
        x, sd["conv1.weight"] * masks["conv1.weight"], sd["conv1.bias"])), 2, 2)
    h = F.max_pool2d(torch.relu(F.conv2d(
        h, sd["conv2.weight"] * masks["conv2.weight"], sd["conv2.bias"])), 2, 2)
    h = h.reshape(h.shape[0], -1)
    h = torch.relu(F.linear(
        h, sd["fc1.weight"] * masks["fc1.weight"], sd["fc1.bias"]))
    h = torch.relu(F.linear(
        h, sd["fc2.weight"] * masks["fc2.weight"], sd["fc2.bias"]))
    return F.linear(h, sd["fc3.weight"] * masks["fc3.weight"], sd["fc3.bias"])


def torch_snip_masks(p, init_sd, Xtr, ytr, train_map):
    """Independent torch SNIP phase 1 (snip.py:21-116 + client.py:30-53):
    per-client IterSNIP |dL/d weight_mask| at mask=1, client mean, concat +
    normalize by the global sum, keep the top dense_ratio fraction."""
    import torch
    import torch.nn as nn

    X_t = torch.tensor(Xtr.transpose(0, 3, 1, 2))
    y_t = torch.tensor(ytr.astype(np.int64))
    loss_fn = nn.CrossEntropyLoss()
    sd = {k: v.clone() for k, v in init_sd.items()}
    I = p.get("itersnip_iterations", 1)
    client_means = []
    for c in range(p["clients"]):
        idx = np.asarray(train_map[c])
        if len(idx) == 0:
            continue
        rs = np.random.RandomState(p["seed"] * 977 + c)
        acc = {k: torch.zeros_like(sd[k]) for k in _MASKABLE}
        for _ in range(I):
            # reference IterSNIP draws the first batch of a fresh shuffle
            # per iteration (client.py:46-49 next(iter(loader)))
            b = rs.permutation(idx)[: p["batch_size"]]
            masks = {k: torch.ones_like(sd[k], requires_grad=True)
                     for k in _MASKABLE}
            loss = loss_fn(_torch_fwd_masked(sd, masks, X_t[b]), y_t[b])
            loss.backward()
            for k in _MASKABLE:
                acc[k] += masks[k].grad.abs()
        client_means.append({k: v / I for k, v in acc.items()})
    # server mean over clients (snip.py:120-140)
    mean = {k: sum(cm[k] for cm in client_means) / len(client_means)
            for k in _MASKABLE}
    # global top-k mask (snip.py:80-116)
    all_scores = torch.cat([mean[k].flatten() for k in _MASKABLE])
    norm = torch.sum(all_scores)
    k_keep = int(len(all_scores) * p.get("dense_ratio", 0.5))
    thr = torch.topk(all_scores / norm, k_keep, sorted=True)[0][-1]
    return {k: ((mean[k] / norm) >= thr).float() for k in _MASKABLE}


def run_torch(p, init_params, Xtr, ytr, Xte, yte, train_map, test_map,
              masks=None):
    """Reference-semantics FedAvg loop in torch (fedavg_api.py:40-117);
    with ``masks``, the SalientGrads masked variant (post-step
    ``param *= mask`` per batch, my_model_trainer.py:228-231)."""
    import torch
    import torch.nn as nn

    torch.manual_seed(p["seed"])
    torch.set_num_threads(max(1, __import__("os").cpu_count() or 1))

    class CNNCifar(nn.Module):
        # layer parity with the reference cnn_cifar10.py:12-52 and the
        # package's flax CNNCifar (models/vision2d.py:67-87)
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 64, 5)
            self.conv2 = nn.Conv2d(64, 64, 5)
            self.fc1 = nn.Linear(5 * 5 * 64, 384)
            self.fc2 = nn.Linear(384, 192)
            self.fc3 = nn.Linear(192, 10)

        def forward(self, x):
            pool = nn.functional.max_pool2d
            x = pool(torch.relu(self.conv1(x)), 2, 2)
            x = pool(torch.relu(self.conv2(x)), 2, 2)
            x = x.reshape(x.shape[0], -1)
            x = torch.relu(self.fc1(x))
            x = torch.relu(self.fc2(x))
            return self.fc3(x)

    use_bn = p.get("model", "cnn_cifar10") == "cnn_cifar10_bn"
    model = _torch_cnn_cifar_bn() if use_bn else CNNCifar()
    model.load_state_dict(_flax_to_torch_state_bn(init_params) if use_bn
                          else _flax_to_torch_state(init_params.params))
    global_sd = {k: v.clone() for k, v in model.state_dict().items()}

    # init-conversion check: torch and flax produce the same logits on a
    # probe batch, so the two runs truly start from the SAME function
    from neuroimagedisttraining_tpu.models import create_model
    import jax.numpy as jnp

    probe = Xtr[:8]
    fx_vars = {"params": init_params.params}
    if use_bn:
        fx_vars["batch_stats"] = init_params.batch_stats
    fx = create_model(p.get("model", "cnn_cifar10"), num_classes=10).apply(
        fx_vars, jnp.asarray(probe), train=False)
    model.eval()
    with torch.no_grad():
        th = model(torch.tensor(probe.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(th, np.asarray(fx), atol=2e-4)

    X_t = torch.tensor(Xtr.transpose(0, 3, 1, 2))  # NHWC -> NCHW
    y_t = torch.tensor(ytr.astype(np.int64))
    Xe_t = torch.tensor(Xte.transpose(0, 3, 1, 2))
    ye_t = torch.tensor(yte.astype(np.int64))
    loss_fn = nn.CrossEntropyLoss()

    def eval_mean_acc(sd):
        model.load_state_dict(sd)
        model.eval()
        accs, correct_all, total_all = [], 0, 0
        with torch.no_grad():
            for c in range(p["clients"]):
                idx = np.asarray(test_map[c])
                if len(idx) == 0:
                    continue
                logits = model(Xe_t[idx])
                pred = logits.argmax(dim=1)
                correct = int((pred == ye_t[idx]).sum())
                accs.append(correct / len(idx))
                correct_all += correct
                total_all += len(idx)
        return float(np.mean(accs)), correct_all / max(total_all, 1)

    curve = []
    t0 = time.time()
    for round_idx in range(p["rounds"]):
        lr = p["lr"] * p["lr_decay"] ** round_idx  # my_model_trainer.py:209
        # client sampling parity (fedavg_api.py:92-100); frac=1 -> all
        sampled = np.arange(p["clients"])
        updates, weights = [], []
        for c in sampled:
            idx = np.asarray(train_map[c])
            if len(idx) == 0:
                continue
            model.load_state_dict(global_sd)  # set_model_params deepcopy
            model.train()
            opt = torch.optim.SGD(model.parameters(), lr=lr,
                                  momentum=p["momentum"],
                                  weight_decay=p["wd"])
            rs = np.random.RandomState(p["seed"] * 131 + round_idx * 17 + c)
            for _ in range(p["epochs"]):
                order = rs.permutation(idx)
                for s in range(0, len(order), p["batch_size"]):
                    b = order[s: s + p["batch_size"]]
                    opt.zero_grad()
                    loss = loss_fn(model(X_t[b]), y_t[b])
                    loss.backward()
                    # clip_grad_norm(10) parity, my_model_trainer.py:224
                    torch.nn.utils.clip_grad_norm_(model.parameters(), 10.0)
                    opt.step()
                    if masks is not None:
                        # post-step re-mask per batch (my_model_trainer.py
                        # :228-231 under args.snip_mask)
                        with torch.no_grad():
                            for name, param in model.named_parameters():
                                if name in masks:
                                    param.data *= masks[name]
            updates.append({k: v.detach().clone()
                            for k, v in model.state_dict().items()})
            weights.append(float(len(idx)))
        # sample-weighted FedAvg (fedavg_api.py:102-117) — EVERY state
        # dict key, BN running stats included (the reference's implicit
        # BN-in-FL semantics); integer buffers (num_batches_tracked) are
        # cast back like load_state_dict's copy_ would
        w = np.asarray(weights) / np.sum(weights)
        global_sd = {
            k: sum(wi * upd[k].float() for wi, upd in
                   zip(w, updates)).to(global_sd[k].dtype)
            for k in global_sd}
        acc, pooled = eval_mean_acc(global_sd)
        curve.append({"round": round_idx, "acc": acc, "acc_pooled": pooled})
    return curve, time.time() - t0


# ---------------------------------------------------------------- masks

def _flax_masks_to_torch(masks):
    """Framework mask pytree -> torch weight-name dict, with the same layout
    transposes as ``_flax_to_torch_state`` (HWIO->OIHW; fc1 rows hwc->chw)."""
    m = {k: np.asarray(masks[k]["kernel"]) for k in
         ("conv1", "conv2", "fc1", "fc2", "fc3")}
    fc1 = m["fc1"].reshape(5, 5, 64, 384).transpose(2, 0, 1, 3)
    return {
        "conv1.weight": m["conv1"].transpose(3, 2, 0, 1),
        "conv2.weight": m["conv2"].transpose(3, 2, 0, 1),
        "fc1.weight": fc1.reshape(5 * 5 * 64, 384).T,
        "fc2.weight": m["fc2"].T,
        "fc3.weight": m["fc3"].T,
    }


def compare_masks(fw_masks, th_masks):
    """Per-layer + overall agreement and densities of the two masks."""
    per_layer, agree_n, total_n, fw_nnz, th_nnz = {}, 0, 0, 0, 0
    for k in _MASKABLE:
        fw = np.asarray(fw_masks[k]) > 0.5
        th = np.asarray(th_masks[k].numpy()) > 0.5
        per_layer[k] = {
            "agreement": float(np.mean(fw == th)),
            "density_framework": float(fw.mean()),
            "density_torch": float(th.mean()),
        }
        agree_n += int(np.sum(fw == th))
        total_n += fw.size
        fw_nnz += int(fw.sum())
        th_nnz += int(th.sum())
    return {
        "overall_agreement": agree_n / total_n,
        "density_framework": fw_nnz / total_n,
        "density_torch": th_nnz / total_n,
        "per_layer": per_layer,
    }


# ------------------------------------------------------- parity protocol

def protocol_verdict(jx_curve, th_curve, tolerance, eps=0.06, k=10):
    """PRE-COMMITTED stopping + comparison rule (VERDICT r4 weak #5 /
    next-step #8): the stop round is the FIRST round >= 2k at which BOTH
    curves' trailing-k std < eps — a plateau — or the run's round cap
    (--rounds) if no round qualifies. The verdict compares the trailing-k
    means AT THE STOP ROUND against the tolerance. Every seed gets the
    same rule; there is no per-seed window choice. (eps=0.06 was fixed
    from the round-4 artifacts BEFORE any round-5 run: converged curves
    on this cohort oscillate with trailing-10 std 0.04-0.05, mid-climb
    curves read 0.1-0.17.)"""
    fw = np.array([r["acc"] for r in jx_curve])
    th = np.array([r["acc"] for r in th_curve])
    R = len(fw)
    k = min(k, R)  # short (smoke) runs: window = whole curve, labeled so
    stop, plateaued = R, False
    for r in range(2 * k, R + 1):
        if fw[r - k:r].std() < eps and th[r - k:r].std() < eps:
            stop, plateaued = r, True
            break
    m_fw = float(fw[stop - k:stop].mean())
    m_th = float(th[stop - k:stop].mean())
    delta = abs(m_fw - m_th)
    return {
        "protocol": {"eps": eps, "k": k, "rule":
                     "first round with both trailing-k stds < eps, else "
                     "the round cap; compare trailing-k means there"},
        "stop_round": stop, "plateaued": plateaued,
        "trailing_fw": m_fw, "trailing_th": m_th, "delta": delta,
        "std_fw_at_stop": float(fw[stop - k:stop].std()),
        "std_th_at_stop": float(th[stop - k:stop].std()),
        "parity": bool(delta <= tolerance),
    }


# ------------------------------------------- BN partial-batch probe

def bn_partial_batch_probe(p, init_params, Xtr, ytr, train_map):
    """Measured size of the documented partial-batch BN deviation
    (core/trainer.py: the static-shape scan's final batch wraps filler
    rows that are VISIBLE to BN batch statistics, where torch's
    DataLoader would see a genuinely smaller batch). One client, one
    epoch, THE SAME permutation on both sides — the only semantic
    differences left are the BN batch-stat population (wrapped rows vs
    smaller batch) and flax's biased vs torch's unbiased running-var
    update. Returns max-abs deltas of the post-epoch BN running stats and
    params."""
    import torch
    import jax
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.config import OptimConfig
    from neuroimagedisttraining_tpu.core.trainer import (
        LocalTrainer, epoch_permutations, shuffle_batch_indices,
    )
    from neuroimagedisttraining_tpu.models import create_model

    idx = np.asarray(train_map[0])
    n = len(idx)
    b = p["batch_size"]
    nmax = max(len(np.asarray(v)) for v in train_map.values())
    X = np.zeros((nmax,) + Xtr.shape[1:], np.float32)
    y = np.zeros((nmax,), np.int32)
    X[:n], y[:n] = Xtr[idx], ytr[idx]

    cfg = OptimConfig(lr=p["lr"], momentum=p["momentum"], wd=p["wd"],
                      grad_clip=10.0, batch_size=b, epochs=1,
                      batch_order="shuffle")
    trainer = LocalTrainer(create_model("cnn_cifar10_bn", num_classes=10),
                           cfg, num_classes=10)
    cs = init_params
    new_cs, _ = trainer.local_train(cs, jnp.asarray(X), jnp.asarray(y),
                                    jnp.int32(n), jnp.float32(p["lr"]),
                                    epochs=1, batch_size=b,
                                    max_samples=nmax)

    # reconstruct the trainer's own permutation and walk it in torch
    prng = jax.random.split(cs.rng)[1]
    perms = epoch_permutations(prng, 1, nmax, n)
    steps = -(-nmax // b)
    sd = _flax_to_torch_state_bn(cs)
    model = _torch_cnn_cifar_bn()
    model.load_state_dict(sd)
    model.train()
    opt = torch.optim.SGD(model.parameters(), lr=p["lr"],
                          momentum=p["momentum"], weight_decay=p["wd"])
    X_t = torch.tensor(X.transpose(0, 3, 1, 2))
    y_t = torch.tensor(y.astype(np.int64))
    loss_fn = torch.nn.CrossEntropyLoss()
    for t in range(steps):
        bidx, wmask = shuffle_batch_indices(perms, t, steps, b, n)
        keep = np.asarray(bidx)[np.asarray(wmask) > 0]
        if len(keep) == 0:
            continue  # masked no-op step beyond the client's quota
        opt.zero_grad()
        loss = loss_fn(model(X_t[keep]), y_t[keep])
        loss.backward()
        torch.nn.utils.clip_grad_norm_(model.parameters(), 10.0)
        opt.step()
    out_sd = model.state_dict()

    def _d(a, bt):
        return float(np.abs(np.asarray(a) - bt.detach().numpy()).max())

    bs = new_cs.batch_stats
    return {
        "client": 0, "n": n, "batch_size": b, "nmax_pad": nmax,
        "partial_batch_rows": int(n % b) if n % b else b,
        "running_mean_max_abs_delta": max(
            _d(bs["bn1"]["mean"], out_sd["bn1.running_mean"]),
            _d(bs["bn2"]["mean"], out_sd["bn2.running_mean"])),
        "running_var_max_abs_delta": max(
            _d(bs["bn1"]["var"], out_sd["bn1.running_var"]),
            _d(bs["bn2"]["var"], out_sd["bn2.running_var"])),
        "param_max_abs_delta": max(
            _d(new_cs.params["conv1"]["kernel"],
               out_sd["conv1.weight"].permute(2, 3, 1, 0)),
            _d(new_cs.params["fc3"]["kernel"], out_sd["fc3.weight"].T)),
    }


# ---------------------------------------------------------------- main

def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=DEF["rounds"])
    ap.add_argument("--algorithm", type=str, default="fedavg",
                    choices=["fedavg", "salientgrads"])
    ap.add_argument("--seed", type=int, default=DEF["seed"])
    ap.add_argument("--itersnip_iterations", type=int, default=10,
                    help="SNIP batches per client (salientgrads mode); "
                         "more batches -> more stable scores -> higher "
                         "expected cross-implementation mask agreement")
    ap.add_argument("--model", type=str, default="cnn_cifar10",
                    choices=["cnn_cifar10", "cnn_cifar10_bn"],
                    help="cnn_cifar10_bn runs the BatchNorm federated-"
                         "parity experiment (VERDICT r4 missing #2)")
    ap.add_argument("--num_train", type=int, default=DEF["num_train"],
                    help="cohort size override (smoke tests)")
    ap.add_argument("--num_test", type=int, default=DEF["num_test"])
    ap.add_argument("--out", type=str, default="PARITY")
    args = ap.parse_args()
    if args.model == "cnn_cifar10_bn" and args.algorithm != "fedavg":
        ap.error("--model cnn_cifar10_bn currently pairs with fedavg "
                 "(the BN parity experiment)")
    p = dict(DEF, rounds=args.rounds, algorithm=args.algorithm,
             seed=args.seed, itersnip_iterations=args.itersnip_iterations,
             dense_ratio=0.5, model=args.model,
             num_train=args.num_train, num_test=args.num_test)

    Xtr, ytr, Xte, yte, train_map, test_map = build_cohort(p)
    print(f"cohort: {len(ytr)} train / {len(yte)} test, "
          f"{p['clients']} clients (n_cls alpha={p['alpha']}), "
          f"algorithm={p['algorithm']}, seed={p['seed']}")

    init_params, jx_curve, jx_s, res = run_framework(
        p, Xtr, ytr, Xte, yte, train_map, test_map)
    print(f"framework run: {jx_s:.1f}s, final acc={jx_curve[-1]['acc']:.4f}")

    bn_probe = None
    if p["model"] == "cnn_cifar10_bn":
        bn_probe = bn_partial_batch_probe(p, init_params, Xtr, ytr,
                                          train_map)
        print(f"BN partial-batch probe: {json.dumps(bn_probe)}")

    mask_report = None
    th_masks = None
    if p["algorithm"] == "salientgrads":
        init_sd = _flax_to_torch_state(init_params.params)
        th_masks = torch_snip_masks(p, init_sd, Xtr, ytr, train_map)
        mask_report = compare_masks(_flax_masks_to_torch(res["masks"]),
                                    th_masks)
        print(f"mask agreement: {mask_report['overall_agreement']:.4f} "
              f"(density fw {mask_report['density_framework']:.4f} / "
              f"torch {mask_report['density_torch']:.4f})")

    th_curve, th_s = run_torch(p, init_params, Xtr, ytr, Xte, yte,
                               train_map, test_map, masks=th_masks)
    print(f"torch run:     {th_s:.1f}s, final acc={th_curve[-1]['acc']:.4f}")

    # Verdict metric: TRAILING-5-ROUND mean accuracy. Both learners
    # oscillate +-0.1 between adjacent rounds at this lr/momentum on the
    # small cohort (visible in both curves), so a single final-round
    # snapshot is dominated by that noise; the trailing mean is the
    # converged-level comparison. The raw final-round delta is reported
    # alongside for transparency.
    k = min(5, len(jx_curve))
    trail_fw = float(np.mean([r["acc"] for r in jx_curve[-k:]]))
    trail_th = float(np.mean([r["acc"] for r in th_curve[-k:]]))
    delta = abs(trail_fw - trail_th)
    ok = delta <= p["tolerance"]
    # trailing-10 rides along for noise diagnosis: when both learners
    # oscillate +-0.1-0.3 mid-convergence (hard partitions), the 5-round
    # window can catch the two sides at opposite phases; the 10-round
    # window says whether a trailing-5 excursion is phase noise
    k10 = min(10, len(jx_curve))
    trail10_fw = float(np.mean([r["acc"] for r in jx_curve[-k10:]]))
    trail10_th = float(np.mean([r["acc"] for r in th_curve[-k10:]]))
    # the PRE-COMMITTED protocol verdict (plateau-or-cap stop, trailing-10
    # comparison) — the headline verdict; trailing-5/10-at-final-round
    # ride along for continuity with the round-4 artifacts
    proto = protocol_verdict(jx_curve, th_curve, p["tolerance"])
    result = {
        "config": p, "mask_report": mask_report,
        "framework_curve": jx_curve, "torch_curve": th_curve,
        "final_acc_framework": jx_curve[-1]["acc"],
        "final_acc_torch": th_curve[-1]["acc"],
        "final_round_delta": abs(jx_curve[-1]["acc"] - th_curve[-1]["acc"]),
        "trailing5_acc_framework": trail_fw,
        "trailing5_acc_torch": trail_th,
        "trailing5_delta": delta,
        "trailing10_acc_framework": trail10_fw,
        "trailing10_acc_torch": trail10_th,
        "trailing10_delta": abs(trail10_fw - trail10_th),
        "tolerance": p["tolerance"], "parity": ok,
        "protocol_verdict": proto,
        "bn_partial_batch_probe": bn_probe,
        "framework_seconds": jx_s, "torch_seconds": th_s,
    }
    with open(args.out + ".json", "w") as f:
        json.dump(result, f, indent=1)
    print(f"\nround  framework_acc  torch_acc")
    for a, b in zip(jx_curve, th_curve):
        print(f"{a['round']:5d}  {a['acc']:.4f}         {b['acc']:.4f}")
    print(f"\ntrailing-5 mean acc: framework {trail_fw:.4f} vs torch "
          f"{trail_th:.4f}; delta = {delta:.4f} "
          f"(tolerance {p['tolerance']}) "
          f"-> {'PARITY OK' if ok else 'PARITY FAIL'}")
    print(f"protocol verdict (pre-committed): stop_round="
          f"{proto['stop_round']} plateaued={proto['plateaued']} "
          f"trailing-10 {proto['trailing_fw']:.4f} vs "
          f"{proto['trailing_th']:.4f}, delta={proto['delta']:.4f} -> "
          f"{'PARITY OK' if proto['parity'] else 'PARITY FAIL'}")
    return 0 if proto["parity"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
