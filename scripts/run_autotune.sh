#!/usr/bin/env bash
# Push-button autotune session (ISSUE 19): screen -> refine -> emit ->
# gate -> install. Runs the seeded successive-halving search over the
# declared space (tune/space.py), emits the per-device-kind recipe +
# the session artifact into a temp dir, gates BOTH against the
# committed baselines (analysis/bench_gate.py — every cell exact: the
# search is seeded and byte-deterministic), then installs
# bench_matrix/recipes/<device_kind>.json and
# bench_matrix/autotune_session.json.
#
# Order matters: fresh temp dir first, gate before install — gating
# after overwriting the committed path would compare the fresh
# artifact against itself and pass vacuously.
#
# Seed/space-change regenerations: every gated cell is exact AT the
# committed seed + space; a run with a different seed, axes, or
# backend legitimately differs, so when the fresh session meta block
# != the committed one the gate verdict is reported but not fatal —
# the operator is establishing a new baseline and reviews + commits it.
#
# Defaults are the CPU-harness configuration (virtual cost-model
# backend over the small default axes, 2 virtual devices so the
# client_mesh=2 cells stay in the space, winner validated through the
# REAL engine.train() driver once). The flagship TPU session measures
# every cell through the real driver instead — run on the pod:
#
#   TUNE_BACKEND=driver TUNE_DEVICES=0 \
#   PROFILE_MODEL=3DCNN PROFILE_SHAPE=121,145,121 PROFILE_LOCAL=512 \
#   PROFILE_CLIENTS=21 NIDT_PEAK_FLOPS=<chip bf16 peak * chips> \
#   TUNE_SCREEN_ROUNDS=2 TUNE_COMMIT_ROUNDS=8 scripts/run_autotune.sh
#
# (driver cells score by nidt_mfu once the peak is armed; the journal
# in TUNE_JOURNAL makes a killed pod session resumable.)
#
# Env:
#   TUNE_BACKEND        virtual | driver       (default virtual)
#   TUNE_SEED           search seed            (default 20)
#   TUNE_DEVICES        virtual CPU devices    (default 2; 0 = none,
#                       real backends)
#   TUNE_SCREEN_ROUNDS  screen fidelity        (default 2)
#   TUNE_COMMIT_ROUNDS  committed fidelity     (default 5)
#   TUNE_SURVIVORS      refine pool size       (default 4)
#   TUNE_JOURNAL        JSONL resume journal   (default: fresh temp)
#   TUNE_OUT_DIR        install dir            (default bench_matrix)
set -euo pipefail
cd "$(dirname "$0")/.."

PY="${PYTHON:-python}"
BACKEND="${TUNE_BACKEND:-virtual}"
SEED="${TUNE_SEED:-20}"
DEVICES="${TUNE_DEVICES:-2}"
SCREEN="${TUNE_SCREEN_ROUNDS:-2}"
COMMIT="${TUNE_COMMIT_ROUNDS:-5}"
SURVIVORS="${TUNE_SURVIVORS:-4}"
OUT_DIR="${TUNE_OUT_DIR:-bench_matrix}"

fresh_dir="$(mktemp -d)"
trap 'rm -rf "$fresh_dir"' EXIT
JOURNAL="${TUNE_JOURNAL:-$fresh_dir/journal.jsonl}"

# the recipe file name follows the device kind the tuner resolves;
# ask the CLI to write into the fresh dir and read the path back from
# the session artifact
echo "== autotune session (fresh; backend=$BACKEND seed=$SEED) =="
args=(--backend "$BACKEND" --seed "$SEED"
      --screen_rounds "$SCREEN" --commit_rounds "$COMMIT"
      --survivors "$SURVIVORS" --journal "$JOURNAL"
      --session_out "$fresh_dir/autotune_session.json"
      --validate_winner)
if [[ "$DEVICES" != "0" ]]; then
    args+=(--virtual_devices "$DEVICES")
fi
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    "$PY" -m neuroimagedisttraining_tpu.tune "${args[@]}" \
    --out "$fresh_dir/recipe.json"

recipe_rel="recipes/$("$PY" - "$fresh_dir/autotune_session.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
print(doc["meta"]["device_kind"].strip().lower().replace(" ", "_")
      + ".json")
EOF
)"
mkdir -p "$fresh_dir/recipes"
mv "$fresh_dir/recipe.json" "$fresh_dir/$recipe_rel"

if [[ -f "$OUT_DIR/autotune_session.json" && -f "$OUT_DIR/$recipe_rel" ]]
then
    echo "== bench gate: fresh session vs committed baseline =="
    same_config="$("$PY" - "$fresh_dir/autotune_session.json" \
        "$OUT_DIR/autotune_session.json" <<'EOF'
import json, sys
fresh = json.load(open(sys.argv[1]))
committed = json.load(open(sys.argv[2]))
print("1" if fresh.get("meta") == committed.get("meta")
      and fresh["space"]["fingerprint"]
      == committed["space"]["fingerprint"] else "0")
EOF
)"
    gate_rc=0
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        "$PY" -m neuroimagedisttraining_tpu.analysis.bench_gate \
        --fresh "$fresh_dir" --committed "$OUT_DIR" \
        --artifact autotune_session.json --artifact "$recipe_rel" \
        --quiet || gate_rc=$?
    if [[ "$same_config" == "1" && "$gate_rc" -ne 0 ]]; then
        echo "autotune session DIVERGED from the committed baseline at" \
             "the SAME seed/space — not installing" >&2
        exit "$gate_rc"
    elif [[ "$same_config" != "1" ]]; then
        echo "NOTE: session seed/space differs from the committed" \
             "baseline — gate verdict above is informational;" \
             "installing as the NEW baseline. Review before committing."
    fi
else
    echo "== no committed autotune baseline yet (first session) =="
fi

mkdir -p "$OUT_DIR/recipes"
cp "$fresh_dir/autotune_session.json" "$OUT_DIR/autotune_session.json"
cp "$fresh_dir/$recipe_rel" "$OUT_DIR/$recipe_rel"
echo "autotune session complete: $OUT_DIR/autotune_session.json +" \
     "$OUT_DIR/$recipe_rel"
