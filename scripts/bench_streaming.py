"""Streaming-mode throughput bench: round-granular host->device feed.

Times the SHIPPED FedAvg streaming round (double-buffered host gather ->
device_put -> jitted round program) on a synthetic ABCD-shaped cohort that
is deliberately larger than the per-round device budget: only the sampled
clients' shards ever reside on device, so the cohort size is bounded by
host RAM, not HBM (the real 11,573-subject cohort is ~24.5 GB uint8 vs
16 GB HBM on one v5e chip).

Prints one JSON line. Env knobs: BENCH_STREAM_CLIENTS (8),
BENCH_STREAM_LOCAL (64 subjects/client), BENCH_STREAM_FRAC (0.5),
BENCH_SHAPE, BENCH_BATCH (16), BENCH_REPS (3), BENCH_MODEL (3DCNN).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig,
    )
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data.stream import StreamingFederation
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    C = int(os.environ.get("BENCH_STREAM_CLIENTS", 8))
    n_local = int(os.environ.get("BENCH_STREAM_LOCAL", 64))
    frac = float(os.environ.get("BENCH_STREAM_FRAC", 0.5))
    batch = int(os.environ.get("BENCH_BATCH", 16))
    reps = int(os.environ.get("BENCH_REPS", 3))
    shape = tuple(int(s) for s in
                  os.environ.get("BENCH_SHAPE", "121,145,121").split(","))

    rng = np.random.default_rng(7)
    N = C * n_local
    X = rng.integers(0, 256, size=(N,) + shape, dtype=np.uint8)
    y = rng.integers(0, 2, size=N).astype(np.int32)
    train_map = {c: np.arange(c * n_local, (c + 1) * n_local)
                 for c in range(C)}
    test_map = {c: train_map[c][:8] for c in range(C)}
    stream = StreamingFederation(X, y, train_map, test_map)

    cfg = ExperimentConfig(
        model=os.environ.get("BENCH_MODEL", "3DCNN"), num_classes=1,
        algorithm="fedavg",
        data=DataConfig(dataset="synthetic"),
        optim=OptimConfig(lr=1e-3, batch_size=batch, epochs=1),
        fed=FedConfig(client_num_in_total=C, frac=frac, comm_round=3,
                      frequency_of_the_test=10**9),
        log_dir="/tmp/nidt_bench")
    model = create_model(cfg.model, num_classes=1, dtype=jnp.bfloat16,
                         remat=False)
    trainer = LocalTrainer(model, cfg.optim, num_classes=1)
    log = ExperimentLogger("/tmp/nidt_bench", "synthetic", cfg.identity(),
                           console=False)
    engine = create_engine("fedavg", cfg, None, trainer, logger=log,
                           stream=stream)

    gs = engine.init_global_state()
    params, bstats = gs.params, gs.batch_stats
    S = min(cfg.fed.client_num_per_round, C)
    steps = -(-n_local // batch)
    bytes_per_round = S * n_local * int(np.prod(shape))

    def one_round(params, bstats, r):
        sampled = engine.client_sampling(r)
        Xs, ys, ns = stream.get_train(sampled)
        stream.prefetch_train(engine.client_sampling(r + 1))
        return engine._round_stream_jit(params, bstats, Xs, ys, ns,
                                        engine.per_client_rngs(r, sampled),
                                        engine.round_lr(r))

    params, bstats, loss, _ = one_round(params, bstats, 0)  # compile+warm
    float(loss)

    n_rounds = 3
    samples = n_rounds * S * steps * batch
    best_sps, best_wall = 0.0, float("inf")
    best_stats = None
    for _ in range(reps):
        stream.prefetch_train(engine.client_sampling(1))
        stream.sync()  # warm prefetch fully done -> excluded from stats
        for k in stream.transfer_stats:
            stream.transfer_stats[k] = 0
        t0 = time.perf_counter()
        for r in range(1, 1 + n_rounds):
            params, bstats, loss, _ = one_round(params, bstats, r)
        float(loss)
        dt = time.perf_counter() - t0
        # drain the reader queue before snapshotting: the trailing
        # prefetch (round n_rounds+1) stands in for round 1's consumed
        # warm fetch, so fetches == n_rounds and no in-flight update races
        # the read
        stream.sync()
        if samples / dt > best_sps:
            best_sps = samples / dt
            best_wall = dt
            best_stats = dict(stream.transfer_stats)

    # host-fetch-only bandwidth (gather_rows + pad) for attribution
    t0 = time.perf_counter()
    stream._fetch(engine.client_sampling(1), "train")
    fetch_s = time.perf_counter() - t0

    # overlap attribution (VERDICT r3 weak #2): host gather AND device_put
    # both run on the reader thread behind the previous round's compute,
    # so wall/round < gather/round + put/round + compute/round when the
    # overlap is real. rounds counted exclude the warm prefetch.
    n_fetches = max(best_stats["fetches"], 1)
    gather_ms = best_stats["host_gather_ms"] / n_fetches
    put_ms = best_stats["device_put_ms"] / n_fetches
    wall_ms = best_wall / n_rounds * 1e3

    print(json.dumps({
        "metric": "abcd_fedavg_streaming_samples_per_sec",
        "value": round(best_sps, 2),
        "unit": f"samples/s ({C}x{n_local} cohort "
                f"{X.nbytes / 1e9:.2f} GB host-resident, "
                f"{S} sampled clients/round device-resident, b{batch})",
        "cohort_gb": round(X.nbytes / 1e9, 2),
        "device_bytes_per_round_gb": round(bytes_per_round / 1e9, 2),
        "host_fetch_gbps": round(bytes_per_round / fetch_s / 1e9, 2),
        "host_gather_ms_per_round": round(gather_ms, 1),
        "device_put_ms_per_round": round(put_ms, 1),
        "wall_ms_per_round": round(wall_ms, 1),
        # both stages run on the reader thread behind the previous round's
        # compute; overlap is real when wall/round < gather+put+compute,
        # i.e. this ratio can exceed 1 without costing wall time
        "transfer_to_wall_ratio": round((gather_ms + put_ms) / wall_ms, 3),
        "timing": f"best of {reps} repeats",
    }))
    stream.close()


if __name__ == "__main__":
    main()
