#!/usr/bin/env bash
# Cohort-sharding bench cell (ISSUE 6) -> bench_matrix/cohort_sharding.json
#
# Runs bench.py in its BENCH_COHORT_DEVICES mode: per-round wall time vs C
# for the sequential C-loop / the cohort-SHARDED program / the shipped
# vmapped round, the flagship 21-site fedavg+salientgrads cells, the K=4
# one-dispatch-per-window pin, and salientgrads_mask_ms under the sharded
# phase-1 driver. Defaults provision an 8-VIRTUAL-device CPU mesh on this
# host — treat the SLOPES and the one-dispatch pin as the stable claims
# (the absolute sharded speedup is a TPU-session measurement); override
# BENCH_COHORT_VIRTUAL=0 and the shape/model knobs on a real chip.
set -euo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_matrix
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    BENCH_COHORT_DEVICES="${BENCH_COHORT_DEVICES:-8}" \
    BENCH_COHORT_VIRTUAL="${BENCH_COHORT_VIRTUAL:-1}" \
    BENCH_MODEL="${BENCH_MODEL:-3dcnn_tiny}" \
    BENCH_SHAPE="${BENCH_SHAPE:-12,14,12}" \
    BENCH_BATCH="${BENCH_BATCH:-8}" \
    BENCH_LOCAL="${BENCH_LOCAL:-16}" \
    BENCH_REPS="${BENCH_REPS:-3}" \
    python bench.py | tee bench_matrix/cohort_sharding.json
