#!/usr/bin/env bash
# Single entry point for the repo's static analysis (ARCHITECTURE.md
# "Static analysis"): generic lint (ruff, pycodestyle/pyflakes tier, config
# in pyproject.toml) + the repo-specific invariant checker (nidtlint).
# Exits non-zero if either reports findings.
set -uo pipefail
cd "$(dirname "$0")/.."

rc=0

if command -v ruff >/dev/null 2>&1; then
    echo "== ruff check (pycodestyle/pyflakes tier) =="
    ruff check neuroimagedisttraining_tpu tests scripts || rc=1
else
    # ruff is optional tooling — nidtlint below is the dependency-free gate
    echo "== ruff not installed; skipping the generic lint tier ==" >&2
fi

# nidtlint walks the whole package, including faults/ AND codec/ — the
# lock-discipline rules cover distributed/ and faults/ (the chaos
# wrapper writes raw frames), the determinism rules hold the fault
# schedule to the same seeded-stream contract as the engines, and the
# trace-safety rules apply to codec/device.py's jitted encode math
# (lossy_roundtrip runs inside every codec-enabled engine round).
# the Byzantine layer (ISSUE 5) rides the same net: the transitive-call
# closure traces faults/adversary.py's apply_attack through its vmapped
# lambda and core/robust.py's aggregators through their vmap/fori_loop
# bodies (no host syncs, no global RNG — one seed, one attack trace)
# the donation-discipline family (ISSUE 4) rides along: round programs
# must declare donate_argnums, and no caller may reread a donated buffer
# the async-discipline family (ISSUE 7) covers asyncfl/: no blocking
# calls (time.sleep, socket recv/accept, bare queue.get) lexically
# inside async def bodies — one blocking call silently serializes the
# whole simulated-client fleet; lock-discipline extends to asyncfl/ too
# the obs-discipline family (ISSUE 9) rides the trace-safety resolver:
# no clock reads (time.time/monotonic/perf_counter) and no metrics-
# registry/flight/span mutation lexically inside functions handed to
# jit/vmap/shard_map/lax combinators — telemetry at host boundaries only
# the ISSUE 13 fan-in extensions ride along: obs-trace-ctx-key (the
# wire trace context is spelled ONLY via ARG_TRACE_CTX — an ad-hoc
# 'trace_ctx' string literal silently unlinks the flow chain) and
# obs-pipe-per-upload (asyncfl/ingest.py telemetry crosses the
# worker->root pipe batched: 'vb'/'beats'/'obs', never per-upload
# 'v'/'beat' events — one pipe send is ~0.5-1 ms on sandboxed kernels)
# the precision-discipline family (ISSUE 10) also rides the trace-safety
# resolver: no bare float32 upcasts (.astype(jnp.float32) /
# jnp.asarray(x, jnp.float32) / jnp.float32(x)) inside traced train-step
# bodies under core/, ops/, models/ — the bf16_mixed contract keeps
# compute in the model dtype; blessed master-weight/loss sites carry
# justified precision-upcast pragmas
# the round-program-discipline family (ISSUE 11) keeps the declarative
# builder the ONLY owner of fused round machinery: no hand-rolled
# lax.scan fused round bodies in engine classes outside
# engines/program.py, and *_fallback_key overrides must name keys from
# the builder's REASONS table (the structured nidt_fallback_total
# counter's single source of truth)
# the ISSUE 14 obs-discipline extension rides the same resolver:
# obs-sync-in-trace — no jax.block_until_ready / .block_until_ready()
# inside traced bodies; the dispatch-boundary profiler (obs/compute.py)
# times the ENQUEUE and closes MFU windows at already-synced host
# boundaries, and a sync smuggled into a round body is exactly the
# hidden-cost bug its zero-sync contract forbids
# the health-rule-discipline family (ISSUE 15) keeps obs/names.py the
# single source of truth for metric names: a full-match nidt_* string
# literal outside obs/ is a finding (health-metric-literal) — the
# anomaly-rule engine (obs/rules.py) validates every rule manifest
# against that declared-name set at startup, and a literal spelling
# elsewhere would let a renamed metric silently leave the set and turn
# the rules watching it permanently dark
echo "== nidtlint (trace-safety / engine-contract / lock-discipline / determinism / donation-discipline / async-discipline / obs-discipline incl. obs-trace-ctx-key + obs-pipe-per-upload + obs-sync-in-trace / precision-discipline / round-program-discipline / health-rule-discipline) =="
# --cache: content-hash per-file finding cache (.nidtlint_cache/,
# gitignored; a rule edit invalidates everything) keeps this sub-10s
# as the tree grows
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m neuroimagedisttraining_tpu.analysis \
    --cache .nidtlint_cache neuroimagedisttraining_tpu || rc=1

# the whole-program contract pass (ISSUE 16): flag<->config lockstep
# across both CLIs, metric-name/REASONS/bench-SPECS closure, the
# generated compatibility matrix (analysis/compat_matrix.py + its
# ARCHITECTURE.md twin, --regen-compat to refresh), and cross-module
# donation summaries. JSON artifact for CI annotation, bench_gate-style
# exit codes (0 clean / 1 findings / 2 usage error).
CONTRACTS_OUT="${CONTRACTS_OUT:-/tmp/nidt_contracts.json}"
echo "== nidtlint --project (flag<->config / metric closure / compat matrix / x-module donation) -> $CONTRACTS_OUT =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m neuroimagedisttraining_tpu.analysis --project --json \
    > "$CONTRACTS_OUT" || { rc=1; cat "$CONTRACTS_OUT"; }

# the example health-rule manifest must stay loadable and metric-closed
echo "== nidtlint --check-manifest scripts/health_rules.example.json =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m neuroimagedisttraining_tpu.analysis \
    --check-manifest scripts/health_rules.example.json || rc=1

exit $rc
