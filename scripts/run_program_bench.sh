#!/usr/bin/env bash
# Round-program builder bench cell (ISSUE 11) ->
# bench_matrix/round_program.json
#
# Runs bench.py in its BENCH_ROUND_PROGRAM mode: per-engine dispatch
# counts and per-round wall for K=1 per-round loops vs K=4 fused windows
# compiled by engines/program.py — including the engines the builder put
# on the fused path for the first time (ditto, dpsgd, subavg) and the
# fedfomo fallback reference. The DISPATCH COUNTS and the
# one-compiled-program-per-window evidence are the stable claims on this
# CPU harness; the wall ratio scales with per-dispatch latency and is a
# TPU-session measurement (PROFILE.md round 2).
set -euo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_matrix
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    BENCH_ROUND_PROGRAM=1 \
    BENCH_MODEL="${BENCH_MODEL:-3dcnn_tiny}" \
    BENCH_SHAPE="${BENCH_SHAPE:-12,14,12}" \
    BENCH_BATCH="${BENCH_BATCH:-8}" \
    BENCH_LOCAL="${BENCH_LOCAL:-16}" \
    BENCH_RP_ROUNDS="${BENCH_RP_ROUNDS:-8}" \
    python bench.py | tee bench_matrix/round_program.json
