#!/usr/bin/env bash
# Precision / fused-update bench cell (ISSUE 10)
#     -> bench_matrix/precision_bench.json
#
# Runs bench.py in its BENCH_PRECISION mode: the SAME shipped FedAvg
# round program under fp32 / bf16_mixed / bf16_mixed+fused-update /
# fp32+fused-update, with per-leg wall/step, XLA memory_analysis
# temp-bytes (the activation working set the --remat policy trades
# against), and the parity columns (fused-vs-unfused bitwise flags,
# bf16-vs-fp32 loss/param deltas).
#
# On this CPU harness the WALL numbers are smoke — the parity columns and
# memory estimates are the stable claims. NEXT TPU SESSION: this script
# is the entry point for the real measurement (alongside --trace_out on a
# training run, PROFILE.md round 9). On the chip run it at flagship
# shape:
#
#   BENCH_MODEL=3DCNN BENCH_SHAPE=121,145,121 BENCH_BATCH=128 \
#   BENCH_LOCAL=512 BENCH_CLIENTS=1 BENCH_REPS=3 \
#   JAX_PLATFORMS='' scripts/run_precision_bench.sh
#
# and sweep BENCH_REMAT in {0, stem, 1} to read the remat-vs-batch
# trade at bf16 (remat exists to buy batch > 128 on-chip — the bf16
# activation halving moves that frontier).
set -euo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_matrix
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    BENCH_PRECISION=1 \
    BENCH_MODEL="${BENCH_MODEL:-3dcnn_tiny}" \
    BENCH_SHAPE="${BENCH_SHAPE:-12,14,12}" \
    BENCH_BATCH="${BENCH_BATCH:-8}" \
    BENCH_LOCAL="${BENCH_LOCAL:-16}" \
    BENCH_CLIENTS="${BENCH_CLIENTS:-2}" \
    BENCH_REMAT="${BENCH_REMAT:-0}" \
    BENCH_REPS="${BENCH_REPS:-3}" \
    python bench.py | tee bench_matrix/precision_bench.json
