#!/usr/bin/env bash
# Byzantine-robustness A/B (ISSUE 5 acceptance): 4-silo simulated
# federations (the engine CLI — the attack runs INSIDE the jitted round
# body via faults/adversary.py) on a hard low-signal synthetic cohort,
# 1 of 4 silos sign-flipping its upload delta from round 0:
#
#   clean          no fault, defense none        -> the attack-free AUC
#   attack_none    byz:1@0:sign_flip, no defense -> degraded (the flipped
#                  silo carries ~its sample weight against the honest
#                  sum; on seeds where it is the heaviest silo the
#                  weighted mean FOLLOWS the attacker below chance)
#   attack_trimmed byz + --defense trimmed_mean  -> recovered
#   attack_krum    byz + --defense krum          -> recovered
#
# Each cell runs SEEDS (default 3 7 11) end to end and the summary
# compares mean final AUC: attack_none must degrade by >= DEGRADE_MIN
# below clean, each defense must recover to within RECOVER_MARGIN of
# clean. A fifth artifact entry pins the other ISSUE 5 acceptance
# criterion in-process: --rounds_per_dispatch 4 (one fused lax.scan
# window) with the attack AND trimmed_mean enabled is BITWISE-equal to
# the sequential 4-round loop. Artifact: bench_matrix/byz_bench.json.
#
# The cohort uses --synthetic_signal 5 (vs the sigma-8 voxel noise;
# default 12): at the default the task saturates in ~2 effective
# rounds, so even a halved effective step learns it and the attack is
# invisible. Large local batches (32) + 2 local epochs keep the honest
# silos' deltas mutually consistent, so the order statistics discard
# the attacker — not honest signal.
set -uo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
PY=${PYTHON:-python}
ROUNDS=${BYZ_BENCH_ROUNDS:-16}
SEEDS=(${BYZ_BENCH_SEEDS:-3 7 11})
OUT=bench_matrix/byz_bench.json
mkdir -p bench_matrix /tmp/byz_bench

run_one() {
    local tag=$1 seed=$2; shift 2
    echo "== byz bench [$tag seed=$seed]: $* =="
    local log="/tmp/byz_bench/${tag}_s${seed}.log"
    if ! $PY -m neuroimagedisttraining_tpu \
        --dataset synthetic --model 3dcnn_tiny \
        --synthetic_num_subjects 192 --synthetic_shape 12 14 12 \
        --synthetic_signal 5 \
        --client_num_in_total 4 --frac 1.0 --comm_round "$ROUNDS" \
        --batch_size 32 --epochs 2 --lr 2e-3 \
        --frequency_of_the_test 99 --seed "$seed" "$@" > "$log" 2>&1
    then
        echo "FAIL($tag seed=$seed)"; tail -20 "$log"; return 1
    fi
    grep -a -o '^{.*}' "$log" | tail -1 \
        > "/tmp/byz_bench/${tag}_s${seed}.json"
}

ATK=(--fault_spec byz:1@0:sign_flip)
rc=0
for seed in "${SEEDS[@]}"; do
    run_one clean          "$seed"                                    || rc=1
    run_one attack_none    "$seed" "${ATK[@]}"                        || rc=1
    run_one attack_trimmed "$seed" "${ATK[@]}" --defense trimmed_mean \
                           --byz_f 1                                  || rc=1
    run_one attack_krum    "$seed" "${ATK[@]}" --defense krum \
                           --byz_f 1                                  || rc=1
done
[ $rc -ne 0 ] && exit $rc

echo "== fused-dispatch bitwise pin (byz + trimmed_mean, K=4 vs K=1) =="
$PY - <<'EOF' > /tmp/byz_bench/fused.json || rc=1
import json

import jax
import numpy as np

from neuroimagedisttraining_tpu.__main__ import add_args, build_experiment
from neuroimagedisttraining_tpu.__main__ import config_from_args
import argparse


def run(k):
    args = add_args(argparse.ArgumentParser()).parse_args([
        "--dataset", "synthetic", "--model", "3dcnn_tiny",
        "--synthetic_num_subjects", "48", "--synthetic_shape", "12", "14",
        "12", "--client_num_in_total", "4", "--frac", "1.0",
        "--comm_round", "4", "--batch_size", "8", "--epochs", "1",
        "--frequency_of_the_test", "99", "--seed", "7",
        "--fault_spec", "byz:1@0:sign_flip",
        "--defense", "trimmed_mean", "--byz_f", "1",
        "--rounds_per_dispatch", str(k)])
    np.random.seed(args.seed)
    engine = build_experiment(config_from_args(args), console=False)
    engine._donate = False  # both runs replay the same initial buffers
    return engine.train()["params"]

seq, fused = run(1), run(4)
bitwise = all(
    np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(seq), jax.tree.leaves(fused)))
print(json.dumps({"fused_bitwise_equal_with_defense": bool(bitwise),
                  "rounds": 4, "k": 4, "defense": "trimmed_mean",
                  "fault_spec": "byz:1@0:sign_flip"}))
assert bitwise
EOF
cat /tmp/byz_bench/fused.json
[ $rc -ne 0 ] && exit $rc

$PY - "$OUT" "$ROUNDS" "${SEEDS[@]}" <<'EOF'
import json
import sys

out_path, rounds = sys.argv[1], int(sys.argv[2])
seeds = [int(s) for s in sys.argv[3:]]
DEGRADE_MIN = 0.10     # attack_none must lose >= this much mean AUC
RECOVER_MARGIN = 0.15  # defenses must land within this of clean

cells = {}
for tag in ("clean", "attack_none", "attack_trimmed", "attack_krum"):
    aucs = []
    for s in seeds:
        res = json.load(open(f"/tmp/byz_bench/{tag}_s{s}.json"))
        aucs.append(float(res["final_global"]["auc"]))
    cells[tag] = {"auc_by_seed": dict(zip(map(str, seeds), aucs)),
                  "mean_auc": sum(aucs) / len(aucs)}

clean = cells["clean"]["mean_auc"]
degrade = clean - cells["attack_none"]["mean_auc"]
summary = {
    "setup": {"silos": 4, "byzantine": 1, "attack": "byz:1@0:sign_flip",
              "rounds": rounds, "seeds": seeds, "model": "3dcnn_tiny",
              "dataset": "synthetic(signal=5, 192 subjects, 12x14x12)",
              "batch_size": 32, "epochs": 2, "lr": 2e-3},
    "cells": cells,
    "degrade_auc": round(degrade, 4),
    "degrade_min": DEGRADE_MIN,
    "recover_margin": RECOVER_MARGIN,
    "fused_dispatch": json.load(open("/tmp/byz_bench/fused.json")),
}
ok = degrade >= DEGRADE_MIN
print(f"attack degradation: clean {clean:.3f} -> "
      f"none {cells['attack_none']['mean_auc']:.3f} "
      f"(-{degrade:.3f}, need >= {DEGRADE_MIN}) -> "
      f"{'PASS' if ok else 'FAIL'}")
for tag in ("attack_trimmed", "attack_krum"):
    gap = clean - cells[tag]["mean_auc"]
    good = gap <= RECOVER_MARGIN
    cells[tag]["recovered"] = bool(good)
    print(f"{tag}: mean AUC {cells[tag]['mean_auc']:.3f} "
          f"(gap to clean {gap:+.3f}, margin {RECOVER_MARGIN}) -> "
          f"{'PASS' if good else 'FAIL'}")
    ok = ok and good
ok = ok and summary["fused_dispatch"]["fused_bitwise_equal_with_defense"]
summary["pass"] = bool(ok)
json.dump(summary, open(out_path, "w"), indent=1, sort_keys=True)
print(f"artifact -> {out_path}")
sys.exit(0 if ok else 1)
EOF
