#!/usr/bin/env bash
# Serving-plane headline bench (ISSUE 17, serve/): train a REAL tiny
# ditto run (per-site personalized heads) to a checkpoint, convert it
# to a bf16 deployment bundle (serve/bundle.py), then drive the seeded
# open-loop loadgen request fleet (1k clients by default) against 2
# SO_REUSEPORT serve workers with jitted micro-batched inference.
#
# Acceptance (gated by the analysis/bench_gate.py serve_bench SPEC):
#   - >= 500 requests served at 1k concurrent clients, all accounted:
#     client-side sent == ok+rejected+errors AND root/bye verdict
#     reconciliation per worker (zero dropped-but-unaccounted)
#   - ONE compiled program per (model, batch-bucket): the compile
#     counter pin, zero recompile-tripwire hits
#   - per-site routing proof: two sites observe two DIFFERENT
#     personalized bundle digests
#   - merged /metrics carries nidt_serve_latency_ms + nidt_client_rtt_ms
#
# Writes bench_matrix/serve_bench.json (committed artifact).
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
PY=${PYTHON:-python}
OUT=${1:-bench_matrix/serve_bench.json}
WORK=$(mktemp -d /tmp/nidt_serve_bench.XXXXXX)
trap 'rm -rf "$WORK"' EXIT

echo "== 1/4 train the source checkpoint (ditto, synthetic cohort) =="
$PY -m neuroimagedisttraining_tpu --algorithm ditto \
    --dataset synthetic --model 3dcnn_tiny \
    --synthetic_num_subjects 32 --synthetic_shape 12 14 12 \
    --client_num_in_total 4 --comm_round 2 --batch_size 4 --epochs 1 \
    --lr 5e-4 --virtual_devices 8 --log_dir "$WORK/log" \
    --checkpoint_dir "$WORK/ckpt" --checkpoint_every 1 \
    --seed "${BENCH_SEED:-1024}"

echo "== 2/4 checkpoint -> bf16 deployment bundle =="
$PY -m neuroimagedisttraining_tpu.serve \
    --bundle "$WORK/bundle" --from_checkpoint "$WORK/ckpt" \
    --model 3dcnn_tiny --input_shape 12,14,12 --build_only

echo "== 3/4 serve fleet: ${BENCH_CLIENTS:-1000} clients, 2 workers =="
$PY -m neuroimagedisttraining_tpu.asyncfl.loadgen \
    --mode serve \
    --clients "${BENCH_CLIENTS:-1000}" \
    --serve_bundle "$WORK/bundle" \
    --serve_workers "${BENCH_SERVE_WORKERS:-2}" \
    --serve_requests "${BENCH_REQUESTS:-2000}" \
    --batch_buckets "${BENCH_BUCKETS:-1,2,4,8}" \
    --max_queue_ms "${BENCH_MAX_QUEUE_MS:-2.0}" \
    --seed "${BENCH_SEED:-1024}" \
    --out "$OUT"

$PY - "$OUT" <<'EOF'
import json, sys
res = json.load(open(sys.argv[1]))
c, s = res["serve"], res["summary"]
assert s["audits_green"], "serve bench: accounting audit came back red"
assert c["requests_ok"] >= 500, \
    f"serve bench: only {c['requests_ok']} requests served (need >= 500)"
assert c["serve_workers"] >= 2, c["serve_workers"]
assert c["compile_pin_ok"], \
    (c["compiled_programs"], c["compiles_total"], c["recompiles_total"])
assert c["routing"]["distinct_site_models"], c["routing"]
assert c["merged_metrics"]["has_serve_latency"], c["merged_metrics"]
assert c["merged_metrics"]["has_rtt_samples"], c["merged_metrics"]
print(f"OK: {c['requests_ok']} served by {c['serve_workers']} workers "
      f"at {c['requests_per_s']} req/s "
      f"(p50 {c['rtt_ms_p50']} ms, p99 {c['rtt_ms_p99']} ms), "
      f"occupancy {c['batch_occupancy']}, "
      f"{c['compiles_total']} compiled programs, 0 recompiles, "
      f"routing digests distinct across {len(c['routing']['per_site'])} "
      f"sites")
EOF

echo "== 4/4 bench gate (serve_bench cell) =="
$PY -m neuroimagedisttraining_tpu.analysis.bench_gate \
    --artifact serve_bench.json --quiet
